"""Elastic mesh tests (resilience/elastic.py; docs/resilience.md).

Fast tier: the CoordinatorSM decision machine on a fake clock, the file
driver (join/commit/generation records), batch rescaling, the coordinator
contract, heartbeat tombstones, and the listener reset semantics — no
subprocesses, no jax world.

Slow tier: THE acceptance scenario — freeze one of four launch.py workers
mid-training; the survivors must reach mesh generation 2 (shrink), the
supervisor's respawned rejoiner must bring the fleet back (grow), the
whole run must end rc=0 with NO exit-75 requeue, and the loss trajectory
must stay continuous against an unkilled oracle.
"""
import json
import os
import socket
import threading
import time

import pytest

from distributed_resnet_tensorflow_tpu.parallel.distributed import (
    elastic_coordinator)
from distributed_resnet_tensorflow_tpu.resilience.elastic import (
    CoordinatorSM, ElasticImpossible, ElasticRuntime, ElasticState,
    rescaled_batch)
from distributed_resnet_tensorflow_tpu.resilience.heartbeat import (
    tombstone_departed)
from distributed_resnet_tensorflow_tpu.resilience.preemption import (
    PreemptionListener)
from distributed_resnet_tensorflow_tpu.utils.config import ExperimentConfig


# ---------------------------------------------------------------------------
# CoordinatorSM: pure decisions on a fake clock
# ---------------------------------------------------------------------------

def test_sm_chief_commits_after_settle():
    sm = CoordinatorSM(0, min_hosts=2, settle_secs=2.0, timeout_secs=60.0)
    assert sm.step(0.0, {0, 1}, None) == ("wait", None)   # first sighting
    assert sm.step(1.0, {0, 1}, None) == ("wait", None)   # settling
    assert sm.step(2.0, {0, 1}, None) == ("commit", None)


def test_sm_non_chief_never_commits_and_adopts_commit():
    sm = CoordinatorSM(1, min_hosts=2, settle_secs=0.5, timeout_secs=60.0)
    for t in (0.0, 1.0, 5.0, 20.0):
        assert sm.step(t, {0, 1}, None) == ("wait", None)
    record = {"generation": 1, "members": [0, 1]}
    assert sm.step(21.0, {0, 1}, record) == ("done", record)


def test_sm_chief_absent_membership_times_out():
    # worker 0 hosts the next coordinator: a membership without it must
    # never commit — everyone waits out the barrier into the 75 fallback
    sm = CoordinatorSM(1, min_hosts=2, settle_secs=0.5, timeout_secs=30.0)
    assert sm.step(0.0, {1, 2}, None) == ("wait", None)
    assert sm.step(10.0, {1, 2}, None) == ("wait", None)
    action, why = sm.step(30.0, {1, 2}, None)
    assert action == "abort" and "timed out" in why


def test_sm_membership_flap_resets_settle_window():
    sm = CoordinatorSM(0, min_hosts=2, settle_secs=2.0, timeout_secs=60.0)
    assert sm.step(0.0, {0, 1}, None) == ("wait", None)
    # a third worker lands mid-settle: the window restarts so several
    # near-simultaneous changes collapse into ONE transition
    assert sm.step(1.5, {0, 1, 2}, None) == ("wait", None)
    assert sm.step(3.0, {0, 1, 2}, None) == ("wait", None)  # 1.5s < 2s
    assert sm.step(3.6, {0, 1, 2}, None) == ("commit", None)


def test_sm_commit_without_us_aborts():
    sm = CoordinatorSM(2, min_hosts=2, settle_secs=0.5, timeout_secs=60.0)
    action, why = sm.step(0.0, {2}, {"generation": 1, "members": [0, 1]})
    assert action == "abort" and "without worker 2" in why


def test_sm_below_min_hosts_never_commits():
    sm = CoordinatorSM(0, min_hosts=2, settle_secs=0.5, timeout_secs=10.0)
    assert sm.step(0.0, {0}, None) == ("wait", None)
    assert sm.step(5.0, {0}, None) == ("wait", None)
    assert sm.step(10.0, {0}, None)[0] == "abort"


# ---------------------------------------------------------------------------
# batch rescaling + the coordinator contract
# ---------------------------------------------------------------------------

def test_rescaled_batch_per_host_keeps_shard_slice():
    assert rescaled_batch("per_host", 16, 4, 3) == (12, "per_host")
    assert rescaled_batch("per_host", 16, 4, 2) == (8, "per_host")


def test_rescaled_batch_keep_global_when_divisible():
    assert rescaled_batch("keep_global", 16, 4, 2) == (16, "keep_global")


def test_rescaled_batch_keep_global_falls_back_on_indivisible():
    # 16 % 3 != 0 — silently flooring would train a different batch than
    # configured, so the policy degrades to per_host with a warning
    assert rescaled_batch("keep_global", 16, 4, 3) == (12, "per_host")


def test_elastic_coordinator_port_stride():
    assert elastic_coordinator("127.0.0.1:8476", 0, 7) == "127.0.0.1:8476"
    assert elastic_coordinator("127.0.0.1:8476", 2, 7) == "127.0.0.1:8490"


def test_elastic_coordinator_requires_host():
    with pytest.raises(ValueError):
        elastic_coordinator("8476", 1)


# ---------------------------------------------------------------------------
# ElasticState: the shared-directory barrier driver
# ---------------------------------------------------------------------------

def test_state_join_and_members(tmp_path):
    st = ElasticState(str(tmp_path))
    assert st.members(1) == set()
    st.post_join(1, 0, {"reason": "peer_lost"})
    st.post_join(1, 2, {"reason": "peer_lost"})
    assert st.members(1) == {0, 2}
    assert st.read_commit(1) is None


def test_state_commit_is_exclusive_first_writer_wins(tmp_path):
    st = ElasticState(str(tmp_path))
    first = st.try_commit(1, {"generation": 1, "members": [0, 1]})
    second = st.try_commit(1, {"generation": 1, "members": [0, 1, 2]})
    # the second writer must ADOPT the first record, not overwrite it
    assert first["members"] == [0, 1]
    assert second["members"] == [0, 1]
    assert st.read_commit(1)["members"] == [0, 1]


def test_state_generation_roundtrip_and_round_cleanup(tmp_path):
    st = ElasticState(str(tmp_path))
    st.post_join(1, 0, {})
    st.post_join(2, 0, {})
    st.write_generation({"generation": 2, "members": [0, 1]})
    assert st.read_generation()["generation"] == 2
    st.cleanup_rounds(2)
    assert st.members(1) == set()   # round-1 is history
    assert st.members(2) == {0}     # the live round's files stay


# ---------------------------------------------------------------------------
# heartbeat tombstones + listener reset across generations
# ---------------------------------------------------------------------------

def test_tombstone_departed_drops_only_departed_ranks(tmp_path):
    d = str(tmp_path)
    for name in ("proc0.json", "proc1.json", "proc1.final.json",
                 "proc3.json", "proc3.final.json", "notabeat.txt"):
        with open(os.path.join(d, name), "w") as f:
            f.write("{}")
    removed = tombstone_departed(d, keep_process_ids=[0, 1])
    assert removed == 2
    left = sorted(os.listdir(d))
    assert left == ["notabeat.txt", "proc0.json", "proc1.final.json",
                    "proc1.json"]


def test_listener_reset_clears_programmatic_stop_only():
    lst = PreemptionListener()
    lst.request_stop("peer_lost: proc3 beats stale")
    assert lst.should_stop() and lst.reason().startswith("peer_lost")
    lst.reset()
    assert not lst.should_stop()
    assert lst.reason() == "not preempted"


def test_listener_reset_preserves_signal_stop():
    lst = PreemptionListener()
    # a REAL operator/SLURM signal must keep stopping the run across
    # generations — reset only forgives programmatic stop requests
    lst._reason = "signal SIGTERM"
    lst._event.set()
    lst.reset()
    assert lst.should_stop()
    assert lst.reason() == "signal SIGTERM"


# ---------------------------------------------------------------------------
# ElasticRuntime against a real config (virtual 8-device CPU mesh)
# ---------------------------------------------------------------------------

def _elastic_cfg(tmp_path, **overrides):
    cfg = ExperimentConfig()
    cfg.log_root = str(tmp_path)
    cfg.mesh.num_processes = 4
    cfg.mesh.process_id = 0
    cfg.mesh.coordinator_address = "127.0.0.1:9000"
    cfg.train.batch_size = 64
    e = cfg.resilience.elastic
    e.enabled = "on"
    e.min_hosts = 1
    e.settle_secs = 0.0
    e.poll_secs = 0.05
    for key, val in overrides.items():
        setattr(e, key, val)
    return cfg


def test_runtime_disabled_without_peers(tmp_path):
    cfg = _elastic_cfg(tmp_path)
    cfg.mesh.num_processes = 1
    assert not ElasticRuntime(cfg).enabled


def test_runtime_can_reshard_needs_explicit_coordinator(tmp_path):
    cfg = _elastic_cfg(tmp_path)
    cfg.mesh.coordinator_address = ""  # SLURM/TPU-pod autodetect shape
    rt = ElasticRuntime(cfg)
    assert rt.enabled and not rt.can_reshard()


def test_runtime_watchdog_defer_is_bounded(tmp_path):
    now = [0.0]
    cfg = _elastic_cfg(tmp_path, reshard_timeout_secs=30.0)
    rt = ElasticRuntime(cfg, clock=lambda: now[0])
    assert rt.watchdog_defer()          # first call arms the bound
    now[0] = 29.0
    assert rt.watchdog_defer()
    now[0] = 31.0
    assert not rt.watchdog_defer()      # bound exceeded: let the 75 happen


def test_runtime_rank_and_derive_config(tmp_path):
    cfg = _elastic_cfg(tmp_path)
    cfg.mesh.process_id = 2
    rt = ElasticRuntime(cfg)
    record = {"generation": 1, "members": [0, 2, 3],
              "coordinator": "127.0.0.1:9007", "restore_step": 5,
              "global_batch": 48}
    assert rt.rank(record) == 1         # sorted member index, chief stays 0
    cfg2 = rt.derive_config(record)
    assert cfg2.mesh.num_processes == 3
    assert cfg2.mesh.process_id == 1
    assert cfg2.mesh.coordinator_address == "127.0.0.1:9007"
    assert cfg2.train.batch_size == 48
    # the source config is untouched (deepcopy)
    assert cfg.mesh.num_processes == 4 and cfg.train.batch_size == 64


def test_runtime_single_worker_transition_commits(tmp_path):
    """The whole barrier driven end to end in one process: worker 0 posts
    its join, settles, commits, and adopts its own record."""
    rt = ElasticRuntime(_elastic_cfg(tmp_path))
    record = rt.transition("peer_lost", lambda: 7)
    assert record["generation"] == 1
    assert record["members"] == [0]
    assert record["restore_step"] == 7
    assert record["reason"] == "peer_lost"
    # epoch-suffixed coordinator: base port + generation * stride
    assert record["coordinator"] == \
        f"127.0.0.1:{9000 + rt.ecfg.port_stride}"
    # per_host policy: per-shard slice constant (64 over 4 hosts x 8
    # devices = 2/shard), global batch scales to the 1-host world
    assert record["global_batch"] == 16
    assert rt.generation == 1 and rt.members == {0}


def test_runtime_transition_times_out_without_chief(tmp_path):
    cfg = _elastic_cfg(tmp_path, barrier_timeout_secs=0.4)
    cfg.mesh.process_id = 1             # non-chief: can never commit
    rt = ElasticRuntime(cfg)
    with pytest.raises(ElasticImpossible):
        rt.transition("peer_lost", lambda: None)
    assert not rt.in_transition         # state cleared for the 75 fallback


def test_runtime_two_workers_meet_in_the_barrier(tmp_path):
    """Two runtimes over the SAME state dir (the two-process shape without
    subprocesses): the chief commits, the peer adopts the same record."""
    cfg0 = _elastic_cfg(tmp_path, min_hosts=2)
    cfg1 = _elastic_cfg(tmp_path, min_hosts=2)
    cfg1.mesh.process_id = 1
    rt0, rt1 = ElasticRuntime(cfg0), ElasticRuntime(cfg1)
    out = {}

    def drive(name, rt):
        out[name] = rt.transition("peer_lost", lambda: 3)

    threads = [threading.Thread(target=drive, args=(n, rt), daemon=True)
               for n, rt in (("chief", rt0), ("peer", rt1))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert out["chief"] == out["peer"]
    assert out["chief"]["members"] == [0, 1]
    assert out["chief"]["restore_step"] == 3


def test_runtime_pending_join_sees_only_new_workers(tmp_path):
    rt = ElasticRuntime(_elastic_cfg(tmp_path))
    assert not rt.pending_join(force=True)
    rt.state.post_join(1, 0, {})        # an existing member is not news
    assert not rt.pending_join(force=True)
    rt.state.post_join(1, 5, {})        # a rejoiner is
    assert rt.pending_join(force=True)


# ---------------------------------------------------------------------------
# the acceptance scenario: kill-and-reshard, then grow back (slow tier)
# ---------------------------------------------------------------------------

def _elastic_launch_args(tmp_path, train_steps, elastic=True):
    args = [
        "--preset", "smoke",
        "--set", "model.name=logistic",
        "--set", "model.input_size=192",
        "--set", "model.num_classes=10",
        "--set", "data.image_size=8",
        "--set", "train.batch_size=16",
        "--set", f"train.train_steps={train_steps}",
        "--set", "train.log_every_steps=1000",
        "--set", "train.summary_every_steps=5",
        "--set", f"log_root={tmp_path}",
        "--set", "checkpoint.save_every_steps=5",
        "--set", "checkpoint.save_every_secs=0",
        "--set", "resilience.watchdog.enabled=on",
        "--set", "resilience.watchdog.interval_secs=0.2",
        "--set", "resilience.watchdog.peer_timeout_secs=5",
        "--set", "resilience.watchdog.min_step_timeout_secs=3",
        "--set", "resilience.watchdog.grace_secs=1",
    ]
    if elastic:
        args += ["--set", "resilience.elastic.enabled=on",
                 "--set", "resilience.elastic.settle_secs=1"]
    return args


def _metric_rows(tmp_path):
    path = os.path.join(str(tmp_path), "train", "metrics.jsonl")
    try:
        with open(path) as f:
            return [json.loads(line) for line in f if line.strip()]
    except FileNotFoundError:
        return []


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow  # multi-minute 4-process subprocess scenario — chaos_smoke.sh --elastic territory, not tier-1
@pytest.mark.heavy
def test_elastic_kill_and_reshard_grows_back(tmp_path):
    """Freeze one of four workers: the survivors must shrink to a 3-host
    generation and keep stepping from the last committed checkpoint, the
    supervisor's rejoiner must grow the fleet back to 4 hosts, and the
    run must complete rc=0 — zero exit-75 requeues. The loss trajectory
    must stay continuous against an unkilled oracle."""
    from distributed_resnet_tensorflow_tpu.launch import launch_local

    steps = 60
    elastic_root = tmp_path / "elastic"
    os.environ["DRT_FAULT_FREEZE_AT_BATCH"] = "3:8"
    try:
        rc = launch_local(
            4, _elastic_launch_args(elastic_root, steps),
            devices_per_process=1, port=_free_port(),
            elastic=True, max_respawns=2, respawn_delay_secs=2.0)
    finally:
        os.environ.pop("DRT_FAULT_FREEZE_AT_BATCH", None)
    assert rc == 0, f"elastic run must complete without a requeue (rc={rc})"

    rows = _metric_rows(elastic_root)
    gens = [r for r in rows if r.get("event") == "mesh_generation"]
    reshards = [r for r in rows if r.get("event") == "reshard"]
    seen_gens = {r["generation"] for r in gens}
    assert {0, 1, 2} <= seen_gens, (seen_gens, reshards)
    reasons = {r["reason"] for r in reshards}
    assert "peer_lost" in reasons and "grow" in reasons, reasons
    shrink = next(r for r in reshards if r["reason"] == "peer_lost")
    grow = next(r for r in reshards if r["reason"] == "grow")
    assert (shrink["old_hosts"], shrink["new_hosts"]) == (4, 3)
    assert (grow["old_hosts"], grow["new_hosts"]) == (3, 4)
    assert shrink["restore_step"] >= 0   # resumed, not restarted
    scalar_steps = [r["step"] for r in rows if "event" not in r]
    assert scalar_steps and max(scalar_steps) >= steps

    # loss continuity: the final loss must land in the same regime as an
    # unkilled 4-process oracle (loose — the reshard replays a few batches
    # and rescales the global batch, exact equality is not the contract)
    oracle_root = tmp_path / "oracle"
    rc = launch_local(4, _elastic_launch_args(oracle_root, steps,
                                              elastic=False),
                      devices_per_process=1, port=_free_port())
    assert rc == 0
    def final_loss(root):
        losses = [r["loss"] for r in _metric_rows(root)
                  if "event" not in r and "loss" in r]
        assert losses, f"no loss scalars under {root}"
        return losses[-1]
    killed, oracle = final_loss(elastic_root), final_loss(oracle_root)
    assert abs(killed - oracle) < max(0.5, 0.5 * abs(oracle)), \
        (killed, oracle)
