from .config import (  # noqa: F401
    CheckpointConfig,
    DataConfig,
    EvalConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    PRESETS,
    ResilienceConfig,
    TrainConfig,
    get_preset,
    parse_args,
)


def cadence_crossed(step: int, every: int, last: int) -> bool:
    """True when (last, step] crosses a multiple of ``every``. Shared by
    hooks and CheckpointManager: fused multi-step loops only surface loop-end
    steps, so plain ``step % every == 0`` would skip cadences the loop size
    does not divide."""
    return step // every > last // every
