"""Training hooks — plain host-side callbacks ``hook(step, state, metrics)``.

Successor of the reference's session-hook stack (SURVEY.md §2.11-2.15):
``LoggingTensorHook`` → LoggingHook, ``SummarySaverHook`` → SummaryHook,
``MonitoredTrainingSession`` checkpointing → CheckpointHook,
``_LearningRateSetterHook`` → gone (the LR schedule is computed inside the
jitted step, no per-step host feed).

Hooks receive device metrics WITHOUT forcing a sync: values are jax.Arrays;
hooks that print/serialize pull them at their own cadence, so the hot loop
stays async-dispatch bound, not host bound.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Dict, Optional

import jax

from ..utils.metrics import MetricsWriter, Throughput

log = logging.getLogger(__name__)


from ..utils import cadence_crossed  # noqa: F401  (re-export; shared impl)


def nonfinite_metric(metrics: Optional[Dict[str, Any]]) -> Optional[str]:
    """The first divergence-indicator key ("loss", "grad_norm") whose value
    is non-finite, else None. ONE definition shared by NanGuardHook (the
    detector) and CheckpointHook (the save gate) — the pair must agree or a
    cadence save could commit the very state the guard is about to flag.
    Calling this forces a device sync (float()); gate on cadence first."""
    import math
    if not metrics:
        return None
    for key in ("loss", "grad_norm"):
        value = metrics.get(key)
        if value is not None and not math.isfinite(float(value)):
            return key
    return None


class _CadenceHook:
    """Shared cadence cursor for hooks gating on ``cadence_crossed``."""

    _last = 0

    def rollback_to(self, step: int) -> None:
        """Rewind the cadence after a checkpoint rollback
        (resilience/sentinel.py): a cursor still pointing at the trip step
        would treat every replayed step as already-handled — for the NaN
        guard that is a blind window in which a cadence save could commit
        NaN params; for logging/summaries the replayed span would vanish."""
        self._last = min(self._last, step)


class _SnapshotExportHook(_CadenceHook):
    """Shared skeleton for the plan/summary exporters (Zero1Hook,
    CommOverlapHook, PrecisionHook, CommCompressHook, CkptShardHook):
    at the cadence, pull a snapshot row and write it as ONE
    ``{"event": <event>}`` record per CHANGE — these rows describe a
    property of the run's compiled programs / writer state, not of any
    single step, so re-exporting an unchanged row per cadence would be
    noise, while gating on anything less than the whole row freezes
    mid-flight values forever (the CkptAsyncHook lesson, round 10).
    Subclasses set ``event`` and implement ``_snapshot() -> dict|None``
    (None = nothing to export yet)."""

    event: str = ""

    def __init__(self, writer: MetricsWriter, every_steps: int = 100):
        self.writer = writer
        self.every_steps = max(1, every_steps)
        self._last = 0
        self._exported: Dict[str, Any] = {}

    def _snapshot(self) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def _gate(self, snap: Dict[str, Any]) -> Dict[str, Any]:
        """The comparison key deciding re-export (default: the whole row).
        Subclasses whose rows carry a live measurement override this to
        quantize it — re-export when the measurement MOVES, without the
        noise of re-exporting its every wiggle (CommTimingHook)."""
        return snap

    def __call__(self, step: int, state, metrics: Dict[str, Any]) -> None:
        if not cadence_crossed(step, self.every_steps, self._last):
            return
        self._last = step
        snap = self._snapshot()
        if snap is None:
            return
        key = self._gate(snap)
        if key != self._exported:
            self._exported = key
            self.writer.write_event(self.event, {"step": int(step),
                                                 **snap})


class LoggingHook(_CadenceHook):
    """Print step/loss/precision/lr every N steps + throughput (reference
    LoggingTensorHook cadence: 20 cifar / 40 imagenet,
    resnet_cifar_main.py:280-285)."""

    def __init__(self, every_steps: int = 20, batch_size: int = 0,
                 print_fn=None, step_flops: Optional[float] = None):
        self.every_steps = max(1, every_steps)
        self.throughput = Throughput(batch_size)
        self.print_fn = print_fn or (lambda s: log.info("%s", s))
        self.step_flops = step_flops  # enables an MFU column when known
        self._last = 0

    def reset_window(self) -> None:
        """Called by Trainer.train at segment start so the throughput
        window never spans an eval round / checkpoint pause between
        segments (which would deflate stp/s and MFU for the first line
        of each segment)."""
        self.throughput.reset()

    def __call__(self, step: int, state, metrics: Dict[str, Any]) -> None:
        if not cadence_crossed(step, self.every_steps, self._last):
            return
        self._last = step
        tp = self.throughput.update(step)
        parts = [f"step {step}"]
        for k in ("loss", "cross_entropy", "precision", "learning_rate"):
            if k in metrics:
                parts.append(f"{k} {float(metrics[k]):.4f}")
        if tp:
            parts.append(f"{tp['steps_per_sec']:.2f} stp/s")
            if self.throughput.batch_size:
                parts.append(f"{tp['images_per_sec']:.0f} img/s")
            if self.step_flops:
                from ..utils.profiling import mfu
                util = mfu(tp["steps_per_sec"], self.step_flops)
                if util is not None:
                    parts.append(f"mfu {util * 100:.1f}%")
        self.print_fn("  ".join(parts))


class SummaryHook(_CadenceHook):
    """Write scalars to the MetricsWriter every N steps (reference
    SummarySaverHook every 100, resnet_cifar_main.py:274-278)."""

    def __init__(self, writer: MetricsWriter, every_steps: int = 100):
        self.writer = writer
        self.every_steps = max(1, every_steps)
        self._last = 0

    def __call__(self, step: int, state, metrics: Dict[str, Any]) -> None:
        if not cadence_crossed(step, self.every_steps, self._last):
            return
        self._last = step
        scalars = {k: float(v) for k, v in metrics.items()
                   if hasattr(v, "__float__") or isinstance(v, (int, float))}
        self.writer.write_scalars(step, scalars)


class InputStagesHook(_CadenceHook):
    """Export the input-pipeline stage counters (utils.metrics.input_stages:
    decode / stack / stage / transfer / dispatch_wait) to metrics.jsonl as a
    typed ``{"event": "input_stages", ...}`` record every N steps — the
    attribution telemetry bench.py and docs/input_pipeline.md describe.
    Counters are cumulative since process start (or the last reset), so
    consumers can difference consecutive records for window rates."""

    def __init__(self, writer: MetricsWriter, every_steps: int = 100):
        self.writer = writer
        self.every_steps = max(1, every_steps)
        self._last = 0

    def __call__(self, step: int, state, metrics: Dict[str, Any]) -> None:
        if not cadence_crossed(step, self.every_steps, self._last):
            return
        self._last = step
        from ..utils.metrics import input_stages
        snap = input_stages.snapshot()
        if snap:
            self.writer.write_event("input_stages",
                                    {"step": int(step), "stages": snap})


class InputEchoHook(_CadenceHook):
    """Export the data-echoing cache counters (utils.metrics.echo_stats:
    decoded/emitted/hits/evictions + cache bytes) to metrics.jsonl as
    typed ``{"event": "input_echo"}`` rows every N steps — the telemetry
    bench.py's imagenet_input row and docs/input_pipeline.md read for the
    echo hit rate. Counters are cumulative, like input_stages; rows are
    only written once the echo path has actually served something (a run
    with echo_factor=1 emits nothing)."""

    def __init__(self, writer: MetricsWriter, every_steps: int = 100):
        self.writer = writer
        self.every_steps = max(1, every_steps)
        self._last = 0

    def __call__(self, step: int, state, metrics: Dict[str, Any]) -> None:
        if not cadence_crossed(step, self.every_steps, self._last):
            return
        self._last = step
        from ..utils.metrics import echo_stats
        snap = echo_stats.snapshot()
        if snap["emitted"]:
            self.writer.write_event("input_echo",
                                    {"step": int(step), **snap})


class GoodputHook(_CadenceHook):
    """Export the goodput classification (telemetry/goodput.py) to
    metrics.jsonl as ``{"event": "goodput"}`` rows every N steps: per-
    category seconds + percentages of the interval's wall clock, summing
    to ~100% by construction (compute is the remainder). The break-down an
    operator needs to know whether the cluster is training or waiting —
    and the number ROADMAP items 2 and 5 are measured against."""

    def __init__(self, writer: MetricsWriter, every_steps: int = 100):
        self.writer = writer
        self.every_steps = max(1, every_steps)
        self._last = 0
        self._based = False

    def reset_window(self) -> None:
        """Trainer.train calls this at every segment start; only the FIRST
        rebases the meter (setup/restore wall before step 1 must not be
        billed as compute). Later segment boundaries must NOT rebase: the
        pause between segments is an eval round or a checkpoint — exactly
        the wall time goodput exists to classify, unlike the throughput
        window (LoggingHook) which rightly excludes it."""
        if not self._based:
            self._based = True
            from ..telemetry.goodput import goodput
            goodput.rebase()

    def __call__(self, step: int, state, metrics: Dict[str, Any]) -> None:
        if not cadence_crossed(step, self.every_steps, self._last):
            return
        self._last = step
        from ..telemetry.goodput import goodput
        itv = goodput.interval()
        if itv["wall_secs"] > 0:
            self.writer.write_event("goodput", {"step": int(step), **itv})


class CkptAsyncHook(_CadenceHook):
    """Export the async-checkpoint charge split (utils.metrics.
    ckpt_async_stats: loop-thread snapshot/backpressure seconds vs
    writer-thread stage/fsync/commit seconds) as ``{"event": "ckpt_async"}``
    rows every N steps WHEN a save advanced since the last export — the
    row that proves the writer's wall time overlapped compute instead of
    stalling the loop (only the snapshot + backpressure legs also appear
    in the goodput ``checkpoint`` bucket). docs/resilience.md has the
    commit-timeline diagram these numbers annotate."""

    def __init__(self, writer: MetricsWriter, every_steps: int = 100):
        self.writer = writer
        self.every_steps = max(1, every_steps)
        self._last = 0
        self._exported: Dict[str, Any] = {}

    def __call__(self, step: int, state, metrics: Dict[str, Any]) -> None:
        if not cadence_crossed(step, self.every_steps, self._last):
            return
        self._last = step
        from ..utils.metrics import ckpt_async_stats
        snap = ckpt_async_stats.snapshot()
        # gate on the WHOLE snapshot changing, not just the save counter:
        # a row exported while the writer was still mid-commit would
        # otherwise freeze writer_seconds/committed at ~0 forever —
        # exactly the final save of every run
        if snap["saves"] > 0 and snap != self._exported:
            self._exported = snap
            self.writer.write_event("ckpt_async",
                                    {"step": int(step), **snap})


class CkptShardHook(_SnapshotExportHook):
    """Export THIS host's sharded-checkpoint accounting as
    ``{"event": "ckpt_shard"}`` rows every N steps when its shard bytes
    advanced — the per-host view ``main.py monitor`` rolls up into
    cluster shard-byte totals. Unlike the chief-only observability
    hooks this runs on EVERY process (each host stages only its own
    shard; the chief's row alone would claim the cluster wrote 1/N of
    what it did). Writes nothing on the single-payload layout (no
    shard files ever staged)."""

    event = "ckpt_shard"

    def _snapshot(self):
        from ..utils.metrics import ckpt_async_stats
        snap = ckpt_async_stats.snapshot()
        if not snap["shard_files"]:
            return None
        return {"process": jax.process_index(),
                "shard_bytes": snap["shard_bytes"],
                "shard_files": snap["shard_files"],
                "shard_seconds": snap["shard_seconds"],
                "finalize_wait_seconds": snap["finalize_wait_seconds"],
                "last_committed_step": snap["last_committed_step"]}


class Zero1Hook(_SnapshotExportHook):
    """Export the ZeRO-1 partition plan (parallel/sharding.zero1_stats:
    sharded/replicated leaf+byte counts, per-replica optimizer bytes,
    fallback reasons, and — under comm.overlap — the bucketed param-
    update all-gather plan) as ONE ``{"event": "zero1"}`` row per
    resolved plan, the comm_overlap contract: the plan is a property of
    the compiled step. Writes nothing when optimizer.zero1 resolved
    off."""

    event = "zero1"

    def _snapshot(self):
        from ..parallel.sharding import zero1_stats
        return zero1_stats.snapshot()


class PrecisionHook(_SnapshotExportHook):
    """Export the resolved mixed-precision policy (parallel/precision.
    precision_stats: policy/compute/master dtypes, effective compression,
    master-tree accounting) as ONE ``{"event": "precision"}`` row per
    resolved policy — the per-run precision summary (docs/precision.md).
    Writes nothing when neither a policy nor compression resolved on."""

    event = "precision"

    def _snapshot(self):
        from ..parallel.precision import precision_stats
        return precision_stats.snapshot()


class CommCompressHook(_SnapshotExportHook):
    """Export the compressed-exchange payload accounting (parallel/
    overlap.overlap_stats wire fields + the ZeRO-1 gather wire plan) as
    ONE ``{"event": "comm_compress"}`` row per traced plan WHEN
    ``comm.compress`` actually compressed something — the byte-halving
    witness next to comm_overlap's bucket plan. Silent when the exchange
    ran uncompressed (the comm_overlap row already carries wire_bytes ==
    grad_bytes there)."""

    event = "comm_compress"

    def _snapshot(self):
        from ..parallel.overlap import overlap_stats
        from ..parallel.sharding import zero1_stats
        snap = overlap_stats.snapshot()
        if snap is None or snap.get("compress", "off") == "off":
            return None
        row = {"compress": snap["compress"],
               "grad_bytes": snap["grad_bytes"],
               "wire_bytes": snap["wire_bytes"],
               "bucket_wire_bytes": snap["bucket_wire_bytes"],
               "wire_ratio": round(snap["wire_bytes"] /
                                   max(snap["grad_bytes"], 1), 4)}
        z1 = zero1_stats.snapshot()
        if z1 is not None and z1.get("gather_compress", "off") != "off":
            row["gather_wire_bytes"] = z1["gather_wire_bytes"]
        return row


class CommOverlapHook(_SnapshotExportHook):
    """Export the bucketed gradient-exchange plan (parallel/overlap.
    overlap_stats) as ONE ``{"event": "comm_overlap"}`` row per traced
    plan. Writes nothing when the overlap path never traced
    (comm.overlap resolved off)."""

    event = "comm_overlap"

    def _snapshot(self):
        from ..parallel.overlap import overlap_stats
        snap = overlap_stats.snapshot()
        if snap is not None:
            # analysis-facing, unbounded (one op string per exchanged leaf
            # per bucket) and not in EVENT_SCHEMAS["comm_overlap"]: the
            # schedule cross-check reads it straight off overlap_stats
            snap.pop("declared_collectives", None)
            # same contract: per-op wire bytes mirror the declared
            # sequence 1:1 — planner/comm-report inputs, not a row field
            snap.pop("bucket_op_wire_bytes", None)
        return snap


class CommTimingHook(_SnapshotExportHook):
    """Export the MEASURED per-bucket exchange timings (utils.metrics.
    comm_timing_stats, fed once per process by parallel/overlap.
    probe_comm_plan) as ``{"event": "comm_timing"}`` rows, JOINED with a
    live per-step wall-time estimate measured between this hook's own
    cadence firings — the runtime attribution ``main.py comm-report``
    reduces against the static collective schedule
    (docs/observability.md). The probe data is static per run, so the
    ``_gate`` override quantizes the live rate to 2 significant digits:
    rows re-export when the measured step time MOVES, not per wiggle."""

    event = "comm_timing"

    def __init__(self, writer: MetricsWriter, every_steps: int = 100):
        super().__init__(writer, every_steps)
        self._rate_prev: Optional[tuple] = None  # (monotonic, step)
        self._pending_step = 0

    def reset_window(self) -> None:
        """Called by Trainer.train at segment start (the LoggingHook
        protocol): a rate pair spanning the eval/checkpoint pause between
        segments would inflate step_secs and understate the
        comm_step_ratio headroom."""
        self._rate_prev = None

    def __call__(self, step: int, state, metrics: Dict[str, Any]) -> None:
        self._pending_step = step  # _snapshot's rate-pair endpoint
        super().__call__(step, state, metrics)

    def _snapshot(self):
        now = time.monotonic()
        step = self._pending_step
        prev, self._rate_prev = self._rate_prev, (now, step)
        from ..utils.metrics import comm_timing_stats
        snap = comm_timing_stats.snapshot()
        if snap is None:
            return None  # the probe has not run (overlap off / knob off)
        if prev is not None and step > prev[1] and now > prev[0]:
            step_secs = (now - prev[0]) / (step - prev[1])
            snap["step_secs"] = round(step_secs, 6)
            snap["comm_step_ratio"] = round(
                snap["comm_secs_total"] / step_secs, 4)
        return snap

    def _gate(self, snap):
        gate = dict(snap)
        if "step_secs" in gate:
            gate["step_secs"] = float(f"{gate['step_secs']:.2g}")
            gate.pop("comm_step_ratio", None)
        return gate


class PlanDriftHook(_CadenceHook):
    """The predicted-vs-measured drift sentinel (docs/planner.md). At the
    first cadence after the bucketed exchange has traced, the chief
    builds THIS run's analytic prediction (telemetry/planner.predict_live
    — step time, comm seconds, per-device HBM, costed from the live
    bucket plan × the fabric's bandwidth catalog), exports it as one
    ``{"event": "plan"}`` row, and arms a planner.DriftSentinel. Every
    cadence after that it compares the prediction against what the run
    actually measures — step time from the heartbeat EWMA (falling back
    to this hook's own rate pairs when no watchdog runs), comm seconds
    from the comm_timing probe, HBM from the live memory sample — and a
    sustained divergence beyond telemetry.plan_tolerance becomes a
    ``{"event": "plan_drift"}`` row plus a flight-recorder dump: the
    model said this run should cost X, the machine disagrees, go look.
    Chief-only (the prediction and the measurements are per-run, not
    per-process)."""

    def __init__(self, writer: MetricsWriter, cfg, trainer,
                 every_steps: int = 100):
        self.writer = writer
        self.cfg = cfg
        self.trainer = trainer
        self.every_steps = max(1, every_steps)
        # main._arm_watchdog_hooks points this at the HeartbeatPublisher
        # so the measured step time is the watchdog's own EWMA — one
        # number, not two competing estimates
        self.heartbeat = None
        self._sentinel = None
        self._predicted: Optional[dict] = None
        self._rate_prev: Optional[tuple] = None  # (monotonic, step)
        self._warned = False

    def reset_window(self) -> None:
        """LoggingHook protocol: a rate pair spanning the eval/checkpoint
        pause between segments would read as a step-time regression."""
        self._rate_prev = None

    def _arm(self) -> bool:
        from ..telemetry import planner
        bw = planner.measured_bandwidth_table() \
            or planner.BandwidthTable.reference()
        pred = planner.predict_live(self.cfg, self.trainer, bandwidth=bw)
        if pred is None:
            if self.cfg.telemetry.plan_drift == "on" and not self._warned:
                self._warned = True
                log.warning(
                    "telemetry.plan_drift=on but no prediction could be "
                    "built yet (the bucketed exchange has not traced — "
                    "comm.overlap off?); the sentinel stays disarmed")
            return False
        tcfg = self.cfg.telemetry
        self._predicted = pred
        self._sentinel = planner.DriftSentinel(
            pred, tolerance=tcfg.plan_tolerance,
            window=tcfg.plan_drift_window,
            cooldown_secs=tcfg.plan_drift_cooldown_secs)
        self.writer.write_event("plan", {
            "preset": self.cfg.model.name,
            "layout": planner.layout_label(self.cfg.mesh),
            "devices": jax.device_count(),
            "knobs": {
                "precision": self.cfg.train.precision,
                "zero1": self.cfg.optimizer.zero1,
                "compress": self.cfg.comm.compress,
                "bucket_mb": self.cfg.comm.bucket_mb,
                "accum": self.cfg.train.grad_accum_steps,
            },
            "predicted": pred,
            "bandwidth_source": bw.source,
            "recommended": True,  # the layout actually running
        })
        log.info("plan-drift sentinel armed: predicted step %.3fms, "
                 "comm %.3fms, HBM %s (bandwidth: %s)",
                 pred["step_secs"] * 1e3, pred["comm_secs"] * 1e3,
                 pred.get("hbm_bytes"), bw.source)
        return True

    def _measured(self, now: float, step: int) -> Dict[str, float]:
        """The live values to hold against the prediction; only metrics
        that actually have a measurement this cadence are checked."""
        out: Dict[str, float] = {}
        prev, self._rate_prev = self._rate_prev, (now, step)
        if self.heartbeat is not None:
            ewma = self.heartbeat.snapshot().get("ewma_step_secs")
            if ewma:
                out["step_secs"] = float(ewma)
        if "step_secs" not in out and prev is not None \
                and step > prev[1] and now > prev[0]:
            out["step_secs"] = (now - prev[0]) / (step - prev[1])
        from ..utils.metrics import comm_timing_stats
        timing = comm_timing_stats.snapshot()
        if timing is not None:
            out["comm_secs"] = float(timing["comm_secs_total"])
        if self._predicted and self._predicted.get("hbm_bytes"):
            from ..telemetry.memory import sample_memory
            sample = sample_memory()
            peaks = [d.get("live_peak_bytes", 0)
                     for d in sample.get("devices", {}).values()]
            if peaks and max(peaks) > 0:
                out["hbm_bytes"] = float(max(peaks))
        return out

    def __call__(self, step: int, state, metrics: Dict[str, Any]) -> None:
        if not cadence_crossed(step, self.every_steps, self._last):
            return
        self._last = step
        now = time.monotonic()
        if self._sentinel is None:
            if not self._arm():
                self._rate_prev = (now, step)
            return
        from ..telemetry.tracer import recorder
        with recorder.span("plan.drift_check", step=step):
            for metric, measured in self._measured(now, step).items():
                firing = self._sentinel.check(metric, measured)
                if firing is None:
                    continue
                dump = recorder.dump_on_anomaly(
                    "plan_drift",
                    detail=f"{metric} predicted "
                           f"{firing['predicted']:.6g} measured "
                           f"{firing['measured']:.6g} at step {step}")
                self.writer.write_event("plan_drift",
                                        {"step": step, **firing,
                                         "dump": dump})
                self.writer.flush()
                log.warning(
                    "plan drift: %s measured %.6g vs predicted %.6g "
                    "(ratio %.2f beyond tolerance %.1f for %d windows)",
                    metric, firing["measured"], firing["predicted"],
                    firing["ratio"], firing["tolerance"],
                    firing["windows"])


class MemoryHook(_SnapshotExportHook):
    """Export the device/host memory sample (telemetry/memory.py:
    per-device live-array bytes + allocator stats where present, host
    RSS, echo-cache and staging-ring occupancy) as ``{"event": "memory"}``
    rows every N steps — the trend line that turns an OOM from a
    postmortem into a graph. Runs on EVERY process (each host samples its
    own devices; non-chief processes export into their per-process
    ``train-p<idx>`` stream, which ``main.py monitor`` rolls up into the
    per-host HBM watermark). Samples change between cadences, so the
    skeleton's change-gate passes and the rows form a time series — for
    memory that is the point, not noise."""

    event = "memory"

    def _snapshot(self):
        from ..telemetry.memory import sample_memory
        return sample_memory()


class CorruptRecordsHook(_CadenceHook):
    """Export the corrupt-TFRecord tally (data/tfrecord.corrupt_records) to
    metrics.jsonl as ``{"event": "corrupt_record"}`` rows — one row per
    cadence WHEN the count advanced, carrying the cumulative count, the
    per-reason breakdown, and the most recent offenders. Dataset bit rot
    thereby shows up in run telemetry instead of only in a decode worker's
    log file."""

    def __init__(self, writer: MetricsWriter, every_steps: int = 100):
        self.writer = writer
        self.every_steps = max(1, every_steps)
        self._last = 0
        self._exported_count = 0

    def __call__(self, step: int, state, metrics: Dict[str, Any]) -> None:
        if not cadence_crossed(step, self.every_steps, self._last):
            return
        self._last = step
        from ..data.tfrecord import corrupt_records
        snap = corrupt_records.snapshot()
        if snap["count"] > self._exported_count:
            self._exported_count = snap["count"]
            self.writer.write_event("corrupt_record",
                                    {"step": int(step), **snap})


class HeartbeatHook:
    """Feed the heartbeat publisher at every step boundary
    (resilience/heartbeat.py): one locked field write, no I/O — the
    publisher's daemon thread does the actual beat. Runs on EVERY process
    (unlike the chief-only observability hooks): peer-loss detection needs
    every host beating. Also maintains the rolling per-step-time estimate
    the watchdog derives its hang deadline from, which is why this hook is
    unthrottled — a cadence would quantize the estimate."""

    def __init__(self, publisher):
        self.publisher = publisher

    def __call__(self, step: int, state, metrics: Dict[str, Any]) -> None:
        self.publisher.update(step=step, phase="train")


class CheckpointHook:
    """Save via CheckpointManager on its step/time policy.

    Refuses to checkpoint a visibly non-finite state: with time-based
    cadence the save timer can fire between a loss blow-up and the NaN
    guard's next check, and a committed NaN checkpoint (valid manifest!)
    would then be what every rollback restores — defeating the recovery in
    resilience/sentinel.py. The finite check runs only when the cadence
    actually fires, so the hot path pays no device sync.

    ``heartbeat`` (assigned by main.py when the watchdog is armed) flips
    the phase to the unmonitored "save" around the save: a large state on
    a slow shared FS can legitimately stall the main thread past the hang
    deadline, and the watchdog must not 75 a healthy run mid-checkpoint.
    The phase flip also marks an EWMA interlude, so the save time never
    inflates the rolling step-time estimate."""

    def __init__(self, manager, heartbeat=None):
        self.manager = manager
        self.heartbeat = heartbeat

    def __call__(self, step: int, state, metrics: Dict[str, Any]) -> None:
        # gate first so the finite check (a device sync via float()) is
        # paid only when the cadence actually fires
        should = getattr(self.manager, "should_save", None)
        if should is not None and not should(step):
            return
        bad = nonfinite_metric(metrics)
        if bad is not None:
            log.warning("skipping checkpoint at step %d: non-finite %s "
                        "(the NaN guard will handle recovery)", step, bad)
            return
        if self.heartbeat is not None:
            self.heartbeat.set_phase("save")
            try:
                self.manager.maybe_save(step, state)
            finally:
                self.heartbeat.set_phase("train")
        else:
            self.manager.maybe_save(step, state)


class NanGuardHook(_CadenceHook):
    """Abort (or callback) on non-finite loss — active divergence detection.

    The reference's only guard was a human watching the 20-step loss log
    (SURVEY.md §4.4); a NaN there kept burning cluster hours until someone
    looked. Checks at a cadence to avoid forcing a device sync every step.
    """

    class NanLossError(RuntimeError):
        pass

    def __init__(self, every_steps: int = 100, on_nan=None):
        self.every_steps = max(1, every_steps)
        self.on_nan = on_nan
        self._last = 0

    def __call__(self, step: int, state, metrics: Dict[str, Any]) -> None:
        if not cadence_crossed(step, self.every_steps, self._last):
            return
        self._last = step
        # loss AND grad_norm (nonfinite_metric): an exploding gradient
        # shows up in grad_norm a step before the loss goes non-finite
        # (the optimizer has already eaten the inf update by then) —
        # catching either is the trigger for the rollback policy in
        # resilience/sentinel.py
        bad = nonfinite_metric(metrics)
        if bad is not None:
            if self.on_nan is not None:
                self.on_nan(step, metrics)
                return
            raise self.NanLossError(
                f"non-finite {bad} {float(metrics[bad])} at step {step}")
