"""Ablation profile of the ImageNet ResNet-50 train step on one TPU chip.

Quantifies where the step time goes — specifically the BatchNorm batch-stat
reduction tax identified in round 2 (MFU plateau at ~35%) — by timing the
SAME fused k-step train dispatch under controlled variants:

  * baseline      — exact BN moments (ops/batch_norm.py, stat_subsample=1)
  * subsample s   — moments from the ::s spatial lattice (s ∈ {2, 4})
  * frozen-stats  — normalize with running stats (NO moment reduction at
                    all; not a training mode — the upper bound on what
                    killing the stat tax could ever buy)
  * fwd-only      — loss forward without grad/update (fwd/bwd split)

Writes docs/perf_imagenet_r3.json and prints a markdown table; the committed
docs/perf_imagenet_r3.md is generated from this output. Run on real TPU:

    python tools/profile_imagenet_bn.py [--bs 128] [--k 8]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

# persistent compile cache: each variant is one compile of a large RN50 scan
# graph; re-runs (and re-invocations per variant) hit the cache
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def build_step(bs: int, k: int, stat_subsample: int = 1):
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        shard_batch, shard_stacked_batch)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    cfg = get_preset("imagenet_resnet50")
    cfg.train.batch_size = bs
    cfg.train.steps_per_loop = k
    cfg.model.bn_stat_subsample = stat_subsample
    cfg.mesh.data = len(jax.devices())
    trainer = Trainer(cfg)
    trainer.init_state()
    multi_fn = trainer.jitted_multi_step(k)
    rng = np.random.RandomState(0)
    batch = shard_stacked_batch({
        "images": rng.randn(k, bs, 224, 224, 3).astype(np.float32),
        "labels": rng.randint(0, 1001, (k, bs)).astype(np.int32),
    }, trainer.mesh)
    one = shard_batch({"images": np.asarray(batch["images"])[0],
                       "labels": np.asarray(batch["labels"])[0]}, trainer.mesh)
    return trainer, multi_fn, batch, one


def time_multi(multi_fn, state, batch, k: int, loops: int = 5, reps: int = 3):
    for _ in range(2):
        state, _ = multi_fn(state, batch)
    jax.block_until_ready(state.params)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(loops):
            # state threads through (its input buffer is donated each call)
            state, _ = multi_fn(state, batch)
        jax.block_until_ready(state.params)
        best = min(best, time.perf_counter() - t0)
    return best / (loops * k)  # sec per optimizer step


def frozen_stats_patch():
    """Context manager: GroupedBatchNorm normalizes with running stats even
    in train mode — removes every batch-moment reduction from the graph."""
    import contextlib
    from distributed_resnet_tensorflow_tpu.ops import batch_norm as bn_mod

    @contextlib.contextmanager
    def patch():
        orig = bn_mod.GroupedBatchNorm.__call__

        def frozen(self, x, train):
            return orig(self, x, False)
        bn_mod.GroupedBatchNorm.__call__ = frozen
        try:
            yield
        finally:
            bn_mod.GroupedBatchNorm.__call__ = orig
    return patch()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bs", type=int, default=128)
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--out", default="docs/perf_imagenet_r3.json")
    ap.add_argument("--variant", default="all",
                    help="all | baseline | subsample2 | subsample4 | "
                         "frozen_stats | fwd_only")
    args = ap.parse_args()
    from distributed_resnet_tensorflow_tpu.utils import profiling

    bs, k = args.bs, args.k
    out = {"batch_size": bs, "steps_per_loop": k,
           "device": jax.devices()[0].device_kind,
           "peak_tflops": profiling.detect_peak_tflops(), "variants": {}}
    if os.path.exists(args.out):  # merge: one variant per invocation works
        with open(args.out) as f:
            prev = json.load(f)
        if prev.get("batch_size") == bs:
            out["variants"].update(prev.get("variants", {}))

    def want(name):
        return args.variant in ("all", name)

    def record(name, sec_per_step, step_flops):
        img_s = bs / sec_per_step
        mfu = profiling.mfu(1.0 / sec_per_step, step_flops) \
            if step_flops else None
        out["variants"][name] = {
            "ms_per_step": round(sec_per_step * 1e3, 3),
            "images_per_sec": round(img_s, 1),
            "step_flops": step_flops,
            "mfu": round(mfu, 4) if mfu else None,
        }
        print(f"{name:>14}: {sec_per_step*1e3:7.2f} ms/step  "
              f"{img_s:7.0f} img/s  MFU={mfu if mfu else float('nan'):.3f}")

    # MFU convention: model FLOPs = the exact-moment graph's FLOPs, so
    # variants are compared on useful work, not on their own (smaller)
    # op counts
    flops_exact = out["variants"].get("baseline", {}).get("step_flops")
    for s in (1, 2, 4):
        name = "baseline" if s == 1 else f"subsample{s}"
        if not want(name):
            continue
        trainer, multi_fn, batch, one = build_step(bs, k, stat_subsample=s)
        sec = time_multi(multi_fn, trainer.state, batch, k)
        if s == 1:
            flops_exact = profiling.flops_per_step(
                trainer.jitted_train_step(), trainer.state, one)
        record(name, sec, flops_exact)

    # frozen running-stats upper bound
    if want("frozen_stats"):
        with frozen_stats_patch():
            trainer, multi_fn, batch, one = build_step(bs, k, stat_subsample=1)
            sec = time_multi(multi_fn, trainer.state, batch, k)
            record("frozen_stats", sec, flops_exact)

    # forward-only (loss value, no grad) — fwd/bwd split
    if not want("fwd_only"):
        return finish(out, args)
    trainer, _multi, batch, one = build_step(bs, k, stat_subsample=1)
    state = trainer.state

    def fwd_loss(state, b):
        variables = {"params": state.params, "batch_stats": state.batch_stats}
        logits, _ = state.apply_fn(variables, b["images"], train=True,
                                   mutable=["batch_stats"])
        oh = jax.nn.one_hot(b["labels"], logits.shape[-1], dtype=jnp.float32)
        import optax
        return optax.softmax_cross_entropy(
            logits.astype(jnp.float32), oh).mean()

    fwd = jax.jit(fwd_loss)

    def fwd_multi(state, batches):
        def body(c, b):
            return c + fwd_loss(state, b), ()
        return jax.lax.scan(body, 0.0, batches)[0]
    fwd_multi_j = jax.jit(fwd_multi)
    fwd_multi_j(state, batch).block_until_ready()
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(5):
            r = fwd_multi_j(state, batch)
        r.block_until_ready()
        best = min(best, (time.perf_counter() - t0) / (5 * k))
    record("fwd_only", best, None)
    del fwd
    finish(out, args)


def finish(out, args):
    v = out["variants"]
    if "baseline" in v and "frozen_stats" in v:
        base = v["baseline"]["ms_per_step"]
        froz = v["frozen_stats"]["ms_per_step"]
        out["bn_stat_tax_fraction"] = round((base - froz) / base, 4)
        print(f"\nBN stat tax: {out['bn_stat_tax_fraction']:.1%} of the step")
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
