"""Bucketed gradient-communication overlap (parallel/overlap.py).

The load-bearing claims, each pinned here on the virtual 8-device mesh:
bucketed and unbucketed (single-bucket) exchanges are BIT-IDENTICAL
(same per-leaf all-reduce over the same operands — the bucketing
transformation must be a pure scheduling change), the overlap path
agrees with the default XLA-propagation step to float rounding across
dp AND dp_fsdp, the envelope resolver refuses unsupported combinations
loudly, and the plan telemetry (comm_overlap event) exports what the
compiled step actually does.
"""
import numpy as np
import pytest

import jax

from distributed_resnet_tensorflow_tpu.parallel import create_mesh
from distributed_resnet_tensorflow_tpu.parallel.overlap import (
    overlap_stats, overlap_unsupported_reason, plan_buckets,
    resolve_overlap)
from distributed_resnet_tensorflow_tpu.train import Trainer
from distributed_resnet_tensorflow_tpu.utils.config import (MeshConfig,
                                                            get_preset)


def _tiny_cfg(**kw):
    cfg = get_preset("smoke")
    cfg.model.compute_dtype = "float32"
    cfg.model.resnet_size = 8
    cfg.model.num_classes = 4
    cfg.data.image_size = 8
    cfg.train.batch_size = 16
    cfg.optimizer.schedule = "constant"
    cfg.checkpoint.save_every_secs = 0.0
    for k, v in kw.items():
        cfg.override(k, v)
    return cfg


def _fixed_batches(n=4, bs=16, size=8, classes=4):
    rng = np.random.RandomState(7)
    imgs = rng.randn(n, bs, size, size, 3).astype(np.float32)
    labs = rng.randint(0, classes, (n, bs)).astype(np.int32)
    return [{"images": imgs[i], "labels": labs[i]} for i in range(n)]


def _flat_params(state):
    return np.concatenate([np.asarray(l).ravel() for l in
                           jax.tree_util.tree_leaves(state.params)])


def _train(mesh_cfg, batches, **kw):
    cfg = _tiny_cfg(**kw)
    tr = Trainer(cfg, mesh=create_mesh(mesh_cfg))
    tr.init_state()
    state, metrics = tr.train(iter(list(batches)), num_steps=len(batches))
    return _flat_params(state), metrics


# ---------------------------------------------------------------------------
# exactness (the acceptance claim)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mesh_cfg", [
    MeshConfig(data=8),                    # dp
    # dp_fsdp re-tiered out of the 870s tier-1 (ISSUE 20, ~10s: two full
    # trainings on the sharded layout); the dp leg keeps the bucketing
    # bit-identity claim in tier-1 and the dp_fsdp LAYOUT stays covered
    # by test_zero1_overlap_matches_plain_path[dp_fsdp]; the full
    # (unfiltered) suite runs both
    pytest.param(MeshConfig(data=4, fsdp=2), marks=pytest.mark.slow),
], ids=["dp", "dp_fsdp"])
def test_bucketed_is_bit_identical_to_unbucketed(mesh_cfg):
    """Many tiny buckets vs one bucket holding everything: the per-leaf
    psum operands are identical either way, so the trained params must be
    BITWISE equal — bucketing may only change collective scheduling,
    never numerics."""
    batches = _fixed_batches()
    many, m1 = _train(mesh_cfg, batches,
                      **{"comm.overlap": "on", "comm.bucket_mb": "0.05"})
    plan = overlap_stats.snapshot()
    assert plan is not None and plan["buckets"] > 1, plan
    one, m2 = _train(mesh_cfg, batches,
                     **{"comm.overlap": "on", "comm.bucket_mb": "4096"})
    assert overlap_stats.snapshot()["buckets"] == 1
    np.testing.assert_array_equal(many, one)
    assert float(m1["loss"]) == float(m2["loss"])


@pytest.mark.parametrize("mesh_cfg", [
    MeshConfig(data=8),
    # dp_fsdp re-tiered out of the 870s tier-1 (ISSUE 19, ~13s: two full
    # trainings on the sharded layout); the dp leg keeps the
    # overlap-vs-default allclose claim in tier-1 and
    # test_bucketed_is_bit_identical_to_unbucketed[dp_fsdp] keeps the
    # fsdp layout pinned — the full (unfiltered) suite runs the cross
    pytest.param(MeshConfig(data=4, fsdp=2), marks=pytest.mark.slow),
], ids=["dp", "dp_fsdp"])
def test_overlap_matches_default_path_to_float_rounding(mesh_cfg):
    """Against the default XLA-propagation exchange the reduction TREE
    differs (local-sum-then-psum vs XLA's schedule), so agreement is to
    float rounding, not bitwise — a few steps of a float32 model stay
    within a tight allclose."""
    batches = _fixed_batches()
    base, mb = _train(mesh_cfg, batches, **{"comm.overlap": "off"})
    over, mo = _train(mesh_cfg, batches, **{"comm.overlap": "on",
                                            "comm.bucket_mb": "0.1"})
    np.testing.assert_allclose(over, base, rtol=2e-4, atol=2e-5)
    assert abs(float(mo["loss"]) - float(mb["loss"])) < 1e-4


# re-tiered out of the 870s tier-1 (ISSUE 17, ~13s). Overlap×fused
# multi-step composition: each side stays pinned in tier-1 on its own
# (test_overlap_matches_default_path_to_float_rounding, the fused
# multi-step tests in test_train), the full (unfiltered) suite runs
# the cross.
@pytest.mark.slow
def test_overlap_composes_with_fused_multi_step(devices):
    """steps_per_loop > 1 wraps the shard_map'd step in lax.scan — the
    fused dispatch must produce the same params as the unfused loop."""
    batches = _fixed_batches(n=4)
    stacked_equal, _ = _train(MeshConfig(data=8), batches,
                              **{"comm.overlap": "on",
                                 "comm.bucket_mb": "0.05",
                                 "train.steps_per_loop": "2"})
    unfused, _ = _train(MeshConfig(data=8), batches,
                        **{"comm.overlap": "on", "comm.bucket_mb": "0.05"})
    np.testing.assert_allclose(stacked_equal, unfused, rtol=1e-5,
                               atol=1e-6)


# ---------------------------------------------------------------------------
# gradient accumulation inside the exchange body
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mesh_cfg", [
    MeshConfig(data=8),
    # dp_fsdp re-tiered out of the 870s tier-1 (~16s: two accumulated
    # trainings on the sharded layout); the dp leg keeps the bit-identity
    # claim in tier-1, the full (unfiltered) suite runs both
    pytest.param(MeshConfig(data=4, fsdp=2), marks=pytest.mark.slow),
], ids=["dp", "dp_fsdp"])
def test_accum_bucketed_is_bit_identical_and_wire_is_1x(mesh_cfg):
    """The acceptance claim for the accumulation scan: many-vs-one-bucket
    accumulated exchanges are BITWISE equal (bucketing stays a pure
    scheduling change with the scan inside the body), and the recorded
    per-step wire bytes equal the gradient bytes ONCE — 1/accum of what
    a per-microbatch exchange would move."""
    batches = _fixed_batches()
    kw = {"comm.overlap": "on", "train.grad_accum_steps": "2"}
    many, m1 = _train(mesh_cfg, batches, **{"comm.bucket_mb": "0.05", **kw})
    plan = overlap_stats.snapshot()
    assert plan["buckets"] > 1 and plan["accum_steps"] == 2
    assert plan["wire_bytes"] == plan["grad_bytes"]  # ONE exchange/step
    one, m2 = _train(mesh_cfg, batches, **{"comm.bucket_mb": "4096", **kw})
    assert overlap_stats.snapshot()["buckets"] == 1
    np.testing.assert_array_equal(many, one)
    assert float(m1["loss"]) == float(m2["loss"])


# re-tiered out of the 870s tier-1 (ISSUE 17, ~16s: a second accum
# exactness oracle). The accumulation contract stays pinned in tier-1
# by test_accum_bucketed_is_bit_identical_and_wire_is_1x[dp] (bit
# identity + wire accounting); the full (unfiltered) suite re-runs it
# against this composition-matched jit oracle too.
@pytest.mark.slow
def test_accum_matches_composition_matched_jit_oracle(devices):
    """The accumulated exchange vs the plain jit accumulation scan. The
    body slices microbatches PER SHARD (each shard's local batch splits
    into accum slices — no cross-shard reshard), while the jit scan
    slices the global batch contiguously; permuting the oracle's batch to
    the body's composition makes the two runs the same math: loss/ce
    agree to float equality, params to float rounding (the accumulation
    summation orders differ)."""
    shards, bs, accum = 8, 16, 2
    lb = bs // shards
    mbl = lb // accum
    perm = np.array([k * lb + m * mbl + j
                     for m in range(accum)
                     for k in range(shards)
                     for j in range(mbl)])
    batches = _fixed_batches()
    permuted = [{"images": b["images"][perm], "labels": b["labels"][perm]}
                for b in batches]
    over, mo = _train(MeshConfig(data=8), batches,
                      **{"comm.overlap": "on", "comm.bucket_mb": "0.05",
                         "train.grad_accum_steps": "2"})
    cfg = _tiny_cfg(**{"comm.overlap": "off", "train.grad_accum_steps": "2"})
    tr = Trainer(cfg, mesh=create_mesh(MeshConfig(data=8)))
    tr.init_state()
    state, mj = tr.train(iter(permuted), num_steps=len(permuted))
    base = _flat_params(state)
    assert abs(float(mo["loss"]) - float(mj["loss"])) < 1e-6
    assert abs(float(mo["cross_entropy"]) - float(mj["cross_entropy"])) \
        < 1e-6
    np.testing.assert_allclose(over, base, rtol=2e-3, atol=2e-5)


# ---------------------------------------------------------------------------
# transformer-family legs (the layout-aware exchange)
# ---------------------------------------------------------------------------

def _vit_cfg(experts=0, **kw):
    cfg = _tiny_cfg()
    cfg.model.name = "vit"
    cfg.model.vit_patch_size = 4
    cfg.model.vit_dim = 16
    cfg.model.vit_depth = 4
    cfg.model.vit_heads = 2
    cfg.model.vit_num_experts = experts
    cfg.optimizer.name = "adam"
    cfg.optimizer.learning_rate = 1e-3
    cfg.optimizer.weight_decay = 0.0
    for k, v in kw.items():
        cfg.override(k, v)
    return cfg


def _mesh_subset(mesh_cfg):
    import math
    n = math.prod(max(1, s) for s in (
        mesh_cfg.data, mesh_cfg.fsdp, mesh_cfg.tensor, mesh_cfg.pipeline,
        mesh_cfg.sequence, mesh_cfg.expert))
    return create_mesh(mesh_cfg, devices=jax.devices()[:n])


@pytest.mark.parametrize("mesh_cfg,experts,expect_axes", [
    # dp_tp re-tiered out of the 870s tier-1 (~16s: ViT leg pair on the
    # tensor-sharded layout); dp_pp and dp_pp_ep keep the multi-axis
    # overlap claim in tier-1, the full (unfiltered) suite runs all three
    pytest.param(MeshConfig(data=4, tensor=2), 0, {"data+fsdp"},
                 marks=pytest.mark.slow),
    (MeshConfig(data=2, pipeline=2), 0,
     {"data+fsdp", "data+fsdp+pipeline"}),
    # dp_pp_ep legs-match re-tiered out of tier-1 too (ISSUE 17, ~16s):
    # the dp_pp_ep layout keeps its tier-1 pin via
    # test_vit_overlap_bucketing_bit_identical_dp_pp_ep (the stronger
    # bit-identity claim); the full suite runs the allclose leg pair
    pytest.param(MeshConfig(data=2, pipeline=2, expert=2), 2,
                 {"data+fsdp", "data+fsdp+expert",
                  "data+fsdp+pipeline+expert"},
                 marks=pytest.mark.slow),
], ids=["dp_tp", "dp_pp", "dp_pp_ep"])
def test_vit_overlap_legs_match_default_path(mesh_cfg, experts,
                                             expect_axes):
    """The transformer legs of the universal envelope: the layout-aware
    exchange (partial-auto tensor / inline pipeline / per-expert-group
    buckets) must agree with the XLA-propagation step to float rounding,
    and the plan's per-bucket reduce-axis sets must be exactly the
    layout's expected partition of the leaves."""
    mesh = _mesh_subset(mesh_cfg)

    def run(overlap):
        cfg = _vit_cfg(experts=experts,
                       **{"comm.overlap": overlap,
                          "comm.bucket_mb": "0.01"})
        tr = Trainer(cfg, mesh=mesh)
        tr.init_state()
        state, metrics = tr.train(iter(_fixed_batches()), num_steps=4)
        return _flat_params(state), metrics

    base, mb = run("off")
    over, mo = run("on")
    plan = overlap_stats.snapshot()
    assert set(plan["bucket_reduce_axes"]) == expect_axes, plan
    np.testing.assert_allclose(over, base, rtol=5e-3, atol=5e-5)
    assert abs(float(mo["loss"]) - float(mb["loss"])) < 5e-4


@pytest.mark.slow  # re-tiered out of the 870s tier-1 (ISSUE 20, ~13s:
# two 4-step MoE-pipeline trainings); tier-1 keeps the same bit-identity
# claim via test_bucketed_is_bit_identical_to_unbucketed[dp] and the
# same dp_pp_ep-family layout through the overlap path via
# test_vit_overlap_legs_match_default_path[dp_pp]; the full (unfiltered)
# suite runs this grouped-bucket composition
def test_vit_overlap_bucketing_bit_identical_dp_pp_ep(devices):
    """Many-vs-one-bucket on the MoE pipeline layout: grouped buckets
    (one reduce-axis set each) are still a pure scheduling change."""
    mesh = _mesh_subset(MeshConfig(data=2, pipeline=2, expert=2))

    def run(bucket_mb):
        cfg = _vit_cfg(experts=2, **{"comm.overlap": "on",
                                     "comm.bucket_mb": bucket_mb})
        tr = Trainer(cfg, mesh=mesh)
        tr.init_state()
        state, _ = tr.train(iter(_fixed_batches(n=2)), num_steps=2)
        return _flat_params(state)

    many = run("0.01")
    assert overlap_stats.snapshot()["buckets"] > 3
    one = run("4096")
    # one bucket PER reduce-axis set is the floor — never fewer
    assert overlap_stats.snapshot()["buckets"] == 3
    np.testing.assert_array_equal(many, one)


# ---------------------------------------------------------------------------
# bucket planning
# ---------------------------------------------------------------------------

def test_plan_buckets_reverse_order_and_cap():
    # leaves of 3,3,3,3 bytes with a 6-byte cap: reverse-order pairs
    assert plan_buckets([3, 3, 3, 3], 6) == [[3, 2], [1, 0]]
    # an oversized leaf gets its own bucket, never split
    assert plan_buckets([100, 1, 1], 8) == [[2, 1], [0]]
    # everything fits: one bucket, still reverse order
    assert plan_buckets([1, 2, 3], 100) == [[2, 1, 0]]
    assert plan_buckets([], 8) == []


def test_plan_buckets_grouped():
    from distributed_resnet_tensorflow_tpu.parallel.overlap import (
        plan_buckets_grouped)
    A, B = ("data", "fsdp"), ("data", "fsdp", "expert")
    # one group degenerates to plan_buckets (same buckets, same order)
    assert plan_buckets_grouped([3, 3, 3, 3], [A] * 4, 6) == \
        [(A, [3, 2]), (A, [1, 0])]
    # mixed signatures never share a bucket, even under the byte cap;
    # issue order follows the reversed position of each bucket's first
    # leaf (backprop availability)
    assert plan_buckets_grouped([3, 3, 3, 3], [A, B, A, B], 100) == \
        [(B, [3, 1]), (A, [2, 0])]
    # per-group caps still apply
    assert plan_buckets_grouped([3, 3, 3, 3], [A, B, A, B], 3) == \
        [(B, [3]), (A, [2]), (B, [1]), (A, [0])]
    assert plan_buckets_grouped([], [], 8) == []


# ---------------------------------------------------------------------------
# envelope / resolver
# ---------------------------------------------------------------------------

def test_resolver_gates(devices):
    mesh = create_mesh(MeshConfig(data=8))
    # off → None regardless of support
    assert resolve_overlap(_tiny_cfg(**{"comm.overlap": "off"}), mesh) is None
    # auto on a single-process run stays off (the DCN path is the target)
    assert resolve_overlap(_tiny_cfg(), mesh) is None
    # on → forced
    plan = resolve_overlap(_tiny_cfg(**{"comm.overlap": "on"}), mesh)
    assert plan is not None and plan.bucket_bytes == 4 * 2 ** 20

    # gradient accumulation is IN-envelope now (the body owns the scan);
    # the resolver only checks the microbatch divisibility
    accum = _tiny_cfg(**{"comm.overlap": "on",
                         "train.grad_accum_steps": "2"})
    assert overlap_unsupported_reason(accum, mesh) is None
    assert resolve_overlap(accum, mesh) is not None

    # unsupported combinations raise WITH the reason under "on"
    for kw, needle in [
        ({"model.cross_replica_bn": "false"}, "cross_replica_bn"),
        ({"train.batch_size": "12"}, "does not divide"),
        # 16 divides 8 shards but not 8 shards × 3 microbatches
        ({"train.grad_accum_steps": "3"}, "microbatches"),
    ]:
        bad = _tiny_cfg(**{"comm.overlap": "on", **kw})
        assert overlap_unsupported_reason(bad, mesh) is not None
        with pytest.raises(ValueError, match=needle):
            resolve_overlap(bad, mesh)
        # ...and quietly resolve off under "auto"
        bad.comm.overlap = "auto"
        assert resolve_overlap(bad, mesh) is None

    # the transformer family is in-envelope on batch/tensor/pipeline
    # meshes now; the remaining refusals are the nesting-shard_map axes,
    # each with its precise reason
    vit = _tiny_cfg(**{"comm.overlap": "on"})
    vit.model.name = "vit"
    assert overlap_unsupported_reason(vit, mesh) is None
    seq_mesh = create_mesh(MeshConfig(data=4, sequence=2))
    assert "seq" in overlap_unsupported_reason(vit, seq_mesh)
    ep_mesh = create_mesh(MeshConfig(data=4, expert=2))
    assert "expert" in overlap_unsupported_reason(vit, ep_mesh)
    tp_pp_mesh = create_mesh(MeshConfig(data=2, tensor=2, pipeline=2))
    assert "tensor" in overlap_unsupported_reason(vit, tp_pp_mesh)

    # a single-shard mesh is what checkpoint consumers (evaluator, a
    # 1-device serving replica) see — a forced train-only knob must
    # resolve off there, loudly, not crash the consumer
    single = create_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    assert resolve_overlap(_tiny_cfg(**{"comm.overlap": "on"}),
                           single) is None


def test_per_replica_bn_envelope_exceptions(devices):
    """norm='group' has no batch coupling, so per-replica-BN gating must
    not block it; frozen BN likewise."""
    mesh = create_mesh(MeshConfig(data=8))
    for norm in ("group", "frozen"):
        cfg = _tiny_cfg(**{"comm.overlap": "on",
                           "model.cross_replica_bn": "false"})
        cfg.model.norm = norm
        assert overlap_unsupported_reason(cfg, mesh) is None


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

def test_comm_overlap_event_row(tmp_path, devices):
    from distributed_resnet_tensorflow_tpu.train.hooks import CommOverlapHook
    from distributed_resnet_tensorflow_tpu.utils.metrics import (
        MetricsWriter, read_metrics)
    overlap_stats.reset()
    batches = _fixed_batches(n=2)
    cfg = _tiny_cfg(**{"comm.overlap": "on", "comm.bucket_mb": "0.05"})
    tr = Trainer(cfg, mesh=create_mesh(MeshConfig(data=8)))
    assert tr.comm_overlap_active
    tr.init_state()
    w = MetricsWriter(str(tmp_path), enable_tensorboard=False)
    hook = CommOverlapHook(w, every_steps=1)
    tr.train(iter(batches), num_steps=2, hooks=(hook,))
    w.close()
    rows = [r for r in read_metrics(str(tmp_path))
            if r.get("event") == "comm_overlap"]
    assert len(rows) == 1  # one row per traced plan, not per step
    row = rows[0]
    assert row["buckets"] > 1
    assert sum(row["bucket_bytes"]) == row["grad_bytes"]
    assert sum(row["bucket_leaves"]) == row["leaves"]


def test_overlap_off_writes_no_plan(devices):
    overlap_stats.reset()
    batches = _fixed_batches(n=1)
    _train(MeshConfig(data=8), batches, **{"comm.overlap": "off"})
    assert overlap_stats.snapshot() is None
