"""Lock-acquisition-order analysis: find cycles before they deadlock.

The process holds ~20 locks (serve batcher/swap/compile-cache, heartbeat,
watchdog escalation, metrics writer, tracer ring, stager ring, stats
registries). Each is individually a short leaf critical section — the
deadlock risk is COMPOSITION: thread 1 holds lock A and calls into code
that takes lock B while thread 2 does the reverse. That cycle is
invisible at either site and only fires under load, as a hang the
watchdog can merely kill.

This module extracts the acquisition-order graph statically:

  * lock identities from ``self._x = threading.Lock()/RLock()/Condition()``
    assignments (→ ``module::Class._x``) and module-level
    ``NAME = threading.Lock()`` (→ ``module::NAME``);
  * acquisition sites from ``with <lock>:`` statements (the codebase's
    idiom — bare ``.acquire()`` is not used);
  * an edge A→B whenever, lexically inside a ``with A:`` body, either a
    nested ``with B:`` appears or a call resolves (via
    ``analysis/callgraph.py``'s conservative resolver) to a function
    that — transitively — acquires B.

``rules/lock_order.py`` fails the gate on any cycle in that graph,
including self-cycles (re-acquiring a non-reentrant ``threading.Lock``
deadlocks immediately). Lock identity is per CLASS attribute, not per
instance: two instances of one class cannot be distinguished statically,
so a reported cycle on one identity may in reality span two objects —
that is still an ordering hazard worth a look, and a vetted exception
carries ``# shardcheck: ok(lock-order-cycle)`` at the acquisition site.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .callgraph import CallGraph, FuncKey, FuncNode, get_callgraph

_LOCK_CTORS = ("Lock", "RLock", "Condition")


@dataclass(frozen=True)
class LockSite:
    """One ``with <lock>:`` acquisition."""

    lock: str          # lock identity, e.g. "serve/batcher.py::DynamicBatcher._in_lock"
    rel: str
    lineno: int
    fn: FuncKey


@dataclass
class LockModel:
    locks: Set[str] = field(default_factory=set)
    sites: List[LockSite] = field(default_factory=list)
    #: fn key -> direct acquisitions in that function's own body
    fn_sites: Dict[FuncKey, List[Tuple[ast.With, LockSite]]] = \
        field(default_factory=dict)


def _short(rel: str) -> str:
    from .callgraph import PACKAGE
    prefix = PACKAGE + "/"
    return rel[len(prefix):] if rel.startswith(prefix) else rel


def _is_lock_ctor(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    fn = value.func
    name = fn.id if isinstance(fn, ast.Name) else \
        fn.attr if isinstance(fn, ast.Attribute) else None
    return name in _LOCK_CTORS


def _lock_identity(expr: ast.AST, fn: FuncNode,
                   known: Set[str]) -> Optional[str]:
    """Map a with-item context expression onto a known lock identity."""
    short = _short(fn.rel)
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self" \
            and fn.cls is not None:
        lid = f"{short}::{fn.cls}.{expr.attr}"
        if lid in known:
            return lid
        # the attribute may be assigned in ANOTHER class this class wraps;
        # fall back to a unique attr-name match across known locks
        cands = [k for k in known if k.endswith("." + expr.attr)]
        return cands[0] if len(cands) == 1 else None
    if isinstance(expr, ast.Name):
        lid = f"{short}::{expr.id}"
        if lid in known:
            return lid
        cands = [k for k in known if k.split("::")[-1] == expr.id]
        return cands[0] if len(cands) == 1 else None
    return None


def build_lock_model(ctx) -> LockModel:
    graph = get_callgraph(ctx)
    model = LockModel()
    # pass 1: lock identities
    for sf in ctx.all_python():
        if sf.tree is None:
            continue
        short = _short(sf.rel)

        def scan(node, cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    scan(child, child.name)
                    continue
                if isinstance(child, ast.Assign) and \
                        _is_lock_ctor(child.value):
                    for tgt in child.targets:
                        if isinstance(tgt, ast.Name) and cls is None:
                            model.locks.add(f"{short}::{tgt.id}")
                        elif isinstance(tgt, ast.Attribute) and \
                                isinstance(tgt.value, ast.Name) and \
                                tgt.value.id == "self" and cls is not None:
                            model.locks.add(f"{short}::{cls}.{tgt.attr}")
                scan(child, cls)

        scan(sf.tree, None)

    # pass 2: acquisition sites per function
    for key, fn in graph.funcs.items():
        sites: List[Tuple[ast.With, LockSite]] = []
        for node in _own_body_withs(fn.node):
            for item in node.items:
                lid = _lock_identity(item.context_expr, fn, model.locks)
                if lid is not None:
                    site = LockSite(lid, fn.rel, node.lineno, key)
                    sites.append((node, site))
                    model.sites.append(site)
        if sites:
            model.fn_sites[key] = sites
    return model


def _own_body_withs(fn_node) -> Iterator[ast.With]:
    from .callgraph import body_walk
    for node in body_walk(fn_node):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            yield node


def _acquires_closure(graph: CallGraph, model: LockModel
                      ) -> Dict[FuncKey, Set[str]]:
    """fn → every lock it may acquire, directly or via resolved calls."""
    out: Dict[FuncKey, Set[str]] = {
        key: {s.lock for _, s in sites}
        for key, sites in model.fn_sites.items()}
    for key in graph.funcs:
        out.setdefault(key, set())
    changed = True
    while changed:
        changed = False
        for key in graph.funcs:
            acc = out[key]
            before = len(acc)
            for callee in graph.edges(key):
                acc |= out.get(callee, set())
            if len(acc) != before:
                changed = True
    return out


@dataclass(frozen=True)
class LockEdge:
    held: str
    acquired: str
    rel: str        # where the inner acquisition is introduced
    lineno: int
    via: str        # "nested with" or the call text that leads there


def build_order_graph(ctx) -> List[LockEdge]:
    """Every held→acquired pair the analyzer can see."""
    graph = get_callgraph(ctx)
    model = build_lock_model(ctx)
    closure = _acquires_closure(graph, model)
    edges: List[LockEdge] = []
    seen = set()

    def add(held, acquired, rel, lineno, via):
        k = (held, acquired, rel, lineno)
        if k not in seen:
            seen.add(k)
            edges.append(LockEdge(held, acquired, rel, lineno, via))

    for key, sites in model.fn_sites.items():
        fn = graph.funcs[key]
        for with_node, site in sites:
            # everything lexically inside this with-body
            inner_withs = []
            inner_calls = []
            stack = list(with_node.body)
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    inner_withs.append(node)
                if isinstance(node, ast.Call):
                    inner_calls.append(node)
                stack.extend(ast.iter_child_nodes(node))
            for iw in inner_withs:
                for item in iw.items:
                    lid = _lock_identity(item.context_expr, fn,
                                         model.locks)
                    if lid is not None:
                        add(site.lock, lid, fn.rel, iw.lineno,
                            "nested with")
            for call in inner_calls:
                for callee in graph.resolve_call(call, fn):
                    for lid in sorted(closure.get(callee.key, ())):
                        add(site.lock, lid, fn.rel, call.lineno,
                            f"call into {callee.short()}")
    return edges


def find_cycles(edges: List[LockEdge]) -> List[List[LockEdge]]:
    """Elementary cycles in the acquisition-order graph (each reported
    once, rotated to start at the smallest lock id). Self-edges (A→A,
    re-acquiring a non-reentrant lock) are length-1 cycles."""
    adj: Dict[str, List[LockEdge]] = {}
    for e in edges:
        adj.setdefault(e.held, []).append(e)
    cycles: List[List[LockEdge]] = []
    seen_keys = set()

    def canon(path: List[LockEdge]):
        names = [e.held for e in path]
        i = names.index(min(names))
        rotated = path[i:] + path[:i]
        return tuple((e.held, e.acquired) for e in rotated), rotated

    def dfs(start: str, node: str, path: List[LockEdge],
            on_path: Set[str]):
        for e in sorted(adj.get(node, ()),
                        key=lambda e: (e.acquired, e.rel, e.lineno)):
            if e.acquired == start:
                key, rotated = canon(path + [e])
                if key not in seen_keys:
                    seen_keys.add(key)
                    cycles.append(rotated)
            elif e.acquired not in on_path and e.acquired > start:
                # only explore ids > start: each cycle found exactly once,
                # from its smallest node
                dfs(start, e.acquired, path + [e],
                    on_path | {e.acquired})

    for e in edges:
        if e.held == e.acquired:
            key = ((e.held, e.acquired),)
            if key not in seen_keys:
                seen_keys.add(key)
                cycles.append([e])
    for start in sorted({e.held for e in edges}):
        dfs(start, start, [], {start})
    return cycles
