"""distributed_resnet_tensorflow_tpu — a TPU-native distributed training framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
hanalice/Distributed-ResNet-Tensorflow (reference mounted at /root/reference):
ResNet-v2 image classification on CIFAR-10/100 and ImageNet with synchronous
data-parallel training. Where the reference used a grpc parameter-server
(`tf.train.SyncReplicasOptimizer`, reference resnet_model.py:102-135) or
Horovod MPI/NCCL allreduce (reference resnet_cifar_main_horovod.py), this
framework uses one SPMD path: `jax.jit` over a `jax.sharding.Mesh` with
sharding-induced XLA collectives riding ICI/DCN.

Layout (mirrors SURVEY.md §7):
  models/      pure-functional ResNet-v2 model zoo (flax.linen)
  ops/         TPU ops: cross-replica batch norm, fused Pallas kernels,
               ring attention / sequence parallelism
  parallel/    mesh construction, sharding rules, collectives, multi-host init
  data/        input pipelines (CIFAR binary, ImageNet TFRecord, synthetic)
  train/       train loop, schedules, optimizers (incl. LARS), hooks
  checkpoint/  orbax-backed async checkpointing with auto-resume
  utils/       config system, metrics/logging
  native/      C++ runtime components (threaded data loader, TFRecord reader)
"""

__version__ = "0.1.0"
