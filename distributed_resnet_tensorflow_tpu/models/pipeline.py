"""Pipeline parallelism — GPipe-style microbatched encoder over the
``pipeline`` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.10: pure data
parallel); this module completes the mesh: every axis of
(data, fsdp, seq, tensor, pipeline) now has a consumer. Design:

  * The encoder's per-layer parameters are STACKED on a leading depth axis
    and sharded over ``pipeline`` (each stage holds depth/P layers) — the
    pipeline analog of the fsdp/tensor rules in parallel/sharding.py.
  * Execution is a ``shard_map`` over the pipeline axis running the GPipe
    schedule as one ``lax.scan`` over M + P - 1 ticks: at tick t, stage s
    processes microbatch t - s; activations hop stages via
    ``lax.ppermute`` (ICI neighbor traffic), stage 0 injects microbatches,
    the last stage collects outputs, and a final masked ``psum`` broadcasts
    them to every stage. Reverse-mode AD is the transposed schedule (scan
    reversed, ppermute inverted) — the backward pipeline for free.
  * Bubble ticks compute on zero-activations and are masked out of the
    result; the bubble fraction is (P-1)/(M+P-1), so M defaults to 2P.
  * ``interleave`` (v) > 1 runs the CIRCULAR schedule (Megatron interleaved /
    praxis circular): each stage holds v non-adjacent chunks of depth/(P*v)
    layers and every microbatch rides the ring v times, shrinking the bubble
    to (P-1)/(v*M+P-1) at the cost of v× more ICI hops per microbatch. At
    tick t, stage s works on u = t - s decomposed as (chunk, microbatch) =
    (u // M, u mod M); wrapped activations re-enter stage 0 through a
    per-microbatch queue because the wrap takes M-P+1 ticks (requires
    M >= P). v=1 reduces to plain GPipe.

On 1F1B: the schedule that cuts *activation memory* (not the bubble) to
O(P) microbatches per stage requires launching each microbatch's backward
eagerly, interleaved with later forwards — a per-microbatch autograd runtime,
which fights XLA's whole-program compilation model. The TPU-native
equivalents are (a) this circular schedule, which attacks the bubble
directly, and (b) ``remat=True``, which bounds the per-tick residual to the
stage inputs that reverse-mode scan transposition must keep — the same
stage-boundary stash 1F1B keeps, held for the whole step rather than P
ticks. Both compose. Measured at fixed global batch (compiled temp bytes per
device, ``tools/pipeline_memory.py`` → ``docs/pipeline_memory_r3.json``):
remat bounds the stash ~10× (738→65 MB at P=4, M=4); at EQUAL bubble the
circular schedule matches GPipe's activation memory (555 MB at v=2, M=4 vs
552 MB at v=1, M=8, both bubble 0.273) while running v× larger microbatches
— the bubble knob that does not shrink the per-tick MXU work — and extends
the reachable bubble floor past where GPipe's microbatches hit size 1.

The block math mirrors ``transformer.EncoderBlock`` op-for-op (pre-LN MHA +
pre-LN MLP with residuals) but is written against explicit stacked params so
one program serves every stage. ``pack_encoder_params`` converts a standard
per-block ViT param tree into the stacked layout (checkpoint migration and
the exact-parity tests).
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

_LN_EPS = 1e-6  # nn.LayerNorm default


def resolve_microbatches(microbatches: int, pstages: int) -> int:
    """The ONE home of the microbatch default (0 → 2 × stages, the GPipe
    sweet spot at bubble (P-1)/(M+P-1)). Shared by the encoder itself,
    Trainer.eval_pad_multiple (eval batches must pad to shards × M) and
    the static elaborator's layout filter — three callers that must agree
    or eval crashes with 'local batch must be a multiple of microbatches'
    at step 1."""
    return microbatches or 2 * pstages


def _layer_norm(x, scale, bias):
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(-1, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + _LN_EPS)
    return (y * scale + bias).astype(x.dtype)


def _block_apply(p, x, num_heads, dtype, tp_axis=None, attn_impl="dense",
                 moe=None, seq=None):
    """One encoder block from a stacked-param slice ``p`` — the explicit-math
    twin of transformer.EncoderBlock (kept in lockstep; exact-parity test:
    tests/test_pipeline.py).

    ``tp_axis``: Megatron tensor parallelism inside the pipeline stage. The
    caller hands this function TENSOR-LOCAL param shards (whole heads of the
    qkv/proj kernels, columns of mlp_w1/b1, rows of mlp_w2 — the same layout
    parallel/sharding.py assigns the per-block modules); the two row-parallel
    contractions (attention out-proj, MLP down-proj) then produce partial
    sums that one ``lax.psum`` each completes — 2 collectives per block,
    exactly the Megatron count. Replicated tensors (x, LN params, mlp_b2)
    stay replicated across ``tp_axis``.

    ``attn_impl``: "dense" (XLA reference), the fused Pallas flash kernel
    ("flash" / "flash_interpret" for CPU tests) — long-context attention
    inside pipeline stages (round 4; the pallas_call runs fine under the
    pipeline shard_map, and the kernel's custom vjp rides the transposed
    scan schedule like any other block op) — or ring attention
    ("ring" / "ring_interpret", round 5, pp×seq): tokens arrive sharded
    over the ``seq`` mesh axis (``seq`` = static (axis_name, n_shards)),
    kv chunks rotate the ICI ring via ppermute INSIDE the pipeline tick,
    and the ring's custom backward rides the transposed scan exactly like
    flash did. "ring" runs the Pallas flash inner block on TPU and the
    pure-lax online recurrence elsewhere (the ring_attention_sharded auto
    rule); "ring_interpret" forces the interpreter kernels (CPU parity
    tests). LayerNorm/MLP are token-pointwise and partition cleanly over
    the extra token sharding.

    When ``p`` carries MoE leaves (moe_w1/...), the MLP is a Switch
    mixture (pp×ep, see _moe_mlp); ``moe`` is the static
    (top_k, capacity_factor, ep_axis) triple. Returns (x, aux) — aux is
    the Switch load-balancing loss for this block (0.0 for the dense
    MLP)."""
    b, t, d = x.shape
    h = _layer_norm(x, p["ln1_scale"], p["ln1_bias"])
    qkv = jnp.einsum("btd,dchk->btchk", h, p["qkv_kernel"].astype(dtype))
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    if attn_impl in ("flash", "flash_interpret"):
        from ..ops.pallas import flash_attention
        o = flash_attention(q, k, v, False, attn_impl == "flash_interpret")
    elif attn_impl == "dense":
        from ..ops.attention import attention
        o = attention(q, k, v)  # local heads only under tp
    elif attn_impl in ("ring", "ring_interpret"):
        from ..ops.attention import resolve_ring_kernel
        seq_axis, n_seq = seq
        kern = resolve_ring_kernel(
            "flash_interpret" if attn_impl == "ring_interpret" else "auto")
        if kern == "lax":
            from ..ops.attention import ring_attention
            o = ring_attention(q, k, v, seq_axis)
        else:
            from ..ops.pallas.flash_attention import ring_flash_attention
            o = ring_flash_attention(q, k, v, seq_axis, n_seq, False,
                                     kern == "flash_interpret")
    else:
        raise ValueError(
            f"pipelined blocks support dense/flash/ring attention, "
            f"got {attn_impl!r}")
    o = jnp.einsum("bthk,hkd->btd", o, p["proj_kernel"].astype(dtype))
    if tp_axis is not None:
        o = lax.psum(o, tp_axis)
    x = x + o
    h = _layer_norm(x, p["ln2_scale"], p["ln2_bias"])
    if "moe_w1" in p:
        top_k, cap_factor, ep_axis = moe or (1, 1.25, None)
        h, aux = _moe_mlp(p, h, dtype, top_k, cap_factor, ep_axis, tp_axis)
        return x + h, aux
    h = jnp.einsum("btd,df->btf", h, p["mlp_w1"].astype(dtype)) \
        + p["mlp_b1"].astype(dtype)
    h = nn.gelu(h)
    h = jnp.einsum("btf,fd->btd", h, p["mlp_w2"].astype(dtype))
    if tp_axis is not None:
        h = lax.psum(h, tp_axis)
    h = h + p["mlp_b2"].astype(dtype)
    return x + h, jnp.float32(0.0)


def _moe_mlp(p, h, dtype, top_k=1, capacity_factor=1.25, ep_axis=None,
             tp_axis=None):
    """Switch MoE MLP from stacked-slice params, expert-sharded over
    ``ep_axis`` inside the pipeline shard_map (pp×ep, round 4).

    Tokens arrive REPLICATED across the expert axis (the pipeline body's
    x spec mentions batch axes only), so each device can gather its LOCAL
    experts' token slots directly — the O(N + E_loc·C) slot-table dispatch
    of models/moe.py, offset into the device's expert range — compute its
    expert block, and contribute a partial combine; ONE ``lax.psum`` over
    the expert axis completes the output. No one-hot tensors, no token
    all-to-all (the replication the pipeline already maintains makes the
    exchange free). Routing runs identically on every expert-peer
    (replicated router params) so drop decisions are globally consistent;
    the capacity group is the (data-shard, microbatch) token block.
    Returns (out, aux) with the Switch load-balancing loss.

    Routing/dispatch/combine/FFN math is the SHARED models/moe.py
    machinery (_route_assign, gather_slot_table, combine_from_slots,
    expert_ffn, switch_aux_loss) — the only pipeline-specific parts are
    the per-device expert offset and the completing psum.

    ``tp_axis`` (pp×ep×tp, round 5): each local expert's FFN is
    additionally Megatron-split over the tensor axis — the caller's
    stacked params arrive column-/row-sharded (stacked_encoder_spec) and
    expert_ffn's internal psum completes the down-projection before the
    expert-axis combine psum."""
    import math
    from .moe import (_route_assign, combine_from_slots, expert_ffn,
                      gather_slot_table, switch_aux_loss)
    b, t, d = h.shape
    n = b * t
    e_glob = p["router_kernel"].shape[-1]
    e_loc = p["moe_w1"].shape[0]
    my = lax.axis_index(ep_axis) if ep_axis is not None else 0
    flat = h.reshape(n, d)
    logits = flat.astype(jnp.float32) @ p["router_kernel"].astype(jnp.float32) \
        + p["router_bias"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    cap = max(1, math.ceil(top_k * (n / e_glob) * capacity_factor))
    assigned = _route_assign(probs, e_glob, cap, top_k)

    sel = gather_slot_table(assigned, n, cap, e_loc, e_lo=my * e_loc)
    padded = jnp.concatenate(
        [flat.astype(dtype), jnp.zeros((1, d), dtype)], axis=0)
    ein = jnp.take(padded, sel, axis=0).reshape(e_loc, cap, d)
    eout = expert_ffn(ein, p["moe_w1"], p["moe_bias1"], p["moe_w2"],
                      p["moe_bias2"], dtype,
                      tp_axis=tp_axis).reshape(e_loc * cap, d)
    out = combine_from_slots(assigned, eout, n, cap, dtype, e_loc,
                             e_lo=my * e_loc)
    if ep_axis is not None:
        out = lax.psum(out, ep_axis)
    return out.reshape(b, t, d), switch_aux_loss(probs)


class PipelinedEncoder(nn.Module):
    """Stacked-parameter transformer encoder, pipelined when
    ``mesh.shape['pipeline'] > 1`` (plain scan over layers otherwise)."""

    depth: int
    num_heads: int
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    mesh: Any = None
    microbatches: int = 0  # 0 → 2 × pipeline stages
    remat: bool = False    # jax.checkpoint each block (GPipe's usual pairing)
    interleave: int = 1    # v>1 → circular schedule, v chunks per stage
    attention_impl: str = "dense"  # dense | flash | flash_interpret
    num_experts: int = 0           # >0 → Switch MoE MLPs (pp×ep)
    expert_capacity_factor: float = 1.25
    moe_top_k: int = 1

    def _local_param_shape(self, name, full_shape):
        """Declared shape of one stacked leaf: the FULL stacked shape
        normally; inside the layout-aware exchange body (the enclosing
        shard_map maps ``pipeline``/``expert`` manually —
        parallel/overlap.py) each peer holds only its own slice, so the
        declaration shrinks by the manual axis sizes along the leaf's
        ``stacked_encoder_spec`` dims — flax's param shape check then
        matches the local shards the body actually receives."""
        from ..parallel.mesh import current_manual_axes
        from ..parallel.sharding import stacked_encoder_spec
        manual = current_manual_axes()
        if not manual or self.mesh is None:
            return full_shape
        spec = stacked_encoder_spec(name, len(full_shape),
                                    self.mesh.shape.get("tensor", 1))
        out = list(full_shape)
        for dim, names in enumerate(spec):
            if names is None:
                continue
            tup = names if isinstance(names, tuple) else (names,)
            div = 1
            for n in tup:
                if n in manual:
                    div *= self.mesh.shape.get(n, 1)
            if div > 1:
                out[dim] //= div
        return tuple(out)

    def _params(self, d):
        hd = d // self.num_heads
        f = self.mlp_ratio * d
        vs = jax.nn.initializers.variance_scaling
        def stacked(name, shape, init):
            return self.param(name, init,
                              self._local_param_shape(
                                  name, (self.depth,) + shape),
                              jnp.float32)
        ones = lambda key, shape, dtype: jnp.ones(shape, dtype)   # noqa: E731
        zeros = nn.initializers.zeros
        p = {
            "ln1_scale": stacked("ln1_scale", (d,), ones),
            "ln1_bias": stacked("ln1_bias", (d,), zeros),
            "qkv_kernel": stacked(
                "qkv_kernel", (d, 3, self.num_heads, hd),
                vs(1.0, "fan_in", "truncated_normal", in_axis=1,
                   out_axis=(2, 3, 4), batch_axis=0)),
            "proj_kernel": stacked(
                "proj_kernel", (self.num_heads, hd, d),
                vs(1.0, "fan_in", "truncated_normal", in_axis=(1, 2),
                   out_axis=3, batch_axis=0)),
            "ln2_scale": stacked("ln2_scale", (d,), ones),
            "ln2_bias": stacked("ln2_bias", (d,), zeros),
        }
        if self.num_experts > 0:
            e = self.num_experts
            # SwitchMlp's stacked-expert layout with a leading depth axis;
            # "bias"-named like models/moe.py so optimizer masks skip them
            p.update({
                "router_kernel": stacked(
                    "router_kernel", (d, e),
                    vs(1.0, "fan_in", "truncated_normal",
                       in_axis=1, out_axis=2, batch_axis=0)),
                "router_bias": stacked("router_bias", (e,), zeros),
                "moe_w1": stacked(
                    "moe_w1", (e, d, f),
                    vs(1.0, "fan_in", "truncated_normal", in_axis=2,
                       out_axis=3, batch_axis=(0, 1))),
                "moe_bias1": stacked("moe_bias1", (e, f), zeros),
                "moe_w2": stacked(
                    "moe_w2", (e, f, d),
                    vs(1.0, "fan_in", "truncated_normal", in_axis=2,
                       out_axis=3, batch_axis=(0, 1))),
                "moe_bias2": stacked("moe_bias2", (e, d), zeros),
            })
        else:
            p.update({
                "mlp_w1": stacked(
                    "mlp_w1", (d, f),
                    vs(1.0, "fan_in", "truncated_normal", in_axis=1,
                       out_axis=2, batch_axis=0)),
                "mlp_b1": stacked("mlp_b1", (f,), zeros),
                "mlp_w2": stacked(
                    "mlp_w2", (f, d),
                    vs(1.0, "fan_in", "truncated_normal", in_axis=1,
                       out_axis=2, batch_axis=0)),
                "mlp_b2": stacked("mlp_b2", (d,), zeros),
            })
        return p

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, t, d = x.shape
        params = self._params(d)
        nblocks = self.depth
        pstages = self.mesh.shape.get("pipeline", 1) \
            if self.mesh is not None else 1

        tp = self.mesh.shape.get("tensor", 1) if self.mesh is not None else 1
        tp_axis = "tensor" if (tp > 1 and pstages > 1) else None
        sp = self.mesh.shape.get("seq", 1) if self.mesh is not None else 1
        ring = self.attention_impl in ("ring", "ring_interpret")
        if sp > 1 and not ring:
            raise ValueError(
                "pipeline x seq runs ring attention inside the stage "
                "blocks; set attention_impl='ring' "
                f"(got {self.attention_impl!r})")
        if ring and sp <= 1:
            raise ValueError(
                "attention_impl='ring' in the pipelined encoder requires "
                "mesh.seq > 1")
        if ring and t % sp:
            raise ValueError(f"{t} tokens not divisible by seq axis {sp}")
        seq_static = ("seq", sp) if ring else None

        block_fn = _block_apply
        if self.remat:
            block_fn = jax.checkpoint(
                _block_apply, static_argnums=(2, 3, 4, 5, 6, 7))
        moe_static = None
        if self.num_experts > 0:
            ep = self.mesh.shape.get("expert", 1) \
                if self.mesh is not None else 1
            moe_static = (self.moe_top_k, self.expert_capacity_factor,
                          "expert" if ep > 1 else None)
            if self.num_experts % max(1, ep):
                raise ValueError(
                    f"num_experts {self.num_experts} not divisible by "
                    f"expert axis {ep}")

        def run_layers(p, h, tp_ax=None, mapped=True):
            """(h, aux_sum) over this param stack's layers. ``mapped=False``
            is for callers OUTSIDE the shard_map (sequential path, init
            fallback): the expert/seq axis names are only bound inside the
            mapped body, so the moe triple drops its axis and ring
            attention falls back to dense — mathematically identical over
            the then-unsharded token dim, and parameter-free either way."""
            mo = moe_static if mapped else moe_unmapped()
            ai, sq = self.attention_impl, seq_static
            if not mapped and ring:
                ai, sq = "dense", None
            def step(hh, pp):
                hh, aux = block_fn(pp, hh, self.num_heads, self.dtype,
                                   tp_ax, ai, mo, sq)
                return hh, aux
            h, auxs = lax.scan(step, h, p)
            return h, jnp.sum(auxs)

        def moe_unmapped():
            return (moe_static[0], moe_static[1], None) \
                if moe_static is not None else None

        v = max(1, self.interleave)
        if pstages > 1 and nblocks % (pstages * v):
            raise ValueError(
                f"depth {nblocks} not divisible by pipeline stages "
                f"{pstages} x interleave {v}")
        if tp_axis is not None:
            if self.num_heads % tp:
                raise ValueError(
                    f"heads {self.num_heads} not divisible by tensor axis {tp}")
            if (self.mlp_ratio * d) % tp:
                raise ValueError(
                    f"mlp hidden {self.mlp_ratio * d} not divisible by "
                    f"tensor axis {tp}")
        m = resolve_microbatches(self.microbatches, pstages)
        if v > 1 and pstages > 1 and m < pstages:
            # the circular wrap takes M-P+1 ticks; M >= P keeps the stage-0
            # re-injection queue causally ahead of its consumption
            raise ValueError(
                f"interleave {v} requires microbatches ({m}) >= pipeline "
                f"stages ({pstages})")
        # microbatching applies to the LOCAL batch: each data-parallel shard
        # runs its own pipeline over its slice of the batch. Inside the
        # layout-aware exchange body (parallel/overlap.py maps the batch
        # axes manually) ``x`` already IS the per-shard slice — dividing
        # again would halve every microbatch.
        from ..parallel.mesh import current_manual_axes
        inline = "pipeline" in current_manual_axes() and pstages > 1
        if self.mesh is not None and not inline:
            from ..parallel.mesh import batch_shard_count
            n_batch_shards = batch_shard_count(self.mesh)
        else:
            n_batch_shards = 1
        local_b = b // max(1, n_batch_shards)

        def finish(y, aux):
            if self.num_experts > 0 and not self.is_initializing():
                self.sow("losses", "moe_aux", aux)
            return y

        if pstages <= 1:
            # sequential path (mesh-less, or pipeline axis collapsed):
            # plain layer scan. The product only reaches PipelinedEncoder
            # with pipeline > 1 (VisionTransformer routes unpipelined MoE
            # through SwitchMlp), so no expert axis handling lives here.
            y, aux = run_layers(params, x, mapped=False)
            return finish(y, aux)
        if local_b < m or local_b % m:
            # the shape-only init dummy may be too small to microbatch —
            # parameters are created identically on both paths, so it runs
            # sequentially; a REAL batch in this state must fail loudly
            # (a silent sequential fallback would idle P-1 stages)
            if self.is_initializing():
                return run_layers(params, x, mapped=False)[0]
            raise ValueError(
                f"local batch {local_b} (global {b} over {n_batch_shards} "
                f"batch shards) must be a multiple of microbatches {m}")

        mesh = self.mesh
        from .transformer import _batch_axes
        x_spec = P(_batch_axes(mesh) or None, "seq" if ring else None, None)
        # per-leaf specs MATCH param_sharding_rule's placement (pipeline on
        # the stacked depth axis, tensor on heads/hidden when tp is active)
        # so the shard_map consumes the training state's own shards with no
        # per-step resharding
        from ..parallel.sharding import stacked_encoder_spec
        p_spec = {name: stacked_encoder_spec(name, leaf.ndim, tp)
                  for name, leaf in params.items()}
        perm = [(i, (i + 1) % pstages) for i in range(pstages)]

        def _aux_reduce(aux_acc):
            """Stage-local aux sums → one replicated (1,)-vector: sum stages,
            mean over microbatches (matching the unpipelined batch-level
            scale) and over the batch (and token, under seq sharding)
            shards. Shape (1,) rather than scalar end-to-end: a rank-0
            value at the shard_map boundary becomes a rank-0 residual
            under AD, and jax 0.4.37's shard_map transpose assigns
            residual cotangents axis names on dim 0 — a _SpecError for
            scalars (the pp×ep MoE failure this comment documents; see
            analysis/elaborate.py which now catches the class)."""
            aux = lax.psum(aux_acc, "pipeline") / m
            for ax in (_batch_axes(mesh) or ()):
                aux = lax.pmean(aux, ax)
            if ring:
                aux = lax.pmean(aux, "seq")
            return aux

        def pipelined(p_local, xg):
            stage = lax.axis_index("pipeline")
            mb = xg.shape[0] // m
            xs = xg.reshape((m, mb) + xg.shape[1:])

            def tick(carry, tt):
                prev, out, aux_acc = carry
                recv = lax.ppermute(prev, "pipeline", perm)
                inject = lax.dynamic_index_in_dim(
                    xs, jnp.clip(tt, 0, m - 1), axis=0, keepdims=False)
                h = jnp.where(stage == 0, inject, recv)
                y, aux = run_layers(p_local, h, tp_axis)
                u = tt - stage  # bubble ticks route zero activations:
                valid = jnp.logical_and(u >= 0, u < m)  # mask their aux
                aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
                idx = tt - (pstages - 1)
                upd = lax.dynamic_update_index_in_dim(
                    out, y.astype(out.dtype), jnp.clip(idx, 0, m - 1), axis=0)
                write = jnp.logical_and(stage == pstages - 1,
                                        jnp.logical_and(idx >= 0, idx < m))
                out = jnp.where(write, upd, out)
                return (y, out, aux_acc), None

            zero = jnp.zeros((mb,) + xg.shape[1:], xg.dtype)
            out0 = jnp.zeros_like(xs)
            (last, out, aux_acc), _ = lax.scan(
                tick, (zero, out0, jnp.zeros((1,), jnp.float32)),
                jnp.arange(m + pstages - 1))
            # outputs live on the last stage only; masked psum broadcasts
            out = lax.psum(
                jnp.where(stage == pstages - 1, out, jnp.zeros_like(out)),
                "pipeline")
            return out.reshape(xg.shape), _aux_reduce(aux_acc)

        def pipelined_circular(p_local, xg):
            """Circular schedule: v chunks of k layers per stage, vM+P-1
            ticks; stage s at tick t works item u = t - s as
            (chunk, microbatch) = (u // M, u mod M). Stage P-1's output for
            chunk c < v-1 rides the same ppermute ring back to stage 0,
            which parks it in a per-microbatch queue until its chunk-(c+1)
            slot comes up M-P+1 ticks later."""
            k = nblocks // (pstages * v)
            stage = lax.axis_index("pipeline")
            mb = xg.shape[0] // m
            xs = xg.reshape((m, mb) + xg.shape[1:])

            def chunk_params(p, c):
                return jax.tree_util.tree_map(
                    lambda a: lax.dynamic_slice_in_dim(a, c * k, k, axis=0),
                    p)

            def tick(carry, tt):
                prev, wrapq, out, aux_acc = carry
                recv = lax.ppermute(prev, "pipeline", perm)
                u = tt - stage
                mi = jnp.mod(u, m)
                ci = jnp.floor_divide(u, m)
                # stage 0: park the wrapped activation that stage P-1
                # produced at tick tt-1 (its work item was u' = tt - P)
                up = tt - pstages
                store = jnp.logical_and(
                    stage == 0,
                    jnp.logical_and(up >= 0,
                                    jnp.floor_divide(up, m) < v - 1))
                wrapq = jnp.where(
                    store,
                    lax.dynamic_update_index_in_dim(
                        wrapq, recv.astype(wrapq.dtype), jnp.mod(up, m),
                        axis=0),
                    wrapq)
                mi_c = jnp.clip(mi, 0, m - 1)
                inject = lax.dynamic_index_in_dim(xs, mi_c, axis=0,
                                                  keepdims=False)
                parked = lax.dynamic_index_in_dim(wrapq, mi_c, axis=0,
                                                  keepdims=False)
                h = jnp.where(stage == 0,
                              jnp.where(ci == 0, inject, parked), recv)
                y, aux = run_layers(
                    chunk_params(p_local, jnp.clip(ci, 0, v - 1)),
                    h, tp_axis)
                valid = jnp.logical_and(u >= 0, u < v * m)
                aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
                write = jnp.logical_and(stage == pstages - 1,
                                        jnp.logical_and(ci == v - 1, u >= 0))
                upd = lax.dynamic_update_index_in_dim(
                    out, y.astype(out.dtype), mi_c, axis=0)
                out = jnp.where(write, upd, out)
                return (y, wrapq, out, aux_acc), None

            zero = jnp.zeros((mb,) + xg.shape[1:], xg.dtype)
            (last, _wq, out, aux_acc), _ = lax.scan(
                tick,
                (zero, jnp.zeros_like(xs), jnp.zeros_like(xs),
                 jnp.zeros((1,), jnp.float32)),
                jnp.arange(v * m + pstages - 1))
            out = lax.psum(
                jnp.where(stage == pstages - 1, out, jnp.zeros_like(out)),
                "pipeline")
            return out.reshape(xg.shape), _aux_reduce(aux_acc)

        from ..parallel.mesh import shard_map_compat
        body = pipelined if v == 1 else pipelined_circular
        if inline:
            # the enclosing exchange shard_map (parallel/overlap.py)
            # already maps pipeline/expert (and the batch axes) manually:
            # params arrived as this peer's stage shards
            # (_local_param_shape), x as its batch slice, and every axis
            # name the body psums/ppermutes over is bound — run the
            # schedule directly. Building the inner shard_map here would
            # re-map consumed axes (and jax 0.4.37 mis-transposes nested
            # shard_map over auto axes — the exchange docstring has the
            # measured failure).
            y, aux = body(params, x)
        else:
            fn = shard_map_compat(body, mesh, in_specs=(p_spec, x_spec),
                                  out_specs=(x_spec, P(None)))
            y, aux = fn(params, x)
        return finish(y, aux[0])


def circular_layer_order(depth: int, pstages: int, interleave: int):
    """stored-row -> network-layer index map for the stacked layout.

    GPipe (interleave=1) stacks layers in network order; the circular
    schedule stores stage-major order (stage s's rows are its v chunks
    back-to-back, keeping the ``pipeline`` sharding of axis 0 contiguous):
    stored[s*(v*k) + c*k + i] = network[(c*pstages + s)*k + i].
    """
    import numpy as np
    v = max(1, interleave)
    if depth % (pstages * v):
        raise ValueError(f"depth {depth} not divisible by {pstages}x{v}")
    k = depth // (pstages * v)
    net = np.arange(depth).reshape(v, pstages, k)
    return net.transpose(1, 0, 2).reshape(depth)


def repack_stacked_params(stacked, depth: int, src=(1, 1), dst=(1, 1)):
    """Re-permute every depth-stacked leaf of an encoder param tree between
    storage layouts — checkpoint migration when (mesh.pipeline, interleave)
    changes between save and restore (the checkpoint manager refuses such
    restores; this is the deliberate-migration path). ``src``/``dst`` are
    (pstages, interleave) pairs; (P, 1) and (1, v) are both network order."""
    import numpy as np
    src_order = circular_layer_order(depth, *src)
    dst_order = circular_layer_order(depth, *dst)
    idx = jnp.asarray(np.argsort(src_order)[dst_order])
    return jax.tree_util.tree_map(lambda a: jnp.take(a, idx, axis=0), stacked)


def pack_encoder_params(vit_params: dict, depth: int, pstages: int = 1,
                        interleave: int = 1) -> dict:
    """Stack a standard per-block ViT param tree (EncoderBlock_i modules)
    into the PipelinedEncoder layout — checkpoint migration between the
    unpipelined and pipelined parameterizations. ``pstages``/``interleave``
    select the circular stacking order (no-ops at their defaults).
    Handles both MLP kinds: dense (Dense_0/Dense_1) and Switch MoE
    (SwitchMlp_0 → router/moe leaves)."""
    order = circular_layer_order(depth, max(1, pstages), interleave)

    def block(i):
        return vit_params[f"EncoderBlock_{i}"]

    def stack(fn):
        return jnp.stack([jnp.asarray(fn(block(int(i)))) for i in order])

    out = {
        "ln1_scale": stack(lambda b: b["LayerNorm_0"]["scale"]),
        "ln1_bias": stack(lambda b: b["LayerNorm_0"]["bias"]),
        "qkv_kernel": stack(
            lambda b: b["MultiHeadAttention_0"]["qkv"]["kernel"]),
        "proj_kernel": stack(
            lambda b: b["MultiHeadAttention_0"]["proj"]["kernel"]),
        "ln2_scale": stack(lambda b: b["LayerNorm_1"]["scale"]),
        "ln2_bias": stack(lambda b: b["LayerNorm_1"]["bias"]),
    }
    if "SwitchMlp_0" in block(0):
        out.update({
            "router_kernel": stack(
                lambda b: b["SwitchMlp_0"]["router"]["kernel"]),
            "router_bias": stack(
                lambda b: b["SwitchMlp_0"]["router"]["bias"]),
            "moe_w1": stack(lambda b: b["SwitchMlp_0"]["w1"]),
            "moe_bias1": stack(lambda b: b["SwitchMlp_0"]["bias1"]),
            "moe_w2": stack(lambda b: b["SwitchMlp_0"]["w2"]),
            "moe_bias2": stack(lambda b: b["SwitchMlp_0"]["bias2"]),
        })
    else:
        out.update({
            "mlp_w1": stack(lambda b: b["Dense_0"]["kernel"]),
            "mlp_b1": stack(lambda b: b["Dense_0"]["bias"]),
            "mlp_w2": stack(lambda b: b["Dense_1"]["kernel"]),
            "mlp_b2": stack(lambda b: b["Dense_1"]["bias"]),
        })
    return out
