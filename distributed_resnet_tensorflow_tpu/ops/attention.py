"""Attention ops: dense reference, blockwise (flash-style) computation, and
ring attention for sequence/context parallelism.

The reference is a vision-only trainer with NO attention or sequence axis
(SURVEY.md §5 'long-context': absent) — but its successor must treat long
context as first-class. This module provides the sequence-parallel substrate:

  * ``attention``          — dense softmax attention (numerical reference).
  * ``blockwise_attention``— online-softmax accumulation over key/value
    blocks (flash-attention recurrence) in pure lax; O(T) memory in the
    sequence dimension instead of O(T²).
  * ``ring_attention``     — the same recurrence where the key/value blocks
    live on DIFFERENT devices along a ``seq`` mesh axis and rotate around the
    ICI ring via ``lax.ppermute``; each device computes attention for its
    query chunk against every kv chunk while only ever holding 1/N of the
    sequence. Use under ``shard_map`` over a mesh with a ``seq`` axis (helper:
    ``ring_attention_sharded``). Supports causal masking via global block
    offsets.

Design notes (jax-ml.github.io/scaling-book model): the ring pattern
overlaps compute of block i with the ppermute of block i+1 — XLA schedules
the collective-permute asynchronously; the loop is a ``lax.fori_loop`` so the
whole ring is one compiled program.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def attention(q: jax.Array, k: jax.Array, v: jax.Array,
              causal: bool = False) -> jax.Array:
    """Dense reference attention. Shapes: (B, T, H, D) — batch, time, heads,
    head_dim. fp32 softmax regardless of input dtype."""
    b, tq, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        tk = k.shape[1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def _online_block(q, k, v, m, l, acc, scale, mask=None):
    """One flash-attention accumulation step.

    q: (B,Tq,H,D); k,v: (B,Tk,H,D); m,l: (B,H,Tq); acc: (B,Tq,H,D);
    mask: (Tq,Tk) bool or None.

    Matmuls run in the INPUT dtype with fp32 accumulation
    (``preferred_element_type``): bf16 inputs ride the MXU at full rate
    (the r3 inner block upcast V to fp32, turning the PV matmul into a
    multi-pass fp32 MXU op — the main reason the ring underperformed the
    Pallas kernel, docs/ring_attention_r4.md); fp32 inputs (CPU tests)
    keep exact-parity semantics. Softmax statistics stay fp32 always.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard fully-masked rows: exp(-inf - (-inf)) → use finite m
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - m_safe[..., None])
    if mask is not None:
        p = jnp.where(mask[None, None], p, 0.0)
    corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
    l_new = l * corr + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    acc_new = acc * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, acc_new


def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        block_size: int = 512,
                        causal: bool = False) -> jax.Array:
    """Single-device flash-style attention via lax.fori_loop over kv blocks."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if tk % block_size != 0:
        block_size = math.gcd(tk, block_size) or tk
    nblocks = tk // block_size
    scale = 1.0 / math.sqrt(d)

    m0 = jnp.full((b, h, tq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, tq), jnp.float32)
    acc0 = jnp.zeros((b, tq, h, d), jnp.float32)
    # dense-reference convention: queries are the LAST tq positions of the
    # key timeline (tril offset tk - tq), so suffix-query decode works
    q_pos = jnp.arange(tq) + (tk - tq)

    def body(i, carry):
        m, l, acc = carry
        kb = lax.dynamic_slice_in_dim(k, i * block_size, block_size, axis=1)
        vb = lax.dynamic_slice_in_dim(v, i * block_size, block_size, axis=1)
        mask = None
        if causal:
            k_pos = i * block_size + jnp.arange(block_size)
            mask = q_pos[:, None] >= k_pos[None, :]
        return _online_block(q, kb, vb, m, l, acc, scale, mask)

    m, l, acc = lax.fori_loop(0, nblocks, body, (m0, l0, acc0))
    l = jnp.where(l == 0.0, 1.0, l)
    return (acc / l.transpose(0, 2, 1)[..., None]).astype(v.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str, causal: bool = False) -> jax.Array:
    """Sequence-parallel attention over a named mesh axis (call under
    shard_map with q/k/v sharded on the time dimension).

    Local shapes: (B, T_local, H, D). Device j initially holds kv chunk j;
    at ring step i it processes kv chunk (j - i) mod N and forwards its
    current chunk to device (j + 1) mod N.
    """
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    scale = 1.0 / math.sqrt(d)

    m0 = jnp.full((b, h, t_local), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, t_local), jnp.float32)
    acc0 = jnp.zeros((b, t_local, h, d), jnp.float32)
    q_pos = my * t_local + jnp.arange(t_local)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def accumulate(i, m, l, acc, k_cur, v_cur):
        src = (my - i) % n  # global chunk index of the kv we currently hold
        k_pos = src * t_local + jnp.arange(t_local)
        mask = q_pos[:, None] >= k_pos[None, :] if causal else None
        return _online_block(q, k_cur, v_cur, m, l, acc, scale, mask)

    def body(i, carry):
        m, l, acc, k_cur, v_cur = carry
        m, l, acc = accumulate(i, m, l, acc, k_cur, v_cur)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return m, l, acc, k_nxt, v_nxt

    # ring for n-1 steps, then the final chunk without a wasted ppermute
    m, l, acc, k_last, v_last = lax.fori_loop(
        0, n - 1, body, (m0, l0, acc0, k, v))
    m, l, acc = accumulate(n - 1, m, l, acc, k_last, v_last)
    l = jnp.where(l == 0.0, 1.0, l)
    return (acc / l.transpose(0, 2, 1)[..., None]).astype(v.dtype)


def resolve_ring_kernel(kernel: str) -> str:
    """The ONE auto rule for the ring inner block: the fused Pallas flash
    kernels on TPU (measured 1.5×-3.6× the lax ring at 8k-32k tokens,
    docs/ring_attention_r4.json), the pure-lax online recurrence elsewhere.
    Shared by ring_attention_sharded and the pipelined stage blocks
    (models/pipeline.py) so the two paths cannot drift."""
    if kernel not in ("auto", "lax", "flash", "flash_interpret"):
        raise ValueError(f"unknown ring attention kernel {kernel!r}")
    if kernel == "auto":
        return "flash" if jax.default_backend() == "tpu" else "lax"
    return kernel


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                           mesh: Mesh, causal: bool = False,
                           seq_axis: str = "seq",
                           batch_axes: tuple = (),
                           kernel: str = "auto") -> jax.Array:
    """Convenience wrapper: shard_map ring attention over ``mesh[seq_axis]``
    with time-dim sharding (B, T/seq, H, D per device).

    ``batch_axes`` names mesh axes the batch dim is already split over (e.g.
    ("data",)) so composition with data parallelism keeps the batch sharded
    instead of all-gathering it at the shard_map boundary.

    ``kernel`` picks the per-step inner block: "lax" = the pure-lax online
    recurrence (any backend); "flash" = the fused Pallas kernel
    (ops/pallas/flash_attention.ring_flash_attention — measured 1.5×-3.6×
    faster at 8k-32k tokens, docs/ring_attention_r4.json);
    "flash_interpret" = the same kernels in the Pallas interpreter (CPU
    parity tests); "auto" = flash on TPU, lax elsewhere."""
    from ..parallel.mesh import shard_map_compat

    n = mesh.shape[seq_axis]
    mode = resolve_ring_kernel(kernel)

    spec = P(batch_axes or None, seq_axis, None, None)
    if mode == "lax":
        body = functools.partial(ring_attention, axis_name=seq_axis,
                                 causal=causal)
    else:
        from .pallas.flash_attention import ring_flash_attention
        interp = mode == "flash_interpret"

        def body(q, k, v):
            return ring_flash_attention(q, k, v, seq_axis, n, causal,
                                        interp)
    fn = shard_map_compat(
        body, mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
