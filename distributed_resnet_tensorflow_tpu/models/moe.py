"""Mixture-of-Experts MLP (Switch top-1 / GShard-style top-2 routing) — the
consumer of the ``expert`` mesh axis.

The reference is a dense-only trainer (SURVEY.md §2.10); this completes the
6-axis mesh so every axis has a model consumer. Design (Switch Transformer
recipe, scoped to what the ViT family needs):

  * E expert MLPs with stacked parameters (E, D, F)/(E, F, D), sharded over
    the ``expert`` axis by parallel/sharding.py's rule — each device group
    holds E/expert_axis experts (and their optimizer moments).
  * Top-1 (Switch) or top-2 (GShard-style) routing with probability gating
    and a fixed per-expert capacity ``ceil(top_k · tokens/E ·
    capacity_factor)``; over-capacity tokens fall through on the residual
    path. Top-2 normalizes the two gates over the selected pair and gives
    first choices capacity priority over second choices (the GShard
    ordering: a token's backup never displaces another token's primary).
  * Two dispatch formulations, selected by ``dispatch``:
      - "einsum": one-hot (N, E, C) dispatch/combine einsums — GSPMD
        partitions them over the sharded expert dimension and inserts the
        token-exchange collectives (the sharding-first formulation; no
        hand-written all-to-all). Cost: the one-hot tensors are O(N·E·C)
        HBM — measured 2.46× a dense MLP step at 8k tokens × 8 experts
        (docs/moe_r3.json).
      - "gather": scatter the kept token ids into an (E·C,) slot table,
        gather expert inputs by slot, gather combines back per token —
        O(N + E·C) memory, no one-hot tensors at all.
    "auto" uses gather when the expert dim is NOT mesh-sharded and einsum
    when it is (scatters across a sharded dim would make GSPMD all-gather
    the slot table; the einsum form keeps the exchange a clean a2a). The
    two are exact-parity tested against each other.
  * The Switch load-balancing auxiliary loss (E · Σ_e fraction_e · prob_e)
    is sown into the ``losses`` collection; the train step adds every sown
    loss scaled by ``model.moe_aux_weight`` (train/loop.py).
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


class SwitchMlp(nn.Module):
    """Drop-in replacement for the EncoderBlock MLP: LN'd input in,
    residual-branch output out. Shapes: (B, T, D) → (B, T, D)."""

    num_experts: int
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    mesh: Any = None
    top_k: int = 1
    dispatch: str = "auto"  # auto | einsum | gather (module docstring)

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, t, d = x.shape
        e = self.num_experts
        f = self.mlp_ratio * d
        n_tokens = b * t
        if self.top_k not in (1, 2) or self.top_k > e:
            raise ValueError(
                f"moe top_k must be 1 or 2 and <= num_experts={e}, "
                f"got {self.top_k}")
        import math
        capacity = max(1, math.ceil(
            self.top_k * (n_tokens / e) * self.capacity_factor))

        vs = jax.nn.initializers.variance_scaling
        w1 = self.param("w1", vs(1.0, "fan_in", "truncated_normal",
                                 in_axis=1, out_axis=2, batch_axis=0),
                        (e, d, f), jnp.float32)
        # "bias" in the name keeps these out of weight decay / LARS trust
        # scaling (the optimizer masks exclude *bias* leaves by path, since
        # expert-stacked biases are 2-D and defeat the ndim heuristic)
        b1 = self.param("bias1", nn.initializers.zeros, (e, f), jnp.float32)
        w2 = self.param("w2", vs(1.0, "fan_in", "truncated_normal",
                                 in_axis=1, out_axis=2, batch_axis=0),
                        (e, f, d), jnp.float32)
        b2 = self.param("bias2", nn.initializers.zeros, (e, d), jnp.float32)

        # --- router (replicated, fp32 for a stable softmax) ---------------
        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            x.astype(jnp.float32))                       # (B, T, E)
        probs = jax.nn.softmax(logits, axis=-1)
        flat_probs = probs.reshape(n_tokens, e)
        expert_idx = jnp.argmax(flat_probs, axis=-1)     # (N,) first choice
        gate1 = jnp.max(flat_probs, axis=-1)             # (N,)
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)

        # Switch aux loss: E * Σ_e (fraction of tokens routed to e) · (mean
        # router prob of e) — pushes the router toward uniform utilization
        # (first-choice fractions in both routing modes, the Switch form)
        fraction = onehot.mean(axis=0)
        mean_prob = flat_probs.mean(axis=0)
        self.sow("losses", "moe_aux", e * jnp.sum(fraction * mean_prob))

        if self.top_k == 2:
            # second choice: argmax with the first masked out; gates
            # renormalized over the selected pair (GShard)
            masked = flat_probs - onehot * 2.0  # probs ∈ [0,1]: -2 loses
            expert_idx2 = jnp.argmax(masked, axis=-1)
            gate2 = jnp.take_along_axis(
                flat_probs, expert_idx2[:, None], axis=-1)[:, 0]
            denom = gate1 + gate2
            waves = [(expert_idx, gate1 / denom), (expert_idx2, gate2 / denom)]
        else:
            waves = [(expert_idx, gate1)]

        # --- capacity assignment ------------------------------------------
        # per-expert queue positions; wave 2 queues BEHIND wave 1 (first
        # choices have priority); >= capacity drops that assignment
        assigned = []                      # (idx, gate, pos, keep) per wave
        base_counts = jnp.zeros((e,), jnp.float32)
        for idx_k, gate_k in waves:
            oh = jax.nn.one_hot(idx_k, e, dtype=jnp.float32)     # (N, E)
            pos_in_expert = (jnp.cumsum(oh, axis=0) - 1.0) * oh  # (N, E)
            pos = (jnp.sum(pos_in_expert, axis=-1)
                   + oh @ base_counts).astype(jnp.int32)         # (N,)
            keep = pos < capacity
            assigned.append((idx_k, gate_k * keep.astype(jnp.float32),
                             pos, keep))
            base_counts = base_counts + oh.sum(axis=0)

        mode = self.dispatch
        if mode == "auto":
            sharded_e = (self.mesh is not None
                         and self.mesh.shape.get("expert", 1) > 1)
            mode = "einsum" if sharded_e else "gather"
        if mode not in ("einsum", "gather"):
            raise ValueError(f"unknown moe dispatch mode {mode!r}")

        flat_x = x.reshape(n_tokens, d)

        def expert_mlp(ein):
            """(E, C, D) expert inputs → (E, C, D) outputs."""
            h = jnp.einsum("ecd,edf->ecf", ein, w1.astype(self.dtype)) \
                + b1[:, None, :].astype(self.dtype)
            h = nn.gelu(h)
            return jnp.einsum("ecf,efd->ecd", h, w2.astype(self.dtype)) \
                + b2[:, None, :].astype(self.dtype)

        if mode == "gather":
            # slot table: kept token n occupies slot idx·C + pos. Dropped
            # assignments write out of bounds (mode="drop"); empty slots
            # keep the sentinel n_tokens, which gathers the appended zero
            # row. O(N + E·C) memory — no (N, E, C) tensors anywhere.
            nslots = e * capacity
            sel = jnp.full((nslots,), n_tokens, jnp.int32)
            for idx_k, _gate, pos_k, keep_k in assigned:
                slot = idx_k * capacity + pos_k
                slot = jnp.where(keep_k, slot, nslots)
                sel = sel.at[slot].set(jnp.arange(n_tokens, dtype=jnp.int32),
                                       mode="drop")
            padded = jnp.concatenate(
                [flat_x.astype(self.dtype),
                 jnp.zeros((1, d), self.dtype)], axis=0)
            ein = jnp.take(padded, sel, axis=0).reshape(e, capacity, d)
            eout = expert_mlp(ein).reshape(nslots, d)
            out = jnp.zeros((n_tokens, d), self.dtype)
            for idx_k, gate_k, pos_k, _keep in assigned:
                slot = jnp.clip(idx_k * capacity + pos_k, 0, nslots - 1)
                out = out + gate_k[:, None].astype(self.dtype) \
                    * jnp.take(eout, slot, axis=0)
            return out.reshape(b, t, d)

        # one-hot einsum dispatch (GSPMD shards the E dim over `expert`)
        dispatch = jnp.zeros((n_tokens, e, capacity), jnp.float32)
        combine = jnp.zeros((n_tokens, e, capacity), jnp.float32)
        for idx_k, gate_k, pos_k, keep_k in assigned:
            oh = jax.nn.one_hot(idx_k, e, dtype=jnp.float32)
            d_k = (oh[:, :, None]
                   * jax.nn.one_hot(pos_k, capacity,
                                    dtype=jnp.float32)[:, None, :]
                   * keep_k[:, None, None].astype(jnp.float32))
            dispatch = dispatch + d_k
            combine = combine + d_k * gate_k[:, None, None]

        ein = jnp.einsum("nec,nd->ecd", dispatch.astype(self.dtype),
                         flat_x.astype(self.dtype))
        ein = self._constrain_e(ein)
        eout = self._constrain_e(expert_mlp(ein))
        out = jnp.einsum("nec,ecd->nd", combine.astype(self.dtype), eout)
        return out.reshape(b, t, d)

    def _constrain_e(self, arr):
        """Pin the expert dim to the `expert` axis so expert compute stays
        where the weights live."""
        mesh = self.mesh
        if mesh is None or mesh.shape.get("expert", 1) <= 1:
            return arr
        from jax.sharding import NamedSharding
        return jax.lax.with_sharding_constraint(
            arr, NamedSharding(mesh, P("expert", None, None)))
