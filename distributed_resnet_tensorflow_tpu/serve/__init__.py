"""serve/ — AOT-compiled batched inference with hot checkpoint swap.

The serving path the ROADMAP north-star requires and the reference never
had (its pipeline ended at the checkpoint): ``main.py serve`` turns a
training run's committed checkpoints into live low-latency capacity.
docs/serving.md is the manual; tests/test_serve.py and
scripts/serve_smoke.sh exercise it on CPU.
"""
from .batcher import DynamicBatcher  # noqa: F401
from .compile_cache import (ServeCompileCache, bucket_sizes,  # noqa: F401
                            pick_bucket)
from .loadgen import run_open_loop, synthetic_requests  # noqa: F401
from .server import InferenceServer, serve_image_spec  # noqa: F401
from .swap import CheckpointSwapper, PendingSwap  # noqa: F401
