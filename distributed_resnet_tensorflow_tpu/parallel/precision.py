"""End-to-end mixed-precision policy: bf16 hot paths, f32 masters.

Compute-side MFU has been flat at ~0.35 on imagenet-rn50 since BENCH_r02
because every hot path still ran f32 end to end. This module is the ONE
resolution point for the three low-precision knobs (docs/precision.md):

  * ``train.precision`` — the TRAINING STEP policy. ``bf16`` computes
    activations/matmuls in bfloat16 while the parameters (and the whole
    optimizer state) stay float32 MASTERS: the model is built with a
    bf16 compute dtype (flax casts params leaf-by-leaf at each op — the
    policy cast that wraps model apply), gradients come out f32 (the
    cast's transpose re-accumulates into the f32 param cotangent), and
    the optimizer update runs entirely in f32. BN moments, softmax and
    the loss already accumulate in f32 by construction (ops/batch_norm
    computes moments in f32; train/loop.make_ce_fn casts logits to f32
    before the softmax). ``off`` (the default) leaves the legacy
    ``model.compute_dtype`` contract untouched — BIT-identical to the
    pre-policy step, the exactness oracle every cast path is tested
    against.
  * ``comm.compress`` — the GRADIENT-EXCHANGE payload dtype
    (parallel/overlap.py): each ``comm.bucket`` psum / reduce-scatter /
    ZeRO-1 all-gather payload is cast to bf16/fp16 on the wire and
    re-materialized f32 on arrival, halving inter-host bytes on the SAME
    bucket plan (arXiv:1811.05233 trained ImageNet/ResNet-50 to
    reference accuracy with half-precision allreduce). Resolved by
    ``parallel.overlap.compress_dtype``; it rides the bucketed exchange,
    so the Trainer warns loudly when compression is requested while
    ``comm.overlap`` resolves off.
  * ``serve.variants`` — reduced-precision SERVING variants
    (serve/compile_cache.py buckets become (batch, variant)): a ``bf16``
    variant serves from a bf16-cast weight copy through a bf16-compute
    predict step; an ``int8`` variant is WEIGHT-ONLY — kernels quantize
    to int8 with per-output-channel f32 scales (¼ the weight HBM) and
    dequantize into an f32 forward at apply time. Resolved by
    :func:`resolve_serve_variants`.

Checkpoints are policy-agnostic by construction: the masters are f32, so
save/restore and the serving hot swap never see a cast leaf —
:func:`check_master_dtypes` is the guard that keeps that true.

Why fp16 is exchange-only: an fp16 TRAINING step needs loss scaling to
keep small gradients out of the subnormal range (bf16 shares f32's
exponent and does not); until a scaler exists, ``train.precision=fp16``
is refused with that reason rather than silently diverging.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

#: dtypes a policy / compressed exchange / serving variant may name
POLICY_DTYPES = {"bf16": jnp.bfloat16, "fp16": jnp.float16}

#: serving-variant names → COMPUTE dtype (``f32`` is the policy-native
#: full-precision variant every server carries implicitly). ``int8`` is
#: WEIGHT-ONLY: kernels live in HBM as int8 with a per-channel f32 scale
#: (make_variant_cast) and dequantize into the f32 forward at apply time
#: — ¼ the weight bytes per replica, full-precision arithmetic.
SERVE_VARIANT_DTYPES = {"f32": jnp.float32, "bf16": jnp.bfloat16,
                        "int8": jnp.float32}

#: variants whose CAST changes the weight REPRESENTATION (not just the
#: dtype): their predict step must dequantize before model apply
#: (train/loop.Trainer.make_variant_predict_step)
WEIGHT_ONLY_VARIANTS = frozenset({"int8"})

#: per-channel symmetric int8 range (the scale denominator); -128 is
#: excluded so the quantizer stays symmetric around zero
INT8_QMAX = 127.0

#: params below this many dims stay f32 under the int8 variant: biases,
#: LayerNorm/BN scales are tiny (no memory win) and precision-critical
INT8_MIN_NDIM = 2


def quantize_leaf_int8(w):
    """One float leaf → ``{"int8_q", "int8_scale"}``: symmetric
    per-OUTPUT-CHANNEL (last dim) scales, values rounded into [-127,127].
    Works on live arrays and under ``jax.eval_shape`` (pure jnp)."""
    wf = jnp.asarray(w).astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=tuple(range(wf.ndim - 1)),
                   keepdims=False)
    scale = jnp.where(amax > 0, amax / INT8_QMAX, 1.0)
    q = jnp.clip(jnp.round(wf / scale), -INT8_QMAX, INT8_QMAX)
    return {"int8_q": q.astype(jnp.int8),
            "int8_scale": scale.astype(jnp.float32)}


def _is_quantized_leaf(x) -> bool:
    return isinstance(x, dict) and set(x) == {"int8_q", "int8_scale"}


def dequantize_params(params):
    """Inverse of the int8 cast: every ``{"int8_q", "int8_scale"}``
    marker dict becomes ``q * scale`` (f32); untouched leaves pass
    through. XLA fuses the dequant into the consuming matmul, so the
    weights stay int8 at rest and widen on the fly."""
    def deq(x):
        if _is_quantized_leaf(x):
            return x["int8_q"].astype(jnp.float32) * x["int8_scale"]
        return x

    return jax.tree_util.tree_map(deq, params,
                                  is_leaf=_is_quantized_leaf)


@dataclass(frozen=True)
class PrecisionPolicy:
    """Resolved ``train.precision`` for one Trainer: compute in
    ``compute_dtype``, keep ``master_dtype`` parameters/optimizer state."""

    name: str                       # "bf16"
    compute_dtype: Any              # jnp.bfloat16
    master_dtype: Any = jnp.float32

    @property
    def compute_dtype_name(self) -> str:
        return jnp.dtype(self.compute_dtype).name

    def cast_compute(self, x: jax.Array) -> jax.Array:
        """The policy input cast (wraps model apply): float arrays enter
        the model in the compute dtype; integer inputs (raw uint8 crops
        headed for the device augment) pass through untouched."""
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            return x.astype(self.compute_dtype)
        return x


def precision_unsupported_reason(cfg) -> Optional[str]:
    """None when ``train.precision`` can apply to this config; else a
    one-line reason (``resolve_precision`` raises it — a precision knob
    that silently trains a different program than requested is exactly
    the failure mode the resolver exists to prevent)."""
    mode = cfg.train.precision
    if mode in ("off", "bf16"):
        return None
    if mode == "fp16":
        return ("an fp16 TRAINING step needs loss scaling to keep small "
                "gradients out of the subnormal range (bf16 shares f32's "
                "exponent range and does not) — use train.precision=bf16; "
                "fp16 is available for the exchange payload "
                "(comm.compress=fp16)")
    return f"unknown train.precision setting {mode!r}"


def resolve_precision(cfg) -> Optional[PrecisionPolicy]:
    """``train.precision`` → a :class:`PrecisionPolicy` or None (off =
    the legacy ``model.compute_dtype`` contract, bit-identical)."""
    mode = cfg.train.precision
    if mode == "off":
        return None
    reason = precision_unsupported_reason(cfg)
    if reason is not None:
        raise ValueError(f"train.precision={mode!r} is unsupported: "
                         f"{reason}")
    return PrecisionPolicy(name=mode, compute_dtype=POLICY_DTYPES[mode])


def resolve_serve_variants(cfg) -> Tuple[str, ...]:
    """``serve.variants`` → validated, deduped variant tuple (order
    preserved; the FIRST entry is the default a variant-less request is
    served from). Unknown names raise with the supported set — a
    misspelled variant must never fall back to silently serving f32."""
    raw = cfg.serve.variants or ("f32",)
    if isinstance(raw, str):
        raw = (raw,)
    out = []
    for v in raw:
        if v not in SERVE_VARIANT_DTYPES:
            raise ValueError(
                f"unknown serve variant {v!r}; supported: "
                f"{sorted(SERVE_VARIANT_DTYPES)}")
        if v not in out:
            out.append(v)
    return tuple(out)


def make_variant_cast(variant: str):
    """``cast(state) -> state`` for one serving variant: float leaves of
    params/batch_stats narrowed to the variant dtype (step/int leaves and
    the optimizer state untouched — serving never reads moments). The
    f32 variant is the identity, so the default server pays nothing.
    Works on live device trees (eager per-leaf casts on the caller
    thread — serve/server.py builds variants at startup and at swap
    boundaries, both single-dispatch-thread safe) AND under
    ``jax.eval_shape`` (serve/compile_cache.py derives each variant's
    abstract state the same way, so the two cannot drift).

    ``int8`` (weight-only, docs/precision.md): every float param leaf
    with ≥ ``INT8_MIN_NDIM`` dims becomes a ``{"int8_q", "int8_scale"}``
    pair — symmetric per-output-channel quantization
    (:func:`quantize_leaf_int8`); biases/norm scales and the
    ``batch_stats`` running moments stay f32 (tiny, precision-critical).
    The matching predict step dequantizes at apply time
    (:func:`dequantize_params` via Trainer.make_variant_predict_step)."""
    if variant in WEIGHT_ONLY_VARIANTS:
        def quant_leaf(x):
            arr = jnp.asarray(x)
            if jnp.issubdtype(arr.dtype, jnp.floating) \
                    and arr.ndim >= INT8_MIN_NDIM:
                return quantize_leaf_int8(arr)
            return x

        def quant(state):
            return state.replace(
                params=jax.tree_util.tree_map(quant_leaf, state.params))

        return quant
    dt = SERVE_VARIANT_DTYPES[variant]
    if dt == jnp.float32:
        return lambda state: state

    def cast_leaf(x):
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            return jnp.asarray(x).astype(dt)
        return x

    def cast(state):
        return state.replace(
            params=jax.tree_util.tree_map(cast_leaf, state.params),
            batch_stats=jax.tree_util.tree_map(cast_leaf,
                                               state.batch_stats))

    return cast


def check_master_dtypes(params, master_dtype=jnp.float32) -> None:
    """Raise when any floating param leaf is not a ``master_dtype``
    master. The precision policy's whole checkpoint story — save/restore
    and serve hot-swap staying policy-agnostic — rests on the persisted
    tree being f32; a model that initialized a cast leaf (a param_dtype
    override drifting in) would silently bake the policy into every
    checkpoint it writes."""
    bad = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        dt = jnp.asarray(leaf).dtype if not hasattr(leaf, "dtype") \
            else leaf.dtype
        if jnp.issubdtype(dt, jnp.floating) and dt != jnp.dtype(master_dtype):
            bad.append(f"{jax.tree_util.keystr(path)}:{jnp.dtype(dt).name}")
    if bad:
        raise ValueError(
            f"precision policy requires {jnp.dtype(master_dtype).name} "
            f"master params but found {bad[:5]} — a non-master float leaf "
            "would bake the compute policy into every checkpoint")


class PrecisionStats:
    """Process-global record of the resolved precision/compression
    configuration — what the ``{"event": "precision"}`` metrics row
    (train/hooks.PrecisionHook) and bench.py's ``precision`` row export.
    Mirrors overlap_stats' contract: written at Trainer build /
    state-init time (a property of the run, not of any step)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._snap: Optional[Dict[str, Any]] = None

    def record_policy(self, policy: Optional[PrecisionPolicy],
                      compress: Optional[str]) -> None:
        with self._lock:
            base = self._snap or {}
            self._snap = {**base,
                          "policy": policy.name if policy else "off",
                          "compute_dtype": policy.compute_dtype_name
                          if policy else None,
                          "master_dtype": jnp.dtype(
                              policy.master_dtype).name if policy
                          else None,
                          "compress": compress or "off"}

    def record_params(self, params) -> None:
        """Master-tree accounting from the LIVE state: leaf count and f32
        master bytes (what checkpoints persist regardless of policy)."""
        leaves = jax.tree_util.tree_leaves(params)
        nbytes = sum(int(l.size) * jnp.dtype(l.dtype).itemsize
                     for l in leaves)
        with self._lock:
            base = self._snap or {}
            self._snap = {**base, "param_leaves": len(leaves),
                          "master_param_bytes": int(nbytes)}

    def reset(self) -> None:
        with self._lock:
            self._snap = None

    def snapshot(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self._snap) if self._snap is not None else None


#: process-global precision telemetry (one policy resolution per process)
precision_stats = PrecisionStats()
