#!/bin/bash
# Local smoke run — successor of the reference's 1ps+2wk localhost cluster
# (reference scripts/submit_mac_dist.sh + run_dist_tf_local.sh: CPU, bs=10,
# 100 steps). Two SPMD processes over a loopback coordinator, synthetic data.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m distributed_resnet_tensorflow_tpu.launch --num_processes 2 -- \
  --preset smoke \
  --set train.batch_size=10 \
  --set train.train_steps=100 \
  --set train.log_every_steps=20 \
  --set checkpoint.save_every_secs=0 \
  --set checkpoint.save_every_steps=0 \
  "$@"
