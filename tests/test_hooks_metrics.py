"""Hooks + metrics writer tests (reference observability, SURVEY.md §2.15)."""
import os

import numpy as np

from distributed_resnet_tensorflow_tpu.train.hooks import (
    CheckpointHook, LoggingHook, SummaryHook)
from distributed_resnet_tensorflow_tpu.utils.metrics import (
    MetricsWriter, Throughput, read_metrics)


def test_metrics_writer_jsonl_roundtrip(tmp_path):
    w = MetricsWriter(str(tmp_path), enable_tensorboard=False)
    w.write_scalars(10, {"loss": 1.5, "precision": 0.5})
    w.write_scalars(20, {"loss": 1.0, "precision": 0.7})
    w.close()
    recs = read_metrics(str(tmp_path))
    assert len(recs) == 2
    assert recs[0]["step"] == 10 and recs[0]["loss"] == 1.5
    assert recs[1]["precision"] == 0.7


def test_metrics_writer_tensorboard(tmp_path):
    w = MetricsWriter(str(tmp_path), enable_tensorboard=True)
    w.write_scalars(1, {"loss": 2.0})
    w.close()
    # tensorboardX event file written alongside the jsonl
    assert any(f.startswith("events") for f in os.listdir(tmp_path))


def test_logging_hook_cadence():
    lines = []
    h = LoggingHook(every_steps=10, batch_size=128, print_fn=lines.append)
    m = {"loss": np.float32(1.0), "precision": np.float32(0.5),
         "learning_rate": np.float32(0.1)}
    for step in range(1, 31):
        h(step, None, m)
    assert len(lines) == 3
    assert "step 10" in lines[0] and "loss 1.0000" in lines[0]
    # throughput appears once a window exists
    assert "stp/s" in lines[1]


def test_summary_hook_cadence(tmp_path):
    w = MetricsWriter(str(tmp_path), enable_tensorboard=False)
    h = SummaryHook(w, every_steps=5)
    for step in range(1, 11):
        h(step, None, {"loss": float(step)})
    w.close()
    recs = read_metrics(str(tmp_path))
    assert [r["step"] for r in recs] == [5, 10]


def test_throughput_meter():
    t = Throughput(batch_size=64)
    assert t.update(0) == {}
    import time
    time.sleep(0.01)
    out = t.update(10)
    assert out["steps_per_sec"] > 0
    assert np.isclose(out["images_per_sec"], out["steps_per_sec"] * 64)


def test_checkpoint_hook_delegates(tmp_path):
    calls = []

    class FakeMngr:
        def maybe_save(self, step, state):
            calls.append(step)

    h = CheckpointHook(FakeMngr())
    h(7, "state", {})
    assert calls == [7]
