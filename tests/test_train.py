"""Train loop tests — step semantics, convergence on learnable data,
gradient accumulation (covers the reference's train() drivers, SURVEY.md
§2.11-2.12, as pure functions)."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_resnet_tensorflow_tpu.data import learnable_synthetic_iterator
from distributed_resnet_tensorflow_tpu.train import Trainer, cross_entropy_loss
from distributed_resnet_tensorflow_tpu.utils.config import get_preset


def _tiny_cfg(**overrides):
    cfg = get_preset("smoke")
    cfg.model.compute_dtype = "float32"
    cfg.model.resnet_size = 8
    cfg.model.num_classes = 4
    cfg.data.image_size = 8
    cfg.train.batch_size = 16
    cfg.optimizer.schedule = "constant"
    cfg.optimizer.learning_rate = 0.05
    for k, v in overrides.items():
        cfg.override(k, v)
    return cfg


def test_cross_entropy_loss():
    logits = jnp.asarray([[10.0, 0.0], [0.0, 10.0]])
    labels = jnp.asarray([0, 1])
    assert float(cross_entropy_loss(logits, labels)) < 1e-3
    # label smoothing raises the floor
    smoothed = float(cross_entropy_loss(logits, labels, label_smoothing=0.1))
    assert smoothed > 0.1


def test_train_step_runs_and_metrics():
    cfg = _tiny_cfg()
    tr = Trainer(cfg)
    tr.init_state()
    it = learnable_synthetic_iterator(16, 8, 4)
    state, m = tr.train(it, num_steps=2)
    assert int(state.step) == 2
    for key in ("loss", "cross_entropy", "precision", "learning_rate",
                "grad_norm"):
        assert key in m
    assert np.isfinite(float(m["loss"]))


def test_loss_decreases_on_learnable_data():
    """Tiny convergence test — the e2e correctness oracle the reference only
    had via its continuous evaluator (SURVEY.md §4.3)."""
    cfg = _tiny_cfg()
    tr = Trainer(cfg)
    tr.init_state()
    it = learnable_synthetic_iterator(16, 8, 4, seed=3)
    losses = []
    step_fn = tr.jitted_train_step()
    from distributed_resnet_tensorflow_tpu.parallel.sharding import shard_batch
    for i in range(30):
        batch = shard_batch(next(it), tr.mesh)
        tr.state, m = step_fn(tr.state, batch)
        losses.append(float(m["cross_entropy"]))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.8, losses


def test_weight_decay_in_loss():
    """Reference adds L2 over trainable kernels to the loss
    (resnet_model.py:78-86): loss > cross_entropy when wd > 0."""
    cfg = _tiny_cfg()
    cfg.optimizer.weight_decay = 0.01
    tr = Trainer(cfg)
    tr.init_state()
    it = learnable_synthetic_iterator(16, 8, 4)
    state, m = tr.train(it, num_steps=1)
    assert float(m["loss"]) > float(m["cross_entropy"])


def test_loss_weight_decay_hand_computed():
    """Both decay modes against hand-computed 0.5*rate*Σ‖w‖² values."""
    from distributed_resnet_tensorflow_tpu.train.optimizers import (
        loss_weight_decay)
    params = {
        "Dense": {"kernel": jnp.asarray([[1.0, 2.0], [3.0, 4.0]]),  # Σsq=30
                  "bias": jnp.asarray([1.0, 1.0])},                  # Σsq=2
        "BatchNorm": {"scale": jnp.asarray([2.0]),                   # Σsq=4
                      "bias": jnp.asarray([3.0])},                   # Σsq=9
    }
    rate = 0.1
    # kernels-only (default): just the 2-D kernel
    assert np.isclose(float(loss_weight_decay(params, rate)), 0.5 * rate * 30)
    # reference-faithful: ALL trainables incl. BN scale/bias and biases
    # (reference resnet_model.py:85-86)
    assert np.isclose(float(loss_weight_decay(params, rate, all_params=True)),
                      0.5 * rate * (30 + 2 + 4 + 9))
    assert loss_weight_decay(params, 0.0) == 0.0


@pytest.mark.slow  # re-tiered out of the 870s tier-1; runs in the full (unfiltered) suite
@pytest.mark.heavy
def test_decay_all_params_config_increases_loss():
    """optimizer.decay_all_params=True adds BN/bias L2 on top of kernels."""
    def run(decay_all):
        cfg = _tiny_cfg()
        cfg.optimizer.weight_decay = 0.01
        cfg.optimizer.decay_all_params = decay_all
        tr = Trainer(cfg)
        tr.init_state(seed=0)
        it = learnable_synthetic_iterator(16, 8, 4, seed=5)
        _, m = tr.train(it, num_steps=1)
        return float(m["loss"]), float(m["cross_entropy"])

    loss_k, ce_k = run(False)
    loss_a, ce_a = run(True)
    assert np.isclose(ce_k, ce_a, rtol=1e-6)  # same init, same data
    # BN scales init to 1.0, so all-params decay is strictly larger
    assert loss_a > loss_k


@pytest.mark.heavy
def test_grad_accum_matches_big_batch():
    """2 microbatches of 8 == one batch of 16 (grads averaged). Uses the
    BN-free logistic model where the equivalence is exact; with BN the
    microbatch moments legitimately differ from full-batch moments."""
    it = learnable_synthetic_iterator(16, 8, 4, seed=7)
    batch = next(it)

    def build(accum):
        cfg = _tiny_cfg()
        cfg.model.name = "logistic"
        cfg.model.num_classes = 4
        cfg.model.input_size = 8 * 8 * 3
        cfg.train.grad_accum_steps = accum
        tr = Trainer(cfg)
        tr.init_state(seed=0)
        return tr

    tr_a, tr_b = build(1), build(2)
    sa, ma = tr_a._train_step(tr_a.state, {k: jnp.asarray(v) for k, v in batch.items()})
    sb, mb = tr_b._train_step(tr_b.state, {k: jnp.asarray(v) for k, v in batch.items()})
    pa = jax.tree_util.tree_leaves(sa.params)
    pb = jax.tree_util.tree_leaves(sb.params)
    for a, b in zip(pa, pb):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    assert np.isclose(float(ma["cross_entropy"]), float(mb["cross_entropy"]),
                      rtol=1e-5)


@pytest.mark.heavy
# re-tiered out of the 870s tier-1 (ISSUE 17, ~13s: a full two-trainer
# A/B against the optax oracle). The fused-xent kernel keeps its own
# unit pins in tier-1 (test_ops) and the fused path trains in
# test_loss_decreases_on_learnable_data; the full (unfiltered) suite
# runs the end-to-end oracle.
@pytest.mark.slow
def test_fused_xent_train_step_matches_optax():
    """train.fused_xent=interpret (Pallas kernel, CPU interpreter) produces
    the same step as the optax path — including gradients, via the custom
    VJP — on the sharded 8-device mesh (shard_map route)."""
    def run(mode):
        cfg = _tiny_cfg()
        cfg.train.fused_xent = mode
        tr = Trainer(cfg)
        tr.init_state(seed=0)
        it = learnable_synthetic_iterator(16, 8, 4, seed=11)
        state, m = tr.train(it, num_steps=2)
        return state, m

    sa, ma = run("off")
    sb, mb = run("interpret")
    assert np.isclose(float(ma["cross_entropy"]), float(mb["cross_entropy"]),
                      rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(sa.params),
                    jax.tree_util.tree_leaves(sb.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_fused_xent_auto_resolves_off_cpu():
    """auto → optax on CPU (kernel only compiles on TPU)."""
    from distributed_resnet_tensorflow_tpu.train.loop import make_ce_fn
    import jax.numpy as jnp
    ce = make_ce_fn(0.0, "auto", None)
    logits = jnp.asarray([[2.0, 0.0], [0.0, 2.0]])
    labels = jnp.asarray([0, 1])
    expected = float(cross_entropy_loss(logits, labels))
    assert np.isclose(float(ce(logits, labels)), expected, rtol=1e-6)


def test_evaluate():
    cfg = _tiny_cfg()
    tr = Trainer(cfg)
    tr.init_state()
    it = learnable_synthetic_iterator(16, 8, 4)
    out = tr.evaluate(it, num_batches=3)
    assert out["count"] == 48
    assert 0.0 <= out["precision"] <= 1.0


@pytest.mark.heavy
def test_lars_optimizer_runs():
    cfg = _tiny_cfg()
    cfg.optimizer.name = "lars"
    cfg.optimizer.schedule = "cosine"
    cfg.optimizer.warmup_steps = 2
    cfg.optimizer.total_steps = 10
    tr = Trainer(cfg)
    tr.init_state()
    it = learnable_synthetic_iterator(16, 8, 4)
    state, m = tr.train(it, num_steps=2)
    assert np.isfinite(float(m["loss"]))


def test_adamw_decoupled_decay():
    """AdamW (the transformer-family presets' optimizer) takes decay inside
    the optimizer: loss == cross_entropy even at wd > 0 (no loss-side L2),
    yet a decayed kernel shrinks under zero gradients while masked params
    (bias, pos_embed) do not."""
    cfg = _tiny_cfg()
    cfg.optimizer.name = "adamw"
    cfg.optimizer.weight_decay = 0.1
    tr = Trainer(cfg)
    tr.init_state()
    it = learnable_synthetic_iterator(16, 8, 4)
    state, m = tr.train(it, num_steps=2)
    assert np.isfinite(float(m["loss"]))
    assert float(m["loss"]) == pytest.approx(float(m["cross_entropy"]))

    # the decay itself, isolated: zero gradients, one update — decayed
    # kernels shrink by ~lr*wd, masked leaves (bias, pos_embed) are frozen
    from distributed_resnet_tensorflow_tpu.train.optimizers import (
        create_optimizer)
    tx = create_optimizer(cfg.optimizer, lambda step: 0.01)
    params = {"Dense_0": {"kernel": jnp.ones((4, 4)),
                          "bias": jnp.ones((4,))},
              "pos_embed": jnp.ones((1, 3, 4))}
    opt_state = tx.init(params)
    grads = jax.tree_util.tree_map(jnp.zeros_like, params)
    updates, _ = tx.update(grads, opt_state, params)
    new = optax.apply_updates(params, updates)
    assert float(jnp.max(jnp.abs(new["Dense_0"]["kernel"]))) < 1.0
    assert float(jnp.min(new["Dense_0"]["bias"])) == 1.0
    assert float(jnp.min(new["pos_embed"])) == 1.0


def test_adamw_rejects_decay_all_params():
    """decay_all_params is the loss-side reference-parity switch; decoupled
    optimizers must refuse it loudly rather than silently ignore it."""
    cfg = _tiny_cfg()
    cfg.optimizer.name = "adamw"
    cfg.optimizer.decay_all_params = True
    with pytest.raises(ValueError, match="decay_all_params"):
        Trainer(cfg)


def test_evaluate_with_masked_batches():
    """Masked eval counts only real examples."""
    cfg = _tiny_cfg()
    tr = Trainer(cfg)
    tr.init_state()
    it = learnable_synthetic_iterator(16, 8, 4)

    def masked(it):
        for b in it:
            b = dict(b)
            b["mask"] = np.concatenate(
                [np.ones(12, np.float32), np.zeros(4, np.float32)])
            yield b

    out = tr.evaluate(masked(it), num_batches=2)
    assert out["count"] == 24


def test_steps_per_loop_matches_sequential():
    """K fused steps (lax.scan) == K sequential steps (logistic, exact)."""
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        shard_stacked_batch)
    it = learnable_synthetic_iterator(8, 8, 4, seed=9)
    batches = [next(it) for _ in range(4)]

    def build(spl):
        cfg = _tiny_cfg()
        cfg.model.name = "logistic"
        cfg.model.num_classes = 4
        cfg.model.input_size = 8 * 8 * 3
        cfg.train.batch_size = 8
        cfg.train.steps_per_loop = spl
        tr = Trainer(cfg)
        tr.init_state(seed=0)
        return tr

    tr_seq = build(1)
    step_fn = tr_seq.jitted_train_step()
    from distributed_resnet_tensorflow_tpu.parallel.sharding import shard_batch
    for b in batches:
        tr_seq.state, m_seq = step_fn(tr_seq.state, shard_batch(b, tr_seq.mesh))

    tr_fused = build(4)
    stacked = {k: np.stack([b[k] for b in batches]) for k in batches[0]}
    multi = tr_fused.jitted_multi_step(4)
    tr_fused.state, m_fused = multi(
        tr_fused.state, shard_stacked_batch(stacked, tr_fused.mesh))

    assert int(tr_seq.state.step) == int(tr_fused.state.step) == 4
    for a, b in zip(jax.tree_util.tree_leaves(tr_seq.state.params),
                    jax.tree_util.tree_leaves(tr_fused.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    assert np.isclose(float(m_seq["loss"]), float(m_fused["loss"]), rtol=1e-5)


@pytest.mark.heavy
def test_trainer_train_with_steps_per_loop_and_tail():
    """num_steps not a multiple of steps_per_loop: tail runs unfused."""
    cfg = _tiny_cfg()
    cfg.train.steps_per_loop = 3
    tr = Trainer(cfg)
    tr.init_state()
    hook_steps = []
    it = learnable_synthetic_iterator(16, 8, 4)
    state, m = tr.train(it, num_steps=7,
                        hooks=(lambda s, st, mm: hook_steps.append(s),))
    assert int(state.step) == 7
    assert hook_steps == [3, 6, 7]


def test_segmented_tail_remainder_no_skip():
    """Segmented training with a fused-loop tail must not discard the
    remainder of the pre-stacked group at the segment boundary: a k=3 run
    split 4+4 must see the same batch sequence as an unfused 8-step run
    (exact on the BN-free model)."""
    def build(spl):
        cfg = _tiny_cfg()
        cfg.model.name = "logistic"
        cfg.model.num_classes = 4
        cfg.model.input_size = 8 * 8 * 3
        cfg.train.steps_per_loop = spl
        tr = Trainer(cfg)
        tr.init_state(seed=0)
        return tr

    tr_a = build(1)
    tr_a.train(learnable_synthetic_iterator(16, 8, 4, seed=21), num_steps=8)

    tr_b = build(3)
    it = learnable_synthetic_iterator(16, 8, 4, seed=21)
    tr_b.train(it, num_steps=4)                  # fused 3 + tail 1 (banks 2)
    tr_b.train(it, num_steps=8, start_step=4)    # remainder 2 + fused 3 - ...
    assert int(tr_b.state.step) == 8
    for a, b in zip(jax.tree_util.tree_leaves(tr_a.state.params),
                    jax.tree_util.tree_leaves(tr_b.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # re-tiered out of the 870s tier-1; runs in the full (unfiltered) suite
def test_finite_stream_ends_training_k1():
    """Same contract on the k==1 (unfused) path: exhaustion ends training
    cleanly instead of leaking StopIteration out of Trainer.train."""
    cfg = _tiny_cfg()
    cfg.train.steps_per_loop = 1
    tr = Trainer(cfg)
    tr.init_state()
    src = learnable_synthetic_iterator(16, 8, 4)
    finite = iter([next(src) for _ in range(5)])
    state, m = tr.train(finite, num_steps=100)
    assert int(state.step) == 5
    assert m is not None and np.isfinite(float(m["loss"]))


def test_finite_stream_ends_training_at_last_full_group():
    """A deliberately truncated input ends training cleanly (the reference's
    serial path stopped on input exhaustion too, SURVEY.md §3.5)."""
    cfg = _tiny_cfg()
    cfg.train.steps_per_loop = 3
    tr = Trainer(cfg)
    tr.init_state()
    src = learnable_synthetic_iterator(16, 8, 4)
    finite = iter([next(src) for _ in range(7)])
    state, m = tr.train(finite, num_steps=100)
    assert int(state.step) == 6  # 2 full groups; the partial 7th is dropped
    assert m is not None and np.isfinite(float(m["loss"]))


def test_detach_device_dataset_restores_config_augment():
    """attach forces device-side augmentation (raw uint8 needs it); detach
    must restore the config-resolved choice or streamed host-standardized
    input would be augmented twice."""
    cfg = _tiny_cfg()
    cfg.data.dataset = "cifar10"
    cfg.data.device_augment = "off"   # CPU: config resolves to host augment
    tr = Trainer(cfg)
    tr.init_state()
    assert tr._aug_fn is None
    imgs = np.zeros((64, 8, 8, 3), np.uint8)
    lbls = np.zeros((64,), np.int32)
    tr.attach_device_dataset(imgs, lbls)
    assert tr._aug_fn is not None
    tr.detach_device_dataset()
    assert tr._aug_fn is None


def test_threaded_stacker_close_stops_worker():
    """Closing the stacker generator must terminate its worker thread
    (otherwise every replaced prefetcher leaks a parked thread + batches)."""
    import threading
    import time as _time
    from distributed_resnet_tensorflow_tpu.data.device_prefetch import (
        threaded_stacker)

    def gen():
        i = 0
        while True:
            yield {"x": np.full((2,), i)}
            i += 1

    existing = set(threading.enumerate())
    it = threaded_stacker(gen(), 3, depth=1)
    first = next(it)
    assert first["x"].shape == (3, 2)
    workers = [t for t in threading.enumerate()
               if t not in existing and "stacker" in t.name]
    assert len(workers) == 1
    it.close()
    workers[0].join(3)
    assert not workers[0].is_alive()


def test_segmented_training_does_not_skip_batches():
    """Repeated train() calls over ONE shared iterator must consume batches
    contiguously despite the device-prefetch lookahead."""
    cfg = _tiny_cfg()
    cfg.model.name = "logistic"
    cfg.model.num_classes = 4
    cfg.model.input_size = 8 * 8 * 3
    tr = Trainer(cfg)
    tr.init_state(seed=0)

    consumed = []

    def tracking_iter():
        i = 0
        it = learnable_synthetic_iterator(16, 8, 4, seed=1)
        while True:
            consumed.append(i)
            i += 1
            yield next(it)

    it = tracking_iter()
    tr.train(it, num_steps=3)
    tr.train(it, num_steps=6, start_step=3)
    # 9 steps total; the staging pipeline may hold transfer_depth (2)
    # queued device batches, one in the worker hand-off, and up to two in
    # the transfer thread's issue window beyond that
    assert len(consumed) <= 9 + 5


def test_loss_decreases_with_group_norm():
    """The BN-free contract trains: same convergence oracle as the BN path
    (VERDICT r4 #1 — the GroupNorm escape hatch must exist AND learn)."""
    cfg = _tiny_cfg()
    cfg.model.norm = "group"
    tr = Trainer(cfg)
    tr.init_state()
    assert not tr.state.batch_stats  # stateless contract
    it = learnable_synthetic_iterator(16, 8, 4, seed=3)
    losses = []
    step_fn = tr.jitted_train_step()
    from distributed_resnet_tensorflow_tpu.parallel.sharding import shard_batch
    for i in range(30):
        batch = shard_batch(next(it), tr.mesh)
        tr.state, m = step_fn(tr.state, batch)
        losses.append(float(m["cross_entropy"]))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.8, losses


@pytest.mark.heavy
def test_loss_decreases_with_frozen_bn():
    """The frozen-BN fine-tune contract also trains from scratch (stats
    pinned at init 0/1 — a learned affine)."""
    cfg = _tiny_cfg()
    cfg.model.norm = "frozen"
    tr = Trainer(cfg)
    tr.init_state()
    # snapshot to numpy: the jitted step donates the state buffers
    before = [np.asarray(x)
              for x in jax.tree_util.tree_leaves(tr.state.batch_stats)]
    it = learnable_synthetic_iterator(16, 8, 4, seed=3)
    losses = []
    step_fn = tr.jitted_train_step()
    from distributed_resnet_tensorflow_tpu.parallel.sharding import shard_batch
    for i in range(30):
        batch = shard_batch(next(it), tr.mesh)
        tr.state, m = step_fn(tr.state, batch)
        losses.append(float(m["cross_entropy"]))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.8, losses
    after = jax.tree_util.tree_leaves(tr.state.batch_stats)
    for b, a in zip(before, after):
        np.testing.assert_array_equal(np.asarray(b), np.asarray(a))


@pytest.mark.slow  # re-tiered out of the 870s tier-1; runs in the full (unfiltered) suite
def test_group_norm_warmupless_high_lr_warns(caplog):
    """The measured GroupNorm plateau (docs/perf_norm_r5.md) warns at
    TRAIN time when the RESOLVED schedule starts high (probing the
    schedule, not raw config fields — piecewise ignores learning_rate and
    constant ignores warmup_steps); an effective warmup stays silent, and
    merely constructing a Trainer (the evaluator does) never warns."""
    import logging
    from distributed_resnet_tensorflow_tpu.data import (
        learnable_synthetic_iterator)
    cfg = _tiny_cfg()
    cfg.model.norm = "group"
    # piecewise starting at 0.1 — learning_rate field deliberately low to
    # prove the probe reads the schedule, not the raw field
    cfg.optimizer.schedule = "piecewise"
    cfg.optimizer.learning_rate = 0.001
    cfg.optimizer.boundaries = (50,)
    cfg.optimizer.values = (0.1, 0.01)
    with caplog.at_level(logging.WARNING):
        tr = Trainer(cfg)
    assert not any("plateau" in r.message for r in caplog.records)
    with caplog.at_level(logging.WARNING):
        tr.train(learnable_synthetic_iterator(16, 8, 4), num_steps=1)
    assert any("plateau" in r.message for r in caplog.records)
    caplog.clear()
    # effective warmup: schedule starts low -> silent
    cfg2 = _tiny_cfg()
    cfg2.model.norm = "group"
    cfg2.optimizer.schedule = "warmup_piecewise"
    cfg2.optimizer.warmup_steps = 500
    cfg2.optimizer.warmup_start = 0.01
    cfg2.optimizer.boundaries = (600,)
    cfg2.optimizer.values = (0.1, 0.01)
    tr2 = Trainer(cfg2)
    with caplog.at_level(logging.WARNING):
        tr2.train(learnable_synthetic_iterator(16, 8, 4), num_steps=1)
    assert not any("plateau" in r.message for r in caplog.records)


def test_exactly_one_transfer_per_training_batch(monkeypatch):
    """Acceptance contract: the hot path issues EXACTLY one host→device
    transfer per training batch (the coalesced stager's single batched
    device_put), counted via a wrapper around the one issue point."""
    from distributed_resnet_tensorflow_tpu.parallel import sharding as sh
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        CoalescedStager)

    calls = []
    real = sh._issue_device_put
    monkeypatch.setattr(sh, "_issue_device_put",
                        lambda arrays, devices:
                        calls.append(1) or real(arrays, devices))

    # k=1 path: N batches -> N transfer issues
    cfg = _tiny_cfg()
    cfg.data.coalesced_transfer = "on"   # auto resolves off on CPU
    tr = Trainer(cfg)
    assert isinstance(tr._put_batch, CoalescedStager)
    tr.init_state()
    src = learnable_synthetic_iterator(16, 8, 4)
    finite = iter([next(src) for _ in range(5)])
    state, _ = tr.train(finite, num_steps=100)
    assert int(state.step) == 5
    assert len(calls) == 5

    # fused path: 6 batches at k=3 -> 2 stacked groups -> 2 transfer issues
    calls.clear()
    cfg = _tiny_cfg()
    cfg.data.coalesced_transfer = "on"
    cfg.train.steps_per_loop = 3
    tr = Trainer(cfg)
    tr.init_state()
    finite = iter([next(src) for _ in range(6)])
    state, _ = tr.train(finite, num_steps=100)
    assert int(state.step) == 6
    assert len(calls) == 2


def test_evaluate_partial_stream_single_process():
    """Pipelined evaluate keeps the exhaustion contract: a one-pass stream
    shorter than num_batches returns metrics over what was consumed
    (single-process; multi-process raises to avoid the collective
    deadlock)."""
    cfg = _tiny_cfg()
    tr = Trainer(cfg)
    tr.init_state()
    src = learnable_synthetic_iterator(16, 8, 4)
    out = tr.evaluate(iter([next(src) for _ in range(2)]), num_batches=5)
    assert out["count"] == 32


def test_evaluate_closes_staging_thread():
    """Each evaluate() call must stop its staging thread on return —
    a polling evaluator would otherwise leak one thread per round."""
    import threading
    import time
    cfg = _tiny_cfg()
    tr = Trainer(cfg)
    tr.init_state()
    it = learnable_synthetic_iterator(16, 8, 4)
    before = {t for t in threading.enumerate()}
    tr.evaluate(it, num_batches=2)
    deadline = time.time() + 5
    while time.time() < deadline:
        leaked = [t for t in threading.enumerate() if t not in before
                  and "drt-device-stage" in t.name and t.is_alive()]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, leaked
