"""untimed-blocking-call: loop/dispatch threads never park unbounded.

A ``queue.get()``, ``Event.wait()`` or ``Thread.join()`` with no timeout
on the train-loop or serve-dispatch thread turns ANY upstream death into
a silent permanent hang: the producer thread that crashed without
posting its sentinel leaves the consumer parked forever, the watchdog's
"stalled progress" verdict fires minutes later (if armed at all), and
the job burns its allocation until the SLURM limit. Bounded waits with a
liveness re-check turn the same failure into a loud error in seconds.

The rule roots at ``analysis/threads.LOOP_ROOTS`` (the train/eval loop
entries and the serve dispatch body) plus every spawn target registered
with the ``dispatch`` role, walks the resolved call graph, and flags any
reachable zero-argument ``.get()`` / ``.wait()`` / ``.join()`` (no
``timeout=``). Zero-arg is the discriminator: ``dict.get(k)``,
``str.join(xs)``, ``os.path.join(a, b)`` all carry arguments; the
blocking signatures bare of arguments are the queue/event/thread forms.

The socket sweep (ISSUE 20 satellite): the fleet front door added the
largest thread inventory since the rule landed — the replica listener's
accept/connection threads and the router's per-replica client pool
(serve/wire.py) all park on sockets, where "untimed" means
``socket.recv``/``accept`` on a socket that never got a ``settimeout``.
Those calls carry arguments, so the zero-arg discriminator above never
sees them; instead the sweep roots at EVERY registered thread spawn
target (any role — a daemon parked forever on a dead peer's socket still
leaks a thread and wedges ``close()``/``join``) plus the loop roots, and
flags reachable socket waits unless a deadline is established for the
root: a ``.settimeout(<not None>)`` anywhere in the root's reachable
call graph, or in a class-sibling method of a reachable method (the
listener arms the accept timeout in ``start()`` BEFORE spawning
``_accept_loop``; the connection handler arms the conn timeout before
``_recv_exact`` parks on it). Root-level blessing is deliberately
coarse — the contract is "this thread's sockets live under deadlines",
not a per-call dataflow proof.

Regression notes (findings this rule surfaced on the real tree, fixed in
the same round it landed):

  * ``data/device_prefetch.threaded_iterator`` — the consumer's
    ``q.get()`` was untimed; a worker thread killed without posting its
    ``_STOP``/error sentinel (interpreter teardown, a hard crash in
    native decode) would park the train loop forever. Now a 5 s timed
    get that re-checks ``thread.is_alive()`` and raises loudly when the
    worker died silently.
  * ``data/imagenet.imagenet_iterator`` — the in-process decoder path's
    ``out_q.get()`` had the same shape (the PROCESS path already polled
    liveness); both paths now share the timed-get-plus-liveness idiom.
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..report import Finding
from .. import threads as threads_mod
from ..callgraph import call_target, body_walk, get_callgraph

RULE_NAME = "untimed-blocking-call"
DOC = __doc__

_BLOCKING_ATTRS = ("get", "wait", "join")

#: attribute calls that park on a socket (or a multiprocessing pipe —
#: ``Connection.recv`` blocks the same way) until the peer speaks
_SOCKET_WAIT_ATTRS = ("accept", "recv", "recv_into", "recvfrom")


def _socket_wait(call: ast.Call) -> "str | None":
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr in _SOCKET_WAIT_ATTRS:
        return fn.attr
    return None


def _arms_deadline(fn_node: ast.AST) -> bool:
    """True when the function calls ``<obj>.settimeout(x)`` with ``x``
    not literally None — ``settimeout(None)`` DISARMS the deadline and
    must not count as arming one."""
    for node in body_walk(fn_node):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "settimeout" and node.args:
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant) and arg.value is None):
                return True
    return False


def _untimed_blocking(call: ast.Call) -> bool:
    fn = call.func
    if not isinstance(fn, ast.Attribute) or fn.attr not in _BLOCKING_ATTRS:
        return False
    if any(kw.arg == "timeout" for kw in call.keywords):
        return False
    # positional timeouts: Event.wait(t) / join(t) / Queue.get(block, t).
    # A one-positional-arg .get(x) is almost always dict.get(key) — flag
    # it only when the arg is literally True (Queue.get(True) blocks
    # forever exactly like bare get()); same for get(block=True).
    if fn.attr == "get":
        for kw in call.keywords:
            if kw.arg == "block":
                return isinstance(kw.value, ast.Constant) and \
                    kw.value.value is True
        if call.args:
            return len(call.args) == 1 and \
                isinstance(call.args[0], ast.Constant) and \
                call.args[0].value is True
        return True
    return not call.args


def check(ctx) -> Iterable[Finding]:
    graph = get_callgraph(ctx)
    wanted = set(threads_mod.LOOP_ROOTS)
    roots = [key for key, fn in graph.funcs.items()
             if fn.short() in wanted]
    for spawn in threads_mod.iter_spawn_sites(ctx):
        if spawn.target is not None and \
                threads_mod.role_of(spawn.target) == \
                threads_mod.ROLE_DISPATCH:
            roots.append(spawn.target.key)
    for key in sorted(graph.reachable(roots)):
        fn = graph.funcs[key]
        for node in body_walk(fn.node):
            if isinstance(node, ast.Call) and _untimed_blocking(node):
                name, _ = call_target(node)
                yield Finding(
                    RULE_NAME, fn.rel, node.lineno,
                    f"untimed blocking .{name}() reachable from the "
                    "loop/dispatch thread — a dead producer parks this "
                    "thread forever; use a timed wait that re-checks "
                    "liveness and fails loudly "
                    "(docs/static_analysis.md hangcheck)")

    # -- socket sweep: waits on sockets reachable from ANY thread root
    # (listener accept/connection threads, router client pool, daemons) —
    # arguments or not, a recv on a socket with no armed settimeout parks
    # the thread until the peer speaks, which a dead peer never does.
    socket_roots = set(roots)
    for spawn in threads_mod.iter_spawn_sites(ctx):
        if spawn.target is not None:
            socket_roots.add(spawn.target.key)
    emitted = set()
    for root in sorted(socket_roots):
        reach = sorted(graph.reachable([root]))
        blessed = any(_arms_deadline(graph.funcs[k].node) for k in reach)
        if not blessed:
            # class-sibling blessing: the deadline is often armed in a
            # lifecycle method OUTSIDE the thread body — the listener's
            # start() calls self._sock.settimeout(...) before spawning
            # _accept_loop — so any settimeout in a class that owns a
            # reachable method blesses the root too
            classes = {(graph.funcs[k].rel, graph.funcs[k].cls)
                       for k in reach if graph.funcs[k].cls is not None}
            blessed = any(
                fn.cls is not None and (fn.rel, fn.cls) in classes and
                _arms_deadline(fn.node)
                for fn in graph.funcs.values())
        if blessed:
            continue
        for key in reach:
            fn = graph.funcs[key]
            for node in body_walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                attr = _socket_wait(node)
                if attr is None:
                    continue
                mark = (fn.rel, node.lineno, attr)
                if mark in emitted:
                    continue
                emitted.add(mark)
                yield Finding(
                    RULE_NAME, fn.rel, node.lineno,
                    f"socket .{attr}() reachable from a thread root with "
                    "no .settimeout(...) armed anywhere on its path — a "
                    "dead peer parks this thread forever and close()/"
                    "join wedges behind it; arm a deadline before the "
                    "loop (docs/static_analysis.md hangcheck)")
