"""Preemption handling: signal listener + resumable-exit contract.

The reference's answer to a SLURM preemption was SIGKILL-after-grace with
whatever checkpoint ``save_checkpoint_secs`` last happened to write — up to
10 minutes of lost work on the ImageNet cadence (SURVEY.md §2.14). Here the
train loop polls a :class:`PreemptionListener` at step boundaries; on
SIGTERM/SIGINT (or an optional wall-clock deadline for maintenance-window
preemption) it stops cleanly, ``main.run_train`` force-commits a final
checkpoint, and the process exits with :data:`RESUMABLE_EXIT_CODE` so
launchers (launch.py, scripts/submit_tpu_slurm.sh) know to requeue rather
than fail the job.

Exit-code contract (docs/resilience.md):
  0   — finished train_steps; nothing to resume.
  75  — preempted; a checkpoint at the last finished step is committed and
        a relaunch with the same config resumes exactly there (EX_TEMPFAIL,
        the sysexits "temporary failure, retry" code).
  else — a real error.
"""
from __future__ import annotations

import logging
import signal
import threading
import time
from typing import Iterable, Optional

log = logging.getLogger(__name__)

#: sysexits.h EX_TEMPFAIL — "temporary failure; user is invited to retry".
RESUMABLE_EXIT_CODE = 75

#: a real (non-resumable) failure — launchers must NOT requeue
FAILURE_EXIT_CODE = 1

#: shell convention 128+SIGINT — the operator hit ^C at the launcher.
#: Deliberate, so NOT resumable (a requeue would resurrect the run the
#: operator just killed) and not a failure either; schedulers leave it
#: alone.
INTERRUPT_EXIT_CODE = 130

_DEFAULT_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class Preempted(Exception):
    """Raised by run_train after a graceful preemption stop; carries the
    step whose checkpoint was committed. main() maps it to
    RESUMABLE_EXIT_CODE."""

    def __init__(self, step: int, reason: str = "signal"):
        super().__init__(f"preempted ({reason}) at step {step}; "
                         f"checkpoint committed — resumable")
        self.step = step
        self.reason = reason


class PreemptionListener:
    """Installable SIGTERM/SIGINT flag + optional deadline.

    The handler only sets a flag (async-signal-safe); the train loop polls
    ``should_stop()`` at step boundaries, so the stop always lands between
    optimizer steps with a consistent TrainState. A second signal while a
    stop is already pending restores the previous handler and re-delivers,
    so a stuck drain can still be killed the ordinary way.
    """

    #: window (secs) in which a repeated signal counts as DUPLICATE
    #: delivery, not operator escalation: terminals and SLURM signal the
    #: whole process group, so a launcher forwarding SIGTERM hands every
    #: child a second copy milliseconds after the first — escalating on
    #: that would kill the child before its preemption checkpoint commits
    ESCALATION_GRACE_SECS = 1.0

    def __init__(self, signals: Iterable[int] = _DEFAULT_SIGNALS,
                 deadline_secs: float = 0.0):
        self._signals = tuple(signals)
        self._deadline = (time.monotonic() + deadline_secs
                          if deadline_secs > 0 else None)
        self._event = threading.Event()
        self._reason: Optional[str] = None
        self._first_signal_time: Optional[float] = None
        self._prev = {}
        self._installed = False

    # -- lifecycle ---------------------------------------------------------
    def install(self) -> bool:
        """Install handlers. Returns False (listener inert) when not on the
        main thread — ``signal.signal`` only works there, and an inert
        listener beats breaking library callers (e.g. tests driving
        run_train from a worker thread)."""
        if self._installed:
            return True
        try:
            for sig in self._signals:
                self._prev[sig] = signal.signal(sig, self._on_signal)
        except ValueError:  # not the main thread
            for sig, prev in self._prev.items():
                signal.signal(sig, prev)
            self._prev.clear()
            log.warning("PreemptionListener: not on the main thread; "
                        "signal handling disabled for this run")
            return False
        self._installed = True
        return True

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):  # pragma: no cover
                pass
        self._prev.clear()
        self._installed = False

    def __enter__(self) -> "PreemptionListener":
        self.install()
        return self

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- signal path -------------------------------------------------------
    def _on_signal(self, signum, frame) -> None:
        first = not self._event.is_set()
        self._reason = self._reason or f"signal {signal.Signals(signum).name}"
        self._event.set()
        if first:
            self._first_signal_time = time.monotonic()
            # logging from a signal handler is not strictly re-entrant, but
            # this fires once and the alternative (silence) costs operators
            # real debugging time on every preemption
            log.warning("%s received: finishing the current step, "
                        "committing a checkpoint, exiting resumable (%d)",
                        signal.Signals(signum).name, RESUMABLE_EXIT_CODE)
            return
        # a repeat within the grace window is duplicate delivery (process
        # group + forwarding launcher), not an operator asking twice
        if self._first_signal_time is not None and \
                time.monotonic() - self._first_signal_time \
                < self.ESCALATION_GRACE_SECS:
            return
        # second signal: restore the previous disposition and re-deliver so
        # the default action (terminate / KeyboardInterrupt) happens now.
        # ``prev`` is None when the pre-existing handler wasn't installed
        # from Python (C extension, embedding launcher) — signal.signal
        # would TypeError on it, leaving the process gracefully unkillable;
        # fall back to the default disposition instead
        prev = self._prev.get(signum)
        if prev is None:
            prev = signal.SIG_DFL
        try:
            signal.signal(signum, prev)
        except TypeError:  # pragma: no cover - exotic prev handler object
            signal.signal(signum, signal.SIG_DFL)
        signal.raise_signal(signum)

    def request_stop(self, reason: str) -> None:
        """Programmatic stop request (no signal): the watchdog's graceful
        escalation path (resilience/watchdog.py) and any other subsystem
        that wants the loop to stop at the next step boundary and exit
        resumable. Thread-safe; first reason wins."""
        if self._reason is None:
            self._reason = reason
        self._event.set()

    def reset(self) -> None:
        """Clear a consumed stop request so the loop can run again — the
        elastic generation transition (resilience/elastic.py): the
        watchdog's peer-lost ``request_stop`` (or the chief's "reshard"
        grow request) belongs to the PREVIOUS mesh generation; without a
        reset the new generation's stop poll would fire on its first
        step. Signal state is deliberately NOT cleared: a real SIGTERM
        must keep stopping the run across generations."""
        if self._reason is not None and \
                not self._reason.startswith("signal "):
            self._reason = None
            self._event.clear()

    # -- polling API (train-loop hot path: one Event.is_set + a clock read) -
    def should_stop(self) -> bool:
        if self._event.is_set():
            return True
        if self._deadline is not None and time.monotonic() >= self._deadline:
            if self._reason is None:
                self._reason = "deadline"
                log.warning("preemption deadline reached: stopping at the "
                            "next step boundary")
            self._event.set()
            return True
        return False

    def preempted(self) -> bool:
        """True once a stop was requested (signal or deadline)."""
        return self.should_stop()

    def reason(self) -> str:
        return self._reason or "not preempted"


def collective_preempted(listener: PreemptionListener) -> bool:
    """One-shot cross-process OR of ``preempted()``.

    The post-train decision to enter the preemption save must be AGREED:
    the save is itself a collective (sharded write + commit barrier), so a
    process entering it on a local-only flag — deadline clock skew, or an
    early return (input exhaustion) between the throttled in-loop sync
    points — would hang on peers that skipped it. Call from ALL processes
    at the same program point; single-process reduces to the local flag.
    """
    import jax
    if jax.process_count() <= 1:
        return listener.preempted()
    import numpy as np
    from jax.experimental import multihost_utils
    flags = multihost_utils.process_allgather(
        np.asarray([listener.preempted()], dtype=np.bool_))
    agreed = bool(np.any(flags))
    if agreed:
        if listener._reason is None:
            listener._reason = "peer preempted"
        listener._event.set()
    return agreed


def collective_should_stop(listener: PreemptionListener,
                           sync_every: int = 8):
    """Cross-process stop agreement for multi-host runs.

    Per-process stop flags are a deadlock hazard: signal delivery skew (or
    clock skew on the deadline) can make process 0 stop after step N while
    process 1 runs on — its next collective step then hangs waiting for a
    participant that left, and the final checkpoint save barriers on
    mismatched step names. The flags are therefore all-gathered and ORed,
    so (a) a signal landing on ANY process stops all of them and (b) the
    decision flips at the SAME poll everywhere — every process polls at
    identical loop points of the same SPMD program.

    The host collective is paid only on every ``sync_every``-th poll (the
    poll COUNT is identical across processes, so the throttle cannot
    desync them); in between, the poll is the local Event check only.
    Preemption reaction latency grows by at most sync_every-1 steps —
    irrelevant against a SLURM grace period — while fast-step multi-host
    runs don't serialize every step on a cross-host round-trip.
    """
    import numpy as np
    calls = {"n": 0, "stopped": False}

    def should_stop() -> bool:
        if calls["stopped"]:
            return True
        local = listener.should_stop()
        calls["n"] += 1
        if calls["n"] % sync_every:
            return False  # between sync points nobody stops unilaterally
        from jax.experimental import multihost_utils
        flags = multihost_utils.process_allgather(
            np.asarray([local], dtype=np.bool_))
        agreed = bool(np.any(flags))
        if agreed:
            calls["stopped"] = True
            if not local and listener._reason is None:
                listener._reason = "peer preempted"
            listener._event.set()  # mirror: preempted()/reason() stay true
        return agreed

    return should_stop
