"""Thread-role contracts: the static complement of the dispatch sanitizer.

This codebase runs a fixed cast of threads (docs/input_pipeline.md's
thread inventory, docs/static_analysis.md's role table): ONE thread per
process may launch multi-device XLA executions (the train loop, or the
serve dispatch thread), staging threads only move bytes, the checkpoint
writer only does host I/O, and the daemons (heartbeat, watchdog,
checkpoint poller) only read/write files. Two shipped bugs define the
stakes: PR 2's cross-thread multi-device dispatch deadlock and PR 4's
gloo collective hang.

``THREAD_ROLES`` is the explicit registry: every ``threading.Thread(
target=...)`` spawn site (and every executor ``submit`` of a package
function) must resolve to a role here — an unregistered spawn is itself
a finding (``rules/thread_dispatch.py``), which is what keeps the
inventory honest as threads are added. The roles:

  ========  ==========================================================
  role      contract
  ========  ==========================================================
  dispatch  MAY launch multi-device executions; every other role may not
  staging   moves host bytes / issues transfers; never executes programs
  writer    checkpoint host I/O only (the zero-stall contract)
  daemon    heartbeat/watchdog/poller: files and sockets only
  ========  ==========================================================

Registry keys are ``<package-relative-file>::<qualname>`` of the spawn
TARGET (see ``callgraph.FuncNode.short``).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

from .callgraph import CallGraph, FuncNode, body_walk, get_callgraph

ROLE_DISPATCH = "dispatch"
ROLE_STAGING = "staging"
ROLE_WRITER = "writer"
ROLE_DAEMON = "daemon"

#: spawn-target → role. Every Thread/executor spawn in the package must
#: resolve here; rules/thread_dispatch.py flags the ones that don't.
THREAD_ROLES = {
    # the serve dispatch thread: the ONE thread of a serving process that
    # executes compiled programs (docs/serving.md threading contract)
    "serve/batcher.py::DynamicBatcher._run": ROLE_DISPATCH,
    # input pipeline workers (docs/input_pipeline.md): decode/stack/stage
    # threads move bytes; the consumer thread finalizes + dispatches
    "data/device_prefetch.py::threaded_iterator.<locals>.worker":
        ROLE_STAGING,
    "data/imagenet.py::imagenet_iterator.<locals>.feeder": ROLE_STAGING,
    "data/imagenet.py::imagenet_iterator.<locals>.decoder": ROLE_STAGING,
    # checkpoint writer thread: stage → fsync → manifest → commit, host
    # I/O only (the zero-stall contract, docs/resilience.md)
    "checkpoint/manager.py::CheckpointManager._write_async": ROLE_WRITER,
    "checkpoint/manager.py::CheckpointManager._write_sharded_async":
        ROLE_WRITER,
    # daemons: beats, peer-health polling, committed-checkpoint polling —
    # files only, never device work
    "resilience/heartbeat.py::HeartbeatPublisher._run": ROLE_DAEMON,
    "resilience/watchdog.py::Watchdog._run": ROLE_DAEMON,
    "serve/swap.py::CheckpointSwapper._run": ROLE_DAEMON,
    # the fleet front door (docs/serving.md fleet section): the replica-
    # side listener threads decode bytes and park on Futures (submitter
    # role — the batcher's dispatch thread still owns every execution);
    # the router/supervisor threads are numpy-and-sockets only by
    # construction (serve/router.py holds no jax state at all)
    "serve/wire.py::ReplicaListener._accept_loop": ROLE_DAEMON,
    "serve/wire.py::ReplicaListener._handle_conn": ROLE_DAEMON,
    "serve/router.py::Router._dispatch_loop": ROLE_DAEMON,
    "serve/router.py::Router._worker_loop": ROLE_DAEMON,
    "serve/router.py::Router._health_loop": ROLE_DAEMON,
    "serve/fleet.py::FleetSupervisor._watch": ROLE_DAEMON,
    # the reshard teardown's bounded jax.distributed.shutdown: shutting
    # down the dead generation's coordination client can block on a lost
    # peer, so it runs on a joined-with-timeout daemon and is abandoned
    # past the deadline (docs/resilience.md, elastic mesh)
    "parallel/distributed.py::teardown_for_reshard.<locals>._shutdown":
        ROLE_DAEMON,
}

#: entry points that constitute the LOOP/DISPATCH side for the blocking-
#: call rule: the train/eval loop plus the functions the serve dispatch
#: thread runs (the batcher's dispatch_fn callback is dynamic, so the
#: server's dispatch body is rooted explicitly), plus the fleet front
#: door's request path — one untimed wait in the router or a connection
#: handler would let a dead replica park the service forever.
LOOP_ROOTS = (
    "train/loop.py::Trainer.train",
    "train/loop.py::Trainer.evaluate",
    "main.py::run_train",
    "main.py::run_eval",
    "main.py::run_train_and_eval",
    "serve/server.py::InferenceServer._run_bucket",
    "serve/router.py::Router._dispatch_loop",
    "serve/router.py::Router._worker_loop",
    "serve/wire.py::ReplicaListener._handle_conn",
)


@dataclass(frozen=True)
class SpawnSite:
    rel: str
    lineno: int
    kind: str                      # "thread" | "submit"
    target: Optional[FuncNode]     # resolved spawn target (None = dynamic)
    target_desc: str               # what the source said


def role_of(target: FuncNode) -> Optional[str]:
    return THREAD_ROLES.get(target.short())


def _resolve_target_expr(expr: ast.AST, caller: FuncNode,
                         graph: CallGraph) -> Tuple[Optional[FuncNode], str]:
    """Resolve a Thread target= / submit first-arg expression to a
    FuncNode where statically possible."""
    if isinstance(expr, ast.Name):
        cands = graph.resolve_name(expr.id, caller.rel)
        return (cands[0] if len(cands) == 1 else None), expr.id
    if isinstance(expr, ast.Attribute):
        desc = f".{expr.attr}"
        if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                and caller.cls is not None:
            own = graph.by_class_method.get((caller.cls, expr.attr), [])
            if len(own) == 1:
                return own[0], f"self.{expr.attr}"
        cands = graph.by_name.get(expr.attr, [])
        return (cands[0] if len(cands) == 1 else None), desc
    return None, ast.dump(expr)[:40]


def _is_thread_ctor(call: ast.Call) -> bool:
    fn = call.func
    return (isinstance(fn, ast.Name) and fn.id == "Thread") or \
        (isinstance(fn, ast.Attribute) and fn.attr == "Thread")


def iter_spawn_sites(ctx) -> Iterator[SpawnSite]:
    """Every ``threading.Thread(target=...)`` construction and every
    ``<executor>.submit(fn, ...)`` whose first argument resolves to a
    package function. Tests are out of scope (the linter never sees
    them); repo-top python (bench.py etc.) is included."""
    graph = get_callgraph(ctx)
    for key, fn in sorted(graph.funcs.items()):
        for node in body_walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            if _is_thread_ctor(node):
                target_expr = next((kw.value for kw in node.keywords
                                    if kw.arg == "target"), None)
                if target_expr is None:
                    yield SpawnSite(fn.rel, node.lineno, "thread", None,
                                    "<no target=>")
                    continue
                tgt, desc = _resolve_target_expr(target_expr, fn, graph)
                yield SpawnSite(fn.rel, node.lineno, "thread", tgt, desc)
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "submit" and node.args:
                tgt, desc = _resolve_target_expr(node.args[0], fn, graph)
                if tgt is not None:  # batcher/server .submit(image) is
                    yield SpawnSite(fn.rel, node.lineno, "submit", tgt,
                                    desc)  # not a spawn — args are data


# -- dispatch-bearing call detection ----------------------------------------

def is_jitted_execution(call: ast.Call) -> bool:
    """``self.jitted_train_step()(state, batch)`` — calling the RESULT of
    a ``jitted_*`` accessor executes a compiled multi-device program.
    (Calling the accessor alone only builds/returns the jit wrapper —
    ``step_flops`` does that to lower for cost analysis, legally.)"""
    fn = call.func
    return isinstance(fn, ast.Call) and isinstance(fn.func, ast.Attribute) \
        and fn.func.attr.startswith("jitted_")


#: call names that finalize a StagedBatch — a multi-device unpack
#: execution (parallel/sharding.py; the PR 2 deadlock's exact shape)
DISPATCH_CALL_NAMES = ("finalize_staged", "finalize", "put_and_finalize")


def dispatch_bearing_calls(fn: FuncNode) -> Iterator[ast.Call]:
    """Calls in this function's own body that launch a multi-device XLA
    execution: jitted-step executions and StagedBatch finalization."""
    for node in body_walk(fn.node):
        if not isinstance(node, ast.Call):
            continue
        if is_jitted_execution(node):
            yield node
            continue
        name, _ = _call_name(node)
        if name in DISPATCH_CALL_NAMES:
            yield node


def _call_name(call: ast.Call) -> Tuple[Optional[str], Optional[str]]:
    from .callgraph import call_target
    return call_target(call)


# -- collective-bearing call detection --------------------------------------

#: direct cross-process/cross-device collective call names: the lax
#: collectives the shard_map'd paths issue plus the multihost barriers.
#: A function containing one of these (or an explicit jitted execution)
#: is collective-bearing; callers inherit transitively over the graph.
COLLECTIVE_CALL_NAMES = frozenset({
    "psum", "psum_scatter", "all_gather", "all_to_all", "ppermute",
    "pmean", "pmax", "pmin",
    "sync_global_devices", "process_allgather", "broadcast_one_to_all",
})


def contains_direct_collective(fn: FuncNode) -> bool:
    for node in body_walk(fn.node):
        if not isinstance(node, ast.Call):
            continue
        if is_jitted_execution(node):
            return True
        name, _ = _call_name(node)
        if name in COLLECTIVE_CALL_NAMES:
            return True
    return False


def collective_bearing_keys(graph: CallGraph) -> set:
    """Transitive closure: every function that can reach a direct
    collective call over resolved edges."""
    seeds = {key for key, fn in graph.funcs.items()
             if contains_direct_collective(fn)}
    # propagate up: caller of a bearing function is bearing
    bearing = set(seeds)
    changed = True
    while changed:
        changed = False
        for key in graph.funcs:
            if key in bearing:
                continue
            if any(e in bearing for e in graph.edges(key)):
                bearing.add(key)
                changed = True
    return bearing


# -- chief-gate detection ----------------------------------------------------

def _is_chief_test(test: ast.AST) -> bool:
    """``is_chief()`` / ``jax.process_index() == 0`` (and negations are
    handled by the caller via the guard-return form)."""
    if isinstance(test, ast.Call):
        name, _ = _call_name(test)
        return name == "is_chief"
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        left, right = test.left, test.comparators[0]
        if isinstance(test.ops[0], ast.Eq):
            for a, b in ((left, right), (right, left)):
                if isinstance(b, ast.Constant) and b.value == 0 \
                        and isinstance(a, ast.Call):
                    name, _ = _call_name(a)
                    if name == "process_index":
                        return True
    return False


def _is_not_chief_test(test: ast.AST) -> bool:
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _is_chief_test(test.operand)
    if isinstance(test, ast.Compare) and len(test.ops) == 1 \
            and isinstance(test.ops[0], ast.NotEq):
        left, right = test.left, test.comparators[0]
        for a, b in ((left, right), (right, left)):
            if isinstance(b, ast.Constant) and b.value == 0 \
                    and isinstance(a, ast.Call):
                name, _ = _call_name(a)
                if name == "process_index":
                    return True
    return False


def chief_gated_statements(fn: FuncNode) -> Iterator[List[ast.stmt]]:
    """Statement groups that only the chief process executes:

      * the body of ``if is_chief():`` / ``if process_index() == 0:``
        (also via a local name assigned from that expression);
      * everything AFTER an early ``if not is_chief(): return`` guard.
    """
    chief_names = set()
    for node in body_walk(fn.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and _is_chief_test(node.value):
            chief_names.add(node.targets[0].id)

    def test_is_chief(test):
        if _is_chief_test(test):
            return True
        return isinstance(test, ast.Name) and test.id in chief_names

    def test_is_not_chief(test):
        if _is_not_chief_test(test):
            return True
        return isinstance(test, ast.UnaryOp) \
            and isinstance(test.op, ast.Not) \
            and isinstance(test.operand, ast.Name) \
            and test.operand.id in chief_names

    def walk_stmts(stmts: List[ast.stmt]):
        for i, stmt in enumerate(stmts):
            if isinstance(stmt, ast.If):
                if test_is_chief(stmt.test):
                    yield stmt.body
                elif test_is_not_chief(stmt.test):
                    if stmt.orelse:
                        yield stmt.orelse
                    if any(isinstance(s, (ast.Return, ast.Raise))
                           for s in stmt.body):
                        yield stmts[i + 1:]
                # branches may nest further gates
                yield from walk_stmts(stmt.body)
                yield from walk_stmts(stmt.orelse)
            elif isinstance(stmt, (ast.For, ast.While, ast.With,
                                   ast.Try, ast.AsyncWith, ast.AsyncFor)):
                for attr in ("body", "orelse", "finalbody", "handlers"):
                    sub = getattr(stmt, attr, None) or []
                    if attr == "handlers":
                        for h in sub:
                            yield from walk_stmts(h.body)
                    else:
                        yield from walk_stmts(sub)

    yield from walk_stmts(getattr(fn.node, "body", []))


def calls_in_statements(stmts: List[ast.stmt],
                        fn: FuncNode) -> Iterator[ast.Call]:
    """Every call in the given statements, excluding nested defs (their
    bodies only run when the nested function is itself invoked)."""
    for stmt in stmts:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        stack = [stmt]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(node, ast.Call):
                yield node
            stack.extend(ast.iter_child_nodes(node))
