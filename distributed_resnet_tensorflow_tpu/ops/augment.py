"""Device-side input augmentation — runs inside the jitted train step.

The reference augmented on the host CPU via TF ops (pad-36 → random 32-crop →
flip → per-image standardize, reference resnet_cifar_main.py:185-199,
cifar_input.py:66-75). At TPU step rates a single host core cannot feed that
pipeline (53k img/s for the CIFAR flagship), so the TPU-native design moves
augmentation into the XLA program: the host only gathers raw uint8 records
(4× smaller transfers, no float work), and the crop/flip/standardize run on
device where they cost noise next to the conv stack. RNG is
``jax.random.fold_in(seed_key, step)`` — deterministic, resume-stable, and
identical across data-parallel replicas' disjoint shards.

Semantics match the host-side numpy pipeline (data/cifar.py) op-for-op; the
random draws differ (jax vs numpy RNG), which changes nothing statistically.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def standardize(images: jax.Array) -> jax.Array:
    """Per-image standardization with TF's adjusted-std semantics:
    (x - mean) / max(std, 1/sqrt(N)) — same formula as the host path
    (data/cifar.py standardize; reference resnet_cifar_main.py:199)."""
    x = images.astype(jnp.float32)
    n = x.shape[1] * x.shape[2] * x.shape[3]
    mean = jnp.mean(x, axis=(1, 2, 3), keepdims=True)
    std = jnp.std(x, axis=(1, 2, 3), keepdims=True)
    adj = jnp.maximum(std, 1.0 / jnp.sqrt(jnp.float32(n)))
    return (x - mean) / adj


def random_crop_flip(images: jax.Array, rng: jax.Array,
                     pad: int = 4) -> jax.Array:
    """Pad H/W by ``pad``, take a per-image random crop back to the original
    size, random horizontal flip — the reference's train augmentation
    (resnet_cifar_main.py:188-198).

    Implementation is TPU-shaped: a per-image-offset crop is a gather, and
    TPU gathers with dynamic offsets serialize badly inside the scanned train
    step (measured 2.2 ms/step for CIFAR bs=128 — more than the whole
    ResNet-50 fwd+bwd). Instead the crop+flip is expressed as two one-hot
    selection matmuls that ride the MXU:

        out[b,i,j,c] = Σ_y Σ_x  R[b,i,y] · padded[b,y,x,c] · C[b,j,x]

    with R/C one-hot in the crop offset (C reversed for flipped images).
    Every output element is exactly one input element (single nonzero per
    row), so bf16 operands are exact for uint8 pixel values; ~0.1 ms/step.
    """
    b, h, w, c = images.shape
    padded = jnp.pad(images.astype(jnp.bfloat16),
                     ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    hp, wp = h + 2 * pad, w + 2 * pad
    ky, kx, kf = jax.random.split(rng, 3)
    ys = jax.random.randint(ky, (b,), 0, 2 * pad + 1)
    xs = jax.random.randint(kx, (b,), 0, 2 * pad + 1)
    flip = jax.random.bernoulli(kf, 0.5, (b,))

    # R[b,i,y] = 1 iff y == ys[b] + i  (row selector)
    iy = jax.lax.broadcasted_iota(jnp.int32, (1, h, hp), 2)
    ii = jax.lax.broadcasted_iota(jnp.int32, (1, h, hp), 1)
    rows = (iy - ii == ys[:, None, None]).astype(jnp.bfloat16)
    # C[b,j,x] = 1 iff x == xs[b] + j, with j reversed for flipped images
    jj = jnp.where(flip[:, None], (w - 1) - jnp.arange(w)[None, :],
                   jnp.arange(w)[None, :])
    ix = jax.lax.broadcasted_iota(jnp.int32, (1, w, wp), 2)
    cols = (ix == (xs[:, None] + jj)[:, :, None]).astype(jnp.bfloat16)

    tmp = jnp.einsum("biy,byxc->bixc", rows, padded,
                     preferred_element_type=jnp.float32)
    return jnp.einsum("bjx,bixc->bijc", cols, tmp,
                      preferred_element_type=jnp.float32)


@partial(jax.jit, static_argnames=("pad",))
def cifar_train_augment(images: jax.Array, rng: jax.Array,
                        pad: int = 4) -> jax.Array:
    """Full train-time pipeline for raw uint8 NHWC batches:
    crop/flip in integer space (like the host path) then standardize."""
    return standardize(random_crop_flip(images, rng, pad))


def vgg_standardize(images: jax.Array, rng: jax.Array = None) -> jax.Array:
    """ImageNet/VGG standardization on device: uint8 → x/255 − RGB means
    (reference vgg_preprocessing.py:37-39,196-227 — constant means, NOT
    per-image moments). The random crop/resize stay on the host (they
    depend on per-image source geometry); moving just this float conversion
    on-device quarters the host→HBM transfer (uint8 vs f32) and removes the
    host's per-pixel float pass — the two costs that dominate a streamed
    224² pipeline after the decode itself. Eval/serve prep; the TRAIN path
    is ``imagenet_train_augment`` (flip + standardize)."""
    del rng  # deterministic; matches the augment_fn(images, rng) contract
    from ..data.preprocessing import RGB_MEANS
    x = images.astype(jnp.float32) / 255.0
    return x - jnp.asarray(RGB_MEANS)


def random_flip(images: jax.Array, rng: jax.Array) -> jax.Array:
    """Per-image random horizontal flip (a width-reversed select — no
    gather, no matmul). Output dtype follows the input."""
    flip = jax.random.bernoulli(rng, 0.5, (images.shape[0],))
    return jnp.where(flip[:, None, None, None], images[:, :, ::-1, :],
                     images)


def imagenet_train_augment(images: jax.Array, rng: jax.Array,
                           pad: int = 0) -> jax.Array:
    """ImageNet TRAIN augmentation for raw uint8 NHWC crops, on device:
    random horizontal flip (+ optional ``pad``-pixel random-crop jitter)
    then the VGG standardize. The host decode keeps the reference's random
    resize/crop (tied to per-image source geometry) and SKIPS its flip
    when this path is active (data/imagenet.py ``device_flip``), so at
    pad=0 the train distribution is exactly the reference's
    resize → crop → flip → standardize with the flip and the float pass
    moved on device. ``pad`` > 0 (data.augment_pad) adds a CIFAR-style
    pad/crop jitter via the MXU-shaped one-hot matmuls of
    ``random_crop_flip`` — spatial diversity for echoed appearances of
    one decoded crop (data/echo.py). Draws are per appearance: the same
    staged sample augments differently every time it feeds a step, which
    is what keeps data echoing from replaying identical batches."""
    from ..data.preprocessing import RGB_MEANS
    if pad > 0:
        x = random_crop_flip(images, rng, pad)  # float32, pixel scale
    else:
        x = random_flip(images, rng).astype(jnp.float32)
    return x / 255.0 - jnp.asarray(RGB_MEANS)


def device_augment_fn(kind: str, pad: int = 0):
    """Resolve a HASHABLE device-augment spec — ``(leaf, kind, pad)`` is
    what the CoalescedStager's fused unpack (parallel/sharding.py) and the
    static elaborator cache/trace on — into the ``fn(images, rng)``
    callable. One resolution point so the fused-unpack path, the step-side
    path and the analysis gate can never disagree about what a spec
    means."""
    if kind == "imagenet_train":
        return lambda images, rng: imagenet_train_augment(images, rng, pad)
    if kind == "imagenet_eval":
        return vgg_standardize
    if kind == "cifar_train":
        return lambda images, rng: cifar_train_augment(images, rng, pad or 4)
    raise ValueError(f"unknown device augment kind {kind!r}")
