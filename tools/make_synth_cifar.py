"""Generate a structured, learnable dataset in CIFAR-10 binary format.

This environment has no network egress, so the real CIFAR-10 binaries cannot
be fetched. This tool writes a stand-in with the exact on-disk format
(reference resnet_cifar_main.py:137-154: data_batch_{1..5}.bin /
test_batch.bin, records = [1 label byte][3072 CHW bytes]) whose classes ARE
learnable — each class is a radial grating with a class-specific spatial
frequency and RGB channel mix, under heavy pixel noise and random phase —
so a truncated training run demonstrates the full
files → loader → device-dataset → augment → train → eval convergence loop.
The class signal survives the training augmentation by construction:
horizontal flips and ±4-pixel crops barely perturb a centered radial
pattern, and per-image standardization removes only mean/scale.

Swap in the real CIFAR-10 binaries and every command runs unchanged.

Usage: python tools/make_synth_cifar.py [out_dir] [--train N] [--test N]
"""
from __future__ import annotations

import argparse
import os

import numpy as np

NUM_CLASSES = 10


def class_images(cls: int, n: int, rng: np.random.RandomState) -> np.ndarray:
    """(n, 32, 32, 3) uint8 images for one class."""
    yy, xx = np.mgrid[0:32, 0:32]
    r = np.sqrt((yy - 15.5) ** 2 + (xx - 15.5) ** 2)          # (32, 32)
    freq = 0.10 + 0.018 * (cls % 5)                            # 5 frequencies
    # channel mixes: two mildly-separated triplets select the other factor
    w = np.array([[1.0, 0.5, -0.2], [0.5, 1.0, 0.2]][cls // 5])
    phase = rng.uniform(0, 2 * np.pi, size=(n, 1, 1))
    base = np.cos(2 * np.pi * freq * r[None] + phase)          # (n, 32, 32)
    img = (128.0 + 18.0 * base[..., None] * w[None, None, None, :]
           + rng.normal(0, 48.0, (n, 32, 32, 3)))
    return np.clip(img, 0, 255).astype(np.uint8)


def make_split(n: int, seed: int) -> tuple[np.ndarray, np.ndarray]:
    rng = np.random.RandomState(seed)
    per = n // NUM_CLASSES
    images = np.concatenate(
        [class_images(c, per, rng) for c in range(NUM_CLASSES)])
    labels = np.repeat(np.arange(NUM_CLASSES), per).astype(np.uint8)
    order = rng.permutation(len(labels))
    return images[order], labels[order]


def write_cifar_files(out_dir: str, images: np.ndarray, labels: np.ndarray,
                      names: list[str]) -> None:
    os.makedirs(out_dir, exist_ok=True)
    shards = np.array_split(np.arange(len(labels)), len(names))
    for name, idx in zip(names, shards):
        recs = np.empty((len(idx), 1 + 3072), np.uint8)
        recs[:, 0] = labels[idx]
        # NHWC → CHW planes, the CIFAR binary layout
        recs[:, 1:] = images[idx].transpose(0, 3, 1, 2).reshape(len(idx), -1)
        recs.tofile(os.path.join(out_dir, name))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("out_dir", nargs="?", default="/tmp/drt_synth_cifar10")
    ap.add_argument("--train", type=int, default=50000)
    ap.add_argument("--test", type=int, default=10000)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    tr_im, tr_lb = make_split(args.train, args.seed)
    te_im, te_lb = make_split(args.test, args.seed + 1)
    write_cifar_files(args.out_dir, tr_im, tr_lb,
                      [f"data_batch_{i}.bin" for i in range(1, 6)])
    write_cifar_files(args.out_dir, te_im, te_lb, ["test_batch.bin"])
    print(f"wrote {args.train} train + {args.test} test records to "
          f"{args.out_dir}")


if __name__ == "__main__":
    main()
