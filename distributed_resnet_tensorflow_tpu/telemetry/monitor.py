"""Cluster rollup: ``main.py monitor`` — what is the whole run doing NOW.

The per-process observability (metrics.jsonl event streams, heartbeat
files, flight-recorder dumps) answers post-mortem questions; an operator
mid-run needs the live aggregate: steps/s, goodput %, per-host skew, the
last committed checkpoint, serving QPS/p99. This module tails every
``metrics.jsonl`` stream under a root directory (the same shared-directory
layout the heartbeat transport and checkpoint manager already use — one
``log_root`` per host, or one shared one), merges the newest rows, and
renders either a live text dashboard or a machine-readable JSON blob:

    python -m distributed_resnet_tensorflow_tpu.main monitor --root /runs/r1
    python -m distributed_resnet_tensorflow_tpu.main monitor --root /runs/r1 \
        --once --json        # scripts / CI

Reads are tolerant by construction: a stream mid-rotation, a torn JSON
line, or a vanished heartbeat file degrade to "unknown", never to a crash —
the monitor must keep rendering exactly when the run is sickest.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import time
from typing import Dict, List, Optional

#: how much of each live stream one monitor frame reads. Every lookup the
#: rollup makes is "newest row of kind X" plus one rate pair — a bounded
#: tail covers them all, and a full-stream parse would make each refresh
#: frame of a week-long rotated run (GBs across segments) re-read
#: everything on the very filesystem the run depends on.
_TAIL_BYTES = 2 * 1024 * 1024


def _read_rows(stream_dir: str, tail_bytes: int = _TAIL_BYTES) -> List[dict]:
    """The newest rows of one metrics stream: the live file's last
    ``tail_bytes`` (partial first line dropped), prefixed by the newest
    rotated segment's tail when the live file is freshly rotated (so
    rates survive a rotation boundary). Torn lines skipped."""
    path = os.path.join(stream_dir, "metrics.jsonl")
    try:
        size = os.path.getsize(path)
    except OSError:
        return []
    paths = [(path, tail_bytes)]
    if size < tail_bytes // 8 and os.path.exists(path + ".1"):
        paths.insert(0, (path + ".1", tail_bytes // 4))
    rows: List[dict] = []
    for p, budget in paths:
        try:
            with open(p, "rb") as f:
                psize = os.fstat(f.fileno()).st_size
                if psize > budget:
                    f.seek(psize - budget)
                    f.readline()  # drop the partial first line
                data = f.read()
        except OSError:
            continue
        for line in data.decode("utf-8", errors="replace").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except ValueError:
                continue  # torn mid-write; the stream is live
    return rows


def _last(rows: List[dict], event: Optional[str]) -> Optional[dict]:
    """Newest row of a kind: ``event=None`` = newest scalar row."""
    for row in reversed(rows):
        if event is None and "event" not in row and "step" in row:
            return row
        if event is not None and row.get("event") == event:
            return row
    return None


#: scalar rows the steps/s window spans (at the log cadence this is
#: minutes of run — wide enough that one hiccup row amortizes away)
_RATE_WINDOW_ROWS = 12


def _steps_per_sec(rows: List[dict],
                   window: int = _RATE_WINDOW_ROWS) -> Optional[float]:
    """WINDOWED rate over the newest ``window`` scalar rows: endpoints
    only, so one hiccup row (an eval pause, a checkpoint, a torn write)
    moves the estimate by its share of the window instead of swinging
    the whole dashboard the way the old newest-pair rate did."""
    scalars = [r for r in rows if "event" not in r and "step" in r
               and "time" in r]
    if len(scalars) < 2:
        return None
    tail = scalars[-max(2, window):]
    # a restart resets the step counter mid-tail: rate only over the
    # monotone suffix
    suffix = [tail[-1]]
    for r in reversed(tail[:-1]):
        if r["step"] >= suffix[0]["step"] or r["time"] >= suffix[0]["time"]:
            break
        suffix.insert(0, r)
    a, b = suffix[0], suffix[-1]
    dt = b["time"] - a["time"]
    ds = b["step"] - a["step"]
    if dt <= 0 or ds <= 0:
        return None
    return ds / dt


def summarize_stream(stream_dir: str, now: Optional[float] = None) -> dict:
    """One stream's rollup (a stream = one directory holding
    metrics.jsonl, e.g. ``<log_root>/train``)."""
    now = time.time() if now is None else now
    rows = _read_rows(stream_dir)
    out: dict = {"rows": len(rows)}
    scalar = _last(rows, None)
    if scalar is not None:
        out["step"] = int(scalar["step"])
        out["age_secs"] = round(now - scalar["time"], 1)
        for key in ("loss", "precision", "eval/precision"):
            if key in scalar:
                out[key.replace("/", "_")] = round(float(scalar[key]), 4)
    rate = _steps_per_sec(rows)
    if rate is not None:
        out["steps_per_sec"] = round(rate, 3)
    gp = _last(rows, "goodput")
    if gp is not None and "pct" in gp:
        out["goodput_pct"] = gp["pct"].get("compute")
        out["goodput"] = gp["pct"]
    strag = _last(rows, "straggler")
    if strag is not None:
        out["lag_steps"] = strag.get("lag_steps")
        out["stragglers_flagged"] = strag.get("flagged")
    hb = _last(rows, "heartbeat")
    if hb is not None:
        out["heartbeat_hosts"] = {
            pid: {"step": h.get("step"), "phase": h.get("phase"),
                  "host": h.get("host")}
            for pid, h in (hb.get("hosts") or {}).items()}
    sr = _last(rows, "serve_request")
    if sr is not None:
        out["serve"] = {"requests": sr.get("requests"),
                        "dropped": sr.get("dropped"),
                        "buckets": sr.get("buckets")}
    sb = _last(rows, "serve_batch")
    if sb is not None:
        out.setdefault("serve", {})["last_batch"] = {
            "bucket": sb.get("bucket"), "n": sb.get("n"),
            "run_ms": sb.get("run_ms")}
    # fleet front door (serve/router.py): the route stream's periodic
    # rollup row plus the newest canary / shed / replace events — enough
    # to render the fleet line without re-deriving router state
    rt = _last(rows, "route")
    if rt is not None:
        out["route"] = {
            "requests": rt.get("requests"),
            "completed": rt.get("completed"),
            "errors": rt.get("errors"), "shed": rt.get("shed"),
            "degraded": rt.get("degraded"), "hedges": rt.get("hedges"),
            "retries": rt.get("retries"), "qps": rt.get("qps"),
            "p99_ms": rt.get("p99_ms"),
            "replicas": rt.get("replicas"),
            "age_secs": round(now - rt.get("time", now), 1)}
    cn = _last(rows, "canary")
    if cn is not None:
        out["canary"] = {
            "action": cn.get("action"), "step": cn.get("step"),
            "from_step": cn.get("from_step"), "canary": cn.get("canary"),
            "rollback": cn.get("rollback"), "reason": cn.get("reason")}
    sh = _last(rows, "shed")
    if sh is not None:
        out["shed"] = {"count": sh.get("count"),
                       "degraded": sh.get("degraded"),
                       "est_queue_ms": sh.get("est_queue_ms")}
    rr = _last(rows, "replica_replace")
    if rr is not None:
        out["replica_replace"] = {
            "replica": rr.get("replica"), "action": rr.get("action"),
            "reason": rr.get("reason")}
    dump = _last(rows, "trace_dump")
    if dump is not None:
        out["trace_dump"] = {"reason": dump.get("reason"),
                             "path": dump.get("path")}
    cs = _last(rows, "ckpt_shard")
    if cs is not None:
        out["ckpt_shard"] = {
            "process": cs.get("process"),
            "shard_bytes": cs.get("shard_bytes"),
            "shard_files": cs.get("shard_files"),
            "shard_seconds": cs.get("shard_seconds"),
            "last_committed_step": cs.get("last_committed_step")}
    z1 = _last(rows, "zero1")
    if z1 is not None:
        out["zero1"] = {
            "data_shards": z1.get("data_shards"),
            "bytes_per_replica": z1.get("bytes_per_replica"),
            "bytes_per_replica_unsharded":
                z1.get("bytes_per_replica_unsharded")}
    cr = _last(rows, "corrupt_record")
    if cr is not None:
        out["corrupt_records"] = cr.get("count")
    mg = _last(rows, "mesh_generation")
    if mg is not None:
        out["mesh_generation"] = {
            "generation": mg.get("generation"),
            "hosts": mg.get("hosts"),
            "devices": mg.get("devices"),
            "step": mg.get("step")}
    rs = _last(rows, "reshard")
    if rs is not None:
        out["reshard"] = {
            "generation": rs.get("generation"),
            "reason": rs.get("reason"),
            "old_hosts": rs.get("old_hosts"),
            "new_hosts": rs.get("new_hosts"),
            "restore_step": rs.get("restore_step"),
            "age_secs": round(now - rs.get("time", now), 1)}
    mem = _last(rows, "memory")
    if mem is not None:
        out["memory"] = _memory_summary(mem)
    return out


def _memory_summary(row: dict) -> dict:
    """One memory row folded to the rollup's per-host shape: the worst
    device's watermark (allocator ``peak_bytes_in_use`` where the backend
    reports it — authoritative — else the sampled live-array peak) plus
    its limit when known, host RSS, and the pipeline-pool occupancy."""
    peak = limit = None
    for cell in (row.get("devices") or {}).values():
        p = cell.get("peak_bytes_in_use", cell.get("live_peak_bytes"))
        if p is not None:
            peak = max(peak or 0, int(p))
        if cell.get("bytes_limit"):
            limit = max(limit or 0, int(cell["bytes_limit"]))
    out = {"process": row.get("process")}
    for key in ("live_bytes_total", "live_peak_bytes_total",
                "host_rss_bytes", "host_peak_rss_bytes",
                "echo_cache_bytes", "staging_ring_inflight"):
        if row.get(key) is not None:
            out[key] = row[key]
    if peak is not None:
        out["device_peak_bytes"] = peak
    if limit:
        out["device_bytes_limit"] = limit
        if peak is not None:
            out["device_peak_frac"] = round(peak / limit, 4)
    return out


def _beat_files(root: str) -> List[str]:
    return sorted(glob.glob(os.path.join(root, "**", "proc*.json"),
                            recursive=True))


def _read_beats(root: str, now: float) -> Dict[str, dict]:
    """Per-process latest beat across every heartbeat dir under root —
    the same files resilience/heartbeat.FileBeatTransport exchanges."""
    out: Dict[str, dict] = {}
    for path in _beat_files(root):
        if "heartbeats" not in os.path.dirname(path):
            continue
        try:
            with open(path) as f:
                beat = json.load(f)
        except (OSError, ValueError):
            continue
        pid = str(beat.get("process_id", "?"))
        prev = out.get(pid)
        if prev is None or beat.get("wall_time", 0) > prev.get("wall_time", 0):
            beat["age_secs"] = round(now - beat.get("wall_time", now), 1)
            out[pid] = beat
    return out


def _checkpoint_step(root: str) -> Optional[int]:
    """Newest committed step of any ``ckpt`` directory under root."""
    from ..resilience.manifest import committed_steps
    newest: Optional[int] = None
    for d in glob.glob(os.path.join(root, "**", "ckpt"), recursive=True) \
            + [os.path.join(root, "ckpt")]:
        try:
            steps = committed_steps(d)
        except OSError:
            continue
        if steps:
            newest = steps[-1] if newest is None else max(newest, steps[-1])
    return newest


#: per-host device-memory watermark share of the limit that flags in the
#: dashboard (where the backend reports bytes_limit); --hbm-warn-frac
_HBM_WARN_FRAC = 0.9


def aggregate(root: str, now: Optional[float] = None,
              hbm_warn_frac: float = _HBM_WARN_FRAC) -> dict:
    """The whole-run rollup: every metrics stream under ``root``, the
    heartbeat fleet, the newest committed checkpoint."""
    now = time.time() if now is None else now
    root = os.path.abspath(root)
    from ..utils.metrics import metric_stream_dirs
    streams: Dict[str, dict] = {}
    for d in metric_stream_dirs(root):
        rel = os.path.relpath(d, root)
        if rel in streams:
            continue
        streams[rel] = summarize_stream(d, now=now)
    beats = _read_beats(root, now)
    out: dict = {"root": root, "time": now, "streams": streams}
    if beats:
        out["hosts"] = beats
        steps = [b.get("step", 0) for b in beats.values()]
        if steps:
            out["host_step_skew"] = max(steps) - min(steps)
        stale = [pid for pid, b in beats.items()
                 if b.get("age_secs", 0) > 60
                 and b.get("phase") not in ("done", "preempted", "failed",
                                            "reshard")]
        if stale:
            out["stale_hosts"] = stale
        # elastic fleet shape: the beats carry the mesh generation each
        # process is currently stepping in (resilience/heartbeat.py);
        # the live count excludes departed phases
        gens = [b.get("generation") for b in beats.values()
                if b.get("generation") is not None]
        if gens:
            out["mesh_generation"] = max(gens)
            out["live_hosts"] = sum(
                1 for b in beats.values()
                if b.get("generation") == out["mesh_generation"]
                and b.get("phase") not in ("done", "preempted", "failed",
                                           "reshard"))
    ckpt = _checkpoint_step(root)
    if ckpt is not None:
        out["last_committed_step"] = ckpt
    # per-host sharded-checkpoint rollup: each process's ckpt_shard rows
    # (chief in its train stream, peers in train-p<idx>) sum to the
    # cluster's staged shard bytes — the number that shows host-balanced
    # sharded saves are actually host-balanced
    shard_hosts = {name: s["ckpt_shard"] for name, s in streams.items()
                   if "ckpt_shard" in s}
    if shard_hosts:
        by_host = {}
        for row in shard_hosts.values():
            pid = str(row.get("process", "?"))
            prev = by_host.get(pid)
            if prev is None or (row.get("shard_bytes") or 0) > \
                    (prev.get("shard_bytes") or 0):
                by_host[pid] = row
        out["ckpt_shard_bytes_by_host"] = {
            pid: row.get("shard_bytes") for pid, row in
            sorted(by_host.items())}
        out["ckpt_shard_bytes_total"] = sum(
            row.get("shard_bytes") or 0 for row in by_host.values())
    # per-host device-memory watermark: each process samples its OWN
    # devices (chief in its train stream, peers in train-p<idx>), so the
    # per-pid max over streams IS the cluster's HBM picture — the trend
    # an OOM used to be the first sign of. A colocated serving replica
    # is a DIFFERENT process with the same jax.process_index(); it gets
    # its own "<pid>/serve" entry rather than shadowing (or being
    # shadowed by) the trainer's watermark
    mem_by_host: Dict[str, dict] = {}
    for name, s in streams.items():
        m = s.get("memory")
        if m is None:
            continue
        pid = str(m.get("process", "?"))
        if os.path.basename(name).startswith("serve"):
            pid = f"{pid}/serve"
        prev = mem_by_host.get(pid)
        if prev is None or (m.get("device_peak_bytes") or 0) > \
                (prev.get("device_peak_bytes") or 0):
            mem_by_host[pid] = m
    if mem_by_host:
        out["memory_by_host"] = {
            pid: m for pid, m in sorted(mem_by_host.items())}
        warn = sorted(
            pid for pid, m in mem_by_host.items()
            if m.get("device_peak_frac") is not None
            and m["device_peak_frac"] >= hbm_warn_frac)
        if warn:
            out["hbm_warn_frac"] = hbm_warn_frac
            out["hbm_warn_hosts"] = warn
    # fleet front door rollup: the route stream carries the router's own
    # periodic row (per-replica health snapshot included), and the same
    # stream's newest canary/shed/replace events ride along — the
    # operator's one-glance answer to "is the fleet healthy, is a
    # rollout in flight, are we shedding"
    fleets = {name: s["route"] for name, s in streams.items()
              if "route" in s}
    if fleets:
        lead_fleet = max(fleets,
                         key=lambda n: fleets[n].get("requests") or 0)
        fleet = dict(fleets[lead_fleet])
        fleet["stream"] = lead_fleet
        for key in ("canary", "shed", "replica_replace"):
            if key in streams[lead_fleet]:
                fleet[key] = streams[lead_fleet][key]
        out["fleet"] = fleet
    # headline: the fastest train-shaped stream is the chief's
    rates = {name: s["steps_per_sec"] for name, s in streams.items()
             if "steps_per_sec" in s}
    if rates:
        lead = max(rates, key=rates.get)
        out["steps_per_sec"] = rates[lead]
        out["lead_stream"] = lead
    for name, s in streams.items():
        if "goodput" in s:
            out.setdefault("goodput", s["goodput"])
            break
    # newest reshard / mesh_generation event rows across streams (the
    # chief emits them; a fresh generation may write to a new stream)
    for key, field in (("last_reshard", "reshard"),
                       ("mesh_generation_event", "mesh_generation")):
        rows = [s[field] for s in streams.values() if field in s]
        if rows:
            out[key] = max(rows, key=lambda r: r.get("generation") or 0)
            if "mesh_generation" not in out and \
                    out[key].get("generation") is not None:
                out["mesh_generation"] = out[key]["generation"]
    return out


def render(agg: dict) -> str:
    """Human-readable dashboard frame."""
    lines = [f"== drt monitor :: {agg['root']} :: "
             f"{time.strftime('%H:%M:%S', time.localtime(agg['time']))} =="]
    if "steps_per_sec" in agg:
        lines.append(f"  steps/s: {agg['steps_per_sec']:.3f} "
                     f"({agg.get('lead_stream')})")
    if "goodput" in agg:
        gp = agg["goodput"]
        lines.append("  goodput: " + "  ".join(
            f"{c} {gp.get(c, 0):.1f}%" for c in
            ("compute", "input_wait", "checkpoint", "eval", "stall",
             "restart", "reshard") if gp.get(c)))
    if "mesh_generation" in agg:
        bits = [f"  elastic: generation {agg['mesh_generation']}"]
        if "live_hosts" in agg:
            bits.append(f"{agg['live_hosts']} live host(s)")
        rs = agg.get("last_reshard")
        if rs:
            bits.append(
                f"last reshard {rs.get('reason')} "
                f"{rs.get('old_hosts')}->{rs.get('new_hosts')} hosts "
                f"(restore step {rs.get('restore_step')}, "
                f"{rs.get('age_secs', '?')}s ago)")
        lines.append(", ".join(bits))
    if "last_committed_step" in agg:
        lines.append(f"  checkpoint: step {agg['last_committed_step']} "
                     "committed")
    if "fleet" in agg:
        fl = agg["fleet"]
        reps = fl.get("replicas") or {}
        states = " ".join(
            f"r{rid}:{(cell or {}).get('state', '?')}"
            f"@{(cell or {}).get('step', '?')}"
            for rid, cell in sorted(reps.items()))
        bits = ["  fleet:"]
        if fl.get("qps") is not None:
            bits.append(f"qps {fl['qps']:.1f}")
        if fl.get("p99_ms") is not None:
            bits.append(f"p99 {fl['p99_ms']:.0f}ms")
        bits.append(f"errors {fl.get('errors', 0)}")
        bits.append(f"shed {fl.get('shed', 0)}")
        bits.append(f"degraded {fl.get('degraded', 0)}")
        bits.append(f"hedges {fl.get('hedges', 0)}")
        lines.append(" ".join(bits) + f" | {states}")
        cn = fl.get("canary")
        if cn:
            verdict = ("ROLLED BACK" if cn.get("rollback")
                       else cn.get("action"))
            lines.append(
                f"  canary: {verdict} step {cn.get('step')} "
                f"(from {cn.get('from_step')}) on {cn.get('canary')} "
                f"reason {cn.get('reason', '-')}")
        rr = fl.get("replica_replace")
        if rr:
            lines.append(
                f"  replace: replica {rr.get('replica')} "
                f"{rr.get('action')} ({rr.get('reason')})")
    if "ckpt_shard_bytes_total" in agg:
        per_host = agg.get("ckpt_shard_bytes_by_host", {})
        mb = agg["ckpt_shard_bytes_total"] / 1e6
        lines.append(
            f"  ckpt shards: {mb:.1f} MB staged across "
            f"{len(per_host)} host(s) " + " ".join(
                f"p{pid}:{(b or 0) / 1e6:.1f}MB"
                for pid, b in per_host.items()))
    if "memory_by_host" in agg:
        bits = []
        for pid, m in agg["memory_by_host"].items():
            peak = m.get("device_peak_bytes",
                         m.get("live_peak_bytes_total"))
            cell = f"p{pid}:{(peak or 0) / 1e9:.2f}GB"
            if m.get("device_peak_frac") is not None:
                cell += f"({m['device_peak_frac'] * 100:.0f}%)"
            bits.append(cell)
        lines.append("  hbm watermark (per-host device peak): "
                     + " ".join(bits))
        if agg.get("hbm_warn_hosts"):
            lines.append(
                f"  !! hbm above {agg['hbm_warn_frac'] * 100:.0f}% of "
                f"limit on host(s): {agg['hbm_warn_hosts']}")
    if "hosts" in agg:
        lines.append(f"  hosts ({len(agg['hosts'])}; "
                     f"skew {agg.get('host_step_skew', 0)} steps):")
        for pid, b in sorted(agg["hosts"].items()):
            lines.append(
                f"    proc{pid} {b.get('host', '?')}: step "
                f"{b.get('step', '?')} phase {b.get('phase', '?')} "
                f"(beat {b.get('age_secs', '?')}s ago)")
    if agg.get("stale_hosts"):
        lines.append(f"  !! stale hosts: {agg['stale_hosts']}")
    for name, s in sorted(agg["streams"].items()):
        bits = [f"  [{name}]"]
        if "step" in s:
            bits.append(f"step {s['step']}")
        if "steps_per_sec" in s:
            bits.append(f"{s['steps_per_sec']:.3f} st/s")
        for k in ("loss", "precision", "eval_precision"):
            if k in s:
                bits.append(f"{k} {s[k]}")
        if "serve" in s:
            srv = s["serve"]
            bits.append(f"serve req {srv.get('requests')} "
                        f"dropped {srv.get('dropped')}")
        if "trace_dump" in s:
            bits.append(f"TRACE DUMPED ({s['trace_dump'].get('reason')})")
        if "corrupt_records" in s:
            bits.append(f"corrupt_records {s['corrupt_records']}")
        lines.append(" ".join(bits))
    return "\n".join(lines)


def main_monitor(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="main.py monitor",
        description="live cluster rollup over a run's log_root")
    ap.add_argument("--root", default="/tmp/drt_tpu",
                    help="the run's log_root (shared directory)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit")
    ap.add_argument("--json", action="store_true",
                    help="emit the aggregate as JSON instead of text")
    ap.add_argument("--interval", type=float, default=5.0,
                    help="refresh cadence in seconds (live mode)")
    ap.add_argument("--hbm-warn-frac", type=float, default=_HBM_WARN_FRAC,
                    help="flag hosts whose device watermark exceeds this "
                         "share of the reported bytes_limit")
    ap.add_argument("--bench", action="store_true",
                    help="render the cross-round bench trajectory "
                         "(tools/bench_trajectory.py over the repo's "
                         "BENCH_r*.json) instead of the live rollup")
    ns = ap.parse_args(argv)
    if ns.bench:
        # the joiner is a stdlib-only standalone script (it must run
        # without jax); load it by path from the repo checkout
        import importlib.util
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        script = os.path.join(repo_root, "tools", "bench_trajectory.py")
        if not os.path.exists(script):
            print(f"monitor --bench: {script} not found (not running "
                  "from a source checkout?)")
            return 1
        spec = importlib.util.spec_from_file_location(
            "bench_trajectory", script)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        extra = ["--json"] if ns.json else []
        return mod.main(extra)
    try:
        while True:
            agg = aggregate(ns.root, hbm_warn_frac=ns.hbm_warn_frac)
            print(json.dumps(agg) if ns.json else render(agg), flush=True)
            if ns.once:
                return 0
            time.sleep(max(0.2, ns.interval))
    except KeyboardInterrupt:
        return 0
