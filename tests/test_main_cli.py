"""CLI entry-point tests — the one binary replacing the reference's six mains
(SURVEY.md §1 L3)."""
import os

import pytest

import numpy as np

from distributed_resnet_tensorflow_tpu import main as main_mod


def _args(tmp_path, *extra):
    return ["--preset", "smoke",
            "--set", "model.compute_dtype=float32",
            "--set", "model.resnet_size=8",
            "--set", "data.image_size=8",
            "--set", "train.batch_size=16",
            "--set", f"log_root={tmp_path}",
            "--set", f"checkpoint.directory={tmp_path}/ckpt",
            "--set", "checkpoint.async_save=false",
            *extra]


@pytest.mark.heavy
def test_main_train_mode(tmp_path, capsys):
    main_mod.main(_args(
        tmp_path,
        "--set", "train.train_steps=4",
        "--set", "train.log_every_steps=2",
        "--set", "checkpoint.save_every_steps=2",
        "--set", "checkpoint.save_every_secs=0",
    ))
    out = capsys.readouterr().out
    assert "step 2" in out and "step 4" in out
    # checkpoints + metrics written
    assert os.path.isdir(os.path.join(tmp_path, "ckpt"))
    assert os.path.exists(os.path.join(tmp_path, "train", "metrics.jsonl"))


@pytest.mark.slow  # re-tiered out of the 870s tier-1; runs in the full (unfiltered) suite
@pytest.mark.heavy
def test_main_train_and_eval_mode(tmp_path, capsys):
    main_mod.main(_args(
        tmp_path,
        "--set", "mode=train_and_eval",
        "--set", "train.train_steps=4",
        "--set", "train.eval_every_steps=2",
        "--set", "eval.eval_batch_count=1",
        "--set", "checkpoint.save_every_steps=2",
        "--set", "checkpoint.save_every_secs=0",
    ))
    out = capsys.readouterr().out
    assert "eval @ step 2" in out and "eval @ step 4" in out


@pytest.mark.heavy
def test_main_eval_once_mode(tmp_path):
    # first train + checkpoint...
    main_mod.main(_args(
        tmp_path,
        "--set", "train.train_steps=2",
        "--set", "checkpoint.save_every_steps=2",
        "--set", "checkpoint.save_every_secs=0",
    ))
    # ...then one-shot evaluation against the written checkpoint
    main_mod.main(_args(
        tmp_path,
        "--set", "mode=eval",
        "--set", "eval.eval_once=true",
        "--set", "eval.eval_batch_count=1",
    ))
    import json
    path = os.path.join(tmp_path, "eval", "metrics.jsonl")
    recs = [json.loads(l) for l in open(path) if l.strip()]
    assert recs and "eval/precision" in recs[-1]
    assert "eval/best_precision" in recs[-1]


@pytest.mark.slow  # re-tiered out of the 870s tier-1; runs in the full (unfiltered) suite
@pytest.mark.heavy
def test_replay_reference_smoke(tmp_path, monkeypatch):
    """tools/replay_reference.py --smoke runs the full recipe machinery
    (preset -> train -> checkpoint -> full-set eval -> report) end to end
    on synthetic stand-in data — the proof the one-command real-data
    replication path works before real data is reachable."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import replay_reference
    report = replay_reference.main(
        ["--dataset", "cifar10", "--smoke",
         "--log_root", str(tmp_path / "replay")])
    assert report["dataset"] == "cifar10"
    assert report["eval_images"] == 200  # the FULL synthetic test split
    assert 0.0 <= report["top1"] <= 1.0
    assert os.path.exists(str(tmp_path / "replay" / "replay_report.md"))


def test_main_mode_dispatch_fast():
    """Quick-tier coverage of the main.py entry (the mode-specific paths
    are heavy-tier): arg parsing + config wiring + the mode dispatch
    rejection, no training compiled."""
    from distributed_resnet_tensorflow_tpu import main as main_mod
    with pytest.raises(ValueError, match="unknown mode"):
        main_mod.main(["--preset", "smoke", "--set", "mode=bogus"])


@pytest.mark.slow  # re-tiered out of the 870s tier-1; runs in the full (unfiltered) suite
@pytest.mark.heavy
def test_resume_config_mismatch_warns(tmp_path, caplog):
    """Resuming a checkpoint dir under a different training recipe warns
    loudly (shape-identical configs restore silently otherwise — e.g. the
    gbs=128 vs gbs=512 presets); benign continuation knobs (train_steps,
    cadences) stay silent."""
    import logging
    from distributed_resnet_tensorflow_tpu.main import run_train
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    def cfg_for(steps, lr):
        cfg = get_preset("smoke")
        cfg.log_root = str(tmp_path)
        cfg.train.train_steps = steps
        cfg.train.batch_size = 16  # divisible over the 8-device test mesh
        cfg.optimizer.learning_rate = lr
        cfg.checkpoint.save_every_steps = 2
        cfg.checkpoint.save_every_secs = 0.0
        return cfg

    run_train(cfg_for(2, 0.1))
    with caplog.at_level(logging.WARNING):
        run_train(cfg_for(4, 0.1))  # benign: just more steps
    assert not [r for r in caplog.records
                if "DIFFERENT config" in r.message]
    with caplog.at_level(logging.WARNING):
        run_train(cfg_for(6, 0.05))  # recipe change: lr
    warns = [r for r in caplog.records if "DIFFERENT config" in r.message]
    assert warns and "learning_rate" in warns[0].message
