"""Sequence-parallel attention measurements (VERDICT r3 #4).

Two surfaces, one artifact (docs/ring_attention_r4.json):

  * ``--tpu`` (default): the ring's INNER BLOCK on the real chip — the
    blockwise online-softmax recurrence (exactly what each ring step
    executes between ppermutes) timed fwd+bwd against the Pallas flash
    kernel and dense XLA attention, causal bf16, 8k-32k tokens. The r4
    change under test: QK/PV matmuls in bf16 with fp32 accumulation
    (preferred_element_type) instead of the r3 fp32-upcast inner.
  * ``--mesh``: ring_attention_sharded over the virtual 8-device CPU
    seq mesh vs the identical computation single-device — proves the
    sequence-parallel path and measures its collective overhead
    structure (CPU wall-clock; no multi-chip TPU exists here).

    python tools/bench_ring_attention.py --tpu
    python tools/bench_ring_attention.py --mesh   # separate process (CPU)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "docs", "ring_attention_r4.json")


def _merge(update: dict) -> None:
    data = {}
    if os.path.exists(OUT):
        with open(OUT) as f:
            data = json.load(f)
    data.update(update)
    with open(OUT, "w") as f:
        json.dump(data, f, indent=2)
    print(f"wrote {OUT}")


def bench_tpu():
    import jax
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    import jax.numpy as jnp
    import numpy as np
    from bench import attention_grad_ms
    from distributed_resnet_tensorflow_tpu.ops.attention import (
        attention, blockwise_attention)
    from distributed_resnet_tensorflow_tpu.ops.pallas import flash_attention

    rng = np.random.RandomState(0)
    out = {"device": jax.devices()[0].device_kind, "rows": {}}
    for t, h in ((8192, 4), (16384, 2), (32768, 1)):
        q, k, v = (jnp.asarray(rng.randn(1, t, h, 64).astype(np.float32))
                   .astype(jnp.bfloat16) for _ in range(3))
        row = {}
        row["blockwise_grad_ms"] = round(attention_grad_ms(
            lambda q, k, v: blockwise_attention(q, k, v, causal=True),
            q, k, v, iters=6), 2)
        row["flash_grad_ms"] = round(attention_grad_ms(
            lambda q, k, v: flash_attention(q, k, v, True, False),
            q, k, v, iters=6), 2)
        if t <= 16384:  # dense O(T²) memory collapses beyond
            row["dense_grad_ms"] = round(attention_grad_ms(
                lambda q, k, v: attention(q, k, v, causal=True),
                q, k, v, iters=6), 2)
        row["blockwise_vs_flash"] = round(
            row["blockwise_grad_ms"] / row["flash_grad_ms"], 2)
        out["rows"][f"T{t}"] = row
        print(f"T{t}: {row}", flush=True)
    _merge({"tpu_inner": out})


def bench_mesh():
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from distributed_resnet_tensorflow_tpu.ops.attention import (
        blockwise_attention, ring_attention_sharded)
    from distributed_resnet_tensorflow_tpu.parallel import create_mesh
    from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig

    mesh = create_mesh(MeshConfig(sequence=8))
    rng = np.random.RandomState(0)
    t = 8192
    q, k, v = (jnp.asarray(rng.randn(1, t, 4, 64).astype(np.float32))
               for _ in range(3))

    def ring_loss(q, k, v):
        return (ring_attention_sharded(q, k, v, mesh, causal=True)
                .astype(jnp.float32) ** 2).sum()

    def single_loss(q, k, v):
        return (blockwise_attention(q, k, v, block_size=t // 8, causal=True)
                .astype(jnp.float32) ** 2).sum()

    sh = NamedSharding(mesh, P(None, "seq", None, None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    ring_g = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))
    single_g = jax.jit(jax.grad(single_loss, argnums=(0, 1, 2)))

    # correctness first: sharded ring == single-device recurrence
    gr = ring_g(qs, ks, vs)
    gs_ = single_g(q, k, v)
    max_diff = max(float(jnp.max(jnp.abs(a - b))) for a, b in zip(gr, gs_))

    def best_ms(fn, args, reps=3):
        jax.block_until_ready(fn(*args))
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            best = min(best, (time.perf_counter() - t0) * 1e3)
        return round(best, 1)

    out = {
        "tokens": t, "seq_devices": 8,
        "grad_max_abs_diff_vs_single": max_diff,
        "ring_grad_ms": best_ms(ring_g, (qs, ks, vs)),
        "single_grad_ms": best_ms(single_g, (q, k, v)),
        "note": "virtual CPU mesh: structure/correctness; per-device "
                "compute is 1/8 but one host core executes all 8",
    }
    print(out, flush=True)
    _merge({"virtual_mesh_ring": out})


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--mesh", action="store_true")
    ap.add_argument("--tpu", action="store_true")
    args = ap.parse_args()
    if args.mesh:
        bench_mesh()
    else:
        bench_tpu()
