#!/bin/bash
# Fleet front door smoke — the end-to-end proof of the routed serving
# tier (serve/router.py + serve/fleet.py; docs/serving.md fleet section),
# pre-merge usable like scripts/serve_smoke.sh: exit 0 = the whole story
# holds, nonzero = broken. One routed run carries BOTH chaos legs:
#
#   1. train 2 steps -> committed checkpoint step 2;
#   2. start `main.py route`: 3 serving replicas behind the router,
#      open-loop load, with a seeded p99-regression fault armed for any
#      replica that reaches checkpoint step 4
#      (DRT_FAULT_SERVE_SLOW_MS=250@4 — resilience/faultinject.py);
#   3. SIGKILL one replica mid-load: hedged retries keep client errors
#      bounded while the watchdog drains -> respawns -> readmits it;
#   4. resume training to step 4 mid-load: the router canaries the new
#      checkpoint onto a fraction of the fleet, the fault makes exactly
#      those replicas slow, and the canary AUTO-ROLLS-BACK — the bad
#      step never reaches a baseline replica.
#
#   scripts/serve_fleet_smoke.sh [workdir]   # default: fresh mktemp dir
#
# Runs in ~4-6 minutes on CPU (three replica jax processes + two short
# training processes).
set -euo pipefail
cd "$(dirname "$0")/.."

ROOT="${1:-$(mktemp -d /tmp/drt_fleet_smoke.XXXXXX)}"
echo "fleet smoke workdir: $ROOT"

# seconds-fast shardcheck first (serve_smoke.sh pattern): spec bugs die
# here, not three minutes into a fleet warm-up
scripts/analysis_gate.sh --preset smoke

SHRINK=(--preset smoke
        --set model.resnet_size=8 --set model.compute_dtype=float32
        --set data.image_size=8 --set train.batch_size=16
        --set data.eval_batch_size=16
        --set "log_root=$ROOT" --set "checkpoint.directory=$ROOT/ckpt"
        --set checkpoint.async_save=false
        --set checkpoint.save_every_secs=0
        --set checkpoint.save_every_steps=2)

# 1) train 2 steps -> committed checkpoint step 2 (the fleet's pin)
env JAX_PLATFORMS=cpu python -m distributed_resnet_tensorflow_tpu.main \
  "${SHRINK[@]}" --set train.train_steps=2

# 2) the routed fleet under open-loop load, p99-regression fault armed
# for step 4 (fleet-wide env: only replicas that SWAP to step 4 — the
# canary fraction — ever become slow; baselines stay pinned at 2)
env JAX_PLATFORMS=cpu DRT_FAULT_SERVE_SLOW_MS="250@4" \
  python -m distributed_resnet_tensorflow_tpu.main \
  route "${SHRINK[@]}" \
  --set route.replicas=3 \
  --set route.load_qps=20 --set route.load_duration_secs=90 \
  --set route.health_interval_secs=0.5 --set route.watch_interval_secs=0.5 \
  --set route.row_interval_secs=2 \
  --set route.hedge_ms=300 --set route.attempt_timeout_ms=3000 \
  --set route.replica_grace_secs=2 \
  --set route.canary_window_secs=10 --set route.canary_min_samples=10 \
  --set serve.max_queue_delay_ms=10 --set serve.poll_interval_secs=0.5 \
  > "$ROOT/route_report.json" &
ROUTE_PID=$!

# wait for the router's READY marker (all replicas warm behind it)
for _ in $(seq 1 600); do
  [[ -f "$ROOT/route/READY" ]] && break
  kill -0 "$ROUTE_PID" 2>/dev/null || { echo "route process died during startup"; exit 1; }
  sleep 0.5
done
[[ -f "$ROOT/route/READY" ]] || { echo "router never became ready"; kill "$ROUTE_PID"; exit 1; }

# 3) SIGKILL replica 0 mid-load (pid from its READY marker, read BEFORE
# the respawn rewrites it)
sleep 3
R0_PID=$(python -c "import json,sys; print(json.load(open(sys.argv[1]))['pid'])" \
  "$ROOT/serve-r0/READY")
echo "fleet smoke: SIGKILL replica 0 (pid $R0_PID)"
kill -9 "$R0_PID"

# 4) publish checkpoint step 4 mid-load: resume training (the canary
# target; the armed fault makes exactly the replicas serving it slow)
env JAX_PLATFORMS=cpu python -m distributed_resnet_tensorflow_tpu.main \
  "${SHRINK[@]}" --set train.train_steps=4

wait "$ROUTE_PID"

# 5) assertions over the route report + the route / replica streams
python - "$ROOT" <<'EOF'
import json, os, sys
root = sys.argv[1]
rep = json.loads(open(os.path.join(root, "route_report.json"))
                 .read().strip().splitlines()[-1])
router, load = rep["router"], rep["load"]

# bounded client damage: a SIGKILLed replica costs at most a handful of
# requests (hedge + retry absorb the rest), and the run drains fully
assert load["offered"] > 500, f"load never ramped: {load}"
assert load["failed"] + router["errors"] <= 5, \
    f"client errors not bounded: {load} {router}"
assert load["unresolved"] == 0, f"undrained requests: {load}"

events = [json.loads(l) for l in
          open(os.path.join(root, "route", "metrics.jsonl")) if l.strip()]
by = lambda kind: [e for e in events if e.get("event") == kind]

# the watchdog replaced replica 0: kill -> respawn -> readmit rows
acts = {e["action"] for e in by("replica_replace") if e.get("replica") == 0}
assert {"kill", "respawn", "readmit"} <= acts, \
    f"replica 0 was not replaced end-to-end: {sorted(acts)}"
assert rep["fleet"]["replaces"] >= 1, rep["fleet"]

# QPS recovered: route rollup rows kept flowing and the fleet ended with
# every replica routable again
assert by("route"), "no route rollup rows"
last_replicas = by("route")[-1]["replicas"]
ready = [r for r, cell in last_replicas.items()
         if cell.get("state") in ("ready", "degraded")]
assert len(ready) == 3, f"fleet did not recover: {last_replicas}"

# the canary started on step 4 and auto-rolled-back on the seeded p99
# regression; the step is remembered bad and the fleet stayed on 2
starts = [e for e in by("canary") if e.get("action") == "start"
          and e.get("step") == 4]
rollbacks = [e for e in by("canary") if e.get("rollback")
             and e.get("step") == 4]
assert starts, "no canary ever started for step 4"
assert rollbacks, f"canary for step 4 did not roll back: {by('canary')}"
assert rollbacks[-1].get("reason") in ("p99_regression",
                                       "confidence_regression"), rollbacks
assert router["fleet_step"] == 2, f"fleet left step 2: {router}"
assert 4 in router["bad_steps"], router

# the bad step NEVER reached a baseline replica: only the canary set may
# show a swap to (or a batch at) step 4
canary_ids = {int(r) for e in starts for r in e["canary"]}
assert canary_ids, starts
for rid in range(3):
    stream = os.path.join(root, f"serve-r{rid}", "metrics.jsonl")
    rows = [json.loads(l) for l in open(stream) if l.strip()]
    hit4 = [r for r in rows
            if (r.get("event") == "serve_swap" and r.get("to_step") == 4)
            or (r.get("event") == "serve_batch" and r.get("step") == 4)]
    if rid not in canary_ids and hit4:
        raise AssertionError(
            f"baseline replica {rid} served unvalidated step 4: {hit4[:2]}")

print("fleet smoke OK:", json.dumps({
    "offered": load["offered"], "failed": load["failed"],
    "errors": router["errors"], "hedges": router["hedges"],
    "replaces": rep["fleet"]["replaces"],
    "canary_rollback_reason": rollbacks[-1].get("reason"),
    "fleet_step": router["fleet_step"]}))
EOF

# 6) protocol trace conformance (analysis/protocol/, docs/
# static_analysis.md): every recorded replica_health / replica_replace /
# canary row must be an edge the DECLARED state machines allow — the
# chaos run above doubles as a protocol-conformance witness. Then the
# witness-can-fail leg: a seeded dead->ready health edge must be caught
# (exit 0 = caught), so a silently-vacuous replayer fails the smoke.
env JAX_PLATFORMS=cpu python -m \
  distributed_resnet_tensorflow_tpu.analysis.protocol.conformance \
  "$ROOT/route/metrics.jsonl" "$ROOT"/serve-r*/metrics.jsonl
env JAX_PLATFORMS=cpu python -m \
  distributed_resnet_tensorflow_tpu.analysis.protocol.conformance \
  --self-test-illegal-edge "$ROOT/route/metrics.jsonl"
echo "fleet smoke: protocol trace conformance OK (incl. seeded-edge self-test)"
