"""Flash attention — Pallas TPU kernels, fused forward AND backward.

Canonical TPU tiling: grid (batch·heads, q_blocks, k_blocks) with the k-block
dimension innermost and sequential ("arbitrary" semantics); online-softmax
accumulators (m, l, acc) live in VMEM scratch and persist across the k-block
iterations, so VMEM holds only one (block_q, d) query tile and one
(block_k, d) key/value tile at a time — O(block) VMEM, any sequence length.
Output (+ the logsumexp residual) is written on the last k iteration.

The backward is the flash-attention-2 formulation in two Pallas passes that
recompute P per tile from (q, k, lse) — no O(T²) residuals and no extra full
forward: a dQ kernel marching k-blocks innermost, and a dK/dV kernel
marching q-blocks innermost, with Δ = rowsum(dO ∘ O) precomputed as one
fused elementwise pass.

Layout: (B, T, H, D). The wrapper pads T up to lcm(block_q, block_k) and D to
the 128-lane width; padded keys are masked via ``valid_len``, padded queries
are sliced off. Causal masking uses the dense-attention convention: with
tq == tk the diagonal, i.e. query i attends keys ≤ i.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU backend bits; fall back gracefully on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
    _HAVE_TPU_PARAMS = True
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = pl.ANY
    _HAVE_TPU_PARAMS = False

_NEG_INF = -1e30
BLOCK_Q = 256
BLOCK_K = 256

# block tables from tools/tune_flash_attention.py on TPU v5e (bf16, causal,
# fwd+bwd grad time over the full {128,256,512}² grid at T ∈ 1k..8k for
# head dims 64 AND 128 — docs/flash_tune_r3.json): each bucket carries its
# measured winner (e.g. T=4096 d=64: 512×512 at 11.9 ms vs 14.9 for the
# old 256×256 guess; T=8192: 12.5 ms vs dense 126.7 → 10.1×). The winners
# shift with head dim (wider heads → smaller tiles; the VMEM working set
# per tile scales with d). Entries must come from the tuner, never
# intuition — an early guessed 256×512 row measured 1.8× slower than what
# it replaced.
_BLOCK_TABLES = {
    64: ((1024, (512, 512)), (2048, (128, 512)),
         (4096, (512, 512)), (8192, (512, 512))),
    128: ((1024, (128, 128)), (2048, (256, 256)),
          (4096, (256, 256)), (8192, (256, 512))),
}


def _pick_blocks(t: int, d: int) -> tuple:
    table = _BLOCK_TABLES[64 if d <= 96 else 128]
    for upper, blocks in table:
        if t <= upper:
            return blocks
    return table[-1][1]


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
                  *, scale, causal, valid_len, block_q, block_k, nk):
    """One (q-block, k-block) tile. Scratch m/l/acc persist across the
    innermost (k-block) grid dimension."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # blocks strictly above the causal diagonal contribute nothing
    live = jnp.logical_or(not causal,
                          kj * block_k <= qi * block_q + block_q - 1)

    @pl.when(live)
    def _accumulate():
        # keep matmul OPERANDS in the input dtype (bf16 on the MXU's native
        # rate — an f32 cast would halve/quarter throughput); accumulate f32
        q = q_ref[0]                                      # (bq, d)
        k = k_ref[0]                                      # (bk, d)
        v = v_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        if valid_len is not None:
            s = jnp.where(k_pos < valid_len, s, _NEG_INF)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev, l_prev, acc_prev = m_ref[:], l_ref[:], acc_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_ref[:] = m_new
        l_ref[:] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_prev * corr + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _finalize():
        l = l_ref[:]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        # logsumexp residual for the fused backward: lse = m + log(l);
        # guard fully-masked rows (m = -inf) to keep exp(s - lse) finite
        m = m_ref[:]
        lse_ref[0] = jnp.where(m <= _NEG_INF / 2, 0.0, m + jnp.log(l))


def _geometry(t, d, block_q, block_k):
    """Common fwd/bwd tiling: clamp blocks to the (padded) sequence, keeping
    them a multiple of the TPU sublane tile (16 covers bf16's (16,128) and
    f32's (8,128)) so Mosaic accepts shapes like t=196 (ViT-224/16)."""
    t16 = -(-t // 16) * 16
    block_q = min(block_q, t16)
    block_k = min(block_k, t16)
    step = math.lcm(block_q, block_k)
    tpad = (-t) % step
    dpad = (-d) % 128
    return block_q, block_k, tpad, dpad


def _fold(x, b, h, d):  # (B,T,H,D) → (B·H, T, D)
    return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)


def _unfold(x, b, h, t, d):  # (B·H, T, D) → (B,T,H,D)
    return x.reshape(b, h, x.shape[1], x.shape[2])[:, :, :t, :d] \
        .transpose(0, 2, 1, 3)


def _flash_forward(q, k, v, causal=False, interpret=False,
                   block_q=BLOCK_Q, block_k=BLOCK_K, return_residuals=False):
    b, t, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    block_q, block_k, tpad, dpad = _geometry(t, d, block_q, block_k)

    qf, kf, vf = (_fold(x, b, h, d) for x in (q, k, v))
    if tpad or dpad:
        pad = ((0, 0), (0, tpad), (0, dpad))
        qf, kf, vf = (jnp.pad(x, pad) for x in (qf, kf, vf))
    tp, dp = qf.shape[1], qf.shape[2]
    nq, nk = tp // block_q, tp // block_k
    grid = (b * h, nq, nk)

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal,
        valid_len=(t if tpad else None), block_q=block_q, block_k=block_k,
        nk=nk)

    if not _HAVE_TPU_PARAMS:  # pragma: no cover
        raise NotImplementedError(
            "flash_attention requires the Pallas TPU backend; use "
            "ops.blockwise_attention on this platform")
    scratch = [pltpu.VMEM((block_q, 1), jnp.float32),
               pltpu.VMEM((block_q, 1), jnp.float32),
               pltpu.VMEM((block_q, dp), jnp.float32)]
    extra = {}
    if not interpret:
        extra = dict(compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")))

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, dp), lambda bh, i, j: (bh, i, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, block_k, dp), lambda bh, i, j: (bh, j, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, block_k, dp), lambda bh, i, j: (bh, j, 0),
                         memory_space=_VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, dp), lambda bh, i, j: (bh, i, 0),
                         memory_space=_VMEM),
            pl.BlockSpec((1, block_q, 1), lambda bh, i, j: (bh, i, 0),
                         memory_space=_VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tp, dp), q.dtype),
            jax.ShapeDtypeStruct((b * h, tp, 1), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
        **extra,
    )(qf, kf, vf)
    # the lse output is computed even when discarded (no-grad path): a
    # second kernel variant isn't worth the (B·H, Tp, 1) f32 write it saves
    out_bthd = _unfold(out, b, h, t, d)
    if return_residuals:
        return out_bthd, lse
    return out_bthd


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, scale, causal, valid_len, block_q, block_k, nk):
    """dQ pass: grid (B·H, nq, nk), k-blocks innermost/sequential.
    dS = P ∘ (dO·Vᵀ − Δ); dQ = scale · dS·K   (flash-attention-2 backward)."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    live = jnp.logical_or(not causal,
                          kj * block_k <= qi * block_q + block_q - 1)

    @pl.when(live)
    def _accumulate():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0]                                   # (bq, 1)
        delta = delta_ref[0]                               # (bq, 1)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        mask = None
        if valid_len is not None:
            mask = k_pos < valid_len
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            cm = q_pos >= k_pos
            mask = cm if mask is None else jnp.logical_and(mask, cm)
        p = jnp.exp(s - lse)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(k.dtype)
        dq_acc[:] += jnp.dot(ds, k, preferred_element_type=jnp.float32) * scale

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *,
                    scale, causal, valid_len, block_q, block_k, nq):
    """dK/dV pass: grid (B·H, nk, nq), q-blocks innermost/sequential.
    dV = Pᵀ·dO;  dK = scale · dSᵀ·Q."""
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = jnp.logical_or(not causal,
                          kj * block_k <= qi * block_q + block_q - 1)

    @pl.when(live)
    def _accumulate():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        mask = None
        if valid_len is not None:
            mask = k_pos < valid_len
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            cm = q_pos >= k_pos
            mask = cm if mask is None else jnp.logical_and(mask, cm)
        p = jnp.exp(s - lse)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dv_acc[:] += jnp.dot(p.astype(do.dtype).T, do,
                             preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_acc[:] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32) * scale

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal=False, interpret=False,
                    block_q=BLOCK_Q, block_k=BLOCK_K):
    """Fused Pallas backward: recomputes P per tile from (q, k, lse) — no
    O(T²) residuals, two passes over the kv/q grids."""
    b, t, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    block_q, block_k, tpad, dpad = _geometry(t, d, block_q, block_k)

    qf, kf, vf, dof, of = (_fold(x, b, h, d) for x in (q, k, v, g, out))
    if tpad or dpad:
        pad = ((0, 0), (0, tpad), (0, dpad))
        qf, kf, vf, dof, of = (jnp.pad(x, pad)
                               for x in (qf, kf, vf, dof, of))
    tp, dp = qf.shape[1], qf.shape[2]
    nq, nk = tp // block_q, tp // block_k
    # Δ = rowsum(dO ∘ O): tiny elementwise pass, let XLA fuse it
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1, keepdims=True)                # (B·H, tp, 1)

    if not _HAVE_TPU_PARAMS:  # pragma: no cover
        raise NotImplementedError(
            "flash_attention requires the Pallas TPU backend; use "
            "ops.blockwise_attention on this platform")

    common = dict(scale=scale, causal=causal,
                  valid_len=(t if tpad else None),
                  block_q=block_q, block_k=block_k)

    # one BlockSpec builder per operand kind; the q/k index maps swap between
    # the (bh, qi, kj) grid of the dQ pass and the (bh, kj, qi) grid of dK/dV
    def qb(im):
        return pl.BlockSpec((1, block_q, dp), im, memory_space=_VMEM)

    def kb(im):
        return pl.BlockSpec((1, block_k, dp), im, memory_space=_VMEM)

    def rb(im):
        return pl.BlockSpec((1, block_q, 1), im, memory_space=_VMEM)

    q_at = lambda bh, i, j: (bh, i, 0)    # noqa: E731
    k_at = lambda bh, i, j: (bh, j, 0)    # noqa: E731
    q_at2 = lambda bh, j, i: (bh, i, 0)   # noqa: E731
    k_at2 = lambda bh, j, i: (bh, j, 0)   # noqa: E731

    extra = {}
    if not interpret:
        extra = dict(compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, nk=nk, **common),
        grid=(b * h, nq, nk),
        in_specs=[qb(q_at), kb(k_at), kb(k_at), qb(q_at), rb(q_at), rb(q_at)],
        out_specs=qb(q_at),
        out_shape=jax.ShapeDtypeStruct((b * h, tp, dp), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, dp), jnp.float32)],
        interpret=interpret,
        **extra,
    )(qf, kf, vf, dof, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, nq=nq, **common),
        grid=(b * h, nk, nq),
        in_specs=[qb(q_at2), kb(k_at2), kb(k_at2), qb(q_at2), rb(q_at2),
                  rb(q_at2)],
        out_specs=[kb(k_at2), kb(k_at2)],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tp, dp), k.dtype),
            jax.ShapeDtypeStruct((b * h, tp, dp), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, dp), jnp.float32),
                        pltpu.VMEM((block_k, dp), jnp.float32)],
        interpret=interpret,
        **extra,
    )(qf, kf, vf, dof, lse, delta)

    return (_unfold(dq, b, h, t, d), _unfold(dk, b, h, t, d),
            _unfold(dv, b, h, t, d))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, interpret: bool = False,
                    block_q: int = 0, block_k: int = 0) -> jax.Array:
    """Pallas flash attention, (B, T, H, D). Differentiable with a FUSED
    Pallas backward (dq + dk/dv kernels recomputing P from the lse
    residual — O(T) memory, no extra full forward). ``block_q``/``block_k``
    of 0 pick the measured-optimal tile for the sequence length and head
    dim (_BLOCK_TABLES; tools/tune_flash_attention.py re-derives them)."""
    bq, bk = _resolve_blocks(q, block_q, block_k)
    return _flash_forward(q, k, v, causal, interpret,
                          block_q=bq, block_k=bk)


def _resolve_blocks(q, block_q, block_k):
    auto_q, auto_k = _pick_blocks(q.shape[1], q.shape[3])
    return block_q or auto_q, block_k or auto_k


def _fa_fwd(q, k, v, causal, interpret, block_q, block_k):
    bq, bk = _resolve_blocks(q, block_q, block_k)
    out, lse = _flash_forward(q, k, v, causal, interpret,
                              block_q=bq, block_k=bk, return_residuals=True)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, interpret, block_q, block_k, res, g):
    q, k, v, out, lse = res
    bq, bk = _resolve_blocks(q, block_q, block_k)
    return _flash_backward(q, k, v, out, lse, g, causal, interpret,
                           block_q=bq, block_k=bk)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


# ---------------------------------------------------------------------------
# Ring attention with the Pallas kernel as the inner block (round 4,
# VERDICT r3 #4). The pure-lax ring (ops/attention.ring_attention) is bound
# by its O(T²) f32 softmax elementwise traffic — measured 1.5×-3.6× slower
# than the fused kernel at 8k-32k tokens (docs/ring_attention_r4.json),
# and re-expressing its matmuls in bf16 measured a wash, so the kernel is
# the only way to make the sequence-parallel path perf-grade.
#
# Forward: per ring step, one _flash_forward call against the resident kv
# chunk (causal only on the diagonal step); per-chunk (out, lse) pairs are
# merged with the standard logsumexp combine. Backward: a custom ring —
# _flash_backward per chunk with the GLOBAL lse (p = exp(s - lse_global)
# recovers the true softmax slice, the flash-2 decomposition), dq
# accumulating locally while dk/dv accumulators ride the ring WITH their
# kv chunks (n hops = home). Causal skips: device `my` executes only ring
# steps i <= my (lax.cond), the same work skipping the lax ring does.
# ---------------------------------------------------------------------------


def _ring_combine(M, S, A, o_i, lse_i):
    """Merge one chunk's normalized output into the running combine.

    M/S (B,H,T) running max / rescaled sumexp; A (B,T,H,D) f32 running
    numerator; o_i chunk output (softmax-normalized within the chunk);
    lse_i (B,H,T) the chunk's logsumexp."""
    M_new = jnp.maximum(M, lse_i)
    w_old = jnp.exp(M - M_new)          # first step: exp(-inf - x) = 0
    w_new = jnp.exp(lse_i - M_new)
    A_new = A * w_old.transpose(0, 2, 1)[..., None] \
        + o_i.astype(jnp.float32) * w_new.transpose(0, 2, 1)[..., None]
    return M_new, S * w_old + w_new, A_new


def _ring_impl(q, k, v, axis_name, n, causal, interpret):
    """Returns (out, global lse (B,H,T) f32). Call under shard_map."""
    b, t, h, d = q.shape
    my = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]
    bq, bk = _pick_blocks(t, d)
    M = jnp.full((b, h, t), -jnp.inf, jnp.float32)
    S = jnp.zeros((b, h, t), jnp.float32)
    A = jnp.zeros((b, t, h, d), jnp.float32)
    k_cur, v_cur = k, v
    for i in range(n):
        # ring step i: this device holds kv chunk (my - i) mod n; with
        # causal masking that chunk is visible iff (my - i) mod n <= my,
        # i.e. iff i <= my — and i == 0 is always the causal diagonal
        is_diag = causal and i == 0

        def compute(args, _diag=is_diag):
            M_, S_, A_, k_c, v_c = args
            o_i, lse_f = _flash_forward(
                q, k_c, v_c, causal=_diag, interpret=interpret,
                block_q=bq, block_k=bk, return_residuals=True)
            lse_i = lse_f[:, :t, 0].reshape(b, h, t)
            return _ring_combine(M_, S_, A_, o_i, lse_i)

        args = (M, S, A, k_cur, v_cur)
        if causal and i > 0:
            M, S, A = jax.lax.cond(
                my >= i, compute, lambda a: (a[0], a[1], a[2]), args)
        else:
            M, S, A = compute(args)
        if i < n - 1:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
    S_safe = jnp.where(S == 0.0, 1.0, S)
    out = (A / S_safe.transpose(0, 2, 1)[..., None]).astype(v.dtype)
    return out, M + jnp.log(S_safe)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def ring_flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                         axis_name: str, axis_size: int,
                         causal: bool = False,
                         interpret: bool = False) -> jax.Array:
    """Sequence-parallel flash attention over mesh axis ``axis_name``
    (size ``axis_size``) — call under shard_map with q/k/v time-sharded
    (B, T/n, H, D per device). Differentiable; the backward rides the same
    ring (see module comment above)."""
    out, _ = _ring_impl(q, k, v, axis_name, axis_size, causal, interpret)
    return out


def _ring_fa_fwd(q, k, v, axis_name, n, causal, interpret):
    out, lse = _ring_impl(q, k, v, axis_name, n, causal, interpret)
    return out, (q, k, v, out, lse)


def _ring_fa_bwd(axis_name, n, causal, interpret, res, g):
    q, k, v, out, lse = res
    b, t, h, d = q.shape
    my = jax.lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]
    bq, bk = _pick_blocks(t, d)
    _, _, tpad, _ = _geometry(t, d, bq, bk)
    lse_f = lse.reshape(b * h, t, 1)
    if tpad:
        # pad rows only meet zero-padded dO rows, so any finite value works
        lse_f = jnp.pad(lse_f, ((0, 0), (0, tpad), (0, 0)))

    dq = jnp.zeros(q.shape, jnp.float32)
    dk_cur = jnp.zeros(k.shape, jnp.float32)
    dv_cur = jnp.zeros(v.shape, jnp.float32)
    k_cur, v_cur = k, v
    for i in range(n):
        is_diag = causal and i == 0

        def compute(args, _diag=is_diag):
            dq_a, dk_c, dv_c, k_c, v_c = args
            dqi, dki, dvi = _flash_backward(
                q, k_c, v_c, out, lse_f, g, causal=_diag,
                interpret=interpret, block_q=bq, block_k=bk)
            return (dq_a + dqi.astype(jnp.float32),
                    dk_c + dki.astype(jnp.float32),
                    dv_c + dvi.astype(jnp.float32))

        args = (dq, dk_cur, dv_cur, k_cur, v_cur)
        if causal and i > 0:
            dq, dk_cur, dv_cur = jax.lax.cond(
                my >= i, compute, lambda a: (a[0], a[1], a[2]), args)
        else:
            dq, dk_cur, dv_cur = compute(args)
        # rotate kv AND the kv-grad accumulators together on every step —
        # after n hops each chunk's accumulated (dk, dv) is back home
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_cur = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_cur = jax.lax.ppermute(dv_cur, axis_name, perm)
    return (dq.astype(q.dtype), dk_cur.astype(k.dtype),
            dv_cur.astype(v.dtype))


ring_flash_attention.defvjp(_ring_fa_fwd, _ring_fa_bwd)
