"""Device mesh construction — the single SPMD replacement for BOTH reference
communication backends.

The reference shipped two data-parallel backends (SURVEY.md §2.8-2.9):
  (a) grpc parameter-server + ``tf.train.SyncReplicasOptimizer``
      (reference resnet_cifar_main.py:350-399, resnet_model.py:102-135) —
      variables sharded round-robin onto ps tasks, gradient push/pull over
      grpc, token-queue chief machinery; documented not to scale
      (reference README.md:7-15).
  (b) Horovod MPI/NCCL ring allreduce (reference resnet_cifar_main_horovod.py).

Here both collapse into one path: a named ``jax.sharding.Mesh`` over which
``jax.jit`` lays out arrays and XLA inserts the collectives (all-reduce /
all-gather / reduce-scatter) on ICI/DCN. The parameter-server topology
disappears; Horovod's rank-0 broadcast becomes replicated init by construction.

Mesh axes (all present from day one so sequence/expert/pipeline workloads can
be added without re-architecting — see SURVEY.md §5 "long-context" note):
  data     — batch data parallelism (the reference's only axis)
  fsdp     — ZeRO-like parameter/optimizer-state sharding
  tensor   — tensor (op-level) parallelism
  pipeline — pipeline stage parallelism
  seq      — sequence/context parallelism (ring attention)
  expert   — expert parallelism
"""
from __future__ import annotations

import math
import threading
import weakref
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis order: fastest-varying (innermost, highest-bandwidth ICI)
# axes last, so tensor/seq collectives ride the tightest links.
AXES = ("pipeline", "data", "fsdp", "expert", "seq", "tensor")


def resolve_axis_sizes(mesh_cfg, num_devices: Optional[int] = None) -> Tuple[int, ...]:
    """Resolve a MeshConfig into concrete per-axis sizes.

    Any axis set to -1 absorbs all remaining devices (at most one may be -1);
    the product must equal the device count.
    """
    if num_devices is None:
        num_devices = jax.device_count()
    sizes = {
        "pipeline": mesh_cfg.pipeline,
        "data": mesh_cfg.data,
        "fsdp": mesh_cfg.fsdp,
        "expert": mesh_cfg.expert,
        "seq": mesh_cfg.sequence,
        "tensor": mesh_cfg.tensor,
    }
    # 0 and 1 both mean "collapsed axis"
    sizes = {a: (1 if s == 0 else s) for a, s in sizes.items()}
    wild = [a for a, s in sizes.items() if s == -1]
    if len(wild) > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {wild}")
    fixed = math.prod(s for s in sizes.values() if s != -1)
    if wild:
        if num_devices % fixed != 0:
            raise ValueError(
                f"{num_devices} devices not divisible by fixed axes product {fixed}")
        sizes[wild[0]] = num_devices // fixed
    total = math.prod(sizes.values())
    if total != num_devices:
        raise ValueError(
            f"mesh {sizes} covers {total} devices but {num_devices} are present")
    return tuple(sizes[a] for a in AXES)


def create_mesh(mesh_cfg=None, devices: Optional[Sequence[jax.Device]] = None,
                axis_sizes: Optional[Tuple[int, ...]] = None) -> Mesh:
    """Build the global mesh. ``jax.make_mesh`` / ``mesh_utils`` pick a
    device permutation that keeps inner axes on the fastest ICI links."""
    if devices is None:
        devices = jax.devices()
    if axis_sizes is None:
        if mesh_cfg is None:
            axis_sizes = tuple(
                1 if a != "data" else len(devices) for a in AXES)
        else:
            axis_sizes = resolve_axis_sizes(mesh_cfg, len(devices))
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(
            axis_sizes, devices=np.asarray(devices))
    except Exception:
        # host-aware fallback order: group each host's devices
        # contiguously (stable by (process_index, id)) before the reshape,
        # so consecutive ``data`` coordinates land on one host whenever
        # the axis sizes allow — the layout data_axis_host_factorization
        # below detects and the hierarchical exchange
        # (parallel/overlap.py, comm.hierarchy) exploits
        ordered = sorted(devices, key=lambda d: (
            getattr(d, "process_index", 0), getattr(d, "id", 0)))
        dev_array = np.asarray(ordered).reshape(axis_sizes)
    return Mesh(dev_array, AXES)


def data_axis_host_factorization(mesh: Mesh) -> Optional[int]:
    """The intra-host group size ``k`` along the ``data`` axis, or None.

    Returns ``k`` (1 < k < data_size, k | data_size) when the data axis
    splits into uniform blocks of ``k`` consecutive coordinates such
    that, for every fixed coordinate on the other mesh axes, all ``k``
    devices of a block live on ONE process (host) and different blocks
    live on different hosts — the factorization the hierarchical
    exchange (parallel/overlap.py, ``comm.hierarchy``) stages its
    reduce-scatter / psum / all-gather tiers over. None when the axis is
    trivial, single-host, or the device order interleaves hosts (no
    honest fast/slow tier split exists; ``comm.intra_axis_size``
    overrides for virtual meshes)."""
    ax = {name: i for i, name in enumerate(mesh.axis_names)}
    if "data" not in ax:
        return None
    dsize = mesh.shape.get("data", 1)
    if dsize <= 1:
        return None
    # one row per data coordinate: the process index of every device at
    # that coordinate, other-axis positions flattened in a fixed order
    moved = np.moveaxis(mesh.devices, ax["data"], 0).reshape(dsize, -1)
    rows = [tuple(getattr(d, "process_index", 0) for d in moved[i])
            for i in range(dsize)]
    k = 1
    while k < dsize and rows[k] == rows[0]:
        k += 1
    if k <= 1 or k >= dsize or dsize % k:
        return None
    blocks = [rows[b * k:(b + 1) * k] for b in range(dsize // k)]
    for blk in blocks:
        if any(r != blk[0] for r in blk[1:]):
            return None
    if len({blk[0] for blk in blocks}) <= 1:
        return None
    return k


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for a batch: leading dim split over every batch-like axis
    (data × fsdp), rest replicated."""
    return NamedSharding(mesh, P(("data", "fsdp")))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def present_batch_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Batch-splitting axes that are actually >1 (tolerates hand-built
    meshes missing axes). May be empty — callers wanting a PartitionSpec
    use ``present_batch_axes(mesh) or None``."""
    return tuple(a for a in ("data", "fsdp") if mesh.shape.get(a, 1) > 1)


def batch_shard_count(mesh: Mesh) -> int:
    return mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)


def shard_map_compat(fn, mesh: Mesh, in_specs, out_specs,
                     auto: frozenset = frozenset()):
    """``jax.shard_map`` across jax versions, replication checks off.

    One home for two version dances every caller needs: the import moved
    out of ``jax.experimental`` in 0.8 (the old alias warns and will be
    removed), and the don't-check-replication flag was renamed
    ``check_rep`` → ``check_vma``. Checks stay off because our shard_map
    bodies wrap collectives/pallas_call, which don't declare varying-mesh
    -axes info.

    ``auto``: mesh axes left AUTOMATIC (GSPMD propagation inside the
    body, like under plain jit) while the rest go manual — the
    partial-manual form the layout-aware gradient exchange uses for the
    propagation-parallel ``tensor`` axis (parallel/overlap.py): specs may
    only name manual axes; values keep their auto-axis sharding."""
    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover - jax < 0.8
        from jax.experimental.shard_map import shard_map
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    if auto:
        kwargs["auto"] = frozenset(auto)
    try:
        return shard_map(fn, check_vma=False, **kwargs)
    except TypeError:  # pragma: no cover - older jax spells it check_rep
        return shard_map(fn, check_rep=False, **kwargs)


# ---------------------------------------------------------------------------
# Manual-axes trace context: how model code learns it is running INSIDE a
# manually-mapped shard_map body (the layout-aware gradient exchange,
# parallel/overlap.make_bucketed_grad) rather than under plain jit.
# Sharding constraints naming a manual axis are illegal inside the body,
# model-internal shard_maps must not re-map an already-manual axis (jax
# 0.4.37 mis-transposes nested shard_map over auto axes — measured, see
# overlap.py), and per-shard batch math must stop dividing by shards the
# enclosing body already split. The context is TRACE-time only: the body
# runs during jit tracing, so its dynamic extent covers exactly the model
# code whose behavior must flip.
# ---------------------------------------------------------------------------

_MANUAL_AXES = threading.local()


def current_manual_axes() -> frozenset:
    """Mesh axes the innermost enclosing exchange shard_map maps manually
    (empty outside one)."""
    return getattr(_MANUAL_AXES, "axes", frozenset())


class manual_axes:
    """Context manager declaring ``axes`` manually mapped for the model
    code traced inside it (parallel/overlap.py wraps the loss body)."""

    def __init__(self, axes):
        self.axes = frozenset(axes)

    def __enter__(self):
        self._prev = current_manual_axes()
        _MANUAL_AXES.axes = self.axes
        return self.axes

    def __exit__(self, *exc):
        _MANUAL_AXES.axes = self._prev
        return False


def filter_spec_axes(spec: P, keep) -> P:
    """PartitionSpec entry filter: keep only axis names for which
    ``keep(name)`` is True, collapsing entries back to
    name / tuple / ``None`` — the ONE home of that normalization, shared
    by the manual-context constraint filter below and the exchange's
    manual/auto spec splits (parallel/overlap.py)."""
    out = []
    for names in spec:
        if names is None:
            out.append(None)
            continue
        tup = names if isinstance(names, tuple) else (names,)
        kept = tuple(n for n in tup if keep(n))
        out.append(kept if len(kept) > 1 else (kept[0] if kept else None))
    return P(*out)


def filter_manual_spec(spec: P) -> P:
    """Drop manual-axis references from a PartitionSpec (constraints and
    shard_map specs inside the exchange body may only name auto axes) —
    axes already consumed by the enclosing manual map become ``None``."""
    manual = current_manual_axes()
    if not manual:
        return spec
    return filter_spec_axes(spec, lambda n: n not in manual)


# weak-key memo: an lru_cache here would pin up to maxsize Mesh objects
# (and their device arrays) for the process lifetime — a real leak in long
# sessions that build many meshes (tests, notebooks). Weak keys drop an
# entry the moment its mesh is collected; equal live meshes still share one.
_batch_slice_cache: "weakref.WeakKeyDictionary[Mesh, Tuple[int, int]]" = \
    weakref.WeakKeyDictionary()
_batch_slice_lock = threading.Lock()


def process_batch_slice(mesh: Mesh) -> Tuple[int, int]:
    """(input_shard_index, num_input_shards) for THIS process.

    Multi-process input feeding must be keyed by which slice of the BATCH
    dimension (the data × fsdp coordinate range) this process's devices
    address — NOT by process_index. When a non-batch axis (pipeline,
    tensor, expert, seq) crosses the process boundary, several processes
    address the SAME batch slice and must feed identical data; sharding
    input by process_index there desynchronizes the replicas (caught by
    tests/test_launch.py::test_two_process_pipeline_vit_checkpoint_eval).
    Pure data-over-processes reduces to (process_index, process_count).

    Memoized per mesh (weak-key, see above): the result is a pure function
    of the mesh, but the computation scans every device coordinate
    (O(total devices) in Python) and the callers (make_global_batch /
    make_global_stacked_batch) sit in the per-step input hot path.
    """
    with _batch_slice_lock:
        hit = _batch_slice_cache.get(mesh)
    if hit is not None:
        return hit
    pi = jax.process_index()
    arr = mesh.devices
    ax = {name: i for i, name in enumerate(mesh.axis_names)}
    fsdp_size = mesh.shape.get("fsdp", 1)
    ids = set()
    for idx in np.ndindex(arr.shape):
        if arr[idx].process_index == pi:
            d = idx[ax["data"]] if "data" in ax else 0
            f = idx[ax["fsdp"]] if "fsdp" in ax else 0
            ids.add(d * fsdp_size + f)
    total = mesh.shape.get("data", 1) * fsdp_size
    lo, n = min(ids), len(ids)
    if sorted(ids) != list(range(lo, lo + n)) or total % n or lo % n:
        raise ValueError(
            f"process {pi}'s devices cover batch shards {sorted(ids)} — "
            "not an aligned contiguous range; choose mesh axis sizes so "
            "each process's batch slice is contiguous")
    result = (lo // n, total // n)
    with _batch_slice_lock:
        _batch_slice_cache[mesh] = result
    return result


def batch_slice_replicated(mesh: Mesh) -> bool:
    """True when several processes feed the SAME batch slice (a non-batch
    mesh axis spans the process boundary): fewer distinct slices than
    processes. Replicas must then assemble byte-identical batches — input
    builders pass this as the pipeline's ``deterministic`` flag
    (data/imagenet.py)."""
    _, num_shards = process_batch_slice(mesh)
    return jax.process_count() > num_shards


def local_batch_size(global_batch: int, mesh: Mesh) -> int:
    n = batch_shard_count(mesh)
    if global_batch % n != 0:
        raise ValueError(f"global batch {global_batch} not divisible by "
                         f"{n} batch shards")
    return global_batch // n
