"""Train state: params + BN batch stats + optimizer state + step.

Successor of the reference's implicit graph-collection state — TF global
variables, BN moving averages updated via UPDATE_OPS control deps (reference
resnet_model.py:118-121), optimizer slots on the parameter servers. Here it
is one explicit pytree, shardable leaf-by-leaf via NamedSharding.

Precision contract (parallel/precision.py; docs/precision.md): every
float leaf of this state — params, BN stats, optimizer moments — is an
f32 MASTER regardless of the ``train.precision`` policy. The bf16 policy
lives entirely in the APPLY (the model's compute dtype casts masters
per-op; the cast's transpose re-accumulates gradients into f32), so
checkpoints, restores and the serving hot swap never see a cast leaf —
``Trainer.init_state`` guards this with ``check_master_dtypes``.
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.sharding import tree_param_shardings


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any
    # static (not traced):
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(step=self.step + 1, params=new_params,
                            opt_state=new_opt_state)


def _make_init_fn(model, tx, input_shape):
    dummy = jnp.zeros(input_shape, jnp.float32)

    def init_fn(rng):
        variables = model.init(rng, dummy, train=False)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        opt_state = tx.init(params)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          batch_stats=batch_stats, opt_state=opt_state,
                          apply_fn=model.apply, tx=tx)

    return init_fn


def abstract_train_state(model, tx, input_shape) -> TrainState:
    """Shape/dtype-only TrainState — zero data, zero compute. The static
    elaborator (analysis/elaborate.py) builds model states for every
    preset × mesh layout this way; create_train_state uses the same init
    function, so the abstract state and the real one cannot drift."""
    return jax.eval_shape(_make_init_fn(model, tx, input_shape),
                          jax.random.PRNGKey(0))


def create_train_state(rng: jax.Array, model, tx, input_shape,
                       mesh: Mesh = None, zero1: bool = False,
                       zero1_min_size: int = 0) -> TrainState:
    """Initialize model + optimizer state.

    When a mesh is given, init runs under jit with output shardings so large
    params materialize directly sharded (never gathered on one host) — the
    replacement for both replica_device_setter placement (reference
    resnet_cifar_main.py:392-396) and Horovod's rank-0 variable broadcast
    (reference resnet_cifar_main_horovod.py:316): replicated init is identical
    on every process by seeded construction.

    ``zero1=True`` lays the optimizer state out in the ZeRO-1 rule-table
    sharding (``parallel/sharding.zero1_state_shardings``): each data
    replica materializes only its 1/N optimizer shard from step 0.
    """
    init_fn = _make_init_fn(model, tx, input_shape)
    if mesh is None:
        return init_fn(rng)

    # Evaluate shapes, derive shardings, then jit-init with those outputs.
    abstract = jax.eval_shape(init_fn, rng)
    shardings = state_shardings(abstract, mesh, zero1=zero1,
                                zero1_min_size=zero1_min_size)
    jit_init = jax.jit(init_fn, out_shardings=shardings)
    return jit_init(rng)


def state_shardings(state_shapes, mesh: Mesh, zero1: bool = False,
                    zero1_min_size: int = 0):
    """NamedShardings for every leaf of a TrainState (params/opt_state follow
    the fsdp rule; step/batch_stats replicated).

    ``zero1=True`` additionally shards the optimizer state over the
    ``data`` axis via the regex→PartitionSpec rule table
    (``parallel/sharding.zero1_state_shardings``, arXiv:2004.13336); each
    resolution records its counted partition report into the process-global
    ``parallel.sharding.zero1_stats``. Params stay replicated-per-fsdp —
    ZeRO-1 shards the UPDATE and its state, not the forward weights."""
    param_sh = tree_param_shardings(state_shapes.params, mesh)
    rep = NamedSharding(mesh, P())
    if zero1:
        from ..parallel.sharding import (ZERO1_MIN_SIZE, Zero1Report,
                                         zero1_state_shardings, zero1_stats)
        report = Zero1Report(mesh.shape.get("data", 1))
        opt_sh = zero1_state_shardings(
            state_shapes.opt_state, mesh,
            min_size=zero1_min_size or ZERO1_MIN_SIZE, report=report)
        zero1_stats.record_report(report)
    else:
        # optimizer moments mirror the param tree INCLUDING names (optax
        # states embed the param pytree), so the name-aware rule (fsdp +
        # tensor) applies to them identically; scalar counters fall
        # through to replicated
        opt_sh = tree_param_shardings(state_shapes.opt_state, mesh)
    bs_sh = jax.tree_util.tree_map(lambda _: rep, state_shapes.batch_stats)
    return TrainState(step=rep, params=param_sh, batch_stats=bs_sh,
                      opt_state=opt_sh, apply_fn=state_shapes.apply_fn,
                      tx=state_shapes.tx)
