"""Dynamic request batcher: coalesce in-flight requests into bucket batches.

Requests arrive one example at a time from any number of submitter threads
(``submit`` returns a ``concurrent.futures.Future``); ONE dispatch thread
drains the queue, holds the first request up to ``max_queue_delay_ms`` to
coalesce late arrivals into a bigger bucket, pads the group into its
power-of-two bucket (``parallel/sharding.pad_batch_to_bucket`` semantics)
and runs the caller-supplied ``dispatch_fn`` — which stages the batch
through the Trainer's put path and executes the AOT-compiled program.

Threading contract (the PR 2 constraint, docs/input_pipeline.md): every
multi-device XLA execution of the serving process — the staged-batch
unpack AND the compiled predict — launches from THIS one dispatch thread.
Submitters only enqueue numpy; the swap thread only reads files and hands
host trees over (serve/swap.py). ``boundary_hook`` fires on the dispatch
thread between batches (and when idle) — the server applies pending
checkpoint swaps there, so a swap can never interleave with an in-flight
batch: requests already dispatched complete on the old params, the next
batch sees the new ones. The dispatch sanitizer
(``--set analysis.dispatch_sanitizer=true``) enforces all of this at
runtime; scripts/serve_smoke.sh runs with it armed.

Zero dropped requests: ``close()`` stops intake first (late ``submit``
raises), then drains everything already queued before the thread exits —
a request accepted is a request answered (or failed loudly via its
future's exception).
"""
from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional, Sequence

import numpy as np

log = logging.getLogger(__name__)


class _Request:
    __slots__ = ("image", "variant", "future", "t_submit")

    def __init__(self, image, variant: str = "f32"):
        self.image = image
        self.variant = variant
        self.future: Future = Future()
        self.t_submit = time.perf_counter()


class DynamicBatcher:
    """Single-consumer dynamic batcher over power-of-two buckets.

    ``dispatch_fn(images, requests)`` runs on the dispatch thread with
    ``images`` already padded to its bucket; it must resolve every
    request's future (the server sets ``(logits_row, step)`` results).
    ``boundary_hook()`` runs on the dispatch thread between batches/idle
    polls (see module docstring).
    """

    def __init__(self, buckets: Sequence[int],
                 dispatch_fn: Callable[[np.ndarray, List[_Request]], None],
                 image_shape, image_dtype,
                 max_queue_delay_ms: float = 5.0,
                 boundary_hook: Optional[Callable[[], None]] = None,
                 variants: Sequence[str] = ("f32",)):
        from .compile_cache import pick_bucket
        self._pick_bucket = pick_bucket
        self.buckets = sorted(int(b) for b in buckets)
        self.max_batch = self.buckets[-1]
        self._dispatch_fn = dispatch_fn
        self._image_shape = tuple(image_shape)
        self._image_dtype = np.dtype(image_dtype)
        self.max_queue_delay_ms = float(max_queue_delay_ms)
        self._boundary_hook = boundary_hook
        # serving variants (docs/precision.md): requests name one; a batch
        # is single-variant (one compiled program per dispatch), so a
        # variant change splits the group. FIRST entry = the default a
        # variant-less submit gets.
        self.variants = tuple(variants) or ("f32",)
        self._held: Optional[_Request] = None  # cross-variant spillover
        self._q: queue_mod.Queue = queue_mod.Queue()
        self._stop = threading.Event()
        self._closed = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # counters (dispatch-thread writes, any-thread reads)
        self.requests_in = 0
        self.batches = 0
        self.errors = 0
        self.failed_requests = 0  # answered via future.set_exception
        self._in_lock = threading.Lock()

    # -- submitter side ----------------------------------------------------
    def submit(self, image, variant: Optional[str] = None) -> Future:
        """Enqueue one example; returns the request's Future. Any thread.

        ``variant`` picks the serving precision variant (None = the
        configured default). Strict like the dtype/shape checks below: an
        unknown variant is rejected loudly, never silently served f32 —
        the client asked for a latency/precision contract this replica
        does not carry."""
        if self._closed.is_set():
            raise RuntimeError("batcher is closed; request rejected")
        if variant is None:
            variant = self.variants[0]
        elif variant not in self.variants:
            raise ValueError(
                f"unknown serve variant {variant!r}; this replica serves "
                f"{list(self.variants)} (serve.variants)")
        arr = np.asarray(image)
        if arr.dtype != self._image_dtype:
            # strict, no silent cast: float32-[0,1] crops coerced to a
            # uint8 spec would truncate to black, uint8 to a float32 spec
            # would serve unstandardized pixels — both answer confidently
            # with garbage. Requests must arrive prepped exactly as the
            # eval input pipeline delivers them (serve_image_spec). The
            # request dtype is VARIANT-INDEPENDENT: variants change the
            # weights/compute, never the input contract.
            raise ValueError(
                f"request image dtype {arr.dtype} != serving spec "
                f"{self._image_dtype}")
        if arr.shape != self._image_shape:
            raise ValueError(
                f"request image shape {arr.shape} != serving spec "
                f"{self._image_shape}")
        req = _Request(arr, variant)
        with self._in_lock:
            # the closed-check and the enqueue share one lock with
            # close(): once close() flips _closed under this lock, no
            # submit can slip a request past the drain — accepted means
            # answered, rejected means this raise, nothing in between
            if self._closed.is_set():
                raise RuntimeError("batcher is closed; request rejected")
            self.requests_in += 1
            self._q.put(req)
        return req.future

    # -- dispatch side -----------------------------------------------------
    def _collect(self, block_secs: float) -> Optional[List[_Request]]:
        """One group: the first request (waiting up to ``block_secs``), then
        late arrivals up to ``max_queue_delay_ms`` or the largest bucket.
        A group is single-VARIANT (one compiled program per dispatch): a
        request for another variant ends the group and is held as the
        next group's head — FIFO order across variants is preserved, a
        mixed stream just batches a little smaller."""
        if self._held is not None:
            first, self._held = self._held, None
        else:
            try:
                first = self._q.get(timeout=block_secs) if block_secs > 0 \
                    else self._q.get_nowait()
            except queue_mod.Empty:
                return None
        group = [first]
        deadline = time.perf_counter() + self.max_queue_delay_ms / 1000.0
        while len(group) < self.max_batch:
            remaining = deadline - time.perf_counter()
            try:
                nxt = self._q.get(timeout=max(0.0, remaining)) \
                    if remaining > 0 else self._q.get_nowait()
            except queue_mod.Empty:
                if remaining <= 0:
                    break
                continue
            if nxt.variant != first.variant:
                self._held = nxt
                break
            group.append(nxt)
        return group

    def _dispatch(self, group: List[_Request]) -> None:
        from ..parallel.sharding import pad_batch_to_bucket
        bucket = self._pick_bucket(self.buckets, len(group))
        # THE bucket-padding implementation (parallel/sharding.py) — one
        # home for the semantics. The mask is dropped: the predict step
        # takes images only, and the padded rows' logits are dead weight
        # nobody slices out (rows are batch-independent under train=False)
        stacked = np.stack([req.image for req in group])
        images = pad_batch_to_bucket({"images": stacked}, bucket)["images"]
        try:
            self._dispatch_fn(images, group)
        except BaseException as e:  # noqa: BLE001 — resolve futures, keep serving
            self.errors += 1
            log.exception("serve dispatch failed (bucket %d, n=%d)",
                          bucket, len(group))
            for req in group:
                if not req.future.done():
                    req.future.set_exception(e)
                    self.failed_requests += 1
        self.batches += 1

    def _drain(self) -> None:
        """Serve everything already queued (no delay wait — the queue's
        current content is the whole remaining load). Intake must be
        sealed before calling."""
        while True:
            group = self._collect(block_secs=0.0)
            if group is None:
                return
            self._dispatch(group)

    def _run(self) -> None:
        while not self._stop.is_set():
            group = self._collect(block_secs=0.05)
            if group is not None:
                self._dispatch(group)
            if self._boundary_hook is not None:
                self._boundary_hook()
        # drain: everything accepted before close() gets served
        self._drain()

    def start(self) -> "DynamicBatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="drt-serve-dispatch")
            self._thread.start()
        return self

    def service_once(self, block_secs: float = 0.0) -> int:
        """Synchronous single service turn on the CALLING thread — tests
        and thread-less embedding: collect one group (if any), dispatch it,
        run the boundary hook. Returns requests served. Must not be mixed
        with a started dispatch thread."""
        if self._thread is not None:
            raise RuntimeError("service_once with a live dispatch thread "
                               "would violate single-thread dispatch")
        group = self._collect(block_secs=block_secs)
        if group is not None:
            self._dispatch(group)
        if self._boundary_hook is not None:
            self._boundary_hook()
        return 0 if group is None else len(group)

    def close(self) -> None:
        """Stop intake, drain the queue, join the dispatch thread."""
        with self._in_lock:  # see submit(): after this, intake is sealed
            self._closed.set()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=60.0)
            if self._thread.is_alive():  # never silent (no-silent-caps rule)
                log.error("serve dispatch thread failed to drain in 60s")
            self._thread = None
        else:
            # thread-less (service_once) mode: the caller IS the dispatch
            # thread — drain here, or requests accepted before close would
            # seal in with futures that never resolve
            self._drain()
