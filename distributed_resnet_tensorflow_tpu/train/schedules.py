"""Learning-rate schedules as pure ``step -> lr`` functions.

Replaces the reference's ``_LearningRateSetterHook`` feed-dict mechanism
(reference resnet_cifar_main.py:287-307, resnet_imagenet_main.py:223-247) —
a session hook that fed a new lr every step — with pure functions evaluated
inside the jitted train step (XLA-friendly: `jnp.where` chains, no Python
branching on traced values).

Reference recipes reproduced exactly:
  * CIFAR: 0.1 / 0.01 / 0.001 / 0.0001 at steps 40k / 60k / 80k
    (reference resnet_cifar_main.py:298-307).
  * ImageNet (Intel-Caffe 8-node recipe, reference README.md:42): linear
    warmup 0.1→0.4 over 6240 steps, then piecewise ×0.1 at 37440 / 74880 /
    99840 (reference resnet_imagenet_main.py:236-247).
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp
import optax


Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def piecewise(boundaries: Sequence[int], values: Sequence[float]) -> Schedule:
    """Step-piecewise constant. len(values) == len(boundaries) + 1.

    Boundary semantics: the value switches AT the boundary step (step >=
    boundary → next value) — matching the reference's own LR hook
    (``train_step < 40000 → 0.1, elif < 60000 → 0.01``, reference
    resnet_cifar_main.py:300-307), NOT tf.piecewise_constant (which holds
    values[i] through step == boundaries[i])."""
    if len(values) != len(boundaries) + 1:
        raise ValueError(f"need {len(boundaries)+1} values, got {len(values)}")
    b = jnp.asarray(boundaries, dtype=jnp.int32)
    v = jnp.asarray(values, dtype=jnp.float32)

    def fn(step):
        idx = jnp.sum(jnp.asarray(step, jnp.int32) >= b)
        return v[idx]

    return fn


def warmup_piecewise(warmup_steps: int, warmup_start: float, peak: float,
                     boundaries: Sequence[int], values: Sequence[float]) -> Schedule:
    """Linear warmup (start→peak over warmup_steps) then piecewise — the
    reference's ImageNet recipe (resnet_imagenet_main.py:236-247)."""
    pw = piecewise(boundaries, values)

    def fn(step):
        step = jnp.asarray(step, jnp.float32)
        frac = jnp.clip(step / jnp.maximum(warmup_steps, 1), 0.0, 1.0)
        warm = warmup_start + frac * (peak - warmup_start)
        return jnp.where(step < warmup_steps, warm, pw(step))

    return fn


def warmup_cosine(warmup_steps: int, peak: float, total_steps: int,
                  end_value: float = 0.0) -> Schedule:
    """Warmup + cosine decay — the standard large-batch (LARS) schedule;
    not in the reference, required for the bs=32k config (BASELINE.json)."""
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=peak, warmup_steps=max(warmup_steps, 1),
        decay_steps=total_steps, end_value=end_value)


def warmup_poly(warmup_steps: int, peak: float, total_steps: int,
                power: float = 2.0, end_value: float = 0.0) -> Schedule:
    """Linear warmup to ``peak`` then polynomial decay to ``end_value`` —
    the LARS large-batch recipe (arXiv:1708.03888 trains with poly(2)
    decay; arXiv:1711.04325 and 1811.05233 pair it with a linear warmup of
    ~5 epochs to cross the bs>512 accuracy cliff). Pure ``step -> lr``
    like every schedule here."""
    warmup_steps = max(warmup_steps, 1)

    def fn(step):
        step = jnp.asarray(step, jnp.float32)
        warm = peak * jnp.clip(step / warmup_steps, 0.0, 1.0)
        frac = jnp.clip((step - warmup_steps)
                        / jnp.maximum(total_steps - warmup_steps, 1),
                        0.0, 1.0)
        decay = (peak - end_value) * (1.0 - frac) ** power + end_value
        return jnp.where(step < warmup_steps, warm, decay)

    return fn


def linear_scaled_lr(base_lr: float, batch_size: int,
                     base_batch: int = 256) -> float:
    """The linear LR scaling rule (arXiv:1711.04325 §2, after Goyal et
    al.): lr = base_lr × batch/base_batch. The warmup presets quote their
    peak LRs directly; this helper is for ad-hoc ``--set`` overrides that
    change the global batch and need the matched peak."""
    return base_lr * batch_size / base_batch


def constant(value: float) -> Schedule:
    return lambda step: jnp.asarray(value, jnp.float32)


def create_schedule(opt_cfg) -> Schedule:
    """Factory from OptimizerConfig."""
    name = opt_cfg.schedule
    if name == "piecewise":
        return piecewise(opt_cfg.boundaries, opt_cfg.values)
    if name == "warmup_piecewise":
        return warmup_piecewise(opt_cfg.warmup_steps, opt_cfg.warmup_start,
                                opt_cfg.values[0], opt_cfg.boundaries,
                                opt_cfg.values)
    if name == "cosine":
        return warmup_cosine(opt_cfg.warmup_steps, opt_cfg.learning_rate,
                             opt_cfg.total_steps)
    if name == "warmup_poly":
        return warmup_poly(opt_cfg.warmup_steps, opt_cfg.learning_rate,
                           opt_cfg.total_steps)
    if name == "constant":
        return constant(opt_cfg.learning_rate)
    raise ValueError(f"unknown schedule {name!r}")
