from .batch_norm import GroupedBatchNorm  # noqa: F401
