"""Cluster trace correlation: ``main.py trace-merge``.

Each process dumps its own flight-recorder ring as
``trace[.procN].json`` (telemetry/tracer.py) — useful alone, but a
distributed incident is a RELATIVE story: a straggling host's late
``comm.bucket`` span is only visibly late against its peers' lanes on
ONE timeline. This module merges the per-process dumps into a single
Perfetto/Chrome-trace file with one process lane per host:

  * every source file's events keep their thread lanes but move to
    ``pid = process_index``, with ``process_name`` /
    ``process_sort_index`` metadata so Perfetto renders "proc0 (host)"
    groups in rank order;
  * timestamps are rebased onto one wall-clock timeline. Each recorder
    stamps ``epoch_wall_time`` at construction, so within one host the
    mapping is exact; ACROSS hosts the wall clocks skew (NTP is
    milliseconds on a good day, seconds on a bad one), so the merge
    estimates per-process clock offsets from the heartbeat
    publish/observe pairs the run already recorded: the chief's
    ``{"event": "heartbeat"}`` rows carry each peer's beat age at
    observation, and ``min(observed age)`` over many observations is a
    BOUNDED estimator of the peer's clock offset (true publish→observe
    latency is in ``[0, beat interval + poll cadence]``; the chief's own
    min age calibrates the zero point, cancelling the shared publish-lag
    bias). The estimate, its bound and the observation count land in the
    merged file's ``otherData.clock_offsets`` — a reader can always see
    how much to trust sub-second cross-host ordering.

Works on exactly the artifacts the chaos/obs smokes produce
(``scripts/obs_smoke.sh``); pure filesystem reads, no jax world.
"""
from __future__ import annotations

import argparse
import glob
import json
import logging
import os
from typing import Dict, List, Optional, Sequence

log = logging.getLogger(__name__)

#: the observer of the heartbeat rows — the chief's watchdog is the only
#: writer-bearing one (resilience/watchdog.py), and its own beats in the
#: same rows calibrate the estimator's zero point
_OBSERVER_PID = "0"


def find_traces(root: str) -> List[str]:
    """Every per-process flight-recorder dump under ``root`` (the merged
    output itself is excluded so re-merges are idempotent)."""
    paths = sorted(
        p for p in glob.glob(os.path.join(root, "**", "trace*.json"),
                             recursive=True)
        if not os.path.basename(p).startswith("trace.merged"))
    return paths


def _heartbeat_rows(root: str) -> List[dict]:
    from ..utils.metrics import iter_metric_streams
    return [r for stream in iter_metric_streams(root) for r in stream
            if r.get("event") == "heartbeat"]


def estimate_clock_offsets(root: str) -> Dict[str, dict]:
    """Per-process clock-offset estimates from the run's heartbeat rows:
    ``{pid: {offset_secs, bound_secs, observations, min_age_secs,
    host}}``. ``offset_secs`` is (process clock − chief clock): subtract
    it from a process's wall timestamps to land on the chief's timeline.
    Empty when the run recorded no heartbeat rows (single process, or
    the watchdog was off) — the merge then trusts raw wall clocks."""
    ages: Dict[str, List[float]] = {}
    hosts: Dict[str, str] = {}
    for row in _heartbeat_rows(root):
        for pid, h in (row.get("hosts") or {}).items():
            age = h.get("age_secs")
            if isinstance(age, (int, float)):
                ages.setdefault(str(pid), []).append(float(age))
            if h.get("host"):
                hosts[str(pid)] = h["host"]
    if not ages:
        return {}
    chief_min = min(ages.get(_OBSERVER_PID, [0.0]))
    out: Dict[str, dict] = {}
    for pid, samples in sorted(ages.items()):
        m = min(samples)
        # |error| <= the chief's and this process's min TRUE
        # publish->observe latencies, each in [0, beat interval + poll
        # cadence]. Neither true latency is observable, so the recorded
        # bound uses the observable proxies: the chief's min age (its
        # offset is 0 by definition, so that IS its min latency) plus
        # the spread of this process's low-end ages (the latency scale
        # on its side).
        lo = sorted(samples)
        spread = lo[len(lo) // 2] - m if len(lo) > 1 else chief_min
        out[pid] = {
            "offset_secs": round(chief_min - m, 4),
            "bound_secs": round(max(0.0, chief_min) + max(0.0, spread), 4),
            "observations": len(samples),
            "min_age_secs": round(m, 4),
        }
        if pid in hosts:
            out[pid]["host"] = hosts[pid]
    return out


def merge_traces(paths: Sequence[str],
                 offsets: Optional[Dict[str, dict]] = None) -> dict:
    """Merge per-process trace dumps into one Perfetto document. Raises
    ValueError when no source loads — the callers are CLIs that should
    fail loudly, unlike the in-run dump paths."""
    offsets = offsets or {}
    sources = []
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            log.warning("trace-merge: skipping unreadable %s (%s)", path, e)
            continue
        other = doc.get("otherData") or {}
        sources.append({
            "path": path,
            "doc": doc,
            "process_index": int(other.get("process_index", 0)),
            "pid": other.get("pid"),
            "epoch_wall_time": float(other.get("epoch_wall_time", 0.0)),
            "span_schema_version": other.get("span_schema_version"),
        })
    if not sources:
        raise ValueError("no readable trace files to merge")
    sources.sort(key=lambda s: s["process_index"])

    def corrected_epoch(src) -> float:
        off = offsets.get(str(src["process_index"]), {})
        return src["epoch_wall_time"] - float(off.get("offset_secs", 0.0))

    t0 = min(corrected_epoch(s) for s in sources)
    events: List[dict] = []
    for src in sources:
        p = src["process_index"]
        off = offsets.get(str(p), {})
        host = off.get("host")
        name = f"proc{p}" + (f" ({host})" if host else "")
        events.append({"name": "process_name", "ph": "M", "pid": p,
                       "ts": 0, "args": {"name": name}})
        events.append({"name": "process_sort_index", "ph": "M", "pid": p,
                       "ts": 0, "args": {"sort_index": p}})
        shift_us = (corrected_epoch(src) - t0) * 1e6
        for ev in src["doc"].get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = p
            if ev.get("ph") == "X":
                ev["ts"] = round(float(ev.get("ts", 0.0)) + shift_us, 3)
            events.append(ev)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged": True,
            "span_schema_version": max(
                (s["span_schema_version"] or 0) for s in sources),
            "t0_wall_time": t0,
            "sources": [{
                "path": os.path.basename(s["path"]),
                "process_index": s["process_index"],
                "pid": s["pid"],
                "epoch_wall_time": s["epoch_wall_time"],
            } for s in sources],
            # the bounded-skew record: how much to trust cross-host
            # sub-second ordering in this file
            "clock_offsets": {
                pid: {k: v for k, v in off.items()}
                for pid, off in sorted(offsets.items())},
        },
    }


def main_trace_merge(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="main.py trace-merge",
        description="merge per-process flight-recorder trace dumps into "
                    "one Perfetto timeline with per-host lanes and "
                    "heartbeat-estimated clock offsets "
                    "(docs/observability.md)")
    ap.add_argument("traces", nargs="*",
                    help="explicit trace.json files (default: every "
                         "trace*.json under --root)")
    ap.add_argument("--root", default="/tmp/drt_tpu",
                    help="the run's log_root (trace dumps + metrics "
                         "streams for the clock-offset estimate)")
    ap.add_argument("--out", default="",
                    help="output path (default: "
                         "<root>/telemetry/trace.merged.json)")
    ns = ap.parse_args(argv)
    paths = list(ns.traces) or find_traces(ns.root)
    if not paths:
        print(f"trace-merge: no trace*.json found under {ns.root}")
        return 1
    offsets = estimate_clock_offsets(ns.root)
    try:
        doc = merge_traces(paths, offsets)
    except ValueError as e:
        print(f"trace-merge: {e}")
        return 1
    out = ns.out or os.path.join(ns.root, "telemetry", "trace.merged.json")
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    tmp = f"{out}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, out)
    spans = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
    lanes = len(doc["otherData"]["sources"])
    print(f"trace-merge: {spans} span(s) across {lanes} process lane(s) "
          f"-> {out}")
    if offsets:
        for pid, off in sorted(offsets.items()):
            print(f"  clock offset proc{pid}: {off['offset_secs']:+.3f}s "
                  f"(±{off['bound_secs']:.3f}s over "
                  f"{off['observations']} beat observations)")
    else:
        print("  no heartbeat rows found: raw wall clocks trusted "
              "(offsets unknown)")
    return 0
