"""Config system tests (replaces nothing in the reference — it had no tests;
models the flag surface of SURVEY.md §2.16)."""
import json

import pytest

from distributed_resnet_tensorflow_tpu.utils.config import (
    ExperimentConfig, get_preset, parse_args, PRESETS)


def test_presets_exist():
    for name in ("cifar10_resnet50", "cifar100_wrn28_10", "imagenet_resnet50",
                 "imagenet_resnet50_lars32k", "smoke"):
        assert name in PRESETS
        cfg = get_preset(name)
        assert isinstance(cfg, ExperimentConfig)


def test_cifar_preset_matches_reference_recipe():
    """Reference CIFAR recipe: gbs 128, momentum, wd 2e-4, LR drops at
    40k/60k/80k (reference resnet_cifar_main.py:97-99,298-307)."""
    cfg = get_preset("cifar10_resnet50")
    assert cfg.train.batch_size == 128
    assert cfg.optimizer.name == "momentum"
    assert cfg.optimizer.weight_decay == 2e-4
    assert cfg.optimizer.boundaries == (40000, 60000, 80000)
    assert cfg.optimizer.values == (0.1, 0.01, 0.001, 0.0001)


def test_imagenet_preset_matches_reference_recipe():
    """Reference ImageNet recipe (resnet_imagenet_main.py:236-247)."""
    cfg = get_preset("imagenet_resnet50")
    assert cfg.train.batch_size == 1024
    assert cfg.optimizer.warmup_steps == 6240
    assert cfg.optimizer.boundaries == (37440, 74880, 99840)
    assert cfg.optimizer.weight_decay == 1e-4
    assert cfg.model.num_classes == 1001


def test_override_coercion():
    cfg = ExperimentConfig()
    cfg.override("train.batch_size", "256")
    assert cfg.train.batch_size == 256
    cfg.override("model.cross_replica_bn", "false")
    assert cfg.model.cross_replica_bn is False
    cfg.override("optimizer.boundaries", "100,200")
    assert cfg.optimizer.boundaries == (100, 200)
    cfg.override("optimizer.learning_rate", "0.5")
    assert cfg.optimizer.learning_rate == 0.5
    with pytest.raises(KeyError):
        cfg.override("train.nonexistent", "1")


def test_json_roundtrip():
    cfg = get_preset("imagenet_resnet50")
    d = json.loads(cfg.to_json())
    cfg2 = ExperimentConfig.from_dict(d)
    assert cfg2.to_dict() == cfg.to_dict()
    assert cfg2.optimizer.boundaries == cfg.optimizer.boundaries


def test_vit_large_224_preset():
    """The transformer-family >=0.55-MFU contract (measured 0.57,
    docs/perf_vit_classic_r5.md): ViT-L/16 shape, dense attention (196
    tokens is far below the 2k flash crossover), per-chip batch pinned at
    the measured optimum."""
    cfg = get_preset("vit_large_224")
    assert cfg.model.name == "vit"
    assert (cfg.model.vit_dim, cfg.model.vit_depth,
            cfg.model.vit_heads) == (1024, 24, 16)
    assert cfg.data.image_size // cfg.model.vit_patch_size == 14  # 196 tokens
    assert cfg.model.attention_impl == "dense"
    assert cfg.train.batch_size == 32
    assert not cfg.train.remat


def test_parse_args():
    cfg = parse_args(["--preset", "smoke", "--set", "train.train_steps=5"])
    assert cfg.train.train_steps == 5
    assert cfg.data.dataset == "synthetic"


def test_bs512_throughput_preset():
    """The measured single-chip throughput optimum (docs/perf_cifar_r5.md)
    as a preset: linear-scaled LR (x4) with the epoch budget of the
    gbs=128 recipe (4x fewer steps, proportional boundaries)."""
    cfg = get_preset("cifar10_resnet50_bs512")
    base = get_preset("cifar10_resnet50")
    assert cfg.train.batch_size == 4 * base.train.batch_size
    assert cfg.train.train_steps * 4 == base.train.train_steps
    assert cfg.optimizer.values[0] == 4 * base.optimizer.values[0]
    assert len(cfg.optimizer.boundaries) == len(base.optimizer.boundaries)
    assert all(4 * b == bb for b, bb in
               zip(cfg.optimizer.boundaries, base.optimizer.boundaries))
    # epoch budget preserved: steps x batch equal
    assert cfg.train.train_steps * cfg.train.batch_size == \
        base.train.train_steps * base.train.batch_size
