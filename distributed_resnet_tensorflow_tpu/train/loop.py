"""Jitted train/eval steps and the explicit training loop.

Replaces the reference's ``MonitoredTrainingSession`` + hook machinery and
``while not should_stop(): run(train_op)`` hot loop (reference
resnet_cifar_main.py:311-337) with an explicit, functional loop:

    state, metrics = train_step(state, batch)    # one fused XLA program

Everything the reference did with session hooks — LR feed (SURVEY §2.12),
logging cadence, summaries, checkpoints — becomes either (a) pure computation
inside the jitted step (LR schedule, metrics) or (b) plain Python callbacks on
the host (hooks.py), with NO per-step host→device feed_dict traffic.

Distribution: the step is jitted over a Mesh; the batch arrives sharded over
the ``data``(×``fsdp``) axes, so XLA's sharding propagation inserts the
gradient all-reduce on ICI — the entire replacement for SyncReplicasOptimizer
(reference resnet_model.py:102-135) and hvd.DistributedOptimizer (reference
resnet_model.py:114-116). Gradient accumulation (lax.scan over microbatches)
stands in for very large global batches on small meshes.
"""
from __future__ import annotations

import contextlib
import time
from functools import partial
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import (batch_shard_count, create_mesh, data_sharding,
                             present_batch_axes, shard_map_compat)
from ..telemetry.tracer import span
from ..parallel.sharding import (finalize_staged, make_global_batch,
                                 shard_batch)
from .optimizers import (create_optimizer, decoupled_decay,
                         loss_weight_decay)
from .schedules import create_schedule
from .state import TrainState, create_train_state, state_shardings


def per_example_cross_entropy(logits: jax.Array, labels: jax.Array,
                              label_smoothing: float = 0.0) -> jax.Array:
    """Per-example softmax CE (optax path). Labels are int class ids (the
    reference one-hotted in the input pipeline, resnet_cifar_main.py:171;
    we one-hot here once, keeping the input pipeline dense)."""
    num_classes = logits.shape[-1]
    onehot = jax.nn.one_hot(labels, num_classes, dtype=jnp.float32)
    if label_smoothing > 0:
        onehot = onehot * (1 - label_smoothing) + label_smoothing / num_classes
    return optax.softmax_cross_entropy(logits.astype(jnp.float32), onehot)


def cross_entropy_loss(logits: jax.Array, labels: jax.Array,
                       label_smoothing: float = 0.0) -> jax.Array:
    """Mean softmax CE over the batch."""
    return per_example_cross_entropy(logits, labels, label_smoothing).mean()


def make_ce_fn(label_smoothing: float = 0.0, fused_xent: str = "off",
               mesh: Optional[Mesh] = None,
               per_example: bool = False) -> Callable:
    """Resolve ``train.fused_xent`` into the batch CE function.

    Modes: "auto" (Pallas kernel iff running on TPU — the default),
    "on" (always compile the kernel), "interpret" (kernel in the Pallas
    interpreter; CPU tests), "off" (optax). The fused kernel replaces the
    reference's fused softmax_cross_entropy_with_logits TF op in-kind
    (reference resnet_model.py:78-80). Label smoothing > 0 falls back to
    optax (the kernel computes plain NLL).

    When the mesh splits the batch over >1 shards, the kernel runs under
    ``shard_map`` so each device computes its local (b/n, C) tile — a plain
    ``jit`` would have to replicate the custom call (all-gathering logits).

    ``per_example=True`` returns the UNREDUCED (b,) CE with the same mode
    resolution and no shard_map wrap — the inside-shard_map caller
    (parallel/overlap.make_bucketed_grad) is already per-shard, so the
    kernel runs directly on the local tile. One resolver for both paths:
    the overlap loss cannot drift from the jit loss."""
    if fused_xent not in ("auto", "on", "interpret", "off"):
        raise ValueError(f"unknown fused_xent mode {fused_xent!r}")
    mode = fused_xent
    if mode == "auto":
        mode = "on" if jax.default_backend() == "tpu" else "off"
    if mode == "off" or label_smoothing > 0:
        per_ex = lambda logits, labels: per_example_cross_entropy(  # noqa: E731
            logits, labels, label_smoothing)
        if per_example:
            return per_ex
        return lambda logits, labels: per_ex(logits, labels).mean()
    interpret = mode == "interpret"
    from ..ops.pallas import softmax_xent

    def per_ex(logits, labels):
        return softmax_xent(logits.astype(jnp.float32), labels, interpret)

    if per_example:
        return per_ex
    if mesh is not None and batch_shard_count(mesh) > 1:
        batch_axes = present_batch_axes(mesh)
        batch_spec = P(batch_axes)
        sharded = shard_map_compat(
            per_ex, mesh,
            in_specs=(P(batch_axes, None), batch_spec),
            out_specs=batch_spec)
        return lambda logits, labels: sharded(logits, labels).mean()
    return lambda logits, labels: per_ex(logits, labels).mean()


def make_train_step(schedule: Callable, weight_decay: float,
                    label_smoothing: float = 0.0,
                    decay_in_loss: bool = True,
                    grad_accum_steps: int = 1,
                    decay_all_params: bool = False,
                    ce_fn: Optional[Callable] = None,
                    augment_fn: Optional[Callable] = None,
                    augment_seed: int = 0,
                    aux_loss_weight: float = 0.01,
                    value_and_grad_fn: Optional[Callable] = None,
                    apply_gradients_fn: Optional[Callable] = None,
                    precision=None):
    """Build the pure train_step(state, batch) -> (state, metrics).

    ``augment_fn(images, rng) -> images`` runs device-side augmentation at
    the top of the step (raw uint8 in, standardized f32 out — see
    ops/augment.py); RNG is fold_in(seed, step): deterministic and
    resume-stable.

    ``value_and_grad_fn`` replaces ``jax.value_and_grad(loss_fn)`` with a
    custom gradient strategy sharing its exact signature/aux contract —
    the bucketed-overlap exchange (parallel/overlap.make_bucketed_grad)
    plugs in here. With grad_accum_steps > 1 it OWNS the accumulation:
    the microbatch scan runs inside its shard_map body (local f32
    accumulation, one bucketed exchange after the final microbatch —
    per-step wire traffic 1× instead of accum×), so the outer
    ``accum_step`` below is bypassed and per-microbatch augmentation
    (``prep``'s midx draws) moves into the body with it.

    ``apply_gradients_fn(state, grads) -> state`` replaces the default
    ``state.apply_gradients(grads)`` — the ZeRO-1 sharded weight update
    (Trainer._make_zero1_apply: reduce-scattered grads → local optimizer
    shard update → all-gathered param updates) plugs in here.

    ``precision`` (a ``parallel.precision.PrecisionPolicy``, or None =
    the bit-identical legacy path): the policy cast that wraps model
    apply — float inputs enter the model in the policy's compute dtype
    (bf16), while the loss/CE/metric arithmetic around the apply stays
    f32 (make_ce_fn casts logits up before the softmax) and the
    gradients/optimizer update run on the f32 masters."""
    if ce_fn is None:
        ce_fn = make_ce_fn(label_smoothing)
    if value_and_grad_fn is not None:
        # the overlap grad fn owns the accumulation scan — its built-in
        # factor must match this step's, or the 'accumulated' run would
        # silently train one giant microbatch (make_bucketed_grad stamps
        # the attribute; a custom fn without one is assumed accum-free)
        vag_accum = getattr(value_and_grad_fn, "grad_accum_steps", 1)
        if vag_accum != max(1, grad_accum_steps):
            raise ValueError(
                f"value_and_grad_fn was built for grad_accum_steps="
                f"{vag_accum} but the step is configured with "
                f"{grad_accum_steps} — build the overlap grad fn with "
                "the step's accumulation factor "
                "(parallel/overlap.make_bucketed_grad)")
    if apply_gradients_fn is None:
        apply_gradients_fn = lambda state, grads: \
            state.apply_gradients(grads)  # noqa: E731

    def prep(images, step, midx=None):
        if augment_fn is None:
            return images
        rng = jax.random.fold_in(jax.random.PRNGKey(augment_seed), step)
        if midx is not None:  # distinct draws per accumulation microbatch
            rng = jax.random.fold_in(rng, midx)
        return augment_fn(images, rng)

    def loss_fn(params, batch_stats, images, labels, apply_fn):
        variables = {"params": params, "batch_stats": batch_stats}
        if precision is not None:
            # the policy cast wraps model apply (parallel/precision.py):
            # activations enter in the compute dtype; params stay f32
            # masters (flax casts them per-op, and the cast's transpose
            # re-accumulates the gradient into the f32 cotangent)
            images = precision.cast_compute(images)
        logits, mutated = apply_fn(variables, images, train=True,
                                   mutable=["batch_stats", "losses"])
        ce = ce_fn(logits, labels)
        loss = ce
        if decay_in_loss:
            # L2 in the loss like the reference (resnet_model.py:78-86);
            # decay_all_params toggles kernels-only vs all-trainables
            loss = loss + loss_weight_decay(params, weight_decay,
                                            decay_all_params)
        # auxiliary losses sown by modules (e.g. the Switch MoE
        # load-balancing term, models/moe.py)
        aux = jax.tree_util.tree_leaves(mutated.get("losses", {}))
        if aux:
            loss = loss + aux_loss_weight * sum(jnp.sum(a) for a in aux)
        return loss, (ce, logits, mutated["batch_stats"])

    def single_step(state: TrainState, batch) -> Tuple[TrainState, Dict[str, Any]]:
        images, labels = batch["images"], batch["labels"]
        if value_and_grad_fn is None or grad_accum_steps <= 1:
            # the overlap body preps per MICROBATCH itself when it owns
            # the accumulation scan (distinct midx draws, like accum_step)
            images = prep(images, state.step)
        if value_and_grad_fn is not None:
            (loss, (ce, logits, new_bs)), grads = value_and_grad_fn(
                state.params, state.batch_stats, images, labels,
                state.apply_fn, step=state.step)
        else:
            (loss, (ce, logits, new_bs)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params, state.batch_stats,
                                       images, labels, state.apply_fn)
        new_state = apply_gradients_fn(state, grads).replace(
            batch_stats=new_bs)
        precision = jnp.mean(
            (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
        metrics = {
            "loss": loss, "cross_entropy": ce, "precision": precision,
            "learning_rate": schedule(state.step),
            "grad_norm": optax.global_norm(grads),
        }
        return new_state, metrics

    if grad_accum_steps <= 1 or value_and_grad_fn is not None:
        # the overlap exchange owns the accumulation scan (one bucketed
        # exchange per optimizer step, inside its shard_map body)
        return single_step

    def accum_step(state: TrainState, batch) -> Tuple[TrainState, Dict[str, Any]]:
        """lax.scan over microbatches: grads averaged, BN stats from the last
        microbatch (the reference had no accumulation; this enables reference
        global-batch parity on few chips).

        Augmentation/standardization runs INSIDE the scan body, one
        microbatch at a time — prepping the whole global batch up front
        would materialize it in float32 (at gbs 32k × 224² that is ~20 GB,
        more than a chip's HBM; the uint8 input is 4×-8× smaller)."""
        images, labels = batch["images"], batch["labels"]
        n = grad_accum_steps
        mb = images.shape[0] // n
        images = images.reshape((n, mb) + images.shape[1:])
        labels = labels.reshape((n, mb) + labels.shape[1:])

        def body(carry, xs):
            grads_acc, ce_acc, prec_acc, bs = carry
            im, lb, midx = xs
            im = prep(im, state.step, midx)
            grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
            (loss, (ce, logits, new_bs)), grads = grad_fn(
                state.params, bs, im, lb, state.apply_fn)
            prec = jnp.mean((jnp.argmax(logits, -1) == lb).astype(jnp.float32))
            grads_acc = jax.tree_util.tree_map(jnp.add, grads_acc, grads)
            return (grads_acc, ce_acc + ce, prec_acc + prec, new_bs), loss

        zero_grads = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, jnp.float32), state.params)
        (grads, ce_sum, prec_sum, new_bs), losses = jax.lax.scan(
            body, (zero_grads, 0.0, 0.0, state.batch_stats),
            (images, labels, jnp.arange(n)))
        grads = jax.tree_util.tree_map(lambda g: g / n, grads)
        new_state = apply_gradients_fn(state, grads).replace(
            batch_stats=new_bs)
        metrics = {
            "loss": losses.mean(), "cross_entropy": ce_sum / n,
            "precision": prec_sum / n, "learning_rate": schedule(state.step),
            "grad_norm": optax.global_norm(grads),
        }
        return new_state, metrics

    return accum_step


def make_eval_step(prep_fn: Optional[Callable] = None):
    """eval_step(state, batch) -> {correct, count, loss_sum} (summable over
    batches — the reference's numpy precision accumulation,
    resnet_cifar_eval.py:111-122, done on-device instead).

    ``prep_fn(images) -> images`` runs device-side input prep (the
    deterministic VGG standardize when the imagenet iterator ships raw
    uint8 crops — data/__init__.device_augment_enabled decides, both
    sides consult it)."""

    def eval_step(state: TrainState, batch):
        variables = {"params": state.params, "batch_stats": state.batch_stats}
        images = batch["images"]
        if prep_fn is not None:
            images = prep_fn(images)
        logits = state.apply_fn(variables, images, train=False)
        labels = batch["labels"]
        # optional "mask" marks padding in the final partial batch
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones((labels.shape[0],), jnp.float32)
        hit = (jnp.argmax(logits, -1) == labels).astype(jnp.float32)
        onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
        per_ex_ce = optax.softmax_cross_entropy(
            logits.astype(jnp.float32), onehot)
        return {"correct": jnp.sum(hit * mask).astype(jnp.int32),
                "count": jnp.sum(mask).astype(jnp.int32),
                "loss_sum": jnp.sum(per_ex_ce * mask)}

    return eval_step


def make_predict_step(prep_fn: Optional[Callable] = None,
                      precision=None, apply_fn: Optional[Callable] = None):
    """predict_step(state, batch) -> float32 logits — the SERVING forward
    (serve/): eval's forward pass without the metric reduction, so the
    dynamic batcher can slice per-request rows out of one bucket dispatch.
    Padding rows (serve buckets) simply produce logits nobody reads; with
    ``train=False`` BN uses running stats, so each row's logits are
    independent of its batchmates — bucket-batched serving is numerically
    the unbatched eval forward.

    ``prep_fn`` is the SAME device-side input prep the eval step uses
    (make_eval_step) — the serve path must agree with eval about who
    standardizes or requests would be double-/un-normalized.

    ``precision`` applies the policy input cast AFTER prep (prep
    standardizes in f32, the model computes in the policy dtype); logits
    always leave f32. ``apply_fn`` overrides ``state.apply_fn`` — the
    serving reduced-precision VARIANT's apply
    (Trainer.make_variant_predict_step builds a same-architecture model
    with a different compute dtype), so one TrainState layout serves
    every variant."""

    def predict_step(state: TrainState, batch):
        variables = {"params": state.params, "batch_stats": state.batch_stats}
        images = batch["images"]
        if prep_fn is not None:
            images = prep_fn(images)
        if precision is not None:
            images = precision.cast_compute(images)
        fn = apply_fn if apply_fn is not None else state.apply_fn
        logits = fn(variables, images, train=False)
        return logits.astype(jnp.float32)

    return predict_step


class Trainer:
    """End-to-end orchestration: mesh + model + optimizer + jitted steps.

    The constructor is the successor of the reference main() bodies
    (reference resnet_cifar_main.py:339-399): build input, build model, build
    train op, pick devices — minus the ps/worker split, which no longer exists.
    """

    def __init__(self, cfg, mesh: Optional[Mesh] = None):
        self.cfg = cfg
        self.mesh = mesh if mesh is not None else create_mesh(cfg.mesh)
        from ..models import create_model
        # mixed-precision policy (parallel/precision.py; docs/precision.md):
        # resolved FIRST because it overrides the model's compute dtype —
        # train.precision=off keeps the legacy model.compute_dtype
        # contract BIT-identical (no policy code on that path)
        from ..parallel.precision import precision_stats, resolve_precision
        self._precision = resolve_precision(cfg)
        # bucketed gradient-communication overlap (parallel/overlap.py):
        # resolved BEFORE the model build because the shard_map'd step
        # computes per-shard BN moments — the model must pmean them over
        # the batch axes (GroupedBatchNorm axis_name) to keep the
        # cross-replica-BN numerics. comm.overlap=on raises here when the
        # (model, mesh, train) combination is outside the envelope.
        from ..parallel.overlap import (BATCH_AXES, compress_dtype,
                                        resolve_overlap)
        self._overlap = resolve_overlap(cfg, self.mesh)
        bn_axis_name = BATCH_AXES if self._overlap is not None else None
        # compressed gradient exchange (comm.compress) rides the bucketed
        # overlap — validate the knob even when the exchange is off, and
        # warn LOUDLY when compression was requested but nothing will
        # compress (the echo_transfer-warning contract: a silently
        # unbucketed run would never halve a byte)
        requested_compress = compress_dtype(cfg)
        if requested_compress is not None and self._overlap is None:
            import logging
            logging.getLogger(__name__).warning(
                "comm.compress=%s with comm.overlap resolved OFF: "
                "compression rides the bucketed gradient exchange "
                "(parallel/overlap.py), so this run exchanges FULL f32 "
                "payloads — enable comm.overlap (or accept the "
                "uncompressed exchange)", cfg.comm.compress)
        # the hierarchical exchange and the startup autotune pass ride
        # the same exchange — validate their knobs even when overlap is
        # off, and warn loudly when they were requested but cannot act
        # (the compress-warning contract above)
        from ..parallel.overlap import autotune_mode, resolve_hierarchy
        self._autotune = autotune_mode(cfg)
        if self._overlap is None:
            resolve_hierarchy(cfg, self.mesh)  # validate / raise on =on
            if cfg.comm.hierarchy != "off" or self._autotune != "off":
                import logging
                logging.getLogger(__name__).warning(
                    "comm.hierarchy=%s / comm.autotune=%s with "
                    "comm.overlap resolved OFF: both ride the bucketed "
                    "exchange (parallel/overlap.py), so neither can act "
                    "— enable comm.overlap",
                    cfg.comm.hierarchy, cfg.comm.autotune)
        elif self._autotune == "startup" and not cfg.telemetry.comm_timing:
            import logging
            logging.getLogger(__name__).warning(
                "comm.autotune=startup without telemetry.comm_timing: "
                "the startup pass tunes FROM the comm probe's "
                "measurements (parallel/overlap.probe_comm_plan) — "
                "autotune degrades to off", )
            self._autotune = "off"
        self._comm_tuned = False
        self._comm_retuned = False
        # ZeRO-1 sharded weight update (arXiv:2004.13336; parallel/
        # sharding.py rule table): optimizer state shards over `data`,
        # gradients reduce-scatter into the shard layout, the update runs
        # on 1/N state per replica, param updates all-gather back.
        # optimizer.zero1=on raises here when the (mesh) is outside the
        # envelope; the replicated (off) path stays bit-identical to the
        # pre-ZeRO step — the exactness oracle the tests pin against.
        from ..parallel.sharding import resolve_zero1
        self._zero1 = resolve_zero1(cfg, self.mesh)
        # cross_replica_bn=True (default): global BN moments — one group.
        # False: reference-faithful per-replica BN — one moment group per
        # batch shard (see ops/batch_norm.py).
        bn_groups = 1 if cfg.model.cross_replica_bn else batch_shard_count(self.mesh)
        # reject dead-axis configs loudly (a >1 axis that shards nothing
        # would silently waste chips): seq/tensor/pipeline/expert only have
        # consumers in the transformer family
        if cfg.model.name != "vit":
            for axis in ("seq", "tensor", "pipeline", "expert"):
                if self.mesh.shape.get(axis, 1) > 1:
                    raise ValueError(
                        f"mesh axis {axis!r} > 1 requires model.name='vit' "
                        f"(got {cfg.model.name!r}); ResNets parallelize over "
                        "data/fsdp")
        else:
            n_exp_axis = self.mesh.shape.get("expert", 1)
            if n_exp_axis > 1:
                if cfg.model.vit_num_experts <= 0:
                    raise ValueError(
                        "mesh axis 'expert' > 1 requires a MoE model: set "
                        "model.vit_num_experts")
                if cfg.model.vit_num_experts % n_exp_axis:
                    raise ValueError(
                        f"vit_num_experts={cfg.model.vit_num_experts} not "
                        f"divisible by the expert axis ({n_exp_axis})")
                # indivisible tensor splits (expert FFNs etc.) warn at the
                # drop-back site itself: parallel/sharding.py
                # _warn_tensor_dropback covers every leaf, not just MoE
            # MoE×tensor composes since round 5: expert FFNs are
            # Megatron-split over `tensor` (parallel/sharding.py SwitchMlp
            # rule, stacked_encoder_spec moe leaves, expert_ffn psum), so
            # ep×tp and pp×ep×tp shard rather than replicate the expert
            # FLOPs. Indivisible hidden dims degrade to replicated weights
            # (the sharding rules check divisibility leaf-by-leaf).
            # pp composes with dp/fsdp (microbatch over local batch), tp
            # (Megatron psums inside each stage), ep (stacked-stage Switch
            # MoE, models/pipeline.py _moe_mlp) and, since round 5, seq
            # (ring attention inside the stage blocks) — no remaining
            # pairwise rejection on the pipeline axis.
        # model-resolution choices saved for the serving variant builder
        # (make_variant_predict_step): a variant must differ ONLY in
        # compute dtype, never in BN wiring or remat
        self._bn_axis_name = bn_axis_name
        self._bn_groups = bn_groups
        self.model = create_model(cfg.model, cfg.data.dataset,
                                  axis_name=bn_axis_name,
                                  remat=cfg.train.remat, bn_groups=bn_groups,
                                  mesh=self.mesh,
                                  compute_dtype=self._precision.compute_dtype
                                  if self._precision is not None else None)
        precision_stats.record_policy(
            self._precision,
            self._overlap.compress if self._overlap is not None else None)
        self.schedule = create_schedule(cfg.optimizer)
        decay_in_loss = not decoupled_decay(cfg.optimizer.name)
        if cfg.optimizer.decay_all_params and not decay_in_loss:
            # LARS/AdamW take decay inside the optimizer (non-BN mask); the
            # reference-faithful all-params L2 only exists on the loss path
            raise ValueError(
                "optimizer.decay_all_params is incompatible with "
                f"optimizer.name={cfg.optimizer.name!r} (decoupled decay "
                "is applied inside the optimizer)")
        self.tx = create_optimizer(cfg.optimizer, self.schedule)
        ct = cfg.data.coalesced_transfer
        if ct not in ("auto", "on", "off"):
            raise ValueError(f"unknown coalesced_transfer setting {ct!r}")
        if ct == "auto":
            # like data.device_augment: auto = on iff a real accelerator is
            # attached. Coalescing exists to amortize per-call transfer
            # overhead on a device link; on the CPU backend (tests, tiny
            # local runs) the extra pack/unpack per batch only costs
            ct = "off" if jax.default_backend() == "cpu" else "on"
        self._coalesced = ct == "on"
        from ..data import device_augment_enabled
        aug_fn = None
        # (leaf, kind, pad) when the imagenet train augmentation FUSES into
        # the CoalescedStager's unpack program (parallel/sharding.py): one
        # XLA program unpacks the staged uint8 bytes AND flips/jitters/
        # standardizes them, keyed per staged batch. Requires the stager,
        # and is OFF under data.echo_transfer > 1: transfer reuse re-runs
        # the STEP on one staged batch, so the augment must draw inside
        # the step (step-keyed RNG) to stay fresh per reuse.
        self._train_augment_spec = None
        # Only the iterator/step contract decides who augments. A streamed
        # iterator with device_augment off yields host-augmented float32, so
        # forcing the device path here would double-augment; when a device
        # dataset (raw uint8 in HBM) is actually attached,
        # attach_device_dataset forces the augment step on itself.
        if device_augment_enabled(cfg, "train"):
            from ..ops.augment import device_augment_fn
            if cfg.data.dataset == "imagenet":
                spec = ("images", "imagenet_train", cfg.data.augment_pad)
                if self._coalesced and cfg.data.echo_transfer <= 1:
                    self._train_augment_spec = spec
                else:
                    aug_fn = device_augment_fn(spec[1], spec[2])
            else:
                from ..ops.augment import cifar_train_augment
                aug_fn = cifar_train_augment
        if cfg.data.echo_transfer > 1 and aug_fn is None \
                and self._train_augment_spec is None:
            # without device-side augmentation a reused dispatch repeats
            # the SAME pixels: k>1 still reshuffles batch composition on
            # device, but k=1 reuses are bit-identical replays — probably
            # not what the operator meant by echoing
            import logging
            logging.getLogger(__name__).warning(
                "data.echo_transfer=%d with no device-side augmentation "
                "(device_augment resolved off): reused dispatches repeat "
                "identical samples (steps_per_loop=1: identical batches). "
                "Enable data.device_augment, or prefer data.echo_factor "
                "(host echo reshuffles every batch)",
                cfg.data.echo_transfer)
        self._aug_fn = aug_fn
        self._cfg_aug_fn = aug_fn  # the config-resolved choice, for detach
        self._train_step = self._build_train_step(aug_fn)
        eval_prep = None
        if cfg.data.dataset == "imagenet" and \
                device_augment_enabled(cfg, "eval"):
            from ..ops.augment import vgg_standardize
            eval_prep = vgg_standardize
        self._eval_prep = eval_prep
        self._eval_step = make_eval_step(eval_prep)
        # serving forward (serve/; elaborated per bucket by
        # analysis/elaborate.py): same prep contract as the eval step
        self._predict_step = make_predict_step(eval_prep,
                                               precision=self._precision)
        self._jitted_train = None
        self._jitted_multi = None
        self._jitted_eval = None
        self._jitted_predict = None
        self._dev_prefetch = None
        self._multi_prefetch = None
        self._dev_data = None
        self._jitted_idx = None
        self._jitted_idx_multi = None
        self.state: Optional[TrainState] = None
        # per-collective runtime attribution (telemetry.comm_timing):
        # one standalone timing pass over the bucketed-exchange plan per
        # process, fired at the first loop boundary after the plan traces
        # (parallel/overlap.probe_comm_plan; every process participates —
        # the probe runs collectives)
        self._comm_probed = False
        # optional resilience/heartbeat.HeartbeatPublisher (set by
        # main.run_train when the watchdog is enabled): evaluate() ticks it
        # per eval batch so hang detection stays live outside the train
        # loop — eval makes no optimizer-step progress, and without ticks a
        # long eval round would read as a wedged process
        self.heartbeat = None
        if self._coalesced:
            # coalesced staging (parallel/sharding.CoalescedStager): one
            # contiguous ring-buffered host region per device, a single
            # device_put issue per batch, per-shard placement via
            # make_array_from_single_device_arrays — covers single- AND
            # multi-process (each process contributes its local regions)
            from ..parallel.sharding import CoalescedStager
            ring = max(cfg.data.staging_ring, cfg.data.transfer_depth + 2)
            self._put_batch = CoalescedStager(self.mesh, stacked=False,
                                              ring=ring)
            self._put_multi_batch = CoalescedStager(self.mesh, stacked=True,
                                                    ring=ring)
            if self._train_augment_spec is not None:
                # TRAIN-only stagers whose unpack program fuses the
                # device augmentation; eval/serve keep the neutral
                # stagers above (an augmenting put must never touch
                # their batches)
                self._put_train_batch = CoalescedStager(
                    self.mesh, stacked=False, ring=ring,
                    augment=self._train_augment_spec,
                    augment_seed=cfg.train.seed)
                self._put_train_multi_batch = CoalescedStager(
                    self.mesh, stacked=True, ring=ring,
                    augment=self._train_augment_spec,
                    augment_seed=cfg.train.seed)
            else:
                self._put_train_batch = self._put_batch
                self._put_train_multi_batch = self._put_multi_batch
        else:
            if jax.process_count() > 1:
                # per-leaf fallback. single-process: device_put the full
                # batch sharded; multi-process: every process contributes
                # its local shard of the global array
                from ..parallel.sharding import make_global_stacked_batch
                self._put_batch = lambda b: make_global_batch(b, self.mesh)
                self._put_multi_batch = \
                    lambda b: make_global_stacked_batch(b, self.mesh)
            else:
                from ..parallel.sharding import shard_stacked_batch
                self._put_batch = lambda b: shard_batch(b, self.mesh)
                self._put_multi_batch = \
                    lambda b: shard_stacked_batch(b, self.mesh)
            self._put_train_batch = self._put_batch
            self._put_train_multi_batch = self._put_multi_batch

    def _zero1_min_size(self) -> int:
        from ..parallel.sharding import ZERO1_MIN_SIZE
        return self.cfg.optimizer.zero1_min_size or ZERO1_MIN_SIZE

    def _state_shardings(self, shapes):
        """state_shardings with this Trainer's resolved ZeRO-1 choice —
        the ONE resolution point every jitted entry uses, so the live
        state, the jit in/out shardings and the grad constraint cannot
        disagree about the optimizer layout."""
        return state_shardings(shapes, self.mesh, zero1=self._zero1,
                               zero1_min_size=self._zero1_min_size())

    def _make_zero1_apply(self):
        """The ZeRO-1 weight update, ``(state, grads) -> state``:
        gradients pinned to the rule-table shard layout (on the jit path
        the ``with_sharding_constraint`` turns the all-reduce XLA would
        emit into reduce-scatter — the arXiv:2004.13336 transformation;
        on the overlap path the bucketed exchange already reduce-scattered
        them), the optimizer transform then runs on each replica's 1/N
        shard (cross-shard reductions like the LARS/LAMB trust-ratio
        norms get their collectives from sharding propagation), and the
        param updates return to the base layout — through the bucketed
        all-gather when the overlap path is active, else through the jit
        output sharding's gather."""
        mesh = self.mesh
        min_size = self._zero1_min_size()
        plan = self._overlap

        def apply_gradients_fn(state, grads):
            from jax.lax import with_sharding_constraint
            from ..parallel.sharding import zero1_grad_specs
            specs = zero1_grad_specs(state.params, mesh,
                                     min_size=min_size)
            shard_tree = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), specs,
                is_leaf=lambda x: isinstance(x, P))
            grads = with_sharding_constraint(grads, shard_tree)
            updates, new_opt = state.tx.update(grads, state.opt_state,
                                               state.params)
            updates = with_sharding_constraint(updates, shard_tree)
            if plan is not None:
                from ..parallel.overlap import make_bucketed_gather
                updates = make_bucketed_gather(plan, mesh, specs)(updates)
            import optax as _optax
            new_params = _optax.apply_updates(state.params, updates)
            return state.replace(step=state.step + 1, params=new_params,
                                 opt_state=new_opt)

        return apply_gradients_fn

    def _build_train_step(self, aug_fn):
        cfg = self.cfg
        vag = None
        if self._overlap is not None:
            # bucketed dp/dp_fsdp gradient exchange replaces the implicit
            # XLA-propagation all-reduce (parallel/overlap.py): the CE /
            # decay / aux-loss recipe is mirrored inside the shard_map
            # body, so the loss semantics are identical to loss_fn's
            from ..parallel.overlap import make_bucketed_grad
            vag = make_bucketed_grad(
                self._overlap, self.mesh,
                weight_decay=cfg.optimizer.weight_decay,
                decay_in_loss=not decoupled_decay(cfg.optimizer.name),
                decay_all_params=cfg.optimizer.decay_all_params,
                label_smoothing=cfg.optimizer.label_smoothing,
                fused_xent=cfg.train.fused_xent,
                aux_loss_weight=cfg.model.moe_aux_weight,
                zero1_min_size=self._zero1_min_size()
                if self._zero1 else None,
                precision=self._precision,
                grad_accum_steps=cfg.train.grad_accum_steps,
                augment_fn=aug_fn, augment_seed=cfg.train.seed)
        return make_train_step(
            self.schedule, cfg.optimizer.weight_decay,
            cfg.optimizer.label_smoothing,
            decay_in_loss=not decoupled_decay(cfg.optimizer.name),
            grad_accum_steps=cfg.train.grad_accum_steps,
            decay_all_params=cfg.optimizer.decay_all_params,
            ce_fn=make_ce_fn(cfg.optimizer.label_smoothing,
                             cfg.train.fused_xent, self.mesh),
            augment_fn=aug_fn, augment_seed=cfg.train.seed,
            aux_loss_weight=cfg.model.moe_aux_weight,
            value_and_grad_fn=vag,
            apply_gradients_fn=self._make_zero1_apply()
            if self._zero1 else None,
            precision=self._precision)

    @property
    def comm_overlap_active(self) -> bool:
        """True when the train step exchanges gradients through the
        bucketed overlap path (parallel/overlap.py)."""
        return self._overlap is not None

    @property
    def zero1_active(self) -> bool:
        """True when the optimizer state and weight update are sharded
        over the ``data`` axis (parallel/sharding.py ZeRO-1 rule table)."""
        return self._zero1

    @property
    def precision_active(self) -> bool:
        """True when a mixed-precision policy (train.precision) shapes
        the step: bf16 compute over f32 masters
        (parallel/precision.py)."""
        return self._precision is not None

    @property
    def comm_compress_active(self) -> bool:
        """True when the gradient exchange actually compresses its
        payloads (comm.compress riding an active bucketed overlap)."""
        return self._overlap is not None and \
            self._overlap.compress is not None

    def make_variant_predict_step(self, variant: str):
        """The serving VARIANT forward (serve/compile_cache.py buckets
        are (batch, variant)): a predict step whose model computes in
        the variant's compute dtype
        (``parallel.precision.SERVE_VARIANT_DTYPES``), sharing every
        other model-resolution choice with this Trainer (BN axis/groups,
        remat, prep contract) so the variant differs only in precision.
        The caller supplies the matching (cast) TrainState — the step
        uses its own apply, not ``state.apply_fn``.

        Weight-only variants ("int8"): the cast state carries quantized
        ``{"int8_q", "int8_scale"}`` kernels, so the apply first
        dequantizes them (``parallel.precision.dequantize_params`` —
        fused into the consuming ops by XLA) and the model computes f32
        over int8-at-rest weights."""
        from ..models import create_model
        from ..parallel.precision import (SERVE_VARIANT_DTYPES,
                                          WEIGHT_ONLY_VARIANTS,
                                          dequantize_params)
        model = create_model(self.cfg.model, self.cfg.data.dataset,
                             axis_name=self._bn_axis_name,
                             remat=self.cfg.train.remat,
                             bn_groups=self._bn_groups, mesh=self.mesh,
                             compute_dtype=SERVE_VARIANT_DTYPES[variant])
        apply_fn = model.apply
        if variant in WEIGHT_ONLY_VARIANTS:
            def apply_fn(variables, *args, _apply=model.apply, **kw):
                variables = dict(variables)
                variables["params"] = dequantize_params(
                    variables["params"])
                return _apply(variables, *args, **kw)
        return make_predict_step(self._eval_prep, apply_fn=apply_fn)

    # -- state ------------------------------------------------------------
    def init_state(self, seed: Optional[int] = None) -> TrainState:
        rng = jax.random.PRNGKey(self.cfg.train.seed if seed is None else seed)
        c = self.cfg
        # one example per batch shard: shard_map-based ops (ring attention)
        # need the init dummy batch divisible by the batch mesh axes
        nb = batch_shard_count(self.mesh)
        shape = (nb, c.data.image_size, c.data.image_size, 3) \
            if c.model.name != "logistic" else (nb, c.model.input_size)
        self.state = create_train_state(
            rng, self.model, self.tx, shape, mesh=self.mesh,
            zero1=self._zero1, zero1_min_size=self._zero1_min_size())
        if self._precision is not None:
            # the policy's checkpoint contract: f32 MASTERS only — a cast
            # param leaf here would bake the compute dtype into every
            # checkpoint this run writes (parallel/precision.py)
            from ..parallel.precision import (check_master_dtypes,
                                              precision_stats)
            check_master_dtypes(self.state.params,
                                self._precision.master_dtype)
            precision_stats.record_params(self.state.params)
        return self.state

    # -- jitted steps ------------------------------------------------------
    def jitted_train_step(self):
        if self._jitted_train is None:
            shapes = jax.eval_shape(lambda s: s, self.state)
            st_sh = self._state_shardings(shapes)
            b_sh = data_sharding(self.mesh)
            self._jitted_train = jax.jit(
                self._train_step,
                in_shardings=(st_sh, {"images": b_sh, "labels": b_sh}),
                out_shardings=(st_sh, None),
                donate_argnums=(0,))
        return self._jitted_train

    @property
    def train_put_augments(self) -> bool:
        """True when the train put path's unpack program carries the fused
        device augmentation (so train batches come out float32 and the
        step itself has no augment op) — bench and tests size their probe
        batches by this."""
        return self._train_augment_spec is not None

    def jitted_multi_step(self, k: int = 0):
        """Fused optimizer steps per dispatch: lax.scan over stacked batches
        (the step count comes from the input's leading axis; ``k`` is
        documentation only). Returns (state, metrics-of-last-step).

        With ``data.echo_transfer`` > 1 the program starts by reshuffling
        the group's batch composition with a step-keyed on-device
        permutation over the flattened K×B samples: each REUSE of one
        staged group (train() dispatches it echo_transfer times) trains on
        differently-composed batches — the transfer-level echo's analog of
        the host echo cache's per-echo reshuffle, at zero extra
        host→device traffic."""
        del k
        if self._jitted_multi is None:
            step = self._train_step
            unroll = max(1, self.cfg.train.scan_unroll)
            reshuffle = self.cfg.data.echo_transfer > 1
            perm_seed = self.cfg.train.seed + 0x5EED

            def multi(state, batches):
                if reshuffle:
                    lead = batches["labels"].shape
                    kb = lead[0] * lead[1]
                    perm = jax.random.permutation(
                        jax.random.fold_in(jax.random.PRNGKey(perm_seed),
                                           state.step), kb)

                    def resh(x):
                        flat = x.reshape((kb,) + x.shape[2:])
                        return jnp.take(flat, perm,
                                        axis=0).reshape(x.shape)

                    batches = jax.tree_util.tree_map(resh, batches)

                def body(s, batch):
                    s, m = step(s, batch)
                    return s, m
                state, ms = jax.lax.scan(body, state, batches, unroll=unroll)
                last = jax.tree_util.tree_map(lambda x: x[-1], ms)
                return state, last

            shapes = jax.eval_shape(lambda s: s, self.state)
            st_sh = self._state_shardings(shapes)
            b_sh = NamedSharding(
                self.mesh, P(None, *data_sharding(self.mesh).spec))
            self._jitted_multi = jax.jit(
                multi,
                in_shardings=(st_sh, {"images": b_sh, "labels": b_sh}),
                out_shardings=(st_sh, None),
                donate_argnums=(0,))
        return self._jitted_multi

    def jitted_eval_step(self):
        if self._jitted_eval is None:
            self._jitted_eval = jax.jit(self._eval_step)
        return self._jitted_eval

    def jitted_predict_step(self):
        """JIT entry for the serving forward — tests and ad-hoc callers;
        the serving hot path AOT-compiles the same ``_predict_step`` per
        batch bucket instead (serve/compile_cache.py) so the first request
        never pays a compile."""
        if self._jitted_predict is None:
            self._jitted_predict = jax.jit(self._predict_step)
        return self._jitted_predict

    # -- device-resident dataset (data/device_dataset.py) ------------------
    def attach_device_dataset(self, images, labels) -> None:
        """Upload the full dataset to HBM (replicated); train() then expects
        an index iterator ({"idx": (bs,) int32}) and gathers batches on
        device. Single-process only.

        The dataset is raw uint8, so the step MUST augment+standardize on
        device — if the Trainer was built without an augment_fn (e.g. config
        resolved device_augment off on a CPU backend), rebuild the step with
        one rather than silently training on unnormalized pixels."""
        if jax.process_count() > 1:
            raise ValueError("device dataset requires a single process")
        if self._aug_fn is None:
            # the idx path bypasses the put stagers, so a FUSED train
            # augmentation (carried by the stager's unpack, step aug_fn
            # None) must move back into the step — and it must be the
            # config's own augmentation, not the cifar default, or an
            # imagenet Trainer would train on cifar-normalized pixels
            from ..ops.augment import device_augment_fn
            if self._train_augment_spec is not None:
                _, kind, pad = self._train_augment_spec
                self._aug_fn = device_augment_fn(kind, pad)
            else:
                self._aug_fn = device_augment_fn("cifar_train")
            self._train_step = self._build_train_step(self._aug_fn)
            self._jitted_train = None
            self._jitted_multi = None
        from ..parallel.mesh import replicated
        from ..parallel.sharding import put_to_sharding
        rep = replicated(self.mesh)
        import numpy as np
        self._dev_data = (put_to_sharding(np.asarray(images), rep),
                          put_to_sharding(np.asarray(labels), rep))
        self._jitted_idx = None
        self._jitted_idx_multi = None

    def detach_device_dataset(self) -> None:
        """Drop the HBM dataset and restore the config-resolved augment
        choice (attach may have forced device-side augmentation; a streamed
        iterator on a non-TPU backend standardizes on the host, and keeping
        the forced augment would double-augment)."""
        self._dev_data = None
        self._jitted_idx = None
        self._jitted_idx_multi = None
        if self._aug_fn is not self._cfg_aug_fn:
            self._aug_fn = self._cfg_aug_fn
            self._train_step = self._build_train_step(self._aug_fn)
            self._jitted_train = None
            self._jitted_multi = None

    def _gathered_step(self):
        step = self._train_step

        def fn(state, batch, images, labels):
            idx = batch["idx"]
            return step(state, {"images": jnp.take(images, idx, axis=0),
                                "labels": jnp.take(labels, idx, axis=0)})
        return fn

    def jitted_index_step(self):
        if self._dev_data is None:
            # a RuntimeError (not assert): the guard must survive python -O
            raise RuntimeError(
                "jitted_index_step requires an attached device dataset "
                "(attach_device_dataset)")
        if self._jitted_idx is None:
            from ..parallel.mesh import replicated
            shapes = jax.eval_shape(lambda s: s, self.state)
            st_sh = self._state_shardings(shapes)
            b_sh = data_sharding(self.mesh)
            rep = replicated(self.mesh)
            jit_fn = jax.jit(
                self._gathered_step(),
                in_shardings=(st_sh, {"idx": b_sh}, rep, rep),
                out_shardings=(st_sh, None),
                donate_argnums=(0,))
            self._jitted_idx_raw = jit_fn
            self._jitted_idx = \
                lambda s, b: jit_fn(s, b, *self._dev_data)
        return self._jitted_idx

    def step_flops(self, batch) -> Optional[float]:
        """XLA cost-analysis FLOPs of one compiled optimizer step. ``batch``
        is one host batch as the training iterator yields it ({"images",..}
        or {"idx"}). Uses the same jit entry training uses, so the lowering
        warms the compile cache rather than adding a compile."""
        from ..utils import profiling
        if self._dev_data is not None and "idx" in batch:
            self.jitted_index_step()
            return profiling.flops_per_step(
                self._jitted_idx_raw, self.state, self._put_idx(batch),
                *self._dev_data)
        # the TRAIN put path: with the fused-augment stager the step's
        # traced program expects the unpack's augmented float32 images,
        # and the counted FLOPs then include the on-device augmentation
        return profiling.flops_per_step(
            self.jitted_train_step(), self.state,
            finalize_staged(self._put_train_batch(batch)))

    def jitted_index_multi_step(self, k: int = 0):
        del k
        if self._dev_data is None:
            raise RuntimeError(
                "jitted_index_multi_step requires an attached device "
                "dataset (attach_device_dataset)")
        if self._jitted_idx_multi is None:
            from ..parallel.mesh import replicated
            gathered = self._gathered_step()
            unroll = max(1, self.cfg.train.scan_unroll)

            def multi(state, batches, images, labels):
                def body(s, batch):
                    return gathered(s, batch, images, labels)
                state, ms = jax.lax.scan(body, state, batches, unroll=unroll)
                last = jax.tree_util.tree_map(lambda x: x[-1], ms)
                return state, last

            shapes = jax.eval_shape(lambda s: s, self.state)
            st_sh = self._state_shardings(shapes)
            b_sh = NamedSharding(
                self.mesh, P(None, *data_sharding(self.mesh).spec))
            rep = replicated(self.mesh)
            jit_fn = jax.jit(
                multi,
                in_shardings=(st_sh, {"idx": b_sh}, rep, rep),
                out_shardings=(st_sh, None),
                donate_argnums=(0,))
            self._jitted_idx_multi = \
                lambda s, b: jit_fn(s, b, *self._dev_data)
        return self._jitted_idx_multi

    def _put_idx(self, batch):
        from ..parallel.sharding import put_to_sharding
        return put_to_sharding(batch, {"idx": data_sharding(self.mesh)})

    def _put_idx_multi(self, batch):
        from ..parallel.sharding import put_to_sharding
        sh = NamedSharding(self.mesh, P(None, *data_sharding(self.mesh).spec))
        return put_to_sharding(batch, {"idx": sh})

    # -- resilience --------------------------------------------------------
    def scale_lr(self, scale: float) -> None:
        """Rebuild the LR schedule multiplied by ``scale`` and invalidate
        the jitted steps — the NaN sentinel's back-off knob
        (resilience/sentinel.py). Costs one recompile on the recovery path;
        the hot path is untouched at scale 1. The live TrainState's
        optimizer is swapped too (tx is a static field, so replace() keeps
        the restored pytree leaves)."""
        base = create_schedule(self.cfg.optimizer)
        self.schedule = base if scale == 1.0 else \
            (lambda step: base(step) * scale)
        self.tx = create_optimizer(self.cfg.optimizer, self.schedule)
        self._train_step = self._build_train_step(self._aug_fn)
        self._jitted_train = None
        self._jitted_multi = None
        self._jitted_idx = None
        self._jitted_idx_multi = None
        if self.state is not None:
            self.state = self.state.replace(tx=self.tx)

    def _maybe_probe_comm(self) -> None:
        """Run the per-bucket collective timing probe ONCE per process,
        the first time the bucketed exchange's plan is available
        (parallel/overlap.probe_comm_plan → utils.metrics.
        comm_timing_stats → the chief's comm_timing rows). Called at step
        dispatch boundaries; every process reaches the same boundary in
        the same order, so the probe's collectives are SPMD-safe. Must
        never kill training — the probe itself swallows measurement
        errors."""
        if self._comm_probed or not self.comm_overlap_active \
                or not self.cfg.telemetry.comm_timing:
            return
        from ..parallel.overlap import (hierarchy_factor, overlap_stats,
                                        probe_comm_plan)
        if overlap_stats.snapshot() is None:
            return  # the step has not traced yet
        self._comm_probed = True
        # the tier legs probe whenever the mesh factors — a flat plan
        # still measures intra/inter bandwidth so the autotune pass (and
        # the offline planner, via the catalog) can rank hierarchy
        hier_k = self._overlap.hierarchy
        if hier_k is None and self._autotune == "startup":
            try:
                hier_k = hierarchy_factor(self.cfg, self.mesh)
            except ValueError:
                hier_k = None
        result = probe_comm_plan(self.mesh,
                                 reps=self.cfg.telemetry.comm_timing_reps,
                                 hier_k=hier_k)
        if result is not None and self._autotune == "startup" \
                and not self._comm_tuned:
            self._comm_tuned = True
            self._retune_comm(result, hier_k)

    def _retune_comm(self, probe_result: dict,
                     hier_k: Optional[int]) -> None:
        """The startup autotune pass (comm.autotune=startup): feed the
        probe's measurements into the planner's cost model
        (telemetry/planner.tune_comm_plan), and when the chosen plan
        differs from the running one, REBUILD the train step around it —
        the tuned plan re-traces, re-records its declared schedule, and
        the next ``_maybe_probe_comm`` boundary re-probes it (guarded by
        ``_comm_tuned`` against a tune loop). Never raises: a failed
        tune keeps the configured plan."""
        import logging
        log = logging.getLogger(__name__)
        try:
            from ..parallel.overlap import overlap_stats
            from ..telemetry.planner import BandwidthTable, tune_comm_plan
            snap = overlap_stats.snapshot()
            if snap is None:
                return
            table = BandwidthTable.from_probe(probe_result)
            choice = tune_comm_plan(
                snap, table,
                intra_k=hier_k,
                bucket_mb=self.cfg.comm.bucket_mb)
        except Exception:
            log.exception("comm autotune failed; keeping the configured "
                          "plan")
            return
        plan = self._overlap
        import dataclasses as _dc
        tuned = _dc.replace(
            plan,
            bucket_bytes=int(choice["bucket_mb"] * 2 ** 20),
            compress=None if choice["compress"] == "off"
            else choice["compress"],
            hierarchy=choice["hierarchy"] or None,
            tuned=True)
        log.info("comm autotune (startup): chose bucket_mb=%s compress=%s "
                 "hierarchy=%s (%s)", choice["bucket_mb"],
                 choice["compress"], choice["hierarchy"] or "flat",
                 choice.get("fallback") or "cost model")
        changed = (tuned.bucket_bytes, tuned.compress, tuned.hierarchy) \
            != (plan.bucket_bytes, plan.compress, plan.hierarchy)
        # rebuild even on a no-change choice: the re-traced plan records
        # tuned=True into overlap_stats, so the comm_overlap row and the
        # schedule artifact show the plan was CHOSEN, not just configured
        self._overlap = tuned
        self._train_step = self._build_train_step(self._aug_fn)
        self._jitted_train = None
        self._jitted_multi = None
        self._jitted_idx = None
        self._jitted_idx_multi = None
        # the hot loops cache the jitted fn in a local — this flag tells
        # them to re-fetch it so the tuned plan takes over MID-RUN (the
        # startup pass must tune the very training it probed)
        self._comm_retuned = True
        if changed:
            self._comm_probed = False  # re-probe the tuned plan's buckets

    # -- loops -------------------------------------------------------------
    def train(self, data_iter: Iterator, num_steps: Optional[int] = None,
              hooks: Tuple = (), start_step: int = 0,
              stop_fn: Optional[Callable[[], bool]] = None):
        """The hot loop (reference resnet_cifar_main.py:336-337).

        With ``train.steps_per_loop > 1``, K steps run inside one XLA
        dispatch (lax.scan); hooks fire at loop boundaries with the last
        step's metrics.

        ``stop_fn`` is polled at step/loop boundaries (after hooks): when it
        returns True the loop returns immediately with the state as of the
        last finished step — the preemption listener's entry point
        (resilience/preemption.py). The poll is one Event check; it does not
        force a device sync.
        """
        if self.state is None:
            self.init_state()
        for h in hooks:
            reset = getattr(h, "reset_window", None)
            if reset is not None:  # throughput windows must not span the
                reset()            # pause between train segments
        if self.cfg.model.norm == "group" \
                and not getattr(self, "_gn_lr_warned", False):
            # measured (docs/perf_norm_r5.md): GroupNorm starting at bare
            # lr>=0.1 sits on a long optimization plateau with
            # seed-dependent escape; a short warmup removes it. Probe the
            # RESOLVED schedule at step 0 (raw config fields lie: piecewise
            # ignores learning_rate, constant ignores warmup_steps). Warn
            # once, at training time only (the evaluator builds a Trainer
            # too), and don't refuse — small models are fine without it.
            self._gn_lr_warned = True
            if float(self.schedule(0)) > 0.05:
                import logging
                logging.getLogger(__name__).warning(
                    "model.norm='group' and the schedule starts at "
                    "lr=%.3g (no effective warmup): GroupNorm measured a "
                    "seed-dependent optimization plateau at bare high lr "
                    "(docs/perf_norm_r5.md) — consider "
                    "optimizer.schedule='warmup_piecewise' with ~500 "
                    "warmup steps", float(self.schedule(0)))
        num_steps = num_steps or self.cfg.train.train_steps
        k = max(1, self.cfg.train.steps_per_loop)
        metrics = None
        # device-resident dataset: data_iter carries {"idx"} batches; the
        # step gathers images/labels from HBM (attach_device_dataset)
        use_idx = self._dev_data is not None
        put_one = self._put_idx if use_idx else self._put_train_batch
        put_multi = self._put_idx_multi if use_idx \
            else self._put_train_multi_batch
        depth = max(1, self.cfg.data.transfer_depth)
        # transfer-level data echoing (data.echo_transfer > 1): each staged
        # batch (group) is dispatched `reuse` times before the next draw —
        # one H2D transfer feeds reuse × k steps. The fused path reshuffles
        # batch composition per dispatch on device (jitted_multi_step) and
        # the step-keyed device augmentation re-draws per step, so reuses
        # are not replays. The index path never reuses (the device dataset
        # ships only indices — there is no transfer to amortize).
        reuse = 1 if use_idx else max(1, self.cfg.data.echo_transfer)
        if k == 1:
            from ..data.device_prefetch import device_prefetch
            step_fn = self.jitted_index_step() if use_idx \
                else self.jitted_train_step()
            # a dedicated transfer thread keeps `depth` device-resident
            # batches queued behind compute; the wrapped iterator is cached
            # per data_iter so segmented training (repeated train() calls
            # over one shared iterator, e.g. train_and_eval) doesn't drop
            # the prefetched batches between segments
            if self._dev_prefetch is None or self._dev_prefetch[0] is not data_iter:
                if self._dev_prefetch is not None:
                    self._dev_prefetch[1].close()  # stop old worker threads
                self._dev_prefetch = (
                    data_iter,
                    device_prefetch(iter(data_iter), put_one, depth=depth))
            dev_iter = self._dev_prefetch[1]
            # heartbeat phase flip around the blocking draw: a hang during
            # the fetch is OUR input pipeline, not a peer's collective —
            # the watchdog attributes by phase (resilience/heartbeat.py
            # data_fetch)
            fetch_cm = self.heartbeat.data_fetch \
                if self.heartbeat is not None else contextlib.nullcontext
            batch = None
            batch_uses = 0
            for step in range(start_step, num_steps):
                if batch_uses <= 0:
                    try:
                        # flight-recorder + goodput: time blocked on input
                        # (telemetry/; the span is ~2 clock reads when
                        # enabled, a shared no-op otherwise)
                        with span("input.wait", category="input_wait"), \
                                fetch_cm():
                            batch = next(dev_iter)
                    except StopIteration:
                        # finite stream exhausted: end training cleanly,
                        # same contract as the fused k>1 path
                        return self.state, metrics
                    batch_uses = reuse
                batch_uses -= 1
                with span("train.step"):
                    self.state, metrics = step_fn(self.state, batch)
                self._maybe_probe_comm()
                if self._comm_retuned:
                    # the startup autotune rebuilt the step around its
                    # chosen plan — swap the fresh jit in mid-run (the
                    # accessor is a cached-attribute check afterwards)
                    step_fn = self.jitted_index_step() if use_idx \
                        else self.jitted_train_step()
                for h in hooks:
                    h(step + 1, self.state, metrics)
                if stop_fn is not None and stop_fn():
                    return self.state, metrics
            return self.state, metrics

        multi_fn = self.jitted_index_multi_step(k) if use_idx \
            else self.jitted_multi_step(k)
        step = start_step
        # K-batch draw + stack runs on its own thread; the dedicated
        # transfer thread stages stacked groups behind the scan dispatch, so
        # the dispatch thread never waits on host-side input prep. Cached per
        # data_iter (like the K=1 path) so segmented training keeps its
        # queue; entry[2] carries a [stacked_group, offset] remainder left by
        # a previous segment's tail so no drawn batch is ever discarded.
        if self._multi_prefetch is None or self._multi_prefetch[0] is not data_iter:
            from ..data.device_prefetch import device_prefetch, threaded_stacker
            if self._multi_prefetch is not None:
                self._multi_prefetch[1].close()  # stop old worker threads
            self._multi_prefetch = [
                data_iter,
                device_prefetch(threaded_stacker(iter(data_iter), k),
                                put_multi, depth=depth),
                None]
        entry = self._multi_prefetch
        stacked_iter = entry[1]
        fetch_cm = self.heartbeat.data_fetch \
            if self.heartbeat is not None else contextlib.nullcontext

        def single_fn():
            return self.jitted_index_step() if use_idx \
                else self.jitted_train_step()

        def run_singles(stacked, offset, count):
            """Returns the number of steps actually run (a stop_fn stop may
            cut it short; the caller's remainder bookkeeping must not drop
            the unconsumed batches)."""
            nonlocal step, metrics
            step_fn = single_fn()
            for i in range(offset, offset + count):
                if stop_fn is not None and stop_fn():
                    return i - offset
                b = jax.tree_util.tree_map(lambda x, i=i: x[i], stacked)
                with span("train.step"):
                    self.state, metrics = step_fn(self.state, b)
                self._maybe_probe_comm()
                if self._comm_retuned:
                    step_fn = single_fn()  # autotuned rebuild — swap in
                step += 1
                for h in hooks:
                    h(step, self.state, metrics)
            return count

        # 1) consume a previous tail's remainder, one step at a time
        if entry[2] is not None and step < num_steps:
            stacked, offset = entry[2]
            take = min(k - offset, num_steps - step)
            done = run_singles(stacked, offset, take)
            offset += done
            entry[2] = None if offset >= k else [stacked, offset]
            if done < take:  # stop_fn fired mid-remainder
                return self.state, metrics
        # 2) fused full groups. A finite stream that exhausts ends training
        # early — the reference's serial path had the same stop condition
        # (input exhaustion, SURVEY.md §3.5); train streams here repeat
        # forever, so this only triggers for deliberately truncated inputs.
        while step + k <= num_steps:
            if stop_fn is not None and stop_fn():
                return self.state, metrics
            try:
                with span("input.wait", category="input_wait"), fetch_cm():
                    stacked = next(stacked_iter)
            except StopIteration:
                return self.state, metrics
            for _r in range(reuse):
                if step + k > num_steps:
                    break
                with span("train.step"):
                    self.state, metrics = multi_fn(self.state, stacked)
                self._maybe_probe_comm()
                if self._comm_retuned:
                    # autotuned rebuild — swap the fused dispatch in too
                    multi_fn = self.jitted_index_multi_step(k) if use_idx \
                        else self.jitted_multi_step(k)
                step += k
                for h in hooks:
                    h(step, self.state, metrics)
                if _r + 1 < reuse and stop_fn is not None and stop_fn():
                    return self.state, metrics
        # 3) tail shorter than k: draw one more group, run the first
        # (num_steps - step) unfused, bank the remainder for the next
        # segment. Never touch data_iter directly — the stacker's worker
        # thread iterates it concurrently.
        if step < num_steps:
            try:
                with fetch_cm():
                    stacked = next(stacked_iter)
            except StopIteration:
                return self.state, metrics
            take = num_steps - step
            done = run_singles(stacked, 0, take)
            entry[2] = [stacked, done] if done < k else None
        return self.state, metrics

    def eval_pad_multiple(self) -> int:
        """The multiple eval batches must pad to: the batch-shard count,
        times the pipeline microbatch count when the encoder is pipelined
        (each shard's LOCAL batch must divide into microbatches — the
        PipelinedEncoder fails loudly otherwise). Found by the static
        elaborator: the default eval_batch_size=100 over a dp=2 × pp=2
        mesh left a local batch of 50 against 4 microbatches — a
        guaranteed step-1 eval crash (analysis/elaborate.py)."""
        n = batch_shard_count(self.mesh)
        pstages = self.mesh.shape.get("pipeline", 1)
        if self.cfg.model.name == "vit" and pstages > 1:
            from ..models.pipeline import resolve_microbatches
            n *= resolve_microbatches(
                self.cfg.model.vit_pipeline_microbatches, pstages)
        return n

    def evaluate(self, data_iter: Iterator, num_batches: int) -> Dict[str, float]:
        """Pipelined evaluation: padding + host→device staging run on the
        dedicated transfer thread (data/device_prefetch.device_prefetch)
        while the consumer dispatches eval steps — the serial
        pad → put → run chain was the measured 46.7 vs 499 img/s eval gap
        (BENCH_r05). The prefetcher may draw up to transfer_depth + 2
        batches beyond ``num_batches`` from ``data_iter``; eval streams are
        one-pass per round (or infinite), so nothing meaningful is lost."""
        from ..data.device_prefetch import device_prefetch
        from ..parallel.sharding import pad_batch_to_multiple
        step_fn = self.jitted_eval_step()
        n_shards = self.eval_pad_multiple()

        def padded():
            for batch in data_iter:
                yield pad_batch_to_multiple(batch, n_shards)

        dev_iter = device_prefetch(
            padded(), self._put_batch,
            depth=max(1, self.cfg.data.transfer_depth))
        # accumulate ON DEVICE (tiny async adds) and pull once at the end —
        # a per-batch int() would sync host<->device every eval step
        totals = None
        hb = self.heartbeat
        # goodput: in-loop eval rounds are their own wall-clock bucket
        # (telemetry/goodput.py); the per-batch spans nest inside this one
        # and charge nothing extra (outermost-categorized-span rule)
        try:
            with span("eval.round", category="eval"):
                for i in range(num_batches):
                    if hb is not None:
                        # batch 0 carries the eval step's XLA compile, which
                        # can legitimately exceed the hang deadline — keep it
                        # in an unmonitored phase, exactly like the train
                        # path's "init" (a mid-compile hard-exit 75 would
                        # requeue-loop the job); monitoring arms at batch 1
                        hb.tick(phase="eval_init" if i == 0 else "eval")
                    with span("eval.batch"):
                        try:
                            batch = next(dev_iter)
                        except StopIteration:
                            # one-pass streams (ImageNet eval) can exhaust
                            # before num_batches; single-process, return
                            # metrics over the batches actually consumed.
                            # Multi-process we must NOT break unilaterally —
                            # the other processes would block in the next
                            # collective — so fail loudly instead.
                            if jax.process_count() > 1:
                                raise RuntimeError(
                                    "eval stream exhausted mid-evaluation on "
                                    "this process; with multiple processes "
                                    "this would deadlock the collective step "
                                    "— size eval_batch_count to the smallest "
                                    "per-process shard") from None
                            break
                        out = step_fn(self.state, batch)
                        totals = out if totals is None else \
                            jax.tree_util.tree_map(jnp.add, totals, out)
        finally:
            # stop the staging thread (the caller keeps ownership of
            # data_iter itself — Evaluator reuses caller-supplied iterators)
            dev_iter.close()
        if totals is None:
            return {"precision": 0.0, "loss": 0.0, "count": 0}
        count = int(totals["count"])
        return {"precision": int(totals["correct"]) / max(count, 1),
                "loss": float(totals["loss_sum"]) / max(count, 1),
                "count": count}
