"""Per-op TPU profile of the ImageNet ResNet-50 train step.

Captures a jax.profiler trace of the fused train dispatch and converts the
xplane via tensorboard_plugin_profile into an HLO-op time breakdown — the
auditable evidence behind docs/perf_imagenet_r3.md (the reference kept its
perf story in README tables; this is the TPU analog with per-op receipts).

    python tools/profile_trace.py [--bs 128] [--k 8] [--sub 1] [--top 25]
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def capture(bs: int, k: int, sub: int, logdir: str):
    from profile_imagenet_bn import build_step
    trainer, multi_fn, batch, _one = build_step(bs, k, stat_subsample=sub)
    state = trainer.state
    for _ in range(2):  # compile + warm
        state, _ = multi_fn(state, batch)
    jax.block_until_ready(state.params)
    with jax.profiler.trace(logdir):
        for _ in range(2):
            state, _ = multi_fn(state, batch)
        jax.block_until_ready(state.params)


def op_table(logdir: str, top: int):
    """xplane → [(op name, category, self_time_us, occurrences)] sorted."""
    from tensorboard_plugin_profile.convert import raw_to_tool_data
    xplanes = glob.glob(os.path.join(
        logdir, "plugins/profile/*/*.xplane.pb"))
    if not xplanes:
        raise FileNotFoundError(f"no xplane under {logdir}")
    data, _ = raw_to_tool_data.xspace_to_tool_data(
        [xplanes[-1]], "hlo_stats", {})
    if isinstance(data, bytes):
        data = data.decode()
    payload = json.loads(data)
    # hlo_stats: a GViz table; rows of [..columns..]
    cols = [c["label"] for c in payload[0]["cols"]] \
        if isinstance(payload, list) else [c["label"] for c in payload["cols"]]
    rows = payload[0]["rows"] if isinstance(payload, list) else payload["rows"]

    def col(name):
        for i, c in enumerate(cols):
            if name.lower() in c.lower():
                return i
        return None
    i_cat = col("category")
    i_name = col("HLO op name") or col("name")
    i_self = col("Total self time (us)") or col("self time")
    i_occ = col("occurrences")
    out = []
    for r in rows:
        c = [x.get("v") if isinstance(x, dict) else x for x in r["c"]]
        out.append({
            "category": c[i_cat] if i_cat is not None else "",
            "op": c[i_name] if i_name is not None else "",
            "self_us": float(c[i_self] or 0) if i_self is not None else 0.0,
            "n": c[i_occ] if i_occ is not None else "",
        })
    out.sort(key=lambda d: -d["self_us"])
    return cols, out[:top]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bs", type=int, default=128)
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--sub", type=int, default=1)
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--logdir", default="/tmp/drt_trace")
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    capture(args.bs, args.k, args.sub, args.logdir)
    cols, table = op_table(args.logdir, args.top)
    total = sum(d["self_us"] for d in table)
    print(f"top-{args.top} HLO ops by self time "
          f"(bs={args.bs}, k={args.k}, stat_subsample={args.sub}):")
    for d in table:
        print(f"{d['self_us']:>10.0f} us  {d['category']:<22} "
              f"{str(d['op'])[:70]}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"bs": args.bs, "k": args.k, "sub": args.sub,
                       "table": table}, f, indent=2)
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
