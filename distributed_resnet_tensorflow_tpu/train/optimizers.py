"""Optimizer factory.

Parity with the reference's optimizer selection — plain SGD or momentum-0.9
(reference resnet_model.py:96-99) — plus Adam (used by the toy model,
reference logist_model.py:60) and LARS for the large-batch bs=32k config
(BASELINE.json config 5; not in the reference, which collapsed at scale —
reference README.md:51-52).

Weight decay is applied in the LOSS like the reference (resnet_model.py:78-86),
not decoupled — except for LARS, which takes decay inside the optimizer per
the LARS paper formulation, and AdamW, which is the decoupled-decay
formulation by definition (the transformer-family presets use it: loss-side
L2 under Adam's per-parameter scaling is neither the reference's semantics
nor AdamW's). The decayed set differs by default: kernels-only (ndim>1,
excluding BN γ/β and biases), with ``optimizer.decay_all_params``
restoring the reference's all-trainables L2 for parity replays — see
``loss_weight_decay``.

There is no SyncReplicasOptimizer / DistributedOptimizer wrapper class: under
``jit`` over a sharded batch, the gradient all-reduce is induced by sharding
propagation (XLA emits it on ICI), so the base optimizer IS the distributed
optimizer.
"""
from __future__ import annotations

from typing import Callable

import optax


def create_optimizer(opt_cfg, schedule: Callable) -> optax.GradientTransformation:
    name = opt_cfg.name
    chain = []
    if opt_cfg.grad_clip_norm and opt_cfg.grad_clip_norm > 0:
        chain.append(optax.clip_by_global_norm(opt_cfg.grad_clip_norm))

    if name == "sgd":
        chain.append(optax.sgd(schedule))
    elif name == "momentum":
        chain.append(optax.sgd(schedule, momentum=opt_cfg.momentum))
    elif name == "adam":
        chain.append(optax.adam(schedule))
    elif name == "adamw":
        # decoupled decay (mask matches LARS: kernels only, no norm/bias);
        # the train loop skips the loss-side L2 for this optimizer
        chain.append(optax.adamw(
            schedule, weight_decay=opt_cfg.weight_decay,
            mask=_non_bn_mask))
    elif name == "lars":
        # optax.lars handles per-layer trust ratios; weight decay is part of
        # the LARS update (masked away from BN/bias by weight_decay_mask).
        chain.append(optax.lars(
            schedule,
            weight_decay=opt_cfg.weight_decay,
            weight_decay_mask=_non_bn_mask,
            trust_ratio_mask=_non_bn_mask,
            trust_coefficient=opt_cfg.lars_trust_coefficient,
            eps=opt_cfg.lars_eps,
            momentum=opt_cfg.momentum))
    elif name == "lamb":
        # LAMB (arXiv:1904.00962): Adam moments + LARS-style per-layer
        # trust ratio, decoupled decay — the large-batch recipe for the
        # bs>=4k presets (arXiv:1811.05233's warmup pairs with it). The
        # same non-BN/bias mask as LARS/AdamW: normalization scales and
        # biases get neither decay nor trust-ratio scaling. Doubles the
        # moment state (m AND v) — which is why the lamb presets turn on
        # optimizer.zero1 (the moments shard across the data axis).
        chain.append(optax.lamb(
            schedule,
            weight_decay=opt_cfg.weight_decay,
            mask=_non_bn_mask))
    else:
        raise ValueError(f"unknown optimizer {name!r}")
    return optax.chain(*chain) if len(chain) > 1 else chain[0]


def decoupled_decay(name: str) -> bool:
    """True for optimizers that take weight decay INSIDE the update (LARS,
    LAMB, AdamW) — the train loop must then skip the loss-side L2, and
    ``decay_all_params`` (a loss-path switch) is rejected. The single
    predicate behind both decisions (train/loop.py)."""
    return name in ("lars", "lamb", "adamw")


def _non_bn_mask(params):
    """True for params that should get weight decay / trust-ratio scaling:
    exclude BatchNorm scale/bias, all 1-D params (biases), and position
    embeddings (`pos_embed`, (1, T, D) — ndim>1 but not a matmul kernel;
    ViT recipes conventionally exempt it from decay)."""
    import jax

    def keep(path, leaf):
        names = [str(p) for p in path]
        if any("BatchNorm" in n for n in names):
            return False
        # expert-stacked MoE biases are 2-D; exclude biases (and the ViT
        # pos_embed) by name too
        if names and ("bias" in names[-1] or "pos_embed" in names[-1]):
            return False
        return leaf.ndim > 1

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    return jax.tree_util.tree_unflatten(
        treedef, [keep(path, leaf) for path, leaf in flat])


def loss_weight_decay(params, rate: float, all_params: bool = False):
    """L2 decay term added to the loss: 0.5*rate*Σ‖w‖².

    Default (``all_params=False``) decays only conv/dense kernels (ndim>1),
    excluding BN γ/β and biases — the modern choice, and this repo's default.
    NOTE this deliberately DIFFERS from the reference, which summed
    ``tf.nn.l2_loss(v)`` over ALL trainable variables including BN scale/bias
    (reference resnet_model.py:85-86). ``all_params=True``
    (config ``optimizer.decay_all_params``) restores the reference-faithful
    behavior for parity replays."""
    import jax
    import jax.numpy as jnp

    if rate == 0.0:
        return 0.0

    def kernel_like(path, leaf):
        # 2-D+ non-bias leaves; "bias" checked by name because
        # expert-stacked MoE biases are 2-D (models/moe.py). pos_embed is
        # exempt like in _non_bn_mask so the loss-side and decoupled decay
        # paths define the SAME default decayed set (kernels only)
        name = str(path[-1])
        return leaf.ndim > 1 and "bias" not in name \
            and "pos_embed" not in name

    leaves = [leaf for path, leaf in
              jax.tree_util.tree_flatten_with_path(params)[0]
              if all_params or kernel_like(path, leaf)]
    return 0.5 * rate * sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                            for l in leaves)
