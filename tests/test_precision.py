"""Low-precision hot paths (parallel/precision.py + comm.compress +
serve variants; docs/precision.md).

The load-bearing claims, pinned on the virtual 8-device mesh:

  * with ``train.precision=off`` and ``comm.compress=off`` NOTHING
    changes: the policy resolves to None, the model keeps its configured
    compute dtype, the exchange carries f32 — and runs are bitwise
    deterministic (the off path is byte-for-byte the pre-policy step; no
    policy code touches it);
  * the bf16 step is allclose to the f32 oracle at the documented
    tolerances on dp AND dp_fsdp, for momentum and LAMB, with and
    without ZeRO-1 — while every persisted leaf stays an f32 MASTER;
  * the compressed exchange is a pure WIRE change: many-vs-one-bucket
    stays BIT-identical under compression (for both the gradient psum
    leg and the ZeRO-1 scatter/gather composition), wire bytes halve on
    the SAME bucket plan, and the result is allclose to the uncompressed
    exchange;
  * checkpoints are policy-agnostic: an f32-master checkpoint written
    under a bf16 policy restores bit-exactly into an off-policy trainer
    (and vice versa), including the per-host sharded layout and the
    serving hot swap of a bf16 variant;
  * serving variants are strict: unknown variants and wrong request
    dtypes are rejected loudly; a bf16 variant bucket answers requests
    close to the f32 variant and hot swaps rebuild every variant.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_resnet_tensorflow_tpu.parallel import create_mesh
from distributed_resnet_tensorflow_tpu.parallel.overlap import (
    compress_dtype, overlap_stats)
from distributed_resnet_tensorflow_tpu.parallel.precision import (
    check_master_dtypes, precision_stats, resolve_precision,
    resolve_serve_variants)
from distributed_resnet_tensorflow_tpu.parallel.sharding import zero1_stats
from distributed_resnet_tensorflow_tpu.train import Trainer
from distributed_resnet_tensorflow_tpu.utils.config import (MeshConfig,
                                                            get_preset)

#: documented bf16-vs-f32 tolerances (docs/precision.md): after a few
#: optimizer steps the cast paths agree with the f32 oracle to bf16
#: rounding amplified through the loss curvature — elementwise within
#: (rtol, atol), globally within a relative-L2 drift bound. LAMB's
#: layer-wise trust ratio rescales whole layers, so its elementwise tail
#: is wider at the same (tiny) global drift; its tests also pin the LR
#: to a sane LAMB range (the default 0.1 is a momentum number — at that
#: LR even two f32 runs with different reduction orders diverge).
BF16_TOL = {"momentum": dict(rtol=0.12, atol=5e-2),
            "lamb": dict(rtol=0.2, atol=0.15)}
BF16_REL_L2 = 0.05
#: loss agreement after a few steps (the trajectory-parity check)
BF16_LOSS_ATOL = 5e-2


def _assert_bf16_close(on, off, opt, m_on, m_off):
    np.testing.assert_allclose(on, off, **BF16_TOL[opt])
    drift = np.linalg.norm(on - off) / max(np.linalg.norm(off), 1e-9)
    assert drift < BF16_REL_L2, f"relative L2 drift {drift:.4f}"
    assert abs(float(m_off["loss"]) - float(m_on["loss"])) < BF16_LOSS_ATOL
    # short-horizon top-1 parity on the training batch itself
    assert abs(float(m_off["precision"]) -
               float(m_on["precision"])) <= 0.25


def _tiny_cfg(**kw):
    cfg = get_preset("smoke")
    cfg.model.compute_dtype = "float32"
    cfg.model.resnet_size = 8
    cfg.model.num_classes = 4
    cfg.data.image_size = 8
    cfg.train.batch_size = 16
    cfg.optimizer.schedule = "constant"
    cfg.checkpoint.save_every_secs = 0.0
    for k, v in kw.items():
        cfg.override(k, v)
    return cfg


def _fixed_batches(n=3, bs=16, size=8, classes=4):
    rng = np.random.RandomState(7)
    imgs = rng.randn(n, bs, size, size, 3).astype(np.float32)
    labs = rng.randint(0, classes, (n, bs)).astype(np.int32)
    return [{"images": imgs[i], "labels": labs[i]} for i in range(n)]


def _flat_params(state):
    return np.concatenate([np.asarray(l, np.float32).ravel() for l in
                           jax.tree_util.tree_leaves(state.params)])


def _train(mesh_cfg, batches, **kw):
    cfg = _tiny_cfg(**kw)
    tr = Trainer(cfg, mesh=create_mesh(mesh_cfg))
    tr.init_state()
    state, metrics = tr.train(iter(list(batches)), num_steps=len(batches))
    return tr, state, _flat_params(state), metrics


# ---------------------------------------------------------------------------
# the off path: bit-identical, policy-free (the acceptance pin)
# ---------------------------------------------------------------------------

def test_precision_off_is_policy_free_and_deterministic(devices):
    """train.precision=off must leave NO policy machinery on the step:
    the resolver returns None, the model keeps the configured compute
    dtype, and two identical runs are BITWISE equal — together with the
    resolver being the only entry point, this pins the off path to the
    pre-policy (PR 11) step."""
    cfg = _tiny_cfg()
    assert cfg.train.precision == "off" and cfg.comm.compress == "off"
    assert resolve_precision(cfg) is None
    batches = _fixed_batches()
    tr, _, a, m1 = _train(MeshConfig(data=8), batches)
    assert not tr.precision_active and not tr.comm_compress_active
    assert tr.model.dtype == jnp.float32  # configured dtype untouched
    _, _, b, m2 = _train(MeshConfig(data=8), batches)
    np.testing.assert_array_equal(a, b)
    assert float(m1["loss"]) == float(m2["loss"])


def test_fp16_step_refused_with_reason():
    cfg = _tiny_cfg()
    cfg.train.precision = "fp16"
    with pytest.raises(ValueError, match="loss scaling"):
        resolve_precision(cfg)
    cfg.train.precision = "maybe"
    with pytest.raises(ValueError, match="unknown"):
        resolve_precision(cfg)


# ---------------------------------------------------------------------------
# bf16 step vs the f32 oracle (the acceptance claim)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mesh_cfg,opt,zero1", [
    (MeshConfig(data=8), "momentum", "off"),
    # momentum-dp_fsdp re-tiered out of the 870s tier-1 (ISSUE 19,
    # ~11s): momentum-dp keeps the bf16-vs-f32 oracle claim in tier-1
    # and the fsdp layout stays pinned by the overlap/zero1 exactness
    # tests; the full (unfiltered) suite runs the layout cross
    pytest.param(MeshConfig(data=4, fsdp=2), "momentum", "off",
                 marks=pytest.mark.slow),
    # lamb_zero1 legs re-tiered out of the 870s tier-1 (ISSUE 13): the
    # momentum legs pin the bf16-vs-f32 oracle; the LAMB×ZeRO-1
    # composition re-runs it with the heaviest optimizer and stays in
    # the full (unfiltered) suite
    pytest.param(MeshConfig(data=8), "lamb", "on",
                 marks=pytest.mark.slow),
    pytest.param(MeshConfig(data=4, fsdp=2), "lamb", "on",
                 marks=pytest.mark.slow),
], ids=["momentum-dp", "momentum-dp_fsdp", "lamb_zero1-dp",
        "lamb_zero1-dp_fsdp"])
def test_bf16_step_allclose_vs_f32_oracle(mesh_cfg, opt, zero1):
    """bf16 activations/matmuls over f32 masters vs the all-f32 oracle:
    params allclose at the documented tolerance, loss trajectory within
    BF16_LOSS_ATOL after a few steps, and every float state leaf still a
    float32 MASTER (the checkpoint contract)."""
    batches = _fixed_batches()
    kw = {"optimizer.name": opt}
    if opt == "lamb":
        kw.update({"optimizer.weight_decay": "1e-4",
                   "optimizer.learning_rate": "0.02"})
    if zero1 == "on":
        kw.update({"optimizer.zero1": "on",
                   "optimizer.zero1_min_size": "16"})
    _, _, off, m0 = _train(mesh_cfg, batches, **kw)
    tr, st, on, m1 = _train(mesh_cfg, batches, **kw,
                            **{"train.precision": "bf16"})
    assert tr.precision_active
    assert tr.model.dtype == jnp.bfloat16  # the policy override landed
    _assert_bf16_close(on, off, opt, m1, m0)
    # masters: every float leaf of params AND optimizer state is f32
    check_master_dtypes(st.params)
    for leaf in jax.tree_util.tree_leaves(st.opt_state):
        if hasattr(leaf, "dtype") and \
                jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.float32


@pytest.mark.parametrize("opt,zero1", [
    # the lamb leg re-tiered out of the 870s tier-1 (ISSUE 13); the
    # momentum_zero1 leg stays as the cheap remaining-matrix pin
    pytest.param("lamb", "off", marks=pytest.mark.slow),
    ("momentum", "on"),
], ids=["lamb", "momentum_zero1"])
def test_bf16_step_allclose_remaining_matrix_dp(opt, zero1):
    """The other half of the (optimizer × zero1) matrix on dp — lamb
    without ZeRO-1, momentum with — so every pairing is covered."""
    batches = _fixed_batches()
    kw = {"optimizer.name": opt}
    if opt == "lamb":
        kw.update({"optimizer.weight_decay": "1e-4",
                   "optimizer.learning_rate": "0.02"})
    if zero1 == "on":
        kw.update({"optimizer.zero1": "on",
                   "optimizer.zero1_min_size": "16"})
    _, _, off, m0 = _train(MeshConfig(data=8), batches, **kw)
    _, _, on, m1 = _train(MeshConfig(data=8), batches, **kw,
                          **{"train.precision": "bf16"})
    _assert_bf16_close(on, off, opt, m1, m0)


# ---------------------------------------------------------------------------
# compressed gradient exchange
# ---------------------------------------------------------------------------

@pytest.mark.slow  # re-tiered out of the 870s tier-1 (ISSUE 20, ~11s: two
# full trainings under compress+overlap on dp_fsdp); tier-1 keeps the
# compressed-wire contract via test_precision_and_compress_event_rows and
# the bf16 numerics via the f32-oracle allclose tests; the full
# (unfiltered) suite still runs this bucketing composition
def test_compressed_exchange_bucketing_is_bit_identical(devices):
    """The compression cast is per-leaf and commutes with bucketing:
    many tiny buckets vs one giant bucket under comm.compress=bf16 must
    produce BITWISE-equal params — compression narrows the wire, never
    the scheduling-invariance contract. Runs on dp_fsdp so the
    fsdp reduce-scatter leg compresses too; plain dp rides the zero1
    composition test below."""
    batches = _fixed_batches()
    kw = {"comm.overlap": "on", "comm.compress": "bf16"}
    mesh_cfg = MeshConfig(data=4, fsdp=2)
    _, _, many, _ = _train(mesh_cfg, batches, **kw,
                           **{"comm.bucket_mb": "0.05"})
    plan = overlap_stats.snapshot()
    assert plan["buckets"] > 1 and plan["compress"] == "bf16"
    _, _, one, _ = _train(mesh_cfg, batches, **kw,
                          **{"comm.bucket_mb": "4096"})
    assert overlap_stats.snapshot()["buckets"] == 1
    np.testing.assert_array_equal(many, one)


# re-tiered out of the 870s tier-1 (ISSUE 19, ~14s: two full zero1
# trainings). Each composed half stays pinned in tier-1 —
# test_compressed_exchange_bucketing_is_bit_identical (compression ×
# bucketing, fsdp leg) and test_zero1.py's overlap-bucketing bitwise
# test (zero1 × bucketing, uncompressed); the full (unfiltered) suite
# runs the triple composition.
@pytest.mark.slow
def test_compressed_exchange_zero1_composition_bit_identical(devices):
    """Compression composed with the ZeRO-1 reduce-scatter AND the
    bucketed param-update all-gather: still bitwise bucket-invariant."""
    batches = _fixed_batches()
    kw = {"comm.overlap": "on", "comm.compress": "bf16",
          "optimizer.zero1": "on", "optimizer.zero1_min_size": "16"}
    _, _, many, _ = _train(MeshConfig(data=8), batches, **kw,
                           **{"comm.bucket_mb": "0.05"})
    z1 = zero1_stats.snapshot()
    assert z1["gather_compress"] == "bf16"
    assert sum(z1["gather_wire_bytes"]) * 2 == \
        sum(z1["gather_bucket_bytes"])
    _, _, one, _ = _train(MeshConfig(data=8), batches, **kw,
                          **{"comm.bucket_mb": "4096"})
    np.testing.assert_array_equal(many, one)


@pytest.mark.slow  # re-tiered out of the 870s tier-1 (~17s: three full bucketed-exchange trainings over one plan); runs in the full (unfiltered) suite
def test_compressed_exchange_halves_wire_bytes_same_plan(devices):
    """The acceptance claim, three runs over ONE bucket plan: (a) the
    compressed exchange halves per-bucket wire bytes on the SAME plan
    and stays allclose to the uncompressed exchange (bf16 wire rounding
    only); (b) the bf16 POLICY composed with the bucketed exchange (the
    shard_map body mirrors the jit path's policy cast) stays allclose to
    the composed f32 step at the policy tolerance."""
    batches = _fixed_batches()
    kw = {"comm.overlap": "on", "comm.bucket_mb": "0.05"}
    _, _, plain, m0 = _train(MeshConfig(data=8), batches, **kw)
    base = overlap_stats.snapshot()
    assert base["compress"] == "off"
    assert base["wire_bytes"] == base["grad_bytes"]
    _, _, comp, _ = _train(MeshConfig(data=8), batches, **kw,
                           **{"comm.compress": "bf16"})
    snap = overlap_stats.snapshot()
    # same plan…
    assert snap["bucket_bytes"] == base["bucket_bytes"]
    assert snap["bucket_leaves"] == base["bucket_leaves"]
    # …half the wire
    assert snap["wire_bytes"] * 2 == snap["grad_bytes"]
    assert all(w * 2 == b for w, b in zip(snap["bucket_wire_bytes"],
                                          snap["bucket_bytes"]))
    np.testing.assert_allclose(comp, plain, rtol=2e-2, atol=5e-3)
    # (b) bf16 policy over the same bucketed exchange
    tr, _, on, m1 = _train(MeshConfig(data=8), batches, **kw,
                           **{"train.precision": "bf16"})
    assert tr.precision_active and tr.comm_overlap_active
    _assert_bf16_close(on, plain, "momentum", m1, m0)


def test_compress_requires_overlap_warns_loudly(caplog, devices):
    """The satellite fix: comm.compress with comm.overlap resolved off
    must warn (compression rides the bucketed exchange — a silently
    unbucketed run would never compress a byte)."""
    import logging
    cfg = _tiny_cfg(**{"comm.compress": "bf16"})  # overlap auto→off (1 proc)
    with caplog.at_level(logging.WARNING,
                         logger="distributed_resnet_tensorflow_tpu.train.loop"):
        tr = Trainer(cfg, mesh=create_mesh(MeshConfig(data=8)))
    assert not tr.comm_compress_active
    assert any("comm.compress" in r.message and "overlap" in r.message
               for r in caplog.records)
    # unknown compress values are refused even with the exchange off
    with pytest.raises(ValueError, match="comm.compress"):
        compress_dtype(_tiny_cfg(**{"comm.compress": "int4"}))


# re-tiered out of the 870s tier-1 (ISSUE 17, ~13s: the triple
# composition). Each pairwise leg stays pinned in tier-1
# (test_compressed_exchange_zero1_composition_bit_identical,
# test_compressed_exchange_bucketing_is_bit_identical, the accum
# bit-identity leg in test_overlap); the full (unfiltered) suite runs
# compress×zero1×accum together.
@pytest.mark.slow
def test_compress_and_zero1_compose_with_accumulation(caplog, devices):
    """The converted warning branch: gradient accumulation used to force
    the exchange off (comm.compress/optimizer.zero1 then warned and ran
    full-f32 replicated) — it is IN-envelope now, so the composition must
    build silently, compress the ONE per-step exchange (wire = grad/2),
    scatter into the ZeRO-1 shard update and gather back bucketed, with
    many-vs-one-bucket still bitwise equal."""
    import logging
    batches = _fixed_batches()
    kw = {"comm.overlap": "on", "comm.compress": "bf16",
          "optimizer.zero1": "on", "train.grad_accum_steps": "2"}
    with caplog.at_level(logging.WARNING,
                         logger="distributed_resnet_tensorflow_tpu.train.loop"):
        tr, _, many, m1 = _train(MeshConfig(data=8), batches, **kw,
                                 **{"comm.bucket_mb": "0.05"})
    assert tr.comm_overlap_active and tr.comm_compress_active \
        and tr.zero1_active
    assert not any("comm.compress" in r.message and "overlap" in
                   r.message for r in caplog.records)
    plan = overlap_stats.snapshot()
    assert plan["accum_steps"] == 2 and plan["compress"] == "bf16"
    assert plan["wire_bytes"] * 2 == plan["grad_bytes"]  # halved, 1×/step
    z1 = zero1_stats.snapshot()
    assert z1["gather_compress"] == "bf16" and z1["gather_buckets"] >= 1
    _, _, one, m2 = _train(MeshConfig(data=8), batches, **kw,
                           **{"comm.bucket_mb": "4096"})
    np.testing.assert_array_equal(many, one)
    assert float(m1["loss"]) == float(m2["loss"])


# ---------------------------------------------------------------------------
# checkpoints: f32 masters, policy-agnostic
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sharded", ["off", "on"], ids=["single", "sharded"])
def test_f32_master_checkpoint_roundtrip_under_bf16_policy(tmp_path,
                                                           sharded,
                                                           devices):
    """Train under the bf16 policy, save, restore into an OFF-policy
    trainer: every restored leaf is f32 and bit-equal — the checkpoint
    never learns what policy wrote it. Covers the per-host sharded
    layout too (checkpoint/shards.py)."""
    from distributed_resnet_tensorflow_tpu.checkpoint import (
        CheckpointManager)
    batches = _fixed_batches(n=2)
    kw = {"train.precision": "bf16"}
    if sharded == "on":
        kw["checkpoint.sharded"] = "on"
    tr, st, flat, _ = _train(MeshConfig(data=8), batches, **kw)
    d = os.path.join(str(tmp_path), "ckpt")
    mngr = CheckpointManager(d, async_save=False, sharded=sharded)
    mngr.save(2, st, force=True)
    mngr.close()
    # restore into a policy-OFF trainer (same shapes)
    cfg2 = _tiny_cfg()
    tr2 = Trainer(cfg2, mesh=create_mesh(MeshConfig(data=8)))
    tr2.init_state()
    mngr2 = CheckpointManager(d, async_save=False, sharded=sharded)
    restored, rstep = mngr2.restore(tr2.state)
    mngr2.close()
    assert rstep == 2
    check_master_dtypes(restored.params)
    np.testing.assert_array_equal(_flat_params(restored), flat)
    # the reverse direction (off-written → bf16-policy trainer) is the
    # same bytes into the same f32 abstract state — covered by the
    # master-dtype guard in Trainer.init_state + this equality


# ---------------------------------------------------------------------------
# serving variants
# ---------------------------------------------------------------------------

def _serve_cfg(tmp_path, **kw):
    cfg = _tiny_cfg(**kw)
    cfg.data.eval_batch_size = 8        # one bucket: [8]
    cfg.log_root = str(tmp_path)
    cfg.checkpoint.directory = os.path.join(str(tmp_path), "ckpt")
    cfg.checkpoint.async_save = False
    cfg.serve.max_queue_delay_ms = 20.0
    cfg.serve.poll_interval_secs = 0.2
    return cfg


def test_resolve_serve_variants_strict():
    cfg = _tiny_cfg()
    assert resolve_serve_variants(cfg) == ("f32",)
    cfg.serve.variants = ("bf16", "f32", "bf16")
    assert resolve_serve_variants(cfg) == ("bf16", "f32")  # deduped, ordered
    cfg.serve.variants = ("int8",)  # weight-only quantized serving
    assert resolve_serve_variants(cfg) == ("int8",)
    cfg.serve.variants = ("int4",)
    with pytest.raises(ValueError, match="int4"):
        resolve_serve_variants(cfg)
    # CLI override coercion keeps string tuples as strings
    cfg2 = _tiny_cfg()
    cfg2.override("serve.variants", "f32,bf16")
    assert cfg2.serve.variants == ("f32", "bf16")


#: pinned parity bound for the int8 weight-only variant vs the f32
#: variant on the same params (docs/precision.md): per-output-channel
#: symmetric quantization keeps serving logits within this relative L2
INT8_PARITY_REL_L2 = 0.05


def test_int8_quantizer_roundtrip_bound():
    """Per-channel symmetric int8: dequantized weights sit within half a
    quantization step of the original, per OUTPUT channel — the static
    half of the serving parity bound."""
    from distributed_resnet_tensorflow_tpu.parallel.precision import (
        INT8_QMAX, dequantize_params, quantize_leaf_int8)
    rng = np.random.RandomState(0)
    w = (rng.randn(3, 3, 8, 16) * rng.rand(16) * 3).astype(np.float32)
    q = quantize_leaf_int8(w)
    assert q["int8_q"].dtype == jnp.int8 and q["int8_scale"].shape == (16,)
    deq = dequantize_params({"k": q})["k"]
    step = np.asarray(q["int8_scale"])
    assert np.all(np.abs(np.asarray(deq) - w) <= step / 2 + 1e-7)
    # scales are per-channel maxima / 127
    np.testing.assert_allclose(
        step, np.abs(w).max(axis=(0, 1, 2)) / float(INT8_QMAX), rtol=1e-6)


def test_int8_variant_serves_within_parity_bound(tmp_path, devices):
    """The int8 weight-only serving variant: kernels live int8-at-rest
    (a real ~4× cut on quantized leaves), biases/norm leaves stay f32,
    AOT warm covers the variant (no serve-time compile), and its logits
    stay within the pinned parity bound of the f32 variant."""
    from distributed_resnet_tensorflow_tpu.serve.server import (
        InferenceServer)
    cfg = _serve_cfg(tmp_path)
    cfg.serve.variants = ("f32", "int8")
    server = InferenceServer(cfg)
    server.start(start_threads=False)
    leaves = jax.tree_util.tree_leaves(server._states["int8"].params)
    int8_bytes = sum(int(l.size) for l in leaves if l.dtype == jnp.int8)
    f32_bytes = sum(int(l.size) * 4 for l in leaves
                    if l.dtype == jnp.float32)
    assert int8_bytes > 0 and int8_bytes > 4 * f32_bytes, \
        (int8_bytes, f32_bytes)  # the kernels really are int8 at rest
    rng = np.random.RandomState(0)
    img = rng.randn(8, 8, 3).astype(np.float32)
    fut32 = server.submit(img, variant="f32")
    fut8 = server.submit(img, variant="int8")
    served = 0
    while served < 2:
        served += server.service_once(block_secs=0.5)
    row32, _ = fut32.result(timeout=5)
    row8, _ = fut8.result(timeout=5)
    rel = np.linalg.norm(row8 - row32) / (np.linalg.norm(row32) + 1e-9)
    assert rel < INT8_PARITY_REL_L2, rel
    assert server.cache.serve_time_compiles == 0
    server.close()


@pytest.mark.heavy
def test_bf16_variant_serves_and_hot_swap_rebuilds(tmp_path, devices):
    """A (bucket, bf16) variant answers requests close to the f32
    variant; unknown variants and wrong dtypes are rejected loudly; and
    a hot swap rebuilds EVERY variant from the new f32 masters (the bf16
    copy must never serve a stale checkpoint)."""
    from distributed_resnet_tensorflow_tpu.checkpoint import (
        CheckpointManager)
    from distributed_resnet_tensorflow_tpu.serve.server import (
        InferenceServer)
    cfg = _serve_cfg(tmp_path)
    cfg.serve.variants = ("f32", "bf16")
    server = InferenceServer(cfg)
    server.start(start_threads=False)
    assert server.variants == ("f32", "bf16")
    # the bf16 variant's weight copy is genuinely bf16
    bf_leaves = jax.tree_util.tree_leaves(server._states["bf16"].params)
    assert all(l.dtype == jnp.bfloat16 for l in bf_leaves
               if jnp.issubdtype(l.dtype, jnp.floating))
    check_master_dtypes(server._states["f32"].params)

    rng = np.random.RandomState(0)
    img = rng.randn(8, 8, 3).astype(np.float32)
    fut32 = server.submit(img)                      # default = f32
    fut16 = server.submit(img, variant="bf16")
    served = 0
    while served < 2:
        served += server.service_once(block_secs=0.5)
    row32, _ = fut32.result(timeout=5)
    row16, _ = fut16.result(timeout=5)
    # two dispatches: the variant change splits the group
    assert server.batcher.batches == 2
    np.testing.assert_allclose(row16, row32, rtol=0.1, atol=0.1)
    assert not np.array_equal(row16, row32)  # genuinely bf16 compute
    # per-variant latency keys (the (batch, variant) breakdown)
    keys = set(server.latency.summary_ms())
    assert {"bucket_8", "bucket_8_bf16"} <= keys
    # strict validation: unknown variant, wrong dtype
    with pytest.raises(ValueError, match="variant"):
        server.submit(img, variant="int8")
    with pytest.raises(ValueError, match="dtype"):
        server.submit((img * 255).astype(np.uint8))
    # zero request-time compiles: warm covered every (bucket, variant)
    assert server.cache.serve_time_compiles == 0

    # hot swap: publish rescaled params; BOTH variants must rebuild
    st = server.trainer.state

    def host(x):
        return np.asarray(x)

    params = jax.tree_util.tree_map(lambda x: host(x) * 0.5, st.params)
    st2 = st.replace(step=np.asarray(7, np.int32), params=params,
                     batch_stats=jax.tree_util.tree_map(host,
                                                        st.batch_stats),
                     opt_state=jax.tree_util.tree_map(host, st.opt_state))
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=False)
    mngr.save(7, st2, force=True)
    mngr.close()
    assert server.swapper.poll_once() is not None
    server.service_once()                     # boundary hook applies it
    assert server.serving_step == 7
    f32_now = np.asarray(jax.tree_util.tree_leaves(
        server._states["f32"].params)[0])
    bf16_now = jax.tree_util.tree_leaves(server._states["bf16"].params)[0]
    assert bf16_now.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(bf16_now, np.float32), f32_now, rtol=0.01, atol=1e-3)
    server.close()
    assert server.dropped == 0


def test_f32_variant_stays_full_precision_under_bf16_policy(tmp_path,
                                                            devices):
    """A serving config that carries train.precision=bf16 (the
    large-batch presets do) must still serve the f32 VARIANT in full
    precision: the trainer's own predict step computes in the policy
    dtype, so the cache needs a dedicated f32-compute program — without
    it both variants silently compute bf16 and the f32 oracle contract
    is broken (review finding, pinned here)."""
    from distributed_resnet_tensorflow_tpu.serve.server import (
        InferenceServer)
    cfg = _serve_cfg(tmp_path, **{"train.precision": "bf16"})
    cfg.serve.variants = ("f32", "bf16")
    cfg.serve.warm_buckets = False     # inspect programs, skip compiles
    server = InferenceServer(cfg)
    server.start(start_threads=False)  # builds the lazy variant states
    assert server.trainer.precision_active
    # the cache's f32 entry is NOT the trainer's policy-cast step
    assert server.cache._predicts["f32"] is not \
        server.trainer._predict_step
    rng = np.random.RandomState(0)
    batch = {"images": rng.randn(1, 8, 8, 3).astype(np.float32)}
    f32_logits = np.asarray(server.cache._predicts["f32"](
        server._states["f32"], batch))
    bf16_logits = np.asarray(server.cache._predicts["bf16"](
        server._states["bf16"], batch))
    policy_logits = np.asarray(server.trainer._predict_step(
        server._states["f32"], batch))
    # f32 variant ≠ the bf16-compute outputs; bf16 variant ≈ the policy
    assert not np.array_equal(f32_logits, bf16_logits)
    np.testing.assert_allclose(bf16_logits, policy_logits, rtol=0.05,
                               atol=0.05)
    server.close()


# ---------------------------------------------------------------------------
# telemetry: precision + comm_compress rows
# ---------------------------------------------------------------------------

def test_precision_and_compress_event_rows(tmp_path, devices):
    from distributed_resnet_tensorflow_tpu.train.hooks import (
        CommCompressHook, PrecisionHook)
    from distributed_resnet_tensorflow_tpu.utils.metrics import (
        MetricsWriter, read_metrics)
    precision_stats.reset()
    overlap_stats.reset()
    batches = _fixed_batches(n=2)
    cfg = _tiny_cfg(**{"train.precision": "bf16", "comm.overlap": "on",
                       "comm.bucket_mb": "0.05", "comm.compress": "bf16"})
    tr = Trainer(cfg, mesh=create_mesh(MeshConfig(data=8)))
    assert tr.precision_active and tr.comm_compress_active
    tr.init_state()
    w = MetricsWriter(str(tmp_path), enable_tensorboard=False)
    hooks = (PrecisionHook(w, every_steps=1),
             CommCompressHook(w, every_steps=1))
    tr.train(iter(batches), num_steps=2, hooks=hooks)
    w.close()
    rows = read_metrics(str(tmp_path))
    prows = [r for r in rows if r.get("event") == "precision"]
    crows = [r for r in rows if r.get("event") == "comm_compress"]
    assert len(prows) == 1        # one row per resolved policy
    assert prows[0]["policy"] == "bf16"
    assert prows[0]["compute_dtype"] == "bfloat16"
    assert prows[0]["master_dtype"] == "float32"
    assert prows[0]["compress"] == "bf16"
    assert prows[0]["master_param_bytes"] > 0
    assert len(crows) == 1        # one row per traced plan
    assert crows[0]["wire_ratio"] == 0.5
    assert crows[0]["wire_bytes"] * 2 == crows[0]["grad_bytes"]


def test_precision_events_registered():
    from distributed_resnet_tensorflow_tpu.telemetry.tracer import (
        SPAN_CATALOG)
    from distributed_resnet_tensorflow_tpu.utils.metrics import (
        EVENT_SCHEMAS)
    for name in ("precision", "comm_compress"):
        assert name in EVENT_SCHEMAS and EVENT_SCHEMAS[name]["fields"]
    assert "serve.variant_build" in SPAN_CATALOG


# ---------------------------------------------------------------------------
# presets
# ---------------------------------------------------------------------------

def test_large_batch_presets_carry_the_bf16_recipe():
    """The arXiv:1811.05233 recipe shape rides the large-batch presets:
    bf16 step + compressed exchange; the accuracy-replay presets stay
    f32-off (the oracle)."""
    for name in ("imagenet_resnet50_lars32k", "imagenet_resnet50_lars4k",
                 "imagenet_resnet50_lamb4k"):
        cfg = get_preset(name)
        assert cfg.train.precision == "bf16", name
        assert cfg.comm.compress == "bf16", name
        assert resolve_precision(cfg) is not None
    for name in ("cifar10_resnet50", "imagenet_resnet50", "smoke"):
        cfg = get_preset(name)
        assert cfg.train.precision == "off", name
        assert cfg.comm.compress == "off", name
