"""chief-gated-collective: no collective runs on the chief alone.

The classic SPMD divergence hang: ``if is_chief(): <something that
issues a collective>``. Every other process never reaches the matching
collective, the chief blocks in it forever, and the job dies as a
watchdog timeout (exit 75 → requeue) instead of an error at the guilty
line. PR 4's gloo hang was this family at one remove — host-side control
flow diverging across processes in front of a collective.

Statically: the rule finds chief-gated statement groups
(``analysis/threads.chief_gated_statements`` — ``if is_chief():`` /
``if jax.process_index() == 0:`` bodies, the same test bound to a local
name, and the tail of a function behind an early ``if not is_chief():
return`` guard) and flags any gated call that is collective-bearing:
a direct lax collective (``psum``/``all_gather``/…), a multihost barrier
(``sync_global_devices``/``process_allgather``/``broadcast_one_to_all``),
an executed ``jitted_*`` step, or a resolved call into a function that
transitively reaches one (``Trainer.evaluate``, ``CheckpointManager.
save``, …).

Chief-gated METRICS/file work (writers, summaries, layout stamps) is the
codebase's norm and stays clean — only collective-bearing reachability
fires. Deliberate single-process exceptions carry
``# shardcheck: ok(chief-gated-collective)``.
"""
from __future__ import annotations

from typing import Iterable

from ..report import Finding
from .. import threads as threads_mod
from ..callgraph import call_target, get_callgraph

RULE_NAME = "chief-gated-collective"
DOC = __doc__


def check(ctx) -> Iterable[Finding]:
    graph = get_callgraph(ctx)
    bearing = threads_mod.collective_bearing_keys(graph)
    for key, fn in sorted(graph.funcs.items()):
        for stmts in threads_mod.chief_gated_statements(fn):
            for call in threads_mod.calls_in_statements(stmts, fn):
                hit = None
                if threads_mod.is_jitted_execution(call):
                    hit = "executes a jitted step"
                else:
                    name, _ = call_target(call)
                    if name in threads_mod.COLLECTIVE_CALL_NAMES:
                        hit = f"collective {name}()"
                    else:
                        for callee in graph.resolve_call(call, fn):
                            if callee.key in bearing:
                                hit = (f"reaches a collective via "
                                       f"{callee.short()}")
                                break
                if hit is not None:
                    yield Finding(
                        RULE_NAME, fn.rel, call.lineno,
                        f"chief-gated call {hit} — peers never post the "
                        "matching collective and the chief hangs in it "
                        "(SPMD divergence); hoist the collective out of "
                        "the is_chief()/process_index()==0 branch")
