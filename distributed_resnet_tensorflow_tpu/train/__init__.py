from .loop import Trainer, make_train_step, make_eval_step, cross_entropy_loss  # noqa: F401
from .state import TrainState, create_train_state, state_shardings  # noqa: F401
from .schedules import create_schedule, piecewise, warmup_piecewise, warmup_cosine  # noqa: F401
from .optimizers import create_optimizer, loss_weight_decay  # noqa: F401
