"""AOT compile cache: every serve bucket compiled BEFORE the first request.

The serving forward (``train/loop.make_predict_step`` — the exact step
``analysis/elaborate.py`` traces per preset × bucket) is lowered and
compiled ahead of time for each batch bucket at server startup, with the
same state shardings the Trainer uses and the batch arriving via
``data_sharding`` — so the request path NEVER pays XLA: a cold server's
first request runs a cached executable, and a latency SLO can't be blown
by a compile hiding behind an unlucky batch size.

Buckets are powers of two (in multiples of ``Trainer.eval_pad_multiple``,
so every padded batch divides over the batch shards × pipeline
microbatches) up to the request-batch cap — a handful of programs total,
compiled once, keyed by (bucket, image shape, dtype, VARIANT).

Variants (``serve.variants``; docs/precision.md): each reduced-precision
serving variant ("bf16") gets its own predict program per bucket —
compiled against the variant's CAST abstract state
(``parallel.precision.make_variant_cast``, the same cast the server
applies to the live weights), so a bf16 variant executes bf16 weights
through a bf16-compute forward while the f32 variant stays the untouched
full-precision oracle. The shardings are dtype-free, so every variant
shares the layout machinery.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Tuple

import jax
import numpy as np

log = logging.getLogger(__name__)


def bucket_sizes(max_batch: int, multiple: int = 1) -> List[int]:
    """Power-of-two batch buckets: ``multiple``, 2×, 4×, ... capped (and
    topped) by ``max_batch`` rounded up to a multiple of ``multiple``.

    ``multiple`` is the pad floor (``Trainer.eval_pad_multiple`` — batch
    shards × pipeline microbatches): every bucket must divide over the
    mesh's batch axes or the dispatch itself would be ill-specced. The cap
    bucket keeps the configured max batch reachable even when it is not a
    power of two (e.g. eval_batch_size=100 over 8 shards → buckets
    8, 16, 32, 64, 104)."""
    if max_batch <= 0:
        raise ValueError(f"max_batch must be positive, got {max_batch}")
    multiple = max(1, multiple)
    cap = -(-max_batch // multiple) * multiple  # round UP to the pad floor
    out = []
    b = multiple
    while b < cap:
        out.append(b)
        b *= 2
    out.append(cap)
    return out


def pick_bucket(buckets: List[int], n: int) -> int:
    """Smallest bucket that fits ``n`` requests (buckets sorted ascending)."""
    for b in buckets:
        if b >= n:
            return b
    raise ValueError(f"{n} requests exceed the largest bucket {buckets[-1]}")


class ServeCompileCache:
    """Per-(bucket, image spec) AOT-compiled serving executables.

    ``warm()`` lowers+compiles every bucket up front (startup cost, logged
    per bucket); a ``get()`` miss after warmup still compiles — correctness
    over refusal — but counts it in ``serve_time_compiles`` and warns,
    because a request paying a compile means the warmup spec and the live
    traffic disagree (wrong dtype/shape) and the SLO story is broken.

    Thread-safety: ``get``/``warm`` may be called from any thread (compile
    is pure — no device execution happens here); EXECUTING the returned
    compiled fn is the caller's single-dispatch-thread responsibility
    (serve/batcher.py; docs/input_pipeline.md threading model).
    """

    def __init__(self, trainer, variant_predicts=None):
        from ..parallel.mesh import data_sharding
        from ..parallel.precision import make_variant_cast
        from ..train.state import state_shardings
        self.trainer = trainer
        self._state_abstract = jax.eval_shape(lambda s: s, trainer.state)
        self._st_sh = state_shardings(self._state_abstract, trainer.mesh)
        self._b_sh = data_sharding(trainer.mesh)
        # variant → (predict step, CAST abstract state): "f32" is the
        # trainer's own forward over the uncast state; reduced-precision
        # variants come from Trainer.make_variant_predict_step with the
        # abstract cast exactly as the server casts the live weights
        self._predicts = {"f32": trainer._predict_step}
        self._abstracts = {"f32": self._state_abstract}
        self._st_shs = {"f32": self._st_sh}
        for name, fn in (variant_predicts or {}).items():
            self._predicts[name] = fn
            self._abstracts[name] = jax.eval_shape(
                make_variant_cast(name), self._state_abstract)
            # weight-only variants (int8) restructure the param tree
            # (quantized marker dicts), so each variant resolves its OWN
            # sharding tree over its cast abstract state — the rule table
            # is path-based and handles the extra q/scale leaves
            self._st_shs[name] = state_shardings(self._abstracts[name],
                                                 trainer.mesh)
        self._compiled: Dict[Tuple, object] = {}
        self._lock = threading.Lock()
        self.warm_secs = 0.0
        self.serve_time_compiles = 0

    def _key(self, bucket: int, image_shape: Tuple[int, ...],
             dtype, variant: str) -> Tuple:
        return (int(bucket), tuple(image_shape), np.dtype(dtype).str,
                str(variant))

    def _compile(self, bucket: int, image_shape: Tuple[int, ...], dtype,
                 variant: str):
        if variant not in self._predicts:
            raise ValueError(f"serve variant {variant!r} has no predict "
                             f"program; have {sorted(self._predicts)}")
        batch_abstract = {"images": jax.ShapeDtypeStruct(
            (bucket,) + tuple(image_shape), np.dtype(dtype))}
        jitted = jax.jit(self._predicts[variant],
                         in_shardings=(self._st_shs[variant],
                                       {"images": self._b_sh}))
        return jitted.lower(self._abstracts[variant],
                            batch_abstract).compile()

    def get(self, bucket: int, image_shape: Tuple[int, ...], dtype,
            variant: str = "f32", warm: bool = False):
        key = self._key(bucket, image_shape, dtype, variant)
        with self._lock:
            hit = self._compiled.get(key)
        if hit is not None:
            return hit
        t0 = time.perf_counter()
        compiled = self._compile(bucket, image_shape, dtype, variant)
        dt = time.perf_counter() - t0
        with self._lock:
            # a concurrent compile of the same key may have won the race;
            # keep the first so executions reuse one executable
            hit = self._compiled.setdefault(key, compiled)
            if warm:
                self.warm_secs += dt
            elif hit is compiled:
                self.serve_time_compiles += 1
        if warm:
            log.info("serve compile cache: bucket %d %s %s [%s] compiled "
                     "in %.2fs", bucket, tuple(image_shape),
                     np.dtype(dtype).name, variant, dt)
        elif hit is compiled:
            log.warning(
                "serve compile cache MISS at request time: bucket %d %s %s "
                "[%s] compiled in %.2fs on the request path — the warmup "
                "spec and live traffic disagree (serve.warm_buckets / "
                "request dtype / serve.variants)", bucket,
                tuple(image_shape), np.dtype(dtype).name, variant, dt)
        return hit

    def warm(self, buckets: List[int], image_shape: Tuple[int, ...],
             dtype, variants: Tuple[str, ...] = ("f32",)) -> float:
        """Compile every (bucket, variant) now; returns total compile
        seconds."""
        t0 = time.perf_counter()
        for v in variants:
            for b in buckets:
                self.get(b, image_shape, dtype, variant=v, warm=True)
        return time.perf_counter() - t0

    @property
    def compiled_buckets(self) -> List[int]:
        with self._lock:
            return sorted({k[0] for k in self._compiled})
