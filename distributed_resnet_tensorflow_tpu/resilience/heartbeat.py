"""Heartbeats: per-process liveness beats over a pluggable transport.

Synchronous SPMD training is exactly as reliable as its least reliable
worker (Horovod, arXiv:1802.05799): when one host dies or wedges inside a
collective, every peer blocks in that collective with NO runtime signal —
the training loop cannot observe its own hang from inside. The heartbeat
subsystem provides the out-of-band liveness channel the loop lacks:

  * every process runs a :class:`HeartbeatPublisher` — a daemon thread that
    publishes a :class:`Beat` (``{step, progress, phase, wall_time, ...}``)
    every ``interval_secs`` REGARDLESS of what the main thread is doing.
    A wedged process therefore keeps beating with a frozen ``progress``
    (distinguishable hang), while a dead process stops beating entirely
    (distinguishable host loss).
  * the train loop / hooks feed the publisher at step boundaries
    (``update``/``tick``) — cheap field writes under a lock, no I/O on the
    hot path. The publisher also maintains the rolling per-step-time
    estimate (EWMA) the watchdog derives its hang deadline from.
  * transport is abstract (:class:`BeatTransport`); the file-based
    implementation works over the shared run directory every SLURM/TPU-pod
    deployment already has (same reliance as checkpoints). A socket/kv
    backend can land later without touching publisher or watchdog.

Consumed by resilience/watchdog.py; see docs/resilience.md for the
detection/teardown story and the metrics.jsonl schemas.
"""
from __future__ import annotations

import collections
import contextlib
import dataclasses
import json
import logging
import os
import socket
import threading
import time
from typing import Dict, Optional

log = logging.getLogger(__name__)

#: phases that mean "this process left the run on purpose" — peers must
#: not flag them as lost (PHASE_FAILED is the loud exception: it marks a
#: real error on that process, see watchdog escalation)
PHASE_DONE = "done"
PHASE_PREEMPTED = "preempted"
PHASE_FAILED = "failed"
#: this process left its CURRENT mesh generation to reshard into the next
#: one (resilience/elastic.py) — a deliberate departure like done/preempted
#: (the next generation's fresh transport epoch makes the beat invisible
#: to the new watchdog either way)
PHASE_RESHARD = "reshard"
DEPARTED_PHASES = (PHASE_DONE, PHASE_PREEMPTED, PHASE_FAILED, PHASE_RESHARD)

#: the train loop's host-side input fetch (``data_fetch`` below): a hang
#: HERE is self-attributable — OUR input pipeline stalled, not a peer's
#: collective — which is what lets the watchdog's elastic fork exit the
#: culprit promptly while the survivors defer and reshard around it
PHASE_DATA = "data"

#: phases in which a stalled ``progress`` counter indicates a hang (init /
#: compile / save are legitimately long and un-ticked)
MONITORED_PHASES = ("train", "eval", PHASE_DATA)


@dataclasses.dataclass
class Beat:
    """One liveness report. ``progress`` is the monotonic counter hang
    detection watches (train steps AND eval batches bump it — ``step``
    alone would false-positive during evaluation); ``wall_time`` is
    ``time.time()`` at publish so peers can age beats across hosts (NTP
    assumed, same as every shared-filesystem timestamp)."""

    process_id: int
    pid: int
    host: str
    seq: int           # publisher iteration, monotonic per run
    step: int          # last completed optimizer step
    progress: int      # steps + eval batches; the liveness counter
    phase: str         # init | train | eval_init | eval | save | poll |
                       # done | preempted | failed | reshard (only
                       # train/eval are hang-monitored, MONITORED_PHASES)
    wall_time: float
    generation: int = 0  # elastic mesh generation this beat was published
                         # in (resilience/elastic.py; 0 = non-elastic run)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Beat":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


class BeatTransport:
    """Abstract beat exchange: publish mine, read everyone's latest."""

    def publish(self, beat: Beat) -> None:
        raise NotImplementedError

    def peers(self) -> Dict[int, Beat]:
        """Latest beat per process id (including our own)."""
        raise NotImplementedError

    def close(self) -> None:  # pragma: no cover - trivial default
        pass


class FileBeatTransport(BeatTransport):
    """Beats as one JSON file per process under a shared directory.

    Writes are atomic (tmp + ``os.replace``) so readers never parse a torn
    file; unparseable files are skipped (NFS clients without atomic rename
    visibility), not fatal. At construction the process deletes its OWN
    stale file from a previous run in the same dir, and ``peers`` ignores
    any beat published before this transport existed — after a requeue the
    dir still holds every OTHER process's previous-run files, and without
    the epoch filter a fast-starting peer would read one (arbitrarily old,
    possibly phase="failed") and fire a spurious teardown before the slow
    peer's first beat lands. A filtered peer looks like "never beat in
    this run", which the watchdog already treats as bootstrap territory.
    Beats refresh every ``interval_secs``, so a live peer that started
    before us becomes visible within one interval (NTP assumed, same as
    beat aging).
    """

    def __init__(self, directory: str, process_id: int,
                 wall_clock=time.time):
        self.directory = directory
        self.process_id = process_id
        self._epoch = wall_clock()
        os.makedirs(directory, exist_ok=True)
        for final in (False, True):
            try:
                os.remove(self._path(process_id, final=final))
            except OSError:
                pass

    def _path(self, pid: int, final: bool = False) -> str:
        # final (departure) beats live in a SIDECAR file: the regular file
        # is last-writer-wins, and a publisher thread stuck in a shared-FS
        # stall past close()'s join timeout could otherwise land a stale
        # phase="train" beat AFTER the final "done" — turning a clean
        # departure into a spurious peer_lost 75 for the survivors
        suffix = ".final.json" if final else ".json"
        return os.path.join(self.directory, f"proc{pid}{suffix}")

    def publish(self, beat: Beat) -> None:
        path = self._path(beat.process_id,
                          final=beat.phase in DEPARTED_PHASES)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(beat.to_dict(), f)
            os.replace(tmp, path)
        except OSError as e:
            # a full/flaky shared FS must degrade heartbeats, not kill
            # training — the watchdog treats missing beats conservatively
            log.warning("heartbeat publish failed: %s", e)

    def peers(self) -> Dict[int, Beat]:
        out: Dict[int, Beat] = {}
        finals: Dict[int, Beat] = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            if not (name.startswith("proc") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.directory, name)) as f:
                    beat = Beat.from_dict(json.load(f))
            except (OSError, ValueError, TypeError):
                continue  # mid-replace on a non-atomic FS, or junk
            if beat.wall_time < self._epoch:
                continue  # previous-run leftover (requeue): see docstring
            if name.endswith(".final.json"):
                finals[beat.process_id] = beat
            else:
                out[beat.process_id] = beat
        out.update(finals)  # a departure statement outranks any live beat
        return out


def tombstone_departed(directory: str, keep_process_ids) -> int:
    """Remove beat files (live AND final sidecars) of processes that are
    no longer part of the run — deliberately drained, replaced, or left
    behind by a smaller mesh generation (resilience/elastic.py calls this
    when a generation goes live, with the new membership's ranks).

    Without tombstoning, only the transport's epoch filter hides a
    departed host's last beat — ``main.py monitor`` (no epoch) would show
    it as a stale host forever, and a future transport without the filter
    would re-flag it. Removal races with concurrent readers are benign:
    ``peers`` already skips unreadable files. Returns the number of files
    removed; unknown/foreign file names are left alone."""
    keep = {int(p) for p in keep_process_ids}
    removed = 0
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    for name in names:
        if not (name.startswith("proc") and name.endswith(".json")):
            continue
        stem = name[len("proc"):].split(".", 1)[0]
        try:
            pid = int(stem)
        except ValueError:
            continue
        if pid in keep:
            continue
        try:
            os.remove(os.path.join(directory, name))
            removed += 1
        except OSError:
            pass
    if removed:
        log.info("heartbeat tombstone: removed %d beat file(s) of "
                 "departed process(es) not in %s", removed, sorted(keep))
    return removed


class HeartbeatPublisher:
    """Daemon publishing thread + the hot-path state it reports.

    The TRAIN LOOP side (``update``/``tick``) only writes fields under a
    lock — no file I/O, no syscalls beyond a clock read. The PUBLISHER
    THREAD serializes a beat every ``interval_secs``. The split is the
    whole point: the thread keeps beating while the main thread is stuck
    in a collective, which is precisely when liveness reporting matters.
    """

    #: EWMA weight for the rolling per-step-time estimate
    EWMA_ALPHA = 0.3

    #: rolling per-step-time SAMPLE window (the perf-anomaly sentinel's
    #: median+MAD input, resilience/watchdog.py) — samples enter under
    #: the same guards as the EWMA (no compile-laden first delta, no
    #: post-interlude delta), so the window holds honest step times only
    STEP_SAMPLE_CAP = 512

    def __init__(self, transport: BeatTransport, process_id: int,
                 interval_secs: float = 1.0,
                 clock=time.monotonic, wall_clock=time.time,
                 generation: int = 0):
        self.transport = transport
        self.process_id = process_id
        self.generation = generation
        self.interval_secs = max(0.05, interval_secs)
        self._clock = clock
        self._wall = wall_clock
        self._lock = threading.Lock()
        self._step = 0
        self._progress = 0
        self._phase = "init"
        self._seq = 0
        self._last_progress_t = clock()
        self._prev_update_t: Optional[float] = None
        self._prev_step: Optional[int] = None
        self._step_stride = 1
        self._ewma_step_secs: Optional[float] = None
        self._step_samples: collections.deque = collections.deque(
            maxlen=self.STEP_SAMPLE_CAP)
        self._step_sample_seq = 0  # total samples ever appended
        # True after any tick()/set_phase() — i.e. non-step activity (eval
        # round, save, poll) happened since the last step boundary, so the
        # NEXT step delta spans that pause and must not enter the EWMA
        self._interlude = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._host = socket.gethostname()
        self._pid = os.getpid()

    # -- hot path (train loop / hooks) --------------------------------------
    def update(self, step: Optional[int] = None,
               phase: Optional[str] = None) -> None:
        """Record a step boundary (and/or phase change). Maintains the
        rolling per-step-time EWMA; the FIRST step delta is discarded — it
        includes compilation and would poison the estimate for the whole
        run — and so is the first delta after any tick()/set_phase()
        interlude (eval round, save): that delta spans the whole pause,
        and one 30-minute eval folded in at alpha 0.3 would inflate the
        hang deadline by hours."""
        now = self._clock()
        with self._lock:
            if phase is not None:
                self._phase = phase
            if step is not None and step != self._step:
                if self._prev_update_t is not None and \
                        self._prev_step is not None and step > self._prev_step:
                    dt = (now - self._prev_update_t) / (step - self._prev_step)
                    # progress only ticks at this granularity (the fused
                    # loop's steps_per_loop): hang deadlines must scale by
                    # it, or a healthy 64-step scan outlives a 10×-one-step
                    # deadline and reads as a hang
                    self._step_stride = step - self._prev_step
                    # skip the compile-laden first delta and post-pause deltas
                    if self._prev_step > 0 and not self._interlude:
                        self._ewma_step_secs = dt if self._ewma_step_secs is None \
                            else (1 - self.EWMA_ALPHA) * self._ewma_step_secs \
                            + self.EWMA_ALPHA * dt
                        self._step_samples.append(dt)
                        self._step_sample_seq += 1
                self._interlude = False
                self._prev_update_t = now
                self._prev_step = step
                self._step = step
                self._progress += 1
                self._last_progress_t = now

    def tick(self, phase: Optional[str] = None) -> None:
        """Liveness bump without a step advance (eval batches, long host
        side work) — keeps hang detection honest outside the train loop."""
        now = self._clock()
        with self._lock:
            if phase is not None:
                self._phase = phase
            self._interlude = True
            self._progress += 1
            self._last_progress_t = now

    def set_phase(self, phase: str) -> None:
        with self._lock:
            self._phase = phase
            self._interlude = True

    @contextlib.contextmanager
    def data_fetch(self):
        """Mark the train loop's blocking host-side input draw: phase
        'train' → 'data' for the duration. A hang verdict then reads the
        culprit off the phase — 'data' means OUR input pipeline stalled
        (exit promptly so an elastic fleet can shrink around us), 'train'
        means we are wedged in a collective (plausibly a peer's fault —
        the watchdog's elastic fork defers that exit; resilience/
        watchdog.py _maybe_exit). Only flips when the current phase IS
        'train': the first fetch lands in the unmonitored 'init' phase
        (XLA compile) and eval owns its own phases. Unlike set_phase this
        is NOT an interlude — a fetch precedes every step, and marking it
        would starve the per-step-time EWMA."""
        with self._lock:
            flip = self._phase == "train"
            if flip:
                self._phase = PHASE_DATA
        try:
            yield
        finally:
            if flip:
                with self._lock:
                    if self._phase == PHASE_DATA:
                        self._phase = "train"

    def snapshot(self) -> dict:
        """Local state for the watchdog (no I/O)."""
        with self._lock:
            return {"step": self._step, "progress": self._progress,
                    "phase": self._phase,
                    "last_progress_t": self._last_progress_t,
                    "ewma_step_secs": self._ewma_step_secs,
                    "step_stride": self._step_stride}

    def step_times(self) -> dict:
        """The rolling per-step-time sample window for the perf-anomaly
        sentinel: ``{"seq": total samples ever, "samples": [...]}``. The
        seq counter lets the detector skip ticks with no NEW sample (a
        paused loop must not re-judge the same window forever)."""
        with self._lock:
            return {"seq": self._step_sample_seq,
                    "samples": list(self._step_samples)}

    # -- publisher thread ----------------------------------------------------
    def _beat(self) -> Beat:
        with self._lock:
            self._seq += 1
            return Beat(process_id=self.process_id, pid=self._pid,
                        host=self._host, seq=self._seq, step=self._step,
                        progress=self._progress, phase=self._phase,
                        wall_time=self._wall(),
                        generation=self.generation)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_secs):
            self.transport.publish(self._beat())

    def start(self) -> "HeartbeatPublisher":
        if self._thread is None:
            self.transport.publish(self._beat())  # beat 1 lands immediately
            self._thread = threading.Thread(
                target=self._run, name="drt-heartbeat", daemon=True)
            self._thread.start()
        return self

    def close(self, final_phase: str = PHASE_DONE) -> None:
        """Stop the thread and publish one last beat whose phase tells the
        peers HOW we left: done/preempted = clean departure (don't flag),
        failed = this process died on a real error (peers stop resumable,
        the supervisor reports the real failure)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval_secs + 1.0)
            self._thread = None
        with self._lock:
            self._phase = final_phase
        self.transport.publish(self._beat())
