"""Request transport between the fleet router and its serving replicas.

One frame = a 4-byte big-endian length, that many bytes of JSON header,
then ``header["nbytes"]`` raw payload bytes (the image or logits array,
C-contiguous). Requests carry {id, variant, shape, dtype, nbytes};
responses {id, ok, step, shape, dtype, nbytes} or {id, ok: false, error}.
A bodyless {"ping": true} frame answers {"pong": true, step, outstanding}
— the router's readmission probe.

Threading: the replica-side connection handlers are SUBMITTER threads in
the docs/serving.md contract — they decode bytes, enqueue via
``InferenceServer.submit`` and park on the Future with a timeout; the one
dispatch thread still owns every multi-device execution. The router-side
client keeps a small pool of persistent connections per replica, each
checked out exclusively per request (no multiplexing — a worker thread
owns one socket for the duration of one attempt). Every socket operation
runs under a deadline-derived ``settimeout``: a dead peer is a loud
``ReplicaError`` in seconds, never a parked thread.
"""
from __future__ import annotations

import json
import logging
import os
import socket
import struct
import threading
from typing import List, Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)

_LEN = struct.Struct(">I")
#: sanity bound on a frame header (a corrupt length prefix must not
#: allocate gigabytes before failing)
_MAX_HEADER = 1 << 20
_MAX_BODY = 1 << 30


class ReplicaError(RuntimeError):
    """A transport attempt failed (connect/send/recv error or timeout) —
    the router's cue to mark the replica suspect and hedge elsewhere."""


def send_frame(sock: socket.socket, header: dict,
               body: bytes = b"") -> None:
    if body:
        header = dict(header, nbytes=len(body))
    raw = json.dumps(header).encode("utf-8")
    sock.sendall(_LEN.pack(len(raw)) + raw + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ReplicaError("connection closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock: socket.socket) -> Tuple[dict, bytes]:
    n = _LEN.unpack(_recv_exact(sock, _LEN.size))[0]
    if n > _MAX_HEADER:
        raise ReplicaError(f"frame header of {n} bytes (corrupt stream?)")
    header = json.loads(_recv_exact(sock, n).decode("utf-8"))
    nbytes = int(header.get("nbytes", 0))
    if not 0 <= nbytes <= _MAX_BODY:
        raise ReplicaError(f"frame body of {nbytes} bytes (corrupt stream?)")
    body = _recv_exact(sock, nbytes) if nbytes else b""
    return header, body


def _array_header(arr: np.ndarray) -> dict:
    return {"shape": list(arr.shape), "dtype": arr.dtype.name}


def _array_from(header: dict, body: bytes) -> np.ndarray:
    arr = np.frombuffer(body, dtype=np.dtype(header["dtype"]))
    return arr.reshape([int(d) for d in header["shape"]]).copy()


# ---------------------------------------------------------------------------
# router side: pooled client
# ---------------------------------------------------------------------------

class TcpReplicaClient:
    """Persistent-connection client for one replica, checkout-per-request.

    ``request`` raises :class:`ReplicaError` on ANY transport problem or
    an error response — the caller (a router worker) translates that into
    health signal + retry/hedge. A failed socket is discarded, never
    returned to the pool."""

    def __init__(self, host: str, port: int,
                 connect_timeout_secs: float = 5.0):
        self.host = host
        self.port = port
        self.connect_timeout_secs = connect_timeout_secs
        self._idle: List[socket.socket] = []
        self._lock = threading.Lock()
        self._closed = False

    def _checkout(self, timeout_secs: float) -> socket.socket:
        with self._lock:
            if self._closed:
                raise ReplicaError("client closed")
            if self._idle:
                sock = self._idle.pop()
                sock.settimeout(timeout_secs)
                return sock
        try:
            sock = socket.create_connection(
                (self.host, self.port),
                timeout=min(self.connect_timeout_secs, timeout_secs))
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(timeout_secs)
            return sock
        except OSError as e:
            raise ReplicaError(
                f"connect to {self.host}:{self.port} failed: {e}") from e

    def _checkin(self, sock: socket.socket) -> None:
        with self._lock:
            if not self._closed and len(self._idle) < 8:
                self._idle.append(sock)
                return
        _close_quietly(sock)

    def _roundtrip(self, header: dict, body: bytes,
                   timeout_secs: float) -> Tuple[dict, bytes]:
        sock = self._checkout(timeout_secs)
        try:
            send_frame(sock, header, body)
            resp, payload = recv_frame(sock)
        except ReplicaError:
            _close_quietly(sock)
            raise
        except (OSError, ValueError) as e:
            _close_quietly(sock)
            raise ReplicaError(
                f"{self.host}:{self.port}: {type(e).__name__}: {e}") from e
        self._checkin(sock)
        return resp, payload

    def request(self, image: np.ndarray, variant: Optional[str],
                timeout_secs: float) -> Tuple[np.ndarray, int]:
        """One inference attempt → (logits_row, served_step)."""
        image = np.ascontiguousarray(image)
        header = {"variant": variant, **_array_header(image)}
        resp, payload = self._roundtrip(header, image.tobytes(),
                                        timeout_secs)
        if not resp.get("ok"):
            raise ReplicaError(
                f"{self.host}:{self.port} rejected request: "
                f"{resp.get('error', 'unknown')}")
        return _array_from(resp, payload), int(resp.get("step", -1))

    def ping(self, timeout_secs: float = 2.0) -> dict:
        """Liveness/step probe (the readmission check)."""
        resp, _ = self._roundtrip({"ping": True}, b"", timeout_secs)
        if not resp.get("pong"):
            raise ReplicaError(f"{self.host}:{self.port}: bad pong {resp}")
        return resp

    def reset(self) -> None:
        """Drop pooled connections (a replaced replica's old sockets are
        dead even though host:port is unchanged)."""
        with self._lock:
            idle, self._idle = self._idle, []
        for sock in idle:
            _close_quietly(sock)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            idle, self._idle = self._idle, []
        for sock in idle:
            _close_quietly(sock)


def _close_quietly(sock: socket.socket) -> None:
    try:
        sock.close()
    except OSError:
        pass


# ---------------------------------------------------------------------------
# replica side: listener
# ---------------------------------------------------------------------------

class ReplicaListener:
    """TCP front of one serving replica: accept loop + per-connection
    handler threads, all strictly submitter-role (enqueue + timed Future
    wait; zero device work — the single-dispatch-thread contract holds by
    construction)."""

    def __init__(self, server, port: int, host: str = "127.0.0.1",
                 result_timeout_secs: float = 60.0):
        self.server = server
        self.host = host
        self.result_timeout_secs = result_timeout_secs
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: List[socket.socket] = []
        self._lock = threading.Lock()

    def start(self) -> "ReplicaListener":
        self._sock.listen(64)
        self._sock.settimeout(0.5)  # accept wakes to observe _stop
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="drt-serve-accept")
        self._accept_thread.start()
        log.info("serve: replica listening on %s:%d", self.host, self.port)
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns = [c for c in self._conns if c.fileno() >= 0]
                self._conns.append(conn)
            threading.Thread(target=self._handle_conn, args=(conn,),
                             daemon=True, name="drt-serve-conn").start()

    def _handle_conn(self, conn: socket.socket) -> None:
        # per-op deadline: a half-sent frame from a dying router must not
        # park this handler past the result timeout
        conn.settimeout(self.result_timeout_secs)
        try:
            while not self._stop.is_set():
                try:
                    header, body = recv_frame(conn)
                except (ReplicaError, socket.timeout, OSError, ValueError):
                    return  # peer gone / idle past deadline: drop the conn
                if header.get("ping"):
                    send_frame(conn, {
                        "pong": True, "step": self.server.serving_step,
                        "pid": os.getpid(),
                        "outstanding": self.server.dropped})
                    continue
                self._serve_one(conn, header, body)
        finally:
            _close_quietly(conn)

    def _serve_one(self, conn: socket.socket, header: dict,
                   body: bytes) -> None:
        try:
            image = _array_from(header, body)
            fut = self.server.submit(image, variant=header.get("variant"))
            row, step = fut.result(timeout=self.result_timeout_secs)
        except Exception as e:  # noqa: BLE001 — answered, not crashed
            send_frame(conn, {"ok": False,
                              "error": f"{type(e).__name__}: {e}"[:300]})
            return
        row = np.ascontiguousarray(row)
        send_frame(conn, {"ok": True, "step": int(step),
                          **_array_header(row)}, row.tobytes())

    def close(self) -> None:
        self._stop.set()
        _close_quietly(self._sock)
        with self._lock:
            conns, self._conns = self._conns, []
        for conn in conns:
            _close_quietly(conn)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
            self._accept_thread = None
