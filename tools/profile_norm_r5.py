"""Round-5 normalization-contract sweep: ImageNet RN50 single-chip MFU under
every norm contract (batch | frozen | group) at bs 32/128 — the measurement
VERDICT r4 #1 demanded to settle the >=55%-MFU north star (BASELINE.md:30-32).
Writes docs/perf_norm_r5.json. Shares bench.py's _bench_imagenet_at harness
so the numbers are directly comparable with BENCH_r0N rows."""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import bench  # noqa: E402


def main():
    out = {"device": jax.devices()[0].device_kind,
           "workload": "imagenet_resnet50 synthetic, fused k=8 dispatch"}
    for norm in ("batch", "frozen", "group"):
        for bs, loops in ((32, 20), (128, 5)):
            key = f"{norm}_bs{bs}"
            t0 = time.time()
            try:
                row = bench._bench_imagenet_at(bs, loops=loops, norm=norm)
                row["measure_secs"] = round(time.time() - t0, 1)
                out[key] = row
            except Exception as e:
                out[key] = {"error": f"{type(e).__name__}: {e}"[:200]}
            print(key, json.dumps(out[key]), flush=True)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "perf_norm_r5.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", path)


if __name__ == "__main__":
    main()
