"""Pipeline parallelism tests (models/pipeline.py) — GPipe schedule over the
`pipeline` mesh axis on the fake 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.models.pipeline import (
    PipelinedEncoder, _block_apply, pack_encoder_params)
from distributed_resnet_tensorflow_tpu.models.transformer import EncoderBlock
from distributed_resnet_tensorflow_tpu.parallel import create_mesh
from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig


def _mesh(**axes):
    return create_mesh(MeshConfig(**axes))


def test_block_apply_matches_encoder_block():
    """The explicit stacked-param block math == the module EncoderBlock."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 8, 32).astype(np.float32))
    block = EncoderBlock(num_heads=4, dtype=jnp.float32)
    variables = block.init(jax.random.PRNGKey(0), x)
    want = block.apply(variables, x)

    packed = pack_encoder_params({"EncoderBlock_0": variables["params"]}, 1)
    p0 = jax.tree_util.tree_map(lambda v: v[0], packed)
    got, aux = _block_apply(p0, x, num_heads=4, dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    assert float(aux) == 0.0  # dense MLP sows no load-balancing loss


@pytest.mark.heavy
def test_full_vit_repacked_pipeline_matches_standard():
    """A standard per-block ViT's params repacked via pack_encoder_params
    (depth=4) and run through the pipelined ViT must give the same logits —
    the checkpoint-migration contract between the two parameterizations."""
    from distributed_resnet_tensorflow_tpu.models import VisionTransformer
    depth = 4
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(8, 16, 16, 3).astype(np.float32))
    std = VisionTransformer(num_classes=4, patch_size=4, dim=32, depth=depth,
                            num_heads=4, dtype=jnp.float32,
                            attention_impl="dense")
    variables = std.init(jax.random.PRNGKey(0), x)
    want = std.apply(variables, x)

    mesh = _mesh(data=2, pipeline=4)
    pp = VisionTransformer(num_classes=4, patch_size=4, dim=32, depth=depth,
                           num_heads=4, dtype=jnp.float32,
                           attention_impl="dense", mesh=mesh,
                           pipeline_microbatches=4)
    std_params = variables["params"]
    pp_params = {k: v for k, v in std_params.items()
                 if not k.startswith("EncoderBlock_")}
    pp_params["encoder"] = pack_encoder_params(std_params, depth)
    got = jax.jit(lambda p, x: pp.apply({"params": p}, x))(pp_params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.heavy
def test_pipelined_encoder_matches_sequential():
    """Pipelined execution over 4 stages == plain layer scan: logits AND
    parameter gradients (the backward pipeline) to fp32 tolerance."""
    depth = 4
    mesh = _mesh(data=2, pipeline=4)
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(8, 8, 32).astype(np.float32))

    enc_seq = PipelinedEncoder(depth=depth, num_heads=4, dtype=jnp.float32,
                               mesh=None)
    enc_pp = PipelinedEncoder(depth=depth, num_heads=4, dtype=jnp.float32,
                              mesh=mesh, microbatches=4)
    variables = enc_seq.init(jax.random.PRNGKey(0), x)

    def loss(enc):
        def fn(params, x):
            y = enc.apply({"params": params}, x)
            return (y ** 2).sum(), y
        return fn

    (ls, ys), gs = jax.jit(jax.value_and_grad(
        loss(enc_seq), has_aux=True))(variables["params"], x)
    (lp, yp), gp = jax.jit(jax.value_and_grad(
        loss(enc_pp), has_aux=True))(variables["params"], x)
    np.testing.assert_allclose(np.asarray(yp), np.asarray(ys),
                               rtol=2e-4, atol=2e-4)
    assert np.isclose(float(lp), float(ls), rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(gs),
                    jax.tree_util.tree_leaves(gp)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=3e-3, atol=3e-4)


def _smoke_vit_cfg(**overrides):
    """Shared tiny-ViT Trainer config for the pipeline smoke tests; mesh
    axes / schedule knobs come in via overrides."""
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset
    cfg = get_preset("smoke")
    cfg.model.name = "vit"
    cfg.model.num_classes = 4
    cfg.model.compute_dtype = "float32"
    cfg.model.vit_dim = 32
    cfg.model.vit_depth = 4
    cfg.model.vit_heads = 2
    cfg.data.image_size = 8
    cfg.train.batch_size = 8
    cfg.optimizer.weight_decay = 0.0
    for k, v in overrides.items():
        cfg.override(k, v)
    return cfg


@pytest.mark.heavy
def test_pipelined_vit_through_trainer():
    """mesh.pipeline > 1 routes the ViT encoder through the GPipe path via
    the Trainer; training runs and stays finite."""
    from distributed_resnet_tensorflow_tpu.data import (
        learnable_synthetic_iterator)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    cfg = _smoke_vit_cfg(**{"mesh.data": 2, "mesh.pipeline": 4,
                            "model.vit_pipeline_microbatches": 4})
    tr = Trainer(cfg)
    tr.init_state()
    state, m = tr.train(learnable_synthetic_iterator(8, 8, 4), num_steps=2)
    assert int(state.step) == 2
    assert np.isfinite(float(m["loss"]))
    # the stacked encoder params exist (pipelined parameterization)
    assert "encoder" in state.params


def test_pipeline_unsupported_combos_rejected():
    """Round 5 closed pp x seq and MoE x tensor; what remains rejected is
    only the genuinely-invalid: an explicit non-ring attention kernel under
    a seq axis, and ring without one."""
    from distributed_resnet_tensorflow_tpu.models import VisionTransformer
    mesh = _mesh(data=2, pipeline=2, sequence=2)
    vit = VisionTransformer(num_classes=4, patch_size=4, dim=32, depth=4,
                            num_heads=4, dtype=jnp.float32,
                            attention_impl="flash", mesh=mesh,
                            pipeline_microbatches=2)
    x = jnp.zeros((8, 8, 8, 3), jnp.float32)
    with pytest.raises(ValueError, match="ring"):
        vit.init(jax.random.PRNGKey(0), x)
    enc = PipelinedEncoder(depth=4, num_heads=4, dtype=jnp.float32,
                           mesh=_mesh(data=4, pipeline=2),
                           attention_impl="ring", microbatches=2)
    with pytest.raises(ValueError, match="seq"):
        enc.init(jax.random.PRNGKey(0), jnp.zeros((8, 8, 32), jnp.float32))


def test_pipeline_seq_and_moe_tensor_accepted_by_trainer():
    """The former loud rejections (pp x seq, MoE x tensor) now construct:
    the Trainer builds both composition families without error."""
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset
    cfg = get_preset("smoke")
    cfg.model.name = "vit"
    cfg.model.vit_depth = 4
    cfg.mesh.data = 2
    cfg.mesh.pipeline = 2
    cfg.mesh.sequence = 2
    Trainer(cfg)
    cfg = get_preset("smoke")
    cfg.model.name = "vit"
    cfg.model.vit_depth = 4
    cfg.mesh.data = 1
    cfg.mesh.pipeline = 2
    cfg.mesh.expert = 2
    cfg.mesh.tensor = 2
    cfg.model.vit_num_experts = 2
    Trainer(cfg)


@pytest.mark.heavy
def test_pipelined_encoder_tp_matches_sequential():
    """pp×tp: 2 pipeline stages × 2-way Megatron tensor split × dp=2 ==
    the plain sequential encoder, logits AND grads (the psum-completed
    row-parallel contractions and their transposes)."""
    depth = 4
    mesh = _mesh(data=2, pipeline=2, tensor=2)
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(8, 8, 32).astype(np.float32))

    enc_seq = PipelinedEncoder(depth=depth, num_heads=4, dtype=jnp.float32,
                               mesh=None)
    enc_tp = PipelinedEncoder(depth=depth, num_heads=4, dtype=jnp.float32,
                              mesh=mesh, microbatches=4)
    variables = enc_seq.init(jax.random.PRNGKey(0), x)

    def loss(enc):
        def fn(params, x):
            y = enc.apply({"params": params}, x)
            return (y ** 2).sum(), y
        return fn

    (ls, ys), gs = jax.jit(jax.value_and_grad(
        loss(enc_seq), has_aux=True))(variables["params"], x)
    (lp, yp), gp = jax.jit(jax.value_and_grad(
        loss(enc_tp), has_aux=True))(variables["params"], x)
    np.testing.assert_allclose(np.asarray(yp), np.asarray(ys),
                               rtol=2e-4, atol=2e-4)
    assert np.isclose(float(lp), float(ls), rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(gs),
                    jax.tree_util.tree_leaves(gp)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=3e-3, atol=3e-4)


@pytest.mark.heavy
def test_pipelined_vit_tp_through_trainer():
    """dp×pp×tp (2×2×2) through the Trainer: the state's stacked encoder
    params carry pipeline×tensor shardings and training stays finite."""
    from distributed_resnet_tensorflow_tpu.data import (
        learnable_synthetic_iterator)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    cfg = _smoke_vit_cfg(**{"mesh.data": 2, "mesh.pipeline": 2,
                            "mesh.tensor": 2,
                            "model.vit_pipeline_microbatches": 4})
    tr = Trainer(cfg)
    tr.init_state()
    # stacked params actually sharded over pipeline AND tensor
    qkv = tr.state.params["encoder"]["qkv_kernel"]
    spec = qkv.sharding.spec
    assert spec[0] == "pipeline" and "tensor" in spec
    state, m = tr.train(learnable_synthetic_iterator(8, 8, 4), num_steps=2)
    assert int(state.step) == 2
    assert np.isfinite(float(m["loss"]))


def test_pipeline_validation_errors():
    mesh = _mesh(data=2, pipeline=4)
    enc = PipelinedEncoder(depth=6, num_heads=2, dtype=jnp.float32, mesh=mesh)
    x = jnp.zeros((8, 8, 32), jnp.float32)
    with pytest.raises(ValueError, match="divisible by pipeline"):
        enc.init(jax.random.PRNGKey(0), x)
    # indivisible microbatches: init falls back (shape-only dummy), but a
    # real apply must fail loudly rather than silently idle P-1 stages
    enc2 = PipelinedEncoder(depth=4, num_heads=2, dtype=jnp.float32,
                            mesh=mesh, microbatches=3)
    variables = enc2.init(jax.random.PRNGKey(0), x)
    with pytest.raises(ValueError, match="microbatches"):
        enc2.apply(variables, x)


def test_circular_layer_order_roundtrip():
    """stored->network map: bijection; identity for interleave=1; the
    Megatron assignment (chunk c of stage s = network layers
    [(c*P+s)*k, ...+k)) for v>1."""
    from distributed_resnet_tensorflow_tpu.models.pipeline import (
        circular_layer_order)
    assert list(circular_layer_order(8, 4, 1)) == list(range(8))
    order = circular_layer_order(8, 2, 2)  # P=2, v=2, k=2
    # stage 0 rows: chunk 0 = net layers 0,1; chunk 1 = net layers 4,5
    # stage 1 rows: chunk 0 = net layers 2,3; chunk 1 = net layers 6,7
    assert list(order) == [0, 1, 4, 5, 2, 3, 6, 7]
    assert sorted(order) == list(range(8))


def _permute_stack(params, order):
    import jax
    import jax.numpy as jnp
    idx = jnp.asarray(order)
    return jax.tree_util.tree_map(lambda a: jnp.take(a, idx, axis=0), params)


@pytest.mark.heavy
def test_circular_pipeline_matches_sequential():
    """Circular schedule (P=2 stages x v=2 chunks, M=4 microbatches) ==
    plain layer scan: logits AND parameter gradients. Exercises the
    wrapped-activation queue (each microbatch rides the ring twice)."""
    from distributed_resnet_tensorflow_tpu.models.pipeline import (
        circular_layer_order)
    depth, pstages, v = 4, 2, 2
    mesh = _mesh(data=4, pipeline=2)
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(16, 8, 32).astype(np.float32))

    enc_seq = PipelinedEncoder(depth=depth, num_heads=4, dtype=jnp.float32,
                               mesh=None)
    enc_cc = PipelinedEncoder(depth=depth, num_heads=4, dtype=jnp.float32,
                              mesh=mesh, microbatches=4, interleave=v)
    variables = enc_seq.init(jax.random.PRNGKey(0), x)
    order = circular_layer_order(depth, pstages, v)
    cc_params = _permute_stack(variables["params"], order)

    def loss(enc):
        def fn(params, x):
            y = enc.apply({"params": params}, x)
            return (y ** 2).sum(), y
        return fn

    (ls, ys), gs = jax.jit(jax.value_and_grad(
        loss(enc_seq), has_aux=True))(variables["params"], x)
    (lc, yc), gc = jax.jit(jax.value_and_grad(
        loss(enc_cc), has_aux=True))(cc_params, x)
    np.testing.assert_allclose(np.asarray(yc), np.asarray(ys),
                               rtol=2e-4, atol=2e-4)
    assert np.isclose(float(lc), float(ls), rtol=1e-4)
    inv = np.argsort(order)
    gc_net = _permute_stack(gc, inv)  # back to network order
    for a, b in zip(jax.tree_util.tree_leaves(gs),
                    jax.tree_util.tree_leaves(gc_net)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=3e-3, atol=3e-4)


@pytest.mark.heavy
def test_circular_pipeline_same_tick_store_consume():
    """M == P — the tightest legal circular case (ADVICE r3 #1): the wrap
    queue's store and consume land on the SAME tick, so correctness
    depends on the store preceding the parked read inside tick(). Full
    fwd+grad parity at pstages=4, microbatches=4, interleave=2 (depth 8,
    dp=2 x pp=4) pins that ordering against regressions."""
    from distributed_resnet_tensorflow_tpu.models.pipeline import (
        circular_layer_order)
    depth, pstages, v = 8, 4, 2
    mesh = _mesh(data=2, pipeline=4)
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(8, 8, 32).astype(np.float32))

    enc_seq = PipelinedEncoder(depth=depth, num_heads=4, dtype=jnp.float32,
                               mesh=None)
    enc_cc = PipelinedEncoder(depth=depth, num_heads=4, dtype=jnp.float32,
                              mesh=mesh, microbatches=4, interleave=v)
    variables = enc_seq.init(jax.random.PRNGKey(0), x)
    order = circular_layer_order(depth, pstages, v)
    cc_params = _permute_stack(variables["params"], order)

    def loss(enc):
        def fn(params, x):
            y = enc.apply({"params": params}, x)
            return (y ** 2).sum(), y
        return fn

    (ls, ys), gs = jax.jit(jax.value_and_grad(
        loss(enc_seq), has_aux=True))(variables["params"], x)
    (lc, yc), gc = jax.jit(jax.value_and_grad(
        loss(enc_cc), has_aux=True))(cc_params, x)
    np.testing.assert_allclose(np.asarray(yc), np.asarray(ys),
                               rtol=2e-4, atol=2e-4)
    assert np.isclose(float(lc), float(ls), rtol=1e-4)
    inv = np.argsort(order)
    gc_net = _permute_stack(gc, inv)
    for a, b in zip(jax.tree_util.tree_leaves(gs),
                    jax.tree_util.tree_leaves(gc_net)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=3e-3, atol=3e-4)


@pytest.mark.heavy
def test_circular_pipeline_with_tensor_parallel():
    """Circular x Megatron: dp=2 x pp=2 x tp=2 with v=2 chunks per stage
    still matches the sequential encoder (logits)."""
    from distributed_resnet_tensorflow_tpu.models.pipeline import (
        circular_layer_order)
    depth, v = 4, 2
    mesh = _mesh(data=2, pipeline=2, tensor=2)
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(8, 8, 32).astype(np.float32))
    enc_seq = PipelinedEncoder(depth=depth, num_heads=4, dtype=jnp.float32,
                               mesh=None)
    enc_cc = PipelinedEncoder(depth=depth, num_heads=4, dtype=jnp.float32,
                              mesh=mesh, microbatches=4, interleave=v)
    variables = enc_seq.init(jax.random.PRNGKey(0), x)
    order = circular_layer_order(depth, 2, v)
    want = enc_seq.apply(variables, x)
    got = jax.jit(lambda p, xx: enc_cc.apply({"params": p}, xx))(
        _permute_stack(variables["params"], order), x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_circular_requires_enough_microbatches():
    """M < P under interleave must fail loudly (the wrap queue would be
    consumed before it is filled)."""
    mesh = _mesh(data=2, pipeline=4)
    enc = PipelinedEncoder(depth=8, num_heads=4, dtype=jnp.float32,
                           mesh=mesh, microbatches=2, interleave=2)
    x = jnp.zeros((8, 8, 32), jnp.float32)
    with pytest.raises(ValueError, match="interleave"):
        enc.init(jax.random.PRNGKey(0), x)


@pytest.mark.heavy
def test_circular_vit_through_trainer():
    """model.vit_pipeline_interleave=2 routes the ViT encoder through the
    circular schedule via the Trainer config path (dp x pp x tp mesh);
    training runs and stays finite."""
    from distributed_resnet_tensorflow_tpu.data import (
        learnable_synthetic_iterator)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    cfg = _smoke_vit_cfg(**{
        "mesh.data": 2, "mesh.pipeline": 2, "mesh.tensor": 2,
        "model.vit_pipeline_microbatches": 4,  # local batch 4 -> mb of 1
        "model.vit_pipeline_interleave": 2})   # depth 4 = 2 stages x 2 chunks
    tr = Trainer(cfg)
    tr.init_state()
    state, m = tr.train(learnable_synthetic_iterator(8, 8, 4), num_steps=2)
    assert int(state.step) == 2
    assert np.isfinite(float(m["loss"]))


@pytest.mark.heavy
def test_pipeline_flash_attention_matches_dense():
    """Flash attention inside pipeline stages (VERDICT r3 #7): the
    Pallas-kernel pipelined encoder == the dense pipelined encoder ==
    the sequential encoder, fwd AND grads (interpret-mode kernels, f32,
    dp=2 x pp=2)."""
    mesh = _mesh(data=4, pipeline=2)
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(16, 8, 32).astype(np.float32))
    enc_seq = PipelinedEncoder(depth=4, num_heads=4, dtype=jnp.float32,
                               mesh=None)
    enc_fl = PipelinedEncoder(depth=4, num_heads=4, dtype=jnp.float32,
                              mesh=mesh, microbatches=4,
                              attention_impl="flash_interpret")
    variables = enc_seq.init(jax.random.PRNGKey(0), x)

    def loss(enc):
        def fn(params, x):
            y = enc.apply({"params": params}, x)
            return (y ** 2).sum(), y
        return fn

    (ls, ys), gs = jax.jit(jax.value_and_grad(
        loss(enc_seq), has_aux=True))(variables["params"], x)
    (lf, yf), gf = jax.jit(jax.value_and_grad(
        loss(enc_fl), has_aux=True))(variables["params"], x)
    np.testing.assert_allclose(np.asarray(yf), np.asarray(ys),
                               rtol=2e-4, atol=2e-4)
    assert np.isclose(float(lf), float(ls), rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(gs),
                    jax.tree_util.tree_leaves(gf)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=3e-3, atol=3e-4)


@pytest.mark.heavy
def test_pipelined_moe_matches_sequential():
    """pp x ep (VERDICT r3 weak #6): stacked-stage Switch MoE blocks —
    dp=2 x pp=2 x ep=2 == the sequential MoE encoder, logits AND grads
    (incl. router), with AMPLE capacity so the per-microbatch capacity
    groups cannot change drop decisions vs the sequential batch group."""
    mesh = _mesh(data=2, pipeline=2, expert=2)
    rng = np.random.RandomState(8)
    x = jnp.asarray(rng.randn(8, 8, 32).astype(np.float32))
    kw = dict(depth=4, num_heads=4, dtype=jnp.float32, num_experts=4,
              expert_capacity_factor=4.0)
    enc_seq = PipelinedEncoder(mesh=None, **kw)
    enc_pp = PipelinedEncoder(mesh=mesh, microbatches=2, **kw)
    variables = enc_seq.init(jax.random.PRNGKey(0), x)
    assert "moe_w1" in variables["params"]

    def loss(enc):
        def fn(params, x):
            y, _ = enc.apply({"params": params}, x, mutable=["losses"])
            return (y ** 2).sum(), y
        return fn

    (ls, ys), gs = jax.jit(jax.value_and_grad(
        loss(enc_seq), has_aux=True))(variables["params"], x)
    (lp, yp), gp = jax.jit(jax.value_and_grad(
        loss(enc_pp), has_aux=True))(variables["params"], x)
    np.testing.assert_allclose(np.asarray(yp), np.asarray(ys),
                               rtol=2e-4, atol=2e-4)
    assert np.isclose(float(lp), float(ls), rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(gs),
                    jax.tree_util.tree_leaves(gp)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=3e-3, atol=3e-4)

    # aux loss: sown on both paths; per-microbatch grouping makes the
    # pipelined value an average of group auxes — close, not identical
    _, st_s = enc_seq.apply(variables, x, mutable=["losses"])
    _, st_p = enc_pp.apply(variables, x, mutable=["losses"])
    aux_s = float(jax.tree_util.tree_leaves(st_s["losses"])[0])
    aux_p = float(jax.tree_util.tree_leaves(st_p["losses"])[0])
    assert aux_s >= 4.0 - 1e-3  # depth x (E sum f*p >= 1) lower bound
    assert abs(aux_p - aux_s) / aux_s < 0.3


@pytest.mark.heavy
def test_pipelined_moe_vit_trains_through_trainer():
    """dp x pp x ep ViT through the Trainer: trains, stays finite, and the
    sown pipeline aux loss reaches the total (loss > cross_entropy, wd 0)."""
    from distributed_resnet_tensorflow_tpu.data import (
        learnable_synthetic_iterator)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    cfg = _smoke_vit_cfg(**{"mesh.data": 2, "mesh.pipeline": 2,
                            "mesh.expert": 2,
                            "model.vit_pipeline_microbatches": 2,
                            "model.vit_num_experts": 4})
    tr = Trainer(cfg)
    tr.init_state()
    # expert-stacked leaves carry pipeline x expert shardings
    spec = tr.state.params["encoder"]["moe_w1"].sharding.spec
    assert spec[0] == "pipeline" and spec[1] == "expert", spec
    state, m = tr.train(learnable_synthetic_iterator(8, 8, 4), num_steps=2)
    assert int(state.step) == 2
    assert np.isfinite(float(m["loss"]))
    assert float(m["loss"]) > float(m["cross_entropy"])


@pytest.mark.heavy
def test_moe_vit_repacked_pipeline_matches_standard():
    """Unpipelined ViT-MoE params repacked via pack_encoder_params run
    through the pp x ep pipelined ViT give the same logits (ample capacity
    so batch-group vs microbatch-group routing cannot drop differently) —
    the checkpoint-migration contract now covers MoE blocks too."""
    from distributed_resnet_tensorflow_tpu.models import VisionTransformer
    depth = 4
    rng = np.random.RandomState(9)
    x = jnp.asarray(rng.randn(8, 16, 16, 3).astype(np.float32))
    kw = dict(num_classes=4, patch_size=4, dim=32, depth=depth, num_heads=4,
              dtype=jnp.float32, num_experts=4, expert_capacity_factor=4.0)
    std = VisionTransformer(attention_impl="dense", **kw)
    variables = std.init(jax.random.PRNGKey(0), x)
    want, _ = std.apply(variables, x, mutable=["losses"])

    mesh = _mesh(data=2, pipeline=2, expert=2)
    pp = VisionTransformer(attention_impl="dense", mesh=mesh,
                           pipeline_microbatches=2, **kw)
    std_params = variables["params"]
    pp_params = {k: v for k, v in std_params.items()
                 if not k.startswith("EncoderBlock_")}
    pp_params["encoder"] = pack_encoder_params(std_params, depth)
    got, _ = jax.jit(lambda p, xx: pp.apply(
        {"params": p}, xx, mutable=["losses"]))(pp_params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.heavy
def test_pipeline_ring_attention_matches_sequential():
    """pp x seq (VERDICT r4 #3): ring attention inside pipeline stages —
    tokens sharded over `seq`, kv rotating via ppermute within each
    pipeline tick — == the sequential dense encoder, fwd AND grads
    (dp=2 x pp=2 x sp=2; the lax ring inner block is exact at f32)."""
    depth = 4
    mesh = _mesh(data=2, pipeline=2, sequence=2)
    rng = np.random.RandomState(11)
    x = jnp.asarray(rng.randn(8, 8, 32).astype(np.float32))
    enc_seq = PipelinedEncoder(depth=depth, num_heads=4, dtype=jnp.float32,
                               mesh=None)
    enc_ring = PipelinedEncoder(depth=depth, num_heads=4,
                                dtype=jnp.float32, mesh=mesh,
                                microbatches=4, attention_impl="ring")
    variables = enc_seq.init(jax.random.PRNGKey(0), x)

    def loss(enc):
        def fn(params, x):
            y = enc.apply({"params": params}, x)
            return (y ** 2).sum(), y
        return fn

    (ls, ys), gs = jax.jit(jax.value_and_grad(
        loss(enc_seq), has_aux=True))(variables["params"], x)
    (lr_, yr), gr = jax.jit(jax.value_and_grad(
        loss(enc_ring), has_aux=True))(variables["params"], x)
    np.testing.assert_allclose(np.asarray(yr), np.asarray(ys),
                               rtol=2e-4, atol=2e-4)
    assert np.isclose(float(lr_), float(ls), rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(gs),
                    jax.tree_util.tree_leaves(gr)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=3e-3, atol=3e-4)


@pytest.mark.heavy
def test_pipelined_vit_ring_through_trainer():
    """dp x pp x sp end-to-end: attention_impl='auto' resolves to ring
    under the seq axis and the pipelined ViT trains finitely."""
    from distributed_resnet_tensorflow_tpu.data import (
        learnable_synthetic_iterator)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    cfg = _smoke_vit_cfg(**{"mesh.data": 2, "mesh.pipeline": 2,
                            "mesh.sequence": 2,
                            "model.vit_pipeline_microbatches": 2})
    tr = Trainer(cfg)
    assert tr.model.attention_impl == "ring"
    tr.init_state()
    state, m = tr.train(learnable_synthetic_iterator(8, 8, 4), num_steps=2)
    assert int(state.step) == 2
    assert np.isfinite(float(m["loss"]))
    assert "encoder" in state.params


@pytest.mark.heavy
@pytest.mark.slow  # re-tiered out of the 870s tier-1 (ISSUE 20, ~12s: the
# joint pp x sp x ep composition trains twice); tier-1 keeps each leg of
# the composition via test_pipelined_moe_matches_sequential (pp x ep) and
# test_ring_flash_matches_lax_ring (sp ring attention); the full
# (unfiltered) suite still runs the joint model
def test_pipeline_ring_moe_matches_sequential():
    """pp x sp x ep — the joint composition the round-4 review called out
    as uncovered ("the 6-axis mesh still cannot jointly cover a
    long-context MoE pipeline model"): ring attention over `seq` AND
    Switch-MoE MLPs over `expert` inside the same pipeline stages ==
    the sequential dense MoE encoder, fwd AND grads (ample capacity so
    seq-local routing groups cannot change drop decisions)."""
    depth = 4
    mesh = _mesh(pipeline=2, sequence=2, expert=2)
    rng = np.random.RandomState(13)
    x = jnp.asarray(rng.randn(4, 8, 32).astype(np.float32))
    kw = dict(depth=depth, num_heads=4, dtype=jnp.float32, num_experts=4,
              expert_capacity_factor=4.0)
    enc_seq = PipelinedEncoder(mesh=None, **kw)
    enc_rm = PipelinedEncoder(mesh=mesh, microbatches=2,
                              attention_impl="ring", **kw)
    variables = enc_seq.init(jax.random.PRNGKey(0), x)
    assert "moe_w1" in variables["params"]

    def loss(enc):
        def fn(params, x):
            y, _ = enc.apply({"params": params}, x, mutable=["losses"])
            return (y ** 2).sum(), y
        return fn

    (ls, ys), gs = jax.jit(jax.value_and_grad(
        loss(enc_seq), has_aux=True))(variables["params"], x)
    (lm, ym), gm = jax.jit(jax.value_and_grad(
        loss(enc_rm), has_aux=True))(variables["params"], x)
    np.testing.assert_allclose(np.asarray(ym), np.asarray(ys),
                               rtol=2e-4, atol=2e-4)
    assert np.isclose(float(lm), float(ls), rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(gs),
                    jax.tree_util.tree_leaves(gm)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=3e-3, atol=3e-4)


@pytest.mark.heavy
def test_pipelined_moe_tensor_matches_sequential():
    """pp x ep x tp (VERDICT r4 #4): Switch-MoE pipeline stages with each
    expert's FFN Megatron-split over `tensor` — pipeline=2 x expert=2 x
    tensor=2 == the sequential MoE encoder, logits AND grads, with AMPLE
    capacity so microbatch grouping cannot change drops."""
    mesh = _mesh(pipeline=2, expert=2, tensor=2)
    rng = np.random.RandomState(12)
    x = jnp.asarray(rng.randn(4, 8, 32).astype(np.float32))
    kw = dict(depth=4, num_heads=4, dtype=jnp.float32, num_experts=4,
              expert_capacity_factor=4.0)
    enc_seq = PipelinedEncoder(mesh=None, **kw)
    enc_pp = PipelinedEncoder(mesh=mesh, microbatches=2, **kw)
    variables = enc_seq.init(jax.random.PRNGKey(0), x)

    def loss(enc):
        def fn(params, x):
            y, _ = enc.apply({"params": params}, x, mutable=["losses"])
            return (y ** 2).sum(), y
        return fn

    (ls, ys), gs = jax.jit(jax.value_and_grad(
        loss(enc_seq), has_aux=True))(variables["params"], x)
    (lp, yp), gp = jax.jit(jax.value_and_grad(
        loss(enc_pp), has_aux=True))(variables["params"], x)
    np.testing.assert_allclose(np.asarray(yp), np.asarray(ys),
                               rtol=2e-4, atol=2e-4)
    assert np.isclose(float(lp), float(ls), rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(gs),
                    jax.tree_util.tree_leaves(gp)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=3e-3, atol=3e-4)
