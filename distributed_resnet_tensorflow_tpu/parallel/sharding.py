"""Sharding rules for params / optimizer state / batches.

Replaces the reference's ``tf.train.replica_device_setter`` variable placement
(reference resnet_cifar_main.py:392-396 — round-robin variables onto ps tasks)
with ``NamedSharding`` annotations: parameters are replicated by default (pure
DP, matching the reference capability) and optionally sharded ZeRO-style over
the ``fsdp`` axis for large models/optimizers, with XLA inserting
all-gather/reduce-scatter instead of grpc push/pull.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stacked_encoder_spec(leaf_name: str, ndim: int, tensor: int = 1) -> P:
    """PartitionSpec for one PipelinedEncoder stacked-param leaf: ``pipeline``
    on the leading depth axis, plus (when ``tensor`` > 1) the Megatron
    placement on the head/hidden axis — whole heads of qkv (L,D,3,H,hd) and
    proj (L,H,hd,D), columns of mlp_w1 (L,D,F)/mlp_b1 (L,F), rows of
    mlp_w2 (L,F,D) — and, for the MoE pipeline (pp×ep), ``expert`` on the
    expert-stacked axis of moe_w1/b1/w2/b2 (L,E,...) while the router
    stays replicated across ``expert`` (routing must be globally
    consistent). Single source of truth for BOTH the training-state
    sharding (param_sharding_rule) and the pipeline shard_map in_specs
    (models/pipeline.py) — they must agree or every step reshards."""
    if leaf_name.startswith("moe_"):
        if tensor > 1:
            # Megatron INSIDE each expert (MoE×tensor, round 5): columns
            # of moe_w1 (L,E,D,F)/moe_bias1 (L,E,F), rows of moe_w2
            # (L,E,F,D); moe_bias2 stays replicated across `tensor`
            # (added after the completing psum, models/moe.expert_ffn)
            spec = {
                "moe_w1": P("pipeline", "expert", None, "tensor"),
                "moe_bias1": P("pipeline", "expert", "tensor"),
                "moe_w2": P("pipeline", "expert", "tensor", None),
            }.get(leaf_name)
            if spec is not None:
                return spec
        return P(*(("pipeline", "expert") + (None,) * (ndim - 2)))
    if tensor > 1:
        spec = {
            "qkv_kernel": P("pipeline", None, None, "tensor", None),
            "proj_kernel": P("pipeline", "tensor", None, None),
            "mlp_w1": P("pipeline", None, "tensor"),
            "mlp_b1": P("pipeline", "tensor"),
            "mlp_w2": P("pipeline", "tensor", None),
        }.get(leaf_name)
        if spec is not None:
            return spec
    return P(*(("pipeline",) + (None,) * (ndim - 1)))


# (leaf, shape, tensor) triples already warned about below — once per
# distinct drop-back, not per retrace/model rebuild
_TENSOR_DROPBACK_WARNED: set = set()


def _warn_tensor_dropback(path: str, shape, tensor: int) -> None:
    """A requested tensor split the shape does not divide falls back to
    replication — numerics stay correct, but the leaf's FLOPs (often the
    dominant MLP matmuls) then run in full on every tensor peer. Silent
    replicated compute is the failure mode the Trainer's dead-axis config
    checks exist to prevent, so say it loudly, once per leaf shape."""
    key = (path.rsplit("['", 1)[-1], tuple(shape), tensor)
    if key in _TENSOR_DROPBACK_WARNED:
        return
    _TENSOR_DROPBACK_WARNED.add(key)
    import logging
    logging.getLogger(__name__).warning(
        "tensor axis (%d) does not divide the split dim of %s (shape %s) "
        "— this leaf will REPLICATE across tensor peers; pick model dims "
        "divisible by the tensor axis", tensor, path, tuple(shape))


def param_sharding_rule(path: str, shape: tuple, mesh: Mesh,
                        fsdp_min_size: int = 2 ** 16) -> P:
    """Parameter placement rule.

    Tensor parallelism (Megatron-style, transformer blocks only): when the
    ``tensor`` axis is >1, attention heads and the MLP hidden dim split
    column-/row-wise so each block needs exactly one all-reduce, inserted by
    XLA at the row-parallel contraction:

        qkv kernel (D, 3, H, hd) → P(None, None, "tensor", None)  (whole heads)
        out  kernel (H, hd, D)   → P("tensor", None, None)
        mlp  up    (D, 4D)       → P(None, "tensor")
        mlp  down  (4D, D)       → P("tensor", None)

    ZeRO-3-style fsdp: shard the largest dimension of big params over
    ``fsdp`` when it divides evenly; small params stay replicated (a sharded
    1-D BN scale buys nothing and costs collective latency)."""
    pipeline = mesh.shape.get("pipeline", 1)
    if pipeline > 1 and "['encoder']" in path and shape \
            and shape[0] % pipeline == 0:
        # PipelinedEncoder stacks per-layer params on a leading depth axis;
        # sharding it over `pipeline` (× `tensor` on the Megatron axes) puts
        # each stage's weights (and optimizer moments) on its own devices —
        # matching the shard_map in_specs so no per-step resharding is needed
        leaf = path.rsplit("['", 1)[-1].rstrip("]'")
        spec = stacked_encoder_spec(leaf, len(shape),
                                    mesh.shape.get("tensor", 1))
        # only honor a tensor split the shape actually divides (dropping
        # back to the tensor-free spec keeps `expert` on MoE leaves)
        for axis_name, dim in zip(spec, shape):
            if axis_name == "tensor" and dim % mesh.shape["tensor"]:
                _warn_tensor_dropback(path, shape, mesh.shape["tensor"])
                return stacked_encoder_spec(leaf, len(shape), 1)
        return spec
    expert = mesh.shape.get("expert", 1)
    tensor = mesh.shape.get("tensor", 1)
    if "SwitchMlp" in path and "router" not in path and shape:
        # Switch MoE expert-stacked weights: each expert group holds its
        # own experts (+ moments); the router stays replicated. With a
        # tensor axis, each expert's FFN additionally splits Megatron-
        # style (w1/bias1 columns, w2 rows; one psum — expert_ffn), so
        # ep×tp and tp-only MoE stop replicating the dominant FLOPs.
        e_ax = "expert" if (expert > 1 and shape[0] % expert == 0) else None
        leaf = path.rsplit("['", 1)[-1].rstrip("]'")
        t_pos = {"w1": 2, "bias1": 1, "w2": 1}.get(leaf)
        spec = [e_ax] + [None] * (len(shape) - 1)
        if tensor > 1 and t_pos is not None and len(shape) > t_pos:
            if shape[t_pos] % tensor == 0:
                spec[t_pos] = "tensor"
            else:
                _warn_tensor_dropback(path, shape, tensor)
        if any(spec):
            return P(*spec)
        # no expert/tensor split applies — fall through to the fsdp rule
    if tensor > 1 and ("EncoderBlock" in path or "MultiHeadAttention" in path):
        if "kernel" in path:
            split_dim = None
            if "qkv" in path and len(shape) == 4:
                split_dim, spec = 2, P(None, None, "tensor", None)
            elif "proj" in path and len(shape) == 3:
                split_dim, spec = 0, P("tensor", None, None)
            elif "Dense_0" in path and len(shape) == 2:
                split_dim, spec = 1, P(None, "tensor")
            elif "Dense_1" in path and len(shape) == 2:
                split_dim, spec = 0, P("tensor", None)
            if split_dim is not None:
                if shape[split_dim] % tensor == 0:
                    return spec
                _warn_tensor_dropback(path, shape, tensor)
        if "bias" in path and len(shape) == 1 and "Dense_0" in path \
                and shape[0] % tensor == 0:
            return P("tensor")
    fsdp = mesh.shape["fsdp"]
    if fsdp <= 1 or int(np.prod(shape)) < fsdp_min_size:
        return P()
    # choose the largest axis divisible by the fsdp size
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in order:
        if shape[i] % fsdp == 0:
            spec = [None] * len(shape)
            spec[i] = "fsdp"
            return P(*spec)
    return P()


def tree_param_shardings(params: Any, mesh: Mesh,
                         fsdp_min_size: int = 2 ** 16):
    """Map a param pytree to NamedShardings via `param_sharding_rule`."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in flat:
        name = "/".join(str(p) for p in path)
        spec = param_sharding_rule(name, np.shape(leaf), mesh)
        out.append(NamedSharding(mesh, spec))
    return jax.tree_util.tree_unflatten(treedef, out)


def shard_batch(batch: Any, mesh: Mesh) -> Any:
    """Device-put a host batch with the leading dim split over the batch axes.

    For multi-host, use `make_global_batch` instead — each process contributes
    its local shard (the reference's Horovod path never sharded input at all;
    each rank shuffled the full dataset independently, SURVEY.md §3.2 — fixed
    here by construction).
    """
    from .mesh import data_sharding
    sharding = data_sharding(mesh)
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), batch)


def pad_batch_to_multiple(batch: dict, multiple: int) -> dict:
    """Pad the leading dim to a multiple of the batch-shard count, adding (or
    extending) a float "mask" entry so padded rows don't count in metrics.
    Needed because an eval batch (reference used 100, resnet_cifar_eval.py)
    need not divide the device count."""
    b = next(iter(batch.values())).shape[0]
    rem = b % multiple
    if rem == 0:
        return batch
    pad = multiple - rem
    out = {}
    for k, v in batch.items():
        if k == "mask":
            continue
        pad_width = ((0, pad),) + ((0, 0),) * (v.ndim - 1)
        out[k] = np.pad(np.asarray(v), pad_width)
    mask = batch.get("mask")
    if mask is None:
        mask = np.ones((b,), np.float32)
    out["mask"] = np.concatenate([np.asarray(mask),
                                  np.zeros((pad,), np.float32)])
    return out


def shard_stacked_batch(batch: Any, mesh: Mesh) -> Any:
    """Like shard_batch but for K-step stacked batches (K, B, ...): the K
    axis is unsharded (scan iterates it), B splits over the batch axes."""
    from .mesh import data_sharding
    sharding = NamedSharding(mesh, P(None, *data_sharding(mesh).spec))
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), batch)


def make_global_stacked_batch(local_batch: Any, mesh: Mesh) -> Any:
    """Multi-process variant of shard_stacked_batch: each process holds
    (K, B_local, ...); the global array is (K, B_local·num_input_shards,
    ...). The multiplier is the number of DISTINCT batch slices across
    processes (mesh.process_batch_slice) — equal to process_count for pure
    data-over-processes, smaller when a non-batch axis spans processes
    (those processes feed identical replicated slices)."""
    from .mesh import data_sharding, process_batch_slice
    sharding = NamedSharding(mesh, P(None, *data_sharding(mesh).spec))
    _, n_shards = process_batch_slice(mesh)

    def _make(x):
        global_shape = (x.shape[0], x.shape[1] * n_shards) + x.shape[2:]
        return jax.make_array_from_process_local_data(sharding, x, global_shape)

    return jax.tree_util.tree_map(_make, local_batch)


def make_global_batch(local_batch: Any, mesh: Mesh) -> Any:
    """Assemble a global jax.Array from per-process local data (multi-host).
    Global batch = local × num distinct batch slices (see
    make_global_stacked_batch)."""
    from .mesh import data_sharding, process_batch_slice
    sharding = data_sharding(mesh)
    _, n_shards = process_batch_slice(mesh)

    def _make(x):
        global_shape = (x.shape[0] * n_shards,) + x.shape[1:]
        return jax.make_array_from_process_local_data(sharding, x, global_shape)

    return jax.tree_util.tree_map(_make, local_batch)
