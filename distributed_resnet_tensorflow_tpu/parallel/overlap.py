"""Bucketed gradient-communication overlap for the dp / dp_fsdp exchange.

The default data-parallel step leaves the gradient all-reduce to XLA's
sharding propagation: one (often fused) collective materializes after the
FULL backward pass, serializing communication behind compute — at
multi-host scale that tail is a first-order step-time term
(arXiv:1711.00705 measures bucketed allreduce interleaved with backprop
hiding most of it; arXiv:1802.05799's tensor-fusion knob is the same
idea). This module rebuilds the exchange explicitly:

  * the loss/grad computation runs inside a ``shard_map`` over the batch
    axes (``data`` × ``fsdp``), so each device produces its LOCAL gradient
    contribution with no implicit collective;
  * gradient leaves are greedily grouped — in REVERSE parameter order,
    approximating backprop availability (output-side layers finish first)
    — into buckets of at most ``comm.bucket_mb`` MB;
  * each bucket is exchanged with its own ``lax.psum`` (plus a
    ``psum_scatter`` over ``fsdp`` for ZeRO-sharded leaves), and buckets
    are chained through ``lax.optimization_barrier`` so they issue in
    order and XLA's all-reduce combiner cannot re-merge them into one
    end-of-step collective. Each bucket's psum depends only on that
    bucket's grads, so the latency-hiding scheduler overlaps it with the
    rest of the backward pass.

Numerics: per leaf, the exchange is the same all-reduce over the same
per-device operands regardless of bucketing, so bucketed and unbucketed
(single-bucket) runs produce BIT-IDENTICAL gradients — pinned by
tests/test_overlap.py on the virtual 8-device mesh. Against the default
XLA-propagation path the result agrees to float rounding (the reduction
tree differs), not bitwise.

Compressed exchange (``comm.compress``, docs/precision.md): each bucket's
payload is cast to bf16/fp16 BEFORE its collective and re-materialized
f32 after — half the inter-host bytes on the SAME bucket plan
(arXiv:1811.05233 trained ImageNet/ResNet-50 to reference accuracy with
half-precision allreduce). The cast is per-leaf and bucketing-independent,
so the bit-identical many-vs-one-bucket claim HOLDS under compression
(pinned by tests/test_precision.py); against the uncompressed exchange the
result is allclose at the compressed dtype's rounding, by design. Local
gradient accumulation and the optimizer update stay f32 — only the wire
format narrows.

Hierarchical exchange (``comm.hierarchy``, arXiv:1811.05233's 2D-torus
allreduce; arXiv:1711.04325's intra-node-reduce-then-inter-node): when
the ``data`` axis factors into a fast intra-host tier of size k and a
slow inter-host tier (host-aware device order — parallel/mesh.
data_axis_host_factorization — or the explicit ``comm.intra_axis_size``
override), each bucket's flat data-axis psum is restaged as
reduce-scatter over the k intra-host peers → psum of the 1/k shard over
the inter-host tier → all-gather back intra-host, all via
``axis_index_groups`` on the ONE ``data`` axis (no mesh rebuild, no
nested shard_map). The full payload crosses only the fast tier; the
slow tier carries 1/k of it — the PR 10 fsdp-leaf trick generalized to
every bucket. It composes with ``comm.compress`` (the cast precedes the
staged collectives), zero1 (data-scattered leaves already move 1/N and
stay on their flat scatter), and the accumulation scan (one staged
exchange per optimizer step). Numerics: flat-vs-hierarchical is the
same sum under a different association, so results agree to float
rounding, not bitwise (tests pin bitwise equality on exactly-
representable payloads, and bitwise determinism of the hierarchical
plan against itself); many-vs-one-bucket stays bit-identical within
either plan.

Layout-aware exchange (the universal overlap envelope): the exchange is
no longer batch-mesh-only. Per leaf, the reduce-axis set derives from
the leaf's PartitionSpec — a tensor-/expert-/pipeline-sharded leaf keeps
its shaping-axis placement and psums over the batch axes (plus any
shaping axis it is REPLICATED over) only; leaves are bucketed BY
reduce-axis set so one bucket's tuple-psum never mixes axis sets (the
MoE expert leaves get their own buckets). Three mechanisms, one per
parallelism style:

  * ``tensor`` (Megatron via GSPMD propagation, dp_tp): left AUTO in a
    partially-manual shard_map — constraints and the per-op collectives
    keep riding propagation inside the body, exactly as under jit.
  * ``pipeline`` (+``expert``: dp_pp, dp_pp_ep): mapped MANUALLY along
    with the batch axes; the PipelinedEncoder detects the enclosing
    manual map (parallel/mesh.manual_axes) and runs its schedule INLINE
    — jax 0.4.37 mis-transposes a nested shard_map over auto axes
    (measured: garbage cotangents), so the model's own shard_map must
    not rebuild inside the body. The bucketed exchange then issues after
    the pipeline's backward flush.
  * gradient accumulation (``train.grad_accum_steps`` > 1): the
    microbatch scan runs INSIDE the shard_map body accumulating LOCAL
    f32 gradients, and ONE bucketed exchange fires after the final
    microbatch — wire traffic per optimizer step drops from ``accum×``
    (the per-microbatch exchange XLA propagation emits inside lax.scan)
    to ``1×``, and the exchange overlaps the final microbatch's
    backprop (the last microbatch is peeled out of the scan so its
    backward is still in flight when the first buckets issue).

Replicated-leaf calculus on shaped meshes: each peer's local loss
contribution is scaled so the SUM over every manual peer equals the
global loss (CE /R, decay/aux /(shards·R), R = product of non-batch
manual axis sizes). Each leaf's local gradient is then the true partial
derivative w.r.t. that peer's shard, and the exchange is uniformly
"psum over the manual axes the leaf's spec does not name" — redundant
compute (a head replicated across pipeline peers) and partial compute
(a router fed through the expert all-to-all) need no case split.

Support envelope (``overlap_unsupported_reason``): batch-parallel,
tensor (unpipelined), pipeline and pipeline×expert meshes across the
conv/logistic/transformer families, with or without gradient
accumulation. Still refused, each with its precise reason: ``seq`` > 1
(ring attention's shard_map nests), ``expert`` > 1 without a pipeline
axis (SwitchMlp's a2a shard_map nests), ``tensor`` × ``pipeline``
(auto axis inside a manual body), and per-replica BN on BatchNorm
models. ``comm.overlap=auto`` quietly stays off outside the envelope;
``=on`` raises with the reason.
"""
from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..telemetry.tracer import span

log = logging.getLogger(__name__)

#: the two batch axes every exchange reduces over (size-1 axes are
#: no-ops; both always exist on a full mesh — parallel/mesh.AXES)
BATCH_AXES = ("data", "fsdp")

#: non-batch mesh axes, in the canonical parallel/mesh.AXES order —
#: the candidates for manual shaping axes in the layout-aware exchange
SHAPING_AXES = ("pipeline", "expert", "seq", "tensor")


def overlap_auto_axes(mesh: Mesh) -> frozenset:
    """Mesh axes the exchange shard_map leaves AUTOMATIC: ``tensor``,
    whose Megatron placement rides GSPMD propagation +
    with_sharding_constraint (models/transformer.py) rather than explicit
    collectives — inside the body it keeps behaving exactly as under
    jit. Everything else the envelope admits is manual."""
    return frozenset({"tensor"}) if mesh.shape.get("tensor", 1) > 1 \
        else frozenset()


def overlap_shaping_axes(mesh: Mesh):
    """Active (>1) non-batch axes the exchange maps MANUALLY, canonical
    order — the axes whose redundancy factor scales the local loss and
    whose names join replicated leaves' reduce sets."""
    auto = overlap_auto_axes(mesh)
    return tuple(a for a in SHAPING_AXES
                 if a not in auto and mesh.shape.get(a, 1) > 1)


def _spec_axis_names(spec: P) -> frozenset:
    names = set()
    for entry in spec:
        if entry is None:
            continue
        tup = entry if isinstance(entry, tuple) else (entry,)
        names.update(tup)
    return frozenset(names)


def leaf_reduce_axes(spec: P, shaping) -> tuple:
    """The psum axis set for one gradient leaf: always the batch axes,
    plus every active shaping axis the leaf's spec does NOT name (a leaf
    sharded over ``pipeline``/``expert`` already holds a distinct shard
    per peer there — summing would corrupt it; a leaf replicated over
    them carries a 1/R-scaled partial that the psum reconstructs)."""
    named = _spec_axis_names(spec)
    return BATCH_AXES + tuple(a for a in shaping if a not in named)


#: dtypes the exchange payload may compress to (``comm.compress``) — the
#: SAME name→dtype map the step policy uses (parallel/precision.py is
#: the one resolution point for every low-precision knob)
from .precision import POLICY_DTYPES as COMPRESS_DTYPES  # noqa: E402


def compress_dtype(cfg) -> Optional[str]:
    """``comm.compress`` → the payload dtype NAME ("bf16"/"fp16") or None
    (off). Pure validation — whether compression actually applies is the
    overlap plan's call (it rides the bucketed exchange; the Trainer
    warns when compression is requested while the exchange is off)."""
    mode = cfg.comm.compress
    if mode == "off":
        return None
    if mode not in COMPRESS_DTYPES:
        raise ValueError(f"unknown comm.compress setting {mode!r}; "
                         f"supported: off, {sorted(COMPRESS_DTYPES)}")
    return mode


def hierarchy_groups(k_intra: int, k_inter: int):
    """``axis_index_groups`` for the two tiers of a factored ``data`` axis
    of size ``k_intra × k_inter``: host-aware device order places a
    host's devices CONSECUTIVELY along the axis, so the intra-tier
    groups are the consecutive blocks ``[b·k, …, b·k+k-1]`` and the
    inter-tier groups are the stride-k columns ``[r, r+k, …]`` (one peer
    per host, matched by intra-host rank)."""
    gi = [[b * k_intra + r for r in range(k_intra)] for b in range(k_inter)]
    ge = [[b * k_intra + r for b in range(k_inter)] for r in range(k_intra)]
    return gi, ge


def hierarchy_factor(cfg, mesh: Mesh) -> Optional[int]:
    """The intra-tier group size k for (cfg, mesh): the explicit
    ``comm.intra_axis_size`` override when set (validated — must be a
    non-trivial divisor of the data axis), else the host-derived
    factorization (parallel/mesh.data_axis_host_factorization). None
    when no non-trivial factorization exists."""
    dsize = int(mesh.shape.get("data", 1))
    k = int(getattr(cfg.comm, "intra_axis_size", 0) or 0)
    if k:
        if dsize <= 1 or k <= 1 or k >= dsize or dsize % k:
            raise ValueError(
                f"comm.intra_axis_size={k} must satisfy 1 < k < data axis "
                f"size ({dsize}) and divide it — the hierarchical exchange "
                "needs a non-trivial uniform two-tier factorization")
        return k
    from .mesh import data_axis_host_factorization
    return data_axis_host_factorization(mesh)


def resolve_hierarchy(cfg, mesh: Mesh) -> Optional[int]:
    """``comm.hierarchy`` → the intra-tier size k or None (flat).
    ``auto`` quietly stays flat when the mesh gives no factorization;
    ``on`` raises instead of silently training a different program."""
    mode = cfg.comm.hierarchy
    if mode not in ("off", "auto", "on"):
        raise ValueError(f"unknown comm.hierarchy setting {mode!r}")
    if mode == "off":
        return None
    k = hierarchy_factor(cfg, mesh)
    if k is None:
        reason = ("the data axis has no intra/inter-host factorization "
                  "(single host, trivial axis, or interleaved device "
                  "order) and no comm.intra_axis_size override")
        if mode == "on":
            raise ValueError(f"comm.hierarchy=on is unsupported here: "
                             f"{reason}")
        log.info("comm.hierarchy=auto resolved flat: %s", reason)
    return k


def autotune_mode(cfg) -> str:
    """``comm.autotune`` validated — "off" or "startup". Whether the
    startup pass actually runs is the Trainer's call (it needs the
    telemetry.comm_timing probe; see train/loop.py)."""
    mode = getattr(cfg.comm, "autotune", "off")
    if mode not in ("off", "startup"):
        raise ValueError(f"unknown comm.autotune setting {mode!r}; "
                         "supported: off, startup")
    return mode


@dataclass(frozen=True)
class OverlapPlan:
    """Resolved overlap configuration for one (cfg, mesh).

    ``compress`` names the exchange payload dtype ("bf16"/"fp16") or None
    — carried on the plan because the gather leg (make_bucketed_gather)
    and the exchange must agree, and both already receive the plan.

    ``hierarchy`` is the intra-tier group size k of the two-tier data-axis
    exchange (module docstring) or None (flat). ``autotune`` mirrors
    ``comm.autotune``; ``tuned`` marks a plan REWRITTEN by the startup
    autotune pass (telemetry/planner.tune_comm_plan) — the comm_overlap
    row carries both so a tuned run is distinguishable from a hand-set
    one."""

    bucket_bytes: int
    compress: Optional[str] = None
    hierarchy: Optional[int] = None
    autotune: str = "off"
    tuned: bool = False


class OverlapStats:
    """Thread-safe record of the most recent bucket plan — what the
    ``{"event": "comm_overlap"}`` metrics row (train/hooks.CommOverlapHook)
    and bench.py's overlap row export. Written when the bucketed grad fn
    TRACES (once per compiled step, not per step)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._plan: Optional[dict] = None

    def record(self, bucket_bytes: int, bucket_sizes: Sequence[int],
               bucket_leaves: Sequence[int], total_bytes: int,
               n_leaves: int, compress: Optional[str] = None,
               wire_bytes: Optional[Sequence[int]] = None,
               declared: Optional[Sequence[Sequence[str]]] = None,
               reduce_axes: Optional[Sequence[str]] = None,
               accum_steps: int = 1,
               hierarchy: Optional[int] = None,
               autotune: str = "off", tuned: bool = False,
               inter_wire: Optional[Sequence[int]] = None,
               op_wire: Optional[Sequence[Sequence[int]]] = None) -> None:
        with self._lock:
            self._plan = {
                "buckets": len(bucket_sizes),
                "bucket_cap_bytes": int(bucket_bytes),
                "bucket_bytes": [int(b) for b in bucket_sizes],
                "bucket_leaves": [int(n) for n in bucket_leaves],
                "grad_bytes": int(total_bytes),
                "leaves": int(n_leaves),
                # layout-aware exchange: per-bucket reduce-axis set (one
                # set per bucket by construction — the grouped planner)
                # and the accumulation factor. Under accumulation the
                # plan fires ONCE per optimizer step, so wire_bytes below
                # is already the per-step number: 1/accum of what a
                # per-microbatch exchange would move.
                "bucket_reduce_axes": ["+".join(a) for a in reduce_axes]
                if reduce_axes is not None
                else ["+".join(BATCH_AXES)] * len(bucket_sizes),
                "accum_steps": int(accum_steps),
                # compressed-exchange payload accounting (comm.compress):
                # the SAME bucket plan, narrower wire format — what the
                # comm_compress metrics row and bench's precision row read
                "compress": compress or "off",
                "bucket_wire_bytes": [int(b) for b in wire_bytes]
                if wire_bytes is not None
                else [int(b) for b in bucket_sizes],
                "wire_bytes": int(sum(wire_bytes)) if wire_bytes is not None
                else int(total_bytes),
                # hierarchical exchange (comm.hierarchy): the resolved
                # intra-tier size k (0 = flat), whether the autotune pass
                # chose this plan, and the per-bucket bytes crossing the
                # SLOW inter-host tier — the 1/k acceptance number (flat:
                # the full wire payload crosses it)
                "hierarchy": int(hierarchy) if hierarchy else 0,
                "autotune": autotune or "off",
                "tuned": bool(tuned),
                "bucket_inter_wire_bytes": [int(b) for b in inter_wire]
                if inter_wire is not None
                else ([int(b) for b in wire_bytes] if wire_bytes is not None
                      else [int(b) for b in bucket_sizes]),
                # per-bucket per-OP wire bytes, aligned 1:1 with the
                # declared collective sequence — the planner/comm-report
                # match staged (RS→psum→AG) plans op-by-op with these
                "bucket_op_wire_bytes": [[int(x) for x in b]
                                         for b in op_wire]
                if op_wire is not None else None,
                # per-bucket declared collective sequences (bucket order =
                # issue order): what analysis/collectives.py cross-checks
                # the traced jaxpr schedule against
                "declared_collectives": [list(b) for b in declared]
                if declared is not None else None,
            }

    def reset(self) -> None:
        with self._lock:
            self._plan = None

    def snapshot(self) -> Optional[dict]:
        with self._lock:
            return dict(self._plan) if self._plan is not None else None


#: process-global plan record (one overlap step per training process)
overlap_stats = OverlapStats()


def overlap_unsupported_reason(cfg, mesh: Mesh) -> Optional[str]:
    """None when the bucketed exchange applies to this (cfg, mesh); else a
    one-line reason (``comm.overlap=on`` raises it, ``auto`` logs it)."""
    from .mesh import batch_shard_count
    n = batch_shard_count(mesh)
    if n <= 1:
        return "a single batch shard has no gradient exchange to bucket"
    accum = max(1, cfg.train.grad_accum_steps)
    if cfg.train.batch_size % (n * accum):
        per = f"{n} batch shards" if accum == 1 else \
            (f"{n} batch shards × {accum} accumulation microbatches")
        return (f"train.batch_size={cfg.train.batch_size} does not divide "
                f"over {per} — the shard_map'd exchange needs equal "
                "per-shard (micro)batches")
    if mesh.shape.get("seq", 1) > 1:
        return ("mesh axis 'seq' > 1 runs ring attention's own shard_map "
                "inside the blocks — the exchange body cannot contain it "
                "(jax 0.4.37 mis-transposes nested shard_map over auto "
                "axes); sequence parallelism stays on the XLA-propagation "
                "exchange")
    if mesh.shape.get("expert", 1) > 1 and mesh.shape.get("pipeline", 1) <= 1:
        return ("mesh axis 'expert' > 1 without a pipeline axis routes "
                "tokens through SwitchMlp's own (data,fsdp,expert) "
                "shard_map — only the pipelined MoE form (dp_pp_ep, "
                "models/pipeline._moe_mlp) runs inline in the exchange "
                "body")
    if mesh.shape.get("tensor", 1) > 1 and mesh.shape.get("pipeline", 1) > 1:
        return ("tensor × pipeline is not wired into the exchange: "
                "'tensor' rides GSPMD propagation as an AUTO axis, which "
                "the manually-mapped pipeline body cannot contain")
    if cfg.model.name == "resnet" and cfg.model.norm == "batch" \
            and not cfg.model.cross_replica_bn:
        return ("per-replica BN (cross_replica_bn=false) is emulated with "
                "grouped moments aligned to the GLOBAL batch layout; under "
                "shard_map the groups would be local — enable "
                "cross_replica_bn or use norm='group'/'frozen'")
    return None


def resolve_overlap(cfg, mesh: Mesh) -> Optional[OverlapPlan]:
    """``comm.overlap`` → an :class:`OverlapPlan` or None (off).

    ``auto`` = on iff the run has peers (jax.process_count() > 1 — the
    multi-host DCN path where the exchange tail is worth hiding) and the
    envelope supports it; ``on`` forces and raises the unsupported reason
    instead of silently training a different program than requested."""
    from .mesh import batch_shard_count
    mode = cfg.comm.overlap
    if mode not in ("auto", "on", "off"):
        raise ValueError(f"unknown comm.overlap setting {mode!r}")
    if mode == "off":
        return None
    reason = overlap_unsupported_reason(cfg, mesh)
    if mode == "on":
        if reason is not None:
            if batch_shard_count(mesh) <= 1:
                # a single-shard mesh has no exchange to bucket — and it
                # is exactly what checkpoint CONSUMERS (the standalone
                # evaluator, a 1-device serving replica) see when they
                # build a Trainer from a training config that forced the
                # knob. A train-step-only option must not crash processes
                # that never run a train step: resolve off, loudly.
                log.warning("comm.overlap=on resolved OFF: %s", reason)
                return None
            raise ValueError(f"comm.overlap=on is unsupported here: "
                             f"{reason}")
    else:
        if reason is not None or jax.process_count() <= 1:
            return None
    if cfg.comm.bucket_mb <= 0:
        raise ValueError(
            f"comm.bucket_mb must be > 0, got {cfg.comm.bucket_mb}")
    return OverlapPlan(bucket_bytes=int(cfg.comm.bucket_mb * 2 ** 20),
                       compress=compress_dtype(cfg),
                       hierarchy=resolve_hierarchy(cfg, mesh),
                       autotune=autotune_mode(cfg))


def plan_buckets(leaf_bytes: Sequence[int],
                 bucket_bytes: int) -> List[List[int]]:
    """Group leaf indices (greedy, REVERSE order) into buckets of at most
    ``bucket_bytes`` each. Reverse order approximates gradient
    availability during backprop — the output-side parameters' grads
    finish first, so their bucket's collective can issue while earlier
    layers are still differentiating (the DDP bucketing order). A leaf
    larger than the cap gets its own bucket (never split)."""
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i in reversed(range(len(leaf_bytes))):
        nb = leaf_bytes[i]
        if cur and cur_bytes + nb > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nb
    if cur:
        buckets.append(cur)
    return buckets


def plan_buckets_grouped(leaf_bytes: Sequence[int],
                         reduce_axes: Sequence[tuple],
                         bucket_bytes: int):
    """Greedy reverse-order bucketing, one open bucket PER reduce-axis
    set: a bucket's replicated leaves ride a single tuple-psum over the
    bucket's axes, so mixing sets in one bucket is ill-formed (the MoE
    expert leaves — no ``expert`` in their reduce set — must not share a
    tuple-psum with the router's ``…+expert`` set). Returns
    ``[(axes, [leaf indices]), …]`` in ISSUE order: buckets sorted by the
    reversed position of their first leaf, approximating backprop
    availability exactly like :func:`plan_buckets` — to which this
    degenerates (one group, same buckets, same order) on the batch-only
    meshes, keeping their plans and artifacts unchanged."""
    open_buckets: dict = {}
    done: List[tuple] = []  # (first_leaf_reversed_pos, axes, [indices])
    n = len(leaf_bytes)
    for pos, i in enumerate(reversed(range(n))):
        axes = tuple(reduce_axes[i])
        cur = open_buckets.get(axes)
        if cur is not None and cur[2] + leaf_bytes[i] > bucket_bytes:
            done.append((cur[0], axes, cur[1]))
            cur = None
        if cur is None:
            cur = [pos, [], 0]
            open_buckets[axes] = cur
        cur[1].append(i)
        cur[2] += leaf_bytes[i]
    for axes, cur in open_buckets.items():
        done.append((cur[0], axes, cur[1]))
    done.sort(key=lambda t: t[0])
    return [(axes, idxs) for _, axes, idxs in done]


def _fsdp_dim(spec: P) -> Optional[int]:
    """The dimension a PartitionSpec shards over ``fsdp``, or None."""
    return _axis_dim(spec, "fsdp")


def _axis_dim(spec: P, axis: str) -> Optional[int]:
    """The dimension a PartitionSpec shards over ``axis``, or None."""
    for d, names in enumerate(spec):
        if names is None:
            continue
        names = names if isinstance(names, tuple) else (names,)
        if axis in names:
            return d
    return None


def _param_specs(params: Any, mesh: Mesh):
    """Per-leaf PartitionSpecs from the SAME rule the training state uses
    (parallel/sharding.param_sharding_rule via tree_param_shardings), so
    the shard_map in_specs match how jit actually lays the params out —
    a drifted spec would force a per-step reshard."""
    from .sharding import tree_param_shardings
    shardings = tree_param_shardings(params, mesh)
    return jax.tree_util.tree_map(lambda s: s.spec, shardings,
                                  is_leaf=lambda x: hasattr(x, "spec"))


def _resolve_hier(hierarchy, data_size, reduce_axes):
    """(k_intra, k_inter) when the hierarchical staging applies to this
    bucket — the bucket reduces over ``data`` and the factorization is
    non-trivial — else None (flat). One resolution point shared by the
    declared plan and the exchange so the two cannot disagree."""
    if not hierarchy or "data" not in reduce_axes:
        return None
    k, dsize = int(hierarchy), int(data_size)
    if dsize <= 1 or k <= 1 or k >= dsize or dsize % k:
        return None
    return k, dsize // k


def _bucket_plan_ops(specs, out_specs=None, reduce_axes=BATCH_AXES,
                     hierarchy=None, data_size=0, leaf_elems=None,
                     wire_itemsize=4, fsdp_size=1) -> List[dict]:
    """One bucket's collective-issue plan, op by op — the single source
    both :func:`declared_bucket_collectives` (signature strings for the
    hangcheck) and make_bucketed_grad's wire-byte accounting read, so the
    declared schedule and the byte ledger cannot drift apart. Each op:

      ``sig``   — ``"<kind>@<axis>[+<axis>…]"``, with a ``[k]`` suffix on
                  grouped (two-tier) collectives naming the GROUP size —
                  analysis/collectives.py tags traced ``axis_index_groups``
                  ops the same way;
      ``wire_bytes`` — that op's input payload in wire dtype bytes
                  (0 when ``leaf_elems`` is not given);
      ``inter`` — True when the payload crosses the slow data tier (a
                  flat data psum/scatter moves the FULL payload across
                  hosts; the staged plan's inter leg moves 1/k).

    The op order is the issue order ``_exchange_bucket`` traces: the
    replicated block first (tuple-psum, or its staged RS→psum→AG
    restaging), then the per-leaf fsdp/zero1 ops, then the staged block
    for fsdp-scattered remainders."""
    if out_specs is None:
        out_specs = specs
    reduce_axes = tuple(reduce_axes)
    hier = _resolve_hier(hierarchy, data_size, reduce_axes)
    elems = list(leaf_elems) if leaf_elems is not None else [0] * len(specs)
    ops: List[dict] = []

    def add(sig, n_elems, inter=False):
        ops.append({"sig": sig, "wire_bytes": int(n_elems) * wire_itemsize,
                    "inter": inter})

    def staged(total_elems, rest):
        # the two-tier restaging of ``psum@data[+rest]``: RS over the k
        # intra peers (payload padded to a multiple of k), psum of the
        # 1/k shard across hosts (+ any non-data reduce axes, flat), AG
        # the reduced shard back intra-host
        k, k_inter = hier
        padded = total_elems + (-total_elems) % k
        shard = padded // k
        add(f"psum_scatter@data[{k}]", padded)
        add(f"psum@data[{k_inter}]", shard, inter=True)
        if rest:
            add("psum@" + "+".join(rest), shard)
        add(f"all_gather@data[{k}]", shard)

    z1_dims = [_axis_dim(o, "data") for o in out_specs]
    rep_idx = [i for i, s in enumerate(specs)
               if _fsdp_dim(s) is None and z1_dims[i] is None]
    if rep_idx:
        rep_elems = sum(elems[i] for i in rep_idx)
        if hier is not None:
            staged(rep_elems, tuple(a for a in reduce_axes if a != "data"))
        else:
            add("psum@" + "+".join(reduce_axes), rep_elems,
                inter="data" in reduce_axes)
    rem_axes = tuple(a for a in reduce_axes if a != "fsdp")
    staged_elems = 0
    staged_any = False
    for i, spec in enumerate(specs):
        d = _fsdp_dim(spec)
        dz = z1_dims[i]
        if d is None and dz is None:
            continue
        e = elems[i]
        if d is not None:
            add("psum_scatter@fsdp", e)
            e = e // max(1, int(fsdp_size))
        if dz is not None:
            # zero1 leaves stay on the flat data scatter: they already
            # move only 1/N and land in the shard layout — restaging
            # would re-gather what the optimizer wants scattered
            add("psum_scatter@data", e, inter=True)
            if d is None:
                add("psum@fsdp", e // max(1, int(data_size) or 1))
        elif hier is not None:
            staged_any = True
            staged_elems += e
        else:
            add("psum@" + "+".join(rem_axes), e, inter="data" in rem_axes)
    if hier is not None and staged_any:
        staged(staged_elems, tuple(a for a in rem_axes if a != "data"))
    return ops


def declared_bucket_collectives(specs, out_specs=None,
                                reduce_axes=BATCH_AXES,
                                hierarchy=None, data_size=0) -> List[str]:
    """The collective-issue sequence ``_exchange_bucket`` will emit for
    one bucket, as ``"<kind>@<axis>[+<axis>…]"`` strings — the DECLARED
    plan hangcheck's schedule extractor (analysis/collectives.py) checks
    the traced jaxpr against: replicated leaves ride ONE tuple-psum over
    the bucket's reduce-axis set (``reduce_axes`` — the batch axes plus
    any shaping axes the leaves replicate over, parallel layouts); each
    fsdp/ZeRO-sharded leaf reduce-scatters FIRST on its sharded axis,
    then psums (or scatters) the remainder. Under ``hierarchy`` (the
    intra-tier size k) the data-axis reductions restage as
    ``psum_scatter@data[k] → psum@data[D/k] → all_gather@data[k]``
    (module docstring). Must mirror ``_exchange_bucket`` exactly — a
    drift between the two IS the gate finding."""
    return [op["sig"] for op in _bucket_plan_ops(
        specs, out_specs, reduce_axes, hierarchy, data_size)]


def _hier_reduce(parts, k_intra, k_inter, rest_axes):
    """All-reduce ``parts`` (a list of same-dtype leaves, summed over the
    full ``data`` axis plus ``rest_axes``) via the two-tier staging:
    flatten + concat into one vector, pad to a multiple of k, then
    ``psum_scatter`` over the intra-tier groups (each of the k intra
    peers ends holding a distinct 1/k shard, already host-locally
    reduced), ``psum`` the shard across the inter-tier groups (the only
    inter-host traffic — 1/k of the payload; ``rest_axes`` fold in here
    too, on the shard), and ``all_gather`` the fully-reduced shards back
    over the intra tier. Returns leaves in input order/shape."""
    gi, ge = hierarchy_groups(k_intra, k_inter)
    shapes = [np.shape(p) for p in parts]
    sizes = [int(np.prod(s, dtype=np.int64)) for s in shapes]
    flat = [p.reshape(-1) for p in parts]
    vec = flat[0] if len(flat) == 1 else jnp.concatenate(flat)
    total = int(vec.shape[0])
    pad = (-total) % k_intra
    if pad:
        vec = jnp.pad(vec, (0, pad))
    shard = lax.psum_scatter(vec, "data", scatter_dimension=0, tiled=True,
                             axis_index_groups=gi)
    shard = lax.psum(shard, "data", axis_index_groups=ge)
    if rest_axes:
        shard = lax.psum(shard, tuple(rest_axes))
    full = lax.all_gather(shard, "data", axis=0, tiled=True,
                          axis_index_groups=gi)
    if pad:
        full = full[:total]
    out, off = [], 0
    for shape, n in zip(shapes, sizes):
        out.append(full[off:off + n].reshape(shape))
        off += n
    return out


def _exchange_bucket(leaves, specs, out_specs=None, compress=None,
                     reduce_axes=BATCH_AXES, hierarchy=None, data_size=0):
    """One bucket's gradient exchange: replicated leaves ride a single
    tuple-psum over the bucket's reduce-axis set (``reduce_axes`` — the
    batch axes, plus the shaping axes the leaves replicate over on
    pipeline/expert layouts; one collective issue); fsdp-sharded leaves
    psum over the remaining axes and psum_scatter over ``fsdp`` on their
    sharded dim (the ZeRO reduce-scatter), landing exactly in the leaf's
    training-state layout. Returns leaves in input order.

    ``out_specs`` (the ZeRO-1 path, arXiv:2004.13336) additionally names
    a ``data`` dim per leaf: those leaves reduce-SCATTER over ``data``
    instead of psumming, so each replica receives only its optimizer
    shard's gradient slice — 1/N the data-axis payload, landing exactly
    in the sharded weight-update layout.

    ``compress`` ("bf16"/"fp16", comm.compress): the payload is cast to
    the compressed dtype BEFORE its collectives and re-materialized f32
    after — the wire carries half the bytes; every f32 accumulation
    around the exchange (local grads, the optimizer) is untouched. The
    cast is per-leaf, so it commutes with bucketing: many-vs-one-bucket
    stays bit-identical under compression.

    ``hierarchy``/``data_size`` (comm.hierarchy, module docstring): when
    the bucket reduces over ``data`` and the k | data_size factorization
    is non-trivial, the flat data-axis psums restage through
    :func:`_hier_reduce` — replicated leaves as one staged block, fsdp-
    scattered remainders as a second staged block after their scatters.
    zero1 leaves keep their flat data scatter (they already move 1/N).
    The issue order mirrors :func:`_bucket_plan_ops` op for op."""
    if out_specs is None:
        out_specs = specs
    reduce_axes = tuple(reduce_axes)
    hier = _resolve_hier(hierarchy, data_size, reduce_axes)
    in_dt = leaves[0].dtype if leaves else jnp.float32
    if compress is not None:
        cdt = COMPRESS_DTYPES[compress]
        leaves = [l.astype(cdt) for l in leaves]
    z1_dims = [_axis_dim(o, "data") for o in out_specs]
    rep_idx = [i for i, s in enumerate(specs)
               if _fsdp_dim(s) is None and z1_dims[i] is None]
    out: List[Any] = [None] * len(leaves)
    if rep_idx:
        if hier is not None:
            reduced = _hier_reduce(
                [leaves[i] for i in rep_idx], hier[0], hier[1],
                tuple(a for a in reduce_axes if a != "data"))
        else:
            reduced = lax.psum(tuple(leaves[i] for i in rep_idx),
                               reduce_axes)
        for i, v in zip(rep_idx, reduced):
            out[i] = v
    rem_axes = tuple(a for a in reduce_axes if a != "fsdp")
    staged_idx: List[int] = []
    staged_vals: List[Any] = []
    for i, (leaf, spec) in enumerate(zip(leaves, specs)):
        d = _fsdp_dim(spec)
        dz = z1_dims[i]
        if d is None and dz is None:
            continue
        # reduce-scatter FIRST on every sharded axis: the remaining
        # collective then carries the scattered shard instead of the full
        # leaf — same sum (the axes reduce independently), N× less
        # payload on the axis this path exists to relieve
        if d is not None:
            leaf = lax.psum_scatter(leaf, "fsdp", scatter_dimension=d,
                                    tiled=True)
        if dz is not None:
            leaf = lax.psum_scatter(leaf, "data", scatter_dimension=dz,
                                    tiled=True)
            if d is None:
                leaf = lax.psum(leaf, "fsdp")
        elif hier is not None:
            staged_idx.append(i)
            staged_vals.append(leaf)
            continue
        else:
            leaf = lax.psum(leaf, rem_axes)
        out[i] = leaf
    if staged_idx:
        reduced = _hier_reduce(staged_vals, hier[0], hier[1],
                               tuple(a for a in rem_axes if a != "data"))
        for i, v in zip(staged_idx, reduced):
            out[i] = v
    if compress is not None:
        # f32 re-materialization: everything downstream of the exchange
        # (grad-norm metric, optimizer update) accumulates full-precision
        out = [v.astype(in_dt) for v in out]
    return out


def make_bucketed_grad(plan: OverlapPlan, mesh: Mesh, *,
                       weight_decay: float,
                       decay_in_loss: bool = True,
                       decay_all_params: bool = False,
                       label_smoothing: float = 0.0,
                       fused_xent: str = "off",
                       aux_loss_weight: float = 0.01,
                       zero1_min_size: Optional[int] = None,
                       precision=None,
                       grad_accum_steps: int = 1,
                       augment_fn: Optional[Callable] = None,
                       augment_seed: int = 0) -> Callable:
    """Drop-in replacement for ``jax.value_and_grad(loss_fn, has_aux=True)``
    in train/loop.make_train_step's single step:

        grad_fn(params, batch_stats, images, labels, apply_fn, step=0)
            -> ((loss, (ce, logits, new_batch_stats)), grads)

    with the gradient exchange bucketed as described in the module
    docstring. loss/ce come out as the GLOBAL batch mean (identical
    semantics to the jit path); logits reassemble into the global array;
    new_batch_stats is replicated by construction (the model's BN pmean's
    its moments over the batch axes — Trainer builds the model with
    ``axis_name=BATCH_AXES`` when overlap is active).

    ``zero1_min_size`` (non-None = ZeRO-1 active, the value is the
    replication floor in elements) switches the exchange to the ZeRO-1
    form (``parallel.sharding.zero1_grad_specs``): leaves the rule table
    assigns a ``data`` dim reduce-SCATTER over ``data`` and come out in
    the sharded weight-update layout — the optimizer then updates only
    each replica's shard, and the bucketed all-gather
    (``make_bucketed_gather``) brings the param updates back.

    ``precision`` (``parallel.precision.PrecisionPolicy``): the SAME
    policy input cast the jit path's loss_fn applies
    (train/loop.make_train_step) — the shard_map body must mirror it or
    the overlap step would compute a different program than the step it
    replaces.

    ``grad_accum_steps`` > 1 runs the microbatch scan INSIDE the body
    (module docstring): local f32 accumulation, the final microbatch
    peeled out of the scan, ONE bucketed exchange after it — per-step
    wire traffic is 1× the gradient bytes instead of accum×, and the
    exchange overlaps the last microbatch's backprop. ``augment_fn`` /
    ``augment_seed`` mirror make_train_step's per-microbatch prep with
    per-(shard, step, microbatch) keys — draws stay i.i.d. per example
    across shards, and both bucketing plans use the same keys so
    bucketing stays a pure scheduling change; ``step`` feeds the RNG."""
    from .mesh import batch_shard_count, manual_axes, shard_map_compat
    from ..train.loop import make_ce_fn
    from ..train.optimizers import loss_weight_decay
    n_shards = batch_shard_count(mesh)
    auto = overlap_auto_axes(mesh)
    manual = frozenset(a for a in mesh.axis_names if a not in auto)
    shaping = overlap_shaping_axes(mesh)
    loss_axes = BATCH_AXES + shaping
    r_scale = int(np.prod([mesh.shape[a] for a in shaping], dtype=np.int64)) \
        if shaping else 1
    n_total = n_shards * r_scale
    accum = max(1, grad_accum_steps)
    # the SAME mode/smoothing resolution the jit path uses, unreduced: the
    # caller's shard_map body is already per-shard, so the Pallas kernel
    # (fused_xent on/interpret) runs directly on the local (b/n, C) tile
    per_example_ce = make_ce_fn(label_smoothing, fused_xent,
                                per_example=True)
    batch_spec = P(BATCH_AXES)

    def grad_fn(params, batch_stats, images, labels, apply_fn, step=0):
        n_global = images.shape[0]
        pspecs = _param_specs(params, mesh)
        if auto:
            # shard_map specs may only name MANUAL axes — auto ("tensor")
            # references are stripped; the auto-axis sharding rides GSPMD
            # propagation through the body instead
            mspecs = jax.tree_util.tree_map(
                _strip_axes(auto), pspecs,
                is_leaf=lambda x: isinstance(x, P))
        else:
            mspecs = pspecs
        if zero1_min_size is not None:
            from .sharding import zero1_grad_specs
            gout_specs = zero1_grad_specs(params, mesh,
                                          min_size=zero1_min_size)
        else:
            gout_specs = mspecs
        bs_specs = jax.tree_util.tree_map(lambda _: P(), batch_stats)

        def body(params_l, bstats, images_l, labels_l):
            # reconstruct full params from fsdp shards (the explicit form
            # of the all-gather XLA propagation inserts on the jit path)
            def gather(leaf, spec):
                d = _fsdp_dim(spec)
                if d is None:
                    return leaf
                return lax.all_gather(leaf, "fsdp", axis=d, tiled=True)

            pfull = jax.tree_util.tree_map(gather, params_l, mspecs)

            def local_loss(pf, bs, images_mb, labels_mb, mb_global):
                variables = {"params": pf, "batch_stats": bs}
                imgs = images_mb if precision is None \
                    else precision.cast_compute(images_mb)
                logits, mutated = apply_fn(variables, imgs, train=True,
                                           mutable=["batch_stats",
                                                    "losses"])
                # local CONTRIBUTION to the global mean loss: sum of this
                # shard's per-example CE over the GLOBAL (micro)batch
                # size; replicated terms (decay, aux) are pre-divided by
                # the total manual peer count, and on shaped meshes the
                # CE part by the redundancy factor R, so the psum over
                # ``loss_axes`` reconstructs each exactly once — grads
                # then exchange as a plain sum, no post-scaling (the
                # module docstring's replicated-leaf calculus)
                ce_part = per_example_ce(logits, labels_mb).sum() \
                    / mb_global
                if r_scale != 1:
                    ce_part = ce_part / r_scale
                loss_part = ce_part
                if decay_in_loss:
                    loss_part = loss_part + loss_weight_decay(
                        pf, weight_decay, decay_all_params) / n_total
                aux = jax.tree_util.tree_leaves(mutated.get("losses", {}))
                if aux:
                    loss_part = loss_part + aux_loss_weight * sum(
                        jnp.sum(a) for a in aux) / n_total
                return loss_part, (ce_part, logits,
                                   mutated["batch_stats"])

            def micro_grad(bs, images_mb, labels_mb, mb_global):
                return jax.value_and_grad(
                    local_loss, has_aux=True)(pfull, bs, images_mb,
                                              labels_mb, mb_global)

            if accum <= 1:
                (loss_part, (ce_part, logits, new_bs)), grads = \
                    micro_grad(bstats, images_l, labels_l, n_global)
            else:
                # the in-envelope accumulation scan: local f32 grads
                # accumulate across the first accum-1 microbatches inside
                # lax.scan; the LAST microbatch runs peeled so its
                # backward is still in flight when the reverse-order
                # buckets start issuing — the exchange hides behind it
                local_b = images_l.shape[0]
                mb = local_b // accum
                mb_global = n_global // accum
                im = images_l.reshape((accum, mb) + images_l.shape[1:])
                lb = labels_l.reshape((accum, mb) + labels_l.shape[1:])

                def prep_mb(images_mb, midx):
                    if augment_fn is None:
                        return images_mb
                    # fold in this shard's batch coordinate: the body is
                    # per-shard, so one shared key would give example i
                    # on EVERY shard identical crop/flip draws — an N×
                    # cut in augmentation diversity vs the jit path's
                    # global-batch draws. Per-(shard, step, microbatch)
                    # keys keep draws i.i.d. per example; bucketing stays
                    # a pure scheduling change (same keys both plans).
                    shard = lax.axis_index("data") * mesh.shape["fsdp"] \
                        + lax.axis_index("fsdp")
                    rng = jax.random.fold_in(
                        jax.random.fold_in(
                            jax.random.fold_in(
                                jax.random.PRNGKey(augment_seed), step),
                            midx), shard)
                    return augment_fn(images_mb, rng)

                def scan_body(carry, xs):
                    grads_acc, bs = carry
                    images_mb, labels_mb, midx = xs
                    (lp, (cp, lg, nbs)), g = micro_grad(
                        bs, prep_mb(images_mb, midx), labels_mb,
                        mb_global)
                    grads_acc = jax.tree_util.tree_map(jnp.add,
                                                       grads_acc, g)
                    return (grads_acc, nbs), (lp, cp, lg)

                zero_grads = jax.tree_util.tree_map(
                    lambda p: jnp.zeros(np.shape(p), jnp.float32), pfull)
                (grads_acc, bs_carry), (lps, cps, lgs) = jax.lax.scan(
                    scan_body, (zero_grads, bstats),
                    (im[:-1], lb[:-1], jnp.arange(accum - 1)))
                (lp_last, (cp_last, lg_last, new_bs)), g_last = \
                    micro_grad(bs_carry, prep_mb(im[-1], accum - 1),
                               lb[-1], mb_global)
                grads = jax.tree_util.tree_map(
                    lambda a, b: (a + b) / accum, grads_acc, g_last)
                # metrics mirror the jit accumulation path: loss/ce are
                # the MEAN over microbatches of the per-microbatch global
                # values; logits reassemble in batch order
                loss_part = (jnp.sum(lps) + lp_last) / accum
                ce_part = (jnp.sum(cps) + cp_last) / accum
                logits = jnp.concatenate(
                    [lgs.reshape((-1,) + lgs.shape[2:]), lg_last], axis=0)

            # bucketed exchange, reverse parameter order, grouped by
            # reduce-axis set; buckets chained through
            # optimization_barrier so they issue in order and the
            # all-reduce combiner can't re-merge them (see module
            # docstring)
            leaves, treedef = jax.tree_util.tree_flatten(grads)
            spec_leaves = treedef.flatten_up_to(mspecs)
            z1_leaves = treedef.flatten_up_to(gout_specs)
            reduce_sets = [leaf_reduce_axes(s, shaping)
                           for s in spec_leaves]
            leaf_bytes = [int(np.prod(np.shape(g)) *
                              np.dtype(g.dtype).itemsize) for g in leaves]
            buckets = plan_buckets_grouped(leaf_bytes, reduce_sets,
                                           plan.bucket_bytes)
            bucket_sizes = [sum(leaf_bytes[i] for i in b)
                            for _, b in buckets]
            # the bucket PLAN is computed from the uncompressed leaf
            # bytes either way — compression narrows the wire format on
            # the same plan, so A/B rows compare like for like
            if plan.compress is not None:
                wire_itemsize = int(
                    np.dtype(COMPRESS_DTYPES[plan.compress]).itemsize)
                ratio = wire_itemsize / np.dtype(np.float32).itemsize
                wire_sizes = [int(b * ratio) for b in bucket_sizes]
            else:
                wire_itemsize = int(np.dtype(np.float32).itemsize)
                wire_sizes = bucket_sizes
            data_size = int(mesh.shape.get("data", 1))
            leaf_elems = [int(np.prod(np.shape(g), dtype=np.int64))
                          for g in leaves]
            plan_ops = [_bucket_plan_ops(
                [spec_leaves[i] for i in b], [z1_leaves[i] for i in b],
                reduce_axes=axes, hierarchy=plan.hierarchy,
                data_size=data_size,
                leaf_elems=[leaf_elems[i] for i in b],
                wire_itemsize=wire_itemsize,
                fsdp_size=int(mesh.shape.get("fsdp", 1)))
                for axes, b in buckets]
            # declared sigs go through the module-level wrapper, NOT the
            # plan_ops list above: declared_bucket_collectives is the
            # drift seam hangcheck's seeded-mismatch test patches, and a
            # plan that bypassed it could never be caught disagreeing
            # with the trace.
            declared = [declared_bucket_collectives(
                [spec_leaves[i] for i in b], [z1_leaves[i] for i in b],
                reduce_axes=axes, hierarchy=plan.hierarchy,
                data_size=data_size)
                for axes, b in buckets]
            overlap_stats.record(plan.bucket_bytes, bucket_sizes,
                                 [len(b) for _, b in buckets],
                                 sum(leaf_bytes), len(leaves),
                                 compress=plan.compress,
                                 wire_bytes=wire_sizes,
                                 declared=declared,
                                 reduce_axes=[axes for axes, _ in buckets],
                                 accum_steps=accum,
                                 hierarchy=plan.hierarchy,
                                 autotune=plan.autotune, tuned=plan.tuned,
                                 inter_wire=[sum(op["wire_bytes"]
                                                 for op in ops
                                                 if op["inter"])
                                             for ops in plan_ops],
                                 op_wire=[[op["wire_bytes"] for op in ops]
                                          for ops in plan_ops])
            out_leaves: List[Any] = [None] * len(leaves)
            anchor = None
            for bi, ((axes, b), nbytes, wbytes) in enumerate(
                    zip(buckets, bucket_sizes, wire_sizes)):
                # flight recorder: one (trace-time) span per planned
                # bucket — the plan is visible in trace.json without
                # instrumenting the compiled program itself. The bucket
                # index joins the span to the plan/comm_timing rows.
                with span("comm.bucket", bucket=bi, bytes=int(nbytes),
                          wire_bytes=int(wbytes), leaves=len(b)):
                    vals = [leaves[i] for i in b]
                    if anchor is not None:
                        vals, _ = lax.optimization_barrier((vals, anchor))
                    exchanged = _exchange_bucket(
                        vals, [spec_leaves[i] for i in b],
                        out_specs=[z1_leaves[i] for i in b],
                        compress=plan.compress, reduce_axes=axes,
                        hierarchy=plan.hierarchy, data_size=data_size)
                    anchor = exchanged[0]
                    for i, v in zip(b, exchanged):
                        out_leaves[i] = v
            if auto:
                # pin the exchanged grads' auto-axis (tensor) placement
                # so the optimizer update consumes them without a reshard
                out_leaves = [
                    _constrain_auto(v, s, mesh, auto)
                    for v, s in zip(out_leaves,
                                    treedef.flatten_up_to(pspecs))]
            grads_out = jax.tree_util.tree_unflatten(treedef, out_leaves)
            loss = lax.psum(loss_part, loss_axes)
            ce = lax.psum(ce_part, loss_axes)
            return loss, ce, logits, new_bs, grads_out

        def ctx_body(params_l, bstats, images_l, labels_l):
            # the manual-axes context (parallel/mesh.py) tells model code
            # traced inside the body that these axes are already mapped:
            # constraints drop them, the PipelinedEncoder runs inline
            with manual_axes(manual):
                return body(params_l, bstats, images_l, labels_l)

        sharded = shard_map_compat(
            ctx_body, mesh,
            in_specs=(mspecs, bs_specs, batch_spec, batch_spec),
            out_specs=(P(), P(), batch_spec, bs_specs, gout_specs),
            auto=auto)
        loss, ce, logits, new_bs, grads = sharded(params, batch_stats,
                                                  images, labels)
        return (loss, (ce, logits, new_bs)), grads

    # the accumulation contract the step builder validates
    # (train/loop.make_train_step): a grad fn built for a different
    # accum factor than the step's would silently skip accumulation
    grad_fn.grad_accum_steps = accum
    return grad_fn


def _strip_axes(drop: frozenset):
    """PartitionSpec transformer removing ``drop``-axis references (the
    shard_map-facing spec: manual specs may not name auto axes)."""
    from .mesh import filter_spec_axes

    def strip(spec: P) -> P:
        return filter_spec_axes(spec, lambda n: n not in drop)
    return strip


def _constrain_auto(leaf, spec: P, mesh: Mesh, auto: frozenset):
    """with_sharding_constraint on the AUTO axes of ``spec`` only — how
    the exchanged gradients keep their tensor placement inside the
    partially-manual body (constraints naming manual axes are illegal
    there)."""
    from .mesh import filter_spec_axes
    aspec = filter_spec_axes(spec, lambda n: n in auto)
    if not any(e is not None for e in aspec):
        return leaf
    from jax.sharding import NamedSharding
    return lax.with_sharding_constraint(leaf, NamedSharding(mesh, aspec))


def make_bucketed_gather(plan: OverlapPlan, mesh: Mesh,
                         zero1_specs: Any) -> Callable:
    """The ZeRO-1 return leg, bucketed: ``gather(updates) -> updates`` —
    all-gather each data-sharded param-UPDATE leaf back to its base param
    layout, one ``lax.all_gather`` issue per bucket (the SAME greedy
    reverse-order plan the gradient exchange uses, ``plan_buckets``),
    buckets chained through ``optimization_barrier`` so the scheduler can
    overlap each gather with the optimizer arithmetic still producing
    later buckets' updates. Leaves the rule table left replicated pass
    through untouched. The gather payload plan is recorded into
    ``parallel.sharding.zero1_stats`` (the ``zero1`` metrics row /
    bench's payload accounting).

    Under ``comm.compress`` (plan.compress) the gathered param-UPDATE
    payload is cast to the compressed dtype for the all-gather and
    re-materialized f32 after — the return leg halves like the exchange.
    Every replica applies the SAME bf16-rounded update (the rounding
    happens before the gather), so params stay replica-consistent; the
    f32 masters accumulate the update in f32 as always."""
    from .mesh import shard_map_compat
    from .sharding import zero1_stats

    def gather(updates):
        flat, treedef = jax.tree_util.tree_flatten(updates)
        specs = treedef.flatten_up_to(zero1_specs)
        z1_dims = [_axis_dim(s, "data") for s in specs]
        # only the GATHERED leaves ride the bucket chain — a replicated
        # pass-through leaf in a bucket would contribute no collective,
        # and anchoring the next barrier on it would let XLA re-merge
        # adjacent buckets' gathers. Bucket by FULL-leaf bytes: that is
        # the all-gather output payload.
        gidx = [i for i, d in enumerate(z1_dims) if d is not None]
        gbytes = [int(np.prod(np.shape(flat[i])) *
                      np.dtype(flat[i].dtype).itemsize) for i in gidx]
        buckets = [[gidx[j] for j in b]
                   for b in plan_buckets(gbytes, plan.bucket_bytes)]
        leaf_bytes = {i: nb for i, nb in zip(gidx, gbytes)}
        gathered_sizes = [sum(leaf_bytes[i] for i in b) for b in buckets]
        if plan.compress is not None:
            cratio = np.dtype(COMPRESS_DTYPES[plan.compress]).itemsize \
                / np.dtype(np.float32).itemsize
            gathered_wire = [int(b * cratio) for b in gathered_sizes]
        else:
            gathered_wire = gathered_sizes
        zero1_stats.record_gather(gathered_sizes,
                                  [len(b) for b in buckets],
                                  compress=plan.compress,
                                  wire_bytes=gathered_wire)
        base_specs = [P(*(None if n == "data" else n for n in s))
                      if d is not None else s
                      for s, d in zip(specs, z1_dims)]

        def body(*leaves):
            out: List[Any] = list(leaves)  # pass-throughs stay as-is
            anchor = None
            for bi, (b, nbytes, wbytes) in enumerate(
                    zip(buckets, gathered_sizes, gathered_wire)):
                with span("zero1.gather", bucket=bi, bytes=int(nbytes),
                          wire_bytes=int(wbytes)):
                    vals = [leaves[i] for i in b]
                    if anchor is not None:
                        vals, _ = lax.optimization_barrier((vals, anchor))
                    for i, v in zip(b, vals):
                        if plan.compress is not None:
                            v = v.astype(COMPRESS_DTYPES[plan.compress])
                        v = lax.all_gather(v, "data", axis=z1_dims[i],
                                           tiled=True)
                        if plan.compress is not None:
                            v = v.astype(leaves[i].dtype)
                        out[i] = v
                    anchor = out[b[0]]
            return tuple(out)

        sharded = shard_map_compat(body, mesh,
                                   in_specs=tuple(specs),
                                   out_specs=tuple(base_specs))
        return jax.tree_util.tree_unflatten(treedef, sharded(*flat))

    return gather


def probe_comm_plan(mesh: Mesh, reps: int = 3,
                    hier_k: Optional[int] = None) -> Optional[dict]:
    """Measure each planned exchange bucket's collective STANDALONE on the
    live mesh — the runtime leg of per-collective attribution
    (docs/observability.md; the static leg is the committed
    collective_schedules.json from analysis/collectives.py).

    For every bucket of the traced plan (``overlap_stats``) this compiles
    and times one ``lax.psum`` over the batch axes whose payload matches
    the bucket's WIRE bytes and dtype (``comm.compress`` narrows the
    probe exactly like the exchange). The time is the bucket's collective
    cost fully exposed — what the overlapped step HIDES when the
    scheduling works — so ``wire_bytes / probe_secs`` is the achieved
    standalone bandwidth and ``Σ probe_secs / step_secs`` is the overlap
    headroom the comm_timing row reports.

    SPMD contract: every process must call this at the same program
    point (Trainer.train does, once, at the first loop boundary after
    the plan traces) — the probe executes real collectives, so a process
    bailing mid-sequence while peers sit inside a psum would be a
    divergence hang (exactly the class docs/static_analysis.md's
    hangcheck exists to prevent). The protocol therefore front-loads all
    fallible LOCAL work (sizing + lowering + AOT compilation — no
    collective issued) into phase 1, then runs ONE tiny agreement psum:
    a process whose local prep failed still participates with a 0 flag,
    and a non-unanimous total makes EVERY process abandon together
    before any bucket collective launches. Phase 3 (payload allocation +
    the timed collectives — coordinated executions by nature, so they
    cannot precede the vote) then carries the same irreducible risk as
    any training-step collective: a mid-execution failure there means
    the mesh is already broken and the watchdog owns recovery. Results land in
    ``utils.metrics.comm_timing_stats``; returns the recorded snapshot,
    or None when no plan has traced / the probe was abandoned. Never
    raises (observability must not kill training).

    ``hier_k`` (the intra-tier size of a data-axis factorization —
    comm.hierarchy / the autotune pass): additionally times, per
    data-reducing axis set, one grouped psum over the INTRA tier (full
    payload = that set's largest bucket wire) and one over the INTER
    tier (1/k payload — the staged plan's cross-host leg). These land as
    ``tiers`` entries in the comm_timing row and fold into the bandwidth
    catalog as ``<axes>:intra`` / ``<axes>:inter`` rows — what
    tune_comm_plan ranks flat-vs-hierarchical with."""
    import math
    import time as _time

    from jax.sharding import NamedSharding

    from ..utils.metrics import comm_timing_stats
    from .mesh import shard_map_compat

    snap = overlap_stats.snapshot()
    if snap is None:
        return None
    compress = snap.get("compress", "off")
    wire_dtype = np.dtype(np.float32) if compress == "off" \
        else np.dtype(COMPRESS_DTYPES[compress])
    axes = [a for a in BATCH_AXES if mesh.shape.get(a, 1) > 1] \
        or list(BATCH_AXES)
    # layout-aware plans carry one reduce-axis set per bucket (the
    # grouped planner) — each bucket's probe psums over ITS set, so the
    # timed collective matches what the step actually issues
    bucket_axes = [tuple(s.split("+"))
                   for s in snap.get("bucket_reduce_axes",
                                     ["+".join(BATCH_AXES)]
                                     * len(snap["bucket_bytes"]))]
    replicated = NamedSharding(mesh, P())

    # -- phase 1: LOCAL prep (deterministic; no collective issued) -------
    programs = []
    tier_programs = []
    agree_c = None
    ok = 1.0
    try:
        def _agree(x):
            return lax.psum(x, tuple(mesh.axis_names))  # global, all axes

        agree_c = jax.jit(shard_map_compat(
            _agree, mesh, in_specs=P(), out_specs=P()))

        for bi, (nbytes, wbytes, leaves, baxes) in enumerate(zip(
                snap["bucket_bytes"], snap["bucket_wire_bytes"],
                snap["bucket_leaves"], bucket_axes)):
            elems = max(1, int(wbytes) // wire_dtype.itemsize)

            def _psum(x, _axes=baxes):
                return lax.psum(x, _axes)

            # AOT-compile BOTH programs now — jax.jit alone is lazy and
            # would push compilation past the vote into phase 3
            fn = jax.jit(shard_map_compat(
                _psum, mesh, in_specs=P(), out_specs=P())).lower(
                    jax.ShapeDtypeStruct((elems,), wire_dtype,
                                         sharding=replicated)).compile()
            fill = jax.jit(lambda e=elems: jnp.zeros((e,), wire_dtype),
                           out_shardings=replicated).lower().compile()
            programs.append((bi, int(nbytes), int(wbytes), int(leaves),
                             baxes, fn, fill))

        # tier legs (hierarchical autotune): per data-reducing axis set,
        # a grouped intra-tier psum at the set's max bucket wire and a
        # grouped inter-tier psum at 1/k of it. Grouped psums of a
        # replicated input are replica-consistent (equal group sizes),
        # so P()→P() is sound.
        dsize = int(mesh.shape.get("data", 1))
        if hier_k and 1 < int(hier_k) < dsize and dsize % int(hier_k) == 0:
            gi, ge = hierarchy_groups(int(hier_k), dsize // int(hier_k))
            sig_payload: dict = {}
            for wbytes, baxes in zip(snap["bucket_wire_bytes"],
                                     bucket_axes):
                if "data" in baxes:
                    s = "+".join(baxes)
                    sig_payload[s] = max(sig_payload.get(s, 0),
                                         int(wbytes))
            for sig in sorted(sig_payload):
                for tier, groups, tbytes in (
                        ("intra", gi, sig_payload[sig]),
                        ("inter", ge,
                         max(1, sig_payload[sig] // int(hier_k)))):
                    elems = max(1, int(tbytes) // wire_dtype.itemsize)

                    def _gpsum(x, _g=groups):
                        return lax.psum(x, "data", axis_index_groups=_g)

                    fn = jax.jit(shard_map_compat(
                        _gpsum, mesh, in_specs=P(),
                        out_specs=P())).lower(
                            jax.ShapeDtypeStruct((elems,), wire_dtype,
                                                 sharding=replicated)
                        ).compile()
                    fill = jax.jit(
                        lambda e=elems: jnp.zeros((e,), wire_dtype),
                        out_shardings=replicated).lower().compile()
                    tier_programs.append(
                        (sig, tier, elems * wire_dtype.itemsize, fn,
                         fill))
    except Exception:  # pragma: no cover - prep is best effort
        log.exception("comm-plan probe prep failed; voting to abandon")
        ok = 0.0

    # -- phase 2: agreement (first coordinated execution) ----------------
    if agree_c is None:  # can't even vote; peers' agreement psum will
        return None      # surface it (irreducible — see the docstring)
    try:
        flag = jax.make_array_from_callback(
            (), replicated, lambda idx: np.asarray(ok, np.float32))
        total_ok = float(np.asarray(jax.device_get(agree_c(flag))))
        n_devices = math.prod(mesh.shape.values())
        if total_ok < n_devices - 0.5:  # a peer's prep failed: all bail
            log.warning("comm-plan probe abandoned by agreement "
                        "(%.0f/%d devices ready)", total_ok, n_devices)
            return None
    except Exception:  # pragma: no cover - mesh already compromised
        log.exception("comm-plan probe agreement failed; comm_timing row "
                      "will be absent")
        return None

    # -- phase 3: the timed collectives (all processes committed) --------
    buckets = []
    tiers = []
    total = 0.0
    try:
        for bi, nbytes, wbytes, leaves, baxes, fn, fill in programs:
            x = fill()
            jax.block_until_ready(fn(x))  # compile + warm
            best = None
            for _ in range(max(1, reps)):
                t0 = _time.perf_counter()
                jax.block_until_ready(fn(x))
                dt = _time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            with span("comm.probe", bucket=bi, bytes=nbytes,
                      wire_bytes=wbytes):
                pass  # the probe span marks the measurement in the trace
            total += best
            buckets.append({
                "bucket": bi,
                "bytes": nbytes,
                "wire_bytes": wbytes,
                "leaves": leaves,
                "axes": "+".join(baxes),
                "probe_secs": round(best, 6),
                "wire_bytes_per_sec": round(wbytes / best, 1)
                if best > 0 else 0.0,
            })
        # tier legs last: same timing discipline, but their times do NOT
        # join comm_secs_total — they measure hypothetical staged legs,
        # not the plan's standalone exchange cost
        for sig, tier, tbytes, fn, fill in tier_programs:
            x = fill()
            jax.block_until_ready(fn(x))
            best = None
            for _ in range(max(1, reps)):
                t0 = _time.perf_counter()
                jax.block_until_ready(fn(x))
                dt = _time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            tiers.append({
                "axes": sig,
                "tier": tier,
                "wire_bytes": int(tbytes),
                "probe_secs": round(best, 6),
                "wire_bytes_per_sec": round(tbytes / best, 1)
                if best > 0 else 0.0,
            })
    except Exception:  # pragma: no cover - the mesh is already broken
        log.exception("comm-plan probe failed mid-measurement; "
                      "comm_timing row will be absent")
        return None
    comm_timing_stats.record(buckets, total, max(1, reps), axes, compress,
                             tiers=tiers)
    log.info("comm probe: %d bucket(s), %.2f ms standalone exchange "
             "(compress=%s)", len(buckets), total * 1e3, compress)
    result = comm_timing_stats.snapshot()
    # persist the measurement into the per-fabric bandwidth catalog
    # (telemetry/bandwidth.py) so main.py comm-report and the what-if
    # planner can cost layouts without a live mesh. Chief-only: the
    # catalog file is one per fabric, and N processes racing the same
    # atomic replace would keep only an arbitrary winner's fold
    if jax.process_index() == 0:
        from ..telemetry.bandwidth import update_from_probe
        update_from_probe(result)
    return result
