"""Ring / blockwise attention tests — the sequence-parallel substrate
(capability beyond the vision-only reference; SURVEY.md §5 notes the mesh
must be designed so a sequence axis can be added — here it is exercised on
the fake 8-device mesh)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.ops.attention import (
    attention, blockwise_attention, ring_attention_sharded)
from distributed_resnet_tensorflow_tpu.parallel import create_mesh
from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig


def _qkv(b=2, t=32, h=4, d=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    return mk(), mk(), mk()


def test_blockwise_matches_dense():
    q, k, v = _qkv()
    want = attention(q, k, v)
    got = blockwise_attention(q, k, v, block_size=8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_blockwise_causal_matches_dense():
    q, k, v = _qkv(seed=1)
    want = attention(q, k, v, causal=True)
    got = blockwise_attention(q, k, v, block_size=8, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.fixture(scope="module")
def seq_mesh():
    return create_mesh(MeshConfig(data=1, sequence=8))


def test_ring_attention_matches_dense(seq_mesh):
    q, k, v = _qkv(t=64, seed=2)
    want = attention(q, k, v)
    got = ring_attention_sharded(q, k, v, seq_mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_causal_matches_dense(seq_mesh):
    """Causal masking across device-chunk boundaries via global offsets."""
    q, k, v = _qkv(t=64, seed=3)
    want = attention(q, k, v, causal=True)
    got = ring_attention_sharded(q, k, v, seq_mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_jits_and_grads(seq_mesh):
    """The ring is differentiable + jittable (training path requirement)."""
    q, k, v = _qkv(t=16, seed=4)

    @jax.jit
    def loss(q, k, v):
        return ring_attention_sharded(q, k, v, seq_mesh).sum()

    g = jax.grad(loss)(q, k, v)
    assert np.isfinite(np.asarray(g)).all()

    def dense_loss(q, k, v):
        return attention(q, k, v).sum()

    gd = jax.grad(dense_loss)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gd),
                               rtol=1e-3, atol=1e-4)


def test_bfloat16_inputs_fp32_softmax():
    q, k, v = _qkv(seed=5)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    got = blockwise_attention(qb, kb, vb, block_size=8)
    assert got.dtype == jnp.bfloat16
    want = attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got, np.float32), np.asarray(want),
                               rtol=0.1, atol=0.1)


def test_blockwise_causal_suffix_queries():
    """tq != tk: dense tril offset (k = tk - tq) must be matched — queries
    are the last tq positions of the key timeline (decode convention)."""
    rng = np.random.RandomState(7)
    k = jnp.asarray(rng.randn(1, 48, 2, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 48, 2, 8).astype(np.float32))
    q = jnp.asarray(rng.randn(1, 16, 2, 8).astype(np.float32))
    want = attention(q, k, v, causal=True)
    got = blockwise_attention(q, k, v, block_size=16, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.fixture(scope="module")
def seq_mesh4():
    return create_mesh(MeshConfig(data=2, sequence=4))


@pytest.mark.slow  # re-tiered out of the 870s tier-1; runs in the full (unfiltered) suite
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.heavy
def test_ring_flash_matches_dense(seq_mesh4, causal):
    """The Pallas-inner ring (flash kernel per step + lse combine,
    interpret mode on CPU) == dense attention, fwd AND grads, causal and
    not, composed with data parallelism. The causal case exercises the
    per-device lax.cond skips and the diagonal-only causal kernel."""
    q, k, v = _qkv(t=64, seed=5)
    want = attention(q, k, v, causal=causal)
    got = ring_attention_sharded(q, k, v, seq_mesh4, causal=causal,
                                 batch_axes=("data",),
                                 kernel="flash_interpret")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)

    def loss(fn):
        return lambda q, k, v: (fn(q, k, v).astype(jnp.float32) ** 2).sum()

    g_ring = jax.jit(jax.grad(loss(
        lambda q, k, v: ring_attention_sharded(
            q, k, v, seq_mesh4, causal=causal, batch_axes=("data",),
            kernel="flash_interpret")), argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.grad(loss(
        lambda q, k, v: attention(q, k, v, causal=causal)),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


@pytest.mark.heavy
def test_ring_flash_matches_lax_ring(seq_mesh4):
    """Same ring topology, two inner blocks: the flash-kernel ring and the
    pure-lax ring agree (they share nothing but the math)."""
    q, k, v = _qkv(t=64, seed=6)
    a = ring_attention_sharded(q, k, v, seq_mesh4, causal=True,
                               kernel="flash_interpret")
    b = ring_attention_sharded(q, k, v, seq_mesh4, causal=True,
                               kernel="lax")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-4, atol=2e-4)
