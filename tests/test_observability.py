"""Performance-observability plane (ISSUE 14): cluster trace merge with
heartbeat-estimated clock offsets, per-collective runtime attribution
(comm-report's static↔runtime join), device-memory telemetry rows, the
watchdog's perf-anomaly sentinel, and the monitor's windowed steps/s +
per-host HBM watermark rollup. The live 2-process leg is
scripts/obs_smoke.sh; everything here is deterministic and fast."""
import json
import os
import time

import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.resilience.heartbeat import (
    BeatTransport)
from distributed_resnet_tensorflow_tpu.telemetry import comm_report, merge
from distributed_resnet_tensorflow_tpu.telemetry.memory import (
    MemoryWatermarks, sample_memory, watermarks)
from distributed_resnet_tensorflow_tpu.telemetry.tracer import recorder
from distributed_resnet_tensorflow_tpu.utils.config import (
    TelemetryConfig, WatchdogConfig)
from distributed_resnet_tensorflow_tpu.utils.metrics import (
    LatencyStats, comm_timing_stats)


class FakeWriter:
    def __init__(self):
        self.events = []

    def write_event(self, event, payload):
        self.events.append({"event": event, **payload})

    def flush(self):
        pass


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _write_stream(d, rows):
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "metrics.jsonl"), "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


# ---------------------------------------------------------------------------
# clock-offset estimation + trace merge
# ---------------------------------------------------------------------------

#: proc1's wall clock reads 2.0s AHEAD of the chief's in every fixture
_SKEW = 2.0


def _write_heartbeats(root):
    """Chief-observed heartbeat rows for a 2-host world where proc1's
    clock is ``_SKEW`` ahead: observed age = true latency − skew."""
    lat0 = [0.10, 0.25, 0.40, 0.15]
    lat1 = [0.12, 0.30, 0.20, 0.45]
    rows = []
    for a0, a1 in zip(lat0, lat1):
        rows.append({"event": "heartbeat", "time": 1000.0, "hosts": {
            "0": {"step": 5, "age_secs": a0, "host": "h0"},
            "1": {"step": 5, "age_secs": a1 - _SKEW, "host": "h1"}}})
    _write_stream(os.path.join(root, "train"), rows)


def test_clock_offset_estimated_from_heartbeat_ages(tmp_path):
    _write_heartbeats(str(tmp_path))
    offs = merge.estimate_clock_offsets(str(tmp_path))
    assert set(offs) == {"0", "1"}
    # offset = (process clock − chief clock); the estimator is bounded by
    # the min true publish→observe latencies on both sides (≤ 0.12+0.10)
    assert offs["1"]["offset_secs"] == pytest.approx(_SKEW, abs=0.25)
    assert offs["0"]["offset_secs"] == pytest.approx(0.0, abs=0.15)
    assert offs["1"]["bound_secs"] >= 0.0
    assert offs["1"]["observations"] == 4
    assert offs["1"]["host"] == "h1"


def _trace_doc(process_index, epoch_wall, events):
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"process_index": process_index,
                          "pid": 100 + process_index,
                          "epoch_wall_time": epoch_wall,
                          "span_schema_version": 6}}


def test_merge_aligns_lanes_within_tolerance(tmp_path):
    """Two hosts start 0.5s apart but proc1's clock is 2.0s ahead: with
    the heartbeat-estimated offset applied, the merged timeline puts
    proc1's t=0 span ~0.5s after proc0's, not 2.5s."""
    t_dir = tmp_path / "telemetry"
    t_dir.mkdir()
    span0 = {"name": "train.step", "ph": "X", "pid": 1, "tid": 1,
             "ts": 100.0, "dur": 50.0}
    span1 = {"name": "train.step", "ph": "X", "pid": 2, "tid": 1,
             "ts": 100.0, "dur": 50.0}
    (t_dir / "trace.json").write_text(
        json.dumps(_trace_doc(0, 1000.0, [span0])))
    (t_dir / "trace.proc1.json").write_text(
        json.dumps(_trace_doc(1, 1000.5 + _SKEW, [span1])))
    _write_heartbeats(str(tmp_path))

    paths = merge.find_traces(str(tmp_path))
    assert len(paths) == 2
    offs = merge.estimate_clock_offsets(str(tmp_path))
    doc = merge.merge_traces(paths, offs)
    xs = {e["pid"]: e for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert set(xs) == {0, 1}
    # proc0 anchors the merged origin; proc1's span lands ~0.5s later
    shift_secs = (xs[1]["ts"] - xs[0]["ts"]) / 1e6
    assert shift_secs == pytest.approx(0.5, abs=0.3)
    # per-host lanes: process_name/process_sort_index metadata per source
    names = {e["pid"]: e["args"]["name"] for e in doc["traceEvents"]
             if e.get("name") == "process_name"}
    assert names[0].startswith("proc0") and names[1].startswith("proc1")
    assert "(h1)" in names[1]
    # the bounded-skew record rides in the merged file's metadata
    assert doc["otherData"]["clock_offsets"]["1"]["offset_secs"] == \
        pytest.approx(_SKEW, abs=0.25)
    assert [s["process_index"] for s in doc["otherData"]["sources"]] == \
        [0, 1]


def test_trace_merge_cli_writes_valid_perfetto_json(tmp_path, capsys):
    t_dir = tmp_path / "telemetry"
    t_dir.mkdir()
    (t_dir / "trace.json").write_text(json.dumps(_trace_doc(
        0, 1000.0, [{"name": "train.step", "ph": "X", "pid": 1,
                     "tid": 1, "ts": 10.0, "dur": 5.0}])))
    (t_dir / "trace.proc1.json").write_text(json.dumps(_trace_doc(
        1, 1001.0, [{"name": "comm.bucket", "ph": "X", "pid": 1,
                     "tid": 1, "ts": 10.0, "dur": 5.0,
                     "args": {"bucket": 0}}])))
    rc = merge.main_trace_merge(["--root", str(tmp_path)])
    assert rc == 0
    out_path = tmp_path / "telemetry" / "trace.merged.json"
    doc = json.load(open(out_path))  # valid Perfetto/Chrome-trace JSON
    assert doc["otherData"]["merged"] is True
    assert {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"} \
        == {"train.step", "comm.bucket"}
    # re-merge is idempotent: the merged output is not a merge input
    assert str(out_path) not in merge.find_traces(str(tmp_path))
    assert merge.main_trace_merge(["--root", str(tmp_path)]) == 0
    assert "no heartbeat rows" in capsys.readouterr().out


def test_trace_merge_cli_fails_loudly_on_empty_root(tmp_path):
    assert merge.main_trace_merge(["--root", str(tmp_path)]) == 1


# ---------------------------------------------------------------------------
# comm-report: the static↔runtime join
# ---------------------------------------------------------------------------

def _timing_row(step_secs=0.01):
    return {
        "buckets": [
            {"bucket": 0, "bytes": 100, "wire_bytes": 100, "leaves": 5,
             "probe_secs": 0.002, "wire_bytes_per_sec": 50000.0},
            {"bucket": 1, "bytes": 50, "wire_bytes": 50, "leaves": 3,
             "probe_secs": 0.001, "wire_bytes_per_sec": 50000.0},
        ],
        "comm_secs_total": 0.003, "reps": 3, "axes": ["data", "fsdp"],
        "compress": "off", "step_secs": step_secs,
    }


def _signatures():
    return {"p@dp_fsdp/overlap": {"ops": [
        {"op": "psum", "axes": ["data", "fsdp"], "bytes": 100,
         "count": 1, "operands": 5},
        {"op": "psum", "axes": ["data", "fsdp"], "bytes": 50,
         "count": 1, "operands": 3},
        {"op": "psum", "axes": ["data", "fsdp"], "bytes": 4,
         "count": 2, "operands": 1},
    ]}}


def test_comm_report_joins_static_schedule_with_measured_buckets():
    report = comm_report.build_report(
        _timing_row(), signatures=_signatures(),
        step_secs_off=0.0085)
    assert report["schedule_key"] == "p@dp_fsdp/overlap"
    assert report["schedule_matched"] == 2
    for b in report["buckets"]:
        assert b["static"]["kind"] == "psum"
        assert b["static"]["axes"] == ["data", "fsdp"]
    assert report["buckets"][0]["pct_of_comm"] == pytest.approx(66.67,
                                                               abs=0.1)
    assert report["buckets"][1]["pct_of_comm"] == pytest.approx(33.33,
                                                               abs=0.1)
    assert report["bottleneck_bucket"] == 0
    assert report["comm_step_ratio"] == pytest.approx(0.3)
    # exposed = 10ms − 8.5ms = 1.5ms of the 3ms exchange → half hidden
    assert report["overlap_fraction"] == pytest.approx(0.5)
    text = comm_report.render(report)
    assert "psum@data,fsdp" in text and "bottleneck: bucket 0" in text


def test_comm_report_measured_only_without_matching_schedule():
    timing = _timing_row()
    timing["buckets"][0]["wire_bytes"] = 999  # no schedule op matches
    report = comm_report.build_report(timing, signatures=_signatures())
    assert report["schedule_key"] is None
    assert report["buckets"][0].get("static") is None
    assert "measured-only" in comm_report.render(report)


def test_comm_report_ambiguous_schedule_reports_candidates():
    sigs = _signatures()
    sigs["q@dp_fsdp/overlap"] = sigs["p@dp_fsdp/overlap"]
    key, candidates = comm_report.select_schedule_key(
        sigs, _timing_row()["buckets"])
    assert key is None and sorted(candidates) == \
        ["p@dp_fsdp/overlap", "q@dp_fsdp/overlap"]
    # an explicit key disambiguates; a bogus one fails loudly
    report = comm_report.build_report(_timing_row(), signatures=sigs,
                                      key="q@dp_fsdp/overlap")
    assert report["schedule_key"] == "q@dp_fsdp/overlap"
    with pytest.raises(KeyError):
        comm_report.build_report(_timing_row(), signatures=sigs,
                                 key="nope")


def test_comm_report_selects_compressed_schedule_variant():
    """comm.compress halves the measured wire bytes, which only the
    committed ``.../bf16+compress`` signature carries — the candidate
    filter must not exclude compressed-exchange variants."""
    sigs = {"p@dp_fsdp/bf16+compress": {"ops": [
        {"op": "psum", "axes": ["data", "fsdp"], "bytes": 50,
         "count": 1, "operands": 5}]}}
    timing = {"buckets": [
        {"bucket": 0, "bytes": 100, "wire_bytes": 50, "leaves": 5,
         "probe_secs": 0.001, "wire_bytes_per_sec": 50000.0}],
        "comm_secs_total": 0.001, "reps": 3, "axes": ["data"],
        "compress": "bf16"}
    report = comm_report.build_report(timing, signatures=sigs)
    assert report["schedule_key"] == "p@dp_fsdp/bf16+compress"
    assert report["schedule_matched"] == 1
    assert report["buckets"][0]["static"]["kind"] == "psum"


def test_comm_report_cli_end_to_end(tmp_path, capsys):
    _write_stream(str(tmp_path / "train"), [
        {"event": "comm_overlap", "time": 10.0, "step": 100,
         "buckets": 2, "bucket_cap_bytes": 262144, "grad_bytes": 150,
         "wire_bytes": 150, "leaves": 8},
        {"event": "comm_timing", "time": 11.0, "step": 100,
         **_timing_row()},
    ])
    sched = tmp_path / "schedules.json"
    sched.write_text(json.dumps({"signatures": _signatures()}))
    rc = comm_report.main_comm_report(
        ["--root", str(tmp_path), "--schedules", str(sched),
         "--step-secs-off", "0.0085"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "p@dp_fsdp/overlap" in out and "overlap fraction" in out


def test_comm_report_cli_without_rows_exits_nonzero(tmp_path, capsys):
    assert comm_report.main_comm_report(["--root", str(tmp_path)]) == 1
    assert "no comm_timing row" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# device-memory telemetry
# ---------------------------------------------------------------------------

def test_sample_memory_reports_devices_host_and_pools():
    watermarks.reset()
    row = sample_memory(process_index=0)
    assert row["process"] == 0
    assert row["devices"] and all(
        "live_bytes" in c for c in row["devices"].values())
    assert row["live_bytes_total"] >= 0
    assert row["live_peak_bytes_total"] >= row["live_bytes_total"]
    assert row["host_rss_bytes"] > 0  # /proc/self/status on linux
    assert "echo_cache_bytes" in row
    assert "staging_ring_slots" in row


def test_memory_watermark_is_monotone_under_shrinking_samples():
    wm = MemoryWatermarks()
    assert wm.update({"0": 100, "1": 50})["total"] == 150
    peaks = wm.update({"0": 30, "1": 20})
    assert peaks["total"] == 150 and peaks["by_device"]["0"] == 100
    wm.reset()
    assert wm.update({"0": 1})["total"] == 1


def test_memory_hook_exports_registered_rows():
    from distributed_resnet_tensorflow_tpu.train.hooks import MemoryHook
    w = FakeWriter()
    hook = MemoryHook(w, every_steps=1)
    hook(1, None, {})
    assert w.events and w.events[0]["event"] == "memory"
    assert "live_bytes_total" in w.events[0]
    assert w.events[0]["step"] == 1


# ---------------------------------------------------------------------------
# comm-timing hook (the probe's exporter)
# ---------------------------------------------------------------------------

def test_comm_timing_hook_exports_once_per_rate_change(monkeypatch):
    from distributed_resnet_tensorflow_tpu.train import hooks as hooks_mod
    clock = FakeClock(t=100.0)
    monkeypatch.setattr(hooks_mod.time, "monotonic", clock)
    comm_timing_stats.reset()
    try:
        w = FakeWriter()
        hook = hooks_mod.CommTimingHook(w, every_steps=1)
        hook(1, None, {})
        assert w.events == []  # the probe has not run yet
        comm_timing_stats.record(
            _timing_row()["buckets"], 0.003, 3, ["data"], "off")
        clock.t += 1.0
        hook(2, None, {})  # probe data + the first measured rate pair
        assert len(w.events) == 1
        assert w.events[0]["event"] == "comm_timing"
        assert w.events[0]["comm_secs_total"] == pytest.approx(0.003)
        assert w.events[0]["step_secs"] == pytest.approx(1.0)
        assert w.events[0]["comm_step_ratio"] == pytest.approx(0.003)
        clock.t += 1.0
        hook(3, None, {})  # same quantized rate → the change gate holds
        assert len(w.events) == 1
        clock.t += 2.0
        hook(4, None, {})  # the rate MOVED → re-export
        assert len(w.events) == 2
        assert w.events[1]["step_secs"] == pytest.approx(2.0)
    finally:
        comm_timing_stats.reset()


# ---------------------------------------------------------------------------
# perf-anomaly sentinel
# ---------------------------------------------------------------------------

class _NullTransport(BeatTransport):
    def publish(self, beat):
        pass

    def peers(self):
        return {}


class _StubPublisher:
    """step_times()/snapshot() stand-in: the only surface the sentinel
    reads."""

    def __init__(self):
        self.samples = []
        self.seq = 0

    def push(self, dt):
        self.samples.append(dt)
        self.seq += 1

    def step_times(self):
        return {"seq": self.seq, "samples": list(self.samples)}

    def snapshot(self):
        return {"step": 42, "progress": 42, "phase": "train",
                "last_progress_t": 0.0, "ewma_step_secs": None,
                "step_stride": 1}


def _make_sentinel(tmp_path, **overrides):
    from distributed_resnet_tensorflow_tpu.resilience.watchdog import (
        Watchdog)
    acfg = TelemetryConfig()
    acfg.anomaly_window = 8
    acfg.anomaly_min_samples = 4
    acfg.anomaly_cooldown_secs = 30.0
    for k, v in overrides.items():
        setattr(acfg, k, v)
    pub = _StubPublisher()
    writer = FakeWriter()
    clock = FakeClock(t=1000.0)
    wd = Watchdog(_NullTransport(), pub, 0, 1, WatchdogConfig(),
                  writer=writer, clock=clock,
                  exit_fn=lambda code: None, anomaly_cfg=acfg)
    return wd, pub, writer, clock


def _rows(writer):
    return [e for e in writer.events if e["event"] == "perf_anomaly"]


def test_perf_anomaly_fires_on_slow_step_and_dumps_trace(tmp_path):
    wd, pub, writer, clock = _make_sentinel(tmp_path)
    dump_dir = str(tmp_path / "telemetry")
    stub = FakeWriter()
    recorder.configure(dump_dir=dump_dir, writer=stub, process_index=0)
    try:
        with recorder.span("train.step"):
            pass
        for _ in range(6):
            pub.push(0.1)
        wd._check_perf_anomaly(clock.t)
        assert _rows(writer) == []  # healthy window: silent
        pub.push(0.5)  # slow-but-alive: 5× the rolling median
        wd._check_perf_anomaly(clock.t)
        rows = _rows(writer)
        assert len(rows) == 1
        assert rows[0]["step"] == 42
        assert rows[0]["step_secs"] == pytest.approx(0.5)
        assert rows[0]["median_secs"] == pytest.approx(0.1)
        assert rows[0]["step_secs"] > rows[0]["threshold_secs"]
        # evidence while the slowness is LIVE: the flight-recorder dump
        assert os.path.exists(os.path.join(dump_dir, "trace.json"))
        dumps = [e for e in stub.events if e["event"] == "trace_dump"]
        assert dumps and dumps[0]["reason"] == "perf_anomaly"
    finally:
        recorder._writer = None


def test_perf_anomaly_episode_fires_once_then_rearms(tmp_path):
    wd, pub, writer, clock = _make_sentinel(tmp_path)
    for _ in range(6):
        pub.push(0.1)
    pub.push(0.5)
    wd._check_perf_anomaly(clock.t)
    assert len(_rows(writer)) == 1
    wd._check_perf_anomaly(clock.t)  # same seq: no re-judgment
    pub.push(0.55)  # still slow, same episode: no second firing
    wd._check_perf_anomaly(clock.t)
    assert len(_rows(writer)) == 1
    pub.push(0.1)  # healthy sample ends the episode
    wd._check_perf_anomaly(clock.t)
    pub.push(0.6)  # new outlier, but inside the cooldown window
    wd._check_perf_anomaly(clock.t)
    assert len(_rows(writer)) == 1
    pub.push(0.1)
    wd._check_perf_anomaly(clock.t)
    clock.t += 31.0  # cooldown over → a new episode may fire
    pub.push(0.6)
    wd._check_perf_anomaly(clock.t)
    assert len(_rows(writer)) == 2


def test_perf_anomaly_catches_transient_slow_step_between_ticks(tmp_path):
    """Several steps land per watchdog tick on a fast run: a slow step
    MASKED by fast ones before the next tick must still fire (the
    sentinel judges the worst fresh sample, not just the newest)."""
    wd, pub, writer, clock = _make_sentinel(tmp_path)
    for _ in range(6):
        pub.push(0.1)
    wd._check_perf_anomaly(clock.t)  # consume the healthy baseline
    assert _rows(writer) == []
    pub.push(0.5)  # one transient slow step...
    pub.push(0.1)  # ...followed by fast ones inside the same tick
    pub.push(0.1)
    wd._check_perf_anomaly(clock.t)
    rows = _rows(writer)
    assert len(rows) == 1
    assert rows[0]["step_secs"] == pytest.approx(0.5)


def test_perf_anomaly_ratio_floor_tolerates_steady_jitter(tmp_path):
    """MAD ≈ 0 on an ultra-steady run: the min_ratio floor keeps a
    micro-hiccup (1.2×) quiet while a real 2× step still fires."""
    wd, pub, writer, clock = _make_sentinel(tmp_path, anomaly_min_ratio=1.5)
    for _ in range(6):
        pub.push(0.1)
    pub.push(0.12)  # 1.2× — within the floor
    wd._check_perf_anomaly(clock.t)
    assert _rows(writer) == []
    pub.push(0.2)  # 2×
    wd._check_perf_anomaly(clock.t)
    assert len(_rows(writer)) == 1


def test_perf_anomaly_disabled_cfg_is_inert(tmp_path):
    wd, pub, writer, clock = _make_sentinel(tmp_path,
                                            anomaly_detection=False)
    for _ in range(6):
        pub.push(0.1)
    pub.push(5.0)
    wd._check_perf_anomaly(clock.t)
    assert _rows(writer) == []


def test_heartbeat_step_samples_respect_interlude_guard():
    """The sentinel's sample window shares the EWMA's honesty guards: no
    compile-laden first delta, no post-interlude (eval/save) delta."""
    from distributed_resnet_tensorflow_tpu.resilience.heartbeat import (
        HeartbeatPublisher)
    clock = FakeClock(t=0.0)
    pub = HeartbeatPublisher(_NullTransport(), 0, clock=clock)
    pub.update(step=1)  # first delta: discarded (compile)
    clock.t += 0.1
    pub.update(step=2)
    assert pub.step_times() == {"seq": 1, "samples": [pytest.approx(0.1)]}
    pub.tick(phase="eval")  # interlude: the next delta spans the pause
    clock.t += 30.0
    pub.update(step=3)
    clock.t += 0.1
    pub.update(step=4)
    st = pub.step_times()
    assert st["seq"] == 2
    assert st["samples"] == [pytest.approx(0.1), pytest.approx(0.1)]


# ---------------------------------------------------------------------------
# monitor: windowed steps/s + per-host HBM watermark
# ---------------------------------------------------------------------------

def test_monitor_windowed_rate_absorbs_hiccup_row(tmp_path):
    from distributed_resnet_tensorflow_tpu.telemetry.monitor import (
        summarize_stream)
    now = 1000.0
    # steady 1 st/s for 20s, then a burst row 1s later (+10 steps): the
    # newest-pair rate would read 10 st/s; the window reads ~1.4
    _write_stream(str(tmp_path / "train"), [
        {"step": 10, "time": now - 21, "loss": 2.0},
        {"step": 20, "time": now - 11, "loss": 1.9},
        {"step": 30, "time": now - 1, "loss": 1.8},
        {"step": 40, "time": now, "loss": 1.7},
    ])
    s = summarize_stream(str(tmp_path / "train"), now=now)
    assert s["steps_per_sec"] == pytest.approx(30 / 21, abs=0.01)


def test_monitor_windowed_rate_survives_step_reset(tmp_path):
    from distributed_resnet_tensorflow_tpu.telemetry.monitor import (
        summarize_stream)
    now = 1000.0
    _write_stream(str(tmp_path / "train"), [
        {"step": 500, "time": now - 40, "loss": 2.0},
        {"step": 600, "time": now - 30, "loss": 1.9},
        {"step": 5, "time": now - 10, "loss": 3.0},   # restarted run
        {"step": 15, "time": now, "loss": 2.8},
    ])
    s = summarize_stream(str(tmp_path / "train"), now=now)
    # only the monotone suffix after the reset counts
    assert s["steps_per_sec"] == pytest.approx(1.0, abs=0.01)


def _memory_row(process, peak, limit=None, live=1000):
    devices = {"0": {"live_bytes": live, "live_peak_bytes": peak}}
    if limit is not None:
        devices["0"].update({"bytes_in_use": live,
                             "peak_bytes_in_use": peak,
                             "bytes_limit": limit})
    return {"event": "memory", "time": 999.0, "step": 50,
            "process": process, "devices": devices,
            "live_bytes_total": live, "live_peak_bytes_total": peak,
            "host_rss_bytes": 10 * 1024 * 1024}


def test_monitor_rolls_up_per_host_hbm_watermark_and_warns(tmp_path):
    from distributed_resnet_tensorflow_tpu.telemetry.monitor import (
        aggregate, render)
    now = 1000.0
    _write_stream(str(tmp_path / "train"), [
        {"step": 50, "time": now - 1, "loss": 1.0},
        _memory_row(0, peak=950, limit=1000)])       # 95% of limit
    _write_stream(str(tmp_path / "train-p1"), [
        _memory_row(1, peak=400, limit=1000)])       # 40%
    agg = aggregate(str(tmp_path), now=now, hbm_warn_frac=0.9)
    mem = agg["memory_by_host"]
    assert set(mem) == {"0", "1"}
    assert mem["0"]["device_peak_bytes"] == 950
    assert mem["0"]["device_peak_frac"] == pytest.approx(0.95)
    assert agg["hbm_warn_hosts"] == ["0"]
    text = render(agg)
    assert "hbm watermark" in text and "!! hbm above 90%" in text
    # under a laxer threshold nothing flags
    agg2 = aggregate(str(tmp_path), now=now, hbm_warn_frac=0.99)
    assert "hbm_warn_hosts" not in agg2
    assert "!! hbm" not in render(agg2)


def test_monitor_memory_rollup_keeps_colocated_serve_distinct(tmp_path):
    """A serving replica shares jax.process_index()==0 with the train
    chief under a shared log_root — its watermark must get its own
    entry, not shadow (or be shadowed by) the trainer's."""
    from distributed_resnet_tensorflow_tpu.telemetry.monitor import (
        aggregate)
    _write_stream(str(tmp_path / "train"), [_memory_row(0, peak=900)])
    _write_stream(str(tmp_path / "serve"), [_memory_row(0, peak=100)])
    agg = aggregate(str(tmp_path), now=1000.0)
    mem = agg["memory_by_host"]
    assert set(mem) == {"0", "0/serve"}
    assert mem["0"]["device_peak_bytes"] == 900
    assert mem["0/serve"]["device_peak_bytes"] == 100


def test_monitor_hbm_line_without_allocator_limit(tmp_path):
    """CPU/portable runs have no bytes_limit: the watermark line renders
    from the live-array peak with no percentage and no warning."""
    from distributed_resnet_tensorflow_tpu.telemetry.monitor import (
        aggregate, render)
    _write_stream(str(tmp_path / "train"), [_memory_row(0, peak=700)])
    agg = aggregate(str(tmp_path), now=1000.0)
    assert agg["memory_by_host"]["0"]["device_peak_bytes"] == 700
    assert "hbm_warn_hosts" not in agg
    assert "hbm watermark" in render(agg)


# ---------------------------------------------------------------------------
# LatencyStats bounded reservoir
# ---------------------------------------------------------------------------

def test_latency_stats_reservoir_is_bounded_and_count_is_true():
    ls = LatencyStats(max_samples_per_key=64)
    for i in range(1000):
        ls.record("bucket_8", i / 1000.0)
    assert len(ls._samples["bucket_8"]) == 64  # memory bound holds
    summary = ls.summary_ms()["bucket_8"]
    assert summary["count"] == 1000  # the true total survives the cap
    # the reservoir is recency-weighted: early (small) samples decay, so
    # the median sits in the later half of the run
    assert summary["p50_ms"] > 250.0


def test_latency_stats_under_cap_keeps_every_sample():
    ls = LatencyStats(max_samples_per_key=64)
    for i in range(10):
        ls.record("k", 0.001 * (i + 1))
    s = ls.summary_ms()["k"]
    assert s["count"] == 10 and s["p50_ms"] == pytest.approx(5.5, abs=0.6)


# ---------------------------------------------------------------------------
# CLI dispatch (main.py trace-merge / comm-report)
# ---------------------------------------------------------------------------

def test_main_dispatches_trace_merge_and_comm_report(tmp_path):
    from distributed_resnet_tensorflow_tpu import main as main_mod
    t_dir = tmp_path / "telemetry"
    t_dir.mkdir()
    (t_dir / "trace.json").write_text(json.dumps(_trace_doc(
        0, 1000.0, [{"name": "train.step", "ph": "X", "pid": 1,
                     "tid": 1, "ts": 10.0, "dur": 5.0}])))
    with pytest.raises(SystemExit) as e:
        main_mod.main(["trace-merge", "--root", str(tmp_path)])
    assert e.value.code == 0
    with pytest.raises(SystemExit) as e:
        main_mod.main(["comm-report", "--root", str(tmp_path)])
    assert e.value.code == 1  # no comm_timing rows in this root
