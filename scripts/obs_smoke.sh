#!/bin/bash
# Observability smoke (docs/observability.md, ISSUE 14) — the performance
# measurement plane end-to-end, no accelerator needed:
#
#   stage 1: 2-process training with one SLOW-BUT-ALIVE peer
#            (DRT_FAULT_SLOW_BATCH_SECS=pid:S@N — delay from batch N, so
#            the perf-anomaly sentinel sees a healthy baseline first).
#            Asserts: a {"event": "perf_anomaly"} row, the anomaly-
#            triggered flight-recorder dump, nonzero {"event": "memory"}
#            rows on BOTH hosts, `main.py trace-merge` producing one
#            valid Perfetto JSON with per-host lanes + clock-offset
#            metadata, and `main.py monitor` rolling up the per-host HBM
#            watermark + windowed steps/s.
#   stage 2: single-process dp_fsdp run with the bucketed exchange on →
#            the per-bucket collective probe fires and
#            `main.py comm-report` joins the measured timings with the
#            committed static schedule (collective_schedules.json).
#
#   scripts/obs_smoke.sh            # both stages (~2 min on a laptop)
#   OBS_SMOKE=1 scripts/chaos_smoke.sh --fast   # opt-in from the gate
set -euo pipefail
cd "$(dirname "$0")/.."

PY=${PYTHON:-python}
TROOT=$(mktemp -d)
trap 'rm -rf "$TROOT"' EXIT

# ---------------------------------------------------------------------------
echo "== obs_smoke stage 1: slow-peer run -> anomaly + memory + merge =="
PORT=$((20000 + RANDOM % 20000))
env JAX_PLATFORMS=cpu DRT_FAULT_SLOW_BATCH_SECS="1:0.6@30" \
  timeout -k 10 300 \
  "$PY" -m distributed_resnet_tensorflow_tpu.launch \
  --num_processes 2 --devices_per_process 1 --port "$PORT" -- \
  --preset smoke \
  --set model.name=logistic --set model.input_size=192 \
  --set model.num_classes=10 --set data.image_size=8 \
  --set train.batch_size=16 --set train.train_steps=45 \
  --set train.log_every_steps=10 --set train.summary_every_steps=5 \
  --set "log_root=$TROOT" \
  --set checkpoint.save_every_steps=0 --set checkpoint.save_every_secs=0 \
  --set resilience.watchdog.enabled=on \
  --set resilience.watchdog.interval_secs=0.2 \
  --set resilience.watchdog.peer_timeout_secs=60 \
  --set resilience.watchdog.min_step_timeout_secs=120 \
  --set resilience.watchdog.straggler_window_secs=3 \
  --set telemetry.anomaly_min_samples=12 \
  --set telemetry.anomaly_window=24 \
  --set telemetry.anomaly_cooldown_secs=5

"$PY" - "$TROOT" <<'PY'
import glob, json, sys
root = sys.argv[1]
rows = []
for path in glob.glob(root + "/**/metrics.jsonl", recursive=True):
    for line in open(path):
        try:
            rows.append(json.loads(line))
        except ValueError:
            pass
anoms = [r for r in rows if r.get("event") == "perf_anomaly"]
assert anoms, "no perf_anomaly row — the sentinel missed a 4x-slow step"
assert anoms[0]["step_secs"] > anoms[0]["threshold_secs"]
dumps = [r for r in rows if r.get("event") == "trace_dump"
         and r.get("reason") == "perf_anomaly"]
assert dumps, "anomaly fired but left no flight-recorder trace_dump row"
mem = [r for r in rows if r.get("event") == "memory"]
procs = {r.get("process") for r in mem}
assert len(mem) > 0 and procs >= {0, 1}, \
    f"memory rows missing a host: {len(mem)} rows from processes {procs}"
traces = glob.glob(root + "/telemetry/trace*.json")
assert traces, "no trace*.json dumped"
print(f"  ok: {len(anoms)} perf_anomaly row(s), {len(mem)} memory row(s) "
      f"from processes {sorted(procs)}, {len(traces)} trace dump(s)")
PY

env JAX_PLATFORMS=cpu "$PY" -m distributed_resnet_tensorflow_tpu.main \
  trace-merge --root "$TROOT"
"$PY" - "$TROOT" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1] + "/telemetry/trace.merged.json"))
other = doc["otherData"]
assert other["merged"] is True
lanes = {s["process_index"] for s in other["sources"]}
assert lanes == {0, 1}, f"expected lanes for both hosts, got {lanes}"
assert other["clock_offsets"], "no heartbeat-estimated clock offsets"
spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
assert spans, "merged trace has no spans"
names = [e for e in doc["traceEvents"] if e.get("name") == "process_name"]
assert len(names) == 2
print(f"  ok: merged trace has {len(spans)} span(s) across 2 host lanes, "
      f"offsets for {sorted(other['clock_offsets'])}")
PY

env JAX_PLATFORMS=cpu "$PY" -m distributed_resnet_tensorflow_tpu.main \
  monitor --root "$TROOT" --once --json > "$TROOT/agg.json"
"$PY" - "$TROOT/agg.json" <<'PY'
import json, sys
agg = json.load(open(sys.argv[1]))
assert "steps_per_sec" in agg, "monitor: no windowed steps/s"
mem = agg.get("memory_by_host") or {}
assert set(mem) >= {"0", "1"}, f"monitor: HBM rollup missing a host: {mem}"
print(f"  ok: monitor steps/s {agg['steps_per_sec']} + per-host HBM "
      f"watermark for hosts {sorted(mem)}")
PY

# ---------------------------------------------------------------------------
echo "== obs_smoke stage 2: dp_fsdp overlap run -> comm-report join =="
CROOT=$(mktemp -d)
trap 'rm -rf "$TROOT" "$CROOT"' EXIT
env JAX_PLATFORMS=cpu \
  XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" \
  timeout -k 10 300 \
  "$PY" -m distributed_resnet_tensorflow_tpu.main \
  --preset cifar10_resnet50 \
  --set mesh.data=4 --set mesh.fsdp=2 \
  --set comm.overlap=on --set data.dataset=synthetic \
  --set train.batch_size=16 --set train.train_steps=3 \
  --set train.log_every_steps=1 --set train.summary_every_steps=1 \
  --set "log_root=$CROOT" \
  --set checkpoint.save_every_steps=0 --set checkpoint.save_every_secs=0 \
  --set checkpoint.async_save=false

env JAX_PLATFORMS=cpu "$PY" -m distributed_resnet_tensorflow_tpu.main \
  comm-report --root "$CROOT" --key cifar10_resnet50@dp_fsdp/overlap \
  --json > "$CROOT/comm_report.json"
"$PY" - "$CROOT/comm_report.json" <<'PY'
import json, sys
r = json.load(open(sys.argv[1]))
assert r["schedule_key"] == "cifar10_resnet50@dp_fsdp/overlap"
assert r["schedule_matched"] >= 1, "static<->runtime join matched nothing"
assert r["buckets"] and all(b["wire_bytes_per_sec"] > 0
                            for b in r["buckets"])
assert r["buckets"][0]["static"]["kind"] == "psum"
print(f"  ok: comm-report joined {r['schedule_matched']} bucket(s) "
      f"against the committed schedule "
      f"({r['buckets'][0]['wire_bytes_per_sec'] / 1e9:.2f} GB/s standalone)")
PY

echo "obs_smoke: all stages passed"
