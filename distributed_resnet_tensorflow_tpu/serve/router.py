"""Fleet front door: health-routed dispatch, hedged retries, canary
rollout with auto-rollback, SLO-aware shedding.

Topology (``main.py route``)::

    loadgen ──▶ Router.submit ──▶ intake ──▶ drt-route-dispatch
                    │ (admission:                  │ least-outstanding
                    │  shed / degrade)             ▼
                    │                        attempt queue
                    │                     ┌────────┴────────┐
                    ▼                     ▼                 ▼
               Future (per       drt-route-worker ×K  (TcpReplicaClient
               request, first        per-attempt timeout; failure →
               winning attempt       health signal + bounded retry)
               resolves it)
                          drt-route-health: heartbeat ages, ping probes,
                          pressure, canary turn, route/shed rows

Three cooperating state machines, all **pure and clock-injected** so the
tier-1 tables drive them with a fake clock and zero sockets:

* :class:`ReplicaHealth` — per-replica ``warming → ready ⇄ degraded``,
  with ``suspect → dead`` on consecutive transport failures, ``dead`` on
  a stale heartbeat, ``draining``/``readmit`` under supervisor control.
  Only ``ready``/``degraded`` replicas take dispatch; when none qualify
  the router falls back to anything not dead/draining rather than
  refusing every request during a rough patch.
* :class:`CanaryController` — a newly committed checkpoint step is first
  pinned to ``ceil(canary_fraction × N)`` replicas (the rest re-pinned
  to the incumbent step). After the watch window, the canary arm must
  beat a p99 ratio and an accuracy-proxy (mean top-1 softmax) drop
  threshold against the control arm, else every canary is re-pinned to
  the old step and the step is remembered as bad — the serving analog of
  the verified-restore ladder (docs/resilience.md).
* Admission — estimated queue delay ``outstanding × EWMA service time /
  eligible replicas``; past ``degrade_queue_ms`` unpinned traffic is
  rewritten to the cheap variant (int8/bf16), past ``shed_queue_ms`` the
  request is refused with :class:`RequestShed` instead of queueing
  without bound.

The router holds NO jax state — numpy in, numpy out — so a wedged
replica can never wedge the front door, and the routing tables run in
tier-1 without devices.
"""
from __future__ import annotations

import json
import logging
import math
import os
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..telemetry.tracer import span
from ..utils.config import RouteConfig
from .wire import ReplicaError

log = logging.getLogger(__name__)

# health states (string-valued: they land in replica_health rows as-is)
WARMING = "warming"
READY = "ready"
DEGRADED = "degraded"
SUSPECT = "suspect"
DRAINING = "draining"
DEAD = "dead"

#: states that take regular dispatch
DISPATCHABLE = (READY, DEGRADED)
#: states excluded even from the nothing-else-left fallback
UNROUTABLE = (DEAD, DRAINING)


class RequestShed(RuntimeError):
    """Admission refused the request: estimated queue delay exceeded
    route.shed_queue_ms. Clients should back off, not retry hot."""


class RouteError(RuntimeError):
    """Every attempt failed or the request deadline passed."""


@dataclass
class Transition:
    """One health-state edge — becomes a replica_health row verbatim."""
    replica: int
    frm: str
    to: str
    reason: str
    beat_age_secs: Optional[float] = None
    failures: int = 0

    def row(self) -> dict:
        out = {"replica": self.replica, "from": self.frm, "to": self.to,
               "reason": self.reason, "failures": self.failures}
        if self.beat_age_secs is not None:
            out["beat_age_secs"] = round(self.beat_age_secs, 1)
        return out


class ReplicaHealth:
    """Health state machine for ONE replica. Pure: inputs are success /
    failure / beat-age / pressure observations, outputs are
    :class:`Transition` records (None when the state didn't move)."""

    def __init__(self, replica: int, suspect_after: int = 2,
                 dead_after: int = 5, beat_stale_secs: float = 15.0,
                 slo_p99_ms: float = 0.0):
        self.replica = replica
        self.suspect_after = max(1, suspect_after)
        self.dead_after = max(self.suspect_after, dead_after)
        self.beat_stale_secs = beat_stale_secs
        self.slo_p99_ms = slo_p99_ms
        self.state = WARMING
        self.failures = 0
        self.beat_age: Optional[float] = None

    def _move(self, to: str, reason: str) -> Optional[Transition]:
        if to == self.state:
            return None
        tr = Transition(self.replica, self.state, to, reason,
                        self.beat_age, self.failures)
        self.state = to
        return tr

    def on_success(self) -> Optional[Transition]:
        """A transport attempt (request or ping) came back."""
        was = self.state
        self.failures = 0
        if was == WARMING:
            return self._move(READY, "probe_ok")
        if was == SUSPECT:
            return self._move(READY, "recovered")
        return None

    def on_failure(self) -> Optional[Transition]:
        """A transport attempt failed (ReplicaError)."""
        if self.state in (DEAD, DRAINING):
            return None
        self.failures += 1
        if self.failures >= self.dead_after:
            return self._move(DEAD, "failures")
        if self.failures >= self.suspect_after and self.state != SUSPECT:
            return self._move(SUSPECT, "failures")
        return None

    def on_beat(self, age_secs: Optional[float]) -> Optional[Transition]:
        """Heartbeat-file age (None = no beat published yet). A warming
        replica is exempt — the supervisor bounds warm-up separately."""
        self.beat_age = age_secs
        if (age_secs is not None and age_secs > self.beat_stale_secs
                and self.state not in (DEAD, DRAINING, WARMING)):
            return self._move(DEAD, "beat_stale")
        return None

    def on_pressure(self, p99_ms: Optional[float]) -> Optional[Transition]:
        """Router-observed p99 for this replica vs. the SLO."""
        if self.slo_p99_ms <= 0 or p99_ms is None:
            return None
        if self.state == READY and p99_ms > self.slo_p99_ms:
            return self._move(DEGRADED, "slo_pressure")
        if self.state == DEGRADED and p99_ms < 0.8 * self.slo_p99_ms:
            return self._move(READY, "recovered")
        return None

    def drain(self) -> Optional[Transition]:
        return self._move(DRAINING, "drain")

    def readmit(self) -> Optional[Transition]:
        """Supervisor respawned the process: back to warming; the next
        successful probe promotes it to ready."""
        self.failures = 0
        self.beat_age = None
        return self._move(WARMING, "readmit")

    @property
    def dispatchable(self) -> bool:
        return self.state in DISPATCHABLE


#: the COMPLETE set of (from, to, reason) edges :class:`ReplicaHealth`
#: can emit — the declared side of the health state machine. The
#: protocol spec (serve/fleet.py, analysis/protocol/) models and
#: trace-checks against this table, so keep it in lockstep with the
#: transition methods above: an edge the code grows without a row here
#: shows up as an undeclared-edge finding on the next chaos smoke.
HEALTH_EDGES = (
    # on_success
    (WARMING, READY, "probe_ok"),
    (SUSPECT, READY, "recovered"),
    # on_failure threshold ladder (dead_after >= suspect_after, so a
    # warming replica can fall straight to dead when they are equal)
    (WARMING, SUSPECT, "failures"),
    (READY, SUSPECT, "failures"),
    (DEGRADED, SUSPECT, "failures"),
    (WARMING, DEAD, "failures"),
    (READY, DEAD, "failures"),
    (DEGRADED, DEAD, "failures"),
    (SUSPECT, DEAD, "failures"),
    # on_beat (warming/dead/draining exempt)
    (READY, DEAD, "beat_stale"),
    (DEGRADED, DEAD, "beat_stale"),
    (SUSPECT, DEAD, "beat_stale"),
    # on_pressure
    (READY, DEGRADED, "slo_pressure"),
    (DEGRADED, READY, "recovered"),
    # drain / readmit (supervisor-driven; _move drops self-loops)
    (WARMING, DRAINING, "drain"),
    (READY, DRAINING, "drain"),
    (DEGRADED, DRAINING, "drain"),
    (SUSPECT, DRAINING, "drain"),
    (DEAD, DRAINING, "drain"),
    (READY, WARMING, "readmit"),
    (DEGRADED, WARMING, "readmit"),
    (SUSPECT, WARMING, "readmit"),
    (DRAINING, WARMING, "readmit"),
    (DEAD, WARMING, "readmit"),
)


def pick_replica(health: Dict[int, ReplicaHealth],
                 outstanding: Dict[int, int],
                 exclude: Sequence[int] = ()) -> Optional[int]:
    """Least-outstanding-requests choice among dispatchable replicas,
    falling back to anything routable; ``exclude`` (replicas this request
    already tried) is a preference, not a veto — a retry with every
    replica tried still goes somewhere."""
    pool = [r for r, h in health.items() if h.dispatchable]
    if not pool:
        pool = [r for r, h in health.items() if h.state not in UNROUTABLE]
    if not pool:
        return None
    fresh = [r for r in pool if r not in exclude]
    return min(fresh or pool, key=lambda r: (outstanding.get(r, 0), r))


def percentile_ms(samples: Sequence[float], q: float = 99.0) -> Optional[float]:
    if not samples:
        return None
    data = sorted(samples)
    idx = max(0, math.ceil(q / 100.0 * len(data)) - 1)
    return data[idx]


def top1_confidence(logits_row: np.ndarray) -> float:
    """Accuracy proxy: top-1 softmax confidence of one logits row. A
    garbage checkpoint (wrong params, NaN-poisoned, stale stats) shows up
    as a confidence collapse long before labeled accuracy is measurable
    router-side."""
    row = np.asarray(logits_row, dtype=np.float64).reshape(-1)
    if row.size == 0 or not np.all(np.isfinite(row)):
        return 0.0
    row = row - row.max()
    ex = np.exp(row)
    return float(ex.max() / ex.sum())


# ---------------------------------------------------------------------------
# canary rollout
# ---------------------------------------------------------------------------

@dataclass
class _Canary:
    step: int
    from_step: int
    canary: Tuple[int, ...]
    all_ids: Tuple[int, ...]
    started: float
    confirmed: Set[int] = field(default_factory=set)
    c_lat: deque = field(default_factory=lambda: deque(maxlen=2048))
    c_conf: deque = field(default_factory=lambda: deque(maxlen=2048))
    b_lat: deque = field(default_factory=lambda: deque(maxlen=2048))
    b_conf: deque = field(default_factory=lambda: deque(maxlen=2048))


class CanaryController:
    """Decides which replica serves which checkpoint step. Pure: commits,
    completions and clock ticks in; (canary rows, pin actions) out. The
    caller executes pins by rewriting each replica's SWAP_CONTROL.json.

    Not thread-safe by itself — the Router serializes calls under its own
    lock (completions arrive from worker threads, ticks from the health
    thread)."""

    def __init__(self, cfg: RouteConfig, initial_step: int = -1):
        self.cfg = cfg
        self.fleet_step = initial_step  # step the non-canary fleet serves
        self.bad_steps: Set[int] = set()
        self.active: Optional[_Canary] = None

    def observe_commit(self, step: Optional[int], healthy: Sequence[int],
                       all_ids: Sequence[int],
                       now: float) -> Tuple[List[dict], List[Tuple[int, int]]]:
        """A newly committed checkpoint step appeared (or None). Starts a
        canary when it is newer than the fleet step and not known-bad."""
        if (step is None or self.active is not None
                or step <= self.fleet_step or step in self.bad_steps
                or not all_ids):
            return [], []
        ids = tuple(sorted(all_ids))
        if len(ids) <= 1:
            # nothing to compare against — promote directly, recorded as
            # such so the operator knows no canary protected this swap
            old = self.fleet_step
            self.fleet_step = step
            row = {"action": "promote", "step": step, "from_step": old,
                   "canary": list(ids), "rollback": False,
                   "reason": "single_replica"}
            return [row], [(r, step) for r in ids]
        k = max(1, math.ceil(self.cfg.canary_fraction * len(ids)))
        k = min(k, len(ids) - 1)  # always keep a control arm
        pool = [r for r in sorted(healthy) if r in ids] or list(ids)
        canary = tuple(sorted(pool[:k]))
        self.active = _Canary(step=step, from_step=self.fleet_step,
                              canary=canary, all_ids=ids, started=now)
        row = {"action": "start", "step": step,
               "from_step": self.fleet_step, "canary": list(canary),
               "rollback": False}
        pins = [(r, step if r in canary else self.fleet_step) for r in ids]
        return [row], pins

    @property
    def unconfirmed(self) -> List[int]:
        """Canary replicas that have not yet been seen serving the canary
        step — the health pass pings these even when healthy (a canary
        starved of regular traffic must not read as no_confirm)."""
        c = self.active
        if c is None:
            return []
        return sorted(set(c.canary) - c.confirmed)

    def observe_step(self, replica: int, step: int) -> None:
        """A replica was SEEN serving ``step`` (health-ping pong). Counts
        as swap confirmation only — latency/confidence samples for the
        verdict still come exclusively from real completions."""
        c = self.active
        if c is not None and replica in c.canary and step == c.step:
            c.confirmed.add(replica)

    def observe_completion(self, replica: int, step: int, latency_ms: float,
                           conf: float) -> None:
        c = self.active
        if c is None:
            return
        if replica in c.canary:
            if step == c.step:
                c.confirmed.add(replica)
                c.c_lat.append(latency_ms)
                c.c_conf.append(conf)
        elif step != c.step:  # control arm; a canary-step answer from a
            c.b_lat.append(latency_ms)  # non-canary replica would be the
            c.b_conf.append(conf)       # leak the smoke asserts against

    def tick(self, now: float) -> Tuple[List[dict], List[Tuple[int, int]]]:
        c = self.active
        if c is None:
            return [], []
        cfg = self.cfg
        elapsed = now - c.started
        confirmed = set(c.canary) <= c.confirmed
        if not confirmed:
            if elapsed >= cfg.canary_confirm_secs:
                return self._rollback("no_confirm")
            return [], []
        enough = (len(c.c_lat) >= cfg.canary_min_samples
                  and len(c.b_lat) >= cfg.canary_min_samples)
        if elapsed < cfg.canary_window_secs:
            return [], []
        if not enough:
            if elapsed >= cfg.canary_window_secs + cfg.canary_confirm_secs:
                # starved of traffic: every canary confirmed the step and
                # nothing regressed in what little we saw — promote
                return self._promote("promoted")
            return [], []
        p99c = percentile_ms(c.c_lat)
        p99b = percentile_ms(c.b_lat)
        if p99b and p99c and p99c > cfg.canary_p99_ratio * p99b:
            return self._rollback("p99_regression")
        conf_c = sum(c.c_conf) / len(c.c_conf) if c.c_conf else 0.0
        conf_b = sum(c.b_conf) / len(c.b_conf) if c.b_conf else 0.0
        if conf_b - conf_c > cfg.canary_conf_drop:
            return self._rollback("confidence_regression")
        return self._promote("promoted")

    def _stats(self, c: _Canary) -> dict:
        out = {"samples_canary": len(c.c_lat), "samples_base": len(c.b_lat)}
        p99c, p99b = percentile_ms(c.c_lat), percentile_ms(c.b_lat)
        if p99c is not None:
            out["p99_canary_ms"] = round(p99c, 2)
        if p99b is not None:
            out["p99_base_ms"] = round(p99b, 2)
        if c.c_conf:
            out["conf_canary"] = round(sum(c.c_conf) / len(c.c_conf), 4)
        if c.b_conf:
            out["conf_base"] = round(sum(c.b_conf) / len(c.b_conf), 4)
        return out

    def _rollback(self, reason: str) -> Tuple[List[dict],
                                              List[Tuple[int, int]]]:
        c = self.active
        self.active = None
        self.bad_steps.add(c.step)
        row = {"action": "rollback", "step": c.step,
               "from_step": c.from_step, "canary": list(c.canary),
               "rollback": True, "reason": reason, **self._stats(c)}
        log.warning("canary: ROLLBACK step %d → %d (%s)", c.step,
                    c.from_step, reason)
        return [row], [(r, c.from_step) for r in c.canary]

    def _promote(self, reason: str) -> Tuple[List[dict],
                                             List[Tuple[int, int]]]:
        c = self.active
        self.active = None
        self.fleet_step = c.step
        row = {"action": "promote", "step": c.step,
               "from_step": c.from_step, "canary": list(c.canary),
               "rollback": False, "reason": reason, **self._stats(c)}
        log.info("canary: promote step %d fleet-wide (%s)", c.step, reason)
        return [row], [(r, c.step) for r in c.all_ids]


# ---------------------------------------------------------------------------
# the router proper
# ---------------------------------------------------------------------------

@dataclass
class _Request:
    id: int
    image: np.ndarray
    variant: Optional[str]
    future: Future
    created: float
    deadline: float
    attempts: int = 0
    inflight: int = 0
    done: bool = False
    hedged: bool = False
    last_issue: float = 0.0
    tried: Set[int] = field(default_factory=set)


class Router:
    """Admission + dispatch over a set of replica clients.

    ``clients`` maps replica id → an object with ``request(image,
    variant, timeout_secs) → (logits_row, step)``, ``ping(timeout_secs)
    → dict`` and ``reset()`` — :class:`serve.wire.TcpReplicaClient` in
    production, in-memory fakes in the tier-1 tables. ``submit`` mirrors
    ``InferenceServer.submit`` (image → Future of (logits_row, step)) so
    ``serve.loadgen`` drives a fleet exactly like a single replica."""

    def __init__(self, cfg: RouteConfig, clients: Dict[int, object],
                 image_shape: Tuple[int, ...], image_dtype,
                 writer=None, beats_dir: Optional[str] = None,
                 committed_steps_fn: Optional[Callable[[], List[int]]] = None,
                 pin_fn: Optional[Callable[[int, int], None]] = None,
                 initial_step: int = -1,
                 clock: Callable[[], float] = time.monotonic,
                 wall_clock: Callable[[], float] = time.time):
        self.cfg = cfg
        self.clients = dict(clients)
        self.image_shape = tuple(image_shape)
        self.image_dtype = np.dtype(image_dtype)
        self.writer = writer
        self.beats_dir = beats_dir
        self.committed_steps_fn = committed_steps_fn
        self.pin_fn = pin_fn
        self.clock = clock
        self.wall_clock = wall_clock
        self.canary = CanaryController(cfg, initial_step=initial_step)

        self._lock = threading.Lock()
        self.health: Dict[int, ReplicaHealth] = {
            rid: ReplicaHealth(rid, cfg.suspect_after_failures,
                               cfg.dead_after_failures,
                               cfg.beat_stale_secs, cfg.slo_p99_ms)
            for rid in self.clients}
        self.outstanding: Dict[int, int] = {r: 0 for r in self.clients}
        self.served: Dict[int, int] = {r: 0 for r in self.clients}
        self.last_step: Dict[int, int] = {r: -1 for r in self.clients}
        self._lat_by_replica: Dict[int, deque] = {
            r: deque(maxlen=512) for r in self.clients}  # (t, ms)
        self._window: deque = deque(maxlen=4096)  # (t, ms) firsts only
        self._ewma_ms = 50.0

        self.requests = 0
        self.completed = 0
        self.errors = 0
        self.shed = 0
        self.degraded = 0
        self.hedges = 0
        self.retries = 0

        self._intake: "queue.Queue[_Request]" = queue.Queue()
        self._attempts: "queue.Queue[Tuple[_Request, int, int]]" = \
            queue.Queue()
        self._pending: Dict[int, _Request] = {}
        self._next_id = 0
        self._last_shed_row = -1e9
        self._last_route_row = 0.0
        self._row_marks: deque = deque(maxlen=8)  # (t, completed) per row
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Router":
        self._last_route_row = self.clock()
        self._row_marks.append((self._last_route_row, 0))
        spawned = [threading.Thread(target=self._dispatch_loop, daemon=True,
                                    name="drt-route-dispatch"),
                   threading.Thread(target=self._health_loop, daemon=True,
                                    name="drt-route-health")]
        spawned += [threading.Thread(target=self._worker_loop, daemon=True,
                                     name="drt-route-worker")
                    for _ in range(max(1, self.cfg.workers))]
        for t in spawned:
            t.start()
            self._threads.append(t)
        return self

    def close(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        now = self.clock()
        with self._lock:
            stuck = [r for r in self._pending.values() if not r.done]
            for req in stuck:
                req.done = True
                self.errors += 1
            self._pending.clear()
        for req in stuck:
            req.future.set_exception(RouteError("router closed"))
        self._write_route_row(now, final=True)
        for client in self.clients.values():
            try:
                client.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    # -- admission ---------------------------------------------------------

    def submit(self, image, variant: Optional[str] = None) -> Future:
        fut: Future = Future()
        now = self.clock()
        with self._lock:
            eligible = sum(1 for h in self.health.values()
                           if h.dispatchable) or 1
            backlog = sum(self.outstanding.values()) + self._intake.qsize()
            est_ms = backlog * self._ewma_ms / eligible
            if (self.cfg.shed_queue_ms > 0
                    and est_ms >= self.cfg.shed_queue_ms):
                self.shed += 1
                self._maybe_shed_row(now, est_ms, self.cfg.shed_queue_ms)
                fut.set_exception(RequestShed(
                    f"estimated queue delay {est_ms:.0f}ms >= "
                    f"{self.cfg.shed_queue_ms:.0f}ms"))
                return fut
            if (self.cfg.degrade_queue_ms > 0 and variant is None
                    and self.cfg.degrade_variant
                    and est_ms >= self.cfg.degrade_queue_ms):
                variant = self.cfg.degrade_variant
                self.degraded += 1
                self._maybe_shed_row(now, est_ms, self.cfg.degrade_queue_ms)
            self._next_id += 1
            req = _Request(
                id=self._next_id,
                image=np.asarray(image, dtype=self.image_dtype),
                variant=variant, future=fut, created=now,
                deadline=now + self.cfg.request_timeout_ms / 1000.0)
            self.requests += 1
            self._pending[req.id] = req
        self._intake.put(req)
        return req.future

    def _maybe_shed_row(self, now: float, est_ms: float,
                        threshold_ms: float) -> None:
        # caller holds _lock; rate-limited to one row/sec so a shed storm
        # cannot swamp the metrics stream
        if self.writer is None or now - self._last_shed_row < 1.0:
            return
        self._last_shed_row = now
        self.writer.write_event("shed", {
            "count": self.shed, "degraded": self.degraded,
            "est_queue_ms": round(est_ms, 1),
            "threshold_ms": threshold_ms})

    # -- dispatch ----------------------------------------------------------

    def _dispatch_loop(self) -> None:
        tick = max(0.005, self.cfg.hedge_ms / 1000.0 / 4.0)
        while not self._stop.is_set():
            try:
                req = self._intake.get(timeout=tick)
            except queue.Empty:
                req = None
            now = self.clock()
            if req is not None and not req.done:
                self._issue(req, now)
            self._scan_pending(now)

    def _issue(self, req: _Request, now: float) -> None:
        with self._lock:
            rid = pick_replica(self.health, self.outstanding, req.tried)
            if rid is None:
                if req.inflight == 0 and not req.done:
                    req.done = True
                    self.errors += 1
                    self._pending.pop(req.id, None)
                    fail = req.future
                else:
                    fail = None
            else:
                req.attempts += 1
                req.inflight += 1
                req.tried.add(rid)
                req.last_issue = now
                self.outstanding[rid] = self.outstanding.get(rid, 0) + 1
                fail = None
        if rid is None:
            if fail is not None:
                fail.set_exception(RouteError("no routable replica"))
            return
        self._attempts.put((req, rid, req.attempts))

    def _scan_pending(self, now: float) -> None:
        timed_out: List[_Request] = []
        hedge: List[_Request] = []
        with self._lock:
            for req in list(self._pending.values()):
                if req.done:
                    self._pending.pop(req.id, None)
                elif now >= req.deadline:
                    req.done = True
                    self.errors += 1
                    self._pending.pop(req.id, None)
                    timed_out.append(req)
                elif (not req.hedged and req.inflight >= 1
                      and req.attempts < self.cfg.max_attempts
                      and now - req.last_issue
                      >= self.cfg.hedge_ms / 1000.0):
                    req.hedged = True
                    self.hedges += 1
                    hedge.append(req)
        for req in timed_out:
            req.future.set_exception(RouteError(
                f"deadline after {req.attempts} attempt(s)"))
        for req in hedge:
            self._issue(req, now)

    # -- workers -----------------------------------------------------------

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            try:
                req, rid, attempt = self._attempts.get(timeout=0.1)
            except queue.Empty:
                continue
            self._run_attempt(req, rid, attempt)

    def _run_attempt(self, req: _Request, rid: int, attempt: int) -> None:
        client = self.clients.get(rid)
        err: Optional[Exception] = None
        result = None
        timeout = min(self.cfg.attempt_timeout_ms / 1000.0,
                      max(0.05, req.deadline - self.clock()))
        try:
            with span("route.attempt", replica=rid, attempt=attempt):
                if client is None:
                    raise ReplicaError(f"no client for replica {rid}")
                result = client.request(req.image, req.variant,
                                        timeout_secs=timeout)
        except ReplicaError as e:
            err = e
        except Exception as e:  # noqa: BLE001 — a client bug is a
            err = ReplicaError(f"{type(e).__name__}: {e}")  # failed attempt
        with self._lock:
            self.outstanding[rid] = max(0, self.outstanding.get(rid, 1) - 1)
            req.inflight -= 1
        if err is None:
            self._attempt_ok(req, rid, *result)
        else:
            self._attempt_failed(req, rid, err)

    def _attempt_ok(self, req: _Request, rid: int, row: np.ndarray,
                    step: int) -> None:
        now = self.clock()
        latency_ms = (now - req.created) * 1000.0
        conf = top1_confidence(row)
        with self._lock:
            tr = self.health[rid].on_success()
            self.served[rid] = self.served.get(rid, 0) + 1
            self.last_step[rid] = step
            self._lat_by_replica[rid].append((now, latency_ms))
            self.canary.observe_completion(rid, step, latency_ms, conf)
            first = not req.done
            if first:
                req.done = True
                self.completed += 1
                self._pending.pop(req.id, None)
                self._window.append((now, latency_ms))
                self._ewma_ms += 0.2 * (latency_ms - self._ewma_ms)
        self._write_transition(tr)
        if first:
            req.future.set_result((row, step))

    def _attempt_failed(self, req: _Request, rid: int,
                        err: Exception) -> None:
        now = self.clock()
        with self._lock:
            tr = self.health[rid].on_failure()
            if req.done:
                retry = final = False
            else:
                retry = (req.attempts < self.cfg.max_attempts
                         and now < req.deadline)
                final = not retry and req.inflight == 0
                if retry:
                    self.retries += 1
                if final:
                    req.done = True
                    self.errors += 1
                    self._pending.pop(req.id, None)
        self._write_transition(tr)
        if retry:
            self._issue(req, now)
        elif final:
            req.future.set_exception(RouteError(
                f"{req.attempts} attempt(s) failed; last: {err}"))

    # -- health / canary ---------------------------------------------------

    def _health_loop(self) -> None:
        interval = max(0.05, self.cfg.health_interval_secs)
        while not self._stop.is_set():
            self._stop.wait(interval)
            if self._stop.is_set():
                return
            try:
                with span("route.health"):
                    self._health_pass(self.clock())
            except Exception:  # noqa: BLE001 — scan must never die
                log.exception("route: health pass failed")

    def _health_pass(self, now: float) -> None:
        transitions: List[Transition] = []
        ages = self._beat_ages()
        with self._lock:
            for rid, h in self.health.items():
                transitions.append(h.on_beat(ages.get(rid)))
                transitions.append(h.on_pressure(self._replica_p99(rid, now)))
            probe = [r for r, h in self.health.items()
                     if h.state in (WARMING, SUSPECT)]
            # healthy canaries that have not confirmed the canary step
            # yet are pinged too: without this, a canary the dispatch
            # policy happens to starve of traffic (least-outstanding
            # concentrates a trickle on one replica) would roll back a
            # good step as no_confirm even though its swap landed
            probe += [r for r in self.canary.unconfirmed
                      if r not in probe and r in self.clients]
        for rid in probe:
            try:
                pong = self.clients[rid].ping(timeout_secs=2.0)
            except Exception:  # noqa: BLE001 — ReplicaError or a fake's
                with self._lock:
                    if self.health[rid].state == SUSPECT:
                        transitions.append(self.health[rid].on_failure())
            else:
                with self._lock:
                    transitions.append(self.health[rid].on_success())
                    step = int(pong.get("step", -1))
                    self.last_step[rid] = step
                    self.canary.observe_step(rid, step)
        for tr in transitions:
            self._write_transition(tr)
        self._canary_turn(now)
        if now - self._last_route_row >= self.cfg.row_interval_secs:
            self._write_route_row(now)

    def _beat_ages(self) -> Dict[int, float]:
        if not self.beats_dir:
            return {}
        out: Dict[int, float] = {}
        wall = self.wall_clock()
        for rid in self.clients:
            path = os.path.join(self.beats_dir, f"proc{rid}.json")
            try:
                with open(path) as f:
                    beat = json.load(f)
                out[rid] = max(0.0, wall - float(beat.get("wall_time", 0)))
            except (OSError, ValueError):
                continue  # no beat yet / torn write: age unknown
        return out

    def _replica_p99(self, rid: int, now: float) -> Optional[float]:
        # caller holds _lock
        dq = self._lat_by_replica.get(rid)
        if not dq:
            return None
        while dq and now - dq[0][0] > 30.0:
            dq.popleft()
        return percentile_ms([ms for _, ms in dq])

    def _canary_turn(self, now: float) -> None:
        newest = None
        if self.committed_steps_fn is not None:
            try:
                steps = self.committed_steps_fn()
                newest = max(steps) if steps else None
            except OSError:
                newest = None
        rows: List[dict] = []
        pins: List[Tuple[int, int]] = []
        with self._lock:
            healthy = [r for r, h in self.health.items() if h.dispatchable]
            all_ids = [r for r, h in self.health.items()
                       if h.state not in UNROUTABLE]
            r1, p1 = self.canary.observe_commit(newest, healthy, all_ids,
                                                now)
            r2, p2 = self.canary.tick(now)
            rows, pins = r1 + r2, p1 + p2
        for rid, step in pins:
            if self.pin_fn is not None:
                try:
                    self.pin_fn(rid, step)
                except OSError:
                    log.exception("route: pin replica %d → step %d failed",
                                  rid, step)
        if self.writer is not None:
            for row in rows:
                self.writer.write_event("canary", row)

    # -- supervisor hooks --------------------------------------------------

    def mark_draining(self, rid: int) -> None:
        with self._lock:
            tr = self.health[rid].drain()
        self._write_transition(tr)

    def readmit(self, rid: int) -> None:
        client = self.clients.get(rid)
        if client is not None:
            client.reset()  # the old process's pooled sockets are corpses
        with self._lock:
            tr = self.health[rid].readmit()
        self._write_transition(tr)

    def health_state(self, rid: int) -> str:
        with self._lock:
            return self.health[rid].state

    # -- reporting ---------------------------------------------------------

    def _write_transition(self, tr: Optional[Transition]) -> None:
        if tr is None:
            return
        log.info("route: replica %d %s → %s (%s)", tr.replica, tr.frm,
                 tr.to, tr.reason)
        if self.writer is not None:
            self.writer.write_event("replica_health", tr.row())

    def _replica_snapshot(self) -> Dict[str, dict]:
        # caller holds _lock
        now = self.clock()
        out = {}
        for rid, h in self.health.items():
            snap = {"state": h.state, "step": self.last_step.get(rid, -1),
                    "outstanding": self.outstanding.get(rid, 0),
                    "served": self.served.get(rid, 0),
                    "failures": h.failures}
            p99 = self._replica_p99(rid, now)
            if p99 is not None:
                snap["p99_ms"] = round(p99, 2)
            if h.beat_age is not None:
                snap["beat_age_secs"] = round(h.beat_age, 1)
            out[str(rid)] = snap
        return out

    def _write_route_row(self, now: float, final: bool = False) -> None:
        with self._lock:
            mark_t, mark_done = (self._row_marks[-1] if self._row_marks
                                 else (now, self.completed))
            dt = max(1e-6, now - mark_t)
            qps = (self.completed - mark_done) / dt
            while self._window and now - self._window[0][0] > dt:
                self._window.popleft()
            p99 = percentile_ms([ms for _, ms in self._window])
            self._row_marks.append((now, self.completed))
            self._last_route_row = now
            payload = {"requests": self.requests,
                       "completed": self.completed, "errors": self.errors,
                       "shed": self.shed, "degraded": self.degraded,
                       "hedges": self.hedges, "retries": self.retries,
                       "qps": round(qps, 2),
                       "replicas": self._replica_snapshot()}
            if p99 is not None:
                payload["p99_ms"] = round(p99, 2)
        if self.writer is not None:
            self.writer.write_event("route", payload)

    def report(self) -> dict:
        with self._lock:
            return {
                "requests": self.requests, "completed": self.completed,
                "errors": self.errors, "shed": self.shed,
                "degraded": self.degraded, "hedges": self.hedges,
                "retries": self.retries,
                "fleet_step": self.canary.fleet_step,
                "bad_steps": sorted(self.canary.bad_steps),
                "replicas": self._replica_snapshot(),
            }
