"""Protocol model checking: declared control-plane state machines,
exhaustive small-scope interleaving search, runtime trace conformance.

Three consumers share the declarations in ``spec.py``:

  * the gate phase (``main.py check``, ``--no-protocol`` to skip) runs
    ``checker.run_protocol`` — BFS over every interleaving of each
    declared model, safety + liveness, committed
    ``analysis/protocol_models.json`` artifact;
  * the ``protocol-drift`` lint rule (``analysis/rules/protocol_drift``)
    resolves the implementation's state/edge/file-name literals against
    the specs so model and code cannot silently diverge;
  * the trace replayer (``conformance.py``) validates recorded
    metrics rows against the declared edges — both chaos smokes run it,
    so every chaos run doubles as a protocol-conformance witness.

docs/static_analysis.md (protocol models) is the manual.
"""
from .checker import (artifact_path, check_model, run_protocol,  # noqa: F401
                      write_artifact)
from .conformance import check_rows, check_stream  # noqa: F401
from .spec import (Model, ProtocolSpec, load_specs,  # noqa: F401
                   register_spec)
