"""Sharded-expert dispatch comparison on the virtual 8-device mesh
(VERDICT r3 #3): one-hot einsum (GSPMD collectives) vs hand-scheduled
all-to-all (shard_map + lax.all_to_all), with the unsharded gather path as
the floor.

Runs on the fake 8-CPU mesh — the only >1-device surface in this
environment — so the numbers compare the COMMUNICATION/MEMORY structure of
the formulations, not TPU kernel speed (single-chip TPU numbers live in
docs/moe_r3.json via tools/bench_moe.py, where no expert axis exists to
shard over). Token budget matches the r3 bench: 8,192 tokens/step.

    python tools/bench_moe_a2a.py          # writes docs/moe_r4.json
"""
from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np


def build(mesh_axes: dict, dispatch: str, num_experts=8, top_k=1,
          bs=32, image=64, patch=4, k=1, depth=2):
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        shard_stacked_batch)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset
    cfg = get_preset("smoke")
    cfg.model.name = "vit"
    cfg.model.num_classes = 16
    cfg.model.vit_dim = 256
    cfg.model.vit_depth = depth  # shallow: the 1-core host pays XLA per
    # layer and the dispatch-formulation difference is per-MoE-block
    cfg.model.vit_heads = 4
    cfg.model.vit_num_experts = num_experts
    cfg.model.vit_moe_top_k = top_k
    cfg.model.vit_moe_dispatch = dispatch
    cfg.data.image_size = image
    cfg.model.vit_patch_size = patch
    cfg.train.batch_size = bs
    cfg.train.steps_per_loop = k
    for a, v in mesh_axes.items():
        setattr(cfg.mesh, a, v)
    tr = Trainer(cfg)
    tr.init_state()
    fn = tr.jitted_multi_step(k)
    rng = np.random.RandomState(0)
    batch = shard_stacked_batch({
        "images": rng.randn(k, bs, image, image, 3).astype(np.float32),
        "labels": rng.randint(0, 16, (k, bs)).astype(np.int32),
    }, tr.mesh)
    return tr, fn, batch, k


def ms_per_step(tr, fn, batch, k, loops=3, reps=3):
    state = tr.state
    for _ in range(2):
        state, _ = fn(state, batch)
    jax.block_until_ready(state.params)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(loops):
            state, _ = fn(state, batch)
        jax.block_until_ready(state.params)
        best = min(best, (time.perf_counter() - t0) / (loops * k))
    return best * 1e3


def main():
    assert len(jax.devices()) == 8, jax.devices()
    out = {"device": "virtual 8x cpu (structure comparison; single-chip "
                     "TPU rows are docs/moe_r3.json)",
           "tokens_per_batch": 32 * (64 // 4) ** 2, "configs": {}}
    rows = (
        # the floor: experts unsharded (all 8 devices data-parallel)
        ("dp8_gather_unsharded", {"data": 8}, "gather"),
        # sharded expert axis: GSPMD one-hot einsum vs hand-scheduled a2a
        ("dp2_ep4_einsum", {"data": 2, "expert": 4}, "einsum"),
        ("dp2_ep4_a2a", {"data": 2, "expert": 4}, "a2a"),
    )
    for name, axes, disp in rows:
        tr, fn, batch, k = build(axes, disp)
        ms = ms_per_step(tr, fn, batch, k)
        out["configs"][name] = round(ms, 3)
        print(f"{name:>22}: {ms:8.2f} ms/step", flush=True)
    c = out["configs"]
    out["a2a_vs_einsum_dp2ep4"] = round(
        c["dp2_ep4_einsum"] / c["dp2_ep4_a2a"], 2)
    out["a2a_vs_unsharded_gather"] = round(
        c["dp2_ep4_a2a"] / c["dp8_gather_unsharded"], 2)
    out_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "moe_r4.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
