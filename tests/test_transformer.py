"""ViT model family tests — attention-based models through the same
Trainer/config path as the ResNets."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.models import VisionTransformer, create_model
from distributed_resnet_tensorflow_tpu.utils.config import ModelConfig, get_preset


def test_vit_shapes_and_dtype():
    model = VisionTransformer(num_classes=10, patch_size=4, dim=32, depth=2,
                              num_heads=2, dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x)
    logits = model.apply(variables, x)
    assert logits.shape == (2, 10) and logits.dtype == jnp.float32


def test_vit_attention_impls_agree():
    """dense and blockwise attention give the same model output."""
    x = jnp.asarray(np.random.RandomState(0).randn(2, 16, 16, 3), jnp.float32)
    outs = []
    for impl in ("dense", "blockwise"):
        model = VisionTransformer(num_classes=4, patch_size=4, dim=32,
                                  depth=1, num_heads=2, dtype=jnp.float32,
                                  attention_impl=impl)
        variables = model.init(jax.random.PRNGKey(0), x)
        outs.append(np.asarray(model.apply(variables, x)))
    np.testing.assert_allclose(outs[0], outs[1], rtol=2e-5, atol=2e-5)


def test_vit_invalid_configs():
    x = jnp.zeros((1, 30, 30, 3))
    with pytest.raises(ValueError):
        VisionTransformer(patch_size=4).init(jax.random.PRNGKey(0), x)
    x2 = jnp.zeros((1, 32, 32, 3))
    with pytest.raises(ValueError):
        VisionTransformer(dim=30, num_heads=4).init(jax.random.PRNGKey(0), x2)


def test_vit_trains_through_trainer():
    from distributed_resnet_tensorflow_tpu.data import learnable_synthetic_iterator
    from distributed_resnet_tensorflow_tpu.train import Trainer
    cfg = get_preset("smoke")
    cfg.model.name = "vit"
    cfg.model.num_classes = 4
    cfg.model.compute_dtype = "float32"
    cfg.model.vit_dim = 32
    cfg.model.vit_depth = 1
    cfg.model.vit_heads = 2
    cfg.data.image_size = 8
    cfg.train.batch_size = 16
    cfg.optimizer.name = "adam"
    cfg.optimizer.schedule = "constant"
    cfg.optimizer.learning_rate = 1e-3
    cfg.optimizer.weight_decay = 0.0
    tr = Trainer(cfg)
    tr.init_state()
    it = learnable_synthetic_iterator(16, 8, 4, seed=2)
    losses = []
    from distributed_resnet_tensorflow_tpu.parallel import shard_batch
    step = tr.jitted_train_step()
    for _ in range(25):
        tr.state, m = step(tr.state, shard_batch(next(it), tr.mesh))
        losses.append(float(m["cross_entropy"]))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])


def test_create_model_vit_factory():
    cfg = ModelConfig(name="vit", num_classes=10, compute_dtype="float32")
    m = create_model(cfg, "cifar10")
    assert isinstance(m, VisionTransformer)


def _mesh(**axes):
    from distributed_resnet_tensorflow_tpu.parallel import create_mesh
    from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig
    return create_mesh(MeshConfig(**axes))


def _small_vit(impl, mesh=None):
    return VisionTransformer(num_classes=4, patch_size=4, dim=32, depth=2,
                             num_heads=4, dtype=jnp.float32,
                             attention_impl=impl, mesh=mesh)


@pytest.mark.heavy
def test_vit_ring_matches_dense_full_model():
    """Sequence parallelism as a MODEL feature: ring attention + seq-sharded
    tokens through the full ViT must reproduce the dense model's logits AND
    parameter gradients (VERDICT r1 item 7)."""
    mesh = _mesh(data=2, sequence=4)
    x = jnp.asarray(np.random.RandomState(0).randn(4, 16, 16, 3), jnp.float32)
    labels = jnp.asarray([0, 1, 2, 3])

    dense = _small_vit("dense")
    ring = _small_vit("ring", mesh=mesh)
    variables = dense.init(jax.random.PRNGKey(0), x)

    def loss(model):
        def fn(params, x):
            logits = model.apply({"params": params}, x)
            onehot = jax.nn.one_hot(labels, 4)
            return -(jax.nn.log_softmax(logits) * onehot).sum(), logits
        return fn

    (ld, logits_d), grads_d = jax.jit(
        jax.value_and_grad(loss(dense), has_aux=True))(variables["params"], x)
    (lr, logits_r), grads_r = jax.jit(
        jax.value_and_grad(loss(ring), has_aux=True))(variables["params"], x)
    np.testing.assert_allclose(np.asarray(logits_r), np.asarray(logits_d),
                               rtol=2e-5, atol=2e-5)
    assert np.isclose(float(lr), float(ld), rtol=1e-5)
    for gd, gr in zip(jax.tree_util.tree_leaves(grads_d),
                      jax.tree_util.tree_leaves(grads_r)):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=2e-4, atol=2e-5)


def test_vit_tensor_parallel_matches_unsharded():
    """Megatron-style tensor parallelism (qkv/proj/mlp over `tensor`) must
    be numerically invisible: same logits as the unsharded model, with the
    kernels actually sharded in the train state (VERDICT r1 item 8)."""
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        param_sharding_rule, tree_param_shardings)
    mesh = _mesh(data=2, tensor=4)
    x = jnp.asarray(np.random.RandomState(1).randn(4, 16, 16, 3), jnp.float32)

    plain = _small_vit("dense")
    tp = _small_vit("dense", mesh=mesh)
    variables = plain.init(jax.random.PRNGKey(0), x)

    # the rule shards the four block projections over `tensor`
    shardings = tree_param_shardings(variables["params"], mesh)
    flat = {"/".join(str(p) for p in path): s for path, s in
            jax.tree_util.tree_flatten_with_path(shardings)[0]}
    qkv = [s for name, s in flat.items() if "qkv" in name and "kernel" in name]
    assert qkv and all("tensor" in str(s.spec) for s in qkv)
    proj = [s for name, s in flat.items() if "proj" in name and "kernel" in name]
    assert proj and all("tensor" in str(s.spec) for s in proj)

    # sharded params + constrained activations == unsharded numerics
    sharded_params = jax.device_put(variables["params"], shardings)
    out_plain = np.asarray(jax.jit(
        lambda p, x: plain.apply({"params": p}, x))(variables["params"], x))
    out_tp = np.asarray(jax.jit(
        lambda p, x: tp.apply({"params": p}, x))(sharded_params, x))
    np.testing.assert_allclose(out_tp, out_plain, rtol=2e-5, atol=2e-5)


def test_vit_ring_routed_through_trainer():
    """mesh.sequence > 1 + attention_impl=auto resolves to ring and trains
    end-to-end through the Trainer."""
    from distributed_resnet_tensorflow_tpu.data import (
        learnable_synthetic_iterator)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    cfg = get_preset("smoke")
    cfg.model.name = "vit"
    cfg.model.num_classes = 4
    cfg.model.compute_dtype = "float32"
    cfg.model.vit_dim = 32
    cfg.model.vit_depth = 1
    cfg.model.vit_heads = 2
    cfg.model.attention_impl = "auto"
    cfg.data.image_size = 8       # 4 tokens with patch 4... use seq=2
    cfg.train.batch_size = 8
    cfg.mesh.data = 4
    cfg.mesh.sequence = 2
    cfg.optimizer.weight_decay = 0.0
    tr = Trainer(cfg)
    assert tr.model.attention_impl == "ring"
    tr.init_state()
    state, m = tr.train(learnable_synthetic_iterator(8, 8, 4), num_steps=2)
    assert int(state.step) == 2
    assert np.isfinite(float(m["loss"]))


def test_dead_mesh_axes_rejected():
    from distributed_resnet_tensorflow_tpu.train import Trainer
    cfg = get_preset("smoke")
    cfg.mesh.data = 4
    cfg.mesh.tensor = 2
    with pytest.raises(ValueError, match="tensor"):
        Trainer(cfg)
    # expert has no consumer in ANY model family yet
    cfg2 = get_preset("smoke")
    cfg2.model.name = "vit"
    cfg2.mesh.data = 4
    cfg2.mesh.expert = 2
    with pytest.raises(ValueError, match="expert"):
        Trainer(cfg2)
    # pipeline for a non-transformer model is rejected
    cfg3 = get_preset("smoke")
    cfg3.mesh.data = 4
    cfg3.mesh.pipeline = 2
    with pytest.raises(ValueError, match="pipeline"):
        Trainer(cfg3)
