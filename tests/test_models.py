"""Model zoo tests — shapes, param structure, variant table, v2 semantics
(covers reference resnet_model_official.py behaviors, SURVEY.md §2.1)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.models import (
    CifarResNetV2, ImageNetResNetV2, IMAGENET_MODEL_PARAMS, LogisticNet,
    count_params, create_model)
from distributed_resnet_tensorflow_tpu.utils.config import ModelConfig


def _init_and_apply(model, shape, train=False):
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros(shape, jnp.float32)
    variables = model.init(rng, x, train=False)
    if train:
        out, _ = model.apply(variables, x, train=True, mutable=["batch_stats"])
    else:
        out = model.apply(variables, x, train=False)
    return variables, out


def test_cifar_resnet_shapes():
    model = CifarResNetV2(resnet_size=20, num_classes=10, dtype=jnp.float32)
    variables, logits = _init_and_apply(model, (4, 32, 32, 3))
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32


def test_cifar_resnet_size_validation():
    """6n+2 constraint (reference resnet_model_official.py:217-231)."""
    model = CifarResNetV2(resnet_size=21)
    with pytest.raises(ValueError):
        _init_and_apply(model, (1, 32, 32, 3))


def test_cifar_resnet20_param_count():
    """ResNet-20 v2 CIFAR ≈ 0.27M params (well-known figure)."""
    model = CifarResNetV2(resnet_size=20, num_classes=10, dtype=jnp.float32)
    variables, _ = _init_and_apply(model, (1, 32, 32, 3))
    n = count_params(variables["params"])
    assert 0.25e6 < n < 0.30e6, n


@pytest.mark.heavy
def test_wide_resnet_28_10_param_count():
    """WRN-28-10 ≈ 36.5M params — exercises the width generalization
    (BASELINE.json config 4)."""
    model = CifarResNetV2(resnet_size=28, width_multiplier=10,
                          num_classes=100, dtype=jnp.float32)
    variables, logits = _init_and_apply(model, (2, 32, 32, 3))
    n = count_params(variables["params"])
    assert 35e6 < n < 38e6, n
    assert logits.shape == (2, 100)


@pytest.mark.parametrize("size", [18, 50])
@pytest.mark.heavy
def test_imagenet_resnet_shapes(size):
    model = ImageNetResNetV2(resnet_size=size, num_classes=1001,
                             dtype=jnp.float32)
    variables, logits = _init_and_apply(model, (2, 64, 64, 3))
    assert logits.shape == (2, 1001)


def test_imagenet_resnet50_param_count():
    """ResNet-50 ≈ 25.6M params (1001 classes)."""
    model = ImageNetResNetV2(resnet_size=50, num_classes=1001,
                             dtype=jnp.float32)
    variables, _ = _init_and_apply(model, (1, 224, 224, 3))
    n = count_params(variables["params"])
    assert 25e6 < n < 26.5e6, n


def test_imagenet_size_table():
    """Size table parity (reference resnet_model_official.py:352-359)."""
    assert set(IMAGENET_MODEL_PARAMS) == {18, 34, 50, 101, 152, 200}
    assert IMAGENET_MODEL_PARAMS[50] == ("bottleneck", (3, 4, 6, 3))
    assert IMAGENET_MODEL_PARAMS[18] == ("building", (2, 2, 2, 2))
    model = ImageNetResNetV2(resnet_size=77)
    with pytest.raises(ValueError):
        _init_and_apply(model, (1, 64, 64, 3))


def test_batch_stats_update_in_train_mode():
    """BN moving stats must change in train mode and be used in eval —
    successor of the reference's UPDATE_OPS control-dep wiring
    (reference resnet_model.py:118-121)."""
    model = CifarResNetV2(resnet_size=20, dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 32, 32, 3))
    variables = model.init(rng, x, train=False)
    _, mutated = model.apply(variables, x, train=True, mutable=["batch_stats"])
    old = jax.tree_util.tree_leaves(variables["batch_stats"])
    new = jax.tree_util.tree_leaves(mutated["batch_stats"])
    assert any(not np.allclose(o, n) for o, n in zip(old, new))


def test_bfloat16_compute_fp32_params():
    model = CifarResNetV2(resnet_size=20, dtype=jnp.bfloat16)
    variables, logits = _init_and_apply(model, (2, 32, 32, 3))
    # params stay fp32 (master weights), head output fp32
    kernels = jax.tree_util.tree_leaves(variables["params"])
    assert all(k.dtype == jnp.float32 for k in kernels)
    assert logits.dtype == jnp.float32


def test_logistic_net():
    """Toy MLP parity (reference logist_model.py)."""
    model = LogisticNet(num_classes=10, hidden_units=100)
    variables, logits = _init_and_apply(model, (4, 32, 32, 3))
    assert logits.shape == (4, 10)


def test_create_model_factory():
    cfg = ModelConfig(resnet_size=20, num_classes=10, compute_dtype="float32")
    m = create_model(cfg, "cifar10")
    assert isinstance(m, CifarResNetV2)
    cfg2 = ModelConfig(resnet_size=50, num_classes=1001, compute_dtype="float32")
    m2 = create_model(cfg2, "imagenet")
    assert isinstance(m2, ImageNetResNetV2)
    cfg3 = ModelConfig(name="logistic")
    assert isinstance(create_model(cfg3, "cifar10"), LogisticNet)


def test_stem_space_to_depth_parity():
    """StemConv(space_to_depth=True) computes the same conv as the plain
    7x7/2 stem — same params (mode-portable checkpoints), reassociated
    arithmetic only (fp32 here, so near-exact)."""
    from distributed_resnet_tensorflow_tpu.models.resnet import StemConv

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 32, 32, 3).astype(np.float32))
    plain = StemConv(16, space_to_depth=False, dtype=jnp.float32)
    s2d = StemConv(16, space_to_depth=True, dtype=jnp.float32)
    variables = plain.init(jax.random.PRNGKey(0), x)
    y_plain = plain.apply(variables, x)
    y_s2d = s2d.apply(variables, x)  # same param tree
    assert y_plain.shape == (2, 16, 16, 16)
    assert y_s2d.shape == y_plain.shape
    np.testing.assert_allclose(np.asarray(y_s2d), np.asarray(y_plain),
                               rtol=1e-5, atol=1e-5)

    # grads agree too (the transform is linear in both x and w)
    def loss(mode):
        m = StemConv(16, space_to_depth=mode, dtype=jnp.float32)
        return lambda v: jnp.sum(m.apply(v, x) ** 2)
    g_plain = jax.grad(loss(False))(variables)
    g_s2d = jax.grad(loss(True))(variables)
    np.testing.assert_allclose(
        np.asarray(g_s2d["params"]["kernel"]),
        np.asarray(g_plain["params"]["kernel"]), rtol=1e-4, atol=1e-4)


# ---- normalization contract (model.norm = batch | frozen | group) --------

def test_group_norm_matches_manual():
    """ChannelGroupNorm == hand-computed GroupNorm (per sample, per group
    over H·W·C/G) at f32."""
    from distributed_resnet_tensorflow_tpu.ops.batch_norm import (
        ChannelGroupNorm)
    rng = np.random.RandomState(0)
    x = rng.randn(3, 5, 5, 8).astype(np.float32)
    m = ChannelGroupNorm(groups=4, epsilon=1e-5, dtype=jnp.float32)
    variables = m.init(jax.random.PRNGKey(0), jnp.asarray(x))
    y = np.asarray(m.apply(variables, jnp.asarray(x)))
    xg = x.reshape(3, 5, 5, 4, 2)
    mean = xg.mean(axis=(1, 2, 4), keepdims=True)
    var = xg.var(axis=(1, 2, 4), keepdims=True)
    ref = ((xg - mean) / np.sqrt(var + 1e-5)).reshape(x.shape)
    np.testing.assert_allclose(y, ref, rtol=2e-4, atol=2e-5)


def test_group_norm_affine_params_learnable():
    from distributed_resnet_tensorflow_tpu.ops.batch_norm import (
        ChannelGroupNorm)
    m = ChannelGroupNorm(groups=2, dtype=jnp.float32)
    x = jnp.ones((2, 4, 4, 4), jnp.float32)
    variables = m.init(jax.random.PRNGKey(0), x)
    assert set(variables["params"]) == {"scale", "bias"}
    assert "batch_stats" not in variables


def test_effective_gn_groups():
    from distributed_resnet_tensorflow_tpu.ops.batch_norm import (
        effective_gn_groups)
    assert effective_gn_groups(64, 32) == 32    # imagenet stages
    assert effective_gn_groups(2048, 32) == 32
    assert effective_gn_groups(16, 32) == 16    # narrow cifar stage
    assert effective_gn_groups(48, 32) == 16    # non-dividing: gcd
    assert effective_gn_groups(7, 32) == 7


def test_norm_group_resnet_stateless_and_batch_independent():
    """norm='group': no batch_stats anywhere; train==eval forward; each
    sample's output independent of the rest of the batch."""
    model = CifarResNetV2(resnet_size=8, num_classes=4, dtype=jnp.float32,
                          norm="group")
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(4, 16, 16, 3).astype(np.float32))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    assert "batch_stats" not in variables or not variables["batch_stats"]
    eval_out = model.apply(variables, x, train=False)
    train_out, mutated = model.apply(variables, x, train=True,
                                     mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(eval_out), np.asarray(train_out),
                               rtol=1e-6)
    solo = model.apply(variables, x[:1], train=True,
                       mutable=["batch_stats"])[0]
    np.testing.assert_allclose(np.asarray(solo[0]),
                               np.asarray(train_out[0]), rtol=1e-4,
                               atol=1e-5)


def test_norm_frozen_train_equals_eval_and_stats_fixed():
    """norm='frozen': training forward uses running stats (== eval
    forward), and the stats don't move."""
    model = CifarResNetV2(resnet_size=8, num_classes=4, dtype=jnp.float32,
                          norm="frozen")
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(4, 16, 16, 3).astype(np.float32))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    assert variables["batch_stats"]  # BN variables still exist (fine-tune)
    eval_out = model.apply(variables, x, train=False)
    train_out, mutated = model.apply(variables, x, train=True,
                                     mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(eval_out), np.asarray(train_out),
                               rtol=1e-6)
    before = jax.tree_util.tree_leaves(variables["batch_stats"])
    after = jax.tree_util.tree_leaves(mutated["batch_stats"])
    for b, a in zip(before, after):
        np.testing.assert_array_equal(np.asarray(b), np.asarray(a))


def test_norm_unknown_rejected():
    model = CifarResNetV2(resnet_size=8, num_classes=4, norm="layer")
    with pytest.raises(ValueError, match="batch|frozen|group"):
        _init_and_apply(model, (1, 16, 16, 3))


def test_create_model_norm_threading():
    cfg = ModelConfig(resnet_size=8, num_classes=4, norm="group",
                      gn_groups=16, compute_dtype="float32")
    model = create_model(cfg, "cifar10")
    assert model.norm == "group" and model.norm_groups == 16
    cfg_i = ModelConfig(resnet_size=18, num_classes=10, norm="frozen",
                        compute_dtype="float32")
    model_i = create_model(cfg_i, "imagenet")
    assert model_i.norm == "frozen"
