"""Topology-aware hierarchical collectives + the startup comm autotune
(ISSUE 18; parallel/overlap.py module docstring, docs/observability.md).

The load-bearing claims, pinned here:

* the two-tier ``axis_index_groups`` factorization is sound: the staged
  RS -> inter-psum -> AG exchange is BITWISE equal to the flat psum on
  exactly-representable integer payloads (floats only reassociate, so
  the bitwise oracle uses payloads where association cannot matter),
  composed with compression, ZeRO-1 out_specs and fsdp-sharded leaves;
* the declared plan and the wire ledger come from ONE source
  (``_bucket_plan_ops``): staged op order is RS@data[k] ->
  psum@data[D/k] (the only inter-tier traffic, ~1/k of the payload) ->
  AG@data[k], and the flat plan moves the FULL payload inter-tier;
* end-to-end (Trainer) the hierarchical run stays allclose to flat
  (reduction reassociation only) and is bitwise REPRODUCIBLE, with the
  comm_overlap snapshot carrying hierarchy/inter-wire accounting;
* ``tune_comm_plan`` is deterministic given a fixed table, only admits
  hierarchical candidates backed by MEASURED plausible tier rows, and
  falls back flat LOUDLY on a seeded probe lie;
* the bandwidth catalog round-trips tier rows (schema v2) and still
  loads v1 documents.
"""
import json
import logging
import os

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from distributed_resnet_tensorflow_tpu.parallel import create_mesh
from distributed_resnet_tensorflow_tpu.parallel import overlap as ov
from distributed_resnet_tensorflow_tpu.parallel.mesh import (
    data_axis_host_factorization, shard_map_compat)
from distributed_resnet_tensorflow_tpu.parallel.overlap import (
    autotune_mode, hierarchy_factor, hierarchy_groups, overlap_stats,
    resolve_hierarchy)
from distributed_resnet_tensorflow_tpu.telemetry import planner
from distributed_resnet_tensorflow_tpu.train import Trainer
from distributed_resnet_tensorflow_tpu.utils.config import (MeshConfig,
                                                            get_preset)


# ---------------------------------------------------------------------------
# group construction + knob validation
# ---------------------------------------------------------------------------

def test_hierarchy_groups_partition():
    gi, ge = hierarchy_groups(4, 2)
    # intra: consecutive host blocks; inter: one peer per host by rank
    assert gi == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert ge == [[0, 4], [1, 5], [2, 6], [3, 7]]
    # both tilings partition the full axis (equal-size groups — the
    # replica-consistency precondition for grouped psum of replicated
    # operands)
    for groups, size in ((gi, 4), (ge, 2)):
        flat = sorted(i for g in groups for i in g)
        assert flat == list(range(8))
        assert all(len(g) == size for g in groups)


def test_hierarchy_factor_override_validation(mesh8):
    cfg = get_preset("smoke")
    for bad in (3, 8, 1, -4):
        cfg.comm.intra_axis_size = bad
        if bad in (1, 0):
            continue
        with pytest.raises(ValueError, match="intra_axis_size"):
            hierarchy_factor(cfg, mesh8)
    for good in (2, 4):
        cfg.comm.intra_axis_size = good
        assert hierarchy_factor(cfg, mesh8) == good


def test_virtual_mesh_resolves_flat_without_override(mesh8):
    """A single-process virtual mesh has no host boundary: auto stays
    flat quietly, on refuses loudly (naming the override), off is None,
    and unknown knob values are refused."""
    cfg = get_preset("smoke")
    assert data_axis_host_factorization(mesh8) is None
    cfg.comm.hierarchy = "off"
    assert resolve_hierarchy(cfg, mesh8) is None
    cfg.comm.hierarchy = "auto"
    assert resolve_hierarchy(cfg, mesh8) is None
    cfg.comm.hierarchy = "on"
    with pytest.raises(ValueError, match="intra_axis_size"):
        resolve_hierarchy(cfg, mesh8)
    cfg.comm.intra_axis_size = 4
    assert resolve_hierarchy(cfg, mesh8) == 4
    cfg.comm.hierarchy = "sometimes"
    with pytest.raises(ValueError, match="hierarchy"):
        resolve_hierarchy(cfg, mesh8)
    cfg.comm.hierarchy = "off"
    cfg.comm.autotune = "startup"
    assert autotune_mode(cfg) == "startup"
    cfg.comm.autotune = "always"
    with pytest.raises(ValueError, match="autotune"):
        autotune_mode(cfg)


# ---------------------------------------------------------------------------
# the staged exchange: bitwise vs flat (exchange level)
# ---------------------------------------------------------------------------

def _int_leaves(rng, shapes, lo=-8, hi=8):
    # exactly representable in f32 AND bf16 (including their 8-way sums):
    # association cannot change a bit, so bitwise equality is the oracle
    return [rng.randint(lo, hi, size=s).astype(np.float32) for s in shapes]


def _exchange(mesh, leaves, specs, hierarchy, data_size, out_specs=None,
              compress=None, reduce_axes=("data", "fsdp"), in_specs=None,
              run_out_specs=None):
    def body(*ls):
        return tuple(ov._exchange_bucket(
            list(ls), specs, out_specs=out_specs, compress=compress,
            reduce_axes=reduce_axes, hierarchy=hierarchy,
            data_size=data_size))
    n = len(leaves)
    f = shard_map_compat(
        body, mesh,
        in_specs=tuple(in_specs or (P(),) * n),
        out_specs=tuple(run_out_specs or in_specs or (P(),) * n))
    return [np.asarray(x) for x in jax.jit(f)(*leaves)]


@pytest.mark.parametrize("compress", [None, "bf16"], ids=["f32", "bf16"])
def test_staged_exchange_bitwise_vs_flat(mesh8, rng, compress):
    leaves = _int_leaves(rng, [(7, 3), (5,), (2, 2, 2)])
    specs = [P(), P(), P()]
    flat = _exchange(mesh8, leaves, specs, None, 8, compress=compress)
    hier = _exchange(mesh8, leaves, specs, 4, 8, compress=compress)
    for a, b in zip(flat, hier):
        np.testing.assert_array_equal(a, b)


def test_staged_exchange_bitwise_with_zero1_out_specs(mesh8, rng):
    """ZeRO-1 leaves keep their flat data reduce-scatter (they already
    move 1/N into the shard layout); the staged block restages only the
    replicated remainder — composition stays bitwise."""
    leaves = _int_leaves(rng, [(8, 3), (5,), (6,)])
    specs = [P(), P(), P()]
    out_specs = [P("data"), P(), P()]
    in_specs = (P(), P(), P())
    run_out = (P("data"), P(), P())
    kw = dict(out_specs=out_specs, in_specs=in_specs, run_out_specs=run_out)
    flat = _exchange(mesh8, leaves, specs, None, 8, **kw)
    hier = _exchange(mesh8, leaves, specs, 4, 8, **kw)
    for a, b in zip(flat, hier):
        np.testing.assert_array_equal(a, b)


def test_staged_exchange_bitwise_with_fsdp_leaves(mesh_dp_fsdp, rng):
    """dp(4)×fsdp(2): fsdp-sharded leaves reduce-scatter on fsdp first,
    then their remainders ride the trailing staged block over the
    factored data axis (k=2)."""
    # gradients enter the exchange FULL-size (replicated) and the
    # fsdp-sharded leaf leaves scattered into its training-state layout
    leaves = _int_leaves(rng, [(7, 3), (4, 6)])
    specs = [P(), P(None, "fsdp")]
    in_specs = (P(), P())
    run_out = (P(), P(None, "fsdp"))
    kw = dict(in_specs=in_specs, run_out_specs=run_out)
    flat = _exchange(mesh_dp_fsdp, leaves, specs, None, 4, **kw)
    hier = _exchange(mesh_dp_fsdp, leaves, specs, 2, 4, **kw)
    for a, b in zip(flat, hier):
        np.testing.assert_array_equal(a, b)


def test_declared_plan_and_inter_wire_quarter():
    """One source for the declared schedule AND the wire ledger: staged
    op order, the [k] group suffixes, and inter-tier bytes ~1/k of the
    flat plan's (the acceptance ratio; pad-tolerant 3x bound)."""
    specs = [P(), P(), P()]
    kw = dict(reduce_axes=("data", "fsdp"), leaf_elems=[21, 5, 8],
              wire_itemsize=4)
    hier = ov._bucket_plan_ops(specs, hierarchy=4, data_size=8, **kw)
    flat = ov._bucket_plan_ops(specs, **kw)
    assert [op["sig"] for op in flat] == ["psum@data+fsdp"]
    assert [op["sig"] for op in hier] == [
        "psum_scatter@data[4]", "psum@data[2]", "psum@fsdp",
        "all_gather@data[4]"]
    assert ov.declared_bucket_collectives(
        specs, reduce_axes=("data", "fsdp"), hierarchy=4,
        data_size=8) == [op["sig"] for op in hier]
    inter_h = sum(op["wire_bytes"] for op in hier if op["inter"])
    inter_f = sum(op["wire_bytes"] for op in flat if op["inter"])
    assert inter_f == 34 * 4  # flat: the FULL payload crosses the tier
    assert inter_h == 36  # 34 elems padded to 36, 1/4 shard, 4B each
    assert inter_h * 3 < inter_f
    # degenerate factorizations resolve flat (k must be a non-trivial
    # divisor and the bucket must reduce over data)
    for k, d, axes in ((8, 8, ("data",)), (3, 8, ("data",)),
                       (4, 8, ("fsdp",))):
        assert ov._resolve_hier(k, d, axes) is None


# ---------------------------------------------------------------------------
# end-to-end: Trainer hier-vs-flat + the snapshot accounting
# ---------------------------------------------------------------------------

def _tiny_cfg(**kw):
    cfg = get_preset("smoke")
    cfg.model.compute_dtype = "float32"
    cfg.model.resnet_size = 8
    cfg.model.num_classes = 4
    cfg.data.image_size = 8
    cfg.train.batch_size = 16
    cfg.optimizer.schedule = "constant"
    cfg.checkpoint.save_every_secs = 0.0
    cfg.comm.overlap = "on"
    cfg.comm.bucket_mb = 0.05
    # keep the per-process comm probe (and its bandwidth-catalog write)
    # out of the non-autotune legs — the autotune test re-enables it
    cfg.telemetry.comm_timing = False
    for k, v in kw.items():
        cfg.override(k, v)
    return cfg


def _fixed_batches(n=3, bs=16, size=8, classes=4):
    rng = np.random.RandomState(7)
    imgs = rng.randn(n, bs, size, size, 3).astype(np.float32)
    labs = rng.randint(0, classes, (n, bs)).astype(np.int32)
    return [{"images": imgs[i], "labels": labs[i]} for i in range(n)]


def _flat_params(state):
    return np.concatenate([np.asarray(l, np.float32).ravel() for l in
                           jax.tree_util.tree_leaves(state.params)])


def _train(mesh_cfg, batches, **kw):
    cfg = _tiny_cfg(**kw)
    tr = Trainer(cfg, mesh=create_mesh(mesh_cfg))
    tr.init_state()
    state, metrics = tr.train(iter(list(batches)), num_steps=len(batches))
    return tr, state, _flat_params(state), metrics


_HIER = {"comm.hierarchy": "on", "comm.intra_axis_size": "4"}


# re-tiered slow (ISSUE 18): ~18 s of multi-device compiles on the one
# CPU core, and the 870 s tier-1 budget has no headroom left. The
# bit-identity and staged-plan claims stay in tier-1 via the
# exchange-level tests above (sub-second each); this leg adds the
# whole-Trainer composition on top.
@pytest.mark.slow
def test_e2e_hierarchical_training_matches_flat(devices):
    """The e2e acceptance leg on the 2x4-factored virtual mesh: the
    staged run stays allclose to flat (float reassociation only — the
    staged sum is a different association of the SAME addends), is
    bitwise REPRODUCIBLE run-to-run, and the snapshot declares the
    staged plan with inter-tier wire ~1/4 of the flat run's."""
    batches = _fixed_batches()
    _, _, flat, m0 = _train(MeshConfig(data=8), batches)
    base = overlap_stats.snapshot()
    assert base["hierarchy"] == 0
    _, _, hier, m1 = _train(MeshConfig(data=8), batches, **_HIER)
    snap = overlap_stats.snapshot()
    assert snap["hierarchy"] == 4 and snap["tuned"] is False
    # same bucket plan, restaged collectives
    assert snap["bucket_bytes"] == base["bucket_bytes"]
    for ops in snap["declared_collectives"]:
        assert ops[0].startswith("psum_scatter@data[4]")
        assert ops[-1] == "all_gather@data[4]"
        assert any(op == "psum@data[2]" for op in ops)
    # the acceptance ratio: per-bucket inter-tier bytes drop to ~1/k
    # (pad-tolerant 3x bound; flat moves the full wire payload)
    assert sum(base["bucket_inter_wire_bytes"]) == base["wire_bytes"]
    assert sum(snap["bucket_inter_wire_bytes"]) * 3 < base["wire_bytes"]
    # op ledger aligns 1:1 with the declared schedule
    assert [len(b) for b in snap["bucket_op_wire_bytes"]] == \
        [len(b) for b in snap["declared_collectives"]]
    np.testing.assert_allclose(hier, flat, rtol=1e-4, atol=1e-5)
    assert abs(float(m0["loss"]) - float(m1["loss"])) < 1e-3
    _, _, hier2, _ = _train(MeshConfig(data=8), batches, **_HIER)
    np.testing.assert_array_equal(hier, hier2)


# re-tiered out of the 870s tier-1 (ISSUE 18, ~25s: four more full
# trainings). Each composition keeps a faster tier-1 sibling: the
# exchange-level bitwise grid above covers compress/zero1/fsdp staging,
# and test_e2e_hierarchical_training_matches_flat pins the plain-dp e2e
# leg; the full (unfiltered) suite runs the e2e compositions.
@pytest.mark.slow
@pytest.mark.parametrize("kw", [
    {"comm.compress": "bf16"},
    {"optimizer.zero1": "on", "optimizer.zero1_min_size": "16"},
    {"train.grad_accum_steps": "2"},
], ids=["compress", "zero1", "accum2"])
def test_e2e_hierarchical_compositions_match_flat(devices, kw):
    batches = _fixed_batches()
    _, _, flat, _ = _train(MeshConfig(data=8), batches, **kw)
    _, _, hier, _ = _train(MeshConfig(data=8), batches, **kw, **_HIER)
    snap = overlap_stats.snapshot()
    assert snap["hierarchy"] == 4
    tol = dict(rtol=2e-2, atol=5e-3) if "comm.compress" in kw \
        else dict(rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(hier, flat, **tol)


def test_hierarchy_without_overlap_warns(caplog, devices):
    """comm.hierarchy rides the bucketed exchange: with overlap resolved
    off the Trainer must warn loudly instead of silently training the
    flat unbucketed program."""
    cfg = _tiny_cfg(**_HIER)
    cfg.comm.overlap = "off"
    with caplog.at_level(
            logging.WARNING,
            logger="distributed_resnet_tensorflow_tpu.train.loop"):
        tr = Trainer(cfg, mesh=create_mesh(MeshConfig(data=8)))
    assert not tr.comm_overlap_active
    assert any("hierarchy" in r.message and "overlap" in r.message
               for r in caplog.records)


# ---------------------------------------------------------------------------
# the startup autotune: chooser determinism, fallback discipline, e2e
# ---------------------------------------------------------------------------

_SNAP = {"grad_bytes": 8 << 20,
         "bucket_bytes": [4 << 20, 4 << 20],
         "bucket_reduce_axes": ["data+fsdp", "data+fsdp"],
         "compress": "off"}


def _table(axes):
    return planner.BandwidthTable(source="probe", axes=axes,
                                  default_bps=4e8, default_latency=2e-4)


def test_tune_comm_plan_deterministic_and_ranks_hier():
    """Fast intra tier + slow-but-thin inter leg -> the staged plan wins;
    called twice on the same inputs the chooser returns the identical
    dict (the autotune-determinism contract)."""
    t = _table({"data+fsdp": (4e8, 2e-4),
                "data+fsdp:intra": (4e9, 1e-5),
                "data+fsdp:inter": (2e8, 2e-4)})
    a = planner.tune_comm_plan(_SNAP, t, intra_k=4, bucket_mb=4.0)
    b = planner.tune_comm_plan(_SNAP, t, intra_k=4, bucket_mb=4.0)
    assert a == b
    assert a["hierarchy"] == 4 and a["fallback"] is None
    assert a["axes"] == "data+fsdp"
    assert a["predicted_secs"] > 0
    # every (bucket_mb x form) candidate was costed
    assert any("/hier4" in k or k.endswith("hier4")
               for k in a["candidates"])
    # a slower intra tier than the flat fabric keeps the flat plan
    slow = _table({"data+fsdp": (4e8, 2e-4),
                   "data+fsdp:intra": (1e7, 5e-3),
                   "data+fsdp:inter": (1e7, 5e-3)})
    c = planner.tune_comm_plan(_SNAP, slow, intra_k=4, bucket_mb=4.0)
    assert c["hierarchy"] == 0 and c["fallback"] is None


def test_tune_comm_plan_requires_measured_tier_rows(caplog):
    with caplog.at_level(logging.WARNING):
        c = planner.tune_comm_plan(
            _SNAP, _table({"data+fsdp": (4e8, 2e-4)}),
            intra_k=4, bucket_mb=4.0)
    assert c["hierarchy"] == 0
    assert "no measured tier rows" in c["fallback"]
    assert any("DISABLED" in r.message for r in caplog.records)


def test_tune_comm_plan_probe_lie_falls_back_flat(caplog):
    """The seeded-probe-lie contract: an implausible tier row (1e15 B/s
    against a 4e8 B/s flat fabric) must NOT produce a hierarchical plan
    — the chooser screens tiers against TUNE_SANITY_FACTOR x flat and
    falls back flat with a loud warning."""
    lie = _table({"data+fsdp": (4e8, 2e-4),
                  "data+fsdp:intra": (1e15, 1e-12),
                  "data+fsdp:inter": (1e15, 1e-12)})
    with caplog.at_level(logging.WARNING):
        c = planner.tune_comm_plan(_SNAP, lie, intra_k=4, bucket_mb=4.0)
    assert c["hierarchy"] == 0
    assert "plausibility" in c["fallback"]
    assert any("DISABLED" in r.message for r in caplog.records)
    # compression candidates never introduce a lossy dtype the operator
    # didn't configure
    assert all("/bf16" not in k and "/fp16" not in k
               for k in c["candidates"])


# re-tiered slow (ISSUE 18): ~9 s — probe + retrace is two extra
# multi-device compiles. tune_comm_plan's choice/fallback/determinism
# contracts stay in tier-1 via the unit tests above; this leg adds the
# live probe -> retune -> mid-run rebuild wiring.
@pytest.mark.slow
def test_e2e_autotune_startup_records_tuned_plan(devices, tmp_path,
                                                 monkeypatch):
    """comm.autotune=startup on the live virtual-8 leg: the probe fires
    at the first step boundary, the chooser rewrites the plan, the step
    REBUILDS around it, and the re-traced snapshot (the comm_overlap
    row's source) records autotune=startup + tuned=True."""
    from distributed_resnet_tensorflow_tpu.telemetry import bandwidth
    monkeypatch.setenv(bandwidth.DIR_ENV, str(tmp_path))  # keep the
    # probe's catalog fold out of the committed results tree
    batches = _fixed_batches(n=4)
    tr, _, _, _ = _train(MeshConfig(data=8), batches,
                         **{"comm.autotune": "startup",
                            "telemetry.comm_timing": "true"})
    assert tr._autotune == "startup"
    snap = overlap_stats.snapshot()
    assert snap is not None
    assert snap["autotune"] == "startup" and snap["tuned"] is True


def test_autotune_without_comm_timing_degrades_loudly(caplog, devices):
    cfg = _tiny_cfg(**{"comm.autotune": "startup"})
    cfg.telemetry.comm_timing = False
    with caplog.at_level(
            logging.WARNING,
            logger="distributed_resnet_tensorflow_tpu.train.loop"):
        tr = Trainer(cfg, mesh=create_mesh(MeshConfig(data=8)))
    assert tr._autotune == "off"
    assert any("autotune" in r.message and "comm_timing" in r.message
               for r in caplog.records)


# ---------------------------------------------------------------------------
# bandwidth catalog v2: tier rows round-trip, v1 documents still load
# ---------------------------------------------------------------------------

def _probe_snapshot():
    return {
        "buckets": [{"axes": "data+fsdp", "wire_bytes": 1 << 20,
                     "probe_secs": 2e-3,
                     "wire_bytes_per_sec": (1 << 20) / 2e-3}],
        "tiers": [
            {"axes": "data+fsdp", "tier": "intra", "wire_bytes": 1 << 20,
             "probe_secs": 1e-3,
             "wire_bytes_per_sec": (1 << 20) / 1e-3},
            {"axes": "data+fsdp", "tier": "inter",
             "wire_bytes": (1 << 20) // 4, "probe_secs": 4e-3,
             "wire_bytes_per_sec": ((1 << 20) // 4) / 4e-3},
        ],
    }


def test_bandwidth_catalog_v2_tier_rows_roundtrip(tmp_path, monkeypatch):
    from distributed_resnet_tensorflow_tpu.telemetry import bandwidth
    monkeypatch.setenv(bandwidth.DIR_ENV, str(tmp_path))
    path = bandwidth.update_from_probe(_probe_snapshot())
    assert path and os.path.dirname(path) == str(tmp_path)
    doc = bandwidth.load_catalog(path)
    assert doc["schema_version"] == bandwidth.SCHEMA_VERSION == 2
    axes = doc["axes"]
    assert set(axes) == {"data+fsdp", "data+fsdp:intra",
                         "data+fsdp:inter"}
    assert axes["data+fsdp:intra"]["tier"] == "intra"
    assert axes["data+fsdp:inter"]["tier"] == "inter"
    # tier-aware lookup: exact tier row; a tiered query without a tier
    # row falls back to the flat base entry
    assert bandwidth.lookup(doc, "data+fsdp:intra") is \
        axes["data+fsdp:intra"]
    assert bandwidth.lookup(doc, "data+expert:intra") is not None
    del axes["data+fsdp:inter"]
    assert bandwidth.lookup(doc, "data+fsdp:inter") is axes["data+fsdp"]


def test_bandwidth_catalog_v1_document_still_loads(tmp_path, monkeypatch):
    from distributed_resnet_tensorflow_tpu.telemetry import bandwidth
    monkeypatch.setenv(bandwidth.DIR_ENV, str(tmp_path))
    v1 = {"schema_version": 1, "fabric": "cpu-8", "platform": "cpu",
          "device_kind": "cpu", "devices": 8,
          "axes": {"data+fsdp": {"bytes_per_sec": 5e8,
                                 "latency_secs": 2e-4, "samples": 3,
                                 "min_wire_bytes": 1024,
                                 "max_wire_bytes": 4096}}}
    p = tmp_path / "cpu-8.json"
    p.write_text(json.dumps(v1))
    doc = bandwidth.load_catalog(str(p))
    assert doc is not None
    assert bandwidth.lookup(doc, "data+fsdp")["bytes_per_sec"] == 5e8
    # a tiered query on a v1 document answers with the flat row
    assert bandwidth.lookup(doc, "data+fsdp:intra")["bytes_per_sec"] == 5e8
    # the first fold on this document stamps the schema forward and adds
    # the tier rows
    path = bandwidth.update_from_probe(_probe_snapshot(), path=str(p))
    doc2 = bandwidth.load_catalog(path)
    assert doc2["schema_version"] == 2
    assert "data+fsdp:intra" in doc2["axes"]
    assert doc2["axes"]["data+fsdp"]["samples"] == 4  # ratchet-merged
