"""Fault-injection harness — makes every resilience behavior testable.

Nothing in the reference could SIMULATE a failure; the fault-tolerance story
was therefore untested by construction (SURVEY.md §4.4). This module is the
missing chaos tooling, used by tests/test_resilience.py and
scripts/chaos_smoke.sh:

  * :func:`deliver_signal_after` / :class:`SignalAfter` — deliver a signal
    to this (or a child) process mid-run from a timer thread.
  * :func:`corrupt_checkpoint` — tear a COMMITTED checkpoint the way real
    failures do: truncate the largest payload file (torn write / full disk)
    or flip a byte in place (bit rot), leaving the manifest stale.
  * :func:`inject_nan` — wrap a training iterator so the N-th batch carries
    non-finite pixels, driving a genuine NaN loss through the real model.
  * :func:`inject_freeze` / :func:`inject_slow` — the watchdog's fault
    menu (resilience/watchdog.py): a process that WEDGES at the N-th batch
    (main thread blocked, heartbeats still flowing — the hung-collective
    shape) and a process that keeps running but ``delay_secs`` slower per
    batch (the straggler shape). A killed peer needs no wrapper: SIGKILL
    via :func:`deliver_signal_after` with the child's pid.
  * :func:`maybe_wrap_from_env` — env-var triggers
    (``DRT_FAULT_NAN_AT_BATCH``, ``DRT_FAULT_FREEZE_AT_BATCH``,
    ``DRT_FAULT_SLOW_BATCH_SECS``) so subprocess tests and chaos scripts
    can inject through the unmodified ``main.py`` CLI. The watchdog
    triggers accept an optional ``<process_id>:`` prefix ("1:40" = only
    process 1 freezes at batch 40) — fault exactly one member of a
    launched world even though the launcher hands every child the same
    environment.

Injection is opt-in and inert by default; none of this runs unless a test or
operator asks for it.
"""
from __future__ import annotations

import logging
import os
import signal as _signal
import threading
import time
from typing import Dict, Iterator, Optional

import numpy as np

log = logging.getLogger(__name__)

NAN_ENV_VAR = "DRT_FAULT_NAN_AT_BATCH"
FREEZE_ENV_VAR = "DRT_FAULT_FREEZE_AT_BATCH"
SLOW_ENV_VAR = "DRT_FAULT_SLOW_BATCH_SECS"
CKPT_COMMIT_SLEEP_ENV_VAR = "DRT_FAULT_CKPT_COMMIT_SLEEP_SECS"
CKPT_COMMIT_MARKER_ENV_VAR = "DRT_FAULT_CKPT_COMMIT_MARKER"


# -- signals ----------------------------------------------------------------

def deliver_signal_after(delay_secs: float, sig: int = _signal.SIGTERM,
                         pid: Optional[int] = None) -> threading.Timer:
    """Arm a timer that delivers ``sig`` to ``pid`` (default: this process)
    after ``delay_secs``. Returns the started Timer (cancel() to disarm)."""
    target = os.getpid() if pid is None else pid

    def fire():
        try:
            os.kill(target, sig)
        except (ProcessLookupError, PermissionError) as e:
            log.warning("fault injection: signal %s to pid %d failed: %s",
                        sig, target, e)

    t = threading.Timer(delay_secs, fire)
    t.daemon = True
    t.start()
    return t


class SignalAfter:
    """Context manager over :func:`deliver_signal_after` that disarms on
    exit, so a test that finishes early doesn't shoot the next one."""

    def __init__(self, delay_secs: float, sig: int = _signal.SIGTERM,
                 pid: Optional[int] = None):
        self._args = (delay_secs, sig, pid)
        self._timer: Optional[threading.Timer] = None

    def __enter__(self) -> "SignalAfter":
        self._timer = deliver_signal_after(*self._args)
        return self

    def __exit__(self, *exc) -> None:
        if self._timer is not None:
            self._timer.cancel()


# -- checkpoint damage ------------------------------------------------------

def _largest_payload(step_dir: str) -> str:
    from .manifest import MANIFEST_NAME
    best, best_size = None, -1
    for dirpath, _dirs, files in os.walk(step_dir):
        for name in files:
            if name == MANIFEST_NAME:
                continue
            full = os.path.join(dirpath, name)
            size = os.path.getsize(full)
            if size > best_size:
                best, best_size = full, size
    if best is None:
        raise FileNotFoundError(f"no payload files under {step_dir}")
    return best


def corrupt_checkpoint(directory: str, step: Optional[int] = None,
                       mode: str = "truncate") -> int:
    """Damage a committed checkpoint in place (default: the latest).

    ``mode="truncate"`` drops the second half of the largest payload file —
    the shape of a torn write; ``mode="flip"`` inverts one byte mid-file
    with the size unchanged — the shape of bit rot, catchable only by
    checksum. Returns the damaged step."""
    from .manifest import committed_steps
    steps = committed_steps(directory)
    if not steps:
        raise FileNotFoundError(f"no committed checkpoints in {directory}")
    step = steps[-1] if step is None else step
    if step not in steps:
        raise FileNotFoundError(f"step {step} not committed in {directory}")
    victim = _largest_payload(os.path.join(directory, str(step)))
    size = os.path.getsize(victim)
    if mode == "truncate":
        with open(victim, "r+b") as f:
            f.truncate(max(0, size // 2))
    elif mode == "flip":
        if size == 0:
            raise ValueError(f"{victim} is empty; nothing to flip")
        with open(victim, "r+b") as f:
            f.seek(size // 2)
            byte = f.read(1)
            f.seek(size // 2)
            f.write(bytes([byte[0] ^ 0xFF]))
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    log.info("fault injection: %s %s (step %d, %d bytes)",
             mode, victim, step, size)
    return step


def maybe_delay_ckpt_commit(step: int) -> None:
    """Env-armed nap in the checkpoint writer BETWEEN staging and the
    manifest/rename commit (checkpoint/manager._write calls this; inert
    unless ``DRT_FAULT_CKPT_COMMIT_SLEEP_SECS`` is set) — the
    kill-during-async-commit window: the staging dir is fully written but
    UNCOMMITTED, so a SIGKILL here must leave restore on the newest
    committed step with the torn staging dir swept at the next manager
    construction (tests/test_checkpoint.py's subprocess case).
    ``DRT_FAULT_CKPT_COMMIT_MARKER`` names a file appended with the step
    at nap start, so the killing test knows the writer is in the window
    (and the charge-split test knows the writer thread, not the loop,
    paid the nap)."""
    raw = os.environ.get(CKPT_COMMIT_SLEEP_ENV_VAR, "")
    if not raw:
        return
    try:
        secs = float(raw)
    except ValueError:
        log.warning("ignoring malformed %s=%r",
                    CKPT_COMMIT_SLEEP_ENV_VAR, raw)
        return
    marker = os.environ.get(CKPT_COMMIT_MARKER_ENV_VAR, "")
    if marker:
        with open(marker, "a") as f:
            f.write(f"{step}\n")
            f.flush()
            os.fsync(f.fileno())  # shardcheck: ok(ckpt-io-thread)
    log.warning("fault injection: checkpoint writer napping %.1fs before "
                "the step-%d commit (%s)", secs, step,
                CKPT_COMMIT_SLEEP_ENV_VAR)
    time.sleep(secs)


# -- NaN loss ---------------------------------------------------------------

def inject_nan(data_iter: Iterator[Dict], at_batch: int,
               key: str = "images") -> Iterator[Dict]:
    """Yield batches unchanged except the ``at_batch``-th (1-based), whose
    ``key`` entry is replaced with NaNs — the loss of that step is then
    genuinely non-finite through the whole real model/optimizer path.

    Batches without ``key`` (e.g. device-resident ``{"idx"}`` batches) pass
    through untouched; NaN injection needs the streamed-image path."""
    if at_batch < 1:
        raise ValueError(f"at_batch is 1-based, got {at_batch}")
    count = 0
    for batch in data_iter:
        count += 1
        if count == at_batch and key in batch:
            poisoned = dict(batch)
            poisoned[key] = np.full_like(
                np.asarray(batch[key], dtype=np.float32), np.nan)
            log.warning("fault injection: batch %d %r poisoned with NaN",
                        count, key)
            yield poisoned
        else:
            yield batch


# -- wedged / slow processes (watchdog fault cases) -------------------------

def inject_freeze(data_iter: Iterator[Dict], at_batch: int,
                  freeze_secs: float = 3600.0) -> Iterator[Dict]:
    """Block (in the consumer's thread) before yielding the ``at_batch``-th
    batch — the hung-collective / wedged-device shape: the main thread
    stops making progress while the heartbeat daemon keeps beating, so
    peers see a live-but-frozen process and the LOCAL watchdog sees a
    stalled progress counter. ``freeze_secs`` bounds the nap so an
    undetected freeze still ends (tests/CI must never rely on that)."""
    if at_batch < 1:
        raise ValueError(f"at_batch is 1-based, got {at_batch}")
    count = 0
    for batch in data_iter:
        count += 1
        if count == at_batch:
            log.warning("fault injection: freezing before batch %d for "
                        "up to %.0fs", count, freeze_secs)
            time.sleep(freeze_secs)
        yield batch


def inject_slow(data_iter: Iterator[Dict], delay_secs: float,
                from_batch: int = 1) -> Iterator[Dict]:
    """Delay every batch from the ``from_batch``-th on by ``delay_secs``
    — the persistent-straggler shape: the process keeps up with every
    collective, just late, which is exactly what the watchdog's per-host
    step-rate accounting (``{"event": "straggler"}`` rows) and the
    perf-anomaly sentinel (``{"event": "perf_anomaly"}``) exist to
    surface. The default onset (batch 1) is the from-the-start straggler;
    a later onset (the ``S@N`` env form) gives the sentinel a healthy
    baseline window first — the slow-REGIME-change shape a median+MAD
    outlier detector is built for."""
    if delay_secs < 0:
        raise ValueError(f"delay_secs must be >= 0, got {delay_secs}")
    if from_batch < 1:
        raise ValueError(f"from_batch is 1-based, got {from_batch}")
    count = 0
    for batch in data_iter:
        count += 1
        if count >= from_batch:
            time.sleep(delay_secs)
        yield batch


def _parse_slow(value: str):
    """``"S"`` or ``"S@N"`` → (delay_secs, from_batch). Raises ValueError
    on junk — including ``N < 1`` (from_batch is 1-based) — so the shared
    scoped-env path logs and disarms instead of arming a wrapper that
    would blow up mid-training on its first batch."""
    if "@" in value:
        secs, _, start = value.partition("@")
        if int(start) < 1:
            raise ValueError(f"from_batch is 1-based, got {start}")
        return float(secs), int(start)
    return float(value), 1


def _parse_scoped(value: str, env_var: str,
                  process_id: Optional[int]) -> Optional[str]:
    """Parse ``"<value>"`` or ``"<pid>:<value>"``; returns the value when
    this process is targeted, else None."""
    if ":" in value:
        target, _, rest = value.partition(":")
        try:
            if process_id != int(target):
                return None
        except ValueError:
            log.warning("ignoring malformed %s=%r", env_var, value)
            return None
        return rest
    return value


def _scoped_env_value(environ, env_var: str, process_id: Optional[int],
                      convert):
    """The shared read→scope→convert path of the ``[pid:]value`` watchdog
    faults; None when unset, scoped to another process, or malformed."""
    raw = environ.get(env_var, "")
    if not raw:
        return None
    scoped = _parse_scoped(raw, env_var, process_id)
    if not scoped:
        return None
    try:
        return convert(scoped)
    except ValueError:
        log.warning("ignoring malformed %s=%r", env_var, raw)
        return None


# -- serving-replica faults (fleet chaos: serve/server.py dispatch path) ----

SERVE_WEDGE_ENV_VAR = "DRT_FAULT_SERVE_WEDGE_AT_BATCH"
SERVE_SLOW_ENV_VAR = "DRT_FAULT_SERVE_SLOW_MS"


def _parse_slow_ms(value: str):
    """``"MS"`` or ``"MS@STEP"`` → (delay_ms, from_step). STEP is a
    CHECKPOINT step (0 = always), not a batch index: the late-onset form
    exists to poison whichever replicas serve a given published step."""
    if "@" in value:
        ms, _, step = value.partition("@")
        return float(ms), int(step)
    return float(value), 0


class ServeFaults:
    """Env-armed faults on a serving replica's DISPATCH thread, fired at
    the top of every batch (serve/server.py ``_run_bucket``) — the fleet
    chaos menu scripts/serve_fleet_smoke.sh and the slow tier drive:

      * ``DRT_FAULT_SERVE_WEDGE_AT_BATCH=[rid:]N`` — block (bounded)
        before dispatching the N-th batch: the wedged-dispatch shape.
        The process stays alive and its heartbeat daemon keeps beating,
        so only request failures/timeouts can condemn it — exactly the
        alive-but-useless case the router health machine + fleet
        supervisor exist for.
      * ``DRT_FAULT_SERVE_SLOW_MS=[rid:]MS[@STEP]`` — MS extra per batch
        once ``serving_step >= STEP``. Set fleet-wide with ``@STEP`` at
        an upcoming checkpoint step, ONLY the replicas pinned to that
        step slow down — a p99-regressing canary checkpoint without
        touching the checkpoint bytes.

    The ``rid:`` prefix scopes to one replica id (``serve.replica_id``),
    mirroring the training faults' ``pid:`` scoping."""

    def __init__(self, wedge_at_batch: Optional[int] = None,
                 slow_ms: float = 0.0, slow_from_step: int = 0,
                 freeze_secs: float = 3600.0):
        self.wedge_at_batch = wedge_at_batch
        self.slow_ms = slow_ms
        self.slow_from_step = slow_from_step
        self.freeze_secs = freeze_secs
        self._wedged = False

    @classmethod
    def from_env(cls, replica_id: Optional[int] = None,
                 env: Optional[Dict[str, str]] = None) -> "ServeFaults":
        environ = os.environ if env is None else env
        rid = replica_id if replica_id is not None and replica_id >= 0 \
            else None
        wedge = _scoped_env_value(environ, SERVE_WEDGE_ENV_VAR, rid, int)
        if wedge is not None and wedge < 1:
            wedge = None
        slow = _scoped_env_value(environ, SERVE_SLOW_ENV_VAR, rid,
                                 _parse_slow_ms)
        slow_ms, slow_from = slow if slow is not None else (0.0, 0)
        out = cls(wedge_at_batch=wedge, slow_ms=max(0.0, slow_ms),
                  slow_from_step=slow_from)
        if out.armed:
            log.warning("fault injection armed (serve replica %s): "
                        "wedge_at_batch=%s slow_ms=%s from_step=%s",
                        rid, out.wedge_at_batch, out.slow_ms,
                        out.slow_from_step)
        return out

    @property
    def armed(self) -> bool:
        return self.wedge_at_batch is not None or self.slow_ms > 0

    def maybe_fire(self, batch_index: int, serving_step: int) -> None:
        """Called per dispatch batch (1-based index) with the step the
        batch will serve. Wedge fires at most once per process."""
        if (self.wedge_at_batch is not None and not self._wedged
                and batch_index >= self.wedge_at_batch):
            self._wedged = True
            log.warning("fault injection: serve dispatch wedging at batch "
                        "%d for up to %.0fs", batch_index, self.freeze_secs)
            time.sleep(self.freeze_secs)
        if self.slow_ms > 0 and serving_step >= self.slow_from_step:
            time.sleep(self.slow_ms / 1000.0)


_nan_armed = False
_freeze_armed = False


def maybe_wrap_from_env(data_iter: Iterator[Dict],
                        env: Optional[Dict[str, str]] = None) -> Iterator[Dict]:
    """Apply the env-var-armed fault wrappers — the hook main.py's train
    source passes through so subprocess tests / chaos scripts can inject
    without patching code: ``DRT_FAULT_NAN_AT_BATCH=N`` (NaN images at
    batch N), ``DRT_FAULT_FREEZE_AT_BATCH=[pid:]N`` (wedge at batch N),
    ``DRT_FAULT_SLOW_BATCH_SECS=[pid:]S[@N]`` (S seconds extra per
    batch, from batch N on — the late onset gives the perf-anomaly
    sentinel a healthy baseline window first).
    The optional ``pid:`` prefix scopes a fault to one process of a
    multi-process world.

    The NaN and freeze faults arm at most ONCE per process: the NaN
    sentinel rebuilds the train source after a rollback, and re-poisoning
    the rebuilt stream would turn one injected fault into an unrecoverable
    run (for freeze, a recurring wedge at the same batch of the replayed
    stream). The slow fault deliberately re-arms — it simulates a
    persistently slow HOST, and the wrapper does not nest on rebuild."""
    global _nan_armed, _freeze_armed
    environ = os.environ if env is None else env
    process_id = None
    freeze_val = environ.get(FREEZE_ENV_VAR, "")
    slow_val = environ.get(SLOW_ENV_VAR, "")
    if ":" in freeze_val or ":" in slow_val:
        import jax
        process_id = jax.process_index()
    at_batch = _scoped_env_value(environ, FREEZE_ENV_VAR, process_id, int)
    if at_batch is not None and at_batch >= 1 and not _freeze_armed:
        _freeze_armed = True
        log.warning("fault injection armed: freeze at batch %d (%s)",
                    at_batch, FREEZE_ENV_VAR)
        data_iter = inject_freeze(data_iter, at_batch)
    slow = _scoped_env_value(environ, SLOW_ENV_VAR, process_id, _parse_slow)
    if slow is not None and slow[0] > 0:
        delay, from_batch = slow
        log.warning("fault injection armed: +%.3fs per batch from batch "
                    "%d (%s)", delay, from_batch, SLOW_ENV_VAR)
        data_iter = inject_slow(data_iter, delay, from_batch=from_batch)
    value = environ.get(NAN_ENV_VAR, "")
    if not value or _nan_armed:
        return data_iter
    _nan_armed = True
    try:
        at_batch = int(value)
    except ValueError:
        log.warning("ignoring malformed %s=%r", NAN_ENV_VAR, value)
        return data_iter
    if at_batch < 1:
        return data_iter
    log.warning("fault injection armed: NaN images at batch %d (%s)",
                at_batch, NAN_ENV_VAR)
    return inject_nan(data_iter, at_batch)
