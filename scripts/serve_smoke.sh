#!/bin/bash
# Serving smoke — the end-to-end proof of the serve/ subsystem on CPU
# (docs/serving.md), pre-merge usable like scripts/analysis_gate.sh /
# chaos_smoke.sh --fast: exit 0 = the whole story holds, nonzero = broken.
#
#   1. train a few steps -> a committed checkpoint (manifest protocol);
#   2. start `main.py serve` with the dispatch sanitizer ARMED (the PR 5
#      guard rail for the batcher/swap threads) and the open-loop load
#      generator driving it;
#   3. publish a NEWER checkpoint mid-load (resumed training);
#   4. assert from the report + metrics.jsonl: the new checkpoint was
#      HOT-SWAPPED in (serve_swap event, swaps >= 1), ZERO requests were
#      dropped, zero request-time compiles (AOT cache held), zero errors.
#
#   scripts/serve_smoke.sh [workdir]     # default: fresh mktemp dir
#
# Runs in ~2-4 minutes on one CPU core (three short jax processes; the
# serve process keeps serving until the swap lands — serve.wait_for_swap_secs).
set -euo pipefail
cd "$(dirname "$0")/.."

ROOT="${1:-$(mktemp -d /tmp/drt_serve_smoke.XXXXXX)}"
echo "serve smoke workdir: $ROOT"

# seconds-fast shardcheck first (analysis_gate.sh pattern): the serve step
# is statically elaborated per bucket — spec bugs die here, not mid-smoke
scripts/analysis_gate.sh --preset smoke

SHRINK=(--preset smoke
        --set model.resnet_size=8 --set model.compute_dtype=float32
        --set data.image_size=8 --set train.batch_size=16
        --set data.eval_batch_size=16
        --set "log_root=$ROOT" --set "checkpoint.directory=$ROOT/ckpt"
        --set checkpoint.async_save=false
        --set checkpoint.save_every_secs=0
        --set checkpoint.save_every_steps=2)

# 1) train 2 steps -> committed checkpoint step 2
env JAX_PLATFORMS=cpu python -m distributed_resnet_tensorflow_tpu.main \
  "${SHRINK[@]}" --set train.train_steps=2

# 2) serve under open-loop load, sanitizer armed; report JSON on stdout
env JAX_PLATFORMS=cpu python -m distributed_resnet_tensorflow_tpu.main \
  serve "${SHRINK[@]}" \
  --set analysis.dispatch_sanitizer=true \
  --set serve.load_qps=25 --set serve.load_duration_secs=45 \
  --set serve.max_queue_delay_ms=10 --set serve.poll_interval_secs=1 \
  --set serve.wait_for_swap_secs=180 \
  > "$ROOT/serve_report.json" &
SERVE_PID=$!

# wait for the server's READY marker (written after the initial restore —
# a checkpoint published before it would be picked up at startup, and the
# smoke would prove nothing about HOT swap)
for _ in $(seq 1 360); do
  [[ -f "$ROOT/serve/READY" ]] && break
  kill -0 "$SERVE_PID" 2>/dev/null || { echo "serve process died during startup"; exit 1; }
  sleep 0.5
done
[[ -f "$ROOT/serve/READY" ]] || { echo "server never became ready"; kill "$SERVE_PID"; exit 1; }

# 3) publish a NEW checkpoint mid-load: resume training to step 4
env JAX_PLATFORMS=cpu python -m distributed_resnet_tensorflow_tpu.main \
  "${SHRINK[@]}" --set train.train_steps=4

wait "$SERVE_PID"

# 4) assertions over the report + the serve metrics stream
python - "$ROOT" <<'EOF'
import json, os, sys
root = sys.argv[1]
rep = json.loads(open(os.path.join(root, "serve_report.json"))
                 .read().strip().splitlines()[-1])
assert rep["swaps"] >= 1, f"no hot swap happened: {rep}"
assert rep["serving_step"] >= 3, \
    f"server never reached the mid-load checkpoint: {rep}"
assert rep["dropped"] == 0, f"dropped requests: {rep}"
assert rep["errors"] == 0, f"dispatch errors: {rep}"
assert rep["compile"]["serve_time_compiles"] == 0, \
    f"a request paid a compile: {rep}"
assert rep["requests"] > 0 and rep["completed"] == rep["requests"], rep
events = [json.loads(l) for l in
          open(os.path.join(root, "serve", "metrics.jsonl")) if l.strip()]
# from_step >= 0: a GENUINE hot swap (old checkpoint -> new), not the
# startup restore (from_step=-1) — the vacuous-pass trap
assert any(e.get("event") == "serve_swap" and e.get("from_step", -1) >= 0
           and "to_step" in e for e in events), \
    "no hot serve_swap event in metrics.jsonl"
assert any(e.get("event") == "serve_batch" for e in events), \
    "no serve_batch events in metrics.jsonl"
print("serve smoke OK:", json.dumps(
    {k: rep[k] for k in ("serving_step", "requests", "dropped", "swaps",
                         "qps")}))
EOF
