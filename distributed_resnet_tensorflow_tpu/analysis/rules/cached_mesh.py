"""cached-mesh: no lru_cache/cache on functions that can receive Mesh or
device objects.

The PR 1 leak: an ``functools.lru_cache`` keyed (even transitively) on a
``jax.sharding.Mesh`` pins the mesh AND its device arrays for the process
lifetime — in sessions that build many meshes (tests, notebooks, per-round
benches) that is an unbounded leak. The codebase's pattern is a weak-key
``WeakKeyDictionary`` memo instead (parallel/mesh.py process_batch_slice,
parallel/sharding.py _UNPACK_CACHE). This rule flags
``functools.lru_cache``/``functools.cache`` decorating (or directly
wrapping) a function whose parameter names or annotations say it can hold
a mesh/device/sharding.
"""
from __future__ import annotations

import ast
from typing import Iterable, Optional

from ..report import Finding

RULE_NAME = "cached-mesh"
DOC = __doc__

# a parameter named (or annotated) like these can hold device-pinning state
_SUSPECT_TOKENS = ("mesh", "device", "sharding")


def _cache_decorator(node: ast.expr) -> Optional[str]:
    """'lru_cache'/'cache' when the expression is that decorator (bare,
    attribute, or called form); None otherwise."""
    target = node.func if isinstance(node, ast.Call) else node
    if isinstance(target, ast.Attribute) and \
            target.attr in ("lru_cache", "cache"):
        return target.attr
    if isinstance(target, ast.Name) and target.id in ("lru_cache", "cache"):
        return target.id
    return None


def _suspect_param(fn) -> Optional[str]:
    """First suspect parameter of a FunctionDef/AsyncFunctionDef/Lambda."""
    args = list(fn.args.posonlyargs) + list(fn.args.args) + \
        list(fn.args.kwonlyargs)
    for a in args:
        name = a.arg.lower()
        ann = ast.dump(a.annotation).lower() \
            if getattr(a, "annotation", None) else ""
        for tok in _SUSPECT_TOKENS:
            if tok in name or tok in ann:
                return a.arg
    return None


def _finding(sf, lineno: int, kind: str, fn_name: str,
             param: str) -> Finding:
    return Finding(
        RULE_NAME, sf.rel, lineno,
        f"functools.{kind} on {fn_name}() whose parameter {param!r} can "
        "hold a Mesh/device — this pins device arrays for the process "
        "lifetime; use a WeakKeyDictionary memo "
        "(parallel/mesh.py process_batch_slice)")


def check(ctx) -> Iterable[Finding]:
    for sf in ctx.all_python():
        if sf.tree is None:
            continue
        # module-level functions by name, for resolving the direct-wrap
        # form `memo = lru_cache(...)(make_fn)`
        fn_defs = {n.name: n for n in ast.walk(sf.tree)
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    kind = _cache_decorator(dec)
                    if kind is None:
                        continue
                    param = _suspect_param(node)
                    if param is not None:
                        yield _finding(sf, dec.lineno, kind, node.name,
                                       param)
            elif isinstance(node, ast.Call) and len(node.args) == 1:
                # direct wrap: lru_cache(...)(fn) / cache(fn)
                kind = _cache_decorator(node.func) \
                    if isinstance(node.func, ast.Call) else \
                    _cache_decorator(node)
                if kind is None:
                    continue
                target = node.args[0]
                wrapped = None
                if isinstance(target, ast.Name):
                    wrapped = fn_defs.get(target.id)
                elif isinstance(target, ast.Lambda):
                    wrapped = target
                if wrapped is None:
                    continue
                param = _suspect_param(wrapped)
                if param is not None:
                    name = getattr(wrapped, "name", "<lambda>")
                    yield _finding(sf, node.lineno, kind, name, param)
