"""Fault-tolerance subsystem: preemption, crash-consistent checkpoints,
NaN rollback, bounded retries, and the fault-injection harness.

The reference framework assumed a clean world — SLURM restarts on failure
and ``tf.train.Saver`` hopefully left something usable (SURVEY.md §2.14,
§4.4). At target scale (ImageNet in minutes over large meshes,
arXiv:1811.05233 / arXiv:1802.05799) preemptions, torn writes, and loss
blow-ups are the COMMON case; this package makes each one a handled,
tested code path. See docs/resilience.md for the protocols and the
launcher exit-code contract.
"""
from .manifest import (  # noqa: F401
    committed_steps, manifest_status, write_manifest)
from .preemption import (  # noqa: F401
    FAILURE_EXIT_CODE, INTERRUPT_EXIT_CODE, Preempted, PreemptionListener,
    RESUMABLE_EXIT_CODE)
from .retry import retry_call  # noqa: F401

#: The process exit-code contract (docs/resilience.md): the ONLY codes this
#: framework deliberately exits with, and what each one tells the launcher
#: (launch.py, scripts/submit_tpu_slurm.sh). Any ``sys.exit``/``os._exit``/
#: ``raise SystemExit`` with an integer literal outside this registry —
#: including literals flowing out of functions whose return value feeds a
#: ``sys.exit(...)`` — is linter-rejected (analysis/rules/exit_codes.py:
#: exit-code-contract) — new codes are a LAUNCHER PROTOCOL CHANGE and must
#: be declared here + documented first.
EXIT_CONTRACT = {
    0: "success — run completed",
    RESUMABLE_EXIT_CODE: "resumable (EX_TEMPFAIL): checkpoint committed "
                         "(preemption / peer loss / hang teardown) — "
                         "requeue to resume",
    FAILURE_EXIT_CODE: "real failure — do not requeue",
    INTERRUPT_EXIT_CODE: "operator ^C at the launcher (128+SIGINT) — "
                         "deliberate stop: do not requeue, do not "
                         "classify as a failure",
}

# sentinel (and faultinject) are NOT re-exported eagerly: sentinel imports
# the train stack (and thus jax), and this package is imported by
# launch.py, which only needs the stdlib-light preemption constants —
# import from resilience.sentinel / resilience.faultinject directly.
