from .config import (  # noqa: F401
    CheckpointConfig,
    DataConfig,
    EvalConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    PRESETS,
    TrainConfig,
    get_preset,
    parse_args,
)
