from .manager import CheckpointManager, wait_for_new_checkpoint  # noqa: F401
