"""Generate a structured, learnable dataset in CIFAR-10 binary format.

This environment has no network egress, so the real CIFAR-10 binaries cannot
be fetched. This tool writes a stand-in with the exact on-disk format
(reference resnet_cifar_main.py:137-154: data_batch_{1..5}.bin /
test_batch.bin, records = [1 label byte][3072 CHW bytes]) whose classes ARE
learnable — each class is a radial grating with a class-specific spatial
frequency and RGB channel mix, under heavy pixel noise and random phase —
so a truncated training run demonstrates the full
files → loader → device-dataset → augment → train → eval convergence loop.
The class signal survives the training augmentation by construction:
horizontal flips and ±4-pixel crops barely perturb a centered radial
pattern, and per-image standardization removes only mean/scale.

Swap in the real CIFAR-10 binaries and every command runs unchanged.

Usage: python tools/make_synth_cifar.py [out_dir] [--train N] [--test N]
       python tools/make_synth_cifar.py out_dir --format cifar100  # train.bin/
           test.bin with [coarse, fine] label bytes, 100 learnable fine
           classes coded by (radial frequency × angular harmonic × channel
           mix) — all survive per-image standardization and ±4-crop/flip
"""
from __future__ import annotations

import argparse
import os

import numpy as np

NUM_CLASSES = 10


def class_images(cls: int, n: int, rng: np.random.RandomState,
                 num_classes: int = NUM_CLASSES) -> np.ndarray:
    """(n, 32, 32, 3) uint8 images for one class.

    10-class coding: 5 radial frequencies × 2 channel mixes. 100-class
    coding adds a 5-level angular harmonic (cos kθ, scale-invariant and
    |·|-preserved under flips): (cls%10) frequencies × ((cls//10)%5)
    harmonics × (cls//50) mixes."""
    yy, xx = np.mgrid[0:32, 0:32]
    r = np.sqrt((yy - 15.5) ** 2 + (xx - 15.5) ** 2)          # (32, 32)
    theta = np.arctan2(yy - 15.5, xx - 15.5)
    if num_classes <= 10:
        freq = 0.10 + 0.018 * (cls % 5)
        harmonic = np.ones_like(theta)
        w = np.array([[1.0, 0.5, -0.2], [0.5, 1.0, 0.2]][cls // 5])
    else:
        freq = 0.08 + 0.016 * (cls % 10)                       # 10 frequencies
        k = (cls // 10) % 5                                    # 5 harmonics
        harmonic = 1.0 + 0.6 * np.cos(k * theta)
        w = np.array([[1.0, 0.5, -0.2], [0.5, 1.0, 0.2]][cls // 50])
    phase = rng.uniform(0, 2 * np.pi, size=(n, 1, 1))
    base = np.cos(2 * np.pi * freq * r[None] + phase) * harmonic[None]
    img = (128.0 + 18.0 * base[..., None] * w[None, None, None, :]
           + rng.normal(0, 40.0, (n, 32, 32, 3)))
    return np.clip(img, 0, 255).astype(np.uint8)


def make_split(n: int, seed: int,
               num_classes: int = NUM_CLASSES) -> tuple[np.ndarray, np.ndarray]:
    rng = np.random.RandomState(seed)
    per = n // num_classes
    images = np.concatenate(
        [class_images(c, per, rng, num_classes) for c in range(num_classes)])
    labels = np.repeat(np.arange(num_classes), per).astype(np.uint8)
    order = rng.permutation(len(labels))
    return images[order], labels[order]


def write_cifar100_files(out_dir: str, images: np.ndarray,
                         labels: np.ndarray, name: str) -> None:
    """cifar100 binary layout: [coarse byte][fine byte][3072 CHW bytes]
    (data/cifar.py reads the fine byte at offset 1)."""
    os.makedirs(out_dir, exist_ok=True)
    recs = np.empty((len(labels), 2 + 3072), np.uint8)
    recs[:, 0] = labels // 5   # a consistent 20-group coarse labeling
    recs[:, 1] = labels
    recs[:, 2:] = images.transpose(0, 3, 1, 2).reshape(len(labels), -1)
    recs.tofile(os.path.join(out_dir, name))


def write_cifar_files(out_dir: str, images: np.ndarray, labels: np.ndarray,
                      names: list[str]) -> None:
    os.makedirs(out_dir, exist_ok=True)
    shards = np.array_split(np.arange(len(labels)), len(names))
    for name, idx in zip(names, shards):
        recs = np.empty((len(idx), 1 + 3072), np.uint8)
        recs[:, 0] = labels[idx]
        # NHWC → CHW planes, the CIFAR binary layout
        recs[:, 1:] = images[idx].transpose(0, 3, 1, 2).reshape(len(idx), -1)
        recs.tofile(os.path.join(out_dir, name))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("out_dir", nargs="?", default="/tmp/drt_synth_cifar10")
    ap.add_argument("--train", type=int, default=50000)
    ap.add_argument("--test", type=int, default=10000)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--format", choices=("cifar10", "cifar100"),
                    default="cifar10")
    args = ap.parse_args()
    nc = 100 if args.format == "cifar100" else NUM_CLASSES
    tr_im, tr_lb = make_split(args.train, args.seed, nc)
    te_im, te_lb = make_split(args.test, args.seed + 1, nc)
    if args.format == "cifar100":
        write_cifar100_files(args.out_dir, tr_im, tr_lb, "train.bin")
        write_cifar100_files(args.out_dir, te_im, te_lb, "test.bin")
    else:
        write_cifar_files(args.out_dir, tr_im, tr_lb,
                          [f"data_batch_{i}.bin" for i in range(1, 6)])
        write_cifar_files(args.out_dir, te_im, te_lb, ["test_batch.bin"])
    print(f"wrote {args.train} train + {args.test} test {args.format} "
          f"records to {args.out_dir}")


if __name__ == "__main__":
    main()
