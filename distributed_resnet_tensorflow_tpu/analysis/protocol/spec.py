"""Declared control-plane protocol models.

The repo runs four hand-rolled distributed protocols — the elastic
reshard barrier, the crash-consistent sharded-checkpoint commit, the
replica health/replace ladder, and the canary swap-control pin — and
each had been verified only by example-based chaos smokes that explore a
handful of interleavings. This module is the declaration side of the
protocol checker (docs/static_analysis.md, protocol models): every
protocol registers a :class:`ProtocolSpec` **co-located with its
implementation** (the way ``THREAD_ROLES`` and ``EVENT_SCHEMAS`` are),
carrying

  * the abstract state machine itself (:class:`Model`): an initial
    state, an enabled-actions function (including crash/restart and
    message/file-loss actions), safety invariants, and liveness goals —
    explored exhaustively by ``analysis/protocol/checker.py`` over ALL
    interleavings at declared small-scope bounds;
  * the declared runtime edge tables (``event_edges``) the trace
    conformance replayer validates recorded metrics rows against;
  * the implementation literals (state strings, marker-file names,
    control-file fields) the ``protocol-drift`` lint rule resolves
    against the modeled source files, so the model cannot silently
    diverge from the code it models;
  * the seeded mutations the spec supports — named guard-weakenings
    (a dropped commit-marker wait, an illegal health edge, a blind
    commit overwrite) that tests inject to prove the checker actually
    catches the bug class the guard exists to prevent.

Everything here is stdlib-only and import-light: implementation modules
import THIS module at load time (to register their spec), and the
checker imports the implementation modules lazily via
:func:`load_specs`.

Model states must be hashable trees of primitives with a deterministic
``repr`` (tuples, strings, ints, bools, None — no sets/frozensets):
the committed ``protocol_models.json`` fingerprint hashes sorted state
and edge reprs and must be byte-identical across runs.
"""
from __future__ import annotations

import os
import sys
from dataclasses import dataclass, field
from typing import Callable, Dict, FrozenSet, Iterable, Mapping, Tuple

#: liveness kinds — ``eventually`` means "from EVERY reachable state a
#: goal state stays reachable" (no livelock traps; fairness-free, so an
#: unfair retry loop does not count as a violation), ``reachable`` means
#: "some schedule reaches the goal from the initial state" (the protocol
#: CAN succeed at these bounds, e.g. a commit actually happens).
LIVENESS_KINDS = ("eventually", "reachable")


@dataclass(frozen=True)
class Model:
    """One explorable protocol instance at fixed small-scope bounds.

    ``actions(state)`` returns every enabled ``(label, next_state)``
    pair; the checker owns the interleaving (it tries them all).
    ``invariants`` are safety properties — ``fn(state) -> True`` on
    every reachable state or the shortest violating action schedule is
    the counterexample. ``liveness`` entries are
    ``(name, kind, goal_fn)`` with ``kind`` in :data:`LIVENESS_KINDS`.
    """

    init: tuple
    actions: Callable[[tuple], Iterable[Tuple[str, tuple]]]
    invariants: Tuple[Tuple[str, Callable[[tuple], bool]], ...] = ()
    liveness: Tuple[Tuple[str, str, Callable[[tuple], bool]], ...] = ()


@dataclass(frozen=True)
class ProtocolSpec:
    """A declared protocol: model factory + conformance tables + the
    literals binding it to the implementation it models."""

    name: str
    title: str
    #: repo-relative implementation files this spec models (the
    #: protocol-drift rule resolves ``literals`` against their source)
    modules: Tuple[str, ...]
    #: small-scope bounds the model is exhaustive at (documentation +
    #: artifact inventory; the model factory bakes them in)
    bounds: Mapping[str, int]
    #: mutations -> Model; a frozenset of names from ``mutations``
    #: weakens the matching guards (seeded-bug legs)
    model: Callable[[FrozenSet[str]], Model]
    #: seeded guard-weakenings the model factory understands
    mutations: Tuple[str, ...] = ()
    #: event kind -> declared runtime-conformance table (see
    #: analysis/protocol/conformance.py for the per-kind shapes)
    event_edges: Mapping[str, Mapping] = field(default_factory=dict)
    #: implementation literal -> human description; each literal must
    #: appear in at least one of ``modules``'s sources
    literals: Mapping[str, str] = field(default_factory=dict)
    #: ((event, field, values), ...) cross-checked against the declared
    #: enum inventory in utils/metrics.EVENT_SCHEMAS field descriptions
    enum_checks: Tuple[Tuple[str, str, Tuple[str, ...]], ...] = ()
    #: registration site (filled by :func:`register_spec`) — findings
    #: about this spec anchor here
    path: str = ""
    line: int = 0

    def safety_names(self) -> Tuple[str, ...]:
        return tuple(n for n, _ in self.model(frozenset()).invariants)

    def liveness_names(self) -> Tuple[str, ...]:
        return tuple(n for n, _, _ in self.model(frozenset()).liveness)


_REGISTRY: Dict[str, ProtocolSpec] = {}

#: the modules that register specs at import time — co-located with the
#: protocol implementations they model (ISSUE 20 tentpole)
_SPEC_MODULES = (
    "distributed_resnet_tensorflow_tpu.resilience.elastic",
    "distributed_resnet_tensorflow_tpu.checkpoint.shards",
    "distributed_resnet_tensorflow_tpu.serve.fleet",
    "distributed_resnet_tensorflow_tpu.serve.swap",
)


def _repo_root() -> str:
    pkg = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.dirname(pkg)


def register_spec(spec: ProtocolSpec) -> ProtocolSpec:
    """Register a spec, stamping the caller's file:line as the anchor
    every checker/lint finding about this protocol points at."""
    frame = sys._getframe(1)
    rel = os.path.relpath(frame.f_code.co_filename, _repo_root())
    stamped = ProtocolSpec(**{**spec.__dict__,
                              "path": rel, "line": frame.f_lineno})
    _REGISTRY[stamped.name] = stamped
    return stamped


def load_specs() -> Tuple[ProtocolSpec, ...]:
    """Import the co-located spec registrations and return every
    declared protocol, sorted by name (deterministic artifact order)."""
    import importlib
    for mod in _SPEC_MODULES:
        importlib.import_module(mod)
    return tuple(_REGISTRY[name] for name in sorted(_REGISTRY))
