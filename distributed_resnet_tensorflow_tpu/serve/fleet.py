"""Fleet supervisor: spawn N serving replicas, watchdog-replace the dead.

The training side's supervisor (launch.py) answers "a child exited — now
what?"; this one also has to answer "a child is ALIVE but useless" — a
wedged dispatch thread keeps the process (and its heartbeat publisher)
running while every request times out. The replace ladder mirrors the
training watchdog escalation (docs/resilience.md):

    condemn (router health says dead, or the process exited)
      → drain   (router stops routing to it; in-flight attempts hedge
                 to survivors)
      → kill    (launch.terminate_child: SIGTERM → grace → SIGKILL)
      → respawn (same replica id, same port, same config file)
      → warm    (wait for the replica's READY marker, bounded)
      → readmit (router resets the client pool and probes it back to
                 ready)

Every rung lands a ``replica_replace`` row; a crash-looping fleet is
bounded by ``route.max_replaces`` (the ``gave_up`` row is the operator's
page). Replicas are ordinary ``main.py`` processes fed a JSON config
(``--config_json``) with ``serve.replica_id`` / ``serve.listen_port`` /
``serve.swap_gate`` set — there is no special replica binary to drift.

Checkpoint pinning: before the first replica spawns, the supervisor
writes every replica's SWAP_CONTROL.json at the newest committed step
(when one exists). From then on replicas only follow the router's pins —
a checkpoint committed mid-rollout reaches the canary fraction first and
the rest of the fleet only after the canary verdict (serve/router.py
CanaryController).
"""
from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from ..launch import terminate_child
from ..utils.config import ExperimentConfig, resolve_checkpoint_dir
from ..resilience.manifest import committed_steps
from ..analysis.protocol.spec import Model, ProtocolSpec, register_spec
from . import router

log = logging.getLogger(__name__)


def replica_dir(log_root: str, rid: int) -> str:
    """Per-replica artifact dir: metrics stream, READY marker, swap pin."""
    return os.path.join(log_root, f"serve-r{rid}")


def pin_path(log_root: str, rid: int) -> str:
    return os.path.join(replica_dir(log_root, rid), "SWAP_CONTROL.json")


def write_pin(log_root: str, rid: int, step: int) -> None:
    """Atomically pin one replica's serving step (the swapper follows it
    forward for a rollout, backward for a rollback)."""
    path = pin_path(log_root, rid)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"target_step": int(step)}, f)
    os.replace(tmp, path)


def _free_port() -> int:
    import socket
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class FleetSupervisor:
    """Owns the replica processes of one routed serving fleet."""

    def __init__(self, cfg: ExperimentConfig, writer=None,
                 clock=time.monotonic):
        self.cfg = cfg
        self.rcfg = cfg.route
        self.writer = writer
        self.clock = clock
        self.router = None  # attached after construction (it needs ports)
        self.route_dir = os.path.join(cfg.log_root, "route")
        self.beats_dir = os.path.join(cfg.log_root, "heartbeats-serve")
        self.procs: Dict[int, subprocess.Popen] = {}
        self.ports: Dict[int, int] = {}
        self.rcs: Dict[int, int] = {}
        self.replaces = 0
        self.pinned_step = -1  # the step every replica was pinned at spawn
        self._gave_up: set = set()
        self._stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        self._logs: List[object] = []

    # -- spawn / warm ------------------------------------------------------

    def start(self, wait_ready: bool = True) -> "FleetSupervisor":
        os.makedirs(self.route_dir, exist_ok=True)
        n = max(1, self.rcfg.replicas)
        for rid in range(n):
            self.ports[rid] = (self.rcfg.base_port + rid
                               if self.rcfg.base_port > 0 else _free_port())
        step = self.initial_step()
        self.pinned_step = step
        if step >= 0:
            # pin BEFORE the first spawn: a checkpoint committed while
            # the fleet warms must reach the canary fraction first, never
            # a baseline replica chasing the newest commit ungated
            for rid in range(n):
                write_pin(self.cfg.log_root, rid, step)
        for rid in range(n):
            self.procs[rid] = self._spawn(rid)
        if wait_ready:
            deadline = self.clock() + self.rcfg.warm_timeout_secs
            for rid in range(n):
                if self._wait_ready(rid, deadline) is None:
                    raise RuntimeError(
                        f"replica {rid} not READY within "
                        f"{self.rcfg.warm_timeout_secs:.0f}s — see "
                        f"{self._log_path(rid)}")
        return self

    def initial_step(self) -> int:
        """Newest committed checkpoint step, or -1 (fresh-init serving)."""
        try:
            steps = committed_steps(resolve_checkpoint_dir(self.cfg))
        except OSError:
            steps = []
        return max(steps) if steps else -1

    def _config_path(self, rid: int) -> str:
        return os.path.join(self.route_dir, f"replica{rid}.json")

    def _log_path(self, rid: int) -> str:
        return os.path.join(self.route_dir, f"replica{rid}.log")

    def _replica_cfg(self, rid: int) -> str:
        """Materialize replica ``rid``'s config file: the fleet's own
        config with mode=serve, fleet identity set, self-driven load off
        (the router is the only load source) and swaps gated on the pin."""
        rep = ExperimentConfig.from_dict(self.cfg.to_dict())
        rep.mode = "serve"
        rep.serve.replica_id = rid
        rep.serve.listen_port = self.ports[rid]
        rep.serve.swap_gate = True
        rep.serve.load_qps = 0.0
        rep.serve.wait_for_swap_secs = 0.0
        path = self._config_path(rid)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(rep.to_json())
        os.replace(tmp, path)
        return path

    def _spawn(self, rid: int) -> subprocess.Popen:
        cfg_path = self._replica_cfg(rid)
        ready = os.path.join(replica_dir(self.cfg.log_root, rid), "READY")
        try:
            os.remove(ready)  # a stale marker must not fake a warm replica
        except OSError:
            pass
        cmd = [sys.executable, "-m",
               "distributed_resnet_tensorflow_tpu.main",
               "--config_json", cfg_path]
        out = open(self._log_path(rid), "a")
        self._logs.append(out)
        proc = subprocess.Popen(cmd, env=dict(os.environ), stdout=out,
                                stderr=out)
        log.info("fleet: replica %d spawned pid %d port %d", rid, proc.pid,
                 self.ports[rid])
        return proc

    def _wait_ready(self, rid: int, deadline: float) -> Optional[dict]:
        """Poll for the replica's READY marker; None on timeout, early
        exit, or supervisor stop."""
        ready = os.path.join(replica_dir(self.cfg.log_root, rid), "READY")
        while self.clock() < deadline and not self._stop.is_set():
            proc = self.procs.get(rid)
            if proc is not None and proc.poll() is not None:
                log.error("fleet: replica %d exited rc=%s while warming",
                          rid, proc.returncode)
                return None
            try:
                with open(ready) as f:
                    raw = f.read().strip()
            except OSError:
                raw = ""
            if raw:
                try:
                    return json.loads(raw)
                except ValueError:
                    return {"pid": int(raw)} if raw.isdigit() else {}
            self._stop.wait(0.2)
        return None

    # -- watchdog ----------------------------------------------------------

    def attach_router(self, router) -> None:
        self.router = router

    def start_watch(self) -> None:
        self._watch_thread = threading.Thread(
            target=self._watch, daemon=True, name="drt-fleet-watch")
        self._watch_thread.start()

    def _watch(self) -> None:
        interval = max(0.1, self.rcfg.watch_interval_secs)
        while not self._stop.is_set():
            self._stop.wait(interval)
            if self._stop.is_set():
                return
            try:
                self._watch_pass()
            except Exception:  # noqa: BLE001 — the watchdog must outlive
                log.exception("fleet: watch pass failed")  # any one replace

    def _watch_pass(self) -> None:
        for rid, proc in list(self.procs.items()):
            if rid in self._gave_up:
                continue
            rc = proc.poll()
            if rc is not None:
                self._replace(rid, "exited", rc=rc)
            elif (self.router is not None
                  and self.router.health_state(rid) == "dead"):
                # alive-but-useless: fresh beats mean the process runs
                # while requests fail (wedged dispatch); stale beats mean
                # the whole process is gone dark
                age = self._beat_age(rid)
                wedged = (age is not None
                          and age <= self.rcfg.beat_stale_secs)
                self._replace(rid, "wedged" if wedged else "dead")

    def _beat_age(self, rid: int) -> Optional[float]:
        path = os.path.join(self.beats_dir, f"proc{rid}.json")
        try:
            with open(path) as f:
                beat = json.load(f)
            return max(0.0, time.time() - float(beat.get("wall_time", 0)))
        except (OSError, ValueError):
            return None

    def _row(self, payload: dict) -> None:
        if self.writer is not None:
            self.writer.write_event("replica_replace", payload)

    def _replace(self, rid: int, reason: str,
                 rc: Optional[int] = None) -> None:
        if self.replaces >= self.rcfg.max_replaces:
            self._gave_up.add(rid)
            log.error("fleet: replace budget exhausted (%d); replica %d "
                      "stays down (%s)", self.replaces, rid, reason)
            self._row({"replica": rid, "action": "gave_up",
                       "reason": reason})
            return
        self.replaces += 1
        proc = self.procs[rid]
        old_pid = proc.pid
        log.warning("fleet: replacing replica %d pid %d (%s, rc=%s) — "
                    "replace %d/%d", rid, old_pid, reason, rc,
                    self.replaces, self.rcfg.max_replaces)
        if self.router is not None:
            self.router.mark_draining(rid)
        kill_row = {"replica": rid, "action": "kill", "reason": reason,
                    "pid": old_pid}
        if rc is not None:
            kill_row["rc"] = rc
        self._row(kill_row)
        self.rcs[rid] = terminate_child(
            proc, grace_secs=self.rcfg.replica_grace_secs)
        t0 = self.clock()
        self.procs[rid] = self._spawn(rid)
        self._row({"replica": rid, "action": "respawn", "reason": reason,
                   "new_pid": self.procs[rid].pid})
        info = self._wait_ready(rid, t0 + self.rcfg.warm_timeout_secs)
        if info is None:
            self._gave_up.add(rid)
            self._row({"replica": rid, "action": "gave_up",
                       "reason": reason, "new_pid": self.procs[rid].pid})
            return
        if self.router is not None:
            self.router.readmit(rid)
        self._row({"replica": rid, "action": "readmit", "reason": reason,
                   "new_pid": self.procs[rid].pid,
                   "wait_secs": round(self.clock() - t0, 1)})

    # -- teardown / reporting ---------------------------------------------

    def stop(self) -> None:
        self._stop.set()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=10.0)
            self._watch_thread = None
        for rid, proc in self.procs.items():
            self.rcs[rid] = terminate_child(
                proc, grace_secs=self.rcfg.replica_grace_secs)
        for out in self._logs:
            try:
                out.close()
            except OSError:
                pass
        self._logs = []

    def report(self) -> dict:
        return {
            "replicas": len(self.procs),
            "ports": dict(self.ports),
            "pids": {r: p.pid for r, p in self.procs.items()},
            "replaces": self.replaces,
            "gave_up": sorted(self._gave_up),
            "exit_codes": dict(self.rcs),
        }


# ---------------------------------------------------------------------------
# declared protocol model (analysis/protocol/, docs/static_analysis.md)
# ---------------------------------------------------------------------------

#: the replace ladder's event actions, in declared order (gave_up is the
#: terminal off-ramp from any rung)
REPLACE_LADDER = ("kill", "respawn", "readmit")


def _health_replace_model(mutations):
    """One replica: the router's ReplicaHealth machine interleaved with
    this supervisor's replace ladder, at suspect_after=1 / dead_after=2
    and a replace budget of 1.

    State: ``(health, fails, sup, budget)`` — ``health`` a
    serve/router.py health-state string, ``fails`` the consecutive-
    failure counter (bounded by dead_after), ``sup`` the supervisor rung
    (watch / pending_kill / pending_respawn / pending_readmit /
    gave_up), ``budget`` replaces remaining. Health observations only
    fire while the supervisor watches — mid-ladder the replica is
    draining, where every ReplicaHealth input is a no-op by
    construction (the model's second invariant pins that coupling).
    """
    suspect_after, dead_after = 1, 2

    def actions(s):
        health, fails, sup, budget = s
        out = []
        if sup == "watch":
            if health == router.WARMING:
                out.append(("probe_ok", (router.READY, 0, sup, budget)))
            if health == router.SUSPECT:
                out.append(("recover_ok", (router.READY, 0, sup, budget)))
            if health in (router.READY, router.DEGRADED) and fails:
                out.append(("ok", (health, 0, sup, budget)))
            if health in (router.WARMING, router.READY,
                          router.DEGRADED, router.SUSPECT):
                # capped at the dead threshold: past it every further
                # failure is behaviorally identical (keeps the mutated
                # zombie_revive model finite too)
                nf = min(fails + 1, dead_after)
                if nf >= dead_after:
                    out.append(("fail", (router.DEAD, nf, sup, budget)))
                elif nf >= suspect_after and health != router.SUSPECT:
                    out.append(("fail", (router.SUSPECT, nf, sup, budget)))
                else:
                    out.append(("fail", (health, nf, sup, budget)))
            if health in (router.READY, router.DEGRADED, router.SUSPECT):
                out.append(("beat_stale", (router.DEAD, fails,
                                           sup, budget)))
            if health == router.READY:
                out.append(("slo_pressure", (router.DEGRADED, fails,
                                             sup, budget)))
            if health == router.DEGRADED:
                out.append(("slo_recovered", (router.READY, fails,
                                              sup, budget)))
            if health == router.DEAD:
                if budget > 0:
                    # condemn: mark_draining precedes the kill row
                    out.append(("condemn", (router.DRAINING, fails,
                                            "pending_kill", budget - 1)))
                else:
                    out.append(("budget_exhausted",
                                (health, fails, "gave_up", budget)))
                if "illegal_health_edge" in mutations:
                    # the bug class HEALTH_EDGES exists to exclude: a
                    # dead replica re-entering rotation without the
                    # drain -> respawn -> warm -> readmit ladder
                    out.append(("zombie_revive",
                                (router.READY, fails, sup, budget)))
        elif sup == "pending_kill":
            out.append(("kill", (health, fails, "pending_respawn",
                                 budget)))
        elif sup == "pending_respawn":
            out.append(("respawn", (health, fails, "pending_readmit",
                                    budget)))
        elif sup == "pending_readmit":
            out.append(("readmit", (router.WARMING, 0, "watch", budget)))
            out.append(("warm_timeout", (health, fails, "gave_up",
                                         budget)))
        return out

    def _dispatchable_below_dead(s):
        health, fails = s[0], s[1]
        return health not in router.DISPATCHABLE or fails < dead_after

    def _ladder_implies_draining(s):
        health, sup = s[0], s[2]
        return (sup not in ("pending_kill", "pending_respawn",
                            "pending_readmit")
                or health == router.DRAINING)

    return Model(
        init=(router.WARMING, 0, "watch", 1),
        actions=actions,
        invariants=(
            ("dead_to_ready_only_via_replace_ladder",
             _dispatchable_below_dead),
            ("mid_ladder_replica_is_draining", _ladder_implies_draining),
        ),
        liveness=(
            ("killed_replica_round_terminates", "eventually",
             lambda s: s[2] == "gave_up" or s[0] == router.READY),
            ("full_ladder_returns_to_service", "reachable",
             lambda s: s[2] == "watch" and s[0] == router.READY
             and s[3] == 0),
        ),
    )


HEALTH_REPLACE_PROTOCOL = register_spec(ProtocolSpec(
    name="replica-health-replace",
    title="router replica-health machine x fleet watchdog replace "
          "ladder: condemn -> drain -> kill -> respawn -> readmit",
    modules=("distributed_resnet_tensorflow_tpu/serve/router.py",
             "distributed_resnet_tensorflow_tpu/serve/fleet.py"),
    bounds={"replicas": 1, "suspect_after": 1, "dead_after": 2,
            "max_replaces": 1},
    model=_health_replace_model,
    mutations=("illegal_health_edge",),
    event_edges={
        "replica_health": {"edges": router.HEALTH_EDGES,
                           "initial": router.WARMING},
        "replica_replace": {"actions": REPLACE_LADDER + ("gave_up",),
                            "reasons": ("exited", "wedged", "dead"),
                            "ladder": REPLACE_LADDER},
    },
    literals={
        router.WARMING: "health state", router.READY: "health state",
        router.DEGRADED: "health state", router.SUSPECT: "health state",
        router.DRAINING: "health state", router.DEAD: "health state",
        "kill": "replace-ladder action", "respawn": "replace-ladder "
        "action", "readmit": "replace-ladder action",
        "gave_up": "replace-budget off-ramp action",
    },
    enum_checks=(
        ("replica_health", "from",
         (router.WARMING, router.READY, router.DEGRADED, router.SUSPECT,
          router.DRAINING, router.DEAD)),
        ("replica_health", "reason",
         ("probe_ok", "failures", "beat_stale", "slo_pressure",
          "recovered", "drain", "readmit")),
        ("replica_replace", "action", REPLACE_LADDER + ("gave_up",)),
        ("replica_replace", "reason", ("exited", "wedged", "dead")),
    ),
))
