"""Evidence run for the imagenet_resnet50_lars32k preset on one chip.

Runs the large-batch config truncated — REAL global batch 32,768 at 224²
via gradient accumulation (256 microbatches of 128 inside one jitted scan),
LARS with the preset's lr=29 + warmup + cosine — long enough to show the
warmup/trust-ratio machinery producing a stable loss descent where plain
momentum at lr 29 would explode. Data is a learnable synthetic pool
(class-coded mean color, the make_synth_imagenet content model) shipped as
uint8 with the VGG standardize on device, so the full global batch fits:
uint8 32k × 224² ≈ 4.6 GB HBM vs 19.7 GB if prepped to f32 up front (which
is why train/loop.py preps per microbatch).

    python tools/run_lars_evidence.py [--steps 60] [--out results/lars32k_evidence.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def make_pool(n_images: int, num_classes: int, size: int,
              seed: int) -> tuple:
    """Learnable uint8 pool: class-coded mean color + noise (the
    tools/make_synth_imagenet signal, generated directly as arrays)."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    from make_synth_imagenet import class_color
    rng = np.random.RandomState(seed)
    labels = rng.randint(1, num_classes + 1, size=(n_images,)).astype(np.int32)
    images = np.empty((n_images, size, size, 3), np.uint8)
    for i, lab in enumerate(labels):
        base = 118.0 + 26.0 * class_color(int(lab) - 1, num_classes)
        img = base + rng.normal(0, 30.0, (size, size, 3))
        images[i] = np.clip(img, 0, 255).astype(np.uint8)
    return images, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--pool", type=int, default=1024)
    ap.add_argument("--warmup", type=int, default=15)
    ap.add_argument("--out", default="results/lars32k_evidence.json")
    args = ap.parse_args()

    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    cfg = get_preset("imagenet_resnet50_lars32k")
    gbs = cfg.train.batch_size                      # 32768
    accum = gbs // 128
    cfg.train.grad_accum_steps = accum
    cfg.data.device_augment = "on"                  # uint8 in, VGG std on device
    cfg.train.train_steps = args.steps
    # traverse warmup AND the full-lr cosine regime inside the truncated run
    cfg.optimizer.warmup_steps = args.warmup
    cfg.optimizer.total_steps = args.steps
    cfg.mesh.data = len(jax.devices())

    print(f"gbs={gbs} accum={accum} lr_peak={cfg.optimizer.learning_rate} "
          f"warmup={args.warmup} steps={args.steps}", flush=True)

    if gbs % args.pool:
        raise SystemExit(f"--pool {args.pool} must divide the global batch "
                         f"{gbs} (the tiled batch would silently shrink)")
    pool_imgs, pool_labels = make_pool(args.pool, 16, cfg.data.image_size,
                                       seed=0)
    reps = gbs // args.pool

    trainer = Trainer(cfg)
    trainer.init_state()
    step_fn = trainer.jitted_train_step()

    # ship only the pool (~150 MB) and tile to the 4.6 GB global batch ON
    # device — the tunnel link would take minutes to push the full batch.
    # The step does not donate its batch argument, so one device batch
    # serves every step.
    import jax.numpy as jnp
    pool_dev = trainer._put_batch({"images": pool_imgs,
                                   "labels": pool_labels})
    tile = jax.jit(lambda b: {
        "images": jnp.tile(b["images"], (reps, 1, 1, 1)),
        "labels": jnp.tile(b["labels"], (reps,))})
    dev_batch = tile(pool_dev)
    jax.block_until_ready(dev_batch["labels"])

    rows = []
    state = trainer.state
    t0 = time.time()
    for step in range(args.steps):
        state, m = step_fn(state, dev_batch)
        row = {"step": step + 1,
               "loss": float(m["loss"]),
               "cross_entropy": float(m["cross_entropy"]),
               "precision": float(m["precision"]),
               "learning_rate": float(m["learning_rate"]),
               "grad_norm": float(m["grad_norm"])}
        rows.append(row)
        print(f"step {row['step']:>3}  loss {row['loss']:.4f}  ce "
              f"{row['cross_entropy']:.4f}  prec {row['precision']:.4f}  "
              f"lr {row['learning_rate']:.3f}  |g| {row['grad_norm']:.2f}",
              flush=True)
    wall = time.time() - t0

    ces = [r["cross_entropy"] for r in rows]
    out = {
        "config": "imagenet_resnet50_lars32k (truncated)",
        "global_batch": gbs, "grad_accum_steps": accum,
        "peak_lr": cfg.optimizer.learning_rate,
        "warmup_steps": args.warmup, "steps": args.steps,
        "wall_secs": round(wall, 1),
        "images_per_sec": round(gbs * args.steps / wall, 1),
        "ce_first": round(ces[0], 4), "ce_last": round(ces[-1], 4),
        "ce_min": round(min(ces), 4),
        "finite": all(np.isfinite(r["loss"]) for r in rows),
        "rows": rows,
    }
    if os.path.dirname(args.out):
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
    print(f"\nce {ces[0]:.3f} -> {ces[-1]:.3f} over {args.steps} steps of "
          f"gbs {gbs}; wrote {args.out}")


if __name__ == "__main__":
    main()
