"""CLI entry point — the successor of ALL the reference mains.

One binary replaces resnet_cifar_main.py / resnet_imagenet_main.py /
resnet_cifar_main_horovod.py / resnet_single.py / resnet_cifar_eval.py /
resnet_imagenet_eval.py (SURVEY.md §1 L3): dataset and topology are config,
not separate entry points, and there is no ps/worker split to dispatch on.

Usage:
    python -m distributed_resnet_tensorflow_tpu.main --preset cifar10_resnet50 \
        --set train.batch_size=256 --set log_root=/tmp/run1
    python -m distributed_resnet_tensorflow_tpu.main --preset cifar10_resnet50 \
        --set mode=eval          # standalone polling evaluator

Multi-host: launch one copy per TPU host (launcher.py / SLURM shim); every
process runs this same SPMD program — replacing the reference's per-role
process trees (reference resnet_cifar_main.py:339-399).
"""
from __future__ import annotations

import contextlib
import logging
import os
import sys
import time
from typing import Optional

import jax

from .checkpoint import CheckpointManager
from .data import create_input_iterator
from .evaluator import Evaluator, make_eval_iterator
from .parallel import initialize_from_config, is_chief
from .resilience import Preempted, PreemptionListener, RESUMABLE_EXIT_CODE
from .resilience.elastic import (ElasticImpossible, ElasticRuntime,
                                 ReshardRequired)
from .resilience.heartbeat import (PHASE_DONE, PHASE_FAILED,
                                   PHASE_PREEMPTED, PHASE_RESHARD)
from .resilience.preemption import (collective_preempted,
                                    collective_should_stop)
from .resilience.faultinject import maybe_wrap_from_env
from .resilience.sentinel import train_with_nan_recovery
from .telemetry import configure_from_config as _configure_telemetry
from .telemetry.tracer import recorder as _flight_recorder
from .train.hooks import (CheckpointHook, CkptAsyncHook, CkptShardHook,
                          CommCompressHook, CommOverlapHook,
                          CommTimingHook, CorruptRecordsHook, GoodputHook,
                          HeartbeatHook, InputEchoHook, InputStagesHook,
                          LoggingHook, MemoryHook, NanGuardHook,
                          PlanDriftHook, PrecisionHook, SummaryHook,
                          Zero1Hook)
from .train.loop import Trainer
from .utils.config import (ExperimentConfig, parse_args,
                           resolve_checkpoint_dir, stacked_layout_stamp)
from .utils.metrics import MetricsWriter

log = logging.getLogger(__name__)


def _make_writer(cfg: ExperimentConfig, sub: str) -> MetricsWriter:
    """The run's metrics stream, size-bounded per the telemetry knobs
    (utils/metrics.MetricsWriter rotation): one construction site so every
    mode gets the same disk bound."""
    t = cfg.telemetry
    return MetricsWriter(
        os.path.join(cfg.log_root, sub),
        max_bytes=int(t.metrics_max_mb * 1024 * 1024),
        max_segments=t.metrics_max_segments)


def _per_process_batch(global_bs: int, nproc: int) -> int:
    """Global batch must divide evenly across input shards — a silent floor
    would train a different effective batch than configured."""
    if global_bs % nproc:
        raise ValueError(
            f"train.batch_size={global_bs} is not divisible by "
            f"{nproc} input shards; the global batch would silently shrink")
    return global_bs // nproc


def _make_train_source(cfg: ExperimentConfig, trainer: Trainer):
    """Training data source. Device-resident dataset (host ships indices,
    data/device_dataset.py) when enabled; otherwise the streamed per-process
    input shard (fixes the reference Horovod path's unsharded input,
    SURVEY.md §3.2)."""
    from .data import device_dataset_enabled
    if device_dataset_enabled(cfg, "train"):
        from .data import load_cifar
        from .data.device_dataset import epoch_index_iterator
        images, labels = load_cifar(
            cfg.data.dataset, cfg.data.data_dir, "train",
            use_native=cfg.data.use_native_loader)
        trainer.attach_device_dataset(images, labels)
        log.info("device-resident dataset: %d examples in HBM", len(labels))
        return epoch_index_iterator(len(labels), cfg.train.batch_size,
                                    cfg.train.seed)
    # input shards are keyed by the process's BATCH slice, not its index:
    # when a non-batch mesh axis (pipeline/tensor/...) spans processes,
    # replica processes must feed identical data (parallel/mesh.py
    # process_batch_slice)
    from .parallel.mesh import batch_slice_replicated, process_batch_slice
    shard_index, num_shards = process_batch_slice(trainer.mesh)
    it = create_input_iterator(
        cfg, mode="train", shard_index=shard_index,
        num_shards=num_shards,
        batch_size=_per_process_batch(cfg.train.batch_size, num_shards),
        deterministic=batch_slice_replicated(trainer.mesh))
    # inert unless the chaos harness armed it via env
    # (resilience/faultinject.py; tests/test_resilience.py)
    return maybe_wrap_from_env(it)


def _start_watchdog(cfg: ExperimentConfig, writer, listener,
                    trainer: Optional[Trainer] = None,
                    role: str = "train", elastic=None):
    """Build + start the heartbeat publisher and the health watchdog
    (resilience/heartbeat.py, resilience/watchdog.py) when enabled —
    ``resilience.watchdog.enabled=auto`` resolves to on iff the run has
    peers. Returns (publisher, watchdog), both None when disabled.

    The watchdog escalates through ``listener.request_stop`` (graceful,
    coordinated stop at a step boundary) before its hard ``os._exit(75)``;
    the publisher is attached to the trainer so eval batches tick liveness
    too. ``role`` scopes the default beat directory: a standalone
    evaluator job is its OWN jax world but shares ``log_root`` with the
    trainers — publishing into their dir as "process 0" would mask
    trainer-0's death from its peers and pollute their straggler
    accounting."""
    from .resilience.watchdog import Watchdog, watchdog_enabled
    wd_cfg = cfg.resilience.watchdog
    if not watchdog_enabled(wd_cfg, jax.process_count()):
        return None, None
    from .resilience.heartbeat import FileBeatTransport, HeartbeatPublisher
    subdir = "heartbeats" if role == "train" else f"heartbeats-{role}"
    if wd_cfg.heartbeat_dir:
        # an explicit override is still role-scoped: trainers keep the
        # exact dir, a non-train world gets a subdir under it — otherwise
        # a standalone evaluator sharing the config would impersonate
        # trainer process 0 in the trainers' beat directory
        hb_dir = wd_cfg.heartbeat_dir if role == "train" \
            else os.path.join(wd_cfg.heartbeat_dir, role)
    else:
        hb_dir = os.path.join(cfg.log_root, subdir)
    transport = FileBeatTransport(hb_dir, jax.process_index())
    publisher = HeartbeatPublisher(
        transport, jax.process_index(),
        interval_secs=wd_cfg.interval_secs,
        # beats are generation-stamped so the monitor (and a peer's
        # straggler accounting) can tell a live host of generation g from
        # a stale file of generation g-1 (resilience/elastic.py)
        generation=elastic.generation if elastic is not None else 0).start()
    if trainer is not None:
        trainer.heartbeat = publisher
    watchdog = Watchdog(
        transport, publisher, jax.process_index(), jax.process_count(),
        wd_cfg, writer=writer,
        request_stop=listener.request_stop if listener is not None else None,
        # perf-anomaly sentinel knobs (telemetry.anomaly_*): the online
        # step-time outlier detector rides the watchdog's detection thread
        anomaly_cfg=cfg.telemetry,
    ).start()
    if elastic is not None:
        # escalation fork: a peer-lost verdict defers its hard exit while
        # this process can reshard instead (resilience/watchdog.py)
        watchdog.set_elastic(elastic.watchdog_defer)
    log.info("health watchdog armed: %d processes, beats -> %s "
             "(peer_timeout %.0fs, grace %.0fs)", jax.process_count(),
             hb_dir, wd_cfg.peer_timeout_secs, wd_cfg.grace_secs)
    return publisher, watchdog


def _teardown_watchdog(publisher, watchdog, final_phase: str) -> None:
    """Orderly watchdog shutdown: disarm FIRST (the run is leaving through
    a legitimate path; the daemon must not hard-exit under it), then
    publish the final phase so peers distinguish done/preempted (clean
    departure) from failed (stop resumable, surface the real error)."""
    if watchdog is not None:
        watchdog.close()
    if publisher is not None:
        publisher.close(final_phase)


@contextlib.contextmanager
def _watchdog_session(cfg: ExperimentConfig, writer, listener,
                      trainer: Optional[Trainer] = None,
                      role: str = "train", elastic=None):
    """The teardown choreography every entry point needs, in ONE place:
    success publishes a final ``done`` beat, Preempted publishes
    ``preempted`` (clean coordinated departure — peers must not flag us as
    lost), and any other error first asks the watchdog whether a PEER
    caused it (exits with the verdict code; does not return) before
    publishing ``failed``. With a live elastic runtime the peer-lost exit
    becomes a :class:`ReshardRequired` unwind instead, leaving through the
    ``reshard`` final phase (a coordinated departure into the next mesh
    generation — resilience/elastic.py). Yields (publisher, watchdog),
    both None when the watchdog is disabled."""
    publisher, watchdog = _start_watchdog(cfg, writer, listener, trainer,
                                          role=role, elastic=elastic)
    try:
        yield publisher, watchdog
    except Preempted:
        _teardown_watchdog(publisher, watchdog, PHASE_PREEMPTED)
        raise
    except ReshardRequired:
        # the grow path raises from the step loop itself (post-loop fork
        # in _train_one_generation): a clean departure into the barrier
        _teardown_watchdog(publisher, watchdog, PHASE_RESHARD)
        raise
    except BaseException as e:
        if isinstance(e, Exception):
            # a collective error caused by a dead peer exits 75 here
            # (does not return) — or, elastic, unwinds into the reshard
            # barrier; our OWN errors fall through and propagate
            try:
                _exit_for_peer_failure(watchdog, e, elastic=elastic)
            except ReshardRequired as rr:
                _teardown_watchdog(publisher, watchdog, PHASE_RESHARD)
                raise rr from e
        _teardown_watchdog(publisher, watchdog, PHASE_FAILED)
        raise
    else:
        _teardown_watchdog(publisher, watchdog, PHASE_DONE)


def _arm_watchdog_hooks(hooks: list, publisher) -> None:
    """Wire the heartbeat publisher into the step-hook chain — shared by
    run_train and run_train_and_eval so the two can't drift."""
    if publisher is None:
        return
    # position 0: the beat must reflect step N even if a later hook
    # raises mid-chain
    hooks.insert(0, HeartbeatHook(publisher))
    for h in hooks:
        # cadence saves flip to the unmonitored "save" phase — a slow
        # shared-FS save must not read as a hang
        if isinstance(h, CheckpointHook):
            h.heartbeat = publisher
        # the drift sentinel's measured step time should be the
        # watchdog's own EWMA, not a second competing estimate
        if isinstance(h, PlanDriftHook):
            h.heartbeat = publisher


#: substrings that mark an exception as possibly caused by a dead/wedged
#: peer (gloo transport, XLA collectives, the jax coordination service) —
#: only these are worth the failure_verdict beat-poll; a plainly local
#: error (NaN give-up, corrupt data, a hook TypeError) must propagate
#: immediately, not stall every process ~peer_timeout_secs first.
#: Deliberately BROAD ("connection", "timeout", "unavailable" can match a
#: local NFS/object-store error too): a false positive costs one bounded
#: ~peer_timeout beat-poll on an already-fatal crash, a false negative
#: turns a requeue-able peer loss into a real-failure exit code
_COLLECTIVE_ERROR_MARKERS = (
    "collective", "gloo", "allreduce", "all-reduce", "all_gather",
    "allgather", "connection", "socket", "barrier", "coordination",
    "distributed", "deadline", "timed out", "timeout", "unavailable",
    "peer", "preempt")


def _collective_shaped(exc: BaseException) -> bool:
    text = f"{type(exc).__name__}: {exc}".lower()
    return any(m in text for m in _COLLECTIVE_ERROR_MARKERS)


def _exit_for_peer_failure(watchdog, exc: BaseException, elastic=None):
    """After a runtime error in a multi-process step: if the beats say a
    peer died or reported failure, exit with the watchdog's verdict code
    (75 = peer loss, requeue; 1 = peer's real failure) instead of letting
    the exception propagate into the atexit ``jax.distributed.shutdown``
    barrier — which would block on the very peers that are gone.

    With a live elastic runtime, a peer-LOST verdict raises
    :class:`ReshardRequired` instead — the shrink entry: the survivors
    meet in the join barrier and continue as a smaller mesh generation;
    exit 75 is now the FALLBACK for when that is impossible
    (docs/resilience.md). A peer-FAILED verdict (the peer reported its
    own real error) still exits 1 — resharding around a determinism bug
    would silently change the experiment.

    Collective-shaped errors poll the beats up to the watchdog's default
    wait (the error can surface milliseconds after the peer died, before
    its beats age past the timeout); other errors get one immediate check
    only — they are our own, and the stall would cost every process
    ~peer_timeout_secs per crash."""
    if watchdog is None:
        return
    verdict = watchdog.failure_verdict(
        wait_secs=None if _collective_shaped(exc) else 0.0)
    if verdict is not None:
        kind, code, detail = verdict
        if kind == "peer_lost" and elastic is not None \
                and elastic.can_reshard():
            log.warning("peer loss behind %r — entering the elastic "
                        "reshard barrier instead of exit 75 (%s)",
                        exc, detail)
            raise ReshardRequired("peer_lost", detail)
        log.error("step loop error attributed to a peer (%s): %r",
                  kind, exc)
        watchdog.exit_now(kind, code, detail)  # does not return


def _peek(data_iter):
    """(first_batch_or_None, iterator yielding the same stream)."""
    import itertools
    try:
        first = next(data_iter)
    except StopIteration:
        return None, data_iter
    return first, itertools.chain([first], data_iter)


def _write_input_grid(writer: MetricsWriter, batch, trainer: Trainer) -> None:
    """One grid of raw input images at step 1 (reference cifar_input.py:114
    logged every summarized batch; once is the useful part)."""
    import numpy as np
    if "idx" in batch and trainer._dev_data is not None:
        # gather the 8 rows ON DEVICE; np.asarray of the full HBM dataset
        # would pull ~600 MB to host for 8 images
        import jax.numpy as jnp
        idx8 = jnp.asarray(np.asarray(batch["idx"])[:8])
        images = np.asarray(trainer._dev_data[0][idx8])
    else:
        images = batch.get("images")
    if images is not None:
        writer.write_images(1, "inputs", np.asarray(images)[:8])


def _check_resume_config(cfg: ExperimentConfig) -> None:
    """Record this run's config next to the checkpoints and WARN loudly
    when resuming under a different training recipe.

    Shape-identical configs (e.g. the gbs=128 and gbs=512 CIFAR presets)
    restore into each other without any error, silently entering the new
    LR schedule mid-stream — the reference had the same hazard via
    MonitoredTrainingSession. A changed recipe can be deliberate
    (fine-tuning), so this warns rather than refuses; the snapshot then
    reflects the NEW recipe."""
    import json as _json
    ckpt_dir = resolve_checkpoint_dir(cfg)
    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(ckpt_dir, "config.json")
    now = cfg.to_dict()
    if cfg.checkpoint.resume and os.path.exists(path):
        try:
            with open(path) as f:
                saved = _json.load(f)
        except Exception:
            saved = None
        if saved:
            # benign continuation knobs — changing them is the normal way
            # to extend/observe a run, not a recipe change
            benign = {("train", "train_steps"), ("train", "log_every_steps"),
                      ("train", "summary_every_steps"),
                      ("train", "eval_every_steps"),
                      ("train", "steps_per_loop"), ("train", "scan_unroll"),
                      ("train", "log_mfu")}

            def norm(v):
                return list(v) if isinstance(v, (tuple, list)) else v

            diffs = []
            for section in ("optimizer", "train", "model", "data"):
                for key, val in now.get(section, {}).items():
                    if (section, key) in benign:
                        continue
                    old = saved.get(section, {}).get(key, val)
                    if norm(old) != norm(val):
                        diffs.append(f"{section}.{key}: {old} -> {val}")
            if diffs:
                log.warning(
                    "resuming %s under a DIFFERENT config than it was "
                    "trained with: %s — if this is not a deliberate "
                    "fine-tune/schedule change, point log_root elsewhere",
                    ckpt_dir, "; ".join(diffs))
    if is_chief():
        with open(path, "w") as f:
            _json.dump(now, f, indent=1, sort_keys=True)


def _newest_committed_step(cfg: ExperimentConfig) -> Optional[int]:
    """The step a new mesh generation restores from: the newest COMMITTED
    checkpoint. The committing chief pins this into the barrier record
    (resilience/elastic.py) so survivors and rejoiners restore the EXACT
    same step with no post-teardown agreement collective."""
    from .resilience.manifest import committed_steps
    steps = committed_steps(resolve_checkpoint_dir(cfg))
    return steps[-1] if steps else None


def run_train(cfg: ExperimentConfig, max_steps: Optional[int] = None):
    """Train across MESH GENERATIONS. Returns (state, metrics).

    Resilience wiring (docs/resilience.md): a PreemptionListener stops the
    loop at a step boundary on SIGTERM/SIGINT or a config deadline, commits
    a final checkpoint, and raises Preempted (main() maps it to exit code
    75); the NaN sentinel rolls back to the last good checkpoint with LR
    back-off when the guard trips.

    With ``resilience.elastic.enabled=on`` (resilience/elastic.py) a lost
    peer no longer ends the job: the step loop unwinds here with
    :class:`ReshardRequired`, the survivors meet in a file-based join
    barrier, tear down the dead jax world, re-initialize over the new
    membership at an epoch-suffixed coordinator, and the next iteration of
    this loop rebuilds the Trainer (every sharding rule re-elaborates
    against the shrunken topology) and restores the committed step the
    barrier pinned. A respawned worker (launch.py --elastic sets
    ``DRT_ELASTIC_REJOIN``) enters the SAME loop through ``rejoin()`` and
    the fleet grows back. Exit 75 remains the fallback whenever the
    transition is impossible (chief lost, < min_hosts, barrier timeout,
    generation budget, non-elastic layout)."""
    res = cfg.resilience
    rejoin = bool(os.environ.get("DRT_ELASTIC_REJOIN"))
    if rejoin:
        # identity comes from the launcher slot (--set mesh.process_id):
        # there is no live jax world to ask yet
        runtime = ElasticRuntime(cfg)
    else:
        runtime = ElasticRuntime(cfg, worker_id=jax.process_index(),
                                 num_processes=jax.process_count())
    if not runtime.enabled:
        runtime = None
    if rejoin and runtime is None:
        raise RuntimeError(
            "DRT_ELASTIC_REJOIN is set but resilience.elastic is off or "
            "the run has no peers — nothing to rejoin")

    listener = None
    if res.handle_signals:
        listener = PreemptionListener(deadline_secs=res.deadline_secs)
        if not listener.install():
            listener = None  # not the main thread — run without handlers

    gen_cfg = cfg
    record = None
    reshard_info = None
    if rejoin:
        from .parallel.distributed import reinitialize
        try:
            # the restore_step_fn covers the whole-fleet-died case: every
            # worker rejoins and the rejoined chief commits the round — it
            # must pin the newest committed checkpoint like a survivor would
            record = runtime.rejoin(lambda: _newest_committed_step(cfg))
        except ElasticImpossible as e:
            # the supervisor respawns on 75 with a bounded budget —
            # re-posting the join later beats failing the slot for good
            log.error("elastic rejoin failed (%s); exiting resumable",
                      e.reason)
            raise Preempted(0, f"rejoin failed: {e.reason}")
        reinitialize(record["coordinator"], len(record["members"]),
                     runtime.rank(record))
        gen_cfg = runtime.derive_config(record)

    try:
        while True:
            try:
                return _train_one_generation(
                    gen_cfg, listener, max_steps, runtime=runtime,
                    record=record, reshard_info=reshard_info)
            except ReshardRequired as rr:
                from .parallel.distributed import (reinitialize,
                                                   teardown_for_reshard)
                from .telemetry.tracer import span
                old_hosts = len(runtime.members)
                t0 = time.monotonic()
                try:
                    with span("reshard.barrier", category="reshard"):
                        record = runtime.transition(
                            rr.reason,
                            lambda: _newest_committed_step(gen_cfg))
                except ElasticImpossible as e:
                    # the requeue contract is the FALLBACK: a mesh that
                    # cannot reshard leaves exactly the way the watchdog
                    # always did — hard resumable exit, no distributed
                    # shutdown barrier against peers that are gone
                    log.error("elastic reshard impossible (%s) — exiting "
                              "resumable for the requeue contract",
                              e.reason)
                    logging.shutdown()
                    os._exit(e.exit_code)
                barrier_ms = (time.monotonic() - t0) * 1000.0
                with span("reshard.teardown", category="reshard"):
                    teardown_for_reshard(runtime.ecfg.teardown_timeout_secs)
                with span("reshard.init", category="reshard"):
                    reinitialize(record["coordinator"],
                                 len(record["members"]),
                                 runtime.rank(record))
                gen_cfg = runtime.derive_config(record)
                if listener is not None:
                    # the old generation's stop request (watchdog peer-lost
                    # escalation / the chief's grow request) is consumed;
                    # a real SIGTERM survives the reset
                    listener.reset()
                reshard_info = {
                    "generation": record["generation"],
                    "reason": rr.reason,
                    "old_hosts": old_hosts,
                    "new_hosts": len(record["members"]),
                    "restore_step": record["restore_step"],
                    "global_batch": record["global_batch"],
                    "barrier_ms": round(barrier_ms, 1),
                    "_t0": t0,  # total_ms completes once the mesh is live
                }
                log.warning(
                    "elastic: generation %d -> %d (%s): %d -> %d hosts, "
                    "restore step %s, global batch %s",
                    record["generation"] - 1, record["generation"],
                    rr.reason, old_hosts, len(record["members"]),
                    record["restore_step"], record["global_batch"])
    finally:
        if listener is not None:
            listener.uninstall()


def _train_one_generation(cfg: ExperimentConfig, listener,
                          max_steps: Optional[int], runtime=None,
                          record=None, reshard_info=None):
    """Build → (maybe) restore → train with hooks for ONE mesh generation
    (the whole job, when elastic is off). Returns (state, metrics);
    raises ReshardRequired to unwind into run_train's generation loop."""
    from .telemetry.tracer import span
    res = cfg.resilience
    rebuild_span = span("reshard.rebuild", category="reshard") \
        if record is not None else contextlib.nullcontext()
    with rebuild_span:
        trainer = Trainer(cfg)
        trainer.init_state()
    if record is None:
        # generation transitions deliberately change world size/batch —
        # re-running the recipe-drift check would warn on every reshard
        _check_resume_config(cfg)

    manager = CheckpointManager(
        resolve_checkpoint_dir(cfg), max_to_keep=cfg.checkpoint.max_to_keep,
        save_every_steps=cfg.checkpoint.save_every_steps,
        save_every_secs=cfg.checkpoint.save_every_secs,
        async_save=cfg.checkpoint.async_save,
        layout_stamp=stacked_layout_stamp(cfg),
        verify_on_restore=res.verify_on_restore,
        io_retries=res.io_retries,
        sharded=cfg.checkpoint.sharded,
        finalize_timeout_secs=cfg.checkpoint.finalize_timeout_secs)

    start_step = 0
    if record is not None and int(record.get("restore_step", -1)) >= 0:
        # the barrier pinned the step: every member of the new generation
        # restores it EXACTLY — through the sharded M≠N assemble path
        # (checkpoint/shards.py) when the layout changed under it
        with span("reshard.restore", category="reshard"):
            trainer.state, restored = manager.restore(
                trainer.state, step=int(record["restore_step"]))
        if restored is None:
            raise RuntimeError(
                f"generation {runtime.generation}: committed restore step "
                f"{record['restore_step']} failed to restore — the "
                "generations would diverge")
        start_step = int(trainer.state.step)
        log.info("generation %d: restored committed step %d into the new "
                 "mesh layout", runtime.generation, start_step)
    elif record is not None:
        log.warning("generation %d: no committed checkpoint existed at the "
                    "transition — restarting from step 0 on the new mesh",
                    runtime.generation)
    elif cfg.checkpoint.resume:
        with span("restore"):
            trainer.state, restored = manager.restore(trainer.state)
        if restored is not None:
            start_step = int(trainer.state.step)
            log.info("resumed from checkpoint at step %d", start_step)

    data_iter = _make_train_source(cfg, trainer)

    # peek ONE batch to (a) log an input-image grid (parity with the
    # reference's tf.summary.image of input batches, cifar_input.py:114) and
    # (b) optionally pre-lower the step for MFU logging; then chain it back
    writer = None
    step_flops = None
    if is_chief():
        writer = _make_writer(cfg, "train")
        first, data_iter = _peek(data_iter)
        if first is not None:
            _write_input_grid(writer, first, trainer)
            if cfg.train.log_mfu:
                step_flops = trainer.step_flops(first)
    # flight recorder + goodput (telemetry/): dump dir, ring bound, the
    # chief's writer for trace_dump/goodput rows; every process records
    _configure_telemetry(cfg, writer, jax.process_index())

    guard_every = res.nan_check_every_steps or max(cfg.train.log_every_steps, 1)
    hooks = [NanGuardHook(every_steps=guard_every)]
    if is_chief():
        hooks.append(LoggingHook(cfg.train.log_every_steps,
                                 batch_size=cfg.train.batch_size,
                                 print_fn=print, step_flops=step_flops))
        hooks.append(SummaryHook(writer, cfg.train.summary_every_steps))
        # input-pipeline stage attribution rides the summary cadence
        hooks.append(InputStagesHook(writer, cfg.train.summary_every_steps))
        # data-echoing cache hit/miss/eviction telemetry (data/echo.py)
        if cfg.data.echo_factor > 1:
            hooks.append(InputEchoHook(writer, cfg.train.summary_every_steps))
        # corrupt-TFRecord tally (data.max_corrupt_records skips) likewise
        hooks.append(CorruptRecordsHook(writer, cfg.train.summary_every_steps))
        # goodput break-down (telemetry/goodput.py): compute vs input_wait
        # vs checkpoint vs eval vs stall vs restart, per interval. Gated
        # on the tracer: with spans off nothing charges the measured
        # buckets and every row would read compute=100% — wrong data is
        # worse than none
        if cfg.telemetry.enabled:
            hooks.append(GoodputHook(writer,
                                     cfg.telemetry.goodput_every_steps
                                     or cfg.train.summary_every_steps))
        # async-checkpoint charge split (loop-thread vs writer-thread
        # seconds) — rows only appear once a save actually ran
        hooks.append(CkptAsyncHook(writer, cfg.train.summary_every_steps))
        # bucketed gradient-exchange plan (parallel/overlap.py) — one row
        # per traced plan; silent when comm.overlap resolved off
        if trainer.comm_overlap_active:
            hooks.append(CommOverlapHook(writer,
                                         cfg.train.summary_every_steps))
        # ZeRO-1 partition plan (parallel/sharding.py rule table) — one
        # row per resolved plan; silent when optimizer.zero1 resolved off
        if trainer.zero1_active:
            hooks.append(Zero1Hook(writer, cfg.train.summary_every_steps))
        # per-run precision/compression summary (parallel/precision.py) —
        # one row per resolved policy; silent when everything runs f32
        if trainer.precision_active or trainer.comm_compress_active:
            hooks.append(PrecisionHook(writer,
                                       cfg.train.summary_every_steps))
        # compressed-exchange payload accounting — one row per traced
        # plan when comm.compress actually narrowed the wire
        if trainer.comm_compress_active:
            hooks.append(CommCompressHook(writer,
                                          cfg.train.summary_every_steps))
        # measured per-bucket exchange timings (parallel/overlap.py
        # probe) joined with the live step rate — rows appear once the
        # probe has run; silent when the bucketed exchange is off
        if trainer.comm_overlap_active and cfg.telemetry.comm_timing:
            hooks.append(CommTimingHook(writer,
                                        cfg.train.summary_every_steps))
        # predicted-vs-measured drift sentinel (telemetry/planner.py,
        # docs/planner.md): the what-if model's prediction for THIS run
        # held against the heartbeat/probe/memory measurements; "auto"
        # arms lazily once the bucketed exchange has traced
        if cfg.telemetry.plan_drift != "off" \
                and trainer.comm_overlap_active:
            hooks.append(PlanDriftHook(writer, cfg, trainer,
                                       cfg.train.summary_every_steps))
    # per-host accounting exported by EVERY process (the chief's stream
    # alone would claim 1/N of the cluster): sharded-checkpoint bytes
    # (ckpt_shard) and the device-memory trend (memory — each host
    # samples its OWN devices). Non-chief processes get a tiny dedicated
    # event stream (train-p<idx>) the monitor's rollup sums across hosts.
    shard_writer = None
    if cfg.checkpoint.sharded != "off" or cfg.telemetry.memory:
        shard_writer = writer
        if shard_writer is None:
            shard_writer = _make_writer(
                cfg, f"train-p{jax.process_index()}")
        if cfg.checkpoint.sharded != "off":
            hooks.append(CkptShardHook(shard_writer,
                                       cfg.train.summary_every_steps))
        if cfg.telemetry.memory:
            hooks.append(MemoryHook(shard_writer,
                                    cfg.train.summary_every_steps))
    if cfg.checkpoint.save_every_steps or cfg.checkpoint.save_every_secs:
        hooks.append(CheckpointHook(manager))

    num_steps = max_steps if max_steps is not None else cfg.train.train_steps
    try:
        # distributed health watchdog: every process beats; peer loss /
        # hangs escalate to a coordinated stop, then exit 75 — or, with
        # elastic on, a reshard into the next generation
        # (docs/resilience.md); the session publishes the final
        # done/preempted/failed/reshard beat on every exit path
        with _watchdog_session(cfg, writer, listener, trainer,
                               elastic=runtime) \
                as (publisher, watchdog):
            _arm_watchdog_hooks(hooks, publisher)
            if runtime is not None:
                if reshard_info is not None and writer is not None:
                    info = dict(reshard_info)
                    t0 = info.pop("_t0", None)
                    if t0 is not None:
                        info["total_ms"] = round(
                            (time.monotonic() - t0) * 1000.0, 1)
                    writer.write_event("reshard", info)
                # generation.json + heartbeat tombstones + the
                # mesh_generation row: the new mesh is about to step
                runtime.mark_live(record, start_step, writer)
            stop_fn = None
            if listener is not None:
                # multi-process: the stop decision must flip at the SAME
                # step boundary on every process or the SPMD step / save
                # barrier deadlocks (preemption.py collective_should_stop)
                stop_fn = collective_should_stop(listener) \
                    if jax.process_count() > 1 else listener.should_stop
                if runtime is not None and jax.process_index() == 0:
                    # chief's between-steps grow poll: a rejoiner posting
                    # into the next round stops the fleet at a step
                    # boundary through the NORMAL collective agreement;
                    # the post-loop fork below turns the stop into a grow
                    base_stop = stop_fn

                    def stop_fn():
                        if runtime.pending_join():
                            listener.request_stop("reshard")
                        return base_stop()
            # NOTE: the phase stays "init" (unmonitored) until the FIRST
            # step completes and HeartbeatHook flips it to "train" — the
            # first step includes XLA compilation, which can legitimately
            # exceed min_step_timeout_secs; arming hang detection before it
            # would hard-exit 75 mid-compile and requeue-loop the job
            if res.nan_max_strikes > 0:
                def iter_factory(attempt: int):
                    if attempt == 0:
                        return data_iter
                    # re-seed so the rollback does not replay the exact
                    # batch sequence that blew up (large odd stride keeps
                    # the offset seeds disjoint across attempts)
                    prev_seed = cfg.train.seed
                    cfg.train.seed = prev_seed + 1_000_003 * attempt
                    try:
                        return _make_train_source(cfg, trainer)
                    finally:
                        cfg.train.seed = prev_seed

                state, metrics = train_with_nan_recovery(
                    trainer, manager, iter_factory, num_steps=num_steps,
                    hooks=tuple(hooks), start_step=start_step,
                    max_strikes=res.nan_max_strikes,
                    lr_backoff=res.nan_lr_backoff, stop_fn=stop_fn)
            else:
                state, metrics = trainer.train(
                    data_iter, num_steps=num_steps, hooks=tuple(hooks),
                    start_step=start_step, stop_fn=stop_fn)
            # agreed across processes: the save below is collective, so no
            # process may enter it on a merely-local flag
            preempted = collective_preempted(listener) \
                if listener is not None else False
            if preempted and int(state.step) < num_steps:
                # a signal landing AFTER the last step finished is not a
                # preemption — the run is done; exiting 75 would requeue a
                # job with nothing left to do. Otherwise commit the
                # preemption checkpoint UNCONDITIONALLY (even when cadence
                # checkpointing is off): the whole point of a graceful stop
                # is that a relaunch resumes instead of restarting
                step = int(state.step)
                reason = listener.reason()
                if (runtime is not None and runtime.can_reshard()
                        and not reason.startswith("signal ")
                        and reason != "deadline"
                        and runtime.pending_join(force=True)):
                    # GROW fork: the stop was the chief's reshard request
                    # (reason "reshard" there, its collective mirror "peer
                    # preempted" elsewhere — both non-signal) and a join
                    # for the next round is pending. Every process reads
                    # the same files + config, so the fork agrees; commit
                    # a checkpoint for the next generation to restore and
                    # unwind into the barrier
                    if publisher is not None:
                        publisher.set_phase("save")
                    manager.save(step, state, force=True)
                    manager.wait_until_finished()
                    log.info("elastic: grow requested — checkpoint "
                             "committed at step %d; entering the join "
                             "barrier", step)
                    raise ReshardRequired("grow",
                                          f"pending join at step {step}")
                if publisher is not None:
                    publisher.set_phase("save")
                manager.save(step, state, force=True)
                manager.wait_until_finished()
                log.warning("preempted (%s): checkpoint committed at step "
                            "%d; exiting resumable", reason, step)
                raise Preempted(step, reason)
            # final checkpoint + drain async saves
            if cfg.checkpoint.save_every_steps or \
                    cfg.checkpoint.save_every_secs:
                if publisher is not None:
                    publisher.set_phase("save")
                manager.save(int(state.step), state, force=True)
    finally:
        # the listener is NOT uninstalled here — run_train owns it across
        # generations (a SIGTERM mid-reshard must still be caught)
        manager.close()
        if shard_writer is not None and shard_writer is not writer:
            shard_writer.close()  # the non-chief ckpt_shard stream
        if writer is not None:
            # tensorboardX buffers events (~2 min flush window): without
            # the close, the tail of a completed run's summaries is lost
            writer.close()
    return state, metrics


def run_eval(cfg: ExperimentConfig, max_evals: Optional[int] = None,
             timeout_secs: float = 0.0):
    writer = None
    if is_chief():
        writer = _make_writer(cfg, "eval")
    _configure_telemetry(cfg, writer, jax.process_index())
    try:
        with _watchdog_session(cfg, writer, None, role="eval") \
                as (publisher, watchdog):
            ev = Evaluator(cfg, writer=writer)
            if publisher is not None:
                # eval batches tick liveness; between rounds the evaluator
                # parks in the unmonitored "poll" phase (checkpoint
                # droughts are normal, not hangs)
                ev.trainer.heartbeat = publisher
                publisher.set_phase("poll")
            return ev.run(max_evals=max_evals, timeout_secs=timeout_secs)
    finally:
        if writer is not None:
            writer.close()  # flush buffered events (see run_train)


def run_serve(cfg: ExperimentConfig):
    """Inference server mode (serve/; docs/serving.md): restore the newest
    committed checkpoint, AOT-compile every batch bucket, serve dynamic
    request batches, hot-swap newer checkpoints with zero downtime.

    With ``serve.load_qps > 0`` the open-loop synthetic load generator
    drives the server for ``serve.load_duration_secs``, then a JSON report
    (p50/p99 per bucket, QPS, swaps, dropped-request count) prints and the
    process exits — scripts/serve_smoke.sh and capacity planning. With
    ``load_qps = 0`` the server runs until SIGINT/SIGTERM (requests come
    from in-process ``InferenceServer.submit`` embedders)."""
    import json as _json
    import time as _time

    from .serve.loadgen import run_open_loop, synthetic_requests
    from .serve.server import InferenceServer, serve_stream_dir

    serve_dir = serve_stream_dir(cfg)
    replica_id = cfg.serve.replica_id
    writer = _make_writer(cfg, os.path.basename(serve_dir)) \
        if is_chief() else None
    _configure_telemetry(cfg, writer, jax.process_index())
    server = InferenceServer(cfg, writer=writer)
    publisher = None
    listener = None
    if replica_id >= 0:
        # fleet replica: publish liveness beats under the replica id so
        # the router/supervisor can tell dead (no beats) from wedged
        # (beats flowing, requests failing) — docs/serving.md fleet
        from .resilience.heartbeat import (FileBeatTransport,
                                           HeartbeatPublisher)
        publisher = HeartbeatPublisher(
            FileBeatTransport(
                os.path.join(cfg.log_root, "heartbeats-serve"), replica_id),
            process_id=replica_id).start()
        publisher.set_phase("serve")
        server.heartbeat = publisher
    load = None
    try:
        server.start()
        if cfg.serve.listen_port > 0:
            from .serve.wire import ReplicaListener
            listener = ReplicaListener(server,
                                       cfg.serve.listen_port).start()
        # orchestration marker (scripts/serve_smoke.sh and the fleet
        # supervisor wait on it before publishing checkpoints / routing:
        # a commit landing before the initial restore would be picked up
        # at startup, not hot-swapped)
        os.makedirs(serve_dir, exist_ok=True)
        with open(os.path.join(serve_dir, "READY"), "w") as f:
            f.write(_json.dumps({
                "pid": os.getpid(),
                "port": listener.port if listener is not None else 0}))
        if cfg.serve.load_qps > 0:
            load = run_open_loop(server, cfg.serve.load_qps,
                                 cfg.serve.load_duration_secs,
                                 seed=cfg.serve.load_seed)
            if cfg.serve.wait_for_swap_secs > 0 and server.swaps == 0:
                # smoke determinism: a training publisher is racing us —
                # keep serving (idle) until its commit lands or we time out
                deadline = _time.monotonic() + cfg.serve.wait_for_swap_secs
                while server.swaps == 0 and _time.monotonic() < deadline:
                    _time.sleep(0.25)
            # post-load probe: a few requests AFTER any swap prove the
            # server still answers (the smoke's "zero downtime" witness)
            probes = [server.submit(im) for im in synthetic_requests(
                server.image_shape, server.image_dtype, pool=4,
                seed=cfg.serve.load_seed + 1)]
            for f in probes:
                f.result(timeout=120.0)
        else:
            # park until SIGTERM/SIGINT — HANDLED, not defaulted: the
            # default SIGTERM action would kill the process mid-request
            # (no drain, no close(), unresolved futures), and systemd/k8s
            # stop with SIGTERM. The finally below then drains: every
            # accepted request is answered before exit.
            import signal
            import threading
            stop = threading.Event()
            prev = {}
            if threading.current_thread() is threading.main_thread():
                for sig in (signal.SIGTERM, signal.SIGINT):
                    prev[sig] = signal.signal(
                        sig, lambda *_args: stop.set())
            log.info("serving (no load generator); SIGTERM/Ctrl-C stops "
                     "with a full drain")
            try:
                while not stop.wait(1.0):
                    pass
            except KeyboardInterrupt:
                pass
            finally:
                for sig, handler in prev.items():
                    signal.signal(sig, handler)
    finally:
        if listener is not None:
            listener.close()  # stop intake before the drain
        server.close()  # drains: every accepted request is answered
        if publisher is not None:
            publisher.close()
        if writer is not None:
            writer.close()
    report = server.report()
    if load is not None:
        report["load"] = load
    print(_json.dumps(report))
    return report


def run_route(cfg: ExperimentConfig):
    """Fleet front door mode (serve/router.py + serve/fleet.py;
    docs/serving.md fleet section): spawn ``route.replicas`` serving
    replica processes, route open-loop load across them with
    least-outstanding dispatch + hedged retries, watchdog-replace dead or
    wedged replicas, canary new checkpoints with auto-rollback, and shed
    or degrade under queue pressure.

    With ``route.load_qps > 0`` the open-loop generator
    (``route.load_shape`` arrival schedule) drives the fleet, an
    in-flight canary is drained to a verdict on trickle traffic, then a
    JSON report prints and the process exits — scripts/serve_fleet_smoke.sh
    and bench's serving_fleet row. With ``load_qps = 0`` the router runs
    until SIGTERM/SIGINT (requests would come from in-process submit)."""
    import json as _json
    import time as _time

    from .resilience.manifest import committed_steps
    from .serve.fleet import FleetSupervisor, write_pin
    from .serve.loadgen import run_open_loop, synthetic_requests
    from .serve.router import Router
    from .serve.server import serve_image_spec
    from .serve.wire import TcpReplicaClient

    route_dir = os.path.join(cfg.log_root, "route")
    writer = _make_writer(cfg, "route")
    _configure_telemetry(cfg, writer, 0)
    ckpt_dir = resolve_checkpoint_dir(cfg)
    fleet = FleetSupervisor(cfg, writer=writer)
    router = None
    load = None
    try:
        fleet.start()
        clients = {rid: TcpReplicaClient("127.0.0.1", port)
                   for rid, port in fleet.ports.items()}
        shape, dtype = serve_image_spec(cfg)
        router = Router(
            cfg.route, clients, shape, dtype, writer=writer,
            beats_dir=fleet.beats_dir,
            committed_steps_fn=lambda: committed_steps(ckpt_dir),
            pin_fn=lambda rid, step: write_pin(cfg.log_root, rid, step),
            initial_step=fleet.pinned_step).start()
        fleet.attach_router(router)
        fleet.start_watch()
        os.makedirs(route_dir, exist_ok=True)
        with open(os.path.join(route_dir, "READY"), "w") as f:
            f.write(_json.dumps({"pid": os.getpid()}))
        if cfg.route.load_qps > 0:
            load = run_open_loop(router, cfg.route.load_qps,
                                 cfg.route.load_duration_secs,
                                 seed=cfg.route.load_seed,
                                 shape=cfg.route.load_shape)
            # a checkpoint committed near the end of the load may not
            # have started its canary yet — give the health loop a few
            # turns to notice it before deciding whether to drain one
            grace = _time.monotonic() + 3 * cfg.route.health_interval_secs
            while (_time.monotonic() < grace
                   and router.canary.active is None):
                steps = committed_steps(ckpt_dir)
                newest = max(steps) if steps else -1
                if (newest <= router.canary.fleet_step
                        or newest in router.canary.bad_steps):
                    break
                _time.sleep(0.2)
            # drain an in-flight canary to a verdict: without traffic the
            # arms never accumulate samples and every canary would decay
            # to no_confirm/starved — trickle probes keep both arms fed
            pool = synthetic_requests(router.image_shape,
                                      router.image_dtype, pool=4,
                                      seed=cfg.route.load_seed + 1)
            deadline = _time.monotonic() + cfg.route.canary_window_secs \
                + cfg.route.canary_confirm_secs + 15.0
            i = 0
            while (router.canary.active is not None
                   and _time.monotonic() < deadline):
                fut = router.submit(pool[i % len(pool)])
                i += 1
                try:
                    fut.result(timeout=10.0)
                except Exception:  # noqa: BLE001 — probe losses are fine
                    pass
                _time.sleep(0.05)
        else:
            import signal
            import threading
            stop = threading.Event()
            prev = {}
            if threading.current_thread() is threading.main_thread():
                for sig in (signal.SIGTERM, signal.SIGINT):
                    prev[sig] = signal.signal(
                        sig, lambda *_args: stop.set())
            log.info("routing (no load generator); SIGTERM/Ctrl-C stops "
                     "with a full drain")
            try:
                while not stop.wait(1.0):
                    pass
            except KeyboardInterrupt:
                pass
            finally:
                for sig, handler in prev.items():
                    signal.signal(sig, handler)
    finally:
        if router is not None:
            router.close()  # before fleet.stop(): no requests race kills
        fleet.stop()
        writer.close()
    report = {"router": router.report() if router is not None else {},
              "fleet": fleet.report()}
    if load is not None:
        report["load"] = load
    print(_json.dumps(report))
    return report


def run_train_and_eval(cfg: ExperimentConfig):
    """In-process alternation: train eval_every_steps, then eval (the
    reference instead dedicated a whole node to the evaluator,
    run_dist_train_eval_daint.sh:211-222 — that mode still exists via two
    processes with mode=train / mode=eval)."""
    trainer = Trainer(cfg)
    trainer.init_state()
    _check_resume_config(cfg)
    manager = CheckpointManager(
        resolve_checkpoint_dir(cfg), max_to_keep=cfg.checkpoint.max_to_keep,
        save_every_steps=cfg.checkpoint.save_every_steps,
        save_every_secs=cfg.checkpoint.save_every_secs,
        async_save=cfg.checkpoint.async_save,
        layout_stamp=stacked_layout_stamp(cfg),
        verify_on_restore=cfg.resilience.verify_on_restore,
        io_retries=cfg.resilience.io_retries,
        sharded=cfg.checkpoint.sharded,
        finalize_timeout_secs=cfg.checkpoint.finalize_timeout_secs)
    if cfg.checkpoint.resume:
        trainer.state, _ = manager.restore(trainer.state)

    writer = _make_writer(cfg, "train") if is_chief() else None
    _configure_telemetry(cfg, writer, jax.process_index())
    # detection-only NaN guard (raises; the rollback sentinel is a
    # run_train capability — docs/resilience.md): dying loudly still beats
    # training and checkpointing NaN state to train_steps
    guard_every = cfg.resilience.nan_check_every_steps \
        or max(cfg.train.log_every_steps, 1)
    hooks = [NanGuardHook(every_steps=guard_every), CheckpointHook(manager)]
    if is_chief():
        hooks.append(LoggingHook(cfg.train.log_every_steps,
                                 batch_size=cfg.train.batch_size,
                                 print_fn=print))
        if writer:
            hooks.append(SummaryHook(writer, cfg.train.summary_every_steps))
            hooks.append(InputStagesHook(writer,
                                         cfg.train.summary_every_steps))
            if cfg.data.echo_factor > 1:
                hooks.append(InputEchoHook(writer,
                                           cfg.train.summary_every_steps))
            # corrupt-TFRecord tally exports here too — bit rot must be
            # visible in telemetry in every training mode
            hooks.append(CorruptRecordsHook(writer,
                                            cfg.train.summary_every_steps))
            if cfg.telemetry.enabled:  # see run_train: no spans, no rows
                hooks.append(GoodputHook(
                    writer, cfg.telemetry.goodput_every_steps
                    or cfg.train.summary_every_steps))
            hooks.append(CkptAsyncHook(writer,
                                       cfg.train.summary_every_steps))
            if trainer.comm_overlap_active:
                hooks.append(CommOverlapHook(
                    writer, cfg.train.summary_every_steps))
            if trainer.zero1_active:
                hooks.append(Zero1Hook(writer,
                                       cfg.train.summary_every_steps))
            if trainer.precision_active or trainer.comm_compress_active:
                hooks.append(PrecisionHook(
                    writer, cfg.train.summary_every_steps))
            if trainer.comm_compress_active:
                hooks.append(CommCompressHook(
                    writer, cfg.train.summary_every_steps))
            if trainer.comm_overlap_active and cfg.telemetry.comm_timing:
                hooks.append(CommTimingHook(
                    writer, cfg.train.summary_every_steps))
            # drift sentinel: see run_train
            if cfg.telemetry.plan_drift != "off" \
                    and trainer.comm_overlap_active:
                hooks.append(PlanDriftHook(
                    writer, cfg, trainer, cfg.train.summary_every_steps))
    # per-host sharded-ckpt + device-memory accounting: every process
    # exports, like run_train (the monitor's per-host rollup reads these)
    te_shard_writer = None
    if cfg.checkpoint.sharded != "off" or cfg.telemetry.memory:
        te_shard_writer = writer
        if te_shard_writer is None:
            te_shard_writer = _make_writer(
                cfg, f"train-p{jax.process_index()}")
        if cfg.checkpoint.sharded != "off":
            hooks.append(CkptShardHook(te_shard_writer,
                                       cfg.train.summary_every_steps))
        if cfg.telemetry.memory:
            hooks.append(MemoryHook(te_shard_writer,
                                    cfg.train.summary_every_steps))

    train_iter = _make_train_source(cfg, trainer)

    listener = None
    if cfg.resilience.handle_signals:
        listener = PreemptionListener(
            deadline_secs=cfg.resilience.deadline_secs)
        if not listener.install():
            listener = None
    stop_fn = None
    if listener is not None:
        stop_fn = collective_should_stop(listener) \
            if jax.process_count() > 1 else listener.should_stop

    every = cfg.train.eval_every_steps or cfg.checkpoint.save_every_steps or 1000
    best = 0.0
    step = int(trainer.state.step)
    result = {}
    try:
        with _watchdog_session(cfg, writer, listener, trainer) \
                as (publisher, watchdog):
            _arm_watchdog_hooks(hooks, publisher)
            while step < cfg.train.train_steps:
                target = min(step + every, cfg.train.train_steps)
                # phase flips to "train" at the first completed step via
                # HeartbeatHook (NOT here): round 1's first step carries
                # the XLA compile, which must stay in the unmonitored
                # "init" phase
                state, _ = trainer.train(train_iter, num_steps=target,
                                         hooks=tuple(hooks), start_step=step,
                                         stop_fn=stop_fn)
                step = int(state.step)
                preempted = collective_preempted(listener) \
                    if listener is not None else False
                if preempted and step < cfg.train.train_steps:
                    if publisher is not None:
                        publisher.set_phase("save")
                    manager.save(step, trainer.state, force=True)
                    manager.wait_until_finished()
                    log.warning("preempted (%s): checkpoint committed at "
                                "step %d; exiting resumable",
                                listener.reason(), step)
                    raise Preempted(step, listener.reason())
                # fresh iterator per round: the ImageNet eval stream is
                # one-pass
                result = trainer.evaluate(
                    make_eval_iterator(cfg, trainer.mesh),
                    cfg.eval.eval_batch_count)
                best = max(best, result["precision"])
                if writer:
                    writer.write_scalars(
                        step, {"eval/precision": result["precision"],
                               "eval/best_precision": best})
                if is_chief():
                    print(f"eval @ step {step}: precision "
                          f"{result['precision']:.4f} best {best:.4f}")
            if publisher is not None:
                publisher.set_phase("save")
            manager.save(step, trainer.state, force=True)
    finally:
        if listener is not None:
            listener.uninstall()
        manager.close()
        if te_shard_writer is not None and te_shard_writer is not writer:
            te_shard_writer.close()  # the non-chief ckpt_shard stream
        if writer:
            # flush buffered tensorboardX events even on a mid-run error
            writer.close()
    return trainer.state, {**result, "best_precision": best}


def main(argv=None):
    # force=True: absl/jax may have already claimed the root logger, which
    # would otherwise swallow our INFO lines (e.g. the resume notice)
    logging.basicConfig(
        level=logging.INFO, force=True,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "check":
        # shardcheck gate (analysis/): lint + static elaboration on a
        # virtual CPU mesh — no cluster, no data (docs/static_analysis.md)
        from .analysis.cli import main_check
        sys.exit(main_check(argv[1:]))
    if argv and argv[0] == "monitor":
        # cluster rollup (telemetry/monitor.py, docs/observability.md):
        # tails every metrics stream + heartbeat file under a log_root —
        # pure filesystem reads, no jax world, safe beside a live run
        from .telemetry.monitor import main_monitor
        sys.exit(main_monitor(argv[1:]))
    if argv and argv[0] == "trace-merge":
        # cluster trace correlation (telemetry/merge.py): merge the
        # per-process trace[.procN].json dumps onto ONE timeline with
        # per-host lanes + heartbeat-estimated clock offsets — pure
        # filesystem reads, like monitor
        from .telemetry.merge import main_trace_merge
        sys.exit(main_trace_merge(argv[1:]))
    if argv and argv[0] == "comm-report":
        # per-collective runtime attribution (telemetry/comm_report.py):
        # join the committed collective schedule with the measured
        # per-bucket exchange timings into achieved bytes/sec per bucket
        from .telemetry.comm_report import main_comm_report
        sys.exit(main_comm_report(argv[1:]))
    if argv and argv[0] == "plan":
        # what-if performance planner (telemetry/planner.py,
        # docs/planner.md): predict step time / HBM watermark / comm
        # fraction per layout × knob candidate from the committed
        # collective schedules × the fabric's bandwidth catalog, rank
        # them, RECOMMEND a layout — no cluster needed
        from .telemetry.planner import main_plan
        sys.exit(main_plan(argv[1:]))
    serve_cmd = False
    if argv and argv[0] == "serve":
        # inference server (serve/, docs/serving.md): same flags as the
        # trainer — `main.py serve --preset X --set serve.load_qps=...`
        # is sugar for `--set mode=serve`
        serve_cmd = True
        argv = argv[1:]
    route_cmd = False
    if argv and argv[0] == "route":
        # serving-fleet front door (serve/router.py + serve/fleet.py,
        # docs/serving.md fleet section) — sugar for `--set mode=route`
        route_cmd = True
        argv = argv[1:]
    # honor JAX_PLATFORMS even when a site plugin (e.g. this environment's
    # axon sitecustomize) overrode it via jax.config at interpreter start
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    cfg = parse_args(argv)
    if serve_cmd:
        cfg.mode = "serve"
    if route_cmd:
        cfg.mode = "route"
    if cfg.analysis.dispatch_sanitizer:
        # opt-in cross-thread dispatch guard (analysis/dispatch_sanitizer):
        # a second dispatching thread raises at its call site instead of
        # deadlocking the next collective
        from .analysis.dispatch_sanitizer import install as _install_ds
        _install_ds()
        log.info("dispatch sanitizer armed (analysis.dispatch_sanitizer)")
    if os.environ.get("DRT_ELASTIC_REJOIN"):
        # elastic rejoin (resilience/elastic.py): the generation this
        # worker died in is gone and its coordinator port with it —
        # run_train joins the live fleet's barrier and initializes into
        # the NEXT generation instead of the config's dead world
        log.info("elastic rejoin: deferring distributed init to the "
                 "join barrier")
    else:
        initialize_from_config(cfg.mesh)
    log.info("devices: %d (%d processes)", jax.device_count(),
             jax.process_count())
    try:
        if cfg.mode == "train":
            run_train(cfg)
        elif cfg.mode == "eval":
            run_eval(cfg, timeout_secs=0.0 if cfg.eval.eval_once else 86400.0)
        elif cfg.mode == "train_and_eval":
            run_train_and_eval(cfg)
        elif cfg.mode == "serve":
            run_serve(cfg)
        elif cfg.mode == "route":
            run_route(cfg)
        else:
            raise ValueError(f"unknown mode {cfg.mode!r}")
    except Preempted as p:
        # the exit-code contract launchers key off (docs/resilience.md):
        # 75 = checkpoint committed, relaunch to resume
        log.info("%s", p)
        sys.exit(RESUMABLE_EXIT_CODE)
    except Exception as e:
        # non-zero exit: leave the flight-recorder dump next to the run —
        # the post-mortem's first stop (telemetry/tracer.py; never raises)
        _flight_recorder.dump_on_anomaly(
            "exception", f"{type(e).__name__}: {e}"[:300])
        if jax.process_count() > 1:
            # a real failure with peers still alive: the run published a
            # final phase="failed" beat (peers stop through their
            # watchdogs) — exit hard NOW. sys.exit would run atexit's
            # jax.distributed.shutdown, whose barrier waits on peers that
            # are already leaving: measured minutes of hang per crash
            log.exception("fatal error in a multi-process run; exiting 1 "
                          "without the distributed shutdown barrier")
            logging.shutdown()
            os._exit(1)
        raise


if __name__ == "__main__":
    main(sys.argv[1:])
