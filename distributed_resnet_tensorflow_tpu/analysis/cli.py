"""The ``check`` subcommand: lint + static elaboration in one gate.

    python -m distributed_resnet_tensorflow_tpu.main check --all-presets
    python -m distributed_resnet_tensorflow_tpu.main check --preset smoke
    python -m distributed_resnet_tensorflow_tpu.main check --lint-only

Exit code 0 = clean, 1 = findings (the exit-code contract's real-failure
code: a red gate must fail the submit). Designed to finish in well under
a minute on CPU — scripts/analysis_gate.sh runs it pre-submit
(scripts/submit_tpu_slurm.sh) and pre-merge (scripts/chaos_smoke.sh
--fast). docs/static_analysis.md is the manual.
"""
from __future__ import annotations

import argparse
import time
from typing import Optional, Sequence


def main_check(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="main.py check",
        description="shardcheck: invariant lint + static elaboration")
    scope = p.add_mutually_exclusive_group()
    scope.add_argument("--all-presets", action="store_true",
                       help="elaborate every preset (also the default)")
    scope.add_argument("--preset", action="append", default=[],
                       help="elaborate only this preset (repeatable)")
    depth = p.add_mutually_exclusive_group()
    depth.add_argument("--lint-only", action="store_true",
                       help="skip elaboration")
    depth.add_argument("--elaborate-only", action="store_true",
                       help="skip the linter")
    p.add_argument("--devices", type=int, default=8,
                   help="virtual CPU mesh size for elaboration (default 8)")
    p.add_argument("--no-zero1-sweep", action="store_true",
                   help="skip the 64/256-device ZeRO-1 big-mesh sweep "
                        "(elab-zero1)")
    p.add_argument("--no-hangcheck", action="store_true",
                   help="skip the hangcheck phases (ISSUE 13): the "
                        "collective-schedule extraction and the "
                        "thread/lock contract rules (cross-thread-"
                        "dispatch, untimed-blocking-call, chief-gated-"
                        "collective, lock-order-cycle); elaborate then "
                        "re-owns the overlap/compress step traces")
    p.add_argument("--no-plan-drift", action="store_true",
                   help="skip the plan-drift phase (ISSUE 17): the "
                        "what-if planner's predictions over the "
                        "committed schedules, the plan_catalog.json "
                        "refresh, and the bandwidth-catalog sanity "
                        "cross-check")
    p.add_argument("--no-protocol", action="store_true",
                   help="skip the protocol phase (ISSUE 20): the "
                        "exhaustive model check of the declared control-"
                        "plane protocols (elastic reshard barrier, "
                        "sharded-checkpoint commit, replica health/"
                        "replace ladder, canary swap pin), the "
                        "protocol_models.json refresh, and the "
                        "protocol-drift lint rule")
    p.add_argument("--root", default=None, help=argparse.SUPPRESS)
    # --root scopes the LINT pass to another tree (tests of the exit-code
    # contract run the real CLI over a known-bad fixture repo)
    p.add_argument("-v", "--verbose", action="store_true",
                   help="print finding detail (full tracebacks)")
    ns = p.parse_args(argv)

    findings = []
    t0 = time.perf_counter()
    if not ns.lint_only:
        # the virtual mesh must exist BEFORE the first jax backend use —
        # and the LINT pass is now a backend user too (unsharded-opt-state
        # resolves preset states via eval_shape), so the flags go down
        # before anything else runs. Sized for the big-mesh ZeRO-1 sweep
        # when it runs (virtual CPU devices are threads over one host
        # platform; 256 of them cost ~nothing at eval_shape-only load).
        from ..utils.virtual_devices import apply_virtual_cpu
        from .elaborate import ZERO1_SWEEP_SIZES
        n_virtual = ns.devices if ns.no_zero1_sweep \
            else max(ns.devices, max(ZERO1_SWEEP_SIZES))
        apply_virtual_cpu(n_virtual)
    if not ns.elaborate_only:
        from .lint import run_lint
        rule_names = None
        if ns.no_hangcheck or ns.no_protocol:
            from . import rules as rules_pkg
            off = set()
            if ns.no_hangcheck:
                off |= {m.RULE_NAME for m in rules_pkg.HANGCHECK_RULES}
            if ns.no_protocol:
                off |= {m.RULE_NAME for m in rules_pkg.PROTOCOL_RULES}
            rule_names = [m.RULE_NAME for m in rules_pkg.ALL_RULES
                          if m.RULE_NAME not in off]
        findings += run_lint(root=ns.root, rule_names=rule_names)
        print(f"lint: {len(findings)} finding(s) "
              f"[{time.perf_counter() - t0:.1f}s]")
    if not ns.lint_only:
        from .elaborate import run_elaborate
        t1 = time.perf_counter()
        presets = ns.preset or None  # None = all
        efs = run_elaborate(presets, n_devices=ns.devices,
                            trace_comm_variants=ns.no_hangcheck)
        print(f"elaborate: {len(efs)} finding(s) "
              f"[{time.perf_counter() - t1:.1f}s]")
        findings += efs
        if not ns.no_zero1_sweep:
            from .elaborate import run_elaborate_zero1
            t2 = time.perf_counter()
            zfs = run_elaborate_zero1(presets)
            print(f"elab-zero1 (64/256-device sweep): {len(zfs)} "
                  f"finding(s) [{time.perf_counter() - t2:.1f}s]")
            findings += zfs
        if not ns.no_hangcheck:
            # hangcheck-schedule (docs/static_analysis.md): collective
            # schedules extracted from the traced jaxprs, determinism +
            # declared-bucket-plan cross-checks, reviewable artifact.
            # This phase OWNS the overlap/compress step traces while it
            # runs (trace_comm_variants=False above) — same trace, more
            # signal.
            from .collectives import run_collectives, write_artifact
            t3 = time.perf_counter()
            cfs, sigs = run_collectives(presets, n_devices=ns.devices)
            print(f"hangcheck-schedule: {len(cfs)} finding(s), "
                  f"{len(sigs)} signature(s) "
                  f"[{time.perf_counter() - t3:.1f}s]")
            findings += cfs
            if presets is None and ns.root is None and ns.devices == 8:
                # full sweeps at the canonical 8-device mesh refresh the
                # committed artifact — a partial run must not shrink it,
                # and a --devices override changes layouts/payload bytes
                # (the artifact diff must only ever mean a comm change)
                path = write_artifact(sigs)
                print(f"hangcheck-schedule: wrote {path}")
        if not ns.no_plan_drift:
            # plan-drift (docs/planner.md): the what-if planner re-costed
            # over the committed collective schedules with the reference
            # constants, plus the measured bandwidth-catalog cross-check
            # against a live micro-probe — a comm/perf regression becomes
            # a reviewable plan_catalog.json diff, a corrupted bandwidth
            # table a red gate
            from .plan_drift import run_plan_drift, write_plan_catalog
            t4 = time.perf_counter()
            sigs_for_plan = None
            if not ns.no_hangcheck and presets is None:
                # full sweeps cost the freshly traced map; a scoped run
                # (--preset X) only traced X's schedules, so costing the
                # planned presets against it would flag every other one
                # as missing — fall back to the committed artifact
                sigs_for_plan = sigs
            pfs, plan_doc = run_plan_drift(sigs_for_plan,
                                           n_devices=ns.devices)
            print(f"plan-drift: {len(pfs)} finding(s), "
                  f"{len(plan_doc.get('plans', {}))} preset plan(s) "
                  f"[{time.perf_counter() - t4:.1f}s]")
            findings += pfs
            if presets is None and ns.root is None and ns.devices == 8:
                # same refresh guard as the schedule artifact above: the
                # plan catalog must only ever diff on a real model /
                # schedule change, never on a partial or resized run
                path = write_plan_catalog(plan_doc)
                print(f"plan-drift: wrote {path}")
        if not ns.no_protocol:
            # protocol (docs/static_analysis.md): BFS over every
            # interleaving of the four declared control-plane protocols
            # at their small-scope bounds — safety counterexamples and
            # liveness traps as findings, model inventory as the
            # committed protocol_models.json artifact
            from .protocol import run_protocol, write_artifact as write_pm
            t5 = time.perf_counter()
            prfs, pm_doc = run_protocol()
            print(f"protocol: {len(prfs)} finding(s), "
                  f"{len(pm_doc.get('specs', {}))} protocol(s) "
                  f"[{time.perf_counter() - t5:.1f}s]")
            findings += prfs
            if ns.root is None:
                # the models live in THIS package's sources, not the
                # --root tree under lint — a fixture-tree run must not
                # rewrite the committed inventory
                path = write_pm(pm_doc)
                print(f"protocol: wrote {path}")

    from .report import format_findings
    print(format_findings(findings, verbose=ns.verbose))
    print(f"shardcheck total: {time.perf_counter() - t0:.1f}s")
    return 1 if findings else 0
