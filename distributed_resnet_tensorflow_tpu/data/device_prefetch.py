"""Device prefetch + background input threads.

The reference's analog was tf.data's prefetch buffering and the 16-thread
queue runners (reference resnet_cifar_main.py:232, cifar_input.py:77-96).
Here:

  * ``device_prefetch``   — keep ``depth`` host→device transfers in flight
    behind compute (JAX transfers are asynchronous).
  * ``threaded_iterator`` — run ANY iterator on a background thread with a
    bounded queue; the single implementation of the worker/stop/error
    machinery used by every threaded input stage.
  * ``threaded_stacker``  — draw K batches + np.stack on a background thread
    (the input side of the fused ``steps_per_loop`` dispatch).

All returned generators stop their worker thread when closed — a replaced
or abandoned pipeline must not leave a thread parked on its queue holding
batches.
"""
from __future__ import annotations

import collections
import queue as queue_mod
import threading
from typing import Callable, Iterator


def device_prefetch(host_iter: Iterator, put: Callable, depth: int = 2
                    ) -> Iterator:
    """Yield device-resident batches with ``depth`` transfers in flight.

    ``put`` is the host→device placement fn (e.g. Trainer._put_batch). The
    queue keeps ``depth`` batches already dispatched; pulling one immediately
    dispatches the next, so transfers run behind compute.
    """
    queue: collections.deque = collections.deque()
    try:
        try:
            for _ in range(depth):
                queue.append(put(next(host_iter)))
        except StopIteration:
            pass
        while queue:
            out = queue.popleft()
            try:
                queue.append(put(next(host_iter)))
            except StopIteration:
                pass
            yield out
    finally:
        # propagate close() (e.g. Trainer replacing its cached prefetcher)
        # down to the source so worker threads shut down
        close = getattr(host_iter, "close", None)
        if close is not None:
            close()


class _WorkerError:
    def __init__(self, exc: BaseException):
        self.exc = exc


_STOP = object()


def threaded_iterator(src: Iterator, depth: int = 2,
                      name: str = "drt-input-worker") -> Iterator:
    """Run ``src`` on a daemon thread feeding a bounded queue of ``depth``.

    Worker exceptions re-raise on the consuming thread; closing the returned
    generator (or GC'ing it) sets a stop event that EVERY queue put honors —
    including the terminal sentinel/error puts — so the thread can never
    park forever on a full queue.
    """
    q: queue_mod.Queue = queue_mod.Queue(maxsize=depth)
    stop = threading.Event()

    def put_checked(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                # re-check after the put: the consumer's shutdown drain may
                # have freed the slot we just filled — starting another
                # next(src) now would outlive the join and leak nested
                # workers, so report shutdown even though the put landed
                return not stop.is_set()
            except queue_mod.Full:
                continue
        return False

    def worker():
        try:
            for item in src:
                if not put_checked(item):
                    return
            put_checked(_STOP)
        except BaseException as e:  # surface on the consumer thread
            put_checked(_WorkerError(e))

    thread = threading.Thread(target=worker, daemon=True, name=name)
    thread.start()
    try:
        while True:
            item = q.get()
            if item is _STOP:
                return
            if isinstance(item, _WorkerError):
                raise item.exc
            yield item
    finally:
        stop.set()
        # The worker may still be executing next(src); a generator cannot be
        # closed from another thread while executing, so unblock any pending
        # put and join (briefly) before closing. A worker stuck in blocking
        # IO is a daemon thread — abandoned after the timeout, and close()
        # then tolerates the cross-thread race.
        try:
            q.get_nowait()
        except queue_mod.Empty:
            pass
        try:
            thread.join(timeout=1.0)
        except TypeError:
            # interpreter teardown: a GC'd generator can land here after
            # threading internals are already None'd out
            pass
        close = getattr(src, "close", None)
        if close is not None:
            try:
                close()
            except ValueError:  # generator still executing on the worker
                pass


def threaded_stacker(host_iter: Iterator, k: int, depth: int = 2) -> Iterator:
    """Draw K batches and np.stack them in a background thread.

    This is the input side of the fused ``steps_per_loop`` dispatch
    (Trainer.jitted_multi_step): the K-batch draw + stack is real host work
    (decode, memcpy) that would otherwise sit between scan dispatches; a
    bounded queue of ``depth`` pre-stacked loops keeps the dispatch thread
    hot. Iterator exhaustion ends the stream cleanly (a trailing partial
    group of < k batches is dropped); closing the returned generator stops
    the worker thread.
    """
    import numpy as np

    def groups():
        while True:
            try:
                batches = [next(host_iter) for _ in range(k)]
            except StopIteration:
                return
            yield {key: np.stack([b[key] for b in batches])
                   for key in batches[0]}

    return threaded_iterator(groups(), depth, name="drt-batch-stacker")
