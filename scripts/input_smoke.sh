#!/bin/bash
# Input-pipeline smoke (round 9) — the echoing / parallel-decode / fused-
# augment stack exercised end-to-end on synthetic JPEG data, CPU-only,
# in a couple of minutes:
#
#   * builds a tiny ImageNet-format TFRecord dataset (tools/make_synth_imagenet),
#   * trains N steps with data echoing (echo_factor=2), decode worker
#     PROCESSES (decode_processes=2), the fused on-device augmentation
#     (device_augment=on + coalesced_transfer=on) and the cross-thread
#     dispatch sanitizer ARMED,
#   * asserts from metrics.jsonl that the {"event": "input_stages"} rows
#     show more than one busy decode worker and the {"event": "input_echo"}
#     rows show echo hits > 0 — the telemetry contract bench.py's
#     attribution is built on.
#
#   scripts/input_smoke.sh            # full smoke
#
# Exit 0 = green; any assertion failure or training error is nonzero.
set -euo pipefail
cd "$(dirname "$0")/.."

ROOT="${TMPDIR:-/tmp}/drt_input_smoke"
DATA="$ROOT/data"
LOGS="$ROOT/logs"
rm -rf "$ROOT"
mkdir -p "$DATA"

echo "== input_smoke: synthesizing JPEG TFRecord shards"
env JAX_PLATFORMS=cpu python - "$DATA" <<'PYEOF'
import sys, os
sys.path.insert(0, "tools")
from make_synth_imagenet import write_split
write_split(sys.argv[1], "train", 4, 4, num_classes=8, per_class=8, seed=0)
PYEOF

echo "== input_smoke: train with echoing + decode processes + fused augment"
env JAX_PLATFORMS=cpu python -m distributed_resnet_tensorflow_tpu.main \
  --preset imagenet_resnet50 \
  --set model.resnet_size=18 \
  --set model.num_classes=8 \
  --set model.compute_dtype=float32 \
  --set data.data_dir="$DATA" \
  --set data.image_size=32 \
  --set data.echo_factor=2 \
  --set data.decode_processes=2 \
  --set data.num_parallel_calls=2 \
  --set data.device_augment=on \
  --set data.coalesced_transfer=on \
  --set analysis.dispatch_sanitizer=true \
  --set train.batch_size=8 \
  --set train.train_steps=8 \
  --set train.log_every_steps=2 \
  --set train.summary_every_steps=2 \
  --set checkpoint.save_every_steps=0 \
  --set checkpoint.save_every_secs=0 \
  --set resilience.handle_signals=false \
  --set log_root="$LOGS"

echo "== input_smoke: asserting telemetry"
env JAX_PLATFORMS=cpu python - "$LOGS/train" <<'PYEOF'
import sys
from distributed_resnet_tensorflow_tpu.utils.metrics import read_metrics
rows = read_metrics(sys.argv[1], tolerant=True)
stages = [r for r in rows if r.get("event") == "input_stages"]
echo = [r for r in rows if r.get("event") == "input_echo"]
assert stages, "no input_stages rows exported"
last = stages[-1]["stages"]
dec = last.get("decode") or {}
assert dec.get("items", 0) > 0, f"no decode items recorded: {last}"
# >1 busy worker: the decode-process pool's per-worker counter merge
# (_StageDelta) must surface more than one worker cell
assert dec.get("workers", 0) > 1, \
    f"expected >1 busy decode workers, got {dec}"
assert echo, "no input_echo rows exported"
e = echo[-1]
assert e["hits"] > 0, f"expected echo hits > 0: {e}"
assert e["echo_factor"] == 2
print(f"input_smoke OK: decode workers={dec['workers']} "
      f"items={dec['items']}, echo hits={e['hits']} "
      f"hit_rate={e['hit_rate']}")
PYEOF

echo "== input_smoke: green"
