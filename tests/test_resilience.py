"""Fault-injection suite for the resilience subsystem (docs/resilience.md):
preemption signals, crash-consistent checkpoint commit/fallback, NaN
rollback + LR back-off, bounded retries. Run standalone via
scripts/chaos_smoke.sh; everything here is tier-1 (CPU fake mesh)."""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.checkpoint import (
    CheckpointManager, wait_for_new_checkpoint)
from distributed_resnet_tensorflow_tpu.checkpoint.manager import (
    CheckpointCorrupt)
from distributed_resnet_tensorflow_tpu.data import learnable_synthetic_iterator
from distributed_resnet_tensorflow_tpu.resilience import (
    Preempted, PreemptionListener, RESUMABLE_EXIT_CODE,
    committed_steps, retry_call)
from distributed_resnet_tensorflow_tpu.resilience import faultinject
from distributed_resnet_tensorflow_tpu.resilience.sentinel import (
    TooManyNanRetries, train_with_nan_recovery)
from distributed_resnet_tensorflow_tpu.resilience.manifest import (
    manifest_status)
from distributed_resnet_tensorflow_tpu.train import Trainer
from distributed_resnet_tensorflow_tpu.train.hooks import NanGuardHook
from distributed_resnet_tensorflow_tpu.utils.config import get_preset


# ---------------------------------------------------------------------------
# retry.py
# ---------------------------------------------------------------------------

def test_retry_succeeds_after_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert retry_call(flaky, retries=3, base_delay=0.0,
                      sleep=lambda s: None) == "ok"
    assert len(calls) == 3


def test_retry_bounded_and_reraises_original():
    calls = []

    def always_down():
        calls.append(1)
        raise OSError("still down")

    with pytest.raises(OSError, match="still down"):
        retry_call(always_down, retries=2, base_delay=0.0,
                   sleep=lambda s: None)
    assert len(calls) == 3  # 1 original + 2 retries, no more


def test_retry_giveup_short_circuits_permanent_errors():
    calls = []

    def already():
        calls.append(1)
        raise RuntimeError("coordinator already initialized")

    with pytest.raises(RuntimeError):
        retry_call(already, retries=5, base_delay=0.0,
                   retry_on=(RuntimeError,),
                   giveup=lambda e: "already" in str(e),
                   sleep=lambda s: None)
    assert len(calls) == 1  # permanent: no retries burned


# ---------------------------------------------------------------------------
# preemption.py
# ---------------------------------------------------------------------------

def test_preemption_listener_flags_sigterm_and_restores_handler():
    prev = signal.getsignal(signal.SIGTERM)
    listener = PreemptionListener()
    assert listener.install()
    try:
        assert not listener.should_stop()
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5.0
        while not listener.should_stop() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert listener.preempted()
        assert "SIGTERM" in listener.reason()
    finally:
        listener.uninstall()
    assert signal.getsignal(signal.SIGTERM) is prev


def test_preemption_deadline():
    listener = PreemptionListener(signals=(), deadline_secs=0.05)
    with listener:
        assert not listener.preempted() or True  # may legally be False yet
        time.sleep(0.06)
        assert listener.should_stop()
        assert listener.reason() == "deadline"


# ---------------------------------------------------------------------------
# commit protocol + restore fallback (no model compile: minimal state)
# ---------------------------------------------------------------------------

class _State:
    """Minimal TrainState-like object for CheckpointManager."""

    def __init__(self, v: float):
        self.step = int(v)
        self.params = {"w": np.full(256, float(v), np.float32)}
        self.batch_stats = {}
        self.opt_state = {}

    def replace(self, **kw):
        out = _State(0)
        out.__dict__.update(self.__dict__)
        out.__dict__.update(kw)
        return out


def _fill(state) -> float:
    return float(np.asarray(state.params["w"])[0])


def test_commit_protocol_manifest_and_no_staging(tmp_path):
    d = str(tmp_path / "c")
    m = CheckpointManager(d, async_save=False)
    m.save(1, _State(1))
    assert m.all_steps() == [1]
    # committed layout: bare-numeric dir, verified manifest, no staging left
    assert manifest_status(os.path.join(d, "1")) == ("ok", "")
    assert not [n for n in os.listdir(d) if n.startswith("_staging")]
    # the evaluator's poll primitive sees the committed step...
    assert wait_for_new_checkpoint(d, None, timeout_secs=0.0) == 1
    # ...but never a staging dir
    os.makedirs(os.path.join(d, "_staging.9"))
    assert wait_for_new_checkpoint(d, 1, timeout_secs=0.0) is None
    m.close()


def test_torn_latest_falls_back_to_previous_valid(tmp_path):
    d = str(tmp_path / "c")
    m = CheckpointManager(d, async_save=False)
    for s in (1, 2, 3):
        m.save(s, _State(s))
    faultinject.corrupt_checkpoint(d, mode="truncate")  # tears step 3
    st, step = m.restore(_State(0))
    assert step == 2 and _fill(st) == 2.0
    # the damaged dir is quarantined so a re-trained step 3 can commit
    assert committed_steps(d) == [1, 2]
    assert os.path.isdir(os.path.join(d, "3.corrupt"))
    m.save(3, _State(33))  # re-commit after rollback must not be blocked
    st, step = m.restore(_State(0))
    assert step == 3 and _fill(st) == 33.0
    m.close()


def test_bitflip_detected_by_checksum(tmp_path):
    """Same size, one byte flipped — only the SHA-256 can catch this."""
    d = str(tmp_path / "c")
    m = CheckpointManager(d, async_save=False)
    m.save(1, _State(1))
    m.save(2, _State(2))
    faultinject.corrupt_checkpoint(d, step=2, mode="flip")
    status, detail = manifest_status(os.path.join(d, "2"))
    assert status == "bad" and "checksum" in detail
    st, step = m.restore(_State(0))
    assert step == 1 and _fill(st) == 1.0
    m.close()


def test_explicitly_requested_corrupt_step_raises(tmp_path):
    d = str(tmp_path / "c")
    m = CheckpointManager(d, async_save=False)
    m.save(1, _State(1))
    m.save(2, _State(2))
    faultinject.corrupt_checkpoint(d, step=2, mode="truncate")
    with pytest.raises(CheckpointCorrupt):
        m.restore(_State(0), step=2)
    m.close()


def test_all_checkpoints_corrupt_refuses_fresh_start(tmp_path):
    d = str(tmp_path / "c")
    m = CheckpointManager(d, async_save=False)
    m.save(1, _State(1))
    m.save(2, _State(2))
    faultinject.corrupt_checkpoint(d, step=1, mode="flip")
    faultinject.corrupt_checkpoint(d, step=2, mode="truncate")
    with pytest.raises(CheckpointCorrupt, match="refusing"):
        m.restore(_State(0))
    m.close()


def test_legacy_checkpoint_without_manifest_restores(tmp_path):
    d = str(tmp_path / "c")
    m = CheckpointManager(d, async_save=False)
    m.save(1, _State(1))
    os.remove(os.path.join(d, "1", "MANIFEST.json"))
    m2 = CheckpointManager(d, async_save=False)
    st, step = m2.restore(_State(0))
    assert step == 1 and _fill(st) == 1.0
    m.close(); m2.close()


def test_async_save_commits_retains_and_sweeps(tmp_path):
    d = str(tmp_path / "c")
    os.makedirs(os.path.join(d, "_staging.7"))  # crashed-writer leftover
    m = CheckpointManager(d, async_save=True, max_to_keep=2)
    assert not os.path.isdir(os.path.join(d, "_staging.7"))  # swept at init
    for s in (1, 2, 3):
        m.save(s, _State(s))
    m.wait_until_finished()
    assert m.all_steps() == [2, 3]  # retention applied
    st, step = m.restore(_State(0))
    assert step == 3 and _fill(st) == 3.0
    m.close()


# ---------------------------------------------------------------------------
# NaN sentinel (real Trainer, logistic model for compile speed)
# ---------------------------------------------------------------------------

def _tiny_cfg(tmp_path):
    cfg = get_preset("smoke")
    cfg.model.name = "logistic"
    cfg.model.input_size = 192  # 8*8*3
    cfg.model.hidden_units = 32
    cfg.model.num_classes = 4
    cfg.model.compute_dtype = "float32"
    cfg.data.image_size = 8
    cfg.train.batch_size = 16
    cfg.train.log_every_steps = 1
    cfg.optimizer.schedule = "constant"
    cfg.optimizer.learning_rate = 0.05
    cfg.log_root = str(tmp_path)
    cfg.checkpoint.directory = os.path.join(str(tmp_path), "ckpt")
    cfg.checkpoint.async_save = False
    return cfg


def test_nan_guard_checks_grad_norm_too():
    h = NanGuardHook(every_steps=1)
    h(1, None, {"loss": 1.0, "grad_norm": 2.0})  # finite: no raise
    with pytest.raises(NanGuardHook.NanLossError, match="grad_norm"):
        h(2, None, {"loss": 1.0, "grad_norm": float("inf")})


def test_nan_sentinel_rolls_back_backs_off_and_recovers(tmp_path):
    cfg = _tiny_cfg(tmp_path)
    tr = Trainer(cfg)
    tr.init_state()
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=False)
    state, _ = tr.train(learnable_synthetic_iterator(16, 8, 4), num_steps=5)
    mngr.save(5, state)
    base_lr = float(tr.schedule(0))

    def factory(attempt):
        if attempt == 0:  # 3rd batch after resume (step 8) goes NaN
            return faultinject.inject_nan(
                learnable_synthetic_iterator(16, 8, 4, seed=1), at_batch=3)
        return learnable_synthetic_iterator(16, 8, 4, seed=10 + attempt)

    guard = NanGuardHook(every_steps=1)
    state, metrics = train_with_nan_recovery(
        tr, mngr, factory, num_steps=20, hooks=(guard,), start_step=5,
        max_strikes=2, lr_backoff=0.5)
    # the run converged to the target step despite the injected NaN...
    assert int(state.step) == 20
    assert np.isfinite(float(metrics["loss"]))
    import jax
    leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(state.params)]
    assert all(np.isfinite(l).all() for l in leaves)
    # ...after exactly one rollback with the LR backed off 0.5x
    assert float(tr.schedule(0)) == pytest.approx(0.5 * base_lr)
    mngr.close()


def test_nan_sentinel_gives_up_after_max_strikes(tmp_path):
    cfg = _tiny_cfg(tmp_path)
    tr = Trainer(cfg)
    tr.init_state()
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=False)

    def factory(attempt):  # every attempt is poisoned immediately
        return faultinject.inject_nan(
            learnable_synthetic_iterator(16, 8, 4, seed=attempt), at_batch=1)

    guard = NanGuardHook(every_steps=1)
    with pytest.raises(TooManyNanRetries):
        train_with_nan_recovery(tr, mngr, factory, num_steps=10,
                                hooks=(guard,), max_strikes=2, lr_backoff=0.5)
    mngr.close()


# ---------------------------------------------------------------------------
# stop_fn + run_train preemption wiring
# ---------------------------------------------------------------------------

def test_trainer_stop_fn_stops_at_step_boundary(tmp_path):
    cfg = _tiny_cfg(tmp_path)
    tr = Trainer(cfg)
    tr.init_state()
    seen = []

    def hook(step, state, metrics):
        seen.append(step)

    state, _ = tr.train(learnable_synthetic_iterator(16, 8, 4),
                        num_steps=50, hooks=(hook,),
                        stop_fn=lambda: len(seen) >= 3)
    assert int(state.step) == 3
    assert seen == [1, 2, 3]  # no extra steps after the stop


def test_run_train_deadline_preempts_commits_and_resumes(tmp_path):
    """The in-process analog of a maintenance-window preemption: run_train
    under a deadline stops at a step boundary, commits a checkpoint, and
    raises Preempted; a relaunch resumes from exactly that step."""
    from distributed_resnet_tensorflow_tpu.main import run_train
    cfg = _tiny_cfg(tmp_path)
    cfg.train.train_steps = 100000  # unbounded-ish: only the deadline stops it
    cfg.checkpoint.save_every_steps = 100000  # no cadence save before preempt
    cfg.checkpoint.save_every_secs = 0.0
    cfg.resilience.deadline_secs = 1.0  # elapses during/after compile
    with pytest.raises(Preempted):
        run_train(cfg)
    steps = committed_steps(cfg.checkpoint.directory)
    assert steps, "preemption must commit a checkpoint even off-cadence"
    assert manifest_status(
        os.path.join(cfg.checkpoint.directory, str(steps[-1])))[0] == "ok"

    cfg2 = _tiny_cfg(tmp_path)
    cfg2.train.train_steps = steps[-1] + 5
    cfg2.resilience.deadline_secs = 0.0
    state, _ = run_train(cfg2)
    assert int(state.step) == steps[-1] + 5


def test_evaluator_skips_damaged_checkpoint(tmp_path):
    """A long-running polling evaluator must skip a checkpoint that gets
    damaged (or quarantined/reaped) between poll and restore, not die —
    that damage is exactly what the resilience layer exists to survive."""
    from distributed_resnet_tensorflow_tpu.evaluator import Evaluator
    cfg = _tiny_cfg(tmp_path)
    cfg.eval.eval_batch_count = 1
    tr = Trainer(cfg)
    tr.init_state()
    mngr = CheckpointManager(cfg.checkpoint.directory, async_save=False)
    state, _ = tr.train(learnable_synthetic_iterator(16, 8, 4), num_steps=2)
    mngr.save(2, state)
    mngr.close()
    faultinject.corrupt_checkpoint(cfg.checkpoint.directory, step=2,
                                   mode="flip")
    ev = Evaluator(cfg, data_iter=learnable_synthetic_iterator(16, 8, 4))
    out = ev.run(timeout_secs=0.0)  # must not raise
    assert out == {}            # nothing evaluable existed...
    assert ev.last_step == 2    # ...but the damaged step was consumed/skipped


def test_env_nan_injection_hook(monkeypatch):
    batches = [{"images": np.ones((2, 2), np.float32),
                "labels": np.zeros((2,), np.int32)} for _ in range(3)]
    monkeypatch.setenv(faultinject.NAN_ENV_VAR, "2")
    monkeypatch.setattr(faultinject, "_nan_armed", False)
    wrapped = faultinject.maybe_wrap_from_env(iter(batches))
    out = [next(wrapped) for _ in range(3)]
    assert np.isfinite(out[0]["images"]).all()
    assert np.isnan(out[1]["images"]).all()
    assert np.isfinite(out[2]["images"]).all()
    # second wrap in the same process stays clean (sentinel retry contract)
    wrapped2 = faultinject.maybe_wrap_from_env(iter(batches))
    assert all(np.isfinite(next(wrapped2)["images"]).all() for _ in range(3))


# ---------------------------------------------------------------------------
# kill-and-resume: SIGTERM a real main.py run mid-way (satellite)
# ---------------------------------------------------------------------------

@pytest.mark.heavy
def test_sigterm_kill_and_resume_exact_continuation(tmp_path):
    """SIGTERM a live trainer: it must exit with the resumable code (75)
    leaving a committed checkpoint at its stop step; the relaunch must reach
    the target with a contiguous, monotonic metrics stream — no duplicated
    or skipped steps across the preemption boundary."""
    from distributed_resnet_tensorflow_tpu.utils.virtual_devices import (
        virtual_cpu_env)

    ckpt_dir = os.path.join(str(tmp_path), "ckpt")
    args = [
        sys.executable, "-m", "distributed_resnet_tensorflow_tpu.main",
        "--preset", "smoke",
        "--set", "model.name=logistic",
        "--set", "model.input_size=192",
        "--set", "model.hidden_units=800",  # slow the step a little
        "--set", "model.num_classes=10",
        "--set", "data.image_size=8",
        "--set", "train.batch_size=8",
        "--set", "train.log_every_steps=1000",
        "--set", "train.summary_every_steps=1",  # JSONL row per step
        "--set", f"log_root={tmp_path}",
        "--set", "checkpoint.save_every_steps=100000",  # only preempt saves
        "--set", "checkpoint.save_every_secs=0",
    ]
    env = virtual_cpu_env(1)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    jsonl = os.path.join(str(tmp_path), "train", "metrics.jsonl")

    def metric_steps():
        # scalar rows only: typed {"event": ...} records (input_stages
        # telemetry) share the step key and would double-count steps
        try:
            with open(jsonl) as f:
                return [r["step"]
                        for r in (json.loads(l) for l in f if l.strip())
                        if "event" not in r]
        except FileNotFoundError:
            return []

    # run 1: unbounded-ish; SIGTERM once a few steps are on record
    p = subprocess.Popen(args + ["--set", "train.train_steps=1000000"],
                         env=env, cwd=repo,
                         stdout=subprocess.DEVNULL,
                         stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 300
        while time.time() < deadline:
            if len(metric_steps()) >= 3:
                break
            if p.poll() is not None:
                raise AssertionError("trainer exited before it was killed")
            time.sleep(0.1)
        else:
            raise AssertionError("no metrics appeared before the deadline")
        p.send_signal(signal.SIGTERM)
        rc = p.wait(timeout=120)
    finally:
        if p.poll() is None:
            p.kill()
    assert rc == RESUMABLE_EXIT_CODE, rc  # the launcher contract

    steps = committed_steps(ckpt_dir)
    assert steps, "graceful preemption must leave a committed checkpoint"
    preempt = steps[-1]
    rows_run1 = metric_steps()
    # the checkpoint is at the exact last finished (and logged) step, and
    # it passes verification — committed, not torn
    assert preempt == rows_run1[-1], (preempt, rows_run1[-6:])
    assert manifest_status(os.path.join(ckpt_dir, str(preempt)))[0] == "ok"

    # run 2: resume to a bounded target
    target = preempt + 15
    rc2 = subprocess.run(
        args + ["--set", f"train.train_steps={target}"], env=env, cwd=repo,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        timeout=600).returncode
    assert rc2 == 0
    all_rows = metric_steps()
    resumed = all_rows[len(rows_run1):]
    # exact continuation: preempt+1 ... target, nothing skipped or repeated
    assert resumed == list(range(preempt + 1, target + 1)), resumed[:5]
    # and the combined stream is strictly monotonic across the boundary
    assert all_rows == sorted(set(all_rows)), "metrics stream not monotonic"
