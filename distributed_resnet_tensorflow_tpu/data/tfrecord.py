"""TFRecord container + tf.train.Example wire-format codec — dependency-free.

The reference consumed ImageNet as 1024 train / 128 validation TFRecord shards
of tf.train.Example protos (reference resnet_imagenet_main.py:103-136). This
module re-implements just enough of both formats in pure numpy/python so the
framework needs neither TensorFlow nor protoc at runtime: a TFRecord
reader/writer (with masked CRC32C), and an Example parser/builder speaking the
protobuf wire format directly.

TFRecord framing (per record):
    uint64 length | uint32 masked_crc32c(length) | bytes data |
    uint32 masked_crc32c(data)

Example proto schema (subset the reference's record_parser touched,
reference resnet_imagenet_main.py:117-136):
    Example       { 1: Features }
    Features      { 1: repeated map entry { 1: key(str), 2: Feature } }
    Feature       { 1: BytesList, 2: FloatList, 3: Int64List }
    BytesList     { 1: repeated bytes }
    FloatList     { 1: repeated float (packed) }
    Int64List     { 1: repeated varint (packed or unpacked) }
"""
from __future__ import annotations

import logging
import os
import struct
import threading
from collections import deque
from typing import Dict, Iterator, List, Optional, Union

import numpy as np

log = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli) with the TFRecord masking, table-driven
# ---------------------------------------------------------------------------

_CRC_TABLE = None


def _crc_table():
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78
        table = np.zeros(256, np.uint32)
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if (c & 1) else (c >> 1)
            table[i] = c
        _CRC_TABLE = table
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    # table-driven; sequential by nature (python-speed — fine for fixtures
    # and spot checks; the C++ native loader owns the high-rate path)
    tbl = _crc_table()
    crc_val = 0xFFFFFFFF
    for b in data:
        crc_val = (crc_val >> 8) ^ int(tbl[(crc_val ^ b) & 0xFF])
    return crc_val ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    crc = crc32c(data)
    return ((((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF)


# ---------------------------------------------------------------------------
# TFRecord container
# ---------------------------------------------------------------------------

class CorruptRecordStats:
    """Thread-safe per-process tally of skipped corrupt/truncated records.

    Shared by every reader thread in the decode pipeline (like
    ``utils.metrics.input_stages``); ``train.hooks.CorruptRecordsHook``
    exports it to metrics.jsonl as ``{"event": "corrupt_record"}`` rows so
    bit rot on the dataset shards is visible in the run telemetry, not just
    buried in a worker log."""

    RECENT = 8

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.repeats = 0
        self._by_reason: Dict[str, int] = {}
        self._recent: deque = deque(maxlen=self.RECENT)
        self._sites: set = set()

    def record(self, path: str, reason: str,
               offset: Optional[int] = None) -> int:
        """Count one corruption; returns the per-process total of DISTINCT
        corrupt sites. ``offset`` (byte position of the record in ``path``)
        dedupes re-reads: the input pipeline re-opens every shard each
        epoch, and one unchanging bad record must cost the budget once, not
        once per pass — only NEW sites count toward ``max_corrupt``.
        ``offset=None`` always counts (no site identity available)."""
        with self._lock:
            if offset is not None:
                site = (path, offset)
                if site in self._sites:
                    self.repeats += 1
                    return self.count
                self._sites.add(site)
            self.count += 1
            self._by_reason[reason] = self._by_reason.get(reason, 0) + 1
            self._recent.append({"file": os.path.basename(path),
                                 "reason": reason})
            return self.count

    def reset(self) -> None:
        with self._lock:
            self.count = 0
            self.repeats = 0
            self._by_reason.clear()
            self._recent.clear()
            self._sites.clear()

    def snapshot(self) -> dict:
        with self._lock:
            return {"count": self.count, "repeats": self.repeats,
                    "by_reason": dict(self._by_reason),
                    "recent": list(self._recent)}


#: process-global tally — every read_tfrecords caller (decode feeder
#: threads, tools) reports here; hooks export it
corrupt_records = CorruptRecordStats()


def read_tfrecords(path: str, verify_crc: bool = False,
                   max_corrupt: int = 0,
                   stats: CorruptRecordStats = corrupt_records
                   ) -> Iterator[bytes]:
    """Yield raw record payloads from one TFRecord file.

    ``max_corrupt`` > 0 tolerates damage instead of dying on the first bad
    byte of a multi-day run: a record with a bad data CRC is skipped
    (framing is still trustworthy — the length parsed fine), while a bad
    LENGTH CRC or a truncated tail abandons the rest of the file (framing
    lost; resyncing a TFRecord stream is guesswork). Every skip is counted
    in ``stats`` (per-PROCESS total of DISTINCT (file, offset) sites —
    re-reading the same bad record on a later epoch logs but does not eat
    the budget) with a warning; when the total exceeds ``max_corrupt`` the
    reader raises — mass corruption is a storage incident, not noise to
    ride through. ``max_corrupt=0`` is the strict legacy behavior. Note
    CRC mismatches are only detectable with ``verify_crc=True``;
    truncation is always detected."""

    def corrupt(reason: str, offset: int) -> bool:
        """True = tolerate (skip/stop file), False = caller must raise."""
        if max_corrupt <= 0:
            return False
        total = stats.record(path, reason, offset=offset)
        log.warning("corrupt TFRecord tolerated (%d/%d this process): "
                    "%s@%d: %s", total, max_corrupt, path, offset, reason)
        if total > max_corrupt:
            raise IOError(
                f"{path}: {reason} — {total} corrupt records exceed "
                f"data.max_corrupt_records={max_corrupt}; the dataset "
                "looks damaged beyond bit rot")
        return True

    with open(path, "rb") as f:
        while True:
            rec_off = f.tell()
            header = f.read(12)
            if len(header) < 12:
                # a partial trailing header was silent EOF in the legacy
                # reader; strict mode (max_corrupt=0) must keep accepting
                # files it always accepted, tolerant mode counts the tear
                if header and max_corrupt > 0:
                    corrupt("truncated header", rec_off)
                return
            (length,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:12])
            if verify_crc and masked_crc32c(header[:8]) != len_crc:
                if not corrupt("corrupt length crc", rec_off):
                    raise IOError(f"{path}: corrupt length crc")
                return  # framing untrustworthy: abandon the file
            data = f.read(length)
            tail = f.read(4)
            if len(data) < length or len(tail) < 4:
                if not corrupt("truncated record", rec_off):
                    raise IOError(f"{path}: truncated record")
                return
            (data_crc,) = struct.unpack("<I", tail)
            if verify_crc and masked_crc32c(data) != data_crc:
                if not corrupt("corrupt data crc", rec_off):
                    raise IOError(f"{path}: corrupt data crc")
                continue  # framing intact: skip just this record
            yield data


def write_tfrecords(path: str, records: List[bytes]) -> None:
    """Write records with proper masked CRCs (test fixture + export path)."""
    with open(path, "wb") as f:
        for rec in records:
            header = struct.pack("<Q", len(rec))
            f.write(header)
            f.write(struct.pack("<I", masked_crc32c(header)))
            f.write(rec)
            f.write(struct.pack("<I", masked_crc32c(rec)))


# ---------------------------------------------------------------------------
# protobuf wire helpers
# ---------------------------------------------------------------------------

def _read_varint(buf: bytes, pos: int):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _write_varint(value: int) -> bytes:
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _iter_fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a message buffer."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:       # varint
            val, pos = _read_varint(buf, pos)
        elif wire == 1:     # 64-bit
            val = buf[pos:pos + 8]; pos += 8
        elif wire == 2:     # length-delimited
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]; pos += ln
        elif wire == 5:     # 32-bit
            val = buf[pos:pos + 4]; pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


FeatureValue = Union[List[bytes], List[float], List[int]]


def parse_example(buf: bytes) -> Dict[str, FeatureValue]:
    """Parse a serialized tf.train.Example into {key: list-of-values}."""
    features: Dict[str, FeatureValue] = {}
    for field, wire, val in _iter_fields(buf):
        if field != 1 or wire != 2:     # Example.features
            continue
        for f2, w2, entry in _iter_fields(val):
            if f2 != 1 or w2 != 2:      # Features.feature map entry
                continue
            key = None
            feature = None
            for f3, w3, v3 in _iter_fields(entry):
                if f3 == 1:
                    key = v3.decode("utf-8")
                elif f3 == 2:
                    feature = v3
            if key is None or feature is None:
                continue
            features[key] = _parse_feature(feature)
    return features


def _parse_feature(buf: bytes) -> FeatureValue:
    for field, wire, val in _iter_fields(buf):
        if field == 1:      # BytesList
            return [v for f, w, v in _iter_fields(val) if f == 1]
        if field == 2:      # FloatList (packed or not)
            floats: List[float] = []
            for f, w, v in _iter_fields(val):
                if f != 1:
                    continue
                if w == 2:  # packed
                    floats.extend(np.frombuffer(v, "<f4").tolist())
                else:       # single 32-bit
                    floats.append(struct.unpack("<f", v)[0])
            return floats
        if field == 3:      # Int64List (packed or not)
            ints: List[int] = []
            for f, w, v in _iter_fields(val):
                if f != 1:
                    continue
                if w == 2:  # packed varints
                    pos = 0
                    while pos < len(v):
                        x, pos = _read_varint(v, pos)
                        ints.append(x)
                else:
                    ints.append(v)
            return ints
    return []


# ---------------------------------------------------------------------------
# Example builder (tests + dataset preparation tooling)
# ---------------------------------------------------------------------------

def _ld(field: int, payload: bytes) -> bytes:
    return _write_varint((field << 3) | 2) + _write_varint(len(payload)) + payload


def build_example(features: Dict[str, FeatureValue]) -> bytes:
    """Serialize {key: values} to a tf.train.Example. Value kind inferred:
    bytes→BytesList, float→FloatList, int→Int64List."""
    entries = b""
    for key, values in features.items():
        if not isinstance(values, (list, tuple)):
            values = [values]
        if values and isinstance(values[0], (bytes, bytearray, str)):
            items = b"".join(
                _ld(1, v.encode() if isinstance(v, str) else bytes(v))
                for v in values)
            feature = _ld(1, items)
        elif values and isinstance(values[0], float):
            packed = np.asarray(values, "<f4").tobytes()
            feature = _ld(2, _ld(1, packed))
        else:
            packed = b"".join(_write_varint(int(v)) for v in values)
            feature = _ld(3, _ld(1, packed))
        entries += _ld(1, _ld(1, key.encode()) + _ld(2, feature))
    return _ld(1, entries)
