"""Round-5 classic-ViT MFU — can the transformer family hit the >=0.5 bar?

docs/perf_vit_r5.md measured the long-context preset (4096 tokens, dim 512)
at <=0.37 MFU bound and attributed the plateau to the dim-512 op mix, with
the width lever (dim 1024) reaching <=0.43. The open question it left: does
a CLASSIC short-sequence ViT — 224² at patch 16 → 196 tokens, where
attention is a rounding error and the step is almost entirely dense
(B·T, D)×(D, 4D) matmuls — fill the MXU the way the WRN-28-10 width lever
did for convs (0.63, docs/perf_cifar_r5.md)?

Dense attention only: every FLOP is visible to XLA's cost analysis, so
these MFU numbers are fully counted (no Pallas custom-call bound games).

Grid: ViT-B/16-shaped (dim 768, depth 12, heads 12) and ViT-L/16-shaped
(dim 1024, depth 24, heads 16), batch 32/64/128, remat off (196 tokens
needs no activation rematerialization).

Writes docs/perf_vit_classic_r5.json.
"""
from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

OUT = os.path.join(REPO, "docs", "perf_vit_classic_r5.json")


def measure(dim: int, depth: int, heads: int, bs: int, k: int = 8,
            loops: int = 5):
    """One grid point through bench._mfu_row — the shared single-chip MFU
    harness (host-pull fence, best-of-reps, XLA-counted FLOPs), so timing
    and accounting fixes land once (same reuse as tools/profile_norm_r5)."""
    import bench
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    cfg = get_preset("vit_large_224")
    cfg.model.vit_dim = dim
    cfg.model.vit_depth = depth
    cfg.model.vit_heads = heads
    row = bench._mfu_row(cfg, bs, 224, 1000, k, loops, host_fence=True)
    row.update(dim=dim, depth=depth, heads=heads,
               tokens_per_image=(224 // 16) ** 2,
               counted_step_flops=row.pop("step_flops"))
    return row


def main():
    out = {"device": jax.devices()[0].device_kind,
           "workload": "classic ViT 224^2 / patch 16 = 196 tokens, dense "
                       "attention (all FLOPs XLA-counted), bf16, no remat"}
    rows = []
    for dim, depth, heads, label in ((768, 12, 12, "ViT-B/16"),
                                     (1024, 24, 16, "ViT-L/16")):
        for bs in (32, 64, 128):
            try:
                r = measure(dim, depth, heads, bs)
                r["shape"] = label
            except Exception as e:
                r = {"shape": label, "dim": dim, "batch_size": bs,
                     "error": f"{type(e).__name__}: {e}"[:200]}
            print(json.dumps(r), flush=True)
            rows.append(r)
    out["rows"] = rows
    with open(OUT, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", OUT)


if __name__ == "__main__":
    main()


