"""Data echoing + decoded-sample cache + fused on-device imagenet
augmentation (round 9: data/echo.py, ops/augment.imagenet_train_augment,
the CoalescedStager's fused unpack, data.echo_transfer reuse, and the
decode-pool auto-scaling resolution)."""
import collections

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.data.echo import echoing_iterator
from distributed_resnet_tensorflow_tpu.utils.metrics import EchoStats


def _src(n_batches=4, b=8, s=4, seed=0):
    rng = np.random.RandomState(seed)
    for i in range(n_batches):
        yield {"images": rng.randint(0, 256, (b, s, s, 3)).astype(np.uint8),
               "labels": np.arange(i * b, (i + 1) * b, dtype=np.int32)}


def test_echo_passthrough_at_factor_one():
    src = _src()
    assert echoing_iterator(src, 1) is src


def test_echo_determinism_same_seed_same_order():
    a = list(echoing_iterator(_src(), 3, cache_mb=64, seed=5,
                              stats=EchoStats()))
    b = list(echoing_iterator(_src(), 3, cache_mb=64, seed=5,
                              stats=EchoStats()))
    c = list(echoing_iterator(_src(), 3, cache_mb=64, seed=6,
                              stats=EchoStats()))
    assert len(a) == len(b) == 12
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x["labels"], y["labels"])
        np.testing.assert_array_equal(x["images"], y["images"])
    assert any(not np.array_equal(x["labels"], y["labels"])
               for x, y in zip(a, c))


def test_echo_epoch_accounting_no_starvation():
    """Finite stream, echo_factor=e, adequate cache: every sample is
    served EXACTLY e times — echoing must not starve (or over-serve) any
    sample, or epoch statistics silently skew."""
    st = EchoStats()
    out = list(echoing_iterator(_src(4, b=8), 2, cache_mb=64, seed=1,
                                stats=st))
    assert len(out) == 8  # 4 batches × e=2
    counts = collections.Counter(
        np.concatenate([b["labels"] for b in out]).tolist())
    assert len(counts) == 32
    assert set(counts.values()) == {2}
    snap = st.snapshot()
    assert snap["decoded"] == 32
    assert snap["emitted"] == 64
    assert snap["hits"] == 32          # every second serving is a hit
    assert snap["hit_rate"] == 0.5
    assert snap["evictions"] == 0


def test_echo_batches_are_reshuffled_not_replayed():
    out = list(echoing_iterator(_src(4, b=8), 2, cache_mb=64, seed=2,
                                stats=EchoStats()))
    # some emitted batch must differ in composition from every source batch
    src_sets = [set(range(i * 8, (i + 1) * 8)) for i in range(4)]
    assert any(set(b["labels"].tolist()) not in src_sets for b in out)


def test_echo_cache_bound_respected_under_eviction():
    """A cache too small for the stream: evictions happen (counted, with
    lost uses) and the byte high-water mark stays within one sample of
    the configured bound."""
    st = EchoStats()
    sample = 4 * 4 * 3 + 8  # image + label bytes per entry (approx)
    cap_mb = (5 * sample) / 1e6
    out = list(echoing_iterator(_src(6, b=8), 3, cache_mb=cap_mb, seed=1,
                                stats=st))
    snap = st.snapshot()
    assert snap["evictions"] > 0
    assert snap["lost_uses"] >= snap["evictions"]
    assert snap["peak_cache_bytes"] <= snap["cache_cap_bytes"] + 2 * sample
    assert out and snap["emitted"] > 0
    # decoded samples all entered the cache even though some were evicted
    assert snap["decoded"] == 48


def test_echo_cache_too_small_raises_loudly():
    """A cache that can never accumulate one batch of servings must be a
    loud ValueError, not a train loop silently blocked in next()."""
    sample = 4 * 4 * 3 + 8
    it = echoing_iterator(_src(3, b=8), 2, cache_mb=(2 * sample) / 1e6,
                          seed=0, stats=EchoStats())
    with pytest.raises(ValueError, match="echo_cache_mb"):
        next(it)


def test_echo_stats_event_row(tmp_path):
    from distributed_resnet_tensorflow_tpu.train.hooks import InputEchoHook
    from distributed_resnet_tensorflow_tpu.utils.metrics import (
        MetricsWriter, echo_stats, read_metrics)

    echo_stats.reset()
    echo_stats.configure(2, 10 ** 6)
    w = MetricsWriter(str(tmp_path), enable_tensorboard=False)
    hook = InputEchoHook(w, every_steps=10)
    hook(10, None, {})  # nothing emitted yet: no row
    echo_stats.add(decoded=8, emitted=16, hits=8, cache_bytes=1000)
    hook(20, None, {})
    w.close()
    rows = [r for r in read_metrics(str(tmp_path))
            if r.get("event") == "input_echo"]
    assert len(rows) == 1
    assert rows[0]["step"] == 20
    assert rows[0]["hits"] == 8 and rows[0]["hit_rate"] == 0.5
    assert rows[0]["echo_factor"] == 2
    echo_stats.reset()


def test_resolve_decode_workers_auto_and_explicit(monkeypatch):
    import distributed_resnet_tensorflow_tpu.data as data_mod
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    cfg = get_preset("imagenet_resnet50")
    import os
    for cores, want_procs, want_threads in ((1, 0, 4), (2, 0, 4),
                                            (4, 4, 4), (16, 8, 8)):
        monkeypatch.setattr(os, "cpu_count", lambda c=cores: c)
        procs, threads = data_mod.resolve_decode_workers(cfg)
        assert (procs, threads) == (want_procs, want_threads), cores
    # explicit settings win over auto
    cfg.data.decode_processes = 2
    cfg.data.num_parallel_calls = 3
    monkeypatch.setattr(os, "cpu_count", lambda: 16)
    assert data_mod.resolve_decode_workers(cfg) == (2, 3)


# ---------------------------------------------------------------------------
# fused on-device imagenet augmentation
# ---------------------------------------------------------------------------

def test_imagenet_eval_standardize_exact_vs_host():
    """Eval-mode device prep is EXACTLY the host float path."""
    from distributed_resnet_tensorflow_tpu.data.preprocessing import RGB_MEANS
    from distributed_resnet_tensorflow_tpu.ops.augment import vgg_standardize

    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (4, 8, 8, 3)).astype(np.uint8)
    host = imgs.astype(np.float32) / 255.0 - RGB_MEANS
    dev = np.asarray(vgg_standardize(jnp.asarray(imgs)))
    np.testing.assert_allclose(dev, host, atol=1e-6)


def test_imagenet_train_augment_parity_modulo_rng():
    """Train-mode device augmentation == the same ops on the host, given
    the device's own flip draws (parity modulo RNG: same operations,
    the random draws extracted from the identical key)."""
    from distributed_resnet_tensorflow_tpu.data.preprocessing import RGB_MEANS
    from distributed_resnet_tensorflow_tpu.ops.augment import (
        imagenet_train_augment)

    rng = np.random.RandomState(1)
    imgs = rng.randint(0, 256, (8, 8, 8, 3)).astype(np.uint8)
    key = jax.random.PRNGKey(9)
    dev = np.asarray(imagenet_train_augment(jnp.asarray(imgs), key, pad=0))
    flips = np.asarray(jax.random.bernoulli(key, 0.5, (8,)))
    host = np.where(flips[:, None, None, None],
                    imgs[:, :, ::-1, :], imgs).astype(np.float32)
    host = host / 255.0 - RGB_MEANS
    np.testing.assert_allclose(dev, host, atol=1e-6)
    assert flips.any() and not flips.all()  # both branches exercised


def test_imagenet_train_augment_pad_jitter_windows():
    """augment_pad > 0: every output is a valid window of the padded
    original (possibly flipped), standardized — the crop machinery is the
    proven cifar one-hot-matmul path."""
    from distributed_resnet_tensorflow_tpu.data.preprocessing import RGB_MEANS
    from distributed_resnet_tensorflow_tpu.ops.augment import (
        imagenet_train_augment)

    s, pad = 8, 2
    base = (np.arange(s * s * 3) % 251).reshape(s, s, 3).astype(np.uint8)
    imgs = np.stack([base] * 4)
    out = np.asarray(imagenet_train_augment(
        jnp.asarray(imgs), jax.random.PRNGKey(3), pad=pad))
    padded = np.pad(base, ((pad, pad), (pad, pad), (0, 0))).astype(np.float32)
    windows = set()
    for y in range(2 * pad + 1):
        for x in range(2 * pad + 1):
            win = padded[y:y + s, x:x + s] / 255.0 - RGB_MEANS
            windows.add(np.round(win, 5).tobytes())
            windows.add(np.round(win[:, ::-1], 5).tobytes())
    for i in range(4):
        assert np.round(out[i], 5).tobytes() in windows, i


def test_fused_unpack_augment_fresh_per_put_and_deterministic():
    """The stager's fused unpack draws a fresh augmentation per put
    (counter embedded in the staged bytes) and is deterministic in
    (seed, counter) — two stagers replay identically."""
    from distributed_resnet_tensorflow_tpu.ops.augment import (
        device_augment_fn)
    from distributed_resnet_tensorflow_tpu.parallel.mesh import create_mesh
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        CoalescedStager)
    from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig

    mesh = create_mesh(MeshConfig())  # data=-1: all (virtual) devices
    rng = np.random.RandomState(0)
    batch = {"images": rng.randint(0, 256, (8, 8, 8, 3)).astype(np.uint8),
             "labels": rng.randint(0, 10, (8,)).astype(np.int32)}
    aug = ("images", "imagenet_train", 0)
    st = CoalescedStager(mesh, ring=3, augment=aug, augment_seed=7)
    out0 = np.asarray(st.put_now(dict(batch))["images"])
    out1 = np.asarray(st.put_now(dict(batch))["images"])
    assert out0.dtype == np.float32
    assert not np.allclose(out0, out1)  # fresh draws per put
    # exact expected value: fn(images, fold_in(PRNGKey(seed), counter))
    fn = device_augment_fn("imagenet_train", 0)
    for ctr, got in ((0, out0), (1, out1)):
        exp = np.asarray(fn(jnp.asarray(batch["images"]),
                            jax.random.fold_in(jax.random.PRNGKey(7),
                                               np.uint32(ctr))))
        np.testing.assert_allclose(got, exp, atol=1e-6)
    st2 = CoalescedStager(mesh, ring=3, augment=aug, augment_seed=7)
    np.testing.assert_allclose(
        np.asarray(st2.put_now(dict(batch))["images"]), out0, atol=1e-6)
    # labels ride through untouched
    np.testing.assert_array_equal(
        np.asarray(st2.put_now(dict(batch))["labels"]), batch["labels"])


def test_fused_unpack_augment_stacked_per_step_keys():
    """Stacked (K, B) groups: each scan step's microbatch augments under
    its own split key — parity with applying the resolved fn per k."""
    from distributed_resnet_tensorflow_tpu.ops.augment import (
        device_augment_fn)
    from distributed_resnet_tensorflow_tpu.parallel.mesh import create_mesh
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        CoalescedStager)
    from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig

    mesh = create_mesh(MeshConfig())  # data=-1: all (virtual) devices
    rng = np.random.RandomState(2)
    sb = {"images": rng.randint(0, 256, (3, 8, 8, 8, 3)).astype(np.uint8),
          "labels": rng.randint(0, 10, (3, 8)).astype(np.int32)}
    st = CoalescedStager(mesh, stacked=True, ring=3,
                         augment=("images", "imagenet_train", 2),
                         augment_seed=11)
    out = np.asarray(st.put_now(dict(sb))["images"])
    fn = device_augment_fn("imagenet_train", 2)
    keys = jax.random.split(
        jax.random.fold_in(jax.random.PRNGKey(11), np.uint32(0)), 3)
    exp = np.stack([np.asarray(fn(jnp.asarray(sb["images"][k]), keys[k]))
                    for k in range(3)])
    np.testing.assert_allclose(out, exp, atol=1e-5)


def test_abstract_staged_unpack_traces_augment():
    """The allocation-free gate entry (analysis/elaborate.py uses it per
    preset): output shapes/dtypes of the fused unpack+augment program."""
    from distributed_resnet_tensorflow_tpu.parallel.mesh import create_mesh
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        abstract_staged_unpack)
    from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig

    mesh = create_mesh(MeshConfig())  # data=-1: all (virtual) devices
    shapes = {"images": jax.ShapeDtypeStruct((8, 8, 8, 3), np.uint8),
              "labels": jax.ShapeDtypeStruct((8,), np.int32)}
    out = abstract_staged_unpack(mesh, shapes,
                                 augment=("images", "imagenet_train", 2))
    assert out["images"].shape == (8, 8, 8, 3)
    assert out["images"].dtype == np.float32  # augmented
    assert out["labels"].dtype == np.int32
    # neutral trace keeps uint8
    out2 = abstract_staged_unpack(mesh, shapes)
    assert out2["images"].dtype == np.uint8


def test_host_flip_skipped_when_device_flips():
    """device_flip contract: the flip is still DRAWN (RNG stream order
    preserved) but not applied — same crop geometry, unflipped pixels."""
    from distributed_resnet_tensorflow_tpu.data.preprocessing import (
        encode_jpeg, train_crop_from_bytes)

    rng = np.random.RandomState(0)
    img = rng.randint(0, 256, (64, 80, 3)).astype(np.uint8)
    data = encode_jpeg(img)
    a = train_crop_from_bytes(data, np.random.RandomState(5), 16,
                              resize_side_min=32, resize_side_max=48)
    b = train_crop_from_bytes(data, np.random.RandomState(5), 16,
                              resize_side_min=32, resize_side_max=48,
                              apply_flip=False)
    # identical crop geometry; the ONLY permitted difference is the flip
    assert np.array_equal(a, b) or np.array_equal(a, b[:, ::-1])
    assert a.shape == b.shape == (16, 16, 3)


# ---------------------------------------------------------------------------
# trainer integration: fused augment + transfer echo
# ---------------------------------------------------------------------------

def _imagenet_cfg(k=1, echo_transfer=1):
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset
    cfg = get_preset("imagenet_resnet50")
    cfg.model.resnet_size = 18
    cfg.model.num_classes = 8
    cfg.model.compute_dtype = "float32"
    cfg.data.image_size = 16
    cfg.train.batch_size = 8
    cfg.train.steps_per_loop = k
    cfg.data.device_augment = "on"
    cfg.data.coalesced_transfer = "on"
    cfg.data.echo_transfer = echo_transfer
    cfg.mesh.data = -1  # all virtual devices (conftest's 8-way CPU mesh)
    cfg.checkpoint.save_every_secs = 0.0
    return cfg


def _uint8_batches(n, b=8, s=16):
    rng = np.random.RandomState(0)
    for _ in range(n):
        yield {"images": rng.randint(0, 256, (b, s, s, 3)).astype(np.uint8),
               "labels": rng.randint(0, 8, (b,)).astype(np.int32)}


@pytest.mark.slow  # re-tiered out of the 870s tier-1 (ISSUE 13); input_smoke.sh covers the live fused-augment+sanitizer path
@pytest.mark.heavy
def test_fused_augment_train_step_sanitizer_green():
    """Fused unpack+augment end-to-end under the cross-thread dispatch
    sanitizer: the augmented unpack is a multi-device program and must
    keep being dispatched ONLY from the consumer thread."""
    from distributed_resnet_tensorflow_tpu.analysis import (
        dispatch_sanitizer as ds)
    from distributed_resnet_tensorflow_tpu.train import Trainer

    cfg = _imagenet_cfg(k=1)
    tr = Trainer(cfg)
    assert tr.train_put_augments  # imagenet + device_augment + stager
    tr.init_state()
    with ds.enabled():
        state, m = tr.train(_uint8_batches(5), num_steps=3)
    assert int(state.step) == 3
    assert np.isfinite(float(m["loss"]))


def test_attach_device_dataset_keeps_imagenet_augment():
    """attach_device_dataset on a fused-augment imagenet Trainer must move
    the IMAGENET augmentation back into the step (the idx path bypasses
    the stager) — not install the cifar default."""
    from distributed_resnet_tensorflow_tpu.ops import augment
    from distributed_resnet_tensorflow_tpu.train import Trainer

    cfg = _imagenet_cfg(k=1)
    tr = Trainer(cfg)
    assert tr.train_put_augments and tr._aug_fn is None
    imgs = np.zeros((16, 16, 16, 3), np.uint8)
    tr.attach_device_dataset(imgs, np.zeros((16,), np.int32))
    key = jax.random.PRNGKey(0)
    out = np.asarray(tr._aug_fn(jnp.asarray(imgs[:2]), key))
    exp = np.asarray(augment.imagenet_train_augment(
        jnp.asarray(imgs[:2]), key, pad=cfg.data.augment_pad))
    np.testing.assert_allclose(out, exp, atol=1e-6)
    tr.detach_device_dataset()
    assert tr._aug_fn is None  # config-resolved fused choice restored


@pytest.mark.slow  # re-tiered out of the 870s tier-1 (ISSUE 13); input_smoke.sh covers live echoing end-to-end
@pytest.mark.heavy
def test_echo_transfer_amortizes_transfers():
    """data.echo_transfer=2: a finite source of exactly 2 stacked groups
    sustains 8 optimizer steps (one H2D transfer feeds
    echo_transfer × steps_per_loop steps). Without reuse the same source
    could feed only 4."""
    from distributed_resnet_tensorflow_tpu.train import Trainer

    cfg = _imagenet_cfg(k=2, echo_transfer=2)
    tr = Trainer(cfg)
    assert not tr.train_put_augments  # reuse forces step-side augment
    tr.init_state()
    state, m = tr.train(_uint8_batches(4), num_steps=8)
    assert int(state.step) == 8
    assert np.isfinite(float(m["loss"]))
