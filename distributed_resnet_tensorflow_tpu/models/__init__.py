from .resnet import (  # noqa: F401
    CifarResNetV2,
    ImageNetResNetV2,
    IMAGENET_MODEL_PARAMS,
    count_params,
    create_model,
)
from .logistic import LogisticNet  # noqa: F401
from .transformer import VisionTransformer  # noqa: F401
