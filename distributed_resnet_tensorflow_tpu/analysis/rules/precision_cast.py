"""unpolicied-matmul: models/ops matmuls must go through the policy cast.

The mixed-precision policy (parallel/precision.py; docs/precision.md)
only delivers its MFU win if every matmul/conv on the hot path actually
computes in the policy dtype. The cast is threaded ONE way: flax modules
take an explicit ``dtype=`` (the policy dtype, or a deliberate
``jnp.float32`` for f32 islands like logit heads and BN affine math);
raw contractions show their dtype choice at the call site (an
``.astype(...)`` on an operand, or ``preferred_element_type=`` pinning
the accumulator). A call site with NEITHER silently computes in flax's
promoted default — f32 — and the policy quietly loses that op: the MFU
gap this rule exists to catch never shows up as an error, only as a
step-time plateau someone has to re-profile months later.

The rule flags, in ``models/`` and ``ops/`` package code:

  * ``nn.Dense`` / ``nn.DenseGeneral`` / ``nn.Conv`` / ``nn.ConvLocal``
    / ``nn.ConvTranspose`` / ``nn.Einsum`` calls without a ``dtype=``
    keyword (flax's ``dtype=None`` promotes to the f32 param dtype —
    bypassing the policy);
  * ``jnp.dot`` / ``jnp.matmul`` / ``jnp.einsum`` /
    ``lax.dot_general`` / ``lax.conv_general_dilated`` calls whose
    source (call segment or its first line) shows neither an
    ``.astype(`` cast nor a ``preferred_element_type=`` argument.

Deliberate f32 call sites stay deliberate: pass ``dtype=jnp.float32``
(preferred — the dtype IS the documentation) or suppress with
``# shardcheck: ok(unpolicied-matmul)``.
"""
from __future__ import annotations

import ast
import os
from typing import Iterable

from ..report import Finding

RULE_NAME = "unpolicied-matmul"
DOC = __doc__

#: flax module constructors whose ``dtype=`` kwarg IS the policy cast
_FLAX_CTORS = {"Dense", "DenseGeneral", "Conv", "ConvLocal",
               "ConvTranspose", "Einsum"}

#: raw contraction entry points that must show their dtype choice
_RAW_CONTRACTIONS = {"dot", "matmul", "einsum", "dot_general",
                     "conv_general_dilated"}

#: package subtrees on the model/op hot path (serve/train loops reuse
#: these — a stray f32 matmul anywhere else is not a *model* FLOP)
_SCOPES = ("models", "ops")


def _in_scope(rel: str) -> bool:
    parts = rel.replace(os.sep, "/").split("/")
    return any(scope in parts[:-1] for scope in _SCOPES)


def _attr_chain(node: ast.AST):
    """Dotted name of a call target: Attribute chains flattened
    ("jax.lax.dot_general" → ["jax", "lax", "dot_general"])."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return list(reversed(parts))


def _has_kwarg(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)


def _call_text(sf, call: ast.Call) -> str:
    """The call's source segment (multi-line args included), falling back
    to its first physical line."""
    seg = ast.get_source_segment(sf.text, call)
    if seg:
        return seg
    lines = sf.lines
    return lines[call.lineno - 1] if 0 < call.lineno <= len(lines) else ""


def check(ctx) -> Iterable[Finding]:
    for sf in ctx.package_py:
        if not _in_scope(sf.rel) or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if not chain:
                continue
            leaf = chain[-1]
            # flax module ctor: nn.Dense(...) / linen.Conv(...)
            if leaf in _FLAX_CTORS and len(chain) >= 2:
                if not _has_kwarg(node, "dtype"):
                    yield Finding(
                        RULE_NAME, sf.rel, node.lineno,
                        f"{'.'.join(chain)}(...) without an explicit "
                        "dtype= computes in flax's promoted f32 default, "
                        "bypassing the precision policy "
                        "(parallel/precision.py) — pass the policy dtype "
                        "(or a deliberate jnp.float32)")
                continue
            if leaf in _RAW_CONTRACTIONS and len(chain) >= 2 and \
                    chain[0] in ("jnp", "jax", "lax", "np"):
                if chain[0] == "np":
                    continue  # host-side numpy math is not a device matmul
                text = _call_text(sf, node)
                # the surrounding line too: `einsum(...).astype(f32)`
                # casts the RESULT — still a visible dtype decision
                line = sf.lines[node.lineno - 1] \
                    if 0 < node.lineno <= len(sf.lines) else ""
                if ".astype(" in text or ".astype(" in line or \
                        "preferred_element_type" in text:
                    continue
                yield Finding(
                    RULE_NAME, sf.rel, node.lineno,
                    f"{'.'.join(chain)}(...) shows no dtype decision "
                    "(no operand .astype(...), no "
                    "preferred_element_type=) — the contraction silently "
                    "computes in the promoted input dtype, bypassing the "
                    "precision policy (parallel/precision.py)")
