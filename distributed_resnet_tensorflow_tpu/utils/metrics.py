"""Metrics / observability.

The reference's three channels (SURVEY.md §2.15, §5):
  1. stdout logging every N steps (``LoggingTensorHook``,
     reference resnet_cifar_main.py:280-285),
  2. TensorBoard scalar summaries every 100 steps (``SummarySaverHook``,
     reference resnet_cifar_main.py:274-278; scalars cross_entropy/cost/lr,
     reference resnet_model.py:82-93),
  3. per-process log files (reference run_dist_train_eval_daint.sh:161,188).

Here: one ``MetricsWriter`` that fans out to a machine-readable JSONL event
stream and (when available) TensorBoard via tensorboardX, plus a
``Throughput`` meter giving steps/sec and images/sec — the number the
reference only derived offline from log timestamps (SURVEY.md §6).
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, Optional

log = logging.getLogger(__name__)


class StageStats:
    """Thread-safe busy-time/item counters for the input-pipeline stages
    (decode / stack / stage / transfer / dispatch_wait).

    Every stage worker calls ``add(stage, seconds, items=...)`` around its
    unit of work; totals are kept PER THREAD so ``rates()`` can estimate a
    stage's throughput as items / busiest-thread-seconds — the number that
    stays honest for multi-worker stages (a 4-thread decode pool that spent
    40 thread-seconds decoding 1000 images over a 10 s wall ran at ~100
    img/s, not 25). ``bench.py`` attributes the end-to-end input rate from
    these counters instead of re-measuring each component in isolation, so
    the attribution reflects the overlapped pipeline as it actually ran.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # (stage, worker_key) -> [count, items, seconds, bytes]
        self._cells: Dict[tuple, list] = {}

    def add(self, stage: str, seconds: float, items: int = 0,
            nbytes: int = 0, worker=None) -> None:
        """``worker`` overrides the default thread-identity cell key — the
        merge path for counters that were accumulated in ANOTHER process
        (imagenet decode worker processes ship snapshots back over their
        result queue; the parent merges them here under a per-worker key so
        ``max_thread_seconds`` still reflects the busiest worker, not the
        merging thread)."""
        key = (stage, threading.get_ident() if worker is None else worker)
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                cell = self._cells[key] = [0, 0, 0.0, 0]
            cell[0] += 1
            cell[1] += items
            cell[2] += seconds
            cell[3] += nbytes

    def reset(self) -> None:
        with self._lock:
            self._cells.clear()

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Per-stage aggregate: count, items, seconds (summed over threads),
        max_thread_seconds (the busiest worker), workers, bytes."""
        with self._lock:
            cells = {k: list(v) for k, v in self._cells.items()}
        out: Dict[str, Dict[str, float]] = {}
        for (stage, _tid), (count, items, secs, nbytes) in cells.items():
            agg = out.setdefault(stage, {
                "count": 0, "items": 0, "seconds": 0.0,
                "max_thread_seconds": 0.0, "workers": 0, "bytes": 0})
            agg["count"] += count
            agg["items"] += items
            agg["seconds"] += secs
            agg["max_thread_seconds"] = max(agg["max_thread_seconds"], secs)
            agg["workers"] += 1
            agg["bytes"] += nbytes
        return out

    def rates(self) -> Dict[str, float]:
        """stage -> items/sec estimate (items / busiest-thread busy time)."""
        out = {}
        for stage, agg in self.snapshot().items():
            if agg["items"] > 0 and agg["max_thread_seconds"] > 0:
                out[stage] = agg["items"] / agg["max_thread_seconds"]
        return out


# process-global input-pipeline telemetry: decode workers, the batch
# stacker, the echo cache, the staging/transfer thread and the dispatch
# loop all feed this one registry; InputStagesHook exports it to
# metrics.jsonl and bench.py reads it for end-to-end attribution. Decode
# worker PROCESSES (data.decode_processes > 0) accumulate in their own
# process and ship counter snapshots back over the result queue; the
# parent merges them here under per-worker keys (data/imagenet.py,
# docs/input_pipeline.md).
input_stages = StageStats()


class EchoStats:
    """Thread-safe counters for the data-echoing decoded-sample cache
    (data/echo.py): decoded (fresh samples inserted = cache misses),
    emitted (samples served into batches), hits (servings of a sample
    past its first — the decodes echoing saved), evictions (samples
    dropped by the byte bound with echo uses still pending) and the lost
    uses those evictions cost. ``InputEchoHook`` exports snapshots to
    metrics.jsonl as ``{"event": "input_echo"}`` rows and bench.py's
    imagenet_input row reads the same registry."""

    def __init__(self):
        self._lock = threading.Lock()
        self._c = dict(decoded=0, emitted=0, hits=0, evictions=0,
                       lost_uses=0)
        self.echo_factor = 1
        self.cache_cap_bytes = 0
        self.cache_bytes = 0
        self.peak_cache_bytes = 0

    def configure(self, echo_factor: int, cache_cap_bytes: int) -> None:
        with self._lock:
            self.echo_factor = int(echo_factor)
            self.cache_cap_bytes = int(cache_cap_bytes)

    def add(self, decoded: int = 0, emitted: int = 0, hits: int = 0,
            evictions: int = 0, lost_uses: int = 0,
            cache_bytes: Optional[int] = None) -> None:
        with self._lock:
            self._c["decoded"] += decoded
            self._c["emitted"] += emitted
            self._c["hits"] += hits
            self._c["evictions"] += evictions
            self._c["lost_uses"] += lost_uses
            if cache_bytes is not None:
                self.cache_bytes = int(cache_bytes)
                self.peak_cache_bytes = max(self.peak_cache_bytes,
                                            self.cache_bytes)

    def reset(self) -> None:
        with self._lock:
            for k in self._c:
                self._c[k] = 0
            self.cache_bytes = 0
            self.peak_cache_bytes = 0

    def snapshot(self) -> Dict[str, Any]:
        """Counters + hit_rate (hits / emitted: the fraction of served
        samples that did NOT cost a fresh decode)."""
        with self._lock:
            out = dict(self._c)
            out["echo_factor"] = self.echo_factor
            out["cache_cap_bytes"] = self.cache_cap_bytes
            out["cache_bytes"] = self.cache_bytes
            out["peak_cache_bytes"] = self.peak_cache_bytes
        out["hit_rate"] = round(out["hits"] / out["emitted"], 4) \
            if out["emitted"] else 0.0
        return out


# process-global echo-cache telemetry (one echoing stream per train run)
echo_stats = EchoStats()


class CkptAsyncStats:
    """Thread-safe counters splitting checkpoint cost by WHO paid it
    (checkpoint/manager.py): the step-loop thread's share (device→host
    snapshot + backpressure waiting on an in-flight save) versus the
    writer thread's share (stage → fsync → manifest → commit) — the
    charge-split behind the goodput contract that only loop-blocking time
    lands in the ``checkpoint`` bucket while writer seconds ride the
    ``{"event": "ckpt_async"}`` row (train/hooks.CkptAsyncHook)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._c = dict(saves=0, committed=0, sync_saves=0, overtakes=0,
                       snapshot_seconds=0.0, backpressure_seconds=0.0,
                       writer_seconds=0.0, shard_bytes=0, shard_files=0,
                       shard_seconds=0.0, finalize_wait_seconds=0.0)
        self.last_committed_step = -1

    def add(self, saves: int = 0, committed: int = 0, sync_saves: int = 0,
            overtakes: int = 0, snapshot_seconds: float = 0.0,
            backpressure_seconds: float = 0.0,
            writer_seconds: float = 0.0,
            shard_bytes: int = 0, shard_files: int = 0,
            shard_seconds: float = 0.0,
            finalize_wait_seconds: float = 0.0,
            step: Optional[int] = None) -> None:
        with self._lock:
            self._c["saves"] += saves
            self._c["committed"] += committed
            self._c["sync_saves"] += sync_saves
            self._c["overtakes"] += overtakes
            self._c["snapshot_seconds"] += snapshot_seconds
            self._c["backpressure_seconds"] += backpressure_seconds
            self._c["writer_seconds"] += writer_seconds
            self._c["shard_bytes"] += shard_bytes
            self._c["shard_files"] += shard_files
            self._c["shard_seconds"] += shard_seconds
            self._c["finalize_wait_seconds"] += finalize_wait_seconds
            if step is not None:
                self.last_committed_step = max(self.last_committed_step,
                                               int(step))

    def reset(self) -> None:
        with self._lock:
            for k in self._c:
                self._c[k] = 0 if isinstance(self._c[k], int) else 0.0
            self.last_committed_step = -1

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out = dict(self._c)
            out["last_committed_step"] = self.last_committed_step
        for k in ("snapshot_seconds", "backpressure_seconds",
                  "writer_seconds", "shard_seconds",
                  "finalize_wait_seconds"):
            out[k] = round(out[k], 4)
        return out


# process-global async-checkpoint accounting (one writer per train run)
ckpt_async_stats = CkptAsyncStats()


class CommTimingStats:
    """Thread-safe record of the MEASURED per-bucket collective timings
    (parallel/overlap.probe_comm_plan): the runtime companion to the
    static bucket plan in ``overlap_stats``. The probe times each planned
    gradient-exchange bucket's collective standalone (wire dtype, wire
    bytes) once per process, so the ``{"event": "comm_timing"}`` row and
    ``main.py comm-report`` can attribute achieved bytes/sec to
    INDIVIDUAL buckets instead of one aggregate ratio
    (docs/observability.md)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._probe: Optional[Dict[str, Any]] = None

    def record(self, buckets, comm_secs_total: float, reps: int,
               axes, compress: str, tiers=None) -> None:
        with self._lock:
            self._probe = {
                "buckets": [dict(b) for b in buckets],
                "comm_secs_total": round(float(comm_secs_total), 6),
                "reps": int(reps),
                "axes": list(axes),
                "compress": compress,
                # hierarchical tier legs (probe hier_k): standalone
                # grouped-psum timings per (axes, intra|inter) — catalog
                # inputs for tune_comm_plan, NOT part of comm_secs_total
                "tiers": [dict(t) for t in tiers] if tiers else [],
            }

    def reset(self) -> None:
        with self._lock:
            self._probe = None

    def snapshot(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return None if self._probe is None else {
                k: (list(v) if isinstance(v, list) else v)
                for k, v in self._probe.items()}


# process-global measured bucket timings (one probed plan per process)
comm_timing_stats = CommTimingStats()


#: The metrics.jsonl event registry — the ONE source of truth for every
#: typed ``{"event": <name>, ...}`` record any part of the framework may
#: emit. Each entry: {"fields": {field: one-line description},
#: "emitted_by": module that writes it}. Scalar rows (step/time + metric
#: keys, no "event" key) are not events and are not registered here.
#:
#: Contract, enforced two ways:
#:   * statically — analysis/rules/registry_drift.py (event-registry)
#:     resolves every ``write_event("name", ...)`` literal and every
#:     ``{"event": "name"}`` mention in docs/ and scripts/ against this
#:     dict, so code and documentation cannot drift apart;
#:   * at runtime — ``MetricsWriter.write_event`` warns once per unknown
#:     name (never raises: observability must not kill a training run).
#:
#: Adding an event = add it HERE first, then emit/document it.
EVENT_SCHEMAS = {
    "input_stages": {
        "emitted_by": "train/hooks.py InputStagesHook",
        "fields": {
            "step": "step at export time",
            "stages": "per-stage {count, items, seconds, "
                      "max_thread_seconds, workers, bytes} — cumulative "
                      "since process start/reset (difference consecutive "
                      "rows for window rates)",
        },
    },
    "input_echo": {
        "emitted_by": "train/hooks.py InputEchoHook",
        "fields": {
            "step": "step at export time",
            "echo_factor": "configured data.echo_factor",
            "decoded": "fresh decoded samples inserted (cache misses) — "
                       "cumulative, like the input_stages counters",
            "emitted": "samples served into training batches",
            "hits": "servings past a sample's first (decodes saved)",
            "hit_rate": "hits / emitted",
            "evictions": "samples evicted by the echo_cache_mb bound with "
                         "echo uses still pending",
            "lost_uses": "echo servings those evictions cost",
            "cache_bytes": "decoded-sample cache size at export",
            "peak_cache_bytes": "high-water cache size (bound witness)",
            "cache_cap_bytes": "configured byte bound",
        },
    },
    "ckpt_async": {
        "emitted_by": "train/hooks.py CkptAsyncHook (summary cadence, "
                      "when saves advanced)",
        "fields": {
            "step": "step at export time",
            "saves": "save() calls that snapshotted/wrote (cumulative)",
            "committed": "commits that completed (manifest + rename)",
            "sync_saves": "saves that ran the whole write on the loop "
                          "thread (async off / multi-process)",
            "overtakes": "saves that found the previous one still in "
                         "flight (backpressure waits)",
            "snapshot_seconds": "loop-thread device→host snapshot time "
                                "(charged to goodput 'checkpoint')",
            "backpressure_seconds": "loop-thread waits on in-flight "
                                    "saves (charged to goodput "
                                    "'checkpoint')",
            "writer_seconds": "dedicated writer-thread stage/fsync/"
                              "commit time (overlaps compute; NOT in "
                              "the goodput checkpoint bucket)",
            "shard_bytes": "bytes THIS host's writer staged into its "
                           "per-host shard files (sharded layout only)",
            "shard_files": "per-host shard files this host staged",
            "shard_seconds": "writer time spent staging this host's "
                             "shard files",
            "finalize_wait_seconds": "writer time waiting on peer-host "
                                     "shard markers / the chief's "
                                     "commit (sharded multi-process "
                                     "finalize)",
            "last_committed_step": "newest step the writer committed",
        },
    },
    "ckpt_shard": {
        "emitted_by": "train/hooks.py CkptShardHook (summary cadence, "
                      "when this host's shard bytes advanced; every "
                      "process exports — the monitor rolls hosts up)",
        "fields": {
            "step": "step at export time",
            "process": "jax.process_index() of the exporting host",
            "shard_bytes": "cumulative bytes this host staged into its "
                           "per-host shard files",
            "shard_files": "cumulative per-host shard files staged",
            "shard_seconds": "cumulative writer time staging them",
            "finalize_wait_seconds": "cumulative writer time in the "
                                     "marker-file finalize wait",
            "last_committed_step": "newest step committed on this host's "
                                   "view",
        },
    },
    "zero1": {
        "emitted_by": "train/hooks.py Zero1Hook (once per resolved "
                      "partition plan, like comm_overlap)",
        "fields": {
            "step": "step at export time",
            "data_shards": "data-axis size the optimizer state shards "
                           "over",
            "sharded_leaves": "optimizer-state leaves sharded over data",
            "replicated_leaves": "leaves left replicated (see reasons)",
            "sharded_bytes": "global bytes of the sharded leaves",
            "replicated_bytes": "global bytes of the replicated leaves",
            "bytes_per_replica": "per-replica optimizer-state bytes "
                                 "under this plan",
            "bytes_per_replica_unsharded": "per-replica bytes the "
                                           "replicated update would "
                                           "cost (the ZeRO-1 saving's "
                                           "denominator)",
            "reasons": "per-reason fallback counts (below-min-size, "
                       "no-divisible-dim, bookkeeping, ...)",
            "gather_buckets": "param-update all-gather buckets "
                              "(comm.overlap composition only)",
            "gather_bucket_bytes": "per-bucket gathered bytes, issue "
                                   "order",
            "gather_bucket_leaves": "per-bucket gathered leaf counts",
        },
    },
    "comm_overlap": {
        "emitted_by": "train/hooks.py CommOverlapHook (once per run, "
                      "when the bucketed exchange traced)",
        "fields": {
            "step": "step at export time",
            "buckets": "gradient-exchange buckets in the compiled step",
            "bucket_cap_bytes": "configured comm.bucket_mb in bytes",
            "bucket_bytes": "per-bucket gradient bytes (reverse "
                            "parameter order — issue order)",
            "bucket_leaves": "per-bucket gradient leaf counts",
            "grad_bytes": "total exchanged gradient bytes per step",
            "leaves": "gradient leaves exchanged",
            "compress": "comm.compress payload dtype (off = f32 wire)",
            "bucket_wire_bytes": "per-bucket bytes actually on the wire "
                                 "(= bucket_bytes when compress is off; "
                                 "halved under bf16/fp16 on the SAME "
                                 "bucket plan)",
            "wire_bytes": "total wire bytes per step exchange (ONE "
                          "exchange per optimizer step — under "
                          "accumulation this is 1/accum of what a "
                          "per-microbatch exchange would move)",
            "bucket_reduce_axes": "per-bucket reduce-axis set "
                                  "('data+fsdp', '…+pipeline+expert' on "
                                  "shaped layouts) — one set per bucket "
                                  "by construction (the grouped planner)",
            "accum_steps": "train.grad_accum_steps the exchange "
                           "accumulates over inside the body (1 = none)",
            "hierarchy": "intra-tier group size k of the two-tier "
                         "data-axis exchange (comm.hierarchy; 0 = flat)",
            "autotune": "comm.autotune mode the plan resolved under "
                        "(off | startup)",
            "tuned": "true when the startup autotune pass REWROTE the "
                     "plan (telemetry/planner.tune_comm_plan)",
            "bucket_inter_wire_bytes": "per-bucket wire bytes crossing "
                                       "the SLOW (inter-host) data tier "
                                       "— the full wire payload when "
                                       "flat, 1/k of it (+pad) when "
                                       "hierarchical",
        },
    },
    "comm_timing": {
        "emitted_by": "train/hooks.py CommTimingHook (chief; once the "
                      "per-bucket collective probe has run — "
                      "parallel/overlap.probe_comm_plan)",
        "fields": {
            "step": "step at export time",
            "buckets": "per-bucket measured attribution, issue order: "
                       "{bucket, bytes, wire_bytes, leaves, axes, "
                       "probe_secs, wire_bytes_per_sec} — probe_secs is "
                       "the bucket's collective timed STANDALONE on the "
                       "live mesh (wire dtype/bytes, the bucket's own "
                       "reduce-axis set), not its in-step exposed time",
            "comm_secs_total": "sum of the per-bucket standalone times — "
                               "what the exchange would cost fully "
                               "exposed",
            "reps": "timed repetitions per bucket (best-of)",
            "axes": "mesh axes the probed collective reduces over",
            "compress": "wire dtype the probe used (comm.compress; off "
                        "= f32)",
            "tiers": "hierarchical tier legs, when probed with a "
                     "factored data axis: {axes, tier: intra|inter, "
                     "wire_bytes, probe_secs, wire_bytes_per_sec} per "
                     "data-reducing axis set — catalog inputs for the "
                     "autotune cost model, NOT included in "
                     "comm_secs_total",
            "step_secs": "measured wall seconds per optimizer step over "
                         "the hook's window (loop-boundary cadence "
                         "pairs)",
            "comm_step_ratio": "comm_secs_total / step_secs — the share "
                               "of each step the exchange would cost if "
                               "NOTHING were overlapped (the overlap "
                               "headroom; docs/observability.md)",
        },
    },
    "memory": {
        "emitted_by": "train/hooks.py MemoryHook (every process, summary "
                      "cadence) + serve/server.py (every 50 dispatch "
                      "batches and at close)",
        "fields": {
            "step": "step at export time (serving: checkpoint step)",
            "process": "jax.process_index() of the exporting host",
            "devices": "per-local-device {live_bytes, live_peak_bytes} "
                       "from jax.live_arrays(), plus the allocator's "
                       "{bytes_in_use, peak_bytes_in_use, bytes_limit} "
                       "where the backend reports memory_stats() (TPU; "
                       "absent on CPU)",
            "live_bytes_total": "live jax.Array bytes across this "
                                "process's devices at sample time",
            "live_peak_bytes_total": "high-water of live_bytes_total "
                                     "over the run's samples (a SAMPLED "
                                     "watermark — peaks between samples "
                                     "are invisible; the allocator peak "
                                     "is authoritative where present)",
            "host_rss_bytes": "this process's resident set size",
            "host_peak_rss_bytes": "VmHWM — the process's RSS high-water",
            "echo_cache_bytes": "decoded-sample echo cache occupancy "
                                "(data/echo.py; 0 when echoing is off)",
            "echo_cache_cap_bytes": "configured echo cache byte bound",
            "staging_ring_slots": "CoalescedStager host-ring slots across "
                                  "live stagers (parallel/sharding.py)",
            "staging_ring_inflight": "ring slots with an in-flight H2D "
                                     "transfer at sample time",
        },
    },
    "perf_anomaly": {
        "emitted_by": "resilience/watchdog.py (perf-anomaly sentinel: "
                      "median+MAD step-time outlier over the rolling "
                      "window; telemetry.anomaly_* knobs)",
        "fields": {
            "step": "last completed step when the outlier fired",
            "detail": "human-readable verdict",
            "step_secs": "the outlying per-step time",
            "median_secs": "rolling-window median per-step time",
            "mad_secs": "rolling-window median absolute deviation",
            "threshold_secs": "median + max(anomaly_mad_k × MAD, "
                              "(anomaly_min_ratio − 1) × median) — what "
                              "the sample exceeded",
            "window": "samples in the rolling window at detection",
        },
    },
    "precision": {
        "emitted_by": "train/hooks.py PrecisionHook (once per resolved "
                      "policy, like comm_overlap — a property of the "
                      "run, not of any step)",
        "fields": {
            "step": "step at export time",
            "policy": "resolved train.precision (off | bf16)",
            "compute_dtype": "activation/matmul dtype under the policy "
                             "(null when off)",
            "master_dtype": "persisted parameter/optimizer dtype "
                            "(float32 — the checkpoint contract)",
            "compress": "effective comm.compress (off when the bucketed "
                        "exchange resolved off — see the Trainer "
                        "warning)",
            "param_leaves": "parameter leaves in the master tree",
            "master_param_bytes": "f32 master parameter bytes (what "
                                  "checkpoints persist regardless of "
                                  "policy)",
        },
    },
    "comm_compress": {
        "emitted_by": "train/hooks.py CommCompressHook (once per traced "
                      "plan WHEN compression is active; silent "
                      "otherwise)",
        "fields": {
            "step": "step at export time",
            "compress": "payload dtype on the wire (bf16 | fp16)",
            "grad_bytes": "f32 gradient bytes the exchange covers",
            "wire_bytes": "bytes actually exchanged after the cast",
            "bucket_wire_bytes": "per-bucket wire bytes, issue order "
                                 "(same bucket plan as comm_overlap's "
                                 "bucket_bytes)",
            "wire_ratio": "wire_bytes / grad_bytes (0.5 for bf16/fp16)",
            "gather_wire_bytes": "ZeRO-1 param-update all-gather wire "
                                 "bytes per bucket (comm.overlap + "
                                 "zero1 composition only)",
        },
    },
    "corrupt_record": {
        "emitted_by": "train/hooks.py CorruptRecordsHook",
        "fields": {
            "step": "step at export time",
            "count": "distinct corrupt (file, offset) sites skipped",
            "repeats": "re-reads of already-counted sites",
            "by_reason": "per-reason breakdown",
            "recent": "most recent offenders (file, reason)",
        },
    },
    "heartbeat": {
        "emitted_by": "resilience/watchdog.py (straggler_window cadence)",
        "fields": {
            "hosts": "per-process {step, progress, phase, host, age_secs}",
        },
    },
    "straggler": {
        "emitted_by": "resilience/watchdog.py (straggler_window cadence)",
        "fields": {
            "window_secs": "accounting window",
            "rates": "per-process steps/sec over the window",
            "median": "median step rate",
            "lag_steps": "per-process steps behind the leader",
            "flagged": "process ids slower than median by straggler_ratio",
        },
    },
    "peer_lost": {
        "emitted_by": "resilience/watchdog.py (detection verdict)",
        "fields": {"detail": "human-readable verdict",
                   "exit_code": "intended exit code (75)",
                   "grace_secs": "grace window before hard exit",
                   "via": "'collective_error' when classified from the "
                          "main thread's exception path"},
    },
    "peer_failed": {
        "emitted_by": "resilience/watchdog.py (detection verdict)",
        "fields": {"detail": "human-readable verdict",
                   "exit_code": "intended exit code (1)",
                   "grace_secs": "grace window before hard exit",
                   "via": "'collective_error' when classified from the "
                          "main thread's exception path"},
    },
    "hang": {
        "emitted_by": "resilience/watchdog.py (detection verdict)",
        "fields": {"detail": "human-readable verdict",
                   "exit_code": "intended exit code (75)",
                   "grace_secs": "grace window before hard exit"},
    },
    "watchdog_cleared": {
        "emitted_by": "resilience/watchdog.py",
        "fields": {"kind": "the verdict that cleared within grace"},
    },
    "watchdog_exit": {
        "emitted_by": "resilience/watchdog.py",
        "fields": {"kind": "verdict kind", "exit_code": "code passed to "
                   "os._exit", "detail": "human-readable verdict"},
    },
    "serve_request": {
        "emitted_by": "serve/server.py InferenceServer (report cadence + "
                      "shutdown)",
        "fields": {
            "step": "serving checkpoint step at export time",
            "requests": "requests completed since process start",
            "dropped": "requests that did not complete (contract: 0)",
            "buckets": "per-bucket {count, p50_ms, p99_ms, mean_ms} request "
                       "latency (submit -> result on host) — cumulative, "
                       "like the input_stages counters",
        },
    },
    "serve_batch": {
        "emitted_by": "serve/server.py InferenceServer (per dispatched "
                      "bucket batch)",
        "fields": {
            "step": "checkpoint step the batch was served from",
            "bucket": "padded batch size dispatched",
            "n": "real (un-padded) requests in the batch",
            "variant": "serving precision variant the batch ran on "
                       "(serve.variants; docs/precision.md)",
            "queue_ms": "oldest request's queue wait before dispatch",
            "run_ms": "dispatch -> logits-on-host wall time",
        },
    },
    "goodput": {
        "emitted_by": "train/hooks.py GoodputHook (summary cadence)",
        "fields": {
            "step": "step at export time",
            "wall_secs": "wall seconds classified in this interval",
            "seconds": "per-category seconds {compute, input_wait, "
                       "checkpoint, eval, stall, restart, reshard} — "
                       "compute is the interval remainder "
                       "(telemetry/goodput.py)",
            "pct": "per-category percentages; sum to ~100 of wall by "
                   "construction",
        },
    },
    "trace_dump": {
        "emitted_by": "telemetry/tracer.py FlightRecorder.dump_on_anomaly "
                      "(watchdog escalations, straggler flags, fatal "
                      "exits)",
        "fields": {
            "reason": "what triggered the dump (hang | peer_lost | "
                      "peer_failed | straggler | perf_anomaly | "
                      "exception | on_demand)",
            "detail": "human-readable trigger detail",
            "path": "trace.json written (Chrome-trace / Perfetto format)",
            "spans": "events in the ring at dump time",
            "span_schema_version": "telemetry.tracer.SPAN_SCHEMA_VERSION",
        },
    },
    "serve_swap": {
        "emitted_by": "serve/server.py / serve/swap.py (hot checkpoint "
                      "swap)",
        "fields": {
            "from_step": "previously serving step (-1 = fresh init)",
            "to_step": "checkpoint step now serving (absent when rejected)",
            "digest": "manifest digest of the swapped-in checkpoint "
                      "(resilience.manifest.manifest_digest)",
            "restore_ms": "off-path host restore + verify wall time",
            "apply_ms": "on-path atomic apply (device placement + pointer "
                        "swap) wall time",
            "rejected": "present (with the reason string) when a damaged/"
                        "torn checkpoint failed manifest verification and "
                        "was skipped without touching the serving params",
            "to_step_attempted": "the rejected checkpoint's step (rejected "
                                 "rows only; applied rows carry to_step)",
        },
    },
    "route": {
        "emitted_by": "serve/router.py Router (route.row_interval_secs "
                      "cadence + shutdown; the fleet front door's "
                      "headline row — docs/serving.md fleet section)",
        "fields": {
            "requests": "requests admitted since router start",
            "completed": "requests answered (first winning attempt)",
            "errors": "client-visible failures (every attempt exhausted "
                      "or deadline passed) — the smoke bounds these",
            "shed": "requests refused with the shed verdict (cumulative)",
            "degraded": "requests rerouted to the degrade variant under "
                        "queue pressure (cumulative)",
            "hedges": "extra attempts issued after hedge_ms without a "
                      "response (cumulative)",
            "retries": "extra attempts issued after a FAILED attempt "
                       "(cumulative; hedges and retries are both bounded "
                       "by route.max_attempts)",
            "qps": "completions/sec over the row's window",
            "p99_ms": "router-observed p99 request latency (submit → "
                      "first winning response) over the row's window",
            "replicas": "per-replica {state, step, outstanding, served, "
                        "failures, p99_ms, beat_age_secs} snapshot",
        },
    },
    "replica_health": {
        "emitted_by": "serve/router.py Router (health-state transitions "
                      "only, not per scan)",
        "fields": {
            "replica": "replica id (serve.replica_id of the process)",
            "from": "previous health state (warming | ready | degraded | "
                    "suspect | draining | dead)",
            "to": "new health state",
            "reason": "what moved it (probe_ok | failures | beat_stale | "
                      "slo_pressure | recovered | drain | readmit)",
            "beat_age_secs": "heartbeat age at the transition (absent "
                             "when the replica never published a beat)",
            "failures": "consecutive transport failures at the "
                        "transition",
        },
    },
    "canary": {
        "emitted_by": "serve/router.py CanaryController (one row per "
                      "lifecycle action: start, promote, rollback)",
        "fields": {
            "action": "start | promote | rollback",
            "step": "checkpoint step under canary",
            "from_step": "fleet step the canary would replace (rollback "
                         "re-pins it)",
            "canary": "replica ids serving the canary fraction",
            "rollback": "true on the rollback row — the auto-rollback "
                        "witness scripts/serve_fleet_smoke.sh asserts",
            "reason": "decision detail (p99_regression | "
                      "confidence_regression | no_confirm | promoted | "
                      "single_replica)",
            "p99_canary_ms": "canary-arm p99 over the watch window",
            "p99_base_ms": "control-arm p99 over the watch window",
            "conf_canary": "canary-arm mean top-1 softmax confidence "
                           "(the accuracy proxy)",
            "conf_base": "control-arm mean top-1 softmax confidence",
            "samples_canary": "canary-arm responses measured",
            "samples_base": "control-arm responses measured",
        },
    },
    "shed": {
        "emitted_by": "serve/router.py Router (rate-limited: at most one "
                      "row per second while shedding/degrading)",
        "fields": {
            "count": "requests shed since router start (cumulative)",
            "degraded": "requests rerouted to the degrade variant "
                        "(cumulative)",
            "est_queue_ms": "estimated queue delay that tripped the "
                            "verdict (outstanding × EWMA service time / "
                            "eligible replicas)",
            "threshold_ms": "route.shed_queue_ms the estimate exceeded",
        },
    },
    "replica_replace": {
        "emitted_by": "serve/fleet.py FleetSupervisor (watchdog replace "
                      "ladder: drain → kill → respawn → readmit)",
        "fields": {
            "replica": "replica id being replaced",
            "action": "kill | respawn | readmit | gave_up",
            "reason": "what condemned it (exited | wedged | dead)",
            "pid": "pid of the condemned process (kill rows)",
            "rc": "exit code observed (when the process had exited)",
            "new_pid": "pid of the respawned process (respawn/readmit "
                       "rows)",
            "wait_secs": "respawn → READY wall time (readmit rows)",
        },
    },
    "reshard": {
        "emitted_by": "resilience/elastic.py ElasticRuntime (one row per "
                      "completed mesh-generation transition; docs/"
                      "resilience.md elastic mesh)",
        "fields": {
            "generation": "mesh generation ENTERED by this transition",
            "reason": "what triggered it (peer_lost | hang | grow | "
                      "rejoin)",
            "old_hosts": "process count of the generation left behind",
            "new_hosts": "process count of the new generation",
            "restore_step": "committed checkpoint step the new generation "
                            "resumed from (-1 = fresh init, no committed "
                            "checkpoint existed)",
            "global_batch": "global batch size of the new generation "
                            "(resilience.elastic.batch_policy)",
            "barrier_ms": "join-barrier wall time (membership settle + "
                          "commit)",
            "total_ms": "whole transition wall time: barrier + teardown + "
                        "re-init + restore + rebuild",
        },
    },
    "mesh_generation": {
        "emitted_by": "resilience/elastic.py ElasticRuntime (chief, one "
                      "row when a generation starts stepping — including "
                      "generation 0 of an elastic run)",
        "fields": {
            "generation": "the mesh generation now live",
            "hosts": "live process count in this generation",
            "devices": "global device count in this generation",
            "step": "first step of this generation's step loop",
            "coordinator": "epoch-suffixed coordinator address the "
                           "generation initialized over "
                           "(parallel/distributed.py)",
        },
    },
    "plan": {
        "emitted_by": "telemetry/planner.py (main.py plan, and the chief "
                      "at run start when the drift sentinel arms; "
                      "docs/planner.md)",
        "fields": {
            "preset": "preset the prediction is for",
            "layout": "layout name (dp | dp_fsdp | dp_tp | dp_pp | "
                      "dp_pp_ep)",
            "devices": "global device count the prediction assumes",
            "knobs": "knob dict {precision, zero1, compress, bucket_mb, "
                     "accum} the prediction assumes",
            "predicted": "{step_secs, compute_secs, comm_secs, "
                         "comm_exposed_secs, comm_fraction, "
                         "hbm_bytes, wire_bytes} — the cost model's "
                         "output (telemetry/planner.py)",
            "bandwidth_source": "'catalog' (results/bandwidth/"
                                "<fabric>.json), 'reference' (baked-in "
                                "table) or 'probe' (live comm_timing)",
            "recommended": "true on the row for the layout main.py plan "
                           "ranked first (plan rows from a live run "
                           "describe the running layout and omit this)",
        },
    },
    "plan_drift": {
        "emitted_by": "train/hooks.py PlanDriftHook (sustained "
                      "predicted-vs-measured divergence beyond "
                      "telemetry.plan_tolerance; docs/planner.md)",
        "fields": {
            "step": "step at detection",
            "metric": "which observable diverged (step_secs | comm_secs "
                      "| hbm_bytes)",
            "predicted": "the cost model's value for this run's layout",
            "measured": "the live value (heartbeat step EWMA / "
                        "comm_timing probe total / memory row peak)",
            "ratio": "measured / predicted (>= 1: slower/bigger than "
                     "predicted; the sentinel fires on either side of "
                     "tolerance)",
            "tolerance": "telemetry.plan_tolerance the ratio exceeded",
            "windows": "consecutive divergent checks before firing "
                       "(telemetry.plan_drift_window)",
            "dump": "trace.json path when the flight recorder dumped "
                    "(absent when tracing is off)",
        },
    },
}

# unknown event names already warned about (warn once, not per row)
_UNKNOWN_EVENTS_WARNED: set = set()


class MetricsWriter:
    """JSONL + optional TensorBoard scalar writer. Process-0-only by default
    (matching chief-only summaries in the reference).

    The JSONL stream is SIZE-BOUNDED: past ``max_bytes`` the file rotates
    (atomic rename to ``metrics.jsonl.1``, older segments shifting up to
    ``max_segments`` before the oldest is dropped) — a week-long serve or
    monitor run cannot fill the disk with event rows. ``read_metrics``
    reads rotated segments oldest-first, so consumers see one continuous
    stream."""

    def __init__(self, logdir: str, enable_tensorboard: bool = True,
                 filename: str = "metrics.jsonl",
                 max_bytes: int = 256 * 1024 * 1024,
                 max_segments: int = 4):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self._path = os.path.join(logdir, filename)
        self._max_bytes = max(0, max_bytes)  # 0 = rotation off
        self._max_segments = max(1, max_segments)
        self._jsonl = open(self._path, "a", buffering=1)
        self._size = self._jsonl.tell()  # append mode: position == size
        # the watchdog's detection thread writes events concurrently with
        # the hook thread's scalars; serialize so rows never interleave
        self._wlock = threading.Lock()
        self._tb = None
        if enable_tensorboard:
            try:
                from tensorboardX import SummaryWriter
                self._tb = SummaryWriter(logdir=logdir)
            except Exception:  # tensorboardX optional
                log.info("tensorboardX unavailable; JSONL metrics only")

    def _write_line(self, line: str) -> None:
        """Caller holds ``_wlock``. Size-triggered rotation happens BEFORE
        the write so a rotated segment never exceeds the bound by more
        than one row."""
        if self._max_bytes and self._size + len(line) > self._max_bytes \
                and self._size > 0:
            self._rotate_locked()
        self._jsonl.write(line)
        self._size += len(line)

    def _rotate_locked(self) -> None:
        """Shift ``.1 -> .2 -> ...`` (dropping the oldest past
        ``max_segments``), atomically rename the live file to ``.1``, and
        reopen. Rotation failures degrade to an unbounded stream — a full
        disk must not kill the run over telemetry."""
        try:
            self._jsonl.close()
            oldest = f"{self._path}.{self._max_segments}"
            if os.path.exists(oldest):
                os.remove(oldest)
            for i in range(self._max_segments - 1, 0, -1):
                seg = f"{self._path}.{i}"
                if os.path.exists(seg):
                    os.replace(seg, f"{self._path}.{i + 1}")
            os.replace(self._path, f"{self._path}.1")
        except OSError as e:
            log.warning("metrics rotation failed (%s); stream unbounded "
                        "until it succeeds", e)
        self._jsonl = open(self._path, "a", buffering=1)
        self._size = self._jsonl.tell()

    def write_images(self, step: int, tag: str, images) -> None:
        """Image summaries (parity with reference cifar_input.py:114's
        tf.summary.image of input batches). TensorBoard-only; no-op without
        tensorboardX. Accepts uint8, or float in any range — floats are
        min-max rescaled per image (training inputs are standardized,
        zero-mean, so clipping to [0,1] would render garbage)."""
        if self._tb is None:
            return
        import numpy as np
        arr = np.asarray(images)
        if arr.dtype != np.uint8:
            arr = arr.astype(np.float32)
            lo = arr.min(axis=(1, 2, 3), keepdims=True)
            hi = arr.max(axis=(1, 2, 3), keepdims=True)
            arr = ((arr - lo) / np.maximum(hi - lo, 1e-6) * 255).astype(np.uint8)
        for i, img in enumerate(arr[:4]):
            self._tb.add_image(f"{tag}/{i}", img, int(step),
                               dataformats="HWC")

    def write_scalars(self, step: int, scalars: Dict[str, Any]) -> None:
        rec = {"step": int(step), "time": time.time()}
        for k, v in scalars.items():
            rec[k] = float(v)
        with self._wlock:
            self._write_line(json.dumps(rec) + "\n")
        if self._tb is not None:
            for k, v in scalars.items():
                self._tb.add_scalar(k, float(v), int(step))

    def write_event(self, event: str, payload: Dict[str, Any]) -> None:
        """Typed (non-scalar) JSONL record: ``{"event": <name>, ...}``.
        Consumers of metrics.jsonl that expect scalar rows must filter on
        the "event" key (read_metrics returns both kinds). ``event`` must
        be declared in EVENT_SCHEMAS — unknown names still write (a
        training run must not die on telemetry) but warn once."""
        if event not in EVENT_SCHEMAS and event not in _UNKNOWN_EVENTS_WARNED:
            _UNKNOWN_EVENTS_WARNED.add(event)
            log.warning(
                "metrics event %r is not declared in "
                "utils.metrics.EVENT_SCHEMAS — register it (the "
                "event-registry lint rejects undeclared literals)", event)
        rec = {"event": event, "time": time.time()}
        rec.update(payload)
        with self._wlock:
            self._write_line(json.dumps(rec) + "\n")

    def flush(self) -> None:
        # under _wlock: rotation closes and swaps the handle mid-write —
        # an unlocked flush from the watchdog/tracer thread could hit the
        # closed file
        with self._wlock:
            self._jsonl.flush()
        if self._tb is not None:
            self._tb.flush()

    def close(self) -> None:
        self.flush()
        self._jsonl.close()
        if self._tb is not None:
            self._tb.close()


class LatencyStats:
    """Thread-safe per-key latency recorder with percentile summaries.

    The serving path (serve/server.py) records one sample per request keyed
    by its dispatch bucket; ``summary_ms`` is what the ``serve_request``
    metrics rows, ``bench.py``'s serving row and the ``main.py serve``
    report all read — one implementation so p50/p99 can't be computed three
    different ways. Samples are capped (default 200k ≈ hours of smoke-load
    serving) to bound memory on long-lived servers; past the cap each new
    sample overwrites a deterministic pseudo-random slot, so the buffer
    becomes a RECENCY-WEIGHTED window (~the last cap samples; older ones
    decay away). For serving that is the useful estimate — current p99,
    not a lifetime average diluted by the warm-up epoch — but it is NOT an
    unbiased whole-run sample; ``count`` still reports the true total.
    """

    def __init__(self, max_samples_per_key: int = 200_000):
        self._lock = threading.Lock()
        self._samples: Dict[str, list] = {}
        self._counts: Dict[str, int] = {}
        self._max = max(1, max_samples_per_key)

    def record(self, key: str, seconds: float) -> None:
        with self._lock:
            buf = self._samples.setdefault(key, [])
            n = self._counts.get(key, 0)
            self._counts[key] = n + 1
            if len(buf) < self._max:
                buf.append(seconds)
            else:
                # deterministic LCG slot (no random import on the hot path)
                buf[(n * 48271 + 11) % self._max] = seconds

    def summary_ms(self) -> Dict[str, Dict[str, float]]:
        """key -> {count, p50_ms, p99_ms, mean_ms} over recorded samples."""
        import numpy as np
        with self._lock:
            snap = {k: (list(v), self._counts.get(k, 0))
                    for k, v in self._samples.items()}
        out = {}
        for key, (vals, count) in snap.items():
            if not vals:
                continue
            arr = np.asarray(vals) * 1000.0
            out[key] = {"count": count,
                        "p50_ms": round(float(np.percentile(arr, 50)), 3),
                        "p99_ms": round(float(np.percentile(arr, 99)), 3),
                        "mean_ms": round(float(arr.mean()), 3)}
        return out


class Throughput:
    """Steps/sec + images/sec meter over a sliding window."""

    def __init__(self, batch_size: int):
        self.batch_size = batch_size
        self._t0: Optional[float] = None
        self._step0: Optional[int] = None

    def reset(self) -> None:
        """Restart the window — call when training resumes after a pause
        (eval round, checkpoint restore): a window spanning non-training
        wall time would deflate steps/sec and the derived MFU column."""
        self._t0 = self._step0 = None

    def update(self, step: int) -> Dict[str, float]:
        now = time.monotonic()
        if self._t0 is None:
            self._t0, self._step0 = now, step
            return {}
        dt = now - self._t0
        dsteps = step - self._step0
        if dt <= 0 or dsteps <= 0:
            return {}
        out = {"steps_per_sec": dsteps / dt,
               "images_per_sec": dsteps * self.batch_size / dt}
        self._t0, self._step0 = now, step
        return out


def read_metrics(logdir: str, filename: str = "metrics.jsonl",
                 tolerant: bool = False):
    """Load the JSONL event stream back (for tests/analysis/monitor),
    including rotated segments in order: ``metrics.jsonl.N`` (oldest,
    highest N) down to ``.1``, then the live file — one continuous stream
    across rotations. ``tolerant`` skips torn lines (a live writer can be
    mid-row) instead of raising."""
    path = os.path.join(logdir, filename)
    segments = []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        segments.append(f"{path}.{i}")
        i += 1
    paths = list(reversed(segments))
    if os.path.exists(path) or not paths:
        paths.append(path)  # preserve FileNotFoundError when nothing exists
    out = []
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    if not tolerant:
                        raise
    return out


def metric_stream_dirs(root: str, filename: str = "metrics.jsonl"):
    """Every directory holding a metrics stream under ``root`` (the root
    itself included — ``**`` matches zero directories) — the ONE
    stream-layout discovery the monitor and the offline reducers share;
    fix the layout here and every consumer follows. Readers differ on
    purpose: the monitor tails a bounded window per frame, the reducers
    read whole streams."""
    import glob as _glob
    return sorted({os.path.dirname(p) for p in _glob.glob(
        os.path.join(root, "**", filename), recursive=True)})


def iter_metric_streams(root: str, filename: str = "metrics.jsonl"):
    """Yield the rows of every metrics stream under ``root``, tolerant
    of torn lines and vanished files — the offline reducers' read path
    (`main.py trace-merge` / `comm-report`)."""
    for d in metric_stream_dirs(root, filename):
        try:
            yield read_metrics(d, filename=filename, tolerant=True)
        except OSError:
            continue
